// Native-runtime unit tests — reference analog: the libnd4j googletest
// suites (tests_cpu/layers_tests/*, run_tests.sh). gtest is not in
// this image, so a minimal CHECK harness covers the same ground:
// exact-value + shape assertions per exported component.
//
// Build & run:  make test
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
int csv_parse_f32(const char*, int64_t, char, int, float*, int64_t,
                  int64_t*, int64_t*);
int64_t encode_threshold_f32(const float*, int64_t, float, int8_t*,
                             float*);
void decode_threshold_f32(const int8_t*, int64_t, float, float*);
void bitmap_encode(const int8_t*, int64_t, uint8_t*, uint8_t*);
void bitmap_decode(const uint8_t*, const uint8_t*, int64_t, float,
                   float*);
void* ws_create(int64_t);
void* ws_alloc(void*, int64_t);
int64_t ws_reset(void*);
int64_t ws_capacity(void*);
void ws_destroy(void*);
void* ring_create(int64_t);
int ring_push(void*, int64_t);
int ring_pop(void*, int64_t*);
int64_t ring_size(void*);
void ring_close(void*);
void ring_destroy(void*);
int img_batch_normalize_u8(const uint8_t*, int64_t, int64_t, int64_t,
                           int64_t, const int32_t*, const int32_t*,
                           const uint8_t*, int64_t, int64_t,
                           const float*, const float*, float*, int);
uint32_t dl4j_crc32(const uint8_t*, int64_t);
int64_t chunk_count(int64_t, int64_t);
int64_t chunk_frame_bytes(int64_t, int64_t);
int64_t chunk_message(uint64_t, const uint8_t*, int64_t, int64_t,
                      uint8_t*);
int64_t chunk_parse_frame(const uint8_t*, int64_t, uint64_t*, uint32_t*,
                          uint32_t*, uint32_t*, int64_t*);
int dl4j_tpu_native_abi_version();
}

static int failures = 0;
#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::printf("FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);     \
      ++failures;                                                     \
    }                                                                 \
  } while (0)
#define CHECK_NEAR(a, b, tol) CHECK(std::fabs((a) - (b)) <= (tol))

static void test_csv_parser() {
  const char* txt = "# header\n1.5,2.5,3\n-4,5e1,0.25\n";
  float out[16];
  int64_t rows = 0, cols = 0;
  int rc = csv_parse_f32(txt, (int64_t)std::strlen(txt), ',', 1, out,
                         16, &rows, &cols);
  CHECK(rc == 0);
  CHECK(rows == 2 && cols == 3);
  CHECK_NEAR(out[0], 1.5f, 1e-6f);
  CHECK_NEAR(out[4], 50.0f, 1e-6f);
  CHECK_NEAR(out[5], 0.25f, 1e-6f);
  // ragged input must be rejected, not silently padded
  const char* ragged = "1,2\n3\n";
  rc = csv_parse_f32(ragged, (int64_t)std::strlen(ragged), ',', 0, out,
                     16, &rows, &cols);
  CHECK(rc == -3);
  // non-numeric -> fall back signal
  const char* alpha = "1,x\n";
  rc = csv_parse_f32(alpha, (int64_t)std::strlen(alpha), ',', 0, out,
                     16, &rows, &cols);
  CHECK(rc == -2);
  // overflow of out buffer
  rc = csv_parse_f32(txt, (int64_t)std::strlen(txt), ',', 1, out, 3,
                     &rows, &cols);
  CHECK(rc == -1);
}

static void test_threshold_codec() {
  const float g[6] = {0.9f, -0.7f, 0.1f, -0.05f, 2.0f, -3.0f};
  int8_t sign[6];
  float residual[6];
  int64_t nz = encode_threshold_f32(g, 6, 0.5f, sign, residual);
  CHECK(nz == 4);                       // |g| > tau at 4 positions
  CHECK(sign[0] == 1 && sign[1] == -1 && sign[2] == 0 && sign[3] == 0);
  float dec[6];
  decode_threshold_f32(sign, 6, 0.5f, dec);
  CHECK_NEAR(dec[0], 0.5f, 1e-6f);
  CHECK_NEAR(dec[2], 0.0f, 1e-6f);
  // residual + decoded == original (the accumulator invariant)
  for (int i = 0; i < 6; ++i)
    CHECK_NEAR(residual[i] + dec[i], g[i], 1e-6f);
}

static void test_bitmap_roundtrip() {
  int8_t sign[16];
  for (int i = 0; i < 16; ++i) sign[i] = (int8_t)((i % 3) - 1);
  uint8_t pos[2], neg[2];
  bitmap_encode(sign, 16, pos, neg);
  float back[16];
  const float tau = 0.25f;
  bitmap_decode(pos, neg, 16, tau, back);
  for (int i = 0; i < 16; ++i)
    CHECK_NEAR(back[i], tau * (float)sign[i], 1e-6f);
}

static void test_workspace_arena() {
  void* ws = ws_create(1024);
  CHECK(ws != nullptr);
  void* a = ws_alloc(ws, 100);
  void* b = ws_alloc(ws, 100);
  CHECK(a != nullptr && b != nullptr && a != b);
  CHECK(((uintptr_t)a % 64) == 0 && ((uintptr_t)b % 64) == 0);
  // spill path: bigger than the arena
  void* big = ws_alloc(ws, 4096);
  CHECK(big != nullptr);
  int64_t high_water = ws_reset(ws);
  CHECK(high_water >= 200 + 4096);
  void* c = ws_alloc(ws, 100);
  CHECK(c == a);                        // cyclic reuse after reset
  CHECK(ws_capacity(ws) == 1024);
  ws_destroy(ws);
}

static void test_ring_queue_threaded() {
  void* q = ring_create(64);
  std::atomic<int64_t> sum(0);
  std::thread consumer([&] {
    int64_t tok;
    while (ring_pop(q, &tok) == 0) sum += tok;
  });
  int64_t want = 0;
  for (int64_t i = 1; i <= 1000; ++i) {
    CHECK(ring_push(q, i) == 0);
    want += i;
  }
  ring_close(q);
  consumer.join();
  CHECK(sum.load() == want);
  CHECK(ring_size(q) == 0);
  ring_destroy(q);
}

static void test_image_normalize() {
  // 1 image, 2x2x1, mean (in 0-1 units) 100/255, std 50/255:
  // out = (px/255 - mean)/std
  uint8_t in[4] = {100, 150, 50, 200};
  float mean[1] = {100.0f / 255.0f}, sd[1] = {50.0f / 255.0f};
  float out[4];
  int rc = img_batch_normalize_u8(in, 1, 2, 2, 1, nullptr, nullptr,
                                  nullptr, 2, 2, mean, sd, out, 1);
  CHECK(rc == 0);
  CHECK_NEAR(out[0], 0.0f, 1e-5f);
  CHECK_NEAR(out[1], 1.0f, 1e-5f);
  CHECK_NEAR(out[3], 2.0f, 1e-5f);
  // horizontal flip swaps columns
  uint8_t fl = 1;
  rc = img_batch_normalize_u8(in, 1, 2, 2, 1, nullptr, nullptr, &fl, 2,
                              2, mean, sd, out, 1);
  CHECK(rc == 0);
  CHECK_NEAR(out[0], 1.0f, 1e-5f);      // was column 1
  CHECK_NEAR(out[1], 0.0f, 1e-5f);
}

static const int64_t kFirstPayloadByte = 24;  // header is 24 bytes

static void test_chunked_framing() {
  const int64_t payload_len = 1000, chunk = 256;
  std::vector<uint8_t> payload(payload_len);
  for (int64_t i = 0; i < payload_len; ++i)
    payload[i] = (uint8_t)(i * 7);
  int64_t n_chunks = chunk_count(payload_len, chunk);
  CHECK(n_chunks == 4);
  int64_t total = chunk_frame_bytes(payload_len, chunk);
  std::vector<uint8_t> wire(total);
  int64_t frames = chunk_message(42u, payload.data(), payload_len,
                                 chunk, wire.data());
  CHECK(frames == n_chunks);
  // reassemble
  std::vector<uint8_t> got(payload_len);
  const uint8_t* p = wire.data();
  int64_t remaining = total;
  for (int64_t c = 0; c < n_chunks; ++c) {
    uint64_t msg_id;
    uint32_t seq, tot, plen;
    int64_t off;
    int64_t consumed =
        chunk_parse_frame(p, remaining, &msg_id, &seq, &tot, &plen,
                          &off);
    CHECK(consumed > 0);
    CHECK(msg_id == 42u && tot == (uint32_t)n_chunks &&
          seq == (uint32_t)c);
    std::memcpy(got.data() + (int64_t)seq * chunk, p + off, plen);
    p += consumed;
    remaining -= consumed;
  }
  CHECK(std::memcmp(got.data(), payload.data(),
                    (size_t)payload_len) == 0);
  // corrupted payload byte must be rejected by crc
  wire[kFirstPayloadByte] ^= 0xFF;
  uint64_t msg_id;
  uint32_t seq, tot, plen;
  int64_t off;
  CHECK(chunk_parse_frame(wire.data(), total, &msg_id, &seq, &tot,
                          &plen, &off) == -2);
  // truncated header
  CHECK(chunk_parse_frame(wire.data(), 10, &msg_id, &seq, &tot, &plen,
                          &off) == -1);
}

static void test_crc() {
  const uint8_t a[4] = {'a', 'b', 'c', 'd'};
  uint32_t c1 = dl4j_crc32(a, 4);
  CHECK(c1 == dl4j_crc32(a, 4));
  const uint8_t b[4] = {'a', 'b', 'c', 'e'};
  CHECK(dl4j_crc32(b, 4) != c1);
}

int main() {
  CHECK(dl4j_tpu_native_abi_version() == 2);
  test_csv_parser();
  test_threshold_codec();
  test_bitmap_roundtrip();
  test_workspace_arena();
  test_ring_queue_threaded();
  test_image_normalize();
  test_chunked_framing();
  test_crc();
  if (failures == 0) {
    std::printf("native tests: ALL PASSED\n");
    return 0;
  }
  std::printf("native tests: %d FAILURES\n", failures);
  return 1;
}
