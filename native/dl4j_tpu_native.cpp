// dl4j_tpu native runtime — C ABI, loaded from Python via ctypes.
//
// TPU-native equivalents of the reference's native runtime pieces that
// live OUTSIDE the XLA compute path (SURVEY §2.1: libnd4j memory/
// workspaces, execution engine, C ABI surface; §2.2 AeronNDArray
// chunking; datavec's native ETL):
//
//   * fast CSV float parser        (datavec CSVRecordReader hot path;
//                                   reference: JavaCV/Java parsing)
//   * threshold gradient codec     (libnd4j encode_threshold /
//     + bitmap pack                 decode_threshold, bitmap encode —
//                                   host-side flavor for DCN shipping;
//                                   the on-device flavor is XLA/Pallas)
//   * workspace arena allocator    (include/memory/Workspace.h: bump
//                                   arena with reset/scope semantics)
//   * blocking MPMC ring queue     (the prefetch machinery behind
//                                   AsyncDataSetIterator / IndexedTail
//                                   fan-out queues)
//
// Pure C++17 + std::thread; no external deps. Built by native/Makefile
// (or deeplearning4j_tpu/native.py on first import) into
// libdl4j_tpu_native.so. Every entry point is exercised against the
// pure-Python fallback in tests/test_native.py.

#include <algorithm>
#include <atomic>
#include <cctype>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <new>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// CSV fast path
// ---------------------------------------------------------------------------

// Parse a numeric CSV buffer into `out` (row-major), returning 0 on
// success. Rows are '\n'-separated (trailing '\r' tolerated), fields by
// `delim`. Empty lines are skipped. On any non-numeric field returns -2
// (caller falls back to the general Python reader). Returns -1 if the
// parsed element count would exceed `max_out`. n_rows/n_cols receive
// the shape; ragged rows return -3.
int csv_parse_f32(const char* buf, int64_t len, char delim, int skip_rows,
                  float* out, int64_t max_out,
                  int64_t* n_rows, int64_t* n_cols) {
    int64_t rows = 0, cols = -1, n = 0;
    const char* p = buf;
    const char* end = buf + len;
    while (p < end) {
        const char* line_end =
            static_cast<const char*>(memchr(p, '\n', end - p));
        if (!line_end) line_end = end;
        const char* le = line_end;
        if (le > p && le[-1] == '\r') --le;
        if (le == p) { p = line_end + 1; continue; }  // empty line
        if (skip_rows > 0) { --skip_rows; p = line_end + 1; continue; }
        int64_t row_cols = 0;
        const char* f = p;
        while (f <= le) {
            const char* fe = f;
            while (fe < le && *fe != delim) ++fe;
            // parse [f, fe) as float
            char tmp[64];
            int64_t flen = fe - f;
            // trim spaces
            while (flen > 0 && isspace(static_cast<unsigned char>(*f))) {
                ++f; --flen;
            }
            while (flen > 0 &&
                   isspace(static_cast<unsigned char>(f[flen - 1])))
                --flen;
            if (flen <= 0 || flen >= 63) return -2;
            memcpy(tmp, f, flen);
            tmp[flen] = '\0';
            char* endptr = nullptr;
            float v = strtof(tmp, &endptr);
            if (endptr != tmp + flen) return -2;
            if (n >= max_out) return -1;
            out[n++] = v;
            ++row_cols;
            if (fe >= le) break;
            f = fe + 1;
        }
        if (cols < 0) cols = row_cols;
        else if (cols != row_cols) return -3;
        ++rows;
        p = line_end + 1;
    }
    *n_rows = rows;
    *n_cols = cols < 0 ? 0 : cols;
    return 0;
}

// ---------------------------------------------------------------------------
// Threshold gradient codec (reference libnd4j encode_threshold /
// decode_threshold / bitmap encode — SURVEY §2.3 EncodedGradients row)
// ---------------------------------------------------------------------------

// g -> ternary sign (|g|>tau), residual = g - tau*sign. Returns count of
// non-zeros (the reference's encoded-update length).
int64_t encode_threshold_f32(const float* g, int64_t n, float tau,
                             int8_t* sign, float* residual) {
    int64_t nnz = 0;
    for (int64_t i = 0; i < n; ++i) {
        float v = g[i];
        int8_t s = (v > tau) ? 1 : (v < -tau ? -1 : 0);
        sign[i] = s;
        residual[i] = v - tau * static_cast<float>(s);
        nnz += (s != 0);
    }
    return nnz;
}

void decode_threshold_f32(const int8_t* sign, int64_t n, float tau,
                          float* out) {
    for (int64_t i = 0; i < n; ++i)
        out[i] = tau * static_cast<float>(sign[i]);
}

// Pack ternary signs into two bitmaps (pos/neg), 8 elements/byte each —
// 16x smaller than f32. n_bytes = ceil(n/8).
void bitmap_encode(const int8_t* sign, int64_t n, uint8_t* pos,
                   uint8_t* neg) {
    int64_t nb = (n + 7) / 8;
    memset(pos, 0, nb);
    memset(neg, 0, nb);
    for (int64_t i = 0; i < n; ++i) {
        if (sign[i] > 0) pos[i >> 3] |= (1u << (i & 7));
        else if (sign[i] < 0) neg[i >> 3] |= (1u << (i & 7));
    }
}

void bitmap_decode(const uint8_t* pos, const uint8_t* neg, int64_t n,
                   float tau, float* out) {
    for (int64_t i = 0; i < n; ++i) {
        bool p = pos[i >> 3] & (1u << (i & 7));
        bool m = neg[i >> 3] & (1u << (i & 7));
        out[i] = p ? tau : (m ? -tau : 0.0f);
    }
}

// ---------------------------------------------------------------------------
// Workspace arena (reference include/memory/Workspace.h: cyclic bump
// allocator; host staging buffers here — device memory is XLA's job)
// ---------------------------------------------------------------------------

struct Workspace {
    char* base;
    int64_t capacity;
    int64_t offset;        // bump pointer
    int64_t spilled;       // bytes served by malloc when arena is full
    std::vector<void*> spill_allocs;
    std::mutex mu;
};

void* ws_create(int64_t bytes) {
    auto* ws = new (std::nothrow) Workspace();
    if (!ws) return nullptr;
    // 64-byte-aligned base: offset alignment in ws_alloc only yields
    // aligned POINTERS if the base itself is aligned (malloc is 16)
    int64_t rounded = (bytes + 63) & ~int64_t(63);
    ws->base = static_cast<char*>(std::aligned_alloc(64, rounded));
    if (!ws->base) { delete ws; return nullptr; }
    ws->capacity = bytes;
    ws->offset = 0;
    ws->spilled = 0;
    return ws;
}

// 64-byte-aligned bump alloc; falls back to malloc "spill" when the
// arena is exhausted (reference workspaces spill to external allocs and
// learn the high-water mark for the next cycle).
void* ws_alloc(void* handle, int64_t bytes) {
    auto* ws = static_cast<Workspace*>(handle);
    std::lock_guard<std::mutex> lk(ws->mu);
    int64_t aligned = (ws->offset + 63) & ~int64_t(63);
    if (aligned + bytes <= ws->capacity) {
        ws->offset = aligned + bytes;
        return ws->base + aligned;
    }
    void* p = std::malloc(bytes);
    if (p) {
        ws->spill_allocs.push_back(p);
        ws->spilled += bytes;
    }
    return p;
}

// End-of-cycle reset: frees spills, rewinds the bump pointer, returns
// the high-water mark (arena use + spill) so callers can grow.
int64_t ws_reset(void* handle) {
    auto* ws = static_cast<Workspace*>(handle);
    std::lock_guard<std::mutex> lk(ws->mu);
    int64_t high_water = ws->offset + ws->spilled;
    for (void* p : ws->spill_allocs) std::free(p);
    ws->spill_allocs.clear();
    ws->offset = 0;
    ws->spilled = 0;
    return high_water;
}

int64_t ws_capacity(void* handle) {
    return static_cast<Workspace*>(handle)->capacity;
}

void ws_destroy(void* handle) {
    auto* ws = static_cast<Workspace*>(handle);
    ws_reset(handle);
    std::free(ws->base);
    delete ws;
}

// ---------------------------------------------------------------------------
// Blocking MPMC ring queue (prefetch backbone; reference
// AsyncDataSetIterator's bounded queue + IndexedTail fan-out)
// ---------------------------------------------------------------------------

struct Ring {
    std::deque<int64_t> q;
    int64_t capacity;
    bool closed = false;
    std::mutex mu;
    std::condition_variable cv_push, cv_pop;
};

void* ring_create(int64_t capacity) {
    auto* r = new (std::nothrow) Ring();
    if (!r) return nullptr;
    r->capacity = capacity;
    return r;
}

// Blocking push of an opaque token (Python passes buffer indices).
// Returns 0 on success, -1 if the ring is closed.
int ring_push(void* handle, int64_t token) {
    auto* r = static_cast<Ring*>(handle);
    std::unique_lock<std::mutex> lk(r->mu);
    r->cv_push.wait(lk, [&] {
        return r->closed ||
               static_cast<int64_t>(r->q.size()) < r->capacity;
    });
    if (r->closed) return -1;
    r->q.push_back(token);
    r->cv_pop.notify_one();
    return 0;
}

// Blocking pop; returns 0 and sets *token, or -1 when closed AND
// drained (the end-of-stream signal).
int ring_pop(void* handle, int64_t* token) {
    auto* r = static_cast<Ring*>(handle);
    std::unique_lock<std::mutex> lk(r->mu);
    r->cv_pop.wait(lk, [&] { return r->closed || !r->q.empty(); });
    if (r->q.empty()) return -1;
    *token = r->q.front();
    r->q.pop_front();
    r->cv_push.notify_one();
    return 0;
}

int64_t ring_size(void* handle) {
    auto* r = static_cast<Ring*>(handle);
    std::lock_guard<std::mutex> lk(r->mu);
    return static_cast<int64_t>(r->q.size());
}

void ring_close(void* handle) {
    auto* r = static_cast<Ring*>(handle);
    std::lock_guard<std::mutex> lk(r->mu);
    r->closed = true;
    r->cv_push.notify_all();
    r->cv_pop.notify_all();
}

void ring_destroy(void* handle) {
    delete static_cast<Ring*>(handle);
}

// ---------------------------------------------------------------------------
// Image batch ETL (reference datavec-data-image NativeImageLoader hot
// path: decoded u8 pixels -> normalized f32 NHWC batch; JavaCV/OpenCV
// there, plain threaded C++ here — decode stays in Python/PIL, the
// per-pixel convert/normalize/augment loop is the native part)
// ---------------------------------------------------------------------------

// in:  u8 [n, h, w, c] (already-decoded pixels)
// out: f32 [n, out_h, out_w, c], (x/255 - mean[ch]) / std[ch]
// crop_y/crop_x: per-image top-left crop offsets; flip: per-image
// horizontal-flip flags (augmentation decided by the Python side's rng,
// applied natively). n_threads <= 0 -> hardware concurrency.
int img_batch_normalize_u8(const uint8_t* in, int64_t n, int64_t h,
                           int64_t w, int64_t c, const int32_t* crop_y,
                           const int32_t* crop_x, const uint8_t* flip,
                           int64_t out_h, int64_t out_w,
                           const float* mean, const float* stddev,
                           float* out, int n_threads) {
    if (out_h > h || out_w > w || c > 16) return -1;
    float inv_std[16], mu[16];
    for (int64_t ch = 0; ch < c; ++ch) {
        mu[ch] = mean ? mean[ch] : 0.0f;
        float sd = stddev ? stddev[ch] : 1.0f;
        inv_std[ch] = 1.0f / (sd == 0.0f ? 1.0f : sd);
    }
    int nt = n_threads > 0
                 ? n_threads
                 : static_cast<int>(std::thread::hardware_concurrency());
    nt = std::max(1, std::min<int>(nt, static_cast<int>(n)));
    std::atomic<int64_t> next(0);
    auto worker = [&] {
        for (;;) {
            int64_t i = next.fetch_add(1);
            if (i >= n) return;
            const uint8_t* src = in + i * h * w * c;
            float* dst = out + i * out_h * out_w * c;
            int64_t cy = crop_y ? crop_y[i] : 0;
            int64_t cx = crop_x ? crop_x[i] : 0;
            cy = std::max<int64_t>(0, std::min(cy, h - out_h));
            cx = std::max<int64_t>(0, std::min(cx, w - out_w));
            bool fl = flip && flip[i];
            for (int64_t y = 0; y < out_h; ++y) {
                const uint8_t* row = src + ((cy + y) * w + cx) * c;
                for (int64_t x = 0; x < out_w; ++x) {
                    int64_t sx = fl ? (out_w - 1 - x) : x;
                    const uint8_t* px = row + sx * c;
                    float* po = dst + (y * out_w + x) * c;
                    for (int64_t ch = 0; ch < c; ++ch)
                        po[ch] = (px[ch] * (1.0f / 255.0f) - mu[ch])
                                 * inv_std[ch];
                }
            }
        }
    };
    std::vector<std::thread> ts;
    for (int t = 1; t < nt; ++t) ts.emplace_back(worker);
    worker();
    for (auto& t : ts) t.join();
    return 0;
}

// ---------------------------------------------------------------------------
// Chunked message framing (reference nd4j-aeron AeronNDArrayPublisher/
// Subscriber: ~64KB chunked NDArray messages with reassembly; the UDP
// transport itself is replaced by jax collectives/DCN, but host-side
// gradient shipping for DCN-constrained topologies still needs framing)
//
// Frame layout (little-endian):
//   u64 msg_id | u32 seq | u32 total | u32 payload_len | u32 crc32
//   followed by payload_len bytes.
// ---------------------------------------------------------------------------

static uint32_t crc32_table[256];
static std::atomic<bool> crc_init_done(false);
static std::mutex crc_init_mu;

static void crc32_init() {
    if (crc_init_done.load(std::memory_order_acquire)) return;
    std::lock_guard<std::mutex> lk(crc_init_mu);
    if (crc_init_done.load(std::memory_order_relaxed)) return;
    for (uint32_t i = 0; i < 256; ++i) {
        uint32_t r = i;
        for (int j = 0; j < 8; ++j)
            r = (r >> 1) ^ (0xEDB88320u & (~(r & 1) + 1));
        crc32_table[i] = r;
    }
    crc_init_done.store(true, std::memory_order_release);
}

uint32_t dl4j_crc32(const uint8_t* p, int64_t n) {
    crc32_init();
    uint32_t crc = 0xFFFFFFFFu;
    for (int64_t i = 0; i < n; ++i)
        crc = (crc >> 8) ^ crc32_table[(crc ^ p[i]) & 0xFF];
    return crc ^ 0xFFFFFFFFu;
}

static const int64_t kHeaderLen = 8 + 4 + 4 + 4 + 4;

// Number of frames needed for a payload at the given chunk size.
int64_t chunk_count(int64_t payload_len, int64_t chunk_bytes) {
    if (chunk_bytes <= 0) return -1;
    return payload_len == 0 ? 1
                            : (payload_len + chunk_bytes - 1) / chunk_bytes;
}

int64_t chunk_frame_bytes(int64_t payload_len, int64_t chunk_bytes) {
    int64_t n = chunk_count(payload_len, chunk_bytes);
    return n < 0 ? -1 : n * kHeaderLen + payload_len;
}

// Serialize `payload` into consecutive frames in `out` (caller sizes it
// with chunk_frame_bytes). Returns the frame count, or -1 on bad args.
int64_t chunk_message(uint64_t msg_id, const uint8_t* payload,
                      int64_t payload_len, int64_t chunk_bytes,
                      uint8_t* out) {
    int64_t total = chunk_count(payload_len, chunk_bytes);
    if (total < 0) return -1;
    uint8_t* p = out;
    for (int64_t seq = 0; seq < total; ++seq) {
        int64_t off = seq * chunk_bytes;
        int64_t len = std::min(chunk_bytes, payload_len - off);
        if (len < 0) len = 0;
        uint32_t crc = dl4j_crc32(payload + off, len);
        std::memcpy(p, &msg_id, 8);
        uint32_t seq32 = static_cast<uint32_t>(seq);
        uint32_t tot32 = static_cast<uint32_t>(total);
        uint32_t len32 = static_cast<uint32_t>(len);
        std::memcpy(p + 8, &seq32, 4);
        std::memcpy(p + 12, &tot32, 4);
        std::memcpy(p + 16, &len32, 4);
        std::memcpy(p + 20, &crc, 4);
        std::memcpy(p + kHeaderLen, payload + off, len);
        p += kHeaderLen + len;
    }
    return total;
}

// Parse one frame at `buf` (which holds `len` readable bytes). Fills
// header fields, sets *payload_off to the payload start offset, and
// returns the total frame length, or -1 on truncation / -2 on CRC
// mismatch.
int64_t chunk_parse_frame(const uint8_t* buf, int64_t len,
                          uint64_t* msg_id, uint32_t* seq,
                          uint32_t* total, uint32_t* payload_len,
                          int64_t* payload_off) {
    if (len < kHeaderLen) return -1;
    std::memcpy(msg_id, buf, 8);
    std::memcpy(seq, buf + 8, 4);
    std::memcpy(total, buf + 12, 4);
    std::memcpy(payload_len, buf + 16, 4);
    uint32_t crc;
    std::memcpy(&crc, buf + 20, 4);
    if (len < kHeaderLen + static_cast<int64_t>(*payload_len)) return -1;
    if (dl4j_crc32(buf + kHeaderLen, *payload_len) != crc) return -2;
    *payload_off = kHeaderLen;
    return kHeaderLen + *payload_len;
}

// ---------------------------------------------------------------------------
// ABI versioning
// ---------------------------------------------------------------------------

int dl4j_tpu_native_abi_version() { return 2; }

}  // extern "C"
