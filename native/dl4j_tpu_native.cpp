// dl4j_tpu native runtime — C ABI, loaded from Python via ctypes.
//
// TPU-native equivalents of the reference's native runtime pieces that
// live OUTSIDE the XLA compute path (SURVEY §2.1: libnd4j memory/
// workspaces, execution engine, C ABI surface; §2.2 AeronNDArray
// chunking; datavec's native ETL):
//
//   * fast CSV float parser        (datavec CSVRecordReader hot path;
//                                   reference: JavaCV/Java parsing)
//   * threshold gradient codec     (libnd4j encode_threshold /
//     + bitmap pack                 decode_threshold, bitmap encode —
//                                   host-side flavor for DCN shipping;
//                                   the on-device flavor is XLA/Pallas)
//   * workspace arena allocator    (include/memory/Workspace.h: bump
//                                   arena with reset/scope semantics)
//   * blocking MPMC ring queue     (the prefetch machinery behind
//                                   AsyncDataSetIterator / IndexedTail
//                                   fan-out queues)
//
// Pure C++17 + std::thread; no external deps. Built by native/Makefile
// (or deeplearning4j_tpu/native.py on first import) into
// libdl4j_tpu_native.so. Every entry point is exercised against the
// pure-Python fallback in tests/test_native.py.

#include <algorithm>
#include <atomic>
#include <cctype>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <new>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// CSV fast path
// ---------------------------------------------------------------------------

// Parse a numeric CSV buffer into `out` (row-major), returning 0 on
// success. Rows are '\n'-separated (trailing '\r' tolerated), fields by
// `delim`. Empty lines are skipped. On any non-numeric field returns -2
// (caller falls back to the general Python reader). Returns -1 if the
// parsed element count would exceed `max_out`. n_rows/n_cols receive
// the shape; ragged rows return -3.
int csv_parse_f32(const char* buf, int64_t len, char delim, int skip_rows,
                  float* out, int64_t max_out,
                  int64_t* n_rows, int64_t* n_cols) {
    int64_t rows = 0, cols = -1, n = 0;
    const char* p = buf;
    const char* end = buf + len;
    while (p < end) {
        const char* line_end =
            static_cast<const char*>(memchr(p, '\n', end - p));
        if (!line_end) line_end = end;
        const char* le = line_end;
        if (le > p && le[-1] == '\r') --le;
        if (le == p) { p = line_end + 1; continue; }  // empty line
        if (skip_rows > 0) { --skip_rows; p = line_end + 1; continue; }
        int64_t row_cols = 0;
        const char* f = p;
        while (f <= le) {
            const char* fe = f;
            while (fe < le && *fe != delim) ++fe;
            // parse [f, fe) as float
            char tmp[64];
            int64_t flen = fe - f;
            // trim spaces
            while (flen > 0 && isspace(static_cast<unsigned char>(*f))) {
                ++f; --flen;
            }
            while (flen > 0 &&
                   isspace(static_cast<unsigned char>(f[flen - 1])))
                --flen;
            if (flen <= 0 || flen >= 63) return -2;
            memcpy(tmp, f, flen);
            tmp[flen] = '\0';
            char* endptr = nullptr;
            float v = strtof(tmp, &endptr);
            if (endptr != tmp + flen) return -2;
            if (n >= max_out) return -1;
            out[n++] = v;
            ++row_cols;
            if (fe >= le) break;
            f = fe + 1;
        }
        if (cols < 0) cols = row_cols;
        else if (cols != row_cols) return -3;
        ++rows;
        p = line_end + 1;
    }
    *n_rows = rows;
    *n_cols = cols < 0 ? 0 : cols;
    return 0;
}

// ---------------------------------------------------------------------------
// Threshold gradient codec (reference libnd4j encode_threshold /
// decode_threshold / bitmap encode — SURVEY §2.3 EncodedGradients row)
// ---------------------------------------------------------------------------

// g -> ternary sign (|g|>tau), residual = g - tau*sign. Returns count of
// non-zeros (the reference's encoded-update length).
int64_t encode_threshold_f32(const float* g, int64_t n, float tau,
                             int8_t* sign, float* residual) {
    int64_t nnz = 0;
    for (int64_t i = 0; i < n; ++i) {
        float v = g[i];
        int8_t s = (v > tau) ? 1 : (v < -tau ? -1 : 0);
        sign[i] = s;
        residual[i] = v - tau * static_cast<float>(s);
        nnz += (s != 0);
    }
    return nnz;
}

void decode_threshold_f32(const int8_t* sign, int64_t n, float tau,
                          float* out) {
    for (int64_t i = 0; i < n; ++i)
        out[i] = tau * static_cast<float>(sign[i]);
}

// Pack ternary signs into two bitmaps (pos/neg), 8 elements/byte each —
// 16x smaller than f32. n_bytes = ceil(n/8).
void bitmap_encode(const int8_t* sign, int64_t n, uint8_t* pos,
                   uint8_t* neg) {
    int64_t nb = (n + 7) / 8;
    memset(pos, 0, nb);
    memset(neg, 0, nb);
    for (int64_t i = 0; i < n; ++i) {
        if (sign[i] > 0) pos[i >> 3] |= (1u << (i & 7));
        else if (sign[i] < 0) neg[i >> 3] |= (1u << (i & 7));
    }
}

void bitmap_decode(const uint8_t* pos, const uint8_t* neg, int64_t n,
                   float tau, float* out) {
    for (int64_t i = 0; i < n; ++i) {
        bool p = pos[i >> 3] & (1u << (i & 7));
        bool m = neg[i >> 3] & (1u << (i & 7));
        out[i] = p ? tau : (m ? -tau : 0.0f);
    }
}

// ---------------------------------------------------------------------------
// Workspace arena (reference include/memory/Workspace.h: cyclic bump
// allocator; host staging buffers here — device memory is XLA's job)
// ---------------------------------------------------------------------------

struct Workspace {
    char* base;
    int64_t capacity;
    int64_t offset;        // bump pointer
    int64_t spilled;       // bytes served by malloc when arena is full
    std::vector<void*> spill_allocs;
    std::mutex mu;
};

void* ws_create(int64_t bytes) {
    auto* ws = new (std::nothrow) Workspace();
    if (!ws) return nullptr;
    ws->base = static_cast<char*>(std::malloc(bytes));
    if (!ws->base) { delete ws; return nullptr; }
    ws->capacity = bytes;
    ws->offset = 0;
    ws->spilled = 0;
    return ws;
}

// 64-byte-aligned bump alloc; falls back to malloc "spill" when the
// arena is exhausted (reference workspaces spill to external allocs and
// learn the high-water mark for the next cycle).
void* ws_alloc(void* handle, int64_t bytes) {
    auto* ws = static_cast<Workspace*>(handle);
    std::lock_guard<std::mutex> lk(ws->mu);
    int64_t aligned = (ws->offset + 63) & ~int64_t(63);
    if (aligned + bytes <= ws->capacity) {
        ws->offset = aligned + bytes;
        return ws->base + aligned;
    }
    void* p = std::malloc(bytes);
    if (p) {
        ws->spill_allocs.push_back(p);
        ws->spilled += bytes;
    }
    return p;
}

// End-of-cycle reset: frees spills, rewinds the bump pointer, returns
// the high-water mark (arena use + spill) so callers can grow.
int64_t ws_reset(void* handle) {
    auto* ws = static_cast<Workspace*>(handle);
    std::lock_guard<std::mutex> lk(ws->mu);
    int64_t high_water = ws->offset + ws->spilled;
    for (void* p : ws->spill_allocs) std::free(p);
    ws->spill_allocs.clear();
    ws->offset = 0;
    ws->spilled = 0;
    return high_water;
}

int64_t ws_capacity(void* handle) {
    return static_cast<Workspace*>(handle)->capacity;
}

void ws_destroy(void* handle) {
    auto* ws = static_cast<Workspace*>(handle);
    ws_reset(handle);
    std::free(ws->base);
    delete ws;
}

// ---------------------------------------------------------------------------
// Blocking MPMC ring queue (prefetch backbone; reference
// AsyncDataSetIterator's bounded queue + IndexedTail fan-out)
// ---------------------------------------------------------------------------

struct Ring {
    std::deque<int64_t> q;
    int64_t capacity;
    bool closed = false;
    std::mutex mu;
    std::condition_variable cv_push, cv_pop;
};

void* ring_create(int64_t capacity) {
    auto* r = new (std::nothrow) Ring();
    if (!r) return nullptr;
    r->capacity = capacity;
    return r;
}

// Blocking push of an opaque token (Python passes buffer indices).
// Returns 0 on success, -1 if the ring is closed.
int ring_push(void* handle, int64_t token) {
    auto* r = static_cast<Ring*>(handle);
    std::unique_lock<std::mutex> lk(r->mu);
    r->cv_push.wait(lk, [&] {
        return r->closed ||
               static_cast<int64_t>(r->q.size()) < r->capacity;
    });
    if (r->closed) return -1;
    r->q.push_back(token);
    r->cv_pop.notify_one();
    return 0;
}

// Blocking pop; returns 0 and sets *token, or -1 when closed AND
// drained (the end-of-stream signal).
int ring_pop(void* handle, int64_t* token) {
    auto* r = static_cast<Ring*>(handle);
    std::unique_lock<std::mutex> lk(r->mu);
    r->cv_pop.wait(lk, [&] { return r->closed || !r->q.empty(); });
    if (r->q.empty()) return -1;
    *token = r->q.front();
    r->q.pop_front();
    r->cv_push.notify_one();
    return 0;
}

int64_t ring_size(void* handle) {
    auto* r = static_cast<Ring*>(handle);
    std::lock_guard<std::mutex> lk(r->mu);
    return static_cast<int64_t>(r->q.size());
}

void ring_close(void* handle) {
    auto* r = static_cast<Ring*>(handle);
    std::lock_guard<std::mutex> lk(r->mu);
    r->closed = true;
    r->cv_push.notify_all();
    r->cv_pop.notify_all();
}

void ring_destroy(void* handle) {
    delete static_cast<Ring*>(handle);
}

// ---------------------------------------------------------------------------
// ABI versioning
// ---------------------------------------------------------------------------

int dl4j_tpu_native_abi_version() { return 1; }

}  // extern "C"
