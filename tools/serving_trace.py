"""Synthetic serving-trace driver — shell CLI over
``deeplearning4j_tpu/serving/loadgen.py``.

Drives sustained multi-tenant load (open- or closed-loop) against a
live continuous-batching gateway and prints the serving SLO quartet:
p50/p99 TTFT, per-token latency, tokens/sec, shed rate — plus the
request-at-a-time ``generate()`` baseline for the speedup column. The
same numbers flow through the ``dl4j_tpu_serving_*`` metric families,
so a run with ``DL4J_TPU_METRICS_PORT`` set is scrapeable (and
``tools/tpu_watch.py`` renders a ``serving`` view per sample).

    python tools/serving_trace.py --smoke                 # CPU wiring run
    python tools/serving_trace.py --shared-prefix         # CoW + spec preset
    python tools/serving_trace.py --mode open --rate 200 \\
        --requests 256 --tenants 4 --slots 16             # open-loop sweep
    python tools/serving_trace.py --mode closed --clients 32 --baseline
    python tools/serving_trace.py --mode burst --prefix-sharing \\
        --spec-k 4                                        # custom shared run

The ``--shared-prefix`` preset runs ``loadgen.shared_prefix_report``:
one long system prompt shared across tenants, baseline gateway vs the
prefix-sharing + speculative-decode gateway, reporting prefix-hit
rate and prefill tokens saved beside the TTFT/tokens-sec speedups.

Exit status 0; one JSON report on stdout (last line).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

# sitecustomize routes to the axon TPU tunnel; trace runs opt into the
# real chip explicitly (same contract as tools/chaos.py)
if os.environ.get("DL4J_TPU_EXAMPLE_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")


def main() -> int:
    from deeplearning4j_tpu.serving import ServingGateway, loadgen
    from deeplearning4j_tpu.zoo import CausalTransformerLM, GPTMini

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="the bench/dossier CPU smoke row "
                         "(loadgen.smoke_report) and exit")
    ap.add_argument("--shared-prefix", action="store_true",
                    help="the spec-decode + prefix-sharing acceptance "
                         "row (loadgen.shared_prefix_report) and exit")
    ap.add_argument("--spec-k", type=int, default=1,
                    help="speculative decode width (1 = single-token)")
    ap.add_argument("--prefix-sharing", action="store_true",
                    help="enable copy-on-write prefix sharing")
    ap.add_argument("--mode", choices=("open", "closed", "burst"),
                    default="closed")
    ap.add_argument("--rate", type=float, default=100.0,
                    help="open-loop arrival rate (req/s)")
    ap.add_argument("--clients", type=int, default=16,
                    help="closed-loop concurrent callers")
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--tenants", type=int, default=2)
    ap.add_argument("--prompt-lens", default="4:48",
                    help="lo:hi prompt length bounds")
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--slots", type=int, default=16)
    ap.add_argument("--block", type=int, default=16)
    ap.add_argument("--pages", type=int, default=0,
                    help="pool pages (0 = full capacity)")
    ap.add_argument("--max-context", type=int, default=0)
    ap.add_argument("--queue-limit", type=int, default=128)
    ap.add_argument("--deadline", type=float, default=None,
                    help="per-request admission deadline (s)")
    ap.add_argument("--model", choices=("smoke", "mini"),
                    default="smoke")
    ap.add_argument("--baseline", action="store_true",
                    help="also measure request-at-a-time generate()")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    if args.smoke:
        print(json.dumps(loadgen.smoke_report()))
        return 0
    if args.shared_prefix:
        print(json.dumps(loadgen.shared_prefix_report()))
        return 0

    if args.model == "mini":
        model = GPTMini(compute_dtype=None)
    else:
        model = CausalTransformerLM(vocab_size=512, hidden=256,
                                    n_layers=4, n_heads=4,
                                    n_kv_heads=2, max_len=256, seed=3)
    net = model.init()
    lo, hi = (int(x) for x in args.prompt_lens.split(":"))
    mc = args.max_context or min(
        model.max_len,
        ((hi + args.max_new + args.block - 1) // args.block + 1)
        * args.block)
    requests = loadgen.gen_requests(
        n_requests=args.requests,
        tenants=tuple(f"tenant{i}" for i in range(args.tenants)),
        prompt_lens=(lo, hi), max_new=args.max_new,
        vocab_size=model.vocab_size, seed=args.seed)

    report = {"model": args.model, "slots": args.slots,
              "block": args.block, "max_context": mc,
              "spec_k": args.spec_k,
              "prefix_sharing": args.prefix_sharing}
    if args.baseline:
        # full warm pass first: every prompt BUCKET must compile
        # before the timed run, or cold jits deflate the baseline and
        # overstate the speedup column
        loadgen.baseline_tokens_per_sec(model, net, requests)
        report["request_at_a_time_tokens_per_sec"] = round(
            loadgen.baseline_tokens_per_sec(model, net, requests), 2)

    gw = ServingGateway(model, net, max_slots=args.slots,
                        block=args.block,
                        n_pages=args.pages or None, max_context=mc,
                        queue_limit=args.queue_limit,
                        default_max_new=args.max_new,
                        spec_k=args.spec_k,
                        prefix_sharing=args.prefix_sharing)
    report["warmup"] = gw.warmup(prompt_lens=range(1, hi + 1))
    stats = loadgen.run_trace(gw, requests, mode=args.mode,
                              rate=args.rate, clients=args.clients,
                              deadline_s=args.deadline)
    gw.shutdown()
    report.update(stats)
    if args.baseline and stats["tokens_per_sec"]:
        report["speedup"] = round(
            stats["tokens_per_sec"]
            / report["request_at_a_time_tokens_per_sec"], 3)
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
