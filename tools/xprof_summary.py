"""Per-op summary of an XProf capture (VERDICT r4 ask #5).

Parses the ``*.xplane.pb`` a ``jax.profiler.trace`` run writes (e.g.
``perf_dossier.py --trace DIR``) with ``jax.profiler.ProfileData`` —
no tensorboard needed — and prints, from the device plane's "XLA Ops"
line:

- steps observed and mean device step time (cross-checks the
  wall-clock differencing protocol in ``perf_dossier._timeit``);
- total device time by op CLASS (fusion kinds, custom-call = Pallas
  kernels, convolution/dot = MXU, copies, ...);
- the top-K individual ops by total time with their share.

    python tools/xprof_summary.py DIR [--top 10]

``DIR`` is the trace dir; the newest ``*.xplane.pb`` under it is read.
"""
from __future__ import annotations

import argparse
import re
from collections import defaultdict
from pathlib import Path


_NAME_RE = re.compile(r"%([a-zA-Z0-9_-]+?)(?:\.\d+)? =")
_KIND_RE = re.compile(r"kind=(k\w+)")


def _classify(name: str) -> str:
    m = _NAME_RE.search(name)
    base = m.group(1) if m else name.split(" ")[0].lstrip("%")
    if base == "fusion":
        k = _KIND_RE.search(name)
        return f"fusion:{k.group(1)[1:].lower()}" if k else "fusion"
    return base


def summarize(trace_dir: str, top: int = 10):
    import jax

    paths = sorted(Path(trace_dir).rglob("*.xplane.pb"),
                   key=lambda p: p.stat().st_mtime)
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    pd = jax.profiler.ProfileData.from_file(str(paths[-1]))
    dev = next((p for p in pd.planes if "/device:" in p.name), None)
    if dev is None:
        raise SystemExit(
            f"{paths[-1]} has no device plane — was the capture taken "
            "on CPU, or did every traced run fail before touching the "
            "device?")
    steps, per_op, per_class = [], defaultdict(float), \
        defaultdict(float)
    counts = defaultdict(int)
    for line in dev.lines:
        if line.name == "Steps":
            steps = [e.duration_ns for e in line.events]
        if line.name != "XLA Ops":
            continue
        for e in line.events:
            cls = _classify(e.name)
            if cls in ("while", "conditional", "call"):
                continue        # containers: children counted already
            per_op[e.name.split(" = ")[0]] += e.duration_ns
            per_class[cls] += e.duration_ns
            counts[cls] += 1
    total = sum(per_class.values())
    if not total:
        raise SystemExit(
            f"{paths[-1]}'s device plane has no 'XLA Ops' events — "
            "nothing executed under the trace")
    out = []
    out.append(f"steps: {len(steps)}, mean device step "
               f"{sum(steps) / max(1, len(steps)) / 1e6:.2f} ms")
    out.append("")
    out.append("| op class | total ms | % | count |")
    out.append("|---|---|---|---|")
    for cls, ns in sorted(per_class.items(), key=lambda kv: -kv[1]):
        if ns / total < 0.005:
            continue
        out.append(f"| {cls} | {ns / 1e6:.2f} | "
                   f"{100 * ns / total:.1f}% | {counts[cls]} |")
    out.append("")
    out.append(f"| top-{top} individual ops | total ms | % |")
    out.append("|---|---|---|")
    for name, ns in sorted(per_op.items(),
                           key=lambda kv: -kv[1])[:top]:
        out.append(f"| `{name[:70]}` | {ns / 1e6:.2f} | "
                   f"{100 * ns / total:.1f}% |")
    return "\n".join(out)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir")
    ap.add_argument("--top", type=int, default=10)
    args = ap.parse_args()
    print(summarize(args.trace_dir, args.top))


if __name__ == "__main__":
    main()
