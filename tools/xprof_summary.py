"""Per-op summary of an XProf capture (VERDICT r4 ask #5) — and, since
PR 2, of an ``obs`` span-trace JSONL.

XProf mode parses the ``*.xplane.pb`` a ``jax.profiler.trace`` run
writes (e.g. ``perf_dossier.py --trace DIR``) through the
dependency-free wire parser in ``obs/devtime.py`` (this jaxlib has no
``jax.profiler.ProfileData``, and the tensorboard plugin wheel ships
no xplane proto) and prints:

- steps observed and mean device step time (cross-checks the
  wall-clock differencing protocol in ``perf_dossier._timeit``);
- total device time by op CLASS (fusion kinds, custom-call = Pallas
  kernels, convolution/dot = MXU, copies, ...);
- the top-K individual ops by total time with their share.

A DIRECTORY argument resolves to the newest capture session under it
and merges EVERY ``*.xplane.pb`` of that session — one file per host,
so a multi-host capture summarizes the whole fleet instead of
silently dropping all hosts but one. An explicit ``*.xplane.pb`` FILE
argument reads exactly that plane (one host of a fleet capture).

``--comm`` mode reuses the communication observatory's attribution
(``obs/commtime.py``) over the same xplane capture: per-scope
collective device time (scope from each event's ``op_name`` metadata
when no executables are registered), per-kind collective op counts,
total comm share of device time, and the wire-bound scopes — the
offline twin of ``tpu_watch --comm``.

Obs mode reads the Chrome-trace JSONL the telemetry spine writes
(``DL4J_TPU_TRACE=...``, ``deeplearning4j_tpu/obs/trace.py``) — the
host-side step/ETL/sync attribution complementing XProf's device view
— and prints per-span-name totals, counts, and share of the traced
wall time per thread.

    python tools/xprof_summary.py DIR_OR_FILE [--top 10]

A ``*.jsonl``/``*.json`` path (or a dir containing one but no
``*.xplane.pb``) selects obs mode; a ``*.xplane.pb`` path or a
capture dir selects XProf mode.
"""
from __future__ import annotations

import argparse
import re
import sys
from collections import defaultdict
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

_NAME_RE = re.compile(r"%?([a-zA-Z0-9_-]+?)(?:\.\d+)? =")
_KIND_RE = re.compile(r"kind=(k\w+)")


def _classify(name: str) -> str:
    m = _NAME_RE.search(name)
    base = m.group(1) if m else name.split(" ")[0].lstrip("%")
    if base == "fusion":
        k = _KIND_RE.search(name)
        return f"fusion:{k.group(1)[1:].lower()}" if k else "fusion"
    # bare post-optimization names ("broadcast_maximum_fusion",
    # "dot.5") — strip the trailing .N the regex above missed
    base = name.split(" ")[0].lstrip("%")
    return base.rsplit(".", 1)[0] if \
        base.rsplit(".", 1)[-1].isdigit() else base


def summarize(trace_path: str, top: int = 10):
    """Per-op device-time table from an XProf capture: an explicit
    ``*.xplane.pb`` file, or a dir whose NEWEST session's planes are
    all merged (multi-host captures keep every host)."""
    from deeplearning4j_tpu.obs import devtime

    paths = devtime.xplane_paths(trace_path)
    steps, per_op, per_class = [], defaultdict(float), \
        defaultdict(float)
    counts = defaultdict(int)
    for p in paths:
        xs = devtime.read_xspace(p)
        steps.extend(devtime.step_durations_ns(xs))
        for ev in devtime.op_events(xs):
            cls = _classify(ev["op"])
            if cls in ("while", "conditional", "call"):
                continue        # containers: children counted already
            per_op[ev["op"]] += ev["dur_ns"]
            per_class[cls] += ev["dur_ns"]
            counts[cls] += 1
    total = sum(per_class.values())
    if not total:
        raise SystemExit(
            f"{trace_path} has no XLA-op execution events — nothing "
            "executed under the trace (or the capture is host-only)")
    out = []
    out.append(f"planes: {len(paths)} file(s) "
               f"({', '.join(Path(p).name for p in paths)})")
    if steps:
        out.append(f"steps: {len(steps)}, mean device step "
                   f"{sum(steps) / max(1, len(steps)) / 1e6:.2f} ms")
    out.append("")
    out.append("| op class | total ms | % | count |")
    out.append("|---|---|---|---|")
    for cls, ns in sorted(per_class.items(), key=lambda kv: -kv[1]):
        if ns / total < 0.005:
            continue
        out.append(f"| {cls} | {ns / 1e6:.2f} | "
                   f"{100 * ns / total:.1f}% | {counts[cls]} |")
    out.append("")
    out.append(f"| top-{top} individual ops | total ms | % |")
    out.append("|---|---|---|")
    for name, ns in sorted(per_op.items(),
                           key=lambda kv: -kv[1])[:top]:
        out.append(f"| `{name[:70]}` | {ns / 1e6:.2f} | "
                   f"{100 * ns / total:.1f}% |")
    return "\n".join(out)


def summarize_comm(trace_path: str, top: int = 10) -> str:
    """Per-scope collective-time table from an XProf capture via the
    comm observatory's attribution. With no registered executables
    the scope join falls back to the events' ``op_name`` metadata —
    sufficient for any capture of ``named_scope``-annotated programs
    (``perf_dossier.py --trace DIR``)."""
    from deeplearning4j_tpu.obs import commtime, devtime

    paths = devtime.xplane_paths(trace_path)
    view = commtime.attribute(paths, maps=None)
    if not view["total_device_ms"]:
        raise SystemExit(
            f"{trace_path} has no XLA-op execution events — nothing "
            "executed under the trace (or the capture is host-only)")
    out = [f"planes: {view['planes']} file(s); total device "
           f"{view['total_device_ms']:.2f} ms, collective "
           f"{view['collective_ms']:.2f} ms "
           f"({100 * view['comm_share']:.1f}%)"]
    if view["estimate_only"]:
        out.append("NOTE: non-TPU capture — collective timings are "
                   "host-side copies, estimate-only")
    if view["by_kind"]:
        out.append("op counts: " + ", ".join(
            f"{c}× {k}" for k, c in view["by_kind"].items()))
    out.append("")
    out.append("| scope | collective ms | share of device | kinds |")
    out.append("|---|---|---|---|")
    ranked = sorted(view["scopes"].items(),
                    key=lambda kv: -kv[1]["collective_ms"])[:top]
    for name, r in ranked:
        kinds = ", ".join(f"{c}× {k}"
                          for k, c in sorted(r["kinds"].items()))
        out.append(f"| {name} | {r['collective_ms']:.3f} | "
                   f"{100 * r['share']:.1f}% | {kinds or '—'} |")
    if view["wire_bound_scopes"]:
        out.append("")
        out.append("wire-bound scopes: "
                   + ", ".join(view["wire_bound_scopes"]))
    return "\n".join(out)


def summarize_obs(path: str, top: int = 10) -> str:
    """Span-name totals from an obs trace JSONL: wall coverage per
    thread, per-name total/count/share — the table the acceptance
    criterion ("spans cover >= 95% of wall time with ETL/step/sync
    attribution") is eyeballed against."""
    import sys as _sys
    _sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from deeplearning4j_tpu.obs import trace as obs_trace

    events = obs_trace.read_trace(path)
    spans = [e for e in events if e.get("ph") == "X"]
    if not spans:
        raise SystemExit(f"{path} contains no complete ('X') spans")
    names = {}
    tid_names = {e["tid"]: e["args"]["name"] for e in events
                 if e.get("ph") == "M"
                 and e.get("name") == "thread_name"}
    by_tid = defaultdict(list)
    for e in spans:
        by_tid[e["tid"]].append(e)
        k = e["name"]
        tot, cnt = names.get(k, (0.0, 0))
        names[k] = (tot + e.get("dur", 0.0), cnt + 1)
    wall = (max(e["ts"] + e.get("dur", 0.0) for e in spans)
            - min(e["ts"] for e in spans))
    out = [f"events: {len(spans)} spans over {wall / 1e3:.1f} ms "
           f"wall, {len(by_tid)} thread(s)"]
    for tid, evs in sorted(by_tid.items()):
        t_wall = (max(e["ts"] + e.get("dur", 0.0) for e in evs)
                  - min(e["ts"] for e in evs)) or 1.0
        # top-level spans only (not contained in any other span of the
        # thread) so nested phases don't double-count coverage
        evs_sorted = sorted(evs, key=lambda e: (e["ts"],
                                                -e.get("dur", 0.0)))
        covered = end = 0.0
        for e in evs_sorted:
            s, d = e["ts"], e.get("dur", 0.0)
            if s + d <= end:
                continue
            covered += (s + d) - max(s, end)
            end = s + d
        out.append(f"thread {tid_names.get(tid, tid)}: "
                   f"{100 * covered / t_wall:.1f}% of "
                   f"{t_wall / 1e3:.1f} ms covered by spans")
    out.append("")
    out.append(f"| span | total ms | % | count |")
    out.append("|---|---|---|---|")
    for k, (tot, cnt) in sorted(names.items(),
                                key=lambda kv: -kv[1][0])[:top]:
        out.append(f"| {k} | {tot / 1e3:.2f} | "
                   f"{100 * tot / wall:.1f}% | {cnt} |")
    return "\n".join(out)


def _is_obs_trace(path: Path) -> bool:
    if path.is_file():
        return path.suffix in (".jsonl", ".json")
    return (not any(path.rglob("*.xplane.pb"))
            and any(path.rglob("*.jsonl")))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir",
                    help="XProf capture dir, or an obs trace JSONL")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--comm", action="store_true",
                    help="per-scope COLLECTIVE time view of an xplane "
                         "capture (obs/commtime.py attribution)")
    args = ap.parse_args()
    p = Path(args.trace_dir)
    if args.comm:
        print(summarize_comm(args.trace_dir, args.top))
    elif _is_obs_trace(p):
        if p.is_dir():
            p = sorted(p.rglob("*.jsonl"),
                       key=lambda q: q.stat().st_mtime)[-1]
        print(summarize_obs(str(p), args.top))
    else:
        print(summarize(args.trace_dir, args.top))


if __name__ == "__main__":
    main()
