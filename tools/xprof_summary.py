"""Per-op summary of an XProf capture (VERDICT r4 ask #5) — and, since
PR 2, of an ``obs`` span-trace JSONL.

XProf mode parses the ``*.xplane.pb`` a ``jax.profiler.trace`` run
writes (e.g. ``perf_dossier.py --trace DIR``) with
``jax.profiler.ProfileData`` — no tensorboard needed — and prints,
from the device plane's "XLA Ops" line:

- steps observed and mean device step time (cross-checks the
  wall-clock differencing protocol in ``perf_dossier._timeit``);
- total device time by op CLASS (fusion kinds, custom-call = Pallas
  kernels, convolution/dot = MXU, copies, ...);
- the top-K individual ops by total time with their share.

Obs mode reads the Chrome-trace JSONL the telemetry spine writes
(``DL4J_TPU_TRACE=...``, ``deeplearning4j_tpu/obs/trace.py``) — the
host-side step/ETL/sync attribution complementing XProf's device view
— and prints per-span-name totals, counts, and share of the traced
wall time per thread.

    python tools/xprof_summary.py DIR_OR_TRACE [--top 10]

A ``*.jsonl``/``*.json`` path (or a dir containing one but no
``*.xplane.pb``) selects obs mode; otherwise the newest
``*.xplane.pb`` under the dir is read.
"""
from __future__ import annotations

import argparse
import re
from collections import defaultdict
from pathlib import Path


_NAME_RE = re.compile(r"%([a-zA-Z0-9_-]+?)(?:\.\d+)? =")
_KIND_RE = re.compile(r"kind=(k\w+)")


def _classify(name: str) -> str:
    m = _NAME_RE.search(name)
    base = m.group(1) if m else name.split(" ")[0].lstrip("%")
    if base == "fusion":
        k = _KIND_RE.search(name)
        return f"fusion:{k.group(1)[1:].lower()}" if k else "fusion"
    return base


def summarize(trace_dir: str, top: int = 10):
    import jax

    paths = sorted(Path(trace_dir).rglob("*.xplane.pb"),
                   key=lambda p: p.stat().st_mtime)
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    pd = jax.profiler.ProfileData.from_file(str(paths[-1]))
    dev = next((p for p in pd.planes if "/device:" in p.name), None)
    if dev is None:
        raise SystemExit(
            f"{paths[-1]} has no device plane — was the capture taken "
            "on CPU, or did every traced run fail before touching the "
            "device?")
    steps, per_op, per_class = [], defaultdict(float), \
        defaultdict(float)
    counts = defaultdict(int)
    for line in dev.lines:
        if line.name == "Steps":
            steps = [e.duration_ns for e in line.events]
        if line.name != "XLA Ops":
            continue
        for e in line.events:
            cls = _classify(e.name)
            if cls in ("while", "conditional", "call"):
                continue        # containers: children counted already
            per_op[e.name.split(" = ")[0]] += e.duration_ns
            per_class[cls] += e.duration_ns
            counts[cls] += 1
    total = sum(per_class.values())
    if not total:
        raise SystemExit(
            f"{paths[-1]}'s device plane has no 'XLA Ops' events — "
            "nothing executed under the trace")
    out = []
    out.append(f"steps: {len(steps)}, mean device step "
               f"{sum(steps) / max(1, len(steps)) / 1e6:.2f} ms")
    out.append("")
    out.append("| op class | total ms | % | count |")
    out.append("|---|---|---|---|")
    for cls, ns in sorted(per_class.items(), key=lambda kv: -kv[1]):
        if ns / total < 0.005:
            continue
        out.append(f"| {cls} | {ns / 1e6:.2f} | "
                   f"{100 * ns / total:.1f}% | {counts[cls]} |")
    out.append("")
    out.append(f"| top-{top} individual ops | total ms | % |")
    out.append("|---|---|---|")
    for name, ns in sorted(per_op.items(),
                           key=lambda kv: -kv[1])[:top]:
        out.append(f"| `{name[:70]}` | {ns / 1e6:.2f} | "
                   f"{100 * ns / total:.1f}% |")
    return "\n".join(out)


def summarize_obs(path: str, top: int = 10) -> str:
    """Span-name totals from an obs trace JSONL: wall coverage per
    thread, per-name total/count/share — the table the acceptance
    criterion ("spans cover >= 95% of wall time with ETL/step/sync
    attribution") is eyeballed against."""
    import sys as _sys
    _sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from deeplearning4j_tpu.obs import trace as obs_trace

    events = obs_trace.read_trace(path)
    spans = [e for e in events if e.get("ph") == "X"]
    if not spans:
        raise SystemExit(f"{path} contains no complete ('X') spans")
    names = {}
    tid_names = {e["tid"]: e["args"]["name"] for e in events
                 if e.get("ph") == "M"
                 and e.get("name") == "thread_name"}
    by_tid = defaultdict(list)
    for e in spans:
        by_tid[e["tid"]].append(e)
        k = e["name"]
        tot, cnt = names.get(k, (0.0, 0))
        names[k] = (tot + e.get("dur", 0.0), cnt + 1)
    wall = (max(e["ts"] + e.get("dur", 0.0) for e in spans)
            - min(e["ts"] for e in spans))
    out = [f"events: {len(spans)} spans over {wall / 1e3:.1f} ms "
           f"wall, {len(by_tid)} thread(s)"]
    for tid, evs in sorted(by_tid.items()):
        t_wall = (max(e["ts"] + e.get("dur", 0.0) for e in evs)
                  - min(e["ts"] for e in evs)) or 1.0
        # top-level spans only (not contained in any other span of the
        # thread) so nested phases don't double-count coverage
        evs_sorted = sorted(evs, key=lambda e: (e["ts"],
                                                -e.get("dur", 0.0)))
        covered = end = 0.0
        for e in evs_sorted:
            s, d = e["ts"], e.get("dur", 0.0)
            if s + d <= end:
                continue
            covered += (s + d) - max(s, end)
            end = s + d
        out.append(f"thread {tid_names.get(tid, tid)}: "
                   f"{100 * covered / t_wall:.1f}% of "
                   f"{t_wall / 1e3:.1f} ms covered by spans")
    out.append("")
    out.append(f"| span | total ms | % | count |")
    out.append("|---|---|---|---|")
    for k, (tot, cnt) in sorted(names.items(),
                                key=lambda kv: -kv[1][0])[:top]:
        out.append(f"| {k} | {tot / 1e3:.2f} | "
                   f"{100 * tot / wall:.1f}% | {cnt} |")
    return "\n".join(out)


def _is_obs_trace(path: Path) -> bool:
    if path.is_file():
        return path.suffix in (".jsonl", ".json")
    return (not any(path.rglob("*.xplane.pb"))
            and any(path.rglob("*.jsonl")))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir",
                    help="XProf capture dir, or an obs trace JSONL")
    ap.add_argument("--top", type=int, default=10)
    args = ap.parse_args()
    p = Path(args.trace_dir)
    if _is_obs_trace(p):
        if p.is_dir():
            p = sorted(p.rglob("*.jsonl"),
                       key=lambda q: q.stat().st_mtime)[-1]
        print(summarize_obs(str(p), args.top))
    else:
        print(summarize(args.trace_dir, args.top))


if __name__ == "__main__":
    main()
