"""Perf dossier: MFU / roofline table for BASELINE.md (VERDICT r1 #5).

For each measured config reports achieved TFLOP/s and % of the v5e
chip's 197 bf16 TFLOP/s peak (MFU), from wall-clock step times synced
via scalar device→host transfers (the only reliable sync through the
axon tunnel — BASELINE.md measurement caveat).  Achieved HBM bandwidth
is NOT derivable from wall-clock alone: pass ``--trace DIR`` to wrap
the timed runs in ``jax.profiler.trace`` and read the memory-bandwidth
counters from the XProf capture (VERDICT r1 #5 asks for exactly that).

Run on the real chip:
  python tools/perf_dossier.py [--trace DIR] [--out FILE] [config ...]
Configs: resnet50 bert lstm flashbwd gpt gpt2geom gpt8k etl lenet
(default: all).
``--smoke``: tiny CPU shapes to validate wiring — table rows are
labeled ``(smoke)`` and carry no MFU claim.
Writes a markdown table to stdout; paste into BASELINE.md.
"""
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

PEAK_TFLOPS_BF16 = 197.0        # v5e MXU peak


def _sync(x):
    import jax.numpy as jnp
    return float(jnp.asarray(x).astype(jnp.float32).ravel()[0])


def _timeit(fn, sync_out, n=20, warmup=5):
    """Marginal per-step time via run-length differencing.

    Through the axon tunnel ONE scalar device→host sync costs
    ~100–150 ms (measured round 5: six back-to-back syncs 99–151 ms)
    and each dispatch ~0.5 ms, so the round-1..4 ``T(n)/n`` protocol
    overstated small steps by the amortised floor (e.g. the flash
    microbench carried ~5.5 ms/step of tunnel overhead at n=20).
    Timing n steps and 3n steps and differencing cancels the constant
    floor exactly while keeping every real per-step cost (kernel time
    + marginal dispatch); the median of 3 paired estimates absorbs the
    tunnel's RTT jitter.  No real deployment pays a 100 ms host
    round-trip per step — this measures the device, not the tunnel."""
    if SMOKE:
        # wiring validation on 1 CPU core: the differencing protocol
        # runs 12n steps — keep it tiny
        n, warmup = 1, 2
    for _ in range(warmup):
        out = fn()
    _sync(sync_out(out))
    est = []
    longs = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn()
        _sync(sync_out(out))
        t1 = time.perf_counter()
        for _ in range(3 * n):
            out = fn()
        _sync(sync_out(out))
        t2 = time.perf_counter()
        est.append(((t2 - t1) - (t1 - t0)) / (2 * n))
        longs.append(t2 - t1)
    dt = sorted(est)[1]
    # jitter guard: a negative/degenerate diff (RTT spike inside the
    # short leg) falls back to the MEDIAN raw long-leg rate
    return dt if dt > 0 else sorted(longs)[1] / (3 * n)


SMOKE = False        # --smoke: tiny shapes on CPU to validate wiring


def _drive_train_step(net):
    """Step driver shared by the image-model configs: handles the
    graph-style vs sequential calling convention and carries the
    donated params/opt/state across calls. Returns ``run(feed, ys)``
    (per-call data — the etl config feeds a fresh batch every call)
    plus the live state dict."""
    import jax
    step = net._make_train_step()
    state = {"p": net.params, "o": net.opt_state, "s": net.state}
    key = jax.random.PRNGKey(0)
    graph = hasattr(net.conf, "inputs")

    def run(feed, ys):
        if graph:
            state["p"], state["o"], state["s"], loss = step(
                state["p"], state["o"], state["s"],
                {net.conf.inputs[0]: feed}, [ys], {}, {}, key)
        else:
            state["p"], state["o"], state["s"], loss = step(
                state["p"], state["o"], state["s"], feed, ys,
                None, None, key)
        return loss

    return run, state


def resnet50():
    """ResNet-50 train step, batch 256 @ 224² bf16 (BASELINE cfg #2)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.zoo import ResNet50

    batch, size = (4, 64) if SMOKE else (256, 224)
    net = ResNet50(num_classes=1000, seed=1, input_shape=(size, size, 3),
                   updater=upd.Nesterovs(learning_rate=0.1, momentum=0.9),
                   compute_dtype="bfloat16").init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, size, size, 3)),
                    jnp.float32)
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.integers(0, 1000, batch)])
    run, _ = _drive_train_step(net)
    one = lambda: run(x, y)
    dt = _timeit(one, lambda l: l)
    # ResNet-50 fwd ≈ 4.1 GFLOP @224²/img; train ≈ 3x fwd
    flops = 3 * 4.1e9 * batch
    return ("ResNet-50 train b256@224 bf16", batch / dt, "img/s", dt,
            flops)


def bert():
    """BERT-base fine-tune step, B=64 T=128 bf16 (BASELINE cfg #4)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.zoo import BertBase

    b, t = (2, 32) if SMOKE else (64, 128)
    if SMOKE:
        from deeplearning4j_tpu.zoo import BertTiny as BertBase  # noqa
    net = BertBase(seed=2,
                   compute_dtype=None if SMOKE else "bfloat16") \
        .init_classifier(num_classes=2, seq_len=t)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, 30000, (b, t)), jnp.int32)
    segs = jnp.zeros((b, t), jnp.int32)
    y = jnp.asarray(np.eye(2, dtype=np.float32)[
        rng.integers(0, 2, b)])
    step = net._make_train_step()
    params, opt, state = net.params, net.opt_state, net.state
    key = jax.random.PRNGKey(0)
    feed = {"tokens": ids, "segments": segs}

    def one():
        nonlocal params, opt, state
        params, opt, state, loss = step(params, opt, state, feed, [y],
                                        {}, {}, key)
        return loss

    dt = _timeit(one, lambda l: l)
    flops = 6 * 109e6 * b * t             # 6·N·tokens (dense transformer)
    return ("BERT-base finetune b64 t128 bf16", b / dt, "samples/s", dt,
            flops)


def _lm_train_bench(model, b, t):
    """Shared causal-LM train-step harness (gpt/gpt2geom rows — the
    two geometries must be measured identically to be comparable):
    time the donating jitted step, rebind the net to the live buffers
    (donation deleted the originals), and derive token-FLOPs from the
    live tree. Returns (dt, flops, net)."""
    import jax
    import jax.numpy as jnp

    net = model.init(seq_len=t)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.integers(0, 200, (b, t)), jnp.int32)
    y = jnp.asarray(rng.integers(0, 200, (b, t)), jnp.int32)
    step = net._make_train_step()
    params, opt, state = net.params, net.opt_state, net.state
    key = jax.random.PRNGKey(0)

    def one():
        nonlocal params, opt, state
        params, opt, state, loss = step(params, opt, state, x, y,
                                        None, None, key)
        return loss

    dt = _timeit(one, lambda l: l)
    # the jitted step donates its inputs — net's original buffers are
    # deleted; point the net at the live copies before any further use
    net.params, net.opt_state, net.state = params, opt, state
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(net.params))
    # 6·N·tokens, plus the tied head's V×F matmul which still runs
    # fwd+bwd every step even though its params left the tree — 6·N
    # alone would understate real compute (and MFU) by ~24% when tied
    head_flops = (6 * model.vocab_size * model.hidden
                  if getattr(model, "tie_embeddings", False) else 0)
    flops = (6 * n_params + head_flops) * b * t
    return dt, flops, net


def gpt():
    """Causal-LM train step + KV-cached decode (BASELINE cfg #6 short-
    context rows: train B=8 T=1024, decode @1k-prompt B=1/B=32)."""
    from deeplearning4j_tpu.zoo import CausalTransformerLM, GPTNano

    if SMOKE:
        model = GPTNano(vocab_size=256, max_len=128)
        b, t = 2, 32
    else:
        # GPT-2-small-class geometry the TPU-native way: 12L/768 with
        # SIX d=128 heads (not GPT-2's twelve d=64) — head_dim 128
        # fills the MXU's 128-lane contraction exactly; d=64 pads
        # every attention matmul 2x. Param count, 6·N FLOPs and the
        # quadratic attention FLOPs (T²·hidden, head-count-
        # independent) are identical to the 12-head layout, so the
        # llm.c-derived bar is apples-to-apples; the comparator-
        # geometry 12xd=64 number rides in its own gpt2geom row
        # (round-5 ADVICE). TIED head, SwiGLU at the 8/3 LLaMA
        # multiplier (param-matches the classic 4x two-matrix MLP)
        # → ~124M params. n_params below is computed from the live
        # tree, so the 6·N row stays honest.
        model = CausalTransformerLM(vocab_size=50257, hidden=768,
                                    n_layers=12, n_heads=6,
                                    max_len=2048, ffn_mult=8 / 3,
                                    tie_embeddings=True,
                                    compute_dtype="bfloat16")
        b, t = 16, 1024       # measured single-chip throughput knee
    dt, flops, net = _lm_train_bench(model, b, t)
    rng = np.random.default_rng(4)

    # decode throughput (BASELINE cfg #6): GENERATED tokens/s with a
    # long prompt — prefill is one batched forward (round 4), so the
    # serving metric is per generated token, at B=1 and B=32.
    # Per-token decode rate by generation-length differencing:
    # T(3n) − T(n) cancels the prefill AND the constant tunnel
    # sync/dispatch floor (~100–150 ms per generate() — each call
    # blocks on host output), leaving the pure per-token device rate.
    # The token loop itself is a device-side lax.scan, so there is no
    # per-token host cost to hide. Also measured with the int8 KV
    # cache (cache_quant="int8", round 5): decode is cache-READ-bound
    # at batch, so int8 codes halve the dominant traffic.
    t0_len, n_new = (8, 8) if SMOKE else (1024, 128)
    q_model = CausalTransformerLM(
        vocab_size=model.vocab_size, hidden=model.hidden,
        n_layers=model.n_layers, n_heads=model.n_heads,
        max_len=model.max_len, ffn_mult=model.ffn_mult,
        tie_embeddings=model.tie_embeddings, cache_quant="int8",
        compute_dtype=model.compute_dtype) if not SMOKE else None
    decode = {}
    for db in ((1, 2) if SMOKE else (1, 32)):
        prompt = np.asarray(rng.integers(0, 200, (db, t0_len)), np.int32)
        n_lo, n_hi = n_new, 3 * n_new
        variants = [("", model)] + ([("_int8kv", q_model)]
                                    if q_model is not None else [])
        for suffix, m in variants:
            m.generate(net, prompt, n_new=n_lo)      # compile both
            m.generate(net, prompt, n_new=n_hi)      # scan lengths
            est = []
            # B=1 is the noisiest row (small absolute times vs RTT
            # jitter): give it more paired estimates
            for _ in range(5 if db == 1 else 3):
                tt = time.perf_counter()
                m.generate(net, prompt, n_new=n_lo)  # blocks (host out)
                t1 = time.perf_counter()
                m.generate(net, prompt, n_new=n_hi)
                est.append(((time.perf_counter() - t1), (t1 - tt)))
            mid = len(est) // 2               # true median index
            diff = sorted(hi_t - lo_t for hi_t, lo_t in est)[mid]
            # jitter guard (same as _timeit): an RTT spike inside the
            # short leg can make the diff non-positive — fall back to
            # the raw long-leg rate (overstates, never negative)
            if diff <= 0:
                diff = sorted(hi_t for hi_t, _ in est)[mid] \
                    * (n_hi - n_lo) / n_hi
            decode[f"B{db}{suffix}"] = db * (n_hi - n_lo) / diff
    # decode figures ride in the structured payload (BASELINE cfg #6
    # sets hard bars on them), not just the label
    extra = {"decode_tok_s": decode, "decode_prompt_len": t0_len,
             "decode_n_new": n_new}
    decode_txt = "; ".join(f"B={k[1:]}: {v:,.0f}"
                           for k, v in decode.items())
    label = (f"causal-LM train b{b} t{t} "
             f"[decode tok/s @{t0_len}-prompt {decode_txt}]")
    return (label, b * t / dt, "tok/s", dt, flops, extra)


def gpt2geom():
    """Causal-LM train step in GPT-2's EXACT head geometry — twelve
    d=64 heads — published alongside gpt()'s MXU-native 6xd=128 row
    wherever the llm.c-derived bar is cited (round-5 ADVICE): the bar
    comes from llm.c's 12-head GPT-2, so the comparator-geometry
    number must ride with the headline one. Params, 6·N FLOPs and the
    quadratic attention FLOPs are identical across the two layouts;
    only MXU lane fill differs (d=64 pads every attention matmul 2x —
    measured round 5 at 0.82x of the 6x128 row)."""
    from deeplearning4j_tpu.zoo import CausalTransformerLM

    if SMOKE:
        # same toy scale as GPTNano but in halved-head-dim geometry
        model = CausalTransformerLM(vocab_size=256, hidden=128,
                                    n_layers=4, n_heads=8,
                                    max_len=256)
        b, t = 2, 32
    else:
        model = CausalTransformerLM(vocab_size=50257, hidden=768,
                                    n_layers=12, n_heads=12,
                                    max_len=2048, ffn_mult=8 / 3,
                                    tie_embeddings=True,
                                    compute_dtype="bfloat16")
        b, t = 16, 1024               # same knee as gpt()
    dt, flops, _net = _lm_train_bench(model, b, t)
    return (f"causal-LM train b{b} t{t} GPT-2 geometry 12xd=64 "
            "(llm.c comparator)", b * t / dt, "tok/s", dt, flops)


def gpt8k():
    """Causal-LM train step at T=8192 (BASELINE cfg #6 long-context
    row): flash attention, single chip. Remat is OFF — at B=2 the
    flash-path activations fit in HBM and skipping the recompute is
    ~25% faster (remat's job is fitting, not speed; it stays tested
    and kicks in for deeper/longer settings). Multi-chip zigzag-ring
    at this length is exercised on the virtual mesh
    (tests + dryrun_multichip); this row is the one-chip number."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.zoo import CausalTransformerLM, GPTNano

    if SMOKE:
        model = GPTNano(vocab_size=256, max_len=512, remat=True)
        b, t = 1, 256
    else:
        # remat OFF: at B=2 T=8192 the flash-path activations fit in
        # HBM and skipping the recompute is ~25% faster — remat's job
        # is fitting, not speed (the remat config stays tested in
        # tests/test_gpt.py and kicks in for deeper/longer settings)
        # six d=128 heads — the MXU-native head geometry (see gpt());
        # at T=8k attention is ~70% of the step, so the 2x MXU
        # utilisation on every attention matmul moves the whole row
        model = CausalTransformerLM(vocab_size=50257, hidden=768,
                                    n_layers=12, n_heads=6,
                                    max_len=8192, remat=False,
                                    ffn_mult=8 / 3,
                                    tie_embeddings=True,
                                    compute_dtype="bfloat16")
        b, t = 2, 8192
    net = model.init(seq_len=t)
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.integers(0, 200, (b, t)), jnp.int32)
    y = jnp.asarray(rng.integers(0, 200, (b, t)), jnp.int32)
    step = net._make_train_step()
    params, opt, state = net.params, net.opt_state, net.state
    key = jax.random.PRNGKey(0)

    def one():
        nonlocal params, opt, state
        params, opt, state, loss = step(params, opt, state, x, y,
                                        None, None, key)
        return loss

    dt = _timeit(one, lambda l: l, n=10)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(params))
    # 6·N·tokens plus the tied head's still-executed V×F matmul plus
    # the quadratic attention term (≈7·B·T²·hidden per layer for
    # causal fwd+bwd) — at T=8k attention is no longer noise
    head_flops = (6 * model.vocab_size * model.hidden
                  if getattr(model, "tie_embeddings", False) else 0)
    flops = ((6 * n_params + head_flops) * b * t
             + model.n_layers * 7 * b * t * t * model.hidden)
    return (f"causal-LM train b{b} t{t} flash",
            b * t / dt, "tok/s", dt, flops)


def lstm():
    """GravesLSTM char-RNN config (BASELINE cfg #3)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.zoo import TextGenerationLSTM

    vocab, b, t = (12, 4, 20) if SMOKE else (77, 64, 200)
    net = TextGenerationLSTM(vocab_size=vocab,
                             hidden=16 if SMOKE else 512,
                             layers=1 if SMOKE else 2,
                             seed=3, tbptt=10 if SMOKE else 50).init()
    rng = np.random.default_rng(2)
    ids = rng.integers(0, vocab, (b, t + 1))
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[ids[:, :-1]])
    y = jnp.asarray(np.eye(vocab, dtype=np.float32)[ids[:, 1:]])
    step = net._make_train_step()
    params, opt, state = net.params, net.opt_state, net.state
    key = jax.random.PRNGKey(0)

    def one():
        nonlocal params, opt, state
        params, opt, state, loss = step(params, opt, state, x, y,
                                        None, None, key)
        return loss

    dt = _timeit(one, lambda l: l, n=10)
    # 2-layer 512 peephole LSTM: ~2·(4·(d_in·d_h + d_h²))·T·B·3(train)
    d = 512
    flops = 3 * 2 * (4 * (vocab * d + d * d) + 4 * 2 * d * d) * t * b
    return ("charRNN 2x512 b64 t200", b * t / dt, "chars/s", dt, flops)


def lenet():
    """LeNet MNIST-shape train step (BASELINE cfg #1 throughput half;
    the ACCURACY half runs on real files via DL4J_TPU_MNIST_DIR —
    synthetic-shape throughput is labeled as such)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.zoo import LeNet

    b = 8 if SMOKE else 512
    net = LeNet(num_classes=10, seed=0).init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((b, 28, 28, 1)), jnp.float32)
    y = jnp.asarray(np.eye(10, dtype=np.float32)[
        rng.integers(0, 10, b)])
    run, _ = _drive_train_step(net)
    one = lambda: run(x, y)
    dt = _timeit(one, lambda l: l, n=30)
    # the ZOO LeNet (20ch 5×5 SAME conv + 50ch 5×5 SAME conv + dense
    # 500): fwd ≈ 0.78M (conv1) + 9.8M (conv2) + 2.45M (dense) ≈
    # 13.1 MFLOP/img; train ≈ 3× fwd
    flops = 3 * 13.1e6 * b
    return ("LeNet train b512 @28x28 (synthetic MNIST shapes)",
            b / dt, "img/s", dt, flops)


def etl():
    """ResNet-50 train with the REAL input pipeline on the clock
    (VERDICT r4 Missing #2): synthetic ImageNet-shaped JPEGs on disk
    → ImageRecordReader (decode + resize) → random crop/flip augment
    → ImagePreProcessingScaler → AsyncDataSetIterator prefetch →
    device step. Reports end-to-end img/s AND ETL-wait% — the
    reference PerformanceListener's ETL metric: cumulative time the
    consumer blocked on the prefetch queue over wall-clock."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.data.image import (
        CropImageTransform, FlipImageTransform, ImageRecordReader,
        PipelineImageTransform)
    from deeplearning4j_tpu.data.iterators import AsyncDataSetIterator
    from deeplearning4j_tpu.data.normalizers import \
        ImagePreProcessingScaler
    from deeplearning4j_tpu.data.records import \
        RecordReaderDataSetIterator
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.zoo import ResNet50

    import cv2

    b, size, src, n_files, classes = ((4, 32, 40, 32, 4) if SMOKE
                                      else (256, 224, 256, 768, 10))
    root = tempfile.mkdtemp(prefix="dl4j_etl_")
    rng = np.random.default_rng(0)
    try:
        for i in range(n_files):
            d = Path(root) / f"cls{i % classes}"
            d.mkdir(exist_ok=True)
            img = rng.integers(0, 256, (src, src, 3), dtype=np.uint8)
            cv2.imwrite(str(d / f"img{i:05d}.jpg"), img)

        aug = PipelineImageTransform([
            (CropImageTransform(src - size), 1.0),
            (FlipImageTransform(1), 0.5)])
        # decode over all host cores (ordered thread-pool map; cv2
        # releases the GIL) — a no-op on this 1-vCPU box, the real
        # lever on production hosts (BASELINE.md: ~10 cores feed one
        # v5e at the full ResNet-50 rate)
        reader = ImageRecordReader(
            size, size, 3, transform=aug,
            workers=os.cpu_count() or 1).initialize(root)
        it = RecordReaderDataSetIterator(reader, b, label_index=1,
                                         num_classes=classes)
        it.set_pre_processor(ImagePreProcessingScaler())
        ait = AsyncDataSetIterator(it, queue_size=8)

        net = ResNet50(num_classes=classes, seed=1,
                       input_shape=(size, size, 3),
                       updater=upd.Nesterovs(learning_rate=0.1,
                                             momentum=0.9),
                       compute_dtype=None if SMOKE
                       else "bfloat16").init()
        run, _ = _drive_train_step(net)

        def run_epoch():
            n = 0
            loss = None
            for ds in ait:
                x = jnp.asarray(ds.features)
                loss = run(x, jnp.asarray(ds.labels))
                n += x.shape[0]
            return n, loss

        _, warm_loss = run_epoch()         # compile + warm the cache
        _sync(warm_loss)                   # drain async device work
        ait.etl_wait_seconds = 0.0
        t0 = time.perf_counter()
        n_imgs = 0
        for _ in range(2 if SMOKE else 4):
            n, loss = run_epoch()
            n_imgs += n
        _sync(loss)
        wall = time.perf_counter() - t0
        etl_pct = 100.0 * ait.etl_wait_seconds / wall

        # pipeline-only rate (no device step, no transfer): what the
        # host can decode+augment+normalize per second — the number
        # that sizes host capacity per chip. This is a PER-HOST rate:
        # the reader maps decode over workers=os.cpu_count() threads
        # (see above), so on a multi-core host this is already the
        # whole-host rate; on this 1-vCPU box host == core.
        t0 = time.perf_counter()
        n_pipe = sum(ds.features.shape[0] for ds in ait)
        pipe_rate = n_pipe / (time.perf_counter() - t0)

        cores = os.cpu_count()
        label = (f"ResNet-50 train + REAL input pipeline "
                 f"(jpeg decode+augment+prefetch) b{b}@{size} "
                 f"[ETL-wait {etl_pct:.0f}%; host pipeline "
                 f"{pipe_rate:,.0f} img/s/host ({cores} core"
                 f"{'s' if cores != 1 else ''})]")
        flops = 3 * 4.1e9 * b          # per step, same model as #2
        return (label, n_imgs / wall, "img/s", wall * b / n_imgs,
                flops, {"etl_wait_pct": etl_pct,
                        "pipeline_img_s": pipe_rate,
                        "n_images": n_imgs,
                        "host_cores": os.cpu_count()})
    finally:
        shutil.rmtree(root, ignore_errors=True)


def flashbwd():
    """Flash-attention fwd+bwd: Pallas backward vs scan recompute."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops import pallas_kernels as pk

    B, T, H, D = (1, 128, 2, 16) if SMOKE else (8, 2048, 8, 64)
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)),
                           jnp.bfloat16) for _ in range(3))
    fold = (lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, T, D))

    def loss_flash(q, k, v):
        return jnp.sum(pk.flash_attention(
            q, k, v, causal=True).astype(jnp.float32) ** 2)

    def loss_scan(q, k, v):
        return jnp.sum(pk._reference_scan(
            fold(q), fold(k), fold(v),
            causal=True).astype(jnp.float32) ** 2)

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))
    gs = jax.jit(jax.grad(loss_scan, argnums=(0, 1, 2)))
    dtf = _timeit(lambda: gf(q, k, v), lambda g: g[0])
    dts = _timeit(lambda: gs(q, k, v), lambda g: g[0])
    # attention train FLOPs ≈ 2(fwd QK+PV) + 5x matmul-equiv bwd
    flops = 3.5 * 4 * B * H * T * T * D / 2   # causal halves the work
    label = (f"flash-attn fwd+bwd b{B} t{T} h{H} d{D} "
             f"[{dts / dtf:.2f}x vs scan-recompute "
             f"{dts*1e3:.1f}→{dtf*1e3:.1f} ms]")
    return (label, 1.0 / dtf, "steps/s", dtf, flops)


def _numerics_section():
    """Diagnostics-on vs -off step time on the LeNet smoke model: the
    cadence-gated diagnostic step (per-layer grad/update/activation
    stats as aux outputs of the same XLA program, obs/numerics.py)
    must stay within a few percent of the plain step. Shares the
    timing harness with bench.py's ``numerics`` section.

    Batch note (ISSUE 15): this entry keeps b=256 even under
    ``--smoke``. Per-layer diagnostics carry a batch-INDEPENDENT
    floor (stats over the param/grad/update trees + ~500 stat-epilogue
    HLO ops of XLA:CPU thunk dispatch); against the old smoke b=8's
    ~17 ms step that floor alone read as ~17-25% and buried the
    marginal tap cost this entry exists to meter. b=256 (the same
    config the real-chip dossier measures) with a shortened
    interleaved protocol keeps the smoke budget at seconds while
    measuring the real quantity — the fused single-pass taps
    (numerics.fused_moments) cut the diag program's extra byte
    traffic 6x, ~17% → ≤8% here."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.obs import numerics
    from deeplearning4j_tpu.zoo import LeNet

    b = 256
    net = LeNet(num_classes=10, seed=0).init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((b, 28, 28, 1)), jnp.float32)
    y = jnp.asarray(np.eye(10, dtype=np.float32)[
        rng.integers(0, 10, b)])
    feed = ({net.conf.inputs[0]: x}, [y], {}, {}) \
        if hasattr(net.conf, "inputs") else (x, y, None, None)
    return {"model": f"LeNet b{b}@28x28",
            **numerics.measure_diag_overhead(
                net, net.params, net.opt_state, net.state, feed,
                jax.random.fold_in(jax.random.PRNGKey(0), 0),
                k=4 if SMOKE else 10, rounds=5 if SMOKE else 3)}


def _hot_path_gaps():
    """Device-time observatory section (obs/devtime.py): warm the
    LeNet train step (the smoke model the numerics section shares),
    run a short ``jax.profiler.trace`` window over real fit steps, and
    emit the gap report — scopes ranked by device-time share with
    roofline utilization and the ``pallas_candidate`` flag. THE
    structured evidence ROADMAP item "Pallas only where XLA has a gap"
    consumes; on ``--smoke`` the utilizations are wiring-validation
    only (CPU time against TPU peaks, labeled)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.obs import devtime
    from deeplearning4j_tpu.perf.warmup import WarmupSpec
    from deeplearning4j_tpu.zoo import LeNet

    b = 8 if SMOKE else 256
    net = LeNet(num_classes=10, seed=0).init()
    # AOT-warm so attribution can read the exact executed HLO (the
    # scope map + cost_analysis source) without recompiling anything
    net.warmup([WarmupSpec(features=(b, 28, 28, 1), labels=(b, 10))])
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((b, 28, 28, 1)), jnp.float32)
    y = jnp.asarray(np.eye(10, dtype=np.float32)[
        rng.integers(0, 10, b)])
    net.fit(x, y)                  # settle: first step off the window
    steps = 2 if SMOKE else 5
    rep = devtime.capture(
        lambda: [net.fit(x, y) for _ in range(steps)],
        executables=devtime.sentry_executables(net._train_step_fn),
        label="perf_dossier.lenet")
    cap = rep["capture"]
    open_gaps = [g["scope"] for g in rep["gaps"]
                 if g["pallas_candidate"]]
    return {
        "model": f"LeNet b{b}@28x28",
        "window_steps": steps,
        "capture_wall_s": rep["capture_wall_s"],
        "total_device_ms": cap["total_device_ms"],
        "scope_coverage": cap["scope_coverage"],
        "peaks": cap["peaks"],
        "gaps": rep["gaps"],
        "pallas_candidates": open_gaps,
        # the loop-closing split (ISSUE 15): scopes whose primitive now
        # dispatches to a registered fused kernel vs gaps still open —
        # the dossier is the proof a named gap was actually filled
        # (open_gaps aliases the candidate list: one computation, two
        # names — the pre-PR-15 key and the split's)
        "closed_gaps": {g["scope"]: g["closed_by"]
                        for g in rep["gaps"] if g["closed_by"]},
        "open_gaps": open_gaps,
        # the comm axis (obs/commtime.py): scopes whose device time is
        # dominated by collectives — a kernel won't close these, the
        # wire will (gap.bound == "wire", gap.comm_ms)
        "wire_bound_scopes": [g["scope"] for g in rep["gaps"]
                              if g.get("bound") == "wire"],
    }


def main(names):
    global SMOKE
    if "--smoke" in names:
        SMOKE = True
        names = [n for n in names if n != "--smoke"]
        import jax
        jax.config.update("jax_platforms", "cpu")
    table = {"resnet50": resnet50, "bert": bert, "lstm": lstm,
             "flashbwd": flashbwd, "gpt": gpt, "gpt2geom": gpt2geom,
             "gpt8k": gpt8k, "etl": etl, "lenet": lenet}
    trace_dir = out_path = None
    for flag in ("--trace", "--out"):
        if flag in names:
            i = names.index(flag)
            if (i + 1 >= len(names) or names[i + 1] in table
                    or names[i + 1].startswith("-")):
                sys.exit(f"usage: perf_dossier.py {flag} PATH "
                         "[config ...]")
            if flag == "--trace":
                trace_dir = names[i + 1]
            else:
                out_path = names[i + 1]
            names = names[:i] + names[i + 2:]
    unknown = [n for n in names if n not in table]
    if unknown:
        sys.exit(f"unknown config(s): {', '.join(unknown)} "
                 f"(valid: {', '.join(table)})")
    if not SMOKE:
        # probe the tunnel in a subprocess FIRST: a down axon backend
        # hangs jax.devices() indefinitely (bench.py's robustness
        # contract, VERDICT r2 #1a applies here too)
        from deeplearning4j_tpu.utils.backend_probe import probe_backend
        ok, detail = probe_backend()
        if not ok:
            sys.exit(f"{detail} — retry later or pass --smoke")
    import jax
    if not SMOKE:
        assert jax.devices()[0].platform in ("tpu", "axon"), \
            "perf dossier must run on the real chip (or pass --smoke)"
    rows = []
    failed = []

    def run_all():
        for name in names or list(table):
            try:
                rows.append(table[name]())
            except Exception as e:
                print(f"{name}: FAILED {type(e).__name__}: {e}")
                failed.append(name)

    if trace_dir:
        with jax.profiler.trace(trace_dir):
            run_all()
        print(f"# XProf capture in {trace_dir} — read the HBM "
              "bandwidth counters there")
    else:
        run_all()
    payload = [{"config": r[0], "throughput": r[1], "unit": r[2],
                "step_s": r[3], "flops": r[4],
                "tflops": r[4] / r[3] / 1e12,
                "mfu_pct": 100 * r[4] / r[3] / 1e12 / PEAK_TFLOPS_BF16,
                "smoke": SMOKE,
                **(r[5] if len(r) > 5 else {})} for r in rows]
    # compile subsystem (perf/): where the dossier's wall-clock went
    # before steady state — total XLA compile time, per-entry-point
    # trace counts, and whether DL4J_TPU_COMPILE_CACHE pre-paid any of
    # it (a dossier re-run on a warm cache should show hits==requests)
    from deeplearning4j_tpu.perf import compile_report
    payload.append({"config": "compile_subsystem", **compile_report(),
                    "smoke": SMOKE})
    # telemetry spine (obs/): off-path instrumentation cost vs the
    # median measured step, plus the merged metric/health summary
    from deeplearning4j_tpu import obs
    steps = sorted(r[3] for r in rows) or [None]
    payload.append({"config": "obs_telemetry",
                    **obs.overhead_report(
                        step_seconds=steps[len(steps) // 2]),
                    "summary": obs.summary(), "smoke": SMOKE})
    # numerics observatory (obs/numerics.py): diagnostics-on vs -off
    # step time on the smoke model (acceptance: <= 5% overhead with
    # scalars-only host traffic at cadence)
    try:
        payload.append({"config": "numerics_observatory",
                        **_numerics_section(), "smoke": SMOKE})
    except Exception as e:
        print(f"numerics_observatory: FAILED {type(e).__name__}: {e}")
        failed.append("numerics_observatory")
    # fleet observability plane (obs/fleet.py): snapshot-publish cost
    # vs the median measured step — off path ~0 (one branch), on path
    # bounded at the default 1 Hz cadence (acceptance: < 1% of step)
    payload.append({"config": "fleet_obs_plane",
                    **obs.fleet.measure_publish_overhead(
                        step_seconds=steps[len(steps) // 2]),
                    "smoke": SMOKE})
    # device-time observatory (obs/devtime.py): the hot-path gap
    # report — per-scope device time + roofline utilization from a
    # short profiler window over the smoke model, ranking where a
    # Pallas kernel would buy the most (ARCHITECTURE.md §16). Skipped
    # inside --trace: the dossier's own profiler session owns the
    # process and a nested capture would fail.
    if trace_dir:
        print("hot_path_gaps: skipped under --trace (one profiler "
              "session per process)")
    else:
        try:
            payload.append({"config": "hot_path_gaps",
                            **_hot_path_gaps(), "smoke": SMOKE})
        except Exception as e:
            print(f"hot_path_gaps: FAILED {type(e).__name__}: {e}")
            failed.append("hot_path_gaps")
    # ZeRO-DP sharded weight update (parallel/zero.py): before/after
    # row — replicated vs sharded SYNC step time, per-device
    # optimizer-state bytes, est. peak HBM. Own forced-CPU
    # 8-virtual-device subprocess (the real-chip box is single-chip;
    # multi-chip step time lands with the MULTICHIP gate).
    from deeplearning4j_tpu.parallel import zero
    zd = zero.subprocess_report()
    payload.append({"config": "zero_dp_sharded_update", **zd,
                    "smoke": SMOKE})
    # ZeRO gather/forward overlap (ISSUE 15 tentpole c): the step-time
    # delta of moving the param all-gather to the top of the next step
    # (ParallelWrapper gather_overlap=True), next to the sharded row
    # it reorders. On the forced-CPU virtual mesh the "overlap" has no
    # async DMA to hide under (compute and gather share one core), so
    # this row is the honest wiring + bit-identity measurement; the
    # win needs real ICI.
    if zd.get("skipped"):
        payload.append({"config": "zero_overlap", **zd,
                        "smoke": SMOKE})
    else:
        payload.append({
            "config": "zero_overlap",
            "n_devices": zd["n_devices"],
            "platform": zd["platform"],
            "sharded_step_ms": zd["sharded"]["step_ms"],
            "overlap_step_ms": zd["sharded_overlap"]["step_ms"],
            "overlap_step_ratio": zd["overlap_step_ratio"],
            "max_param_rel_diff_overlap":
                zd["max_param_rel_diff_overlap"],
            "smoke": SMOKE})
    # communication observatory (obs/commtime.py): the permanent
    # wire-bytes axis next to step time — the ZeRO sharded step's
    # per-scope wire ledger gated against the PR 5 HLO byte model,
    # plus the off-path fence. Same forced-CPU subprocess protocol.
    from deeplearning4j_tpu.obs import commtime
    payload.append({"config": "comm_observatory",
                    **commtime.subprocess_report(), "smoke": SMOKE})
    # fused-primitive kernel library (ops/fused_norms.py): per-kernel
    # interpret-parity + fallback timings — the fused_epilogues row
    # next to the existing flash-attn row.
    from deeplearning4j_tpu.ops import fused_norms
    payload.append({"config": "fused_epilogues",
                    **fused_norms.subprocess_report(), "smoke": SMOKE})
    # continuous-batching serving gateway (serving/): tokens/sec and
    # p99 TTFT under the synthetic multi-tenant trace, continuous vs
    # request-at-a-time baseline, zero-retrace proof. Forced-CPU
    # subprocess (the smoke row is a one-device measurement).
    from deeplearning4j_tpu.serving import loadgen
    payload.append({"config": "continuous_batching",
                    **loadgen.subprocess_report(), "smoke": SMOKE})
    # speculative decode + copy-on-write prefix sharing (serving/):
    # baseline gateway vs spec_k=4 + prefix_sharing on the shared-
    # system-prompt trace — TTFT and tokens/sec speedups, prefix-hit
    # rate, prefill tokens saved, accept rate. Same forced-CPU
    # subprocess protocol as the continuous_batching row.
    payload.append({"config": "spec_decode",
                    **loadgen.subprocess_report(
                        report="shared-prefix"), "smoke": SMOKE})
    if out_path:
        Path(out_path).write_text(json.dumps(payload, indent=1))
    if SMOKE:
        print("\n# SMOKE RUN — wiring check only; labels describe the "
              "real configs but shapes were tiny. NOT for BASELINE.md.")
        print("| Config | Step |")
        print("|---|---|")
        for label, thr, unit, dt, flops, *_ in rows:
            print(f"| {label} (smoke) | {dt*1e3:.1f} ms |")
    else:
        print("\n| Config | Throughput | Step | TFLOP/s | MFU |")
        print("|---|---|---|---|---|")
        for label, thr, unit, dt, flops, *_ in rows:
            tflops = flops / dt / 1e12
            mfu = 100 * tflops / PEAK_TFLOPS_BF16
            print(f"| {label} | {thr:,.0f} {unit} | {dt*1e3:.1f} ms | "
                  f"{tflops:.1f} | {mfu:.1f}% |")
        print(json.dumps(payload))
    if failed:
        # a mid-run tunnel drop (or any config crash) must NOT read as
        # a landed dossier: nonzero rc sends tpu_watch back to watching
        print(f"# {len(failed)} config(s) FAILED: {', '.join(failed)}")
        sys.exit(1)


if __name__ == "__main__":
    main(sys.argv[1:])
