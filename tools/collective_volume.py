"""Collective-volume accounting for the multi-chip scaling claim
(BASELINE #5 "linear to 32 chips"; VERDICT r2 #10).

Compiles representative distributed train steps on the virtual
8-device CPU mesh, extracts every collective op and its byte volume
from the optimized HLO, and projects per-step ICI time at v5e link
bandwidth against MXU compute time — the derisking evidence for the
scaling claim until real multi-chip hardware is reachable.

Wire-volume model (ring algorithms, per device):
  all-reduce      2·N·(n−1)/n     (reduce-scatter + all-gather)
  all-gather      S·(n−1)         (S = per-device shard bytes sent)
  reduce-scatter  (N/n)·(n−1)
  collective-permute  N           (one neighbor hop)
  all-to-all      N·(n−1)/n

    python tools/collective_volume.py [--markdown]
"""
import argparse
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

# public v5e figure (jax-ml.github.io/scaling-book): ICI 45 GB/s per
# link per direction (2D torus; ring collectives ride one link
# direction per neighbor hop)
V5E_ICI_GBPS = 45e9

# HLO line shape: `%name = <shape-or-tuple> <opcode>(...), ...` — the
# result may be a TUPLE (XLA fuses many gradients into one all-reduce)
_LINE_RE = re.compile(
    r"=\s*(\(?[^(=]*?(?:\([^)]*\))?)\s*"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?\(")
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4,
                "u32": 4, "s64": 8, "u64": 8, "s16": 2, "u16": 2,
                "s8": 1, "u8": 1, "pred": 1, "c64": 8, "c128": 16}


def _bytes(dtype, dims):
    n = 1
    for d in dims.split(",") if dims else []:
        n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def collectives_of(compiled, n_devices=8):
    """Parse optimized HLO → [(kind, tensor_bytes, wire_bytes)].

    Collectives inside a `while` body (the ring attention fori_loop)
    execute once per trip; the ring's trip count is the mesh size, so
    those are multiplied by ``n_devices``.
    """
    out = []
    for line in compiled.as_text().splitlines():
        head = line.split("metadata=")[0]
        m = _LINE_RE.search(head)
        if not m or "-done" in head:
            continue
        shapes, kind = m.groups()
        nb = sum(_bytes(d, dims)
                 for d, dims in _SHAPE_RE.findall(shapes))
        n = n_devices
        wire = {"all-reduce": 2 * nb * (n - 1) / n,
                # HLO all-gather result is the FULL gathered tensor;
                # each device sends its shard to n-1 peers
                "all-gather": nb / n * (n - 1),
                "reduce-scatter": nb * (n - 1),   # result is the shard
                "collective-permute": nb,
                "all-to-all": nb * (n - 1) / n}[kind]
        trips = n_devices if "/while/" in line else 1
        out.append((kind, nb, wire * trips))
    return out


_GROUPS_LITERAL_RE = re.compile(r"replica_groups=\{(\{[0-9,]+\}"
                                r"(?:,\{[0-9,]+\})*)\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\]"
    r"(?:T\(([0-9,]+)\))?")


def parse_replica_groups(line):
    """Replica groups of one HLO collective line, as a frozenset of
    frozensets of device ids — handles both the literal
    ``{{0,2},{1,3}}`` and the iota ``[G,S]<=[dims]T(perm)`` forms."""
    m = _GROUPS_LITERAL_RE.search(line)
    if m:
        return frozenset(
            frozenset(int(d) for d in g.split(","))
            for g in m.group(1)[1:-1].split("},{"))
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        g, s = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        arr = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            arr = arr.transpose([int(p) for p in m.group(4).split(",")])
        arr = arr.reshape(g, s)
        return frozenset(frozenset(int(d) for d in row) for row in arr)
    return None


def axis_groups(mesh_axes):
    """Expected replica-group partition for every non-empty subset of
    mesh axes: {axes_tuple: frozenset of frozensets}. ``mesh_axes`` is
    an ordered dict-like of axis name → size with MAJOR-first device
    numbering (the ``make_mesh`` convention)."""
    names = list(mesh_axes)
    sizes = [mesh_axes[n] for n in names]
    ids = np.arange(int(np.prod(sizes))).reshape(sizes)
    out = {}
    from itertools import combinations
    for r in range(1, len(names) + 1):
        for subset in combinations(range(len(names)), r):
            other = [i for i in range(len(names)) if i not in subset]
            moved = ids.transpose(list(other) + list(subset)).reshape(
                -1, int(np.prod([sizes[i] for i in subset])))
            out[tuple(names[i] for i in subset)] = frozenset(
                frozenset(int(d) for d in row) for row in moved)
    return out


def collectives_with_axes(compiled, mesh_axes):
    """[(kind, tensor_bytes, axes_or_None, in_while)] for every
    collective in the optimized HLO — ``axes`` is the mesh-axis subset
    whose group partition matches the op's replica groups (None when
    the groups don't align to axes, e.g. a point-to-point permute's
    source-target pairs; collective-permute reports the axes whose
    subgrid contains every source→target hop instead)."""
    expected = axis_groups(mesh_axes)
    out = []
    for line in compiled.as_text().splitlines():
        head = line.split("metadata=")[0]
        m = _LINE_RE.search(head)
        if not m or "-done" in head:
            continue
        shapes, kind = m.groups()
        nb = sum(_bytes(d, dims)
                 for d, dims in _SHAPE_RE.findall(shapes))
        axes = None
        if kind == "collective-permute":
            pm = re.search(r"source_target_pairs=\{([0-9,{} ]*)\}",
                           line)
            if pm:
                pairs = [tuple(int(x) for x in p.split(","))
                         for p in pm.group(1)[1:-1].split("},{")]
                for ax, part in expected.items():
                    by = {frozenset(g) for g in part}
                    if all(any(s in g and t in g for g in by)
                           for s, t in pairs):
                        axes = ax
                        break
        else:
            groups = parse_replica_groups(line)
            if groups is not None:
                for ax, part in expected.items():
                    if groups == part:
                        axes = ax
                        break
        out.append((kind, nb, axes, "/while/" in line))
    return out


def composed_lm(mesh_devices=8):
    """Composed DP×SP×TP causal-LM train step on one
    {"data":2, "seq":2, "tensor":N//4} mesh (dryrun stage 7 /
    tests/test_composed_parallel.py workload) — for the per-axis
    collective gates."""
    from deeplearning4j_tpu.parallel import (
        composed_context, composed_data_sharding, make_mesh,
        shard_lm_for_composed)
    from deeplearning4j_tpu.zoo import CausalTransformerLM

    model = CausalTransformerLM(
        vocab_size=64, hidden=32, n_layers=2, n_heads=2, max_len=32,
        ffn_mult=2.0, tie_embeddings=True, sequence_parallel="ring",
        seed=7)
    net = model.init(seq_len=32)
    mesh = make_mesh({"data": 2, "seq": 2,
                      "tensor": mesh_devices // 4})
    shard_lm_for_composed(net, mesh, tensor_axis="tensor")
    ds = composed_data_sharding(mesh)
    rng = np.random.default_rng(0)
    x = jax.device_put(
        jnp.asarray(rng.integers(0, 64, (4, 32)), jnp.int32), ds)
    y = jax.device_put(
        jnp.asarray(rng.integers(0, 64, (4, 32)), jnp.int32), ds)
    step = net._make_train_step()
    args = (net.params, net.opt_state, net.state, x, y, None, None,
            jax.random.PRNGKey(0))
    return step, args, composed_context(mesh), dict(
        data=2, seq=2, tensor=mesh_devices // 4)


def analyze(name, jitted, args, n_devices=8):
    """HLO-derived collective counts + wire bytes + projected ICI time.

    No compute-time column here: XLA-CPU cost analysis is meaningless
    for TPU projection — BASELINE.md pairs these ICI times with the
    round-1 MEASURED per-step times on the real chip instead.
    """
    compiled = jitted.lower(*args).compile()
    colls = collectives_of(compiled, n_devices)
    wire = sum(w for _, _, w in colls)
    by_kind = {}
    for kind, _, w in colls:
        c, tot = by_kind.get(kind, (0, 0.0))
        by_kind[kind] = (c + 1, tot + w)
    t_ici = wire / V5E_ICI_GBPS
    return {"name": name, "collectives": by_kind,
            "wire_bytes": wire, "t_ici_ms": t_ici * 1e3}


# ---------------------------------------------------------------------------
# representative configs (mirror __graft_entry__.dryrun_multichip stages)
# ---------------------------------------------------------------------------
def dp_resnet(mesh_devices=8, sharded=True):
    """DP ResNet-50 sync step: the BASELINE #5 workload. Collective
    volume = one gradient all-reduce of every parameter.

    ``sharded=False`` compiles the SAME step with the batch replicated
    — the classic lost-sharding regression; the CI gate uses it as the
    detection canary (no gradient all-reduce is emitted)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import optax
    from deeplearning4j_tpu.zoo import ResNet50
    from deeplearning4j_tpu.nn import updaters as upd

    mesh = Mesh(np.array(jax.devices()[:mesh_devices]), ("data",))
    net = ResNet50(num_classes=1000, seed=0, input_shape=(64, 64, 3),
                   updater=upd.Nesterovs(learning_rate=0.1,
                                         momentum=0.9)).init()
    x = jnp.zeros((16, 64, 64, 3), jnp.float32)
    y = jnp.zeros((16, 1000), jnp.float32)
    rng = jax.random.PRNGKey(0)
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P("data"))

    def step(params, opt_state, state, x, y):
        (loss, new_state), g = jax.value_and_grad(
            net._loss_fn, has_aux=True)(params, state,
                                        {net.conf.inputs[0]: x}, [y],
                                        {}, {}, rng)
        updates, opt_state = net._optimizer.update(g, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, new_state, loss

    dshard = shard if sharded else repl
    jitted = jax.jit(step,
                     in_shardings=(repl, repl, repl, dshard, dshard),
                     out_shardings=(repl, repl, repl, repl))
    return jitted, (net.params, net.opt_state, net.state, x, y)


def dp_sharded_wrapper(mesh_devices=8, sharded_update=True):
    """ParallelWrapper SYNC step with the ZeRO sharded weight update
    (or the replicated baseline with ``sharded_update=False``): the
    gradient sync becomes per-leaf reduce-scatter + param all-gather,
    and the optimizer-state footprint drops to 1/N per device.
    Returns ``(jitted_step, args, accounting)`` — accounting carries
    the per-device optimizer/param/grad byte model the CI gate asserts
    against the HLO."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.parallel import (ParallelWrapper,
                                             per_device_bytes)

    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(upd.Adam(learning_rate=1e-3)).list()
            .layer(DenseLayer(n_out=256, activation="relu"))
            .layer(DenseLayer(n_out=256, activation="relu"))
            .layer(OutputLayer(n_out=16, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(64)).build())
    net = MultiLayerNetwork(conf).init()
    w = ParallelWrapper(net, workers=mesh_devices,
                        sharded_update=sharded_update)
    w._prepare()
    dshard = NamedSharding(w.mesh, P("data"))
    b = 8 * mesh_devices
    x = jax.device_put(jnp.zeros((b, 64), jnp.float32), dshard)
    y = jax.device_put(jnp.zeros((b, 16), jnp.float32), dshard)
    rng = jax.random.PRNGKey(0)
    if sharded_update:
        args = (net.params, w._dp_state, net.state, x, y, rng)
    else:
        args = (net.params, net.opt_state, net.state, x, y, rng)
    p_bytes = sum(int(np.prod(p.shape)) * p.dtype.itemsize
                  for p in jax.tree.leaves(net.params))
    acct = {
        "param_bytes": p_bytes,
        "grad_bytes": p_bytes,           # f32 grads mirror f32 params
        "opt_bytes_replicated_per_device":
            per_device_bytes(net.opt_state),
        "opt_bytes_per_device":
            per_device_bytes(w._dp_state, mesh_devices)
            if sharded_update else per_device_bytes(net.opt_state),
    }
    return w._step, args, acct


def tp_mlp(mesh_devices=8):
    """Tensor-parallel 2-layer MLP (col→row sharded): all-reduce of
    activations, not params."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()[:mesh_devices]), ("model",))
    d, h = 1024, 4096
    params = {"W1": jnp.zeros((d, h), jnp.bfloat16),
              "W2": jnp.zeros((h, d), jnp.bfloat16)}
    x = jnp.zeros((32, d), jnp.bfloat16)
    shardings = {"W1": NamedSharding(mesh, P(None, "model")),
                 "W2": NamedSharding(mesh, P("model", None))}

    def fwd(p, x):
        hdn = jax.nn.relu(x @ p["W1"])
        return jnp.sum((hdn @ p["W2"]) ** 2)

    def step(p, x):
        return jax.value_and_grad(fwd)(p, x)

    jitted = jax.jit(step,
                     in_shardings=({"W1": shardings["W1"],
                                    "W2": shardings["W2"]},
                                   NamedSharding(mesh, P())))
    return jitted, (jax.device_put(params, shardings), x)


def sp_ring(mesh_devices=8, t_total=8192):
    """Ring-attention fwd+bwd: collective-permute KV/mask blocks per
    ring step (the long-context SP path)."""
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.ring_attention import \
        ring_self_attention
    mesh = make_mesh({"seq": mesh_devices})
    b, h, d = 1, 8, 128
    q = jnp.zeros((b, t_total, h, d), jnp.bfloat16)

    def loss(q):
        return jnp.sum(
            ring_self_attention(q, q, q, mesh, causal=True)
            .astype(jnp.float32) ** 2)

    jitted = jax.jit(jax.value_and_grad(loss))
    return jitted, (q,)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--markdown", action="store_true")
    args = ap.parse_args()

    rows = []
    for name, build in [("DP ResNet-50 (grad allreduce)", dp_resnet),
                        ("TP MLP col→row (activation allreduce)",
                         tp_mlp),
                        ("SP ring attention T=8k causal", sp_ring)]:
        jitted, a = build()
        rows.append(analyze(name, jitted, a))
    # ZeRO-DP sharded weight update: reduce-scatter + all-gather
    # replace the gradient allreduce at identical ring wire volume
    jitted, a, _acct = dp_sharded_wrapper()
    rows.append(analyze("ZeRO-DP MLP (sharded weight update)", jitted,
                        a))
    # composed DP×SP×TP LM step: compiled under its ambient context
    step, a, ctx, _axes = composed_lm()
    with ctx:
        rows.append(analyze("Composed DP×SP×TP causal-LM step", step,
                            a))

    if args.markdown:
        print("| config | collectives (count × kind) | wire MB/step "
              "| projected ICI ms (45 GB/s link) |")
        print("|---|---|---|---|")
        for r in rows:
            kinds = ", ".join(f"{c}× {k}"
                              for k, (c, _) in sorted(
                                  r["collectives"].items()))
            print(f"| {r['name']} | {kinds} "
                  f"| {r['wire_bytes'] / 1e6:.1f} "
                  f"| {r['t_ici_ms']:.2f} |")
    else:
        for r in rows:
            print(r)


if __name__ == "__main__":
    main()
