"""Collective-volume accounting for the multi-chip scaling claim
(BASELINE #5 "linear to 32 chips"; VERDICT r2 #10).

Compiles representative distributed train steps on the virtual
8-device CPU mesh, extracts every collective op and its byte volume
from the optimized HLO, and projects per-step ICI time at v5e link
bandwidth against MXU compute time — the derisking evidence for the
scaling claim until real multi-chip hardware is reachable.

Wire-volume model (ring algorithms, per device):
  all-reduce      2·N·(n−1)/n     (reduce-scatter + all-gather)
  all-gather      S·(n−1)         (S = per-device shard bytes sent)
  reduce-scatter  (N/n)·(n−1)
  collective-permute  N           (one neighbor hop)
  all-to-all      N·(n−1)/n

    python tools/collective_volume.py [--markdown]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

# the HLO collective walker now lives in the communication
# observatory; this tool is a thin analytic front-end over it
from deeplearning4j_tpu.obs import commtime as _commtime  # noqa: E402

# public v5e figure (jax-ml.github.io/scaling-book): ICI 45 GB/s per
# link per direction (2D torus; ring collectives ride one link
# direction per neighbor hop)
V5E_ICI_GBPS = 45e9

def collectives_of(compiled, n_devices=8):
    """Parse optimized HLO → [(kind, tensor_bytes, wire_bytes)].

    Delegates to :func:`obs.commtime.collective_records`;
    ``uniform_ring=n_devices`` pins the legacy analytic model (every
    ring sized to the full mesh) so the BASELINE rows stay put.
    Collectives inside a `while` body (the ring attention fori_loop)
    execute once per trip; the ring's trip count is the mesh size, so
    those are multiplied by ``n_devices``.
    """
    return [(r["kind"], r["tensor_bytes"], r["wire_bytes"])
            for r in _commtime.collective_records(
                compiled.as_text(), uniform_ring=n_devices)]


# re-exported from the observatory (the walker's canonical home)
parse_replica_groups = _commtime.parse_replica_groups


def axis_groups(mesh_axes):
    """Expected replica-group partition for every non-empty subset of
    mesh axes: {axes_tuple: frozenset of frozensets}. ``mesh_axes`` is
    an ordered dict-like of axis name → size with MAJOR-first device
    numbering (the ``make_mesh`` convention)."""
    names = list(mesh_axes)
    sizes = [mesh_axes[n] for n in names]
    ids = np.arange(int(np.prod(sizes))).reshape(sizes)
    out = {}
    from itertools import combinations
    for r in range(1, len(names) + 1):
        for subset in combinations(range(len(names)), r):
            other = [i for i in range(len(names)) if i not in subset]
            moved = ids.transpose(list(other) + list(subset)).reshape(
                -1, int(np.prod([sizes[i] for i in subset])))
            out[tuple(names[i] for i in subset)] = frozenset(
                frozenset(int(d) for d in row) for row in moved)
    return out


def collectives_with_axes(compiled, mesh_axes):
    """[(kind, tensor_bytes, axes_or_None, in_while)] for every
    collective in the optimized HLO — ``axes`` is the mesh-axis subset
    whose group partition matches the op's replica groups (None when
    the groups don't align to axes, e.g. a point-to-point permute's
    source-target pairs; collective-permute reports the axes whose
    subgrid contains every source→target hop instead)."""
    expected = axis_groups(mesh_axes)
    out = []
    for r in _commtime.collective_records(compiled.as_text()):
        axes = None
        if r["kind"] == "collective-permute":
            pairs = r["source_target_pairs"]
            if pairs:
                for ax, part in expected.items():
                    by = {frozenset(g) for g in part}
                    if all(any(s in g and t in g for g in by)
                           for s, t in pairs):
                        axes = ax
                        break
        else:
            groups = r["replica_groups"]
            if groups is not None:
                for ax, part in expected.items():
                    if groups == part:
                        axes = ax
                        break
        out.append((r["kind"], r["tensor_bytes"], axes, r["in_while"]))
    return out


def composed_lm(mesh_devices=8):
    """Composed DP×SP×TP causal-LM train step on one
    {"data":2, "seq":2, "tensor":N//4} mesh (dryrun stage 7 /
    tests/test_composed_parallel.py workload) — for the per-axis
    collective gates."""
    from deeplearning4j_tpu.parallel import (
        composed_context, composed_data_sharding, make_mesh,
        shard_lm_for_composed)
    from deeplearning4j_tpu.zoo import CausalTransformerLM

    model = CausalTransformerLM(
        vocab_size=64, hidden=32, n_layers=2, n_heads=2, max_len=32,
        ffn_mult=2.0, tie_embeddings=True, sequence_parallel="ring",
        seed=7)
    net = model.init(seq_len=32)
    mesh = make_mesh({"data": 2, "seq": 2,
                      "tensor": mesh_devices // 4})
    shard_lm_for_composed(net, mesh, tensor_axis="tensor")
    ds = composed_data_sharding(mesh)
    rng = np.random.default_rng(0)
    x = jax.device_put(
        jnp.asarray(rng.integers(0, 64, (4, 32)), jnp.int32), ds)
    y = jax.device_put(
        jnp.asarray(rng.integers(0, 64, (4, 32)), jnp.int32), ds)
    step = net._make_train_step()
    args = (net.params, net.opt_state, net.state, x, y, None, None,
            jax.random.PRNGKey(0))
    return step, args, composed_context(mesh), dict(
        data=2, seq=2, tensor=mesh_devices // 4)


def analyze(name, jitted, args, n_devices=8):
    """HLO-derived collective counts + wire bytes + projected ICI time.

    No compute-time column here: XLA-CPU cost analysis is meaningless
    for TPU projection — BASELINE.md pairs these ICI times with the
    round-1 MEASURED per-step times on the real chip instead.
    """
    compiled = jitted.lower(*args).compile()
    colls = collectives_of(compiled, n_devices)
    wire = sum(w for _, _, w in colls)
    by_kind = {}
    for kind, _, w in colls:
        c, tot = by_kind.get(kind, (0, 0.0))
        by_kind[kind] = (c + 1, tot + w)
    t_ici = wire / V5E_ICI_GBPS
    # per-scope wire account through the observatory's metadata join
    # (group-sized rings, so composed meshes may differ from the
    # uniform-ring analytic column — that is the point)
    led = _commtime.wire_ledger([compiled], n_devices=n_devices)
    return {"name": name, "collectives": by_kind,
            "wire_bytes": wire, "t_ici_ms": t_ici * 1e3,
            "by_scope": {k: round(v["wire_bytes"] / 1e6, 3)
                         for k, v in sorted(led["by_scope"].items())}}


# ---------------------------------------------------------------------------
# representative configs (mirror __graft_entry__.dryrun_multichip stages)
# ---------------------------------------------------------------------------
def dp_resnet(mesh_devices=8, sharded=True):
    """DP ResNet-50 sync step: the BASELINE #5 workload. Collective
    volume = one gradient all-reduce of every parameter.

    ``sharded=False`` compiles the SAME step with the batch replicated
    — the classic lost-sharding regression; the CI gate uses it as the
    detection canary (no gradient all-reduce is emitted)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import optax
    from deeplearning4j_tpu.zoo import ResNet50
    from deeplearning4j_tpu.nn import updaters as upd

    mesh = Mesh(np.array(jax.devices()[:mesh_devices]), ("data",))
    net = ResNet50(num_classes=1000, seed=0, input_shape=(64, 64, 3),
                   updater=upd.Nesterovs(learning_rate=0.1,
                                         momentum=0.9)).init()
    x = jnp.zeros((16, 64, 64, 3), jnp.float32)
    y = jnp.zeros((16, 1000), jnp.float32)
    rng = jax.random.PRNGKey(0)
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P("data"))

    def step(params, opt_state, state, x, y):
        (loss, new_state), g = jax.value_and_grad(
            net._loss_fn, has_aux=True)(params, state,
                                        {net.conf.inputs[0]: x}, [y],
                                        {}, {}, rng)
        updates, opt_state = net._optimizer.update(g, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, new_state, loss

    dshard = shard if sharded else repl
    jitted = jax.jit(step,
                     in_shardings=(repl, repl, repl, dshard, dshard),
                     out_shardings=(repl, repl, repl, repl))
    return jitted, (net.params, net.opt_state, net.state, x, y)


def dp_sharded_wrapper(mesh_devices=8, sharded_update=True):
    """ParallelWrapper SYNC step with the ZeRO sharded weight update
    (or the replicated baseline with ``sharded_update=False``): the
    gradient sync becomes per-leaf reduce-scatter + param all-gather,
    and the optimizer-state footprint drops to 1/N per device.
    Returns ``(jitted_step, args, accounting)`` — accounting carries
    the per-device optimizer/param/grad byte model the CI gate asserts
    against the HLO."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.parallel import (ParallelWrapper,
                                             per_device_bytes)

    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(upd.Adam(learning_rate=1e-3)).list()
            .layer(DenseLayer(n_out=256, activation="relu"))
            .layer(DenseLayer(n_out=256, activation="relu"))
            .layer(OutputLayer(n_out=16, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(64)).build())
    net = MultiLayerNetwork(conf).init()
    w = ParallelWrapper(net, workers=mesh_devices,
                        sharded_update=sharded_update)
    w._prepare()
    dshard = NamedSharding(w.mesh, P("data"))
    b = 8 * mesh_devices
    x = jax.device_put(jnp.zeros((b, 64), jnp.float32), dshard)
    y = jax.device_put(jnp.zeros((b, 16), jnp.float32), dshard)
    rng = jax.random.PRNGKey(0)
    if sharded_update:
        args = (net.params, w._dp_state, net.state, x, y, rng)
    else:
        args = (net.params, net.opt_state, net.state, x, y, rng)
    p_bytes = sum(int(np.prod(p.shape)) * p.dtype.itemsize
                  for p in jax.tree.leaves(net.params))
    acct = {
        "param_bytes": p_bytes,
        "grad_bytes": p_bytes,           # f32 grads mirror f32 params
        "opt_bytes_replicated_per_device":
            per_device_bytes(net.opt_state),
        "opt_bytes_per_device":
            per_device_bytes(w._dp_state, mesh_devices)
            if sharded_update else per_device_bytes(net.opt_state),
    }
    return w._step, args, acct


def encoded_wrapper(mesh_devices=8):
    """ParallelWrapper ENCODED step (same MLP geometry as
    ``dp_sharded_wrapper``): threshold-encode per shard, exchange,
    decode. The plain encoded exchange psums the DECODED f32
    gradients — DENSE wire volume on the wire; the measured-vs-dense
    column this row feeds is the honest number the ROADMAP item-4
    packed exchange (1-bit words all-gathered, ~16x less) must beat.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.parallel import ParallelWrapper

    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(upd.Adam(learning_rate=1e-3)).list()
            .layer(DenseLayer(n_out=256, activation="relu"))
            .layer(DenseLayer(n_out=256, activation="relu"))
            .layer(OutputLayer(n_out=16, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(64)).build())
    net = MultiLayerNetwork(conf).init()
    w = ParallelWrapper(net, workers=mesh_devices,
                        mode=ParallelWrapper.ENCODED)
    w._prepare()
    dshard = NamedSharding(w.mesh, P("data"))
    b = 8 * mesh_devices
    x = jax.device_put(jnp.zeros((b, 64), jnp.float32), dshard)
    y = jax.device_put(jnp.zeros((b, 16), jnp.float32), dshard)
    rng = jax.random.PRNGKey(0)
    args = (net.params, net.opt_state, net.state, w._dp_state, x, y,
            rng)
    return w._step, args


def tp_mlp(mesh_devices=8):
    """Tensor-parallel 2-layer MLP (col→row sharded): all-reduce of
    activations, not params."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()[:mesh_devices]), ("model",))
    d, h = 1024, 4096
    params = {"W1": jnp.zeros((d, h), jnp.bfloat16),
              "W2": jnp.zeros((h, d), jnp.bfloat16)}
    x = jnp.zeros((32, d), jnp.bfloat16)
    shardings = {"W1": NamedSharding(mesh, P(None, "model")),
                 "W2": NamedSharding(mesh, P("model", None))}

    def fwd(p, x):
        hdn = jax.nn.relu(x @ p["W1"])
        return jnp.sum((hdn @ p["W2"]) ** 2)

    def step(p, x):
        return jax.value_and_grad(fwd)(p, x)

    jitted = jax.jit(step,
                     in_shardings=({"W1": shardings["W1"],
                                    "W2": shardings["W2"]},
                                   NamedSharding(mesh, P())))
    return jitted, (jax.device_put(params, shardings), x)


def sp_ring(mesh_devices=8, t_total=8192):
    """Ring-attention fwd+bwd: collective-permute KV/mask blocks per
    ring step (the long-context SP path)."""
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.ring_attention import \
        ring_self_attention
    mesh = make_mesh({"seq": mesh_devices})
    b, h, d = 1, 8, 128
    q = jnp.zeros((b, t_total, h, d), jnp.bfloat16)

    def loss(q):
        return jnp.sum(
            ring_self_attention(q, q, q, mesh, causal=True)
            .astype(jnp.float32) ** 2)

    jitted = jax.jit(jax.value_and_grad(loss))
    return jitted, (q,)


def _try_row(rows, name, build_and_analyze):
    """One table row, or a visibly-skipped placeholder when the
    config needs a capability this environment lacks (the ring
    attention path wants ``jax.typeof``) — a broken config must not
    take down the other rows' evidence."""
    try:
        row = build_and_analyze()
    except Exception as e:
        row = {"name": name, "collectives": {}, "wire_bytes": 0.0,
               "t_ici_ms": 0.0, "by_scope": {},
               "skipped": f"{type(e).__name__}: {e}"}
    rows.append(row)
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--markdown", action="store_true")
    args = ap.parse_args()

    rows = []
    for name, build in [("DP ResNet-50 (grad allreduce)", dp_resnet),
                        ("TP MLP col→row (activation allreduce)",
                         tp_mlp),
                        ("SP ring attention T=8k causal", sp_ring)]:
        _try_row(rows, name,
                 lambda name=name, build=build: analyze(
                     name, *build()[:2]))
    # ZeRO-DP sharded weight update: reduce-scatter + all-gather
    # replace the gradient allreduce at identical ring wire volume
    _try_row(rows, "ZeRO-DP MLP (sharded weight update)",
             lambda: analyze("ZeRO-DP MLP (sharded weight update)",
                             *dp_sharded_wrapper()[:2]))
    # dense DP baseline on the SAME model — the comparator the
    # encoded row is measured against
    dense = _try_row(
        rows, "DP MLP dense baseline (replicated update)",
        lambda: analyze("DP MLP dense baseline (replicated update)",
                        *dp_sharded_wrapper(sharded_update=False)[:2]))
    # encoded-gradient exchange (ROADMAP item 4's measurement bed):
    # measured wire vs the dense baseline, through the ledger API
    enc = _try_row(
        rows, "Encoded DP MLP (ParallelWrapper ENCODED)",
        lambda: analyze("Encoded DP MLP (ParallelWrapper ENCODED)",
                        *encoded_wrapper()))
    if not enc.get("skipped") and dense["wire_bytes"]:
        enc["vs_dense"] = enc["wire_bytes"] / dense["wire_bytes"]

    def _composed():
        step, a, ctx, _axes = composed_lm()
        with ctx:   # compiled under its ambient context
            return analyze("Composed DP×SP×TP causal-LM step", step, a)

    _try_row(rows, "Composed DP×SP×TP causal-LM step", _composed)

    if args.markdown:
        print("| config | collectives (count × kind) | wire MB/step "
              "| projected ICI ms (45 GB/s link) | vs dense |")
        print("|---|---|---|---|---|")
        for r in rows:
            if r.get("skipped"):
                print(f"| {r['name']} | skipped: {r['skipped']} "
                      "| — | — | — |")
                continue
            kinds = ", ".join(f"{c}× {k}"
                              for k, (c, _) in sorted(
                                  r["collectives"].items()))
            vs = (f"{r['vs_dense']:.2f}×"
                  if r.get("vs_dense") is not None else "—")
            print(f"| {r['name']} | {kinds} "
                  f"| {r['wire_bytes'] / 1e6:.1f} "
                  f"| {r['t_ici_ms']:.2f} | {vs} |")
    else:
        for r in rows:
            print(r)


if __name__ == "__main__":
    main()
