"""TPU-tunnel watcher: timestamped retry log + auto-dossier on success.

Telemetry (PR 2): pass ``--metrics-url http://HOST:PORT/metrics`` (and
optionally ``--healthz-url``, ``--trace-jsonl PATH``) to also scrape a
live run's telemetry endpoint each interval — step counts/latency
sums, retrace/compile counters, stale workers, and the top span names
from the Chrome-trace JSONL — appending one structured line per
sample to the same retry log. When the run publishes numerics
observatory families (``dl4j_tpu_numerics_*``, PR 4) each sample also
emits a ``numerics`` view: top-k update:param ratio outliers, a
total-grad-norm sparkline across samples, worst replica divergence,
and a NaN alarm from the nonfinite counters. This replaces the old private-format
approach: the watcher reads the SAME ``/metrics`` exposition and trace
JSONL every other consumer uses (``docs/OPS.md`` "Telemetry
operations").

Devtime (obs/devtime.py): when the run publishes device-time
observatory families (``dl4j_tpu_devtime_*``, a ``DL4J_TPU_DEVTIME``
cadence monitor or explicit captures) each sample also emits a
``devtime`` view: the last capture's scope ranking (share, device ms,
roofline utilization — the gap report's ``gap.scope``/``gap.share``/
``gap.utilization`` columns) and the scopes flagged
``gap.pallas_candidate``.

Commtime (obs/commtime.py): when the run publishes communication
observatory families (``dl4j_tpu_comm_*``, a ``DL4J_TPU_COMMTIME``
cadence monitor or explicit captures) each sample also emits a
``comm`` view: per-scope wire MB/step + collective ms, a link-
utilization sparkline across samples, the top wire-bound scopes from
the authoritative ``dl4j_tpu_comm_wire_bound_scopes`` flags, and a
WIRE_BOUND alarm when collective seconds exceed half the measured
device time. ``--comm`` narrows the metrics scrape to just this view.

Fleet (obs/fleet.py): pass ``--fleet-dir <elastic_dir>`` to tail an
elastic fleet's telemetry snapshots incrementally (same model as the
trace-JSONL tail: the snapshots are small atomic files, the skew
history accumulates across samples). Each interval emits a ``fleet``
view: the per-host step/epoch/age table, a collective-skew sparkline
with the straggler named, and NONFINITE / EVICTED alarms from the
merged exposition and the postmortem bundles. When the fleet is a
SERVING fleet (serving/fleet.py) the same sample adds a ``replicas``
table — lease-backed readiness, router-facing address, queue depth,
KV-page occupancy, warm buckets, shed count, lease age — plus a
NOT_READY alarm from ``dl4j_tpu_serving_fleet_replica_ready``; and
when the scraped ``/metrics`` endpoint is a router front end, a
``router`` view renders ``dl4j_tpu_router_requests_total`` by
replica, ``dl4j_tpu_router_replicas_ready``, re-route/shed totals
(``dl4j_tpu_router_reroutes_total`` / ``dl4j_tpu_router_sheds_total``
by reason), and the supervisor's
``dl4j_tpu_serving_fleet_spawns_total`` /
``dl4j_tpu_serving_fleet_evictions_total`` counters.

VERDICT r3 Next #1: the perf dossier must land the instant the tunnel
answers, and if it never does the round must carry "a timestamped retry
log proving the tunnel never came up". This script is that loop:

  * every ``--interval`` seconds, probe the backend in a subprocess
    (bounded; a hung tunnel manifests as a timeout, never a hang);
  * append one JSON line per attempt to ``TPU_RETRY_LOG.jsonl``;
  * on the FIRST successful probe, run ``bench.py`` and
    ``tools/perf_dossier.py`` (all configs), log their exit status, and
    exit 0 so the caller can pick up the results.

Run it backgrounded for the whole round:

    python tools/tpu_watch.py --interval 600
"""
from __future__ import annotations

import argparse
import datetime
import json
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
LOG = REPO / "TPU_RETRY_LOG.jsonl"


def _log(**fields) -> None:
    fields["ts"] = datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds"
    )
    with LOG.open("a") as f:
        f.write(json.dumps(fields) + "\n")
    print(json.dumps(fields), flush=True)


# incremental trace tail: the JSONL is append-only and can reach
# hundreds of MB over a traced multi-hour round — re-reading it whole
# every interval would grow without bound, so track (offset, partial
# last line) per file and accumulate span totals across samples
_TRACE_POS: dict = {}      # path -> (byte offset, carry-over fragment)
_SPAN_TOTALS: dict = {}    # span name -> total dur (us)


def _trace_tail(path):
    offset, carry = _TRACE_POS.get(path, (0, ""))
    with open(path) as f:
        f.seek(offset)
        chunk = f.read()
        offset = f.tell()
    text = carry + chunk
    lines = text.split("\n")
    carry = lines.pop()            # possibly-partial last line
    _TRACE_POS[path] = (offset, carry)
    for line in lines:
        line = line.strip().rstrip(",")
        if not line or line in ("[", "]"):
            continue
        try:
            yield json.loads(line)
        except ValueError:
            continue


_METRIC_KEYS = ("dl4j_tpu_step_latency_seconds_count",
                "dl4j_tpu_step_latency_seconds_sum",
                "dl4j_tpu_steps_total",
                "dl4j_tpu_fit_etl_seconds_total",
                "dl4j_tpu_retrace_", "dl4j_tpu_compile_",
                "dl4j_tpu_worker_stale",
                "dl4j_tpu_inference_requests_total",
                "dl4j_tpu_numerics_", "dl4j_tpu_serving_",
                "dl4j_tpu_devtime_", "dl4j_tpu_comm_")

# numerics view state: total-grad-norm history across samples feeds the
# sparkline (bounded — one char per retained sample)
_GRAD_HISTORY: list = []
_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(values, width=32) -> str:
    vals = values[-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(_SPARK[int((v - lo) / span * (len(_SPARK) - 1))]
                   for v in vals)


def _numerics_view(fams) -> dict:
    """Render the numerics observatory families from one /metrics
    scrape: top-k update:param ratio outliers, a total-grad-norm
    sparkline across samples, worst replica divergence, and a NaN
    alarm (nonzero nonfinite counters)."""
    def family(name):
        return {dict(labels).get("layer", ""): v
                for (n, labels), v in fams.items() if n == name}

    ratios = family("dl4j_tpu_numerics_update_ratio")
    grads = family("dl4j_tpu_numerics_grad_norm")
    diverg = family("dl4j_tpu_numerics_replica_divergence")
    nonfinite = {
        (dict(labels).get("layer", ""), dict(labels).get("kind", "")): v
        for (n, labels), v in fams.items()
        if n == "dl4j_tpu_numerics_nonfinite_total"}
    view: dict = {}
    if ratios:
        top = sorted(ratios.items(), key=lambda kv: -kv[1])[:5]
        view["top_update_ratios"] = {l: round(v, 6) for l, v in top}
    if grads:
        total = sum(grads.values())
        _GRAD_HISTORY.append(total)
        del _GRAD_HISTORY[:-64]
        view["grad_norm_total"] = round(total, 6)
        view["grad_norm_sparkline"] = _sparkline(_GRAD_HISTORY)
    if diverg:
        worst = max(diverg.items(), key=lambda kv: kv[1])
        view["replica_divergence_max"] = {"layer": worst[0],
                                          "value": round(worst[1], 6)}
    alarms = {f"{l}/{k}": int(v) for (l, k), v in nonfinite.items()
              if v > 0}
    if alarms:
        view["NONFINITE_ALARM"] = alarms
    return view


# serving view state: tokens_total across samples feeds a throughput
# sparkline (deltas between scrapes)
_TOKENS_HISTORY: list = []
_LAST_TOKENS: list = [None]


def _hist_quantile(fams, name, q):
    """Quantile estimate from one scrape's cumulative histogram
    buckets (upper-bound of the first bucket whose cumulative count
    reaches the quantile)."""
    buckets = sorted(
        ((float("inf") if dict(labels)["le"] == "+Inf"
          else float(dict(labels)["le"])), v)
        for (n, labels), v in fams.items()
        if n == name + "_bucket")
    total = fams.get((name + "_count", ()), 0)
    if not buckets or not total:
        return None
    target = q * total
    for le, cum in buckets:
        if cum >= target:
            return None if le == float("inf") else le
    return None


def _serving_view(fams) -> dict:
    """Render the continuous-batching gateway families from one
    /metrics scrape: occupancy (slots/queue/pages), TTFT p50/p99 from
    the histogram, shed totals by reason, and a token-throughput
    sparkline across samples."""
    def val(name, default=None):
        return fams.get((name, ()), default)

    tokens = val("dl4j_tpu_serving_tokens_total")
    if tokens is None:
        return {}
    view = {
        "active_slots": val("dl4j_tpu_serving_active_slots"),
        "queue_depth": val("dl4j_tpu_serving_queue_depth"),
        "kv_pages_free": val("dl4j_tpu_serving_kv_pages_free"),
        "tokens_total": int(tokens),
    }
    if _LAST_TOKENS[0] is not None:
        _TOKENS_HISTORY.append(max(0.0, tokens - _LAST_TOKENS[0]))
        del _TOKENS_HISTORY[:-64]
        view["tokens_sparkline"] = _sparkline(_TOKENS_HISTORY)
    _LAST_TOKENS[0] = tokens
    for q, key in ((0.5, "ttft_p50_s"), (0.99, "ttft_p99_s")):
        est = _hist_quantile(fams, "dl4j_tpu_serving_ttft_seconds", q)
        if est is not None:
            view[key] = est
    occ = val("dl4j_tpu_serving_kv_page_occupancy")
    if occ is not None:
        view["kv_page_occupancy"] = round(occ, 4)
    reserved = {dict(labels).get("tenant", ""): int(v)
                for (n, labels), v in fams.items()
                if n == "dl4j_tpu_serving_kv_pages_reserved" and v > 0}
    if reserved:
        view["kv_pages_reserved"] = dict(sorted(
            reserved.items(), key=lambda kv: -kv[1])[:8])
    shed = {dict(labels).get("reason", ""): int(v)
            for (n, labels), v in fams.items()
            if n == "dl4j_tpu_serving_requests_shed_total" and v > 0}
    if shed:
        view["SHED"] = shed
    # speculative decode: live accept rate from the cumulative
    # drafted/accepted counters (dl4j_tpu_serving_spec_accept_rate is
    # the per-step histogram; the counter ratio is the cheap scrape-
    # time aggregate)
    drafted = val("dl4j_tpu_serving_spec_drafted_total")
    if drafted:
        accepted = val("dl4j_tpu_serving_spec_accepted_total", 0)
        view["spec_drafted"] = int(drafted)
        view["spec_accept_rate"] = round(accepted / drafted, 4)
    # copy-on-write prefix sharing: admission hits, prefill tokens the
    # shared pages saved, pages currently multi-referenced, CoW clones
    hits = val("dl4j_tpu_serving_prefix_hits_total")
    if hits:
        view["prefix_hits"] = int(hits)
        view["prefix_tokens_saved"] = int(
            val("dl4j_tpu_serving_prefix_prefill_tokens_saved_total",
                0))
        view["prefix_cow_copies"] = int(
            val("dl4j_tpu_serving_prefix_cow_copies_total", 0))
    shared = val("dl4j_tpu_serving_prefix_shared_pages")
    if shared:
        view["prefix_shared_pages"] = int(shared)
    return view


def _router_view(fams) -> dict:
    """Render the elastic-fleet routing plane (serving/fleet.py) from
    one /metrics scrape: per-replica routed-request counters, the
    ready-replica gauge, re-route/shed totals, and the supervisor's
    spawn/eviction counters. A SHED alarm keys structural losses by
    reason — every one is a client-visible ``SequenceAborted``."""
    def val(name, default=None):
        return fams.get((name, ()), default)

    routed = {dict(labels).get("replica", ""): int(v)
              for (n, labels), v in fams.items()
              if n == "dl4j_tpu_router_requests_total"}
    ready = val("dl4j_tpu_router_replicas_ready")
    if not routed and ready is None:
        return {}
    view: dict = {"requests_by_replica": dict(sorted(routed.items()))}
    if ready is not None:
        view["replicas_ready"] = int(ready)
    reroutes = val("dl4j_tpu_router_reroutes_total")
    if reroutes:
        view["reroutes"] = int(reroutes)
    spawns = val("dl4j_tpu_serving_fleet_spawns_total")
    if spawns:
        view["fleet_spawns"] = int(spawns)
    evictions = val("dl4j_tpu_serving_fleet_evictions_total")
    if evictions:
        view["fleet_evictions"] = int(evictions)
    warm = val("dl4j_tpu_serving_fleet_warm_buckets")
    if warm is not None:
        view["warm_buckets"] = int(warm)
    shed = {dict(labels).get("reason", ""): int(v)
            for (n, labels), v in fams.items()
            if n == "dl4j_tpu_router_sheds_total" and v > 0}
    if shed:
        view["SHED"] = shed
    return view


def _devtime_view(fams) -> dict:
    """Render the device-time observatory families from one /metrics
    scrape: the last capture's scope ranking (each entry mirrors the
    gap report's ``gap.scope`` / ``gap.share`` / ``gap.utilization``
    columns) and the scopes it flagged as ``gap.pallas_candidate``."""
    def by_scope(name):
        return {dict(labels).get("scope", ""): v
                for (n, labels), v in fams.items() if n == name}

    shares = by_scope("dl4j_tpu_devtime_scope_share")
    if not shares:
        return {}
    secs = by_scope("dl4j_tpu_devtime_scope_seconds")
    utils_ = by_scope("dl4j_tpu_devtime_scope_utilization")
    top = sorted(shares.items(), key=lambda kv: -kv[1])[:8]
    view: dict = {
        "captures": fams.get(("dl4j_tpu_devtime_captures_total", ())),
        "top_scopes": {
            s: {"share": round(v, 4),
                "device_ms": round(secs.get(s, 0.0) * 1e3, 3),
                **({"utilization": round(utils_[s], 4)}
                   if s in utils_ else {})}
            for s, v in top},
    }
    # the AUTHORITATIVE per-scope flag published with the gap report
    # — never re-derive the candidate rule scrape-side
    cands = sorted(
        s for s, v in by_scope(
            "dl4j_tpu_devtime_scope_pallas_candidate").items() if v)
    if cands:
        view["PALLAS_CANDIDATES"] = cands
    return view


# comm view state: per-sample max link utilization feeds the sparkline
_LINK_HISTORY: list = []

# WIRE_BOUND alarm threshold: total collective share of device time
_WIRE_BOUND_ALARM_SHARE = 0.5


def _comm_view(fams) -> dict:
    """Render the communication observatory families from one
    /metrics scrape: per-scope wire MB/step + collective ms table, a
    link-utilization sparkline across samples, the top wire-bound
    scopes (the AUTHORITATIVE ``dl4j_tpu_comm_wire_bound_scopes``
    flags — never re-derived scrape-side), and a WIRE_BOUND alarm
    when collective time exceeds half the measured device time."""
    def by(name, label="scope"):
        return {dict(labels).get(label, ""): v
                for (n, labels), v in fams.items() if n == name}

    secs = by("dl4j_tpu_comm_scope_collective_seconds")
    wire = by("dl4j_tpu_comm_scope_wire_bytes_per_step")
    if not secs and not wire:
        return {}
    shares = by("dl4j_tpu_comm_scope_step_share")
    utils_ = by("dl4j_tpu_comm_scope_link_utilization")
    names = sorted(set(secs) | set(wire),
                   key=lambda s: -secs.get(s, 0.0))
    view: dict = {
        "captures": fams.get(("dl4j_tpu_comm_captures_total", ())),
        "scopes": {
            s: {"collective_ms": round(secs.get(s, 0.0) * 1e3, 3),
                **({"wire_mb_per_step": round(wire[s] / 1e6, 3)}
                   if s in wire else {}),
                **({"share": round(shares[s], 4)}
                   if s in shares else {}),
                **({"link_utilization": round(utils_[s], 4)}
                   if s in utils_ else {})}
            for s in names[:8]},
    }
    if utils_:
        _LINK_HISTORY.append(max(utils_.values()))
        del _LINK_HISTORY[:-64]
        view["link_utilization_sparkline"] = _sparkline(_LINK_HISTORY)
    counts = by("dl4j_tpu_comm_op_count", label="kind")
    if counts:
        view["op_counts"] = {k: int(v) for k, v in sorted(
            counts.items(), key=lambda kv: -kv[1])}
    bound = sorted(s for s, v in by(
        "dl4j_tpu_comm_wire_bound_scopes").items() if v)
    if bound:
        view["wire_bound_scopes"] = bound
    total_share = sum(shares.values())
    if total_share >= _WIRE_BOUND_ALARM_SHARE or bound:
        view["WIRE_BOUND_ALARM"] = {
            "comm_share": round(total_share, 4),
            "scopes": bound,
        }
    return view


# fleet view state: per-sample max collective skew feeds the sparkline
# (bounded, like the grad-norm history)
_SKEW_HISTORY: list = []


def _fleet_view(fleet_dir) -> dict:
    """One sample of an elastic fleet's merged telemetry: the per-host
    table, the skew sparkline + named straggler, and the alarms."""
    from deeplearning4j_tpu.obs import fleet as obs_fleet
    from deeplearning4j_tpu.obs import metrics as obs_metrics

    view = obs_fleet.aggregate(fleet_dir)
    out: dict = {"hosts": view.table()}
    serving = view.serving_table()
    if serving:
        # serving-replica columns (serving/fleet.py): lease-backed
        # readiness + the load signals the router steers on
        out["replicas"] = {
            host: {
                "ready": bool(row.get("ready")),
                "live": bool(row.get("live")),
                "addr": row.get("addr"),
                "queue_depth": row.get("queue_depth"),
                "kv_page_occupancy": row.get("kv_page_occupancy"),
                "warm_buckets": row.get("warm_buckets"),
                "sheds": row.get("sheds"),
                "lease_age_s": row.get("lease_age_s"),
                "mesh_epoch": row.get("mesh_epoch"),
            }
            for host, row in sorted(serving.items())}
    rep = view.skew_report()
    if rep:
        _SKEW_HISTORY.append(rep["max_skew_s"])
        del _SKEW_HISTORY[:-64]
        out["skew"] = {
            "step": rep["step"],
            "max_skew_s": rep["max_skew_s"],
            "straggler": rep["straggler"],
            "sparkline": _sparkline(_SKEW_HISTORY),
            # per-step [step, skew_s, last_in_host] — who entered the
            # collective last, step by step
            "series": rep["series"][-8:],
        }
    alarms: dict = {}
    fams = obs_metrics.parse_exposition(view.exposition())
    nonfinite = {
        f"{dict(labels).get('host', '')}:"
        f"{dict(labels).get('layer', '')}/"
        f"{dict(labels).get('kind', '')}": int(v)
        for (name, labels), v in fams.items()
        if name == "dl4j_tpu_numerics_nonfinite_total" and v > 0}
    if nonfinite:
        alarms["NONFINITE"] = nonfinite
    evicted = view.evicted()
    if evicted:
        alarms["EVICTED"] = evicted
    # a lease-live replica the router will NOT admit to (warming, or
    # its readiness probe regressed) — the merged exposition's
    # dl4j_tpu_serving_fleet_replica_ready gauge is authoritative
    not_ready = sorted(
        dict(labels).get("host", "")
        for (name, labels), v in fams.items()
        if name == "dl4j_tpu_serving_fleet_replica_ready" and v < 1)
    if not_ready:
        alarms["NOT_READY"] = not_ready
    if alarms:
        out["alarms"] = alarms
    return out


def _scrape_telemetry(metrics_url, healthz_url, trace_jsonl,
                      fleet_dir=None, comm_only=False) -> None:
    """One sample of a live run's telemetry, appended to the log.
    Scrape failures are logged, never fatal — the run may simply not
    have started its endpoint yet."""
    import urllib.error
    import urllib.request

    from deeplearning4j_tpu.obs import metrics as obs_metrics

    if metrics_url:
        try:
            with urllib.request.urlopen(metrics_url, timeout=5) as r:
                fams = obs_metrics.parse_exposition(r.read().decode())
            if comm_only:
                # --comm: the focused wire watch — just the comm view
                cview = _comm_view(fams)
                _log(event="comm", url=metrics_url, **cview)
                return
            sample = {f"{name}{dict(labels) if labels else ''}": v
                      for (name, labels), v in sorted(fams.items())
                      if name.startswith(_METRIC_KEYS)}
            _log(event="metrics", url=metrics_url, sample=sample)
            view = _numerics_view(fams)
            if view:
                _log(event="numerics", url=metrics_url, **view)
            sview = _serving_view(fams)
            if sview:
                _log(event="serving", url=metrics_url, **sview)
            rview = _router_view(fams)
            if rview:
                _log(event="router", url=metrics_url, **rview)
            dview = _devtime_view(fams)
            if dview:
                _log(event="devtime", url=metrics_url, **dview)
            cview = _comm_view(fams)
            if cview:
                _log(event="comm", url=metrics_url, **cview)
        except Exception as e:
            _log(event="metrics", url=metrics_url, error=repr(e))
    if healthz_url:
        try:
            with urllib.request.urlopen(healthz_url, timeout=5) as r:
                _log(event="healthz", url=healthz_url,
                     body=json.loads(r.read().decode()))
        except urllib.error.HTTPError as e:
            # /healthz answers 503 WITH a body naming the stale
            # workers — the one payload this flag exists to capture
            try:
                body = json.loads(e.read().decode())
            except Exception:
                body = None
            _log(event="healthz", url=healthz_url, status=e.code,
                 body=body)
        except Exception as e:
            _log(event="healthz", url=healthz_url, error=repr(e))
    if trace_jsonl:
        try:
            for ev in _trace_tail(trace_jsonl):
                if ev.get("ph") == "X":
                    _SPAN_TOTALS[ev["name"]] = \
                        _SPAN_TOTALS.get(ev["name"], 0.0) \
                        + ev.get("dur", 0.0)
            top = sorted(_SPAN_TOTALS.items(),
                         key=lambda kv: -kv[1])[:8]
            _log(event="trace", path=trace_jsonl,
                 top_spans_ms={k: round(v / 1e3, 3) for k, v in top})
        except Exception as e:
            _log(event="trace", path=trace_jsonl, error=repr(e))
    if fleet_dir:
        try:
            _log(event="fleet", dir=str(fleet_dir),
                 **_fleet_view(fleet_dir))
        except Exception as e:
            _log(event="fleet", dir=str(fleet_dir), error=repr(e))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=int, default=600)
    ap.add_argument("--probe-timeout", type=int, default=120)
    ap.add_argument("--max-attempts", type=int, default=0,
                    help="stop after N failed attempts (0 = forever)")
    ap.add_argument("--metrics-url", default=None,
                    help="Prometheus /metrics endpoint of a live run "
                         "to sample each interval")
    ap.add_argument("--healthz-url", default=None,
                    help="/healthz endpoint to sample each interval")
    ap.add_argument("--trace-jsonl", default=None,
                    help="obs trace JSONL to summarize each interval")
    ap.add_argument("--comm", action="store_true",
                    help="narrow the --metrics-url scrape to the "
                         "communication observatory view: per-scope "
                         "wire MB/step, link-utilization sparkline, "
                         "top wire-bound scopes, WIRE_BOUND alarm")
    ap.add_argument("--fleet-dir", default=None,
                    help="elastic fleet dir (DL4J_TPU_ELASTIC_DIR) to "
                         "aggregate each interval: per-host table, "
                         "collective-skew sparkline + straggler, "
                         "NONFINITE/EVICTED alarms")
    args = ap.parse_args()

    sys.path.insert(0, str(REPO))
    from deeplearning4j_tpu.utils.backend_probe import probe_backend

    attempt = 0
    while True:
        attempt += 1
        if args.metrics_url or args.healthz_url or args.trace_jsonl \
                or args.fleet_dir:
            _scrape_telemetry(args.metrics_url, args.healthz_url,
                              args.trace_jsonl, args.fleet_dir,
                              comm_only=args.comm)
        ok, info = probe_backend(timeout=args.probe_timeout)
        _log(event="probe", attempt=attempt, ok=ok, info=info)
        if ok:
            _log(event="tunnel_up", attempt=attempt)
            # The tunnel can flap: bench/dossier re-probe internally and
            # emit {"skipped": true} with rc=0 on a drop, so "rc==0" is
            # NOT success — require a non-skip bench line too, else fall
            # back into the retry loop.
            landed = True
            for label, cmd in [
                ("bench", [sys.executable, str(REPO / "bench.py")]),
                ("dossier", [sys.executable, str(REPO / "tools/perf_dossier.py"),
                             "--out", str(REPO / "PERF_DOSSIER_r04.json")]),
            ]:
                t0 = time.time()
                try:
                    r = subprocess.run(cmd, cwd=REPO, capture_output=True,
                                       text=True, timeout=5400)
                    skipped = '"skipped": true' in r.stdout
                    _log(event=label, rc=r.returncode, skipped=skipped,
                         seconds=round(time.time() - t0, 1),
                         tail=r.stdout[-2000:], err_tail=r.stderr[-1000:])
                    if r.returncode != 0 or skipped:
                        landed = False
                except Exception as e:  # timeout or spawn failure
                    _log(event=label, rc=-1, error=repr(e))
                    landed = False
            if landed:
                return 0
            _log(event="tunnel_flapped_resuming_watch")
        if args.max_attempts and attempt >= args.max_attempts:
            _log(event="giving_up", attempts=attempt)
            return 1
        time.sleep(args.interval)


if __name__ == "__main__":
    raise SystemExit(main())
