"""TPU-tunnel watcher: timestamped retry log + auto-dossier on success.

VERDICT r3 Next #1: the perf dossier must land the instant the tunnel
answers, and if it never does the round must carry "a timestamped retry
log proving the tunnel never came up". This script is that loop:

  * every ``--interval`` seconds, probe the backend in a subprocess
    (bounded; a hung tunnel manifests as a timeout, never a hang);
  * append one JSON line per attempt to ``TPU_RETRY_LOG.jsonl``;
  * on the FIRST successful probe, run ``bench.py`` and
    ``tools/perf_dossier.py`` (all configs), log their exit status, and
    exit 0 so the caller can pick up the results.

Run it backgrounded for the whole round:

    python tools/tpu_watch.py --interval 600
"""
from __future__ import annotations

import argparse
import datetime
import json
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
LOG = REPO / "TPU_RETRY_LOG.jsonl"


def _log(**fields) -> None:
    fields["ts"] = datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds"
    )
    with LOG.open("a") as f:
        f.write(json.dumps(fields) + "\n")
    print(json.dumps(fields), flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=int, default=600)
    ap.add_argument("--probe-timeout", type=int, default=120)
    ap.add_argument("--max-attempts", type=int, default=0,
                    help="stop after N failed attempts (0 = forever)")
    args = ap.parse_args()

    sys.path.insert(0, str(REPO))
    from deeplearning4j_tpu.utils.backend_probe import probe_backend

    attempt = 0
    while True:
        attempt += 1
        ok, info = probe_backend(timeout=args.probe_timeout)
        _log(event="probe", attempt=attempt, ok=ok, info=info)
        if ok:
            _log(event="tunnel_up", attempt=attempt)
            # The tunnel can flap: bench/dossier re-probe internally and
            # emit {"skipped": true} with rc=0 on a drop, so "rc==0" is
            # NOT success — require a non-skip bench line too, else fall
            # back into the retry loop.
            landed = True
            for label, cmd in [
                ("bench", [sys.executable, str(REPO / "bench.py")]),
                ("dossier", [sys.executable, str(REPO / "tools/perf_dossier.py"),
                             "--out", str(REPO / "PERF_DOSSIER_r04.json")]),
            ]:
                t0 = time.time()
                try:
                    r = subprocess.run(cmd, cwd=REPO, capture_output=True,
                                       text=True, timeout=5400)
                    skipped = '"skipped": true' in r.stdout
                    _log(event=label, rc=r.returncode, skipped=skipped,
                         seconds=round(time.time() - t0, 1),
                         tail=r.stdout[-2000:], err_tail=r.stderr[-1000:])
                    if r.returncode != 0 or skipped:
                        landed = False
                except Exception as e:  # timeout or spawn failure
                    _log(event=label, rc=-1, error=repr(e))
                    landed = False
            if landed:
                return 0
            _log(event="tunnel_flapped_resuming_watch")
        if args.max_attempts and attempt >= args.max_attempts:
            _log(event="giving_up", attempts=attempt)
            return 1
        time.sleep(args.interval)


if __name__ == "__main__":
    raise SystemExit(main())
