"""Generate docs/INVENTORY.md — the auto-generated component inventory
(analog of the reference's contrib/codegen-tools op-def generation:
there it generates op classes + docs from definitions; here the living
registries ARE the definitions, and this script renders them).

    python tools/gen_inventory.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

# sitecustomize forces the axon TPU platform and overrides
# JAX_PLATFORMS; force CPU before any device use so doc generation
# never waits on (or hangs with) the TPU tunnel
jax.config.update("jax_platforms", "cpu")


# hand-maintained operations doc, re-emitted on every regeneration so
# the auto-generated op reference never clobbers it (ISSUE 2 satellite:
# the telemetry workflow lives in docs/OPS.md)
TELEMETRY_OPS_SECTION = """
## Telemetry operations (obs/)

Operating a run with the telemetry spine (ARCHITECTURE.md §9):

**Capture a timeline.** `DL4J_TPU_TRACE=1 python train.py` writes
`dl4j_tpu_trace_<pid>.jsonl` (or set the flag to an explicit path).
Drop the file into `chrome://tracing` or https://ui.perfetto.dev to
see per-thread `fit/etl` / `fit/step` / `fit/h2d` / `fit/dispatch` /
`fit/sync` spans. Summarize from the shell with

    python tools/xprof_summary.py dl4j_tpu_trace_<pid>.jsonl

(the same tool's XProf mode covers the device side: point it at a
`jax.profiler.trace` capture dir).

**Scrape metrics.** Start the endpoint with
`DL4J_TPU_METRICS_PORT=9464` (or `obs.metrics.start_server()` in
code), then point Prometheus — or `curl` — at
`http://127.0.0.1:9464/metrics`; `/healthz` returns 503 naming any
worker whose heartbeat is older than `DL4J_TPU_STALE_WORKER_SECS`.
Step-latency histograms, ETL waits, serving queue depth, retrace
sentry and compile-cache counters all appear as `dl4j_tpu_*`
families.

**Watch a long round.** `tools/tpu_watch.py` samples the same
surfaces between backend probes:

    python tools/tpu_watch.py --interval 600 \\
        --metrics-url http://127.0.0.1:9464/metrics \\
        --healthz-url http://127.0.0.1:9464/healthz \\
        --trace-jsonl dl4j_tpu_trace_<pid>.jsonl

appending one structured JSONL line per sample to
`TPU_RETRY_LOG.jsonl` (step counts/latency sums, retrace/compile
counters, stale workers, top span totals).

**Post-mortems.** HBM-OOM crash dumps (`utils/crashreport.py`) carry
`perf.compile_report()` and `obs.report()` — metric values, worker
health, and the last spans of the dying run — next to the device
memory map.
"""

# hand-maintained operations doc, re-emitted on every regeneration
# (ISSUE 3 satellite: the failure & recovery runbook lives in
# docs/OPS.md next to the telemetry workflow)
RESILIENCE_OPS_SECTION = """
## Failure & recovery runbook (resilience/)

Operating a run through failure (ARCHITECTURE.md §10):

**Preemption.** SIGTERM (what a preemptible slice receives) is honored
at the next iteration boundary when training under
`FaultTolerantTrainer`: the run checkpoints, persists `progress.json`
(with the mid-epoch `batch_in_epoch` position), and returns cleanly —
exit code 0. Re-running the same script resumes via
`resume_or_init(factory, ckpt_dir)`: newest *valid* checkpoint +
progress counters, replaying the exact uninterrupted trajectory
(`dl4j_tpu_preemptions_total` counts the clean stops).

**Corrupt checkpoints.** Every restore path verifies before it
restores (zip CRC sweep + required entries + the sidecar
`*.manifest.json` CRC32/size). A corrupt or partial checkpoint is
moved to `<ckpt_dir>/corrupt/` — inspect it there, it never blocks
the restart loop — and restore falls back to the newest valid one
(`dl4j_tpu_checkpoints_quarantined_total`). Writes are atomic
(tmp+fsync+`os.replace`), so only an external writer or disk fault
can produce one. The orbax sharded path behaves the same:
`ShardedCheckpointer.restore_latest_valid()` quarantines unrestorable
step dirs.

**Sharded optimizer checkpoints (ZeRO).** Training with
`ParallelWrapper(..., sharded_update=True)` carries the optimizer
state as 1/N shards per replica; checkpoint it with
`ShardedCheckpointer.save_wrapper(step, wrapper)` and restore with
`restore_wrapper(wrapper)` onto the SAME mesh topology — each device
writes/reads only its shard and the replicated layout is never
materialized. For zip/`ModelSerializer` export, fold first with
`wrapper.gather_opt_state()` (replicated-layout copy: export only,
never in the training loop).

**Retries.** `FaultTolerantTrainer` classifies errors
(`resilience.policy.classify`): transient (OSError/ConnectionError/
TimeoutError/bare RuntimeError) → restore newest valid checkpoint and
retry under exponential backoff with seeded jitter; deterministic
(shape/dtype/NaN messages) → ONE restore, then re-raise. Watch
`dl4j_tpu_resilience_restarts_total` — a climbing counter with flat
loss means the job is paying restore tax, not training.

**Serving under overload.** `ParallelInference` sheds instead of
blocking: a full queue raises `QueueFullError` at enqueue; a request
whose deadline (the `output(timeout=)` budget, or
`output_async(deadline_s=)`) expires in the queue is dropped
undispatched; `shutdown()` errors queued requests out immediately.
All three surface as
`dl4j_tpu_inference_requests_shed_total{reason=queue_full|deadline|shutdown}`
— alert on its rate vs `dl4j_tpu_inference_requests_total`.

**Fault drills.** Inject failures into a real run with
`DL4J_TPU_FAULT_PLAN` — named plans (`ckpt-io-flake`, `worker-crash`,
`etl-flake`, `serving-crash`, `preempt`) or rule syntax
`site:error=OSError:p=0.5:seed=3:max=2;...` over sites `ckpt_write`,
`ckpt_commit`, `step`, `iterator`, `worker_step`, `serving`. Unset,
the sites cost one branch (counter-asserted). Fires appear in
`dl4j_tpu_faults_injected_total{site=}`. The standing drill harness:

    python tools/chaos.py --plan ckpt-io-flake     # train scenario
    python tools/chaos.py --plan serving-crash     # serving scenario
    python tools/chaos.py --plan "ckpt_write:error=OSError:nth=1" --example lenet_mnist
    python tools/chaos.py --list

asserts convergence-to-baseline under each plan (bit-exact resume for
clean restore paths) and exits nonzero on any regression — run it
after touching checkpoint, trainer, or serving code.
"""


# hand-maintained operations doc, re-emitted on every regeneration
# (ISSUE 4 satellite: the divergence-diagnosis runbook lives in
# docs/OPS.md next to the telemetry workflow)
NUMERICS_OPS_SECTION = """
## Diagnosing divergence (obs/numerics.py)

Operating a run through numeric trouble (ARCHITECTURE.md §11):

**Turn the observatory on.** `net.monitor_numerics(every=N)` makes
every N-th step a *diagnostic step*: the same XLA program returns
per-layer gradient/update/param norms, activation stats from the real
training forward, and non-finite counts as aux outputs — only scalars
cross to host, only at cadence. A `StatsListener` attaches a
record-aligned monitor automatically, so the training dashboard's
grad-norm / update:param-ratio / replica-divergence panels fill in
with zero extra configuration.

**Read the panels.** Healthy runs show update:param ratios drifting
around 1e-3 (the reference StatsListener's rule of thumb) and
per-layer grad norms moving together. A layer whose ratio runs orders
of magnitude hotter than its peers is mis-scaled (LR override,
init); a grad norm collapsing to 0 is a dead layer (check the
`dl4j_tpu_numerics_grad_norm` family); absmax activations marching
toward 3e38 forecast an overflow before it happens.

**NaN attribution.** When gradients or activations go non-finite, the
sentinel raises `NonFiniteError{layer, kind, iteration}` — forward
origin for activations (first layer in forward order), backward
origin for gradients. Under `FaultTolerantTrainer` this classifies
deterministic: ONE restore from the newest valid checkpoint, then
re-raise if it recurs — the log reads "layer gpt.h3.attn gradients
went non-finite at iteration 412 ... restoring iter_400". A
non-finite *score* at a sparse cadence escalates the next step to a
diagnostic one, so attribution is at most one step late.

**Replica divergence.** On the `ParallelWrapper` SYNC path, the
diagnostic step is an explicit `shard_map`: per-replica gradient
norms are `pmax − pmin` reduced before the mean erases them, and the
spread surfaces as `dl4j_tpu_numerics_replica_divergence{layer=}`. A
growing spread with healthy per-replica losses is the signature of a
sick chip (or a desynced data shard) — restart that worker before
the allreduce averages the damage into every replica.

**Watch remotely.** `tools/tpu_watch.py --metrics-url ...` renders a
`numerics` view per sample: top-k update:param outliers, a
total-grad-norm sparkline, worst replica divergence, and a
NONFINITE_ALARM line from the `dl4j_tpu_numerics_nonfinite_total`
counters. With `DL4J_TPU_TRACE` on, per-layer norms also stream as
Perfetto counter tracks (`numerics/grad_norm`) next to the step
spans.

**Drill it.** `DL4J_TPU_FAULT_PLAN="step:error=NonFiniteError:nth=6"`
injects the structured sentinel at the step site — the standing way
to verify the attribute-classify-restore path end-to-end without
poisoning real params.
"""


# hand-maintained operations doc, re-emitted on every regeneration
# (ISSUE 7 satellite: the elastic-fleet runbook lives in docs/OPS.md
# next to the failure & recovery workflow)
ELASTIC_OPS_SECTION = """
## Elastic fleets & host preemption (resilience/elastic.py)

Operating training on preemptible/spot capacity (ARCHITECTURE.md §13):

**Bring-up.** Every host joins the fleet through a
`MembershipCoordinator` over a shared directory: an atomically-written
*lease* file per host, renewed like a heartbeat and mirrored into
`obs/health.py` (a dying peer is named on `/healthz` before the fleet
even reacts). `ElasticTrainer.bring_up()` waits for the expected
hosts, runs the propose→ack→commit agreement round, and forms the
mesh at the agreed world size — the committed *mesh epoch*
(generation number, `dl4j_tpu_mesh_epoch`) stamps every subsequent
step.

**Lease timing.** `DL4J_TPU_HOST_LEASE_SECS` (default 15) is the
eviction window: a host that misses it is moved aside
(`members/evicted/`, `dl4j_tpu_hosts_evicted_total`) at the next
agreement. The collective watchdog defaults to twice the lease — a
peer's death turns an indefinite collective hang into a
`CollectiveTimeoutError` within that window (a gloo/ICI connection
reset surfaces even faster). Size the lease to tolerate your worst
GC/compile pause: the background auto-renew thread keeps a busy host
alive, and a *wedged* host is fenced by the epoch stamp
(`StaleMeshEpoch`), not by lease expiry.

**Host loss.** The survivors' failed step raises (no indefinite
hang), and re-formation happens by *exec*: the wedged collective
runtime cannot be torn down in-process, so each survivor replaces its
process image, re-joins, agrees on the reduced membership (epoch+1,
a new epoch-salted coordinator port — stragglers from the old
generation are rejected, `dl4j_tpu_resilience_restarts_total` counts
the reforms), and **reshard-restores** the newest valid checkpoint:
`ShardedCheckpointer.restore_wrapper(reshard=True)` reads the
`world_<step>.json` manifest, gathers the N-sharded optimizer state,
and re-scatters it through `FlatShardLayout` onto the surviving M
devices — bit-exact on the real content. A corrupt newest step
quarantines and the next-newest valid one still reshards
(`restore_latest_valid(wrapper=...)`).

**Preemption.** SIGTERM on one host of a fleet = graceful departure:
the host drops its lease (`leave()`), peers re-form without waiting
out the window. SIGTERM on a *single-host* world checkpoints first
(the PR 3 behavior). Under `FaultTolerantTrainer` with a ZeRO
`sharded_update=True` wrapper, the preemption checkpoint publishes
through `save_wrapper` (1/N shards + world manifest) — never the
replicated zip path — and resume picks the newer of the sharded and
zip chains.

**Drills.** The standing fleet drill (also
`tests/test_elastic.py`):

    python tools/chaos.py --elastic                    # SIGKILL one of 3 hosts
    python tools/chaos.py --elastic --plan host-preempt  # graceful SIGTERM departure

asserts: bounded-timeout raise within the lease window, re-formation
at the reduced world size, reshard-restore of the newest valid step,
and a post-recovery trajectory bit-identical to the same-scale
uninterrupted baseline. Site-level drills: `host_death` and
`coordinator` fire under `DL4J_TPU_FAULT_PLAN` (named plans
`host-preempt`, `coord-flake`) like every other failure mode.
"""


# hand-maintained operations doc, re-emitted on every regeneration
# (ISSUE 12 satellite: the fleet observability & straggler-hunting
# runbook lives in docs/OPS.md next to the elastic-fleet workflow)
FLEET_OPS_SECTION = """
## Fleet observability & straggler hunting (obs/fleet.py)

Operating a multi-host fleet with the fleet plane (ARCHITECTURE.md
§14):

**What publishes.** Every elastic host (`ElasticTrainer`) atomically
writes a versioned snapshot — its `/metrics` exposition, heartbeat
ages, a numerics tail, mesh epoch, step, and per-step barrier
entry/exit stamps — into `<elastic_dir>/telemetry/<host>.json` at the
`DL4J_TPU_FLEET_PUBLISH_SECS` cadence (default 1 Hz). Non-elastic
training pays one branch and publishes nothing
(`dl4j_tpu_fleet_snapshots_published_total` stays 0).

**Read the fleet view.** Aggregate from anywhere that sees the shared
dir:

    python tools/tpu_watch.py --interval 30 --fleet-dir <elastic_dir>

emits one `fleet` line per sample: the per-host step/epoch/age table,
a collective-skew sparkline with the straggler named, and
NONFINITE/EVICTED alarms. In code, `obs.fleet.aggregate(dir)` merges
every snapshot into one Prometheus exposition where each sample
carries `host=` and `mesh_epoch=` labels; the standing `/metrics`
server also serves it on `/fleet` after
`obs.metrics.set_fleet_dir(dir)` (done automatically by
`ElasticTrainer.bring_up`).

**Hunt stragglers.** `dl4j_tpu_collective_skew_seconds{host=}` is how
late each host entered the anchor collective relative to the first-in
peer; `dl4j_tpu_collective_straggler{host=}` is 1 for the last-in
host. A host that is 40ms late EVERY step is a sick chip or a starved
input pipeline — compare its `fit_etl` share before blaming the ICI.
Attribution anchors on lease evidence, never snapshot staleness: with
every lease live it uses the newest step COMMON to all hosts'
published windows (a snapshot lagging by the publish cadence is
normal, not a verdict); a lease-dead host (expired or no lease at
all) is the straggler, so a corpse is named even while every survivor
is wedged at the same barrier. `/healthz` tells the same story from
one table: `stale_hosts` (lease ages, each under its OWN lease
window) next to `stale_workers`.

**Post-mortems.** On `NonFiniteError`, `StaleMeshEpoch`,
`CollectiveTimeoutError`, SIGTERM preemption, or eviction, the flight
recorder dumps a versioned bundle into `<elastic_dir>/postmortem/`:
the last `DL4J_TPU_FLEET_RING` step records (barrier stamps, loss,
mesh-epoch events), the obs span/metric tail, and the fleet skew view
at the moment of death (`dl4j_tpu_flight_recorder_dumps_total{cause=}`).
When a host is evicted, the surviving leader snapshots the corpse's
FINAL telemetry into `<host>.evicted.<ts>.json` — the dead host's
last step survives the death. Start there: the eviction bundle's
`fleet.skew.straggler` is the ADJUDICATED naming (computed after the
lease verdict); survivor crash dumps race instant transport errors
and are best-effort testimony.

**Drill it.** `python tools/chaos.py --elastic` SIGKILLs one host of
a live fleet and asserts the whole chain: survivor bundles exist with
skew views, the eviction bundle names the corpse as the final-step
straggler and carries its last step, and the post-reform fleet
exposition carries the bumped `mesh_epoch=` labels.
"""


# hand-maintained operations doc, re-emitted on every regeneration
# (ISSUE 13 satellite: the serving-under-load runbook lives in
# docs/OPS.md next to the failure & recovery workflow)
SERVING_OPS_SECTION = """
## Serving under load (serving/)

Operating the continuous-batching gateway (ARCHITECTURE.md §15):

**Bring-up.** Build the gateway over a trained LM and warm it BEFORE
taking traffic:

    gw = ServingGateway(model, net, max_slots=16, block=16,
                        max_context=2048, queue_limit=256)
    gw.warmup()          # decode step + every prefill bucket, AOT

After `warmup()` the retrace sentry must stay flat no matter how
traffic arrives — shapes are fixed at `(max_slots, block)` and
prompts snap to the same power-of-two buckets `generate()` uses
(`zoo.gpt.prompt_bucket`, one shared table). A climbing
`dl4j_tpu_retrace_unplanned_shapes{function="serving.decode_step"}`
means someone changed the step signature without re-warming.

**Size the pool.** The paged KV cache is the admission currency: each
request reserves `ceil(max(prompt_bucket, prompt+max_new-1)/block)`
pages for its WHOLE life, so an admitted sequence never stalls
mid-flight. Watch `dl4j_tpu_serving_kv_pages_free` against
`dl4j_tpu_serving_queue_depth`: pages pinned at 0 with a standing
queue means the pool (`n_pages`) is the bottleneck, not the slots.
Pool bytes = `n_pages x n_layers x Hkv x 2D x block` (x1 int8, x4
f32) — int8 pages (`cache_quant="int8"`) halve the read traffic AND
double the sequences a pool holds.

**Watch the SLOs.** `dl4j_tpu_serving_ttft_seconds` (submit -> first
token: queue wait + prefill) is the admission-health histogram —
a fattening p99 with free pages means slot pressure; with
`dl4j_tpu_serving_kv_pages_free` at 0 it means pool pressure.
`dl4j_tpu_serving_step_seconds` IS the per-token latency every
in-flight sequence pays per iteration. Shed posture mirrors
ParallelInference:
`dl4j_tpu_serving_requests_shed_total{reason=queue_full|deadline|shutdown|fault}`
— alert on its rate vs `dl4j_tpu_serving_requests_total`.
`tools/tpu_watch.py --metrics-url ...` renders a `serving` view per
sample (occupancy, TTFT p50/p99, token-throughput sparkline, SHED
alarms).

**Load-test.** The standing trace driver:

    python tools/serving_trace.py --mode open --rate 200 --requests 256
    python tools/serving_trace.py --mode closed --clients 32 --baseline

(open loop = arrivals you don't control, overload shows up as shed
rate + TTFT tail; closed loop = sustainable throughput at fixed
concurrency; `--baseline` adds the request-at-a-time `generate()`
comparison). `bench.py`'s `serving` section and the dossier's
`continuous_batching` row run the same driver's smoke config.

**Fault posture.** An exception inside a decode iteration (including
the `serving` fault site under `DL4J_TPU_FAULT_PLAN`) sheds every
in-flight sequence with a structured `SequenceAborted` carrying the
tokens already streamed, releases their pages, and keeps serving —
never a wedged slot or leaked page. Drill it:

    python tools/chaos.py --plan serving-crash

asserts both front ends (batched queue + gateway) shed-and-survive,
with page conservation checked.

**Request-scoped traces.** Under `DL4J_TPU_TRACE` every request
leaves an async track in the Chrome JSONL keyed by its request id:
`serving.request` (submit → retire/abort, tenant + outcome + token
count in the args) with nested `serving.request/queue_wait`,
`/prefill`, and `/decode_steps` phases — drop the file into Perfetto
to see exactly where one tenant's p99 went. With tracing off the
request path emits zero events (one branch, the PR 2 contract).

**KV-page occupancy.** `dl4j_tpu_serving_kv_page_occupancy` (fraction
of usable pages reserved — 1.0 means admission control is the
bottleneck, add pages or shed earlier) and
`dl4j_tpu_serving_kv_pages_reserved` per tenant (whole-life
reservations — one tenant pinning the pool starves the rest; the
`tpu_watch` serving view surfaces both next to `kv_pages_free`).
"""

# hand-maintained operations doc, re-emitted on every regeneration
# (ISSUE 16 satellite: the spec-decode + prefix-sharing runbook lives
# in docs/OPS.md next to the serving runbook it extends)
SPEC_DECODE_OPS_SECTION = """
## Speculative decode + prefix sharing (serving/)

Two opt-in gateway features (ARCHITECTURE.md §18) that attack the
serving cost from both ends — admission (copy-on-write prefix
sharing: requests repeating a known prefix adopt its pages and
prefill only the novel suffix) and steady-state decode (self-
speculative multi-token steps: k-1 host-drafted tokens verified in
one fixed-shape forward, the agreeing prefix accepted):

    gw = ServingGateway(model, net, max_slots=16, block=16,
                        spec_k=4, prefix_sharing=True)
    gw.warmup()    # + per-k spec step, CoW copy, suffix buckets

**The k grid.** `spec_k` must come from `scheduler.SPEC_KS` (the
constructor rejects off-grid widths): warmup AOT-compiles one spec
executable per configured k plus the downward closure of suffix
prefill buckets, so ANY admission order — fresh prompt, whole-prompt
repeat, partial-prefix extension — stays retrace-free. Lint rule 10
(`tools/lint_instrumentation.py`) holds the builder set, the
`WARMUP_FEEDS` table, and `SPEC_KS` in lockstep, and fails CI when a
`dl4j_tpu_serving_spec_*` family loses its dashboard/runbook surface.

**Watch the accept rate.** `dl4j_tpu_serving_spec_accept_rate`
(per-step histogram of accepted/(k-1)) is the feature's health
number: tokens/step = `1 + accept_rate * (k-1)`, so a rate pinned
near 0 means the verify rows are pure overhead — lower k or turn
spec off for that workload. The cumulative pair
`dl4j_tpu_serving_spec_accepted_total` /
`dl4j_tpu_serving_spec_drafted_total` gives the same ratio across a
whole deployment window (`tpu_watch`'s serving view renders it as
`spec_accept_rate`). Greedy only: the gateway refuses
`sample=True` + spec, because the accept rule compares argmax.

**Watch the sharing win.** `dl4j_tpu_serving_prefix_hits_total` over
`dl4j_tpu_serving_requests_total` is the admission hit rate;
`dl4j_tpu_serving_prefix_prefill_tokens_saved_total` is the prefill
work sharing deleted (the TTFT win is proportional);
`dl4j_tpu_serving_prefix_shared_pages` gauges how much of the pool is
multi-referenced right now, and
`dl4j_tpu_serving_prefix_cow_copies_total` counts tail-page clones —
a high CoW rate with a low hit rate means prompts share page-aligned
prefixes rarely (raise the system-prompt length, or align it to
`block`).

**Acceptance measurement.** The shared-system-prompt A/B (baseline
gateway vs spec+sharing on the same weight-read-bound CPU smoke LM):

    python tools/serving_trace.py --shared-prefix

reports TTFT and tokens/sec speedups beside prefix-hit rate, prefill
tokens saved, and the accept rate; the dossier's `spec_decode` row
records the same report via the forced-CPU subprocess protocol.
Custom traces: `--prefix-sharing --spec-k 4` on any
`tools/serving_trace.py` run.

**Fault posture.** Refcounted pages keep the shed contract exact: an
aborted sequence drops only its OWN refs — shared pages survive for
their siblings, and the pager's `check_invariants()` machine-checks
refcount conservation (no free-while-referenced, no leak) after
every transition. Drill it:

    python tools/chaos.py --plan serving-crash

runs the gateway with CoW sharing + spec decode live, faults a step
mid-trace, and asserts page conservation plus a dense-identical
post-fault shared wave.
"""

# hand-maintained operations doc, re-emitted on every regeneration
# (ISSUE 18 satellite: the serving-fleet autoscaling runbook lives in
# docs/OPS.md between the serving runbook and the elastic-fleet
# machinery it composes)
SERVING_FLEET_OPS_SECTION = """
## Serving fleet autoscaling (serving/fleet.py)

One gateway is one process; the fleet layer (ARCHITECTURE.md §20)
turns N of them into one elastic service on three already-shipped
planes: PR 6 membership leases, PR 7 fleet telemetry, and the
content-addressed compile store. Nothing here adds a side channel —
the router steers by exactly what replicas publish.

**Bring-up.** Each replica runs startup prefetch BEFORE its first
lease: `ServingReplica.start()` AOT-compiles every `STARTUP_PREFETCH`
bucket (lint rule 12 holds that tuple equal to the scheduler's
`WARMUP_FEEDS` keys, and holds the warmup call ahead of the lease
calls), consults the compile store's manifest for its program
fingerprint, then opens the HTTP front end and renews. `/healthz`
answers 503 `warming` until the gateway is warm — a cold replica is
never routable. Point every replica and the router at the same
shared directory; set `DL4J_TPU_COMPILE_STORE` to the fleet store so
a respawned process deserializes its siblings' compiles (the
`--serving-fleet` drill asserts cold p50 TTFT ≤ 1.2× warm via
`aot_hits` and persistent-cache counters).

**Routing.** `ServingRouter.submit` places each request on the
least-loaded live+ready replica (published queue depth + active
slots + the router's own in-flight count); transport failures
re-route; an impossible placement is shed as a structured
`SequenceAborted` bounded by `DL4J_TPU_FLEET_SHED_BUDGET` — never a
hung client. Watch the plane:

    python tools/tpu_watch.py --fleet-dir /shared/fleet

adds replica columns (ready/live, queue depth, KV occupancy, warm
buckets, sheds, lease age) and a NOT_READY alarm; the router's own
exposition carries `dl4j_tpu_router_requests_total` (per replica),
`dl4j_tpu_router_replicas_ready`, `dl4j_tpu_router_reroutes_total`,
and `dl4j_tpu_router_sheds_total` (by reason — `no_replica` means
capacity, `over_budget` means the contract breached, page the
operator). Fleet capacity moves show as
`dl4j_tpu_serving_fleet_spawns_total` /
`dl4j_tpu_serving_fleet_evictions_total`, per-replica warmth as
`dl4j_tpu_serving_fleet_warm_buckets` and
`dl4j_tpu_serving_fleet_replica_ready`.

**Scaling + failure.** `FleetSupervisor.poll()` evicts expired
leases and respawns toward `target` (a spawn stays pending until its
lease appears — no double-spawn). A killed replica disappears from
routing within one lease window; its postmortem bundle lands under
`<fleet>/postmortem/`. Drill the whole contract:

    python tools/chaos.py --serving-fleet

kills one of three replicas mid-trace and asserts detection ≤ one
lease window, zero hung clients, losses ≤ the shed budget (all
structured), store-warmed respawn TTFT, and the epoch flip with the
new replica ready.
"""

# hand-maintained operations doc, re-emitted on every regeneration
# (ISSUE 14 satellite: the Pallas-gap-naming runbook lives in
# docs/OPS.md next to the other runbooks)
DEVTIME_OPS_SECTION = """
## Naming the Pallas gaps (obs/devtime.py)

ARCHITECTURE §4's policy is "Pallas only where XLA has a gap"; the
device-time observatory (ARCHITECTURE.md §16) is the instrument that
names the gaps. Host wall-clock spans cannot attribute
asynchronously-dispatched device time to layers — this pipeline asks
the device itself.

**On demand.** The perf dossier emits the ranked report on every run:

    python tools/perf_dossier.py --smoke --out dossier.json
    # -> the "hot_path_gaps" section

Each entry carries `gap.scope` (the `named_scope`-derived layer /
phase name, or `op:<class>` for unattributed ops), `gap.device_ms` /
`gap.share` (measured device time and its share of the window),
`gap.ops` / `gap.fusions` / `gap.backward_ms`, `gap.flops` /
`gap.bytes` (HLO-derived estimates), `gap.utilization` and
`gap.bound` (achieved-vs-roofline fraction of the binding resource,
peaks from `DL4J_TPU_PEAK_TFLOPS` / `DL4J_TPU_PEAK_HBM_GBS`), and
`gap.pallas_candidate` — true when the scope is ≥5% of the window,
under 35% of roofline, and not already a custom call. Rank by
`gap.share`, filter by `gap.pallas_candidate`: that list IS the
kernel-library backlog, with the evidence attached.

**On cadence.** `DL4J_TPU_DEVTIME=1` installs the fit-loop monitor:
every `DL4J_TPU_DEVTIME_EVERY`-th iteration opens a
`jax.profiler.trace` window for `DL4J_TPU_DEVTIME_STEPS` steps,
attributes it, and publishes `dl4j_tpu_devtime_scope_seconds` /
`dl4j_tpu_devtime_scope_share` / `dl4j_tpu_devtime_scope_utilization`
(per scope, last capture), `dl4j_tpu_devtime_pallas_candidates`, and
the capture-cost meters `dl4j_tpu_devtime_captures_total` /
`dl4j_tpu_devtime_capture_seconds_total` — budget the cadence with
the latter: a capture costs a profiler session plus an xplane parse,
so keep `EVERY` in the hundreds. `tpu_watch --metrics-url` renders
the ranking as the `devtime` view. Unset, the fit loops pay one
branch and run zero profiler sessions (counter-fenced).

**Raw captures.** `tools/xprof_summary.py DIR` summarizes the newest
capture session under DIR, merging every host's `*.xplane.pb`; pass
an explicit `.xplane.pb` file to read one host. Attribution quality:
scopes come from the executed programs' HLO metadata — AOT-warm the
step (`net.warmup(...)`) before capturing, or un-warmed programs fall
back to `op:<class>` buckets.
"""


# hand-maintained operations doc, re-emitted on every regeneration
# (ISSUE 15 satellite: the gap-closing runbook lives in docs/OPS.md
# next to the gap-naming runbook it completes)
FUSED_OPS_SECTION = """
## Closing a named gap (ops/ fused-primitive library)

The §4 policy is "Pallas only where XLA has a gap"; "Naming the
Pallas gaps" (above) produces the candidate list. This runbook is the
other half — turning a named gap into a closed one (ARCHITECTURE §17).

**1. Confirm the gap.** Re-run the dossier and check the scope still
ranks: `python tools/perf_dossier.py --smoke --out d.json`, read
`hot_path_gaps` — you want `gap.share` ≥ 5%, `gap.utilization` < 35%,
`gap.closed_by` null. Scopes already closed are listed under
`closed_gaps` with the kernel that closed them; `open_gaps` is the
remaining backlog.

**2. Write the kernel in `ops/`.** Fwd + bwd Pallas kernels with a
`jax.custom_vjp`, a trace-time dispatch gate (TPU or
`DL4J_TPU_KERNEL_FORCE`), and a fallback that is the EXACT expression
the call site ran before — gate-off programs must stay
byte-identical. `ops/fused_norms.py` is the template: single-pass
forward, recompute-style backward, cross-row parameter grads
accumulated over the sequential grid.

**3. Register it.** Add a `KERNEL_REGISTRY` entry
(`ops/kernel_registry.py`): fallback, parity test reference, the
kernel's own `devtime.scope` name, and `closes` patterns matching the
gap-report scopes it serves. Add the kernel to `SCOPE_SITES`
(`tools/lint_instrumentation.py`). Lint rule 9 fails tier-1 until all
of it lines up — and rejects any `pl.pallas_call` outside `ops/`.

**4. Prove the close.** Parity tests (fwd AND bwd, interpret mode,
run under `DL4J_TPU_KERNEL_FORCE=1`), the gate-off byte-identity
fence, and a before/after dossier row. The next `gap_report()` marks
the scope `gap.closed_by` = your kernel, drops its
`dl4j_tpu_devtime_scope_pallas_candidate` gauge to 0, and the
`fused_kernels` bench section / `fused_epilogues` dossier row carry
the per-kernel parity status from then on.

**Ride-alongs to check.** If the kernel serves the training path,
verify the numerics observatory still attributes (the diagnostic taps
ride the same forward) and the strict-sentry fit fence still passes
(the kernel must not add traced shapes). If it serves decode/serving,
re-run the serving identity fences (paged decode is token-identical
to dense decode by contract).
"""


# hand-maintained operations doc, re-emitted on every regeneration
# (ISSUE 17 satellite: the wire-bound-hunting runbook lives in
# docs/OPS.md next to the gap-naming runbook it extends)
COMM_OPS_SECTION = """
## Hunting wire-bound steps (obs/commtime.py)

"Naming the Pallas gaps" (above) attributes device time to scopes;
this runbook attributes the INTERCONNECT — per-collective wire bytes
and collective device time, joined to the same `dl4j.*` scopes
(ARCHITECTURE.md §19). A scope whose collective time exceeds half its
device time is wire-bound: the link, not a kernel, is the ceiling, so
it is never a Pallas candidate — fix it with overlap, sharding, or
gradient compression instead.

**Static (any box, no capture).** The wire ledger reads compiled HLO:

    python -m tools.collective_volume --markdown

prints per-config collective counts, ring-model wire bytes/step, the
projected ICI time at the `DL4J_TPU_PEAK_ICI_GBS` roofline (default
45 GB/s, the public v5e figure), and the measured-vs-dense column for
the encoded-gradient exchange. In code,
`commtime.wire_ledger(executables)` gives the same account per scope
(`by_scope["zero.reduce_scatter"]`, ...) — anonymous collectives land
in `op:<kind>` buckets, and lint rule 11 keeps the in-repo emitters
scoped so those stay empty.

**On cadence.** `DL4J_TPU_COMMTIME=1` installs the fit-loop monitor
(`DL4J_TPU_COMMTIME_EVERY` / `DL4J_TPU_COMMTIME_STEPS`, same shape as
the devtime monitor): each window publishes
`dl4j_tpu_comm_scope_wire_bytes_per_step`,
`dl4j_tpu_comm_scope_collective_seconds`,
`dl4j_tpu_comm_scope_step_share`,
`dl4j_tpu_comm_scope_link_utilization` (achieved GB/s over the
`DL4J_TPU_PEAK_ICI_GBS` peak), `dl4j_tpu_comm_op_count` per kind,
`dl4j_tpu_comm_wire_bound_scopes`, and the capture meters
`dl4j_tpu_comm_captures_total` /
`dl4j_tpu_comm_capture_seconds_total`. `tpu_watch --comm` renders the
ranking; the fleet snapshot carries it host-labeled for free. Unset,
the fit loops pay one branch and run zero profiler sessions
(counter-fenced).

**Reading the numbers.** On TPU the collective seconds are ICI time
and `link_utilization` is achieved-vs-peak; on CPU/gloo captures they
time host-side copies — the views are marked `estimate_only` and only
the ledger bytes are exact. `gap.bound == "wire"` in the dossier's
`hot_path_gaps` (and `comm_observatory.wire_bound_scopes`) is the
per-scope alarm; `tools/xprof_summary.py DIR --comm` is the offline
twin over a kept capture. Gates: the ZeRO step's ledger must show
reduce-scatter tensor bytes ≈ grad_bytes/N under
`zero.reduce_scatter` and all-gather tensor bytes ≈ param bytes under
`zero.all_gather` (the bench `comm` section asserts both ≈ 1.0).
"""


def main():
    import warnings
    warnings.filterwarnings("ignore")
    import deeplearning4j_tpu.nn.layers  # noqa: F401 (registers layers)
    from deeplearning4j_tpu.autodiff.ops_registry import OPS
    from deeplearning4j_tpu.nn.layers.base import _LAYER_REGISTRY
    from deeplearning4j_tpu.ops import activations, losses
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.nn.constraints import _CONSTRAINTS, _NOISES
    from deeplearning4j_tpu import zoo

    lines = ["# Component inventory (auto-generated)",
             "",
             "Run `python tools/gen_inventory.py` to refresh.",
             ""]

    def section(title, names, per_line=6):
        lines.append(f"## {title} ({len(names)})")
        lines.append("")
        names = sorted(names)
        for i in range(0, len(names), per_line):
            lines.append(", ".join(f"`{n}`"
                                   for n in names[i:i + per_line]) + ",")
        if lines[-1].endswith(","):
            lines[-1] = lines[-1][:-1]
        lines.append("")

    # honesty split: an "alias" is a second name bound to the same
    # implementation object (the reference registry aliases the same
    # way, e.g. multiply/mul) — report base vs alias counts separately
    # so the headline number can't be read as inflated
    seen_impl = {}
    aliases = []
    for name in OPS:
        impl = OPS[name]
        if id(impl) in seen_impl:
            aliases.append(name)
        else:
            seen_impl[id(impl)] = name
    base_ops = [n for n in OPS if n not in set(aliases)]
    lines.append(f"## SameDiff ops ({len(OPS)} registered = "
                 f"{len(base_ops)} base + {len(aliases)} aliases)")
    lines.append("")
    section("Base ops", base_ops)
    section("Aliases (same implementation object as a base op)",
            sorted(aliases))
    section("Layers", list(_LAYER_REGISTRY))
    section("Activations", list(activations._REGISTRY))
    section("Losses", list(losses._REGISTRY))
    def all_subclasses(cls):
        out = []
        for c in cls.__subclasses__():
            out.append(c.__name__)
            out.extend(all_subclasses(c))
        return out

    section("Updaters", all_subclasses(upd.Updater))
    scheds = [c.__name__ for c in upd.Schedule.__subclasses__()]
    section("LR schedules", scheds)
    section("Constraints", list(_CONSTRAINTS))
    section("Weight noise", list(_NOISES))
    import inspect
    zoo_models = [
        n for n in dir(zoo)
        if inspect.isclass(getattr(zoo, n))
        and issubclass(getattr(zoo, n), zoo.ZooModel)
        and getattr(zoo, n) is not zoo.ZooModel]
    zoo_models += [n for n in dir(zoo)
                   if not inspect.isclass(getattr(zoo, n))
                   and callable(getattr(zoo, n)) and n[:1].isupper()
                   and n not in ("DL4JResources",)]
    section("Zoo models", sorted(set(zoo_models)))

    from deeplearning4j_tpu.nn.vertices import _VERTEX_REGISTRY
    section("Graph vertices", list(_VERTEX_REGISTRY))
    from deeplearning4j_tpu.nn.preprocessors import _PREPROC_REGISTRY
    section("Input preprocessors", list(_PREPROC_REGISTRY))
    from deeplearning4j_tpu import clustering as _cl
    section("Clustering / manifold / ANN",
            [n for n in _cl.__all__])
    from deeplearning4j_tpu import nlp as _nlp
    section("NLP", [n for n in _nlp.__all__])
    from deeplearning4j_tpu.train import solver as _sv
    section("Solvers", [c.__name__ for c in
                        _sv.BaseOptimizer.__subclasses__()])
    from deeplearning4j_tpu import eval_ as _ev
    section("Evaluation", [n for n in _ev.__all__])

    out = os.path.join(os.path.dirname(__file__), "..", "docs",
                       "INVENTORY.md")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {os.path.normpath(out)}:")
    for ln in lines:
        if ln.startswith("## "):
            print(" ", ln[3:])

    # ---- per-op API reference (docs/OPS.md) ---------------------------
    # analog of the reference codegen's generated op documentation
    # (contrib/codegen-tools): signature + alias target + OpValidation
    # status per op, straight from the living registry and the
    # coverage-gated validation suite
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tests"))
    from test_op_validation import CASES  # noqa: E402
    alias_of = {n: seen_impl[id(OPS[n])] for n in aliases}
    n_grad = sum(1 for cs in CASES.values()
                 if any(c[2] for c in cs))
    n_gold = sum(1 for cs in CASES.values()
                 if any(c[3] is not None for c in cs))
    op_lines = [
        "# SameDiff op reference (auto-generated)", "",
        "Every op is a pure jax-traceable function in "
        "`autodiff.ops_registry.OPS`, callable eagerly, through "
        "`sd.math.<name>(...)` in a SameDiff graph, or via "
        "`Nd4j.exec`. Signatures below: positional args are arrays, "
        "keyword args are static attributes (reference: iArgs/tArgs/"
        "bArgs of the declarable op).", "",
        "**OpValidation status** (reference "
        "`org.nd4j.autodiff.opvalidation`, coverage-gated by "
        "`tests/test_op_validation.py::test_every_op_has_validation_"
        "case`): every op below has at least one executed forward "
        f"case; {n_grad} are finite-difference gradient-checked "
        f"(`grad`), {n_gold} are compared against numpy goldens "
        "(`golden`). An op with neither marker is forward-validated "
        "only (shape + finiteness).", ""]
    for name in sorted(OPS):
        fn = OPS[name]
        try:
            sig = str(inspect.signature(fn))
        except (ValueError, TypeError):
            sig = "(...)"
        doc = (inspect.getdoc(fn) or "").split("\n")[0].strip()
        entry = f"- **`{name}`**`{sig}`"
        tags = []
        if name in alias_of:
            tags.append(f"alias of `{alias_of[name]}`")
        cs = CASES.get(name, [])
        if any(c[2] for c in cs):
            tags.append("grad")
        if any(c[3] is not None for c in cs):
            tags.append("golden")
        if tags:
            entry += f" [{', '.join(tags)}]"
        if doc and not doc.startswith("lambda"):
            entry += f" — {doc}"
        op_lines.append(entry)
    op_lines += ["", TELEMETRY_OPS_SECTION.strip(),
                 "", RESILIENCE_OPS_SECTION.strip(),
                 "", NUMERICS_OPS_SECTION.strip(),
                 "", ELASTIC_OPS_SECTION.strip(),
                 "", FLEET_OPS_SECTION.strip(),
                 "", SERVING_OPS_SECTION.strip(),
                 "", SPEC_DECODE_OPS_SECTION.strip(),
                 "", SERVING_FLEET_OPS_SECTION.strip(),
                 "", DEVTIME_OPS_SECTION.strip(),
                 "", FUSED_OPS_SECTION.strip(),
                 "", COMM_OPS_SECTION.strip()]
    ops_out = os.path.join(os.path.dirname(out), "OPS.md")
    with open(ops_out, "w") as f:
        f.write("\n".join(op_lines) + "\n")
    print(f"wrote {os.path.normpath(ops_out)} ({len(OPS)} ops, "
          f"{n_grad} gradchecked, {n_gold} golden-checked)")


if __name__ == "__main__":
    main()
