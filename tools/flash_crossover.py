"""Measure the einsum-vs-flash crossover for scaled_dot_attention.

Grounds DL4J_TPU_FLASH_MIN_T (the platform-helper dispatch threshold,
``nn.layers.attention._use_flash``) in measurement instead of folklore
(VERDICT r3 Next #6): times one fwd+bwd attention step through BOTH
paths at a sweep of sequence lengths on the real chip and prints the
per-T ratio plus the smallest T where the kernel wins.

    python tools/flash_crossover.py [--heads 8] [--dim 64] [--batch 4]

Timing protocol per BASELINE.md: compile first, then median of 5,
synced via a scalar device->host transfer (block_until_ready does not
block through the axon tunnel).
"""
from __future__ import annotations

import argparse
import sys
import time

from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--lens", type=int, nargs="*",
                    default=[256, 512, 1024, 2048, 4096])
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.pallas_kernels import flash_attention

    assert jax.default_backend() == "tpu", \
        "crossover must be measured on the real chip"

    def dense(q, k, v):
        d = q.shape[-1]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
            jnp.asarray(d, q.dtype))
        return jnp.einsum("bhqk,bkhd->bqhd",
                          jax.nn.softmax(s, axis=-1), v)

    # one host↔device sync costs ~100-150 ms through the axon tunnel —
    # far more than a single attention step. Chain dependent steps
    # inside one jit (device-side fori_loop), time a REPS-length and a
    # 3·REPS-length chain, and DIFFERENCE them: the constant
    # sync/dispatch floor cancels exactly, leaving the pure per-step
    # device time (round-5 protocol, same as perf_dossier._timeit).
    REPS = 50

    def timed(fn, x):
        import jax.lax as lax

        grad1 = jax.grad(
            lambda x: jnp.sum(fn(x, x, x).astype(jnp.float32)))

        def chain(n):
            return jax.jit(lambda x: lax.fori_loop(
                0, n, lambda i, xx: grad1(xx).astype(x.dtype), x))

        lo, hi = chain(REPS), chain(3 * REPS)
        float(lo(x).sum())                        # compile + sync
        float(hi(x).sum())
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(lo(x).sum())
            t1 = time.perf_counter()
            float(hi(x).sum())
            ts.append(((time.perf_counter() - t1), (t1 - t0)))
        dt = sorted(hi_t - lo_t for hi_t, lo_t in ts)[2]
        if dt <= 0:
            # RTT-spike guard: fall back to the raw long-chain rate
            dt = sorted(hi_t for hi_t, _ in ts)[2] * 2 / 3
        return dt / (2 * REPS)

    key = jax.random.PRNGKey(0)
    print("| T | einsum ms | flash ms | flash/einsum |")
    print("|---|---|---|---|")
    crossover = None
    for t in args.lens:
        x = jax.random.normal(
            key, (args.batch, t, args.heads, args.dim), jnp.bfloat16)
        te = timed(dense, x)
        tf = timed(lambda q, k, v: flash_attention(q, k, v), x)
        # ≥5% win, else it's timing noise; once crossed, stays crossed
        if crossover is None and tf < 0.95 * te:
            crossover = t
        print(f"| {t} | {te * 1e3:.2f} | {tf * 1e3:.2f} "
              f"| {tf / te:.2f} |")
    print(f"# flash wins (>5%) from T={crossover} "
          f"(set DL4J_TPU_FLASH_MIN_T accordingly; masked/long-context "
          f"workloads may prefer it lower — the einsum path "
          f"materialises [T,T] scores)"
          if crossover else "# einsum won at every measured T")


if __name__ == "__main__":
    main()
