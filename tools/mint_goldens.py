"""Mint the checked-in pretrained goldens under resources/pretrained.

Reference analog: the weights dl4j hosts on dl4jResources; here the
artifacts are *tiny* variants (small input shapes / vocab) trained
briefly on deterministic synthetic tasks, so the repository stays
small while the full export→checksum→restore→forward contract is
exercised.  Each model directory also carries ``golden_io.npz``
(input, expected output) so restores can be verified bit-for-bit
against the forward pass that minted them.

Run: ``python tools/mint_goldens.py`` (idempotent; rewrites goldens).
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu.data import DataSet, ListDataSetIterator  # noqa: E402
from deeplearning4j_tpu.zoo import (CausalTransformerLM,  # noqa: E402
                                    LeNet, SimpleCNN,
                                    TextGenerationLSTM)
from deeplearning4j_tpu.zoo.pretrained import export_pretrained  # noqa: E402

BASE = Path(__file__).resolve().parents[1] / "resources" / "pretrained"


def _train_briefly(net, x, y, epochs=3, batch=16):
    it = ListDataSetIterator(DataSet(x, y), batch_size=batch)
    for _ in range(epochs):
        net.fit(it)
    return net


def _synthetic_images(rng, n, h, w, c, classes):
    y_idx = rng.integers(0, classes, n)
    x = rng.normal(size=(n, h, w, c)).astype(np.float32) * 0.3
    # class-dependent mean so the task is learnable
    x += (y_idx[:, None, None, None] / classes).astype(np.float32)
    return x, np.eye(classes, dtype=np.float32)[y_idx]


def mint(model_cls, net, x, base=BASE, dataset="default"):
    art = export_pretrained(net, model_cls.model_name(), dataset, base)
    out = np.asarray(net.output(x[:4]))
    np.savez_compressed(art.parent / f"{dataset}_golden_io.npz",
                        x=x[:4], y=out)
    print(f"minted {art} ({art.stat().st_size/1e3:.0f} kB), "
          f"golden out mean {out.mean():.4f}")


def main():
    rng = np.random.default_rng(20260730)

    # LeNet on a 14x14 synthetic digit task (tiny flagship variant)
    x, y = _synthetic_images(rng, 128, 14, 14, 1, 10)
    lenet = LeNet(num_classes=10, seed=7, input_shape=(14, 14, 1)).init()
    mint(LeNet, _train_briefly(lenet, x, y), x)

    # SimpleCNN tiny variant (16x16x3, 4 classes) to keep the golden
    # small; the reference default input is 48x48x3
    x, y = _synthetic_images(rng, 64, 16, 16, 3, 4)
    scnn = SimpleCNN(num_classes=4, seed=7, input_shape=(16, 16, 3)).init()
    mint(SimpleCNN, _train_briefly(scnn, x, y), x)

    # TextGenerationLSTM with a tiny vocabulary
    vocab, t, n = 12, 20, 64
    ids = rng.integers(0, vocab, (n, t + 1))
    xs = np.eye(vocab, dtype=np.float32)[ids[:, :-1]]      # [N,T,V]
    ys = np.eye(vocab, dtype=np.float32)[ids[:, 1:]]
    lstm = TextGenerationLSTM(vocab_size=vocab, seed=7, hidden=16,
                              layers=1, tbptt=10).init()
    mint(TextGenerationLSTM, _train_briefly(lstm, xs, ys), xs)

    # CausalTransformerLM nano variant (decoder-only LM family)
    model = CausalTransformerLM(vocab_size=16, hidden=32, n_layers=2,
                                n_heads=4, n_kv_heads=2, max_len=32,
                                seed=7)
    net = model.init(seq_len=12)
    tokens = np.arange(13) % 5 + 1
    lx = np.tile(tokens[:12], (8, 1)).astype(np.int32)
    ly = np.tile(tokens[1:13], (8, 1)).astype(np.int32)
    mint(CausalTransformerLM,
         _train_briefly(net, lx, ly, epochs=20, batch=8), lx)


if __name__ == "__main__":
    main()
