"""Chaos harness — run training/serving under a named fault plan and
assert it converges to the fault-free baseline.

The resilience subsystem's claim is "robust by construction, verified
by injected faults" (ARCHITECTURE.md §10); this tool IS the
verification loop, runnable from a shell and wired into tier-1 by
``tests/test_chaos_smoke.py``:

    python tools/chaos.py --plan ckpt-io-flake
    python tools/chaos.py --plan worker-crash --plan etl-flake
    python tools/chaos.py --plan serving-crash
    python tools/chaos.py --plan "ckpt_write:error=OSError:nth=1" --example lenet_mnist
    python tools/chaos.py --list

Default (builtin scenario): train one seeded MLP twice — uninterrupted
baseline, then a fresh identical net under the fault plan with
``FaultTolerantTrainer`` absorbing the injected failures — and assert
the chaotic run's final params/loss match the baseline (exact-resume
property: restore + mid-epoch skip + per-iteration rng folds replay
the same trajectory). Serving plans flood a ``ParallelInference``
queue instead and assert requests shed (fast errors) rather than
block, with the worker surviving its injected crash.

``--example NAME`` runs ``examples/NAME.py`` as a subprocess with the
plan in ``DL4J_TPU_FAULT_PLAN`` under a restart supervisor (the
slice-restart idiom: a crashed process is simply re-run, max
``--restarts`` times) and asserts eventual completion.

Exit status 0 = all assertions held; JSON report on stdout.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import textwrap
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

# sitecustomize routes to the axon TPU tunnel; chaos scenarios are
# tiny — keep them on CPU unless explicitly opted in
if os.environ.get("DL4J_TPU_EXAMPLE_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _build_net(seed=11):
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(upd.Adam(learning_rate=5e-3)).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


def _data(n=96, seed=5):
    from deeplearning4j_tpu.data.dataset import DataSet
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return DataSet(x, y)


def _train_scenario(plan_name: str, epochs: int, tol: float) -> dict:
    """Baseline vs chaotic FaultTolerantTrainer run; convergence-to-
    baseline means the recovered trajectory reproduces the
    uninterrupted one (params within ``tol``)."""
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.resilience import faults
    from deeplearning4j_tpu.train.fault_tolerance import (
        FaultTolerantTrainer)
    from deeplearning4j_tpu.obs import metrics

    ds = _data()
    it = ListDataSetIterator([b for b in ds.batch_by(24)], batch_size=24)

    base = _build_net()
    base.fit(it, epochs=epochs)
    base_loss = float(base.score(ds))

    chaotic = _build_net()
    preempted = False
    with tempfile.TemporaryDirectory(prefix="chaos_ckpt_") as d:
        trainer = FaultTolerantTrainer(chaotic, d,
                                       save_every_n_iterations=2,
                                       max_restarts=8)
        t0 = time.perf_counter()
        with faults.active(plan_name):
            trainer.fit(it, epochs=epochs)
            fired = sum(s["fires"] for s in faults.stats().values())
        if trainer.preempted:
            # the preempt plan stops the "job" cleanly mid-run; model
            # the slice restart: a fresh process resumes from the
            # checkpoint dir and finishes the epoch budget
            preempted = True
            from deeplearning4j_tpu.train.fault_tolerance import \
                resume_or_init
            chaotic = resume_or_init(_build_net, d)
            FaultTolerantTrainer(
                chaotic, d, save_every_n_iterations=2,
                max_restarts=8).fit(it, epochs=epochs - chaotic.epoch)
        wall = time.perf_counter() - t0
    chaos_loss = float(chaotic.score(ds))
    max_dp = max(float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
                 for a, b in zip(jax.tree.leaves(base.params),
                                 jax.tree.leaves(chaotic.params)))
    quarantined = metrics.CKPT_QUARANTINED._children[()].get()
    ok = (fired > 0 and np.isfinite(chaos_loss)
          and abs(chaos_loss - base_loss) <= tol)
    return {"mode": "train", "plan": plan_name,
            "faults_fired": fired, "restarts": trainer.restarts,
            "preempted": preempted,
            "baseline_loss": round(base_loss, 6),
            "chaos_loss": round(chaos_loss, 6),
            "max_param_delta": max_dp,
            "exact_resume": max_dp < 1e-5,
            "quarantined": quarantined,
            "wall_s": round(wall, 2), "ok": bool(ok)}


def _serving_scenario(plan_name: str) -> dict:
    """Flood a bounded serving queue under the plan: requests must shed
    (fast QueueFullError) or complete — never block — and the dispatch
    worker must survive its injected crash."""
    from deeplearning4j_tpu.parallel.inference import (
        ParallelInference, QueueFullError)
    from deeplearning4j_tpu.resilience import faults
    from deeplearning4j_tpu.obs import metrics

    net = _build_net()
    pi = ParallelInference(net, batch_limit=8, queue_limit=8,
                           buckets=(1, 2, 4, 8))
    x = np.random.RandomState(0).randn(64, 8).astype(np.float32)
    shed, failed, okc = 0, 0, 0
    t0 = time.perf_counter()
    with faults.active(plan_name):
        # phase 1 — overload burst: the bounded queue must shed (fast
        # QueueFullError) instead of blocking the submitter
        burst = []
        for i in range(32):
            try:
                burst.append(pi.output_async(x[i], deadline_s=10.0))
            except QueueFullError:
                shed += 1
        # phase 2 — paced waves (submit, then gather, so the worker
        # forms several batches): the injected crash takes one whole
        # batch (those requests get the error immediately), later
        # waves are served by the SAME worker thread — it recovered,
        # not died
        for ob in burst:
            try:
                ob.get(timeout=10.0)
                okc += 1
            except Exception:
                failed += 1
        for _ in range(4):
            wave = [pi.output_async(x[j], deadline_s=10.0)
                    for j in range(4)]
            for ob in wave:
                try:
                    ob.get(timeout=10.0)
                    okc += 1
                except Exception:
                    failed += 1
        fired = sum(s["fires"] for s in faults.stats().values())
    # the worker survived the injected batch failure: a fresh request
    # still round-trips
    post = np.asarray(pi.output(x[0], timeout=10.0))
    pi.shutdown()
    wall = time.perf_counter() - t0
    total = 32 + 4 * 4
    shed_total = sum(
        c.get() for c in metrics.REQS_SHED._children.values())
    ok = (fired > 0 and okc > 0 and failed > 0 and shed > 0
          and post.shape[-1] == 3 and okc + failed + shed == total
          and wall < 30.0)
    return {"mode": "serving", "plan": plan_name, "requests": total,
            "completed": okc, "errored_by_fault": failed,
            "shed_at_enqueue": shed, "shed_metric_total": shed_total,
            "faults_fired": fired, "worker_survived": True,
            "wall_s": round(wall, 2), "ok": bool(ok)}


def _gateway_scenario(plan_name: str) -> dict:
    """Continuous-batching gateway under an injected serving fault
    (ISSUE 13 satellite): the fault takes one decode iteration
    mid-trace — every in-flight sequence must shed with a structured
    ``SequenceAborted`` (tokens-so-far attached) or complete, the
    paged pool must come back whole (no leaked page, invariants
    clean), and the SAME worker must serve a post-fault wave — never
    a wedged slot. The drill runs under an obs trace so the Chrome
    JSONL carries the REQUEST-SCOPED spans (submit → admit → prefill
    → decode-steps → retire/abort, async tracks keyed by request id)
    — asserted here: every submitted request must leave a terminal
    ``serving.request`` span, aborts included."""
    import tempfile

    from deeplearning4j_tpu.obs import metrics, trace as obs_trace
    from deeplearning4j_tpu.resilience import faults
    from deeplearning4j_tpu.serving import SequenceAborted, ServingGateway
    from deeplearning4j_tpu.zoo import GPTNano

    model = GPTNano(vocab_size=64, max_len=64, seed=7)
    net = model.init()
    gw = ServingGateway(model, net, max_slots=4, block=8,
                        max_context=64, queue_limit=32,
                        default_max_new=24)
    gw.warmup(prompt_lens=(6,))
    rng = np.random.RandomState(0)
    prompts = rng.randint(0, 64, (8, 6)).astype(np.int32)
    completed, aborted, tokens_salvaged = 0, 0, 0
    # reuse a live user trace (enable() would close and redirect it);
    # otherwise trace into a drill-local file and tear down after
    trace_was_on = obs_trace.enabled() and obs_trace.trace_path()
    if trace_was_on:
        trace_path = obs_trace.trace_path()
        started_trace = False
    else:
        trace_path = tempfile.mktemp(prefix="dl4j_gateway_drill_",
                                     suffix=".jsonl")
        obs_trace.enable(trace_path)
        started_trace = True
    t0 = time.perf_counter()
    try:
        with faults.active(plan_name):
            wave = [gw.submit(p) for p in prompts]
            for ob in wave:
                try:
                    ob.result(timeout=60)
                    completed += 1
                except SequenceAborted as e:
                    aborted += 1
                    tokens_salvaged += len(e.tokens)
            fired = sum(s["fires"] for s in faults.stats().values())
        # the worker survived: a post-fault wave round-trips on the
        # same gateway, and the pool is conserved
        post = [gw.submit(p, max_new=8) for p in prompts[:3]]
        post_ok = sum(ob.result(timeout=60).shape == (14,)
                      for ob in post)
    finally:
        obs_trace.flush()
        if started_trace:
            obs_trace.disable()
    gw._sched.pager.check_invariants()
    pages_whole = (gw._sched.pager.free_pages()
                   == gw._sched.pager.n_pages - 1)
    shed_fault = metrics.SERVING_SHED.labels(reason="fault").get()
    gw.shutdown()
    wall = time.perf_counter() - t0
    # request-scoped span fence: 11 submits -> 11 terminal request
    # tracks (retired or aborted), nested decode phases present (>=
    # when riding a pre-existing user trace with earlier traffic)
    evs = obs_trace.read_trace(trace_path)
    req_begins = [e for e in evs if e.get("ph") == "b"
                  and e.get("name") == "serving.request"]
    phases = {e.get("name") for e in evs
              if e.get("ph") in ("b", "i")
              and str(e.get("name", "")).startswith("serving.request")}
    outcomes = [e["args"].get("outcome") for e in req_begins
                if "args" in e]
    spans_ok = (len(req_begins) >= 11
                and {"serving.request", "serving.request/submit",
                     "serving.request/queue_wait",
                     "serving.request/prefill",
                     "serving.request/decode_steps"} <= phases
                and any(o.startswith("aborted") for o in outcomes)
                and any(o == "retired" for o in outcomes))
    ok = (fired > 0 and aborted > 0 and completed + aborted == 8
          and tokens_salvaged > 0 and post_ok == 3 and pages_whole
          and spans_ok and wall < 60.0)
    return {"mode": "serving-gateway", "plan": plan_name,
            "requests": 8, "completed": completed, "aborted": aborted,
            "tokens_salvaged": tokens_salvaged,
            "post_fault_completed": post_ok,
            "pages_conserved": pages_whole,
            "shed_fault_metric": shed_fault, "faults_fired": fired,
            "worker_survived": True,
            "request_spans": len(req_begins),
            "request_span_phases": sorted(phases),
            "trace_jsonl": trace_path,
            "wall_s": round(wall, 2), "ok": bool(ok)}


def _gateway_cow_scenario(plan_name: str) -> dict:
    """Gateway drill under copy-on-write prefix sharing + speculative
    decode (ISSUE 16 satellite): a serving-site fault takes a decode
    iteration while sibling sequences share refcounted pages. The
    fence: aborted sequences release only their OWN refs (the donor
    retiring early must not free pages its siblings still read, and a
    mid-flight shed must not leak or double-free a shared page), the
    pool comes back conserved with invariants clean, and the same
    worker then serves a fresh shared wave whose outputs match the
    dense ``generate()`` token-for-token."""
    from deeplearning4j_tpu.obs import metrics
    from deeplearning4j_tpu.resilience import faults
    from deeplearning4j_tpu.serving import SequenceAborted, ServingGateway
    from deeplearning4j_tpu.zoo import GPTNano

    model = GPTNano(vocab_size=64, max_len=64, seed=7)
    net = model.init()
    gw = ServingGateway(model, net, max_slots=4, block=8,
                        max_context=64, queue_limit=32,
                        default_max_new=24, spec_k=2,
                        prefix_sharing=True)
    # every prompt in the drill is the 12-token base (bucket 16) and
    # the suffix warmup closes downward on its own — more admit
    # buckets would only add fresh-model compile time to the smoke
    gw.warmup(prompt_lens=(12,))
    rng = np.random.RandomState(3)
    base = rng.randint(0, 64, 12).astype(np.int32)
    hits0 = metrics.SERVING_PREFIX_HITS.snapshot().get("", 0)
    cow0 = metrics.SERVING_PREFIX_COW.snapshot().get("", 0)
    completed = aborted = 0
    t0 = time.perf_counter()
    with faults.active(plan_name):
        # park the worker so the whole wave admits in ONE sweep: the
        # donor registers the prefix chain and every sibling adopts
        # its pages (tail CoW) before the first — faultable — step
        gw.pause()
        wave = [gw.submit(base, max_new=2)]          # donor: retires
        wave += [gw.submit(base, max_new=24)          # early, sharers
                 for _ in range(3)]                   # decode on
        gw.resume()
        for ob in wave:
            try:
                ob.result(timeout=60)
                completed += 1
            except SequenceAborted:
                aborted += 1
        fired = sum(s["fires"] for s in faults.stats().values())
    gw._sched.pager.check_invariants()
    pages_whole = (gw._sched.pager.free_pages()
                   == gw._sched.pager.n_pages - 1)
    # post-fault: same worker, fresh shared wave, dense-identical out
    dense = np.asarray(model.generate(net, base[None], n_new=8))[0]
    gw.pause()
    post = [gw.submit(base, max_new=8) for _ in range(3)]
    gw.resume()
    post_ok = sum(
        bool(np.array_equal(np.asarray(ob.result(timeout=60)), dense))
        for ob in post)
    gw._sched.pager.check_invariants()
    pages_whole &= (gw._sched.pager.free_pages()
                    == gw._sched.pager.n_pages - 1)
    hits = metrics.SERVING_PREFIX_HITS.snapshot().get("", 0) - hits0
    cows = metrics.SERVING_PREFIX_COW.snapshot().get("", 0) - cow0
    gw.shutdown()
    wall = time.perf_counter() - t0
    # 3 wave siblings + >=2 post siblings adopt the donor chain; each
    # whole-prompt adoption clones the tail page before writing it
    ok = (fired > 0 and aborted > 0 and completed + aborted == 4
          and post_ok == 3 and pages_whole and hits >= 5
          and cows >= 3 and wall < 60.0)
    return {"mode": "serving-gateway-cow", "plan": plan_name,
            "requests": 4, "completed": completed, "aborted": aborted,
            "post_fault_dense_identical": post_ok,
            "pages_conserved": pages_whole,
            "prefix_hits": int(hits), "cow_copies": int(cows),
            "faults_fired": fired, "worker_survived": True,
            "wall_s": round(wall, 2), "ok": bool(ok)}


# ---------------------------------------------------------------------------
# elastic multi-host drill (resilience/elastic.py on tests/mp_harness.py)
# ---------------------------------------------------------------------------

# One elastic host: join the fleet, form the mesh at the agreed world
# size, reshard-restore the newest valid sharded checkpoint, train
# under bounded-timeout collectives, re-form by exec on peer death.
# The victim host (PROC_ID == KILL_HOST) SIGKILLs itself at iteration
# KILL_AT — a real kill -9 mid-epoch, deterministic where a wall-clock
# kill is not (the parent's mp_harness kill_after stays armed as the
# backstop for a pre-step wedge).
ELASTIC_WORKER = textwrap.dedent("""
    import os, signal, sys, warnings
    sys.path.insert(0, %(repo)r)
    warnings.filterwarnings("ignore")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import hashlib
    import numpy as np

    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.resilience import elastic

    host = "h%%s" %% os.environ["PROC_ID"]
    EPOCHS = int(os.environ["EPOCHS"])
    LEASE = float(os.environ["LEASE_S"])
    BASELINE_STEP = int(os.environ.get("BASELINE_STEP", "0"))
    SAVE_EVERY = int(os.environ.get("SAVE_EVERY", "2"))
    KILL_AT = int(os.environ.get("KILL_AT", "0"))
    victim = os.environ.get("KILL_HOST", "") == os.environ["PROC_ID"]

    def factory():
        conf = (NeuralNetConfiguration.builder().seed(23)
                .updater(upd.Adam(learning_rate=2e-3)).list()
                .layer(DenseLayer(n_out=18, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(10)).build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(5)          # same data on every host
    x = rng.standard_normal((32, 10)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
    it = ListDataSetIterator(DataSet(x, y), batch_size=8)  # 4/epoch

    co = elastic.MembershipCoordinator(
        os.environ["ELASTIC_DIR"], host, lease_secs=LEASE,
        port_base=int(os.environ["PORT_BASE"]))
    tr = elastic.ElasticTrainer(
        factory, os.environ["CKPT_DIR"], coordinator=co,
        save_every=SAVE_EVERY, keep_last=50)
    wrapper, rec = tr.bring_up(expected=int(os.environ["NPROC"]))
    net = tr.net
    print("%%s WORLD=%%d EPOCH=%%d DEV=%%d" %% (
        host, len(rec["members"]), rec["epoch"],
        len(jax.devices())), flush=True)
    if tr.resumed_step is not None:
        print("%%s RESUMED step=%%d" %% (host, tr.resumed_step),
              flush=True)
    if BASELINE_STEP:
        # same-scale uninterrupted baseline: pin the restore to the
        # exact step the survivors resumed from
        tr._ck.restore_wrapper(wrapper, step=BASELINE_STEP)
        print("%%s PINNED step=%%d" %% (host, BASELINE_STEP),
              flush=True)

    if victim and KILL_AT:
        class Killer:
            def iteration_done(self, _net, iteration, _epoch):
                if iteration >= KILL_AT:
                    print("%%s SELF-SIGKILL at iter %%d" %% (
                        host, iteration), flush=True)
                    os.kill(os.getpid(), signal.SIGKILL)
            def on_epoch_start(self, _net):
                pass
            def on_epoch_end(self, _net):
                pass
        net.listeners.append(Killer())

    status = tr.fit(it, epochs=EPOCHS)       # execs on peer death
    digest = hashlib.sha1()
    for leaf in jax.tree_util.tree_leaves(net.params):
        digest.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    print("%%s FINAL status=%%s iter=%%d epoch=%%d loss=%%.6f "
          "checksum=%%s" %% (host, status, net.iteration, net.epoch,
                             net.score_, digest.hexdigest()),
          flush=True)
    from deeplearning4j_tpu.obs import metrics as M
    for line in M.exposition().splitlines():
        if line.startswith(("dl4j_tpu_mesh_epoch",
                            "dl4j_tpu_hosts_evicted_total",
                            "dl4j_tpu_resilience_restarts_total",
                            "dl4j_tpu_preemptions_total")):
            print("%%s METRIC %%s" %% (host, line), flush=True)
    print("proc %%s DONE" %% os.environ["PROC_ID"], flush=True)
    # skip the interpreter's atexit distributed-shutdown barrier: a
    # host that departs (preempted) or finishes while a peer is dead
    # would wedge or abort inside it — the work is done, leave hard
    sys.stdout.flush()
    os._exit(0)
""")


def _elastic_scenario(hosts: int = 3, kill_host: int = 2,
                      kill_at_iter: int = 9, epochs: int = 8,
                      lease_s: float = 3.0, port: int = 0) -> dict:
    """The multi-host chaos drill (acceptance fence of ISSUE 7):
    SIGKILL one host of an ``hosts``-process fleet mid-epoch, assert
    the survivors (a) raise out of the dead collective within the
    lease window, (b) re-form the mesh at the reduced world size with
    a bumped mesh epoch, (c) reshard-restore the newest valid sharded
    checkpoint, and (d) reach a final state bit-identical to a
    same-scale uninterrupted baseline resumed from the same step.
    (Graceful SIGTERM departure is the sibling drill,
    :func:`_elastic_preempt_scenario`.)"""
    import re
    import tempfile

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tests"))
    from mp_harness import run_workers

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = port or 30200 + (os.getpid() % 300)
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="chaos_elastic_") as d:
        script = os.path.join(d, "elastic_worker.py")
        with open(script, "w") as f:
            f.write(ELASTIC_WORKER % {"repo": repo})
        ckdir = os.path.join(d, "ckpt")
        env = {"ELASTIC_DIR": os.path.join(d, "elastic"),
               "CKPT_DIR": ckdir, "EPOCHS": str(epochs),
               "LEASE_S": str(lease_s), "PORT_BASE": str(port + 50),
               "KILL_HOST": str(kill_host), "SAVE_EVERY": "2",
               "KILL_AT": str(kill_at_iter),
               # fleet plane at a fast cadence so the victim's final
               # telemetry is fresh when the leader snapshots it
               "DL4J_TPU_FLEET_PUBLISH_SECS": "0.05"}
        # mp_harness kill_after is the BACKSTOP (a host wedged before
        # its self-kill iteration still dies); the deterministic kill
        # is the victim's in-worker SIGKILL at iteration KILL_AT
        procs, outs = run_workers(
            script, port, n=hosts, timeout=420,
            kill_after={kill_host: 90.0},
            extra_env=env)

        survivors = [i for i in range(hosts) if i != kill_host]
        victim_rc = procs[kill_host].returncode
        ok = victim_rc == -9        # a real SIGKILL took the host
        finals = {}
        resumed = None
        detect_s = None
        mesh_epoch = None
        world = None
        evicted = 0
        restarts = 0
        for i in survivors:
            out = outs[i]
            ok = ok and procs[i].returncode == 0 and \
                f"proc {i} DONE" in out
            m = re.findall(r"WORLD=(\d+) EPOCH=(\d+)", out)
            if m:
                world, mesh_epoch = int(m[-1][0]), int(m[-1][1])
            r = re.search(r"RESUMED step=(\d+)", out)
            if r:
                resumed = int(r.group(1))
            dm = re.search(r"ELASTIC_REFORM .*detect_s=([\d.]+)", out)
            if dm:
                detect_s = float(dm.group(1))
            fm = re.search(r"FINAL .*checksum=([0-9a-f]+)", out)
            if fm:
                finals[i] = fm.group(1)
            em = re.search(
                r"dl4j_tpu_hosts_evicted_total (\d+)", out)
            if em:
                evicted = max(evicted, int(em.group(1)))
            rm = re.search(
                r"dl4j_tpu_resilience_restarts_total (\d+)", out)
            if rm:
                restarts = max(restarts, int(rm.group(1)))
        ok = (ok and len(finals) == len(survivors)
              and len(set(finals.values())) == 1
              and resumed is not None and resumed > 0
              and world == hosts - 1 and mesh_epoch == 2
              and detect_s is not None and detect_s <= 4 * lease_s
              and evicted >= 1 and restarts >= 1)

        # fleet observability plane (obs/fleet.py): the drill doubles
        # as the acceptance fence for the flight recorder + fleet
        # exposition — (a) a survivor's postmortem bundle must exist
        # whose skew series names the killed host as the final-step
        # straggler, (b) the surviving leader's eviction bundle must
        # carry the corpse's final telemetry (host + last step), and
        # (c) the post-reform fleet exposition must carry
        # mesh_epoch="2" labels
        import glob

        from deeplearning4j_tpu.obs import fleet as obs_fleet
        from deeplearning4j_tpu.obs import metrics as obs_metrics
        victim = f"h{kill_host}"
        pm = sorted(glob.glob(os.path.join(env["ELASTIC_DIR"],
                                           "postmortem", "*.json")))
        straggler_final = None
        survivor_bundles = 0
        evicted_named = False
        dead_last_step = None
        for b in pm:
            try:
                with open(b) as f:
                    rec = json.load(f)
            except ValueError:
                continue
            if rec.get("cause") == "Evicted" and \
                    rec.get("host") == victim:
                evicted_named = True
                dead_last_step = (rec.get("final_telemetry")
                                  or {}).get("step")
                # the ADJUDICATED final-step straggler: the eviction
                # bundle's skew view is computed after the lease
                # verdict, so it names the corpse deterministically
                # (survivor dumps race instant transport errors and
                # are best-effort testimony)
                straggler_final = ((rec.get("fleet") or {})
                                   .get("skew") or {}).get("straggler")
            elif rec.get("host") != victim and \
                    ((rec.get("fleet") or {}).get("skew") or {}
                     ).get("straggler"):
                survivor_bundles += 1
        view = obs_fleet.aggregate(env["ELASTIC_DIR"])
        fams = obs_metrics.parse_exposition(view.exposition())
        expo_epochs = sorted({dict(labels).get("mesh_epoch")
                              for _n, labels in fams
                              if "mesh_epoch" in dict(labels)})
        fleet_epoch2 = "2" in expo_epochs
        ok = (ok and straggler_final == victim and evicted_named
              and survivor_bundles >= 1
              and dead_last_step is not None and dead_last_step > 0
              and fleet_epoch2)

        # same-scale uninterrupted baseline: fresh fleet of the
        # surviving size, pinned to the exact step the survivors
        # resumed from, trained to the same epoch budget — the
        # post-recovery trajectory must match it bit-for-bit
        base_env = dict(env, ELASTIC_DIR=os.path.join(d, "el_base"),
                        BASELINE_STEP=str(resumed or 0),
                        SAVE_EVERY="0", KILL_AT="0", KILL_HOST="")
        base_env["PORT_BASE"] = str(port + 150)
        bprocs, bouts = run_workers(script, port + 100,
                                    n=hosts - 1, timeout=420,
                                    extra_env=base_env)
        base_finals = set()
        for i, out in enumerate(bouts):
            ok = ok and bprocs[i].returncode == 0
            fm = re.search(r"FINAL .*checksum=([0-9a-f]+)", out)
            if fm:
                base_finals.add(fm.group(1))
        trajectory_match = (len(base_finals) == 1 and len(finals) > 0
                            and base_finals == set(finals.values()))
        ok = ok and trajectory_match
        if not ok:                  # post-mortem material
            tails = {f"drill_{i}": (outs[i] or "")[-1500:]
                     for i in range(hosts)}
            tails.update({f"base_{i}": (bouts[i] or "")[-1500:]
                          for i in range(len(bouts))})
            print(json.dumps({"output_tails": tails}, indent=1),
                  file=sys.stderr)
        return {"mode": "elastic", "hosts": hosts,
                "killed": kill_host, "victim_rc": victim_rc,
                "survivor_world": world, "mesh_epoch": mesh_epoch,
                "resumed_step": resumed,
                "detect_s": detect_s, "lease_s": lease_s,
                "hosts_evicted": evicted, "restarts": restarts,
                "trajectory_match": trajectory_match,
                "flight_bundles": len(pm),
                "survivor_bundles": survivor_bundles,
                "straggler_final": straggler_final,
                "evict_bundle_named_dead": evicted_named,
                "dead_last_step": dead_last_step,
                "fleet_mesh_epochs": expo_epochs,
                "fleet_epoch2": fleet_epoch2,
                "wall_s": round(time.perf_counter() - t0, 2),
                "ok": bool(ok)}


def _elastic_preempt_scenario(hosts: int = 2,
                              plan: str = "host-preempt",
                              epochs: int = 8, lease_s: float = 3.0,
                              port: int = 0) -> dict:
    """host-preempt named-plan drill: host ``hosts-1`` trains under
    ``DL4J_TPU_FAULT_PLAN=host-preempt`` (SIGTERM at its nth elastic
    step), departs GRACEFULLY (lease dropped, no checkpoint torn),
    and the survivors re-form and finish."""
    import tempfile

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tests"))
    from mp_harness import run_workers

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = port or 30600 + (os.getpid() % 200)
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="chaos_preempt_") as d:
        script = os.path.join(d, "elastic_worker.py")
        with open(script, "w") as f:
            f.write(ELASTIC_WORKER % {"repo": repo})
        env = {"ELASTIC_DIR": os.path.join(d, "elastic"),
               "CKPT_DIR": os.path.join(d, "ckpt"),
               "EPOCHS": str(epochs), "LEASE_S": str(lease_s),
               "PORT_BASE": str(port + 50), "SAVE_EVERY": "2",
               "KILL_AT": "0", "KILL_HOST": ""}
        procs, outs = run_workers(
            script, port, n=hosts, timeout=420, extra_env=env,
            per_proc_env={hosts - 1: {"DL4J_TPU_FAULT_PLAN": plan}})
        victim_out = outs[hosts - 1] or ""
        ok = (procs[hosts - 1].returncode == 0
              and "status=preempted" in victim_out
              and "fault injection: firing 'sigterm' at site "
                  "'host_death'" in victim_out)
        survivor_done = 0
        for i in range(hosts - 1):
            out = outs[i] or ""
            if procs[i].returncode == 0 and "status=done" in out:
                survivor_done += 1
        ok = ok and survivor_done == hosts - 1
        res = {"mode": "elastic-preempt", "plan": plan,
               "hosts": hosts, "survivors_done": survivor_done,
               "victim_preempted": "status=preempted" in victim_out,
               "wall_s": round(time.perf_counter() - t0, 2),
               "ok": bool(ok)}
        if not ok:                  # post-mortem material
            res["output_tails"] = {
                i: (outs[i] or "")[-1500:] for i in range(hosts)}
        return res


REPLICA_WORKER = textwrap.dedent("""
    import json, os, sys, time, warnings
    sys.path.insert(0, %(repo)r)
    warnings.filterwarnings("ignore")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from deeplearning4j_tpu.obs import fleet as obs_fleet
    from deeplearning4j_tpu.perf import compile_store
    from deeplearning4j_tpu.resilience.elastic import \\
        MembershipCoordinator
    from deeplearning4j_tpu.serving.fleet import ServingReplica
    from deeplearning4j_tpu.serving.gateway import ServingGateway
    from deeplearning4j_tpu.zoo import GPTNano

    host = os.environ["REPLICA_ID"]
    fleet_dir = os.environ["FLEET_DIR"]
    lease = float(os.environ["LEASE_S"])
    reports = os.path.join(fleet_dir, "reports")
    os.makedirs(reports, exist_ok=True)

    model = GPTNano(vocab_size=64, max_len=64, seed=7)
    net = model.init()
    gw = ServingGateway(model, net, max_slots=4, block=8,
                        max_context=64)
    co = MembershipCoordinator(fleet_dir, host, n_devices=1,
                               lease_secs=lease)
    tel = obs_fleet.FleetTelemetry(fleet_dir, host, every_s=0.05)
    rep = ServingReplica(gw, co, tel,
                         store=compile_store.from_env())
    t0 = time.perf_counter()
    report = rep.start(prompt_lens=(8, 16))
    report["warm_s"] = round(time.perf_counter() - t0, 3)
    report["port"] = rep.server.port
    report["cache"] = dict(rep.server.stats().get("cache") or {})
    with open(os.path.join(reports, host + ".json"), "w") as f:
        json.dump(report, f)
    print("REPLICA %%s READY port=%%d warm_s=%%.3f manifest_hit=%%s"
          %% (host, rep.server.port, report["warm_s"],
             report["manifest_hit"]), flush=True)
    stop = os.path.join(fleet_dir, "STOP")
    while not os.path.exists(stop):
        rep.tick()
        time.sleep(lease / 4.0)
    final = rep.server.stats()
    final["epoch"] = tel.mesh_epoch
    with open(os.path.join(reports, host + "_final.json"), "w") as f:
        json.dump(final, f)
    rep.stop()
    print("REPLICA %%s DONE epoch=%%d" %% (host, final["epoch"]),
          flush=True)
    sys.stdout.flush()
    os._exit(0)
""")


def _serving_fleet_scenario(replicas: int = 3, lease_s: float = 2.0,
                            trace_requests: int = 36,
                            threads: int = 6,
                            tenants: int = 4) -> dict:
    """ISSUE 18 acceptance drill (``--serving-fleet``): a 3-replica
    serving fleet under a multi-tenant loadgen trace; one replica is
    killed mid-trace by the ``replica-crash`` plan (``os._exit`` at
    its nth decode step). Asserts (a) the router stops routing to the
    corpse within one lease window, (b) every loss is a structured
    ``SequenceAborted`` bounded by the shed budget — zero hung
    clients, (c) the supervisor respawns a replica whose startup
    prefetch rides the shared compile store (manifest hit +
    persistent-cache hits + AOT hits; p50 TTFT <= 1.2x warm), and
    (d) the post-drill fleet view shows the membership epoch flipped
    with the new replica live+ready."""
    import statistics
    import tempfile
    import threading as _threading
    import urllib.request

    from deeplearning4j_tpu import environment
    from deeplearning4j_tpu.resilience.elastic import \
        MembershipCoordinator
    from deeplearning4j_tpu.serving.fleet import (FleetSupervisor,
                                                  HttpTransport,
                                                  ServingRouter)
    from deeplearning4j_tpu.serving.gateway import SequenceAborted

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    victim = "r1"
    shed_budget = int(
        environment.get_flag("DL4J_TPU_FLEET_SHED_BUDGET"))
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="chaos_fleet_") as d:
        fleet_dir = os.path.join(d, "fleet")
        os.makedirs(fleet_dir)
        script = os.path.join(d, "replica_worker.py")
        with open(script, "w") as f:
            f.write(REPLICA_WORKER % {"repo": repo})
        base_env = dict(os.environ,
                        FLEET_DIR=fleet_dir, LEASE_S=str(lease_s),
                        JAX_PLATFORMS="cpu",
                        DL4J_TPU_COMPILE_STORE=os.path.join(d, "store"),
                        DL4J_TPU_FLEET_PUBLISH_SECS="0.05")
        base_env.pop("DL4J_TPU_FAULT_PLAN", None)
        procs: dict = {}
        logs: dict = {}
        spawn_times: dict = {}
        sup_stop = _threading.Event()

        def spawn(host, plan=None):
            env = dict(base_env, REPLICA_ID=host)
            if plan:
                env["DL4J_TPU_FAULT_PLAN"] = plan
            logs[host] = os.path.join(d, f"{host}.log")
            out = open(logs[host], "w")
            spawn_times[host] = time.perf_counter()
            procs[host] = subprocess.Popen(
                [sys.executable, script], env=env, cwd=repo,
                stdout=out, stderr=subprocess.STDOUT)
            return host

        def tails():
            return {h: open(p).read()[-1500:]
                    for h, p in logs.items() if os.path.exists(p)}

        def fail(why, **extra):
            # tear the fleet down before reporting: the drill never
            # leaks subprocesses, even on a failed assertion path
            sup_stop.set()
            with open(os.path.join(fleet_dir, "STOP"), "w"):
                pass
            for p in list(procs.values()):
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
            out = {"mode": "serving-fleet", "ok": False, "why": why,
                   "wall_s": round(time.perf_counter() - t0, 2),
                   "output_tails": tails()}
            out.update(extra)
            return out

        for i in range(replicas):
            spawn(f"r{i}",
                  plan="replica-crash" if f"r{i}" == victim else None)
        router = ServingRouter(fleet_dir, shed_budget=shed_budget,
                               request_timeout_s=30.0)

        deadline = time.perf_counter() + 300
        while len(router.replicas()) < replicas:
            if time.perf_counter() > deadline:
                return fail("fleet never became ready")
            time.sleep(0.1)

        # stable membership baseline: every replica leased and the
        # epoch committed over the full set before any chaos
        co_sup = MembershipCoordinator(fleet_dir, "supervisor",
                                       n_devices=1,
                                       lease_secs=lease_s)
        all_hosts = sorted(f"r{i}" for i in range(replicas))
        deadline = time.perf_counter() + 120
        epoch0 = None
        while time.perf_counter() < deadline:
            rec = co_sup.epoch_record()
            if rec and sorted(rec.get("members", [])) == all_hosts:
                epoch0 = int(rec["epoch"])
                break
            time.sleep(0.1)
        if epoch0 is None:
            return fail("no committed epoch over the full fleet")

        transport = HttpTransport(timeout_s=30.0)

        def probe_ttfts(host, n=5):
            addr = router.replicas()[host]["addr"]
            vals = []
            for i in range(n):
                out = transport.generate(addr, {
                    "prompt": [1 + i, 2, 3, 4, 5, 6], "max_new": 4,
                    "tenant": "probe", "temperature": None})
                vals.append(float(out["ttft_s"]))
            return vals

        # warm TTFT baseline from the two survivors-to-be (probing
        # the victim would advance its fault counter off-trace)
        warm_ttfts = []
        for h in all_hosts:
            if h != victim:
                warm_ttfts.extend(probe_ttfts(h, n=4))
        warm_p50 = statistics.median(warm_ttfts)

        # capacity supervisor: respawn on eviction, same worker
        # script — its warm path must ride the shared compile store
        next_id = [replicas]

        def _spawn_next():
            host = f"r{next_id[0]}"
            next_id[0] += 1
            return spawn(host)

        sup = FleetSupervisor(co_sup, _spawn_next, target=replicas)

        def _sup_loop():
            while not sup_stop.is_set():
                try:
                    sup.poll()
                except OSError:
                    pass
                sup_stop.wait(0.3)

        sup_thread = _threading.Thread(target=_sup_loop, daemon=True)
        sup_thread.start()

        # the multi-tenant loadgen trace, driven through the router
        rng = np.random.RandomState(17)
        reqs = [{"prompt": rng.randint(0, 64, rng.randint(4, 15)
                                       ).astype(int).tolist(),
                 "max_new": int(rng.randint(6, 11)),
                 "tenant": f"t{i % tenants}"}
                for i in range(trace_requests)]
        results: list = []
        res_lock = _threading.Lock()

        def drive(chunk):
            for r in chunk:
                try:
                    out = router.submit(r["prompt"],
                                        max_new=r["max_new"],
                                        tenant=r["tenant"],
                                        deadline_s=30.0)
                    rec = {"ok": True, "replica": out["replica"],
                           "ttft_s": out["ttft_s"]}
                except SequenceAborted as e:
                    rec = {"ok": False, "aborted": True,
                           "message": str(e)}
                except Exception as e:   # anything else fails the drill
                    rec = {"ok": False, "aborted": False,
                           "error": repr(e)}
                with res_lock:
                    results.append(rec)

        drivers = [_threading.Thread(
            target=drive, args=(reqs[i::threads],), daemon=True)
            for i in range(threads)]
        for th in drivers:
            th.start()

        # the victim self-destructs mid-trace (replica-crash plan);
        # measure how long the router keeps believing in the corpse
        deadline = time.perf_counter() + 120
        while procs[victim].poll() is None:
            if time.perf_counter() > deadline:
                return fail("victim never crashed")
            time.sleep(0.02)
        t_dead = time.perf_counter()
        detect_s = None
        while time.perf_counter() - t_dead < 4 * lease_s:
            if victim not in router.replicas():
                detect_s = time.perf_counter() - t_dead
                break
            time.sleep(0.05)

        for th in drivers:
            th.join(timeout=180)
        hung = sum(1 for th in drivers if th.is_alive())

        # the respawned replica: ready via the compile store
        new_host = f"r{replicas}"
        deadline = time.perf_counter() + 300
        while new_host not in router.replicas():
            if time.perf_counter() > deadline:
                return fail("supervisor never respawned capacity",
                            detect_s=detect_s)
            time.sleep(0.1)
        respawn_ready_s = (time.perf_counter()
                          - spawn_times.get(new_host, t_dead))
        cold_p50 = statistics.median(probe_ttfts(new_host, n=5))
        with open(os.path.join(fleet_dir, "reports",
                               f"{new_host}.json")) as f:
            new_report = json.load(f)
        with urllib.request.urlopen(
                "http://{}/stats".format(
                    router.replicas()[new_host]["addr"]),
                timeout=10) as r:
            new_stats = json.loads(r.read())

        # post-drill fleet view: epoch flipped, new replica live+ready
        survivors = sorted(set(all_hosts) - {victim} | {new_host})
        deadline = time.perf_counter() + 120
        epoch_after = None
        while time.perf_counter() < deadline:
            rec = co_sup.epoch_record()
            if rec and sorted(rec.get("members", [])) == survivors \
                    and int(rec["epoch"]) > epoch0:
                epoch_after = int(rec["epoch"])
                break
            time.sleep(0.1)
        from deeplearning4j_tpu.obs import fleet as obs_fleet
        table = obs_fleet.aggregate(fleet_dir).serving_table()
        new_row = table.get(new_host) or {}

        with open(os.path.join(fleet_dir, "STOP"), "w"):
            pass
        sup_stop.set()
        sup_thread.join(timeout=10)
        clean_exit = True
        for h, p in procs.items():
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
                clean_exit = False
        for h in survivors:
            clean_exit = clean_exit and procs[h].returncode == 0

        victim_log = open(logs[victim]).read()
        completed = sum(1 for r in results if r["ok"])
        aborted = sum(1 for r in results if r.get("aborted"))
        errors = [r for r in results
                  if not r["ok"] and not r.get("aborted")]
        ok = (procs[victim].returncode == 17
              and "fault injection: firing" in victim_log
              and detect_s is not None and detect_s <= lease_s + 1.0
              and hung == 0 and not errors
              and completed + aborted == trace_requests
              and aborted <= shed_budget
              and router.sheds <= shed_budget
              and router.reroutes >= 1
              and new_report.get("manifest_hit") is True
              and int(new_report.get("cache", {})
                      .get("persistent_hits", 0)) > 0
              and int(new_stats.get("aot_hits", 0)) > 0
              and cold_p50 <= 1.2 * warm_p50 + 0.01
              and epoch_after is not None
              and bool(new_row.get("ready"))
              and bool(new_row.get("live"))
              and clean_exit)
        res = {"mode": "serving-fleet", "replicas": replicas,
               "victim": victim,
               "victim_rc": procs[victim].returncode,
               "lease_s": lease_s, "detect_s": detect_s,
               "requests": trace_requests, "completed": completed,
               "aborted": aborted, "hung": hung,
               "router_sheds": router.sheds,
               "router_reroutes": router.reroutes,
               "shed_budget": shed_budget,
               "warm_ttft_p50_s": round(warm_p50, 4),
               "cold_ttft_p50_s": round(cold_p50, 4),
               "respawn_ready_s": round(respawn_ready_s, 2),
               "new_replica": new_host,
               "new_manifest_hit": new_report.get("manifest_hit"),
               "new_persistent_hits": int(
                   new_report.get("cache", {})
                   .get("persistent_hits", 0)),
               "new_aot_hits": int(new_stats.get("aot_hits", 0)),
               "new_warm_s": new_report.get("warm_s"),
               "epoch_before": epoch0, "epoch_after": epoch_after,
               "new_replica_ready": bool(new_row.get("ready")),
               "new_replica_live": bool(new_row.get("live")),
               "clean_exit": clean_exit,
               "wall_s": round(time.perf_counter() - t0, 2),
               "ok": bool(ok)}
        if not ok:                  # post-mortem material
            res["output_tails"] = tails()
            res["errors"] = errors[:5]
        return res


def _example_scenario(example: str, plan: str, restarts: int) -> dict:
    """Slice-restart supervision: run the example under the plan env;
    a crash (injected fault escaping to the top) is answered by simply
    re-running the process — completion within the restart budget is
    the assertion. The plan is injected into the FIRST attempt only
    (a seeded plan would fire identically in every restarted process;
    the model is "the fault happened, the restarted job runs clean" —
    exactly what a transient slice failure looks like)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "examples", f"{example}.py")
    if not os.path.exists(script):
        raise SystemExit(f"no such example: {script}")
    attempts = 0
    rc = None
    fault_fired = False
    t0 = time.perf_counter()
    while attempts <= restarts:
        attempts += 1
        env = dict(os.environ,
                   DL4J_TPU_EXAMPLE_FAST="1",
                   JAX_PLATFORMS="cpu")
        env.pop("DL4J_TPU_FAULT_PLAN", None)
        if attempts == 1:
            env["DL4J_TPU_FAULT_PLAN"] = plan
        r = subprocess.run([sys.executable, script], env=env, cwd=repo,
                           timeout=900, capture_output=True, text=True)
        rc = r.returncode
        sys.stdout.write(r.stdout)
        if attempts == 1 and \
                "fault injection: firing" in (r.stderr + r.stdout):
            fault_fired = True       # the harness logs every fire
        if rc == 0:
            break
    # a drill that never fired its fault proved nothing — pick a plan
    # whose site/nth the example actually reaches (the builtin
    # scenarios assert fires the same way)
    return {"mode": "example", "plan": plan, "example": example,
            "attempts": attempts, "returncode": rc,
            "fault_fired": fault_fired,
            "wall_s": round(time.perf_counter() - t0, 2),
            "ok": rc == 0 and fault_fired}


def main() -> int:
    from deeplearning4j_tpu.resilience.faults import (FaultPlan,
                                                      NAMED_PLANS)
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--plan", action="append", default=[],
                    help="named plan or raw rule spec (repeatable)")
    ap.add_argument("--example", default=None,
                    help="run examples/<NAME>.py under the plan instead "
                         "of the builtin scenario")
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--tol", type=float, default=0.05,
                    help="max |chaos_loss - baseline_loss|")
    ap.add_argument("--restarts", type=int, default=3,
                    help="restart budget for --example supervision")
    ap.add_argument("--elastic", action="store_true",
                    help="multi-host drill: SIGKILL one host of a "
                         "live fleet mid-epoch, assert re-formation + "
                         "resharded restore + baseline-matching "
                         "trajectory (with --plan host-preempt: the "
                         "victim departs via SIGTERM instead)")
    ap.add_argument("--hosts", type=int, default=3,
                    help="fleet size for --elastic")
    ap.add_argument("--serving-fleet", action="store_true",
                    help="elastic serving-fleet drill (ISSUE 18 "
                         "acceptance): 3 leased replicas under a "
                         "multi-tenant trace, one killed mid-trace "
                         "by the replica-crash plan; asserts routing "
                         "detection within one lease window, bounded "
                         "structured sheds, zero hung clients, and a "
                         "compile-store-warm respawn with flipped "
                         "membership epoch")
    ap.add_argument("--list", action="store_true",
                    help="list named plans and exit")
    args = ap.parse_args()
    if args.list:
        for name, spec in NAMED_PLANS.items():
            print(f"{name:<16} {spec}")
        return 0
    if args.serving_fleet:
        results = [_serving_fleet_scenario(replicas=args.hosts)]
        print(json.dumps({"results": results,
                          "ok": all(r["ok"] for r in results)},
                         indent=1))
        return 0 if all(r["ok"] for r in results) else 1
    if args.elastic:
        if args.plan:
            results = [_elastic_preempt_scenario(hosts=args.hosts,
                                                 plan=args.plan[0])]
        else:
            results = [_elastic_scenario(hosts=args.hosts,
                                         kill_host=args.hosts - 1)]
        print(json.dumps({"results": results,
                          "ok": all(r["ok"] for r in results)},
                         indent=1))
        return 0 if all(r["ok"] for r in results) else 1
    if not args.plan:
        ap.error("--plan required (see --list)")

    results = []
    for plan in args.plan:
        parsed = FaultPlan.parse(plan)     # fail fast on bad specs
        if args.example:
            spec = NAMED_PLANS.get(plan, plan)
            results.append(
                _example_scenario(args.example, spec, args.restarts))
        elif any(r.site.startswith("serving") for r in parsed.rules):
            # serving plans drill all three front-end postures: the
            # batched ParallelInference queue, the continuous-batching
            # gateway, and the gateway with CoW prefix sharing +
            # speculative decode live (each parses the plan fresh ->
            # independent rule state, the nth/max counters start over)
            results.append(_serving_scenario(plan))
            results.append(_gateway_scenario(plan))
            results.append(_gateway_cow_scenario(plan))
        elif any(r.site.startswith(("host_death", "coordinator"))
                 for r in parsed.rules):
            results.append(_elastic_preempt_scenario(
                hosts=args.hosts, plan=plan))
        else:
            results.append(_train_scenario(plan, args.epochs, args.tol))
    print(json.dumps({"results": results,
                      "ok": all(r["ok"] for r in results)}, indent=1))
    return 0 if all(r["ok"] for r in results) else 1


if __name__ == "__main__":
    raise SystemExit(main())
