"""Chaos harness — run training/serving under a named fault plan and
assert it converges to the fault-free baseline.

The resilience subsystem's claim is "robust by construction, verified
by injected faults" (ARCHITECTURE.md §10); this tool IS the
verification loop, runnable from a shell and wired into tier-1 by
``tests/test_chaos_smoke.py``:

    python tools/chaos.py --plan ckpt-io-flake
    python tools/chaos.py --plan worker-crash --plan etl-flake
    python tools/chaos.py --plan serving-crash
    python tools/chaos.py --plan "ckpt_write:error=OSError:nth=1" --example lenet_mnist
    python tools/chaos.py --list

Default (builtin scenario): train one seeded MLP twice — uninterrupted
baseline, then a fresh identical net under the fault plan with
``FaultTolerantTrainer`` absorbing the injected failures — and assert
the chaotic run's final params/loss match the baseline (exact-resume
property: restore + mid-epoch skip + per-iteration rng folds replay
the same trajectory). Serving plans flood a ``ParallelInference``
queue instead and assert requests shed (fast errors) rather than
block, with the worker surviving its injected crash.

``--example NAME`` runs ``examples/NAME.py`` as a subprocess with the
plan in ``DL4J_TPU_FAULT_PLAN`` under a restart supervisor (the
slice-restart idiom: a crashed process is simply re-run, max
``--restarts`` times) and asserts eventual completion.

Exit status 0 = all assertions held; JSON report on stdout.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

# sitecustomize routes to the axon TPU tunnel; chaos scenarios are
# tiny — keep them on CPU unless explicitly opted in
if os.environ.get("DL4J_TPU_EXAMPLE_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _build_net(seed=11):
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(upd.Adam(learning_rate=5e-3)).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


def _data(n=96, seed=5):
    from deeplearning4j_tpu.data.dataset import DataSet
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return DataSet(x, y)


def _train_scenario(plan_name: str, epochs: int, tol: float) -> dict:
    """Baseline vs chaotic FaultTolerantTrainer run; convergence-to-
    baseline means the recovered trajectory reproduces the
    uninterrupted one (params within ``tol``)."""
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.resilience import faults
    from deeplearning4j_tpu.train.fault_tolerance import (
        FaultTolerantTrainer)
    from deeplearning4j_tpu.obs import metrics

    ds = _data()
    it = ListDataSetIterator([b for b in ds.batch_by(24)], batch_size=24)

    base = _build_net()
    base.fit(it, epochs=epochs)
    base_loss = float(base.score(ds))

    chaotic = _build_net()
    preempted = False
    with tempfile.TemporaryDirectory(prefix="chaos_ckpt_") as d:
        trainer = FaultTolerantTrainer(chaotic, d,
                                       save_every_n_iterations=2,
                                       max_restarts=8)
        t0 = time.perf_counter()
        with faults.active(plan_name):
            trainer.fit(it, epochs=epochs)
            fired = sum(s["fires"] for s in faults.stats().values())
        if trainer.preempted:
            # the preempt plan stops the "job" cleanly mid-run; model
            # the slice restart: a fresh process resumes from the
            # checkpoint dir and finishes the epoch budget
            preempted = True
            from deeplearning4j_tpu.train.fault_tolerance import \
                resume_or_init
            chaotic = resume_or_init(_build_net, d)
            FaultTolerantTrainer(
                chaotic, d, save_every_n_iterations=2,
                max_restarts=8).fit(it, epochs=epochs - chaotic.epoch)
        wall = time.perf_counter() - t0
    chaos_loss = float(chaotic.score(ds))
    max_dp = max(float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
                 for a, b in zip(jax.tree.leaves(base.params),
                                 jax.tree.leaves(chaotic.params)))
    quarantined = metrics.CKPT_QUARANTINED._children[()].get()
    ok = (fired > 0 and np.isfinite(chaos_loss)
          and abs(chaos_loss - base_loss) <= tol)
    return {"mode": "train", "plan": plan_name,
            "faults_fired": fired, "restarts": trainer.restarts,
            "preempted": preempted,
            "baseline_loss": round(base_loss, 6),
            "chaos_loss": round(chaos_loss, 6),
            "max_param_delta": max_dp,
            "exact_resume": max_dp < 1e-5,
            "quarantined": quarantined,
            "wall_s": round(wall, 2), "ok": bool(ok)}


def _serving_scenario(plan_name: str) -> dict:
    """Flood a bounded serving queue under the plan: requests must shed
    (fast QueueFullError) or complete — never block — and the dispatch
    worker must survive its injected crash."""
    from deeplearning4j_tpu.parallel.inference import (
        ParallelInference, QueueFullError)
    from deeplearning4j_tpu.resilience import faults
    from deeplearning4j_tpu.obs import metrics

    net = _build_net()
    pi = ParallelInference(net, batch_limit=8, queue_limit=8,
                           buckets=(1, 2, 4, 8))
    x = np.random.RandomState(0).randn(64, 8).astype(np.float32)
    shed, failed, okc = 0, 0, 0
    t0 = time.perf_counter()
    with faults.active(plan_name):
        # phase 1 — overload burst: the bounded queue must shed (fast
        # QueueFullError) instead of blocking the submitter
        burst = []
        for i in range(32):
            try:
                burst.append(pi.output_async(x[i], deadline_s=10.0))
            except QueueFullError:
                shed += 1
        # phase 2 — paced waves (submit, then gather, so the worker
        # forms several batches): the injected crash takes one whole
        # batch (those requests get the error immediately), later
        # waves are served by the SAME worker thread — it recovered,
        # not died
        for ob in burst:
            try:
                ob.get(timeout=10.0)
                okc += 1
            except Exception:
                failed += 1
        for _ in range(4):
            wave = [pi.output_async(x[j], deadline_s=10.0)
                    for j in range(4)]
            for ob in wave:
                try:
                    ob.get(timeout=10.0)
                    okc += 1
                except Exception:
                    failed += 1
        fired = sum(s["fires"] for s in faults.stats().values())
    # the worker survived the injected batch failure: a fresh request
    # still round-trips
    post = np.asarray(pi.output(x[0], timeout=10.0))
    pi.shutdown()
    wall = time.perf_counter() - t0
    total = 32 + 4 * 4
    shed_total = sum(
        c.get() for c in metrics.REQS_SHED._children.values())
    ok = (fired > 0 and okc > 0 and failed > 0 and shed > 0
          and post.shape[-1] == 3 and okc + failed + shed == total
          and wall < 30.0)
    return {"mode": "serving", "plan": plan_name, "requests": total,
            "completed": okc, "errored_by_fault": failed,
            "shed_at_enqueue": shed, "shed_metric_total": shed_total,
            "faults_fired": fired, "worker_survived": True,
            "wall_s": round(wall, 2), "ok": bool(ok)}


def _example_scenario(example: str, plan: str, restarts: int) -> dict:
    """Slice-restart supervision: run the example under the plan env;
    a crash (injected fault escaping to the top) is answered by simply
    re-running the process — completion within the restart budget is
    the assertion. The plan is injected into the FIRST attempt only
    (a seeded plan would fire identically in every restarted process;
    the model is "the fault happened, the restarted job runs clean" —
    exactly what a transient slice failure looks like)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "examples", f"{example}.py")
    if not os.path.exists(script):
        raise SystemExit(f"no such example: {script}")
    attempts = 0
    rc = None
    fault_fired = False
    t0 = time.perf_counter()
    while attempts <= restarts:
        attempts += 1
        env = dict(os.environ,
                   DL4J_TPU_EXAMPLE_FAST="1",
                   JAX_PLATFORMS="cpu")
        env.pop("DL4J_TPU_FAULT_PLAN", None)
        if attempts == 1:
            env["DL4J_TPU_FAULT_PLAN"] = plan
        r = subprocess.run([sys.executable, script], env=env, cwd=repo,
                           timeout=900, capture_output=True, text=True)
        rc = r.returncode
        sys.stdout.write(r.stdout)
        if attempts == 1 and \
                "fault injection: firing" in (r.stderr + r.stdout):
            fault_fired = True       # the harness logs every fire
        if rc == 0:
            break
    # a drill that never fired its fault proved nothing — pick a plan
    # whose site/nth the example actually reaches (the builtin
    # scenarios assert fires the same way)
    return {"mode": "example", "plan": plan, "example": example,
            "attempts": attempts, "returncode": rc,
            "fault_fired": fault_fired,
            "wall_s": round(time.perf_counter() - t0, 2),
            "ok": rc == 0 and fault_fired}


def main() -> int:
    from deeplearning4j_tpu.resilience.faults import (FaultPlan,
                                                      NAMED_PLANS)
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--plan", action="append", default=[],
                    help="named plan or raw rule spec (repeatable)")
    ap.add_argument("--example", default=None,
                    help="run examples/<NAME>.py under the plan instead "
                         "of the builtin scenario")
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--tol", type=float, default=0.05,
                    help="max |chaos_loss - baseline_loss|")
    ap.add_argument("--restarts", type=int, default=3,
                    help="restart budget for --example supervision")
    ap.add_argument("--list", action="store_true",
                    help="list named plans and exit")
    args = ap.parse_args()
    if args.list:
        for name, spec in NAMED_PLANS.items():
            print(f"{name:<16} {spec}")
        return 0
    if not args.plan:
        ap.error("--plan required (see --list)")

    results = []
    for plan in args.plan:
        parsed = FaultPlan.parse(plan)     # fail fast on bad specs
        if args.example:
            spec = NAMED_PLANS.get(plan, plan)
            results.append(
                _example_scenario(args.example, spec, args.restarts))
        elif any(r.site.startswith("serving") for r in parsed.rules):
            results.append(_serving_scenario(plan))
        else:
            results.append(_train_scenario(plan, args.epochs, args.tol))
    print(json.dumps({"results": results,
                      "ok": all(r["ok"] for r in results)}, indent=1))
    return 0 if all(r["ok"] for r in results) else 1


if __name__ == "__main__":
    raise SystemExit(main())
