"""Instrumentation lint — the telemetry spine's CI fence (tier-1 via
``tests/test_lint_instrumentation.py``).

Four AST rules over ``deeplearning4j_tpu/``:

1. **Every ``sentry.jit``-wrapped hot path emits obs telemetry.** A
   module that builds jitted entry points with ``sentry.jit(...)`` is
   a hot path by definition; it must also call one of the obs
   emission APIs (``obs.record_step`` / ``record_etl`` /
   ``record_worker_step`` / ``span`` / ``trace.add_span``) so the
   timeline can attribute the wall time those entry points consume.
   Without this rule a future PR can add a jitted path whose cost is
   invisible to ``chrome://tracing`` and ``/metrics``.

2. **No ``time.time()`` for step timing outside ``obs/``.** The spine
   has ONE step clock — ``obs.now`` (``time.perf_counter``): mixing in
   wall clocks reintroduces exactly the disconnected-timing mess this
   layer replaced (non-monotonic under NTP slew, incomparable bases).
   Allowlisted: modules using wall time for *calendar* purposes
   (termination deadlines, record timestamps), never step timing.

3. **No host-side device reductions over params/grads in
   listener/stats paths.** Listener code (``train/stats.py``,
   ``train/listeners.py``) runs per recording interval on the host;
   building ``jnp``/``jax.tree.map`` reductions there re-dispatches
   a device program per layer per record AND pins full param trees
   between records (the old ``StatsListener._prev_params`` copy this
   rule fences out). Per-layer training health is computed IN-STEP
   by the numerics observatory — ``obs/numerics.py`` is the
   allowlisted home for these reductions (it lives outside the
   scanned listener set by construction); listeners consume its
   scalars. ``jax.tree.leaves`` + numpy stays legal (the explicit
   opt-in host histograms).

4. **Every ``ParallelWrapper`` step variant has a warmup feed.** The
   wrapper's ``warmup()`` iterates the module-level ``WARMUP_FEEDS``
   table; a ``_build_*_step`` method without a table entry is a step
   signature ``perf/warmup.py`` can never AOT-compile — its first
   real batch cold-traces and stalls the whole mesh. The rule keeps
   the builder set and the feed table in lockstep (both directions:
   no missing feeds, no stale feeds).

Exit status 0 = clean; 1 = violations (printed one per line).
"""
from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "deeplearning4j_tpu"

# wall-clock (calendar) users, not step timers — keep this list short
# and justified:
TIME_TIME_ALLOWLIST = {
    # max-seconds termination condition compares against a deadline
    "train/earlystopping.py",
    # cluster-event records carry epoch timestamps for cross-host logs
    "train/fault_tolerance.py",
}

_OBS_EMITTERS = {"record_step", "record_etl", "record_worker_step",
                 "span", "add_span", "instant", "counter",
                 "observe_step"}

# listener/stats paths scanned by rule 3 — per-record host code where
# device reductions over params/grads are banned (obs/numerics.py is
# the sanctioned in-step home, outside this set by construction)
LISTENER_STATS_PATHS = {"train/stats.py", "train/listeners.py"}

# rule 4 target: the SPMD wrapper whose step builders must each have a
# WARMUP_FEEDS entry
WRAPPER_PATH = "parallel/wrapper.py"


def _calls(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node


def _attr_chain(func: ast.AST) -> str:
    """Dotted name of a call target ('sentry.jit', 'obs.trace.add_span',
    'time.time') — '' for anything fancier."""
    parts: List[str] = []
    while isinstance(func, ast.Attribute):
        parts.append(func.attr)
        func = func.value
    if isinstance(func, ast.Name):
        parts.append(func.id)
        return ".".join(reversed(parts))
    return ""


def lint_file(path: Path, rel: str) -> List[str]:
    try:
        tree = ast.parse(path.read_text())
    except SyntaxError as e:
        return [f"{rel}: unparseable ({e})"]
    chains = [_attr_chain(c.func) for c in _calls(tree)]
    problems = []

    uses_sentry_jit = any(ch == "sentry.jit" or ch.endswith(".sentry.jit")
                          for ch in chains)
    emits_obs = any(ch.split(".")[-1] in _OBS_EMITTERS and
                    ("obs" in ch.split(".") or ch.startswith("trace."))
                    for ch in chains)
    if uses_sentry_jit and not emits_obs:
        problems.append(
            f"{rel}: builds sentry.jit hot paths but never emits an "
            "obs span/metric (obs.record_step / obs.span / "
            "obs.trace.add_span) — jitted wall time would be invisible "
            "to the telemetry spine")

    in_obs = rel.startswith("obs/")
    if not in_obs and rel not in TIME_TIME_ALLOWLIST:
        for c in _calls(tree):
            if _attr_chain(c.func) == "time.time":
                problems.append(
                    f"{rel}:{c.lineno}: time.time() outside obs/ — "
                    "use obs.now (the one step clock) or, for "
                    "calendar timestamps, datetime + an allowlist "
                    "entry here")

    if rel in LISTENER_STATS_PATHS:
        for c in _calls(tree):
            ch = _attr_chain(c.func)
            if ch.startswith("jnp.") or ch.startswith("jax.numpy.") \
                    or ch in ("jax.tree.map", "jax.tree_map"):
                problems.append(
                    f"{rel}:{c.lineno}: host-side device reduction "
                    f"({ch}) in a listener/stats path — per-layer "
                    "training health is computed in-step by the "
                    "numerics observatory (obs/numerics.py, the "
                    "allowlisted home); consume net.last_numerics / "
                    "obs.numerics.tree_norms scalars instead")

    if rel == WRAPPER_PATH:
        problems.extend(_lint_wrapper_warmup(tree, rel))
    return problems


def _lint_wrapper_warmup(tree: ast.AST, rel: str) -> List[str]:
    """Rule 4: every ``_build_*_step`` method on ParallelWrapper has a
    ``WARMUP_FEEDS`` entry (and no entry is stale), and ``warmup()``
    actually reads the table."""
    builders = set()
    warmup_reads_table = False
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and \
                node.name == "ParallelWrapper":
            for sub in ast.walk(node):
                if isinstance(sub, ast.FunctionDef):
                    if sub.name.startswith("_build_") and \
                            sub.name.endswith("_step"):
                        builders.add(sub.name)
                    if sub.name == "warmup":
                        warmup_reads_table = any(
                            isinstance(n, ast.Name)
                            and n.id == "WARMUP_FEEDS"
                            for n in ast.walk(sub))
    feeds = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "WARMUP_FEEDS"
                for t in node.targets):
            if isinstance(node.value, ast.Dict):
                feeds = {k.value for k in node.value.keys
                         if isinstance(k, ast.Constant)
                         and isinstance(k.value, str)}
    problems = []
    if not builders:
        return problems
    if feeds is None:
        return [f"{rel}: no WARMUP_FEEDS dict literal — step variants "
                "have no warmup feeds and will cold-trace their first "
                "real batch"]
    for b in sorted(builders - feeds):
        problems.append(
            f"{rel}: step builder {b} has no WARMUP_FEEDS entry — its "
            "step signature cannot be AOT-warmed and the first real "
            "batch stalls the mesh on a cold trace")
    for b in sorted(feeds - builders):
        problems.append(
            f"{rel}: WARMUP_FEEDS entry {b!r} names no step builder — "
            "stale feed (renamed/removed variant?)")
    if not warmup_reads_table:
        problems.append(
            f"{rel}: warmup() never reads WARMUP_FEEDS — the feed "
            "table is dead and step variants cold-trace")
    return problems


def run(package_dir: Path = PACKAGE) -> List[str]:
    problems: List[str] = []
    for path in sorted(package_dir.rglob("*.py")):
        rel = path.relative_to(package_dir).as_posix()
        problems.extend(lint_file(path, rel))
    return problems


def main() -> int:
    problems = run()
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} instrumentation lint violation(s)")
        return 1
    print("instrumentation lint: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
