"""Instrumentation lint — the telemetry spine's CI fence (tier-1 via
``tests/test_lint_instrumentation.py``).

Twelve AST rules over ``deeplearning4j_tpu/``:

1. **Every ``sentry.jit``-wrapped hot path emits obs telemetry.** A
   module that builds jitted entry points with ``sentry.jit(...)`` is
   a hot path by definition; it must also call one of the obs
   emission APIs (``obs.record_step`` / ``record_etl`` /
   ``record_worker_step`` / ``span`` / ``trace.add_span``) so the
   timeline can attribute the wall time those entry points consume.
   Without this rule a future PR can add a jitted path whose cost is
   invisible to ``chrome://tracing`` and ``/metrics``.

2. **No ``time.time()`` for step timing outside ``obs/``.** The spine
   has ONE step clock — ``obs.now`` (``time.perf_counter``): mixing in
   wall clocks reintroduces exactly the disconnected-timing mess this
   layer replaced (non-monotonic under NTP slew, incomparable bases).
   Allowlisted: modules using wall time for *calendar* purposes
   (termination deadlines, record timestamps), never step timing.

3. **No host-side device reductions over params/grads in
   listener/stats paths.** Listener code (``train/stats.py``,
   ``train/listeners.py``) runs per recording interval on the host;
   building ``jnp``/``jax.tree.map`` reductions there re-dispatches
   a device program per layer per record AND pins full param trees
   between records (the old ``StatsListener._prev_params`` copy this
   rule fences out). Per-layer training health is computed IN-STEP
   by the numerics observatory — ``obs/numerics.py`` is the
   allowlisted home for these reductions (it lives outside the
   scanned listener set by construction); listeners consume its
   scalars. ``jax.tree.leaves`` + numpy stays legal (the explicit
   opt-in host histograms).

4. **Every ``ParallelWrapper`` step variant has a warmup feed.** The
   wrapper's ``warmup()`` iterates the module-level ``WARMUP_FEEDS``
   table; a ``_build_*_step`` method without a table entry is a step
   signature ``perf/warmup.py`` can never AOT-compile — its first
   real batch cold-traces and stalls the whole mesh. The rule keeps
   the builder set and the feed table in lockstep (both directions:
   no missing feeds, no stale feeds).

5. **Every fault-injection site is declared, live, and drillable.**
   ``resilience/faults.py`` failure modes only exist where a
   ``faults.inject("<site>")`` call is threaded through a real code
   path, and only stay honest while something exercises them. Three
   checks keep the site table and the codebase in lockstep: every
   literal ``inject`` site must appear in ``KNOWN_SITES`` (else the
   plan parser rejects plans that target it), every ``KNOWN_SITES``
   entry must have at least one call site (a dead site advertises a
   drill that cannot fire), and every injected site must be covered
   by a ``NAMED_PLANS`` rule or referenced from ``tests/`` (an
   unplanned, untested site rots silently as code moves — exactly how
   the elastic layer's ``host_death``/``coordinator`` sites would
   otherwise age out).

6. **Every metric family name is declared in the one FAMILIES
   table.** ``obs/metrics.py::FAMILIES`` is the single registry of
   ``dl4j_tpu_*`` family names (and kinds). Three checks kill
   stringly-typed family drift between producers and consumers:
   every emit site in the package (a ``REGISTRY.counter/gauge/
   histogram`` registration, a pull-time collector tuple, or a fleet
   ``AGGREGATE_FAMILIES`` entry) must name a declared family with the
   declared kind; every declared family must have an emit site (no
   dead declarations advertising metrics that never exist); and every
   ``dl4j_tpu_*`` token in ``tools/tpu_watch.py`` and ``docs/OPS.md``
   must resolve to a declared family (exactly, via a histogram
   ``_bucket``/``_sum``/``_count`` suffix, or as a prefix filter
   matching at least one family) — a dashboard or runbook can't watch
   a family the code stopped (or never started) emitting.

7. **Every jitted entry point in ``serving/`` is sentried and has a
   warmup feed.** The serving gateway's whole contract is zero
   retraces after ``warmup()`` — a raw ``jax.jit`` there bypasses the
   retrace sentry's accounting, and a sentried entry point outside a
   ``_build_*`` builder (or a builder without a ``WARMUP_FEEDS``
   entry) is a compile the warmup can never reach: the first live
   request pays it mid-traffic. Same shape as rule 4 (the
   ``ParallelWrapper`` feed-table rule): builders ⊆ feeds ⊆ builders,
   and ``warmup`` must actually read the table.

8. **The device-time observatory's scope contract holds.** Per-layer
   device-time attribution (``obs/devtime.py``, ARCHITECTURE.md §16)
   only works while the annotation points stay annotated: the layer
   loops in ``nn/multilayer.py``/``nn/graph.py`` ``_forward`` (ONE
   site covers every registered layer type and every zoo model built
   from them), the hand-rolled zoo transformer's decode/prefill
   paths, the serving scheduler's paged decode step, and the ZeRO
   collective phases — each listed function must contain a
   ``devtime.scope``/``jax.named_scope`` call (:data:`SCOPE_SITES`).
   The ``dl4j_tpu_devtime_*`` family block must exist in the
   FAMILIES table (rule 6 already checks kinds — this catches the
   block being deleted outright), and every ``gap.<key>`` token
   ``docs/OPS.md``/``tools/tpu_watch.py`` reference must resolve
   against ``obs/devtime.py``'s ``GAP_KEYS`` tuple, so the runbook
   and dashboard can't drift from the gap-report schema.

9. **The fused-kernel library stays registered and honest.** Pallas
   kernels live in ``ops/`` ONLY (a raw ``pl.pallas_call`` anywhere
   else bypasses the dispatch-gate/fallback/parity contract of
   ARCHITECTURE §17), and every PUBLIC kernel — a non-underscore
   module-level function that reaches a ``pallas_call`` through
   private same-module helpers — must be declared in
   ``ops/kernel_registry.py`` ``KERNEL_REGISTRY`` with (a) a
   ``fallback`` naming a function that exists in its module (the
   value-identical XLA path the gate-off program runs), (b) a
   ``parity`` test reference that resolves to a real test
   (``tests/<file>.py::<test>``), and (c) a ``scope`` that the kernel
   function actually emits via ``devtime.scope`` AND that is listed in
   :data:`SCOPE_SITES` so rule 8 keeps enforcing it — the same
   table-driven fence that keeps rules 4/7/8 honest, in both
   directions (no unregistered kernels, no stale registry entries).

10. **The speculative-decode grid stays warmable and observable.**
    The serving scheduler's spec-decode entry points compile one
    executable per draft width ``k`` — if ``serving/scheduler.py``
    defines any ``_build_spec*`` builder it must also define the
    module-level ``SPEC_KS`` tuple literal (the supported k grid the
    constructor pins requests to), list the builder in
    ``WARMUP_FEEDS`` (rule 7's table), and ``warmup()`` must reference
    ``SPEC_KS`` so the warmed signatures and the admissible widths
    cannot drift apart (an off-grid k would cold-trace mid-traffic —
    exactly the stall the zero-retrace fence exists to prevent). On
    the consumer side every ``dl4j_tpu_serving_spec_*`` /
    ``dl4j_tpu_serving_prefix_*`` token in ``tools/tpu_watch.py`` and
    ``docs/OPS.md`` must resolve against the FAMILIES table, and each
    consumer must reference at least one ``dl4j_tpu_serving_spec_*``
    family — a spec-decode rollout whose accept rate no dashboard or
    runbook watches regresses silently.

11. **The communication observatory's attribution contract holds.**
    The wire ledger (``obs/commtime.py``, ARCHITECTURE.md §19) joins
    every collective to a ``dl4j.*`` scope — which only works while
    the modules that EMIT collectives explicitly keep their emitting
    phases scope-annotated. Every bare or ``jax.lax.*`` call to a
    collective primitive (``psum``/``pmean``/``psum_scatter``/
    ``all_gather``/``ppermute``/``all_to_all``/``pshuffle``) in
    :data:`COLLECTIVE_SCOPE_PATHS` (``parallel/zero.py``,
    ``parallel/composed.py``, ``parallel/compression.py``) must sit
    inside a function carrying a ``devtime.scope``/``named_scope``
    call — an unscoped collective lands in the ledger's anonymous
    ``op:*`` bucket and the per-scope wire attribution silently
    degrades. While ``obs/commtime.py`` exists the
    ``dl4j_tpu_comm_*`` family block must exist in FAMILIES (rule 6
    already checks kinds — this catches the block being deleted
    outright), every ``dl4j_tpu_comm_*`` token in
    ``tools/tpu_watch.py``/``docs/OPS.md`` must resolve against the
    table, and ``tpu_watch`` must reference at least one comm family
    — a wire-bound regression with no dashboard surface lands
    unwatched.

12. **The elastic serving fleet stays routable and prefetch-warm.**
    The fleet layer's whole contract (``serving/fleet.py``,
    ARCHITECTURE.md §20) is that a replica is only visible to the
    router once every jitted entry point is AOT-warm, and that the
    routing plane is observable. Producer side: the module-level
    ``STARTUP_PREFETCH`` tuple literal must name exactly the
    scheduler's ``WARMUP_FEEDS`` keys (both directions — a builder
    missing from the prefetch table cold-traces on the respawned
    replica's first request; a stale entry advertises a warmup that
    cannot run), and inside ``ServingReplica.start`` the ``warmup``
    call must precede every lease acquisition (``renew`` /
    ``start_auto_renew``) — lease-before-warm would let the router
    route to a cold replica. Metric side: every
    ``dl4j_tpu_router_*`` / ``dl4j_tpu_serving_fleet_*`` family must
    be declared in FAMILIES *and* have a live emit site (rule 6's
    lockstep, re-checked here so deleting the fleet block fails with
    a fleet-specific message), at least one family of each prefix
    must exist while the fleet module does, every such token in
    ``tools/tpu_watch.py``/``docs/OPS.md`` must resolve, and
    ``tpu_watch`` must reference at least one router family — an
    unwatched routing plane sheds silently.

Exit status 0 = clean; 1 = violations (printed one per line).
"""
from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Optional

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "deeplearning4j_tpu"

# wall-clock (calendar) users, not step timers — keep this list short
# and justified:
TIME_TIME_ALLOWLIST = {
    # max-seconds termination condition compares against a deadline
    "train/earlystopping.py",
    # cluster-event records carry epoch timestamps for cross-host logs
    "train/fault_tolerance.py",
    # membership leases are CROSS-PROCESS deadlines: wall clock is the
    # only clock whose readings are comparable between hosts
    "resilience/elastic.py",
}

_OBS_EMITTERS = {"record_step", "record_etl", "record_worker_step",
                 "span", "add_span", "instant", "counter",
                 "observe_step"}

# listener/stats paths scanned by rule 3 — per-record host code where
# device reductions over params/grads are banned (obs/numerics.py is
# the sanctioned in-step home, outside this set by construction)
LISTENER_STATS_PATHS = {"train/stats.py", "train/listeners.py"}

# rule 4 target: the SPMD wrapper whose step builders must each have a
# WARMUP_FEEDS entry
WRAPPER_PATH = "parallel/wrapper.py"

# rule 5 source of truth: the site table + named-plan vocabulary
FAULTS_PATH = "resilience/faults.py"

# rule 6 source of truth: the metric-family registry table
METRICS_PATH = "obs/metrics.py"

# rule 7 target: the serving gateway package whose jitted entry
# points must all be sentried, builder-scoped, and warmup-fed
SERVING_DIR = "serving"

# rule 6: non-family dl4j_tpu_* tokens that legitimately appear in the
# watched docs/tools (file-name stems, not metric families) — keep
# short and justified:
FAMILY_TOKEN_ALLOWLIST = {
    # the span tracer's default output file, dl4j_tpu_trace_<pid>.jsonl
    "dl4j_tpu_trace_",
}

# rule 8 annotation points: each listed function must contain a
# devtime.scope / jax.named_scope call. ONE site in each fit forward
# covers every registered layer type (and every zoo model built from
# layers); the remaining entries are the hand-rolled programs the fit
# forwards never trace. The ops/ entries are the PUBLIC Pallas kernels
# — rule 9 requires every registry kernel to be listed here, and this
# rule then keeps the kernel's own devtime scope from silently
# disappearing.
SCOPE_SITES = {
    "nn/multilayer.py": ("_forward",),
    "nn/graph.py": ("_forward",),
    "zoo/gpt.py": ("_token_logits", "_prefill_forward"),
    "serving/scheduler.py": ("_build_step_fn", "_build_spec_step_fn",
                             "_build_suffix_admit_fn"),
    "parallel/zero.py": ("scatter_mean", "gather"),
    "ops/pallas_kernels.py": ("flash_attention", "flash_block_fwd",
                              "flash_block_bwd", "threshold_encode",
                              "threshold_decode"),
    "ops/fused_norms.py": ("rms_norm", "add_rms_norm", "layer_norm"),
}

# rule 8 source of truth for gap-report keys
DEVTIME_PATH = "obs/devtime.py"

# rule 9: the Pallas kernel library's home + its registry table
OPS_DIR = "ops"
KERNEL_REGISTRY_PATH = "ops/kernel_registry.py"

# rule 11: the communication observatory module, its metric-family
# prefix, the modules whose EXPLICIT collective emissions must be
# scope-annotated (GSPMD-inserted collectives are attributed through
# named_scope metadata already), and the primitive names that count
# as an emission
COMMTIME_PATH = "obs/commtime.py"
COMM_FAMILY_PREFIX = "dl4j_tpu_comm_"
COLLECTIVE_SCOPE_PATHS = ("parallel/zero.py", "parallel/composed.py",
                          "parallel/compression.py")
COLLECTIVE_EMITTERS = frozenset({
    "psum", "pmean", "psum_scatter", "all_gather", "ppermute",
    "all_to_all", "pshuffle"})


def _calls(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node


def _attr_chain(func: ast.AST) -> str:
    """Dotted name of a call target ('sentry.jit', 'obs.trace.add_span',
    'time.time') — '' for anything fancier."""
    parts: List[str] = []
    while isinstance(func, ast.Attribute):
        parts.append(func.attr)
        func = func.value
    if isinstance(func, ast.Name):
        parts.append(func.id)
        return ".".join(reversed(parts))
    return ""


def lint_file(path: Path, rel: str) -> List[str]:
    try:
        tree = ast.parse(path.read_text())
    except SyntaxError as e:
        return [f"{rel}: unparseable ({e})"]
    chains = [_attr_chain(c.func) for c in _calls(tree)]
    problems = []

    uses_sentry_jit = any(ch == "sentry.jit" or ch.endswith(".sentry.jit")
                          for ch in chains)
    emits_obs = any(ch.split(".")[-1] in _OBS_EMITTERS and
                    ("obs" in ch.split(".") or ch.startswith("trace."))
                    for ch in chains)
    if uses_sentry_jit and not emits_obs:
        problems.append(
            f"{rel}: builds sentry.jit hot paths but never emits an "
            "obs span/metric (obs.record_step / obs.span / "
            "obs.trace.add_span) — jitted wall time would be invisible "
            "to the telemetry spine")

    in_obs = rel.startswith("obs/")
    if not in_obs and rel not in TIME_TIME_ALLOWLIST:
        for c in _calls(tree):
            if _attr_chain(c.func) == "time.time":
                problems.append(
                    f"{rel}:{c.lineno}: time.time() outside obs/ — "
                    "use obs.now (the one step clock) or, for "
                    "calendar timestamps, datetime + an allowlist "
                    "entry here")

    if rel in LISTENER_STATS_PATHS:
        for c in _calls(tree):
            ch = _attr_chain(c.func)
            if ch.startswith("jnp.") or ch.startswith("jax.numpy.") \
                    or ch in ("jax.tree.map", "jax.tree_map"):
                problems.append(
                    f"{rel}:{c.lineno}: host-side device reduction "
                    f"({ch}) in a listener/stats path — per-layer "
                    "training health is computed in-step by the "
                    "numerics observatory (obs/numerics.py, the "
                    "allowlisted home); consume net.last_numerics / "
                    "obs.numerics.tree_norms scalars instead")

    if rel == WRAPPER_PATH:
        problems.extend(_lint_wrapper_warmup(tree, rel))
    return problems


def _lint_wrapper_warmup(tree: ast.AST, rel: str) -> List[str]:
    """Rule 4: every ``_build_*_step`` method on ParallelWrapper has a
    ``WARMUP_FEEDS`` entry (and no entry is stale), and ``warmup()``
    actually reads the table."""
    builders = set()
    warmup_reads_table = False
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and \
                node.name == "ParallelWrapper":
            for sub in ast.walk(node):
                if isinstance(sub, ast.FunctionDef):
                    if sub.name.startswith("_build_") and \
                            sub.name.endswith("_step"):
                        builders.add(sub.name)
                    if sub.name == "warmup":
                        warmup_reads_table = any(
                            isinstance(n, ast.Name)
                            and n.id == "WARMUP_FEEDS"
                            for n in ast.walk(sub))
    feeds = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "WARMUP_FEEDS"
                for t in node.targets):
            if isinstance(node.value, ast.Dict):
                feeds = {k.value for k in node.value.keys
                         if isinstance(k, ast.Constant)
                         and isinstance(k.value, str)}
    problems = []
    if not builders:
        return problems
    if feeds is None:
        return [f"{rel}: no WARMUP_FEEDS dict literal — step variants "
                "have no warmup feeds and will cold-trace their first "
                "real batch"]
    for b in sorted(builders - feeds):
        problems.append(
            f"{rel}: step builder {b} has no WARMUP_FEEDS entry — its "
            "step signature cannot be AOT-warmed and the first real "
            "batch stalls the mesh on a cold trace")
    for b in sorted(feeds - builders):
        problems.append(
            f"{rel}: WARMUP_FEEDS entry {b!r} names no step builder — "
            "stale feed (renamed/removed variant?)")
    if not warmup_reads_table:
        problems.append(
            f"{rel}: warmup() never reads WARMUP_FEEDS — the feed "
            "table is dead and step variants cold-trace")
    return problems


def _parse_fault_vocabulary(faults_path: Path):
    """``(KNOWN_SITES literals, named-plan site patterns)`` straight
    from the AST of ``resilience/faults.py`` — the lint never imports
    the package."""
    tree = ast.parse(faults_path.read_text())
    declared: set = set()
    plan_patterns: set = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        names = {t.id for t in node.targets
                 if isinstance(t, ast.Name)}
        if "KNOWN_SITES" in names:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) and \
                        isinstance(sub.value, str):
                    declared.add(sub.value)
        if "NAMED_PLANS" in names and isinstance(node.value, ast.Dict):
            for v in node.value.values:
                spec = ""
                # string literal or implicit concatenation folds to one
                # Constant; anything fancier is skipped (plans are
                # plain literals by construction)
                if isinstance(v, ast.Constant) and \
                        isinstance(v.value, str):
                    spec = v.value
                for chunk in spec.split(";"):
                    chunk = chunk.strip()
                    if chunk:
                        plan_patterns.add(chunk.split(":")[0])
    return declared, plan_patterns


def _inject_sites(package_dir: Path):
    """Every literal ``faults.inject("<site>")`` call site in the
    package: ``{site: [rel:lineno, ...]}``."""
    sites: dict = {}
    for path in sorted(package_dir.rglob("*.py")):
        rel = path.relative_to(package_dir).as_posix()
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue                # rule-agnostic: lint_file reports it
        for c in _calls(tree):
            ch = _attr_chain(c.func)
            if not ch.endswith(".inject"):
                continue
            base = ch.rsplit(".", 2)[-2] if "." in ch else ""
            if base not in ("faults", "_faults"):
                continue
            if c.args and isinstance(c.args[0], ast.Constant) and \
                    isinstance(c.args[0].value, str):
                sites.setdefault(c.args[0].value, []).append(
                    f"{rel}:{c.lineno}")
    return sites


def _lint_fault_sites(package_dir: Path,
                      tests_dir: Optional[Path]) -> List[str]:
    """Rule 5: declared ⊆ injected ⊆ declared, and every injected site
    is named by a plan or a test."""
    import fnmatch
    faults_path = package_dir / FAULTS_PATH
    if not faults_path.is_file():
        return []
    declared, plan_patterns = _parse_fault_vocabulary(faults_path)
    injected = _inject_sites(package_dir)
    problems: List[str] = []
    for site in sorted(set(injected) - declared):
        problems.append(
            f"{injected[site][0]}: faults.inject({site!r}) is not in "
            f"{FAULTS_PATH} KNOWN_SITES — no fault plan can ever "
            "target it (the parser rejects unknown literal sites)")
    for site in sorted(declared - set(injected)):
        problems.append(
            f"{FAULTS_PATH}: KNOWN_SITES entry {site!r} has no "
            "faults.inject() call site anywhere in the package — a "
            "dead site advertising a drill that cannot fire")
    test_text = ""
    if tests_dir is not None and Path(tests_dir).is_dir():
        test_text = "\n".join(
            p.read_text() for p in sorted(Path(tests_dir).glob("*.py")))
    for site in sorted(set(injected) & declared):
        planned = any(fnmatch.fnmatchcase(site, pat)
                      for pat in plan_patterns)
        tested = f'"{site}"' in test_text or f"'{site}'" in test_text
        if not planned and not tested:
            problems.append(
                f"{injected[site][0]}: fault site {site!r} is covered "
                "by no NAMED_PLANS rule and referenced by no test — "
                "an undrillable site rots as the code around it moves")
    return problems


def _parse_families(metrics_path: Path) -> Optional[dict]:
    """``{family: kind}`` from the FAMILIES dict literal in
    ``obs/metrics.py`` — AST only, the lint never imports the
    package. None when the file/table is absent (synthetic trees)."""
    if not metrics_path.is_file():
        return None
    tree = ast.parse(metrics_path.read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "FAMILIES"
                for t in node.targets):
            if isinstance(node.value, ast.Dict):
                out = {}
                for k, v in zip(node.value.keys, node.value.values):
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str) and \
                            isinstance(v, ast.Constant) and \
                            isinstance(v.value, str):
                        out[k.value] = v.value
                return out
    return None


_FAMILY_KINDS = ("counter", "gauge", "histogram")


def _family_emit_sites(package_dir: Path) -> dict:
    """Every place the package EMITS a metric family:
    ``{name: [(kind, "rel:lineno"), ...]}`` — registration calls
    (``REGISTRY.counter/gauge/histogram("name", ...)``), pull-time
    collector tuples (``("name", "kind", doc, samples)``), and
    aggregator family tables (dict literals named
    ``AGGREGATE_FAMILIES``)."""
    sites: dict = {}

    def add(name, kind, where):
        sites.setdefault(name, []).append((kind, where))

    for path in sorted(package_dir.rglob("*.py")):
        rel = path.relative_to(package_dir).as_posix()
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue                # rule-agnostic: lint_file reports it
        for c in _calls(tree):
            ch = _attr_chain(c.func)
            parts = ch.split(".")
            if parts[-1] in _FAMILY_KINDS and "REGISTRY" in parts and \
                    c.args and isinstance(c.args[0], ast.Constant) and \
                    isinstance(c.args[0].value, str):
                add(c.args[0].value, parts[-1], f"{rel}:{c.lineno}")
        for node in ast.walk(tree):
            if isinstance(node, ast.Tuple) and len(node.elts) >= 3 \
                    and isinstance(node.elts[0], ast.Constant) \
                    and isinstance(node.elts[0].value, str) \
                    and node.elts[0].value.startswith("dl4j_tpu_") \
                    and isinstance(node.elts[1], ast.Constant) \
                    and node.elts[1].value in _FAMILY_KINDS:
                add(node.elts[0].value, node.elts[1].value,
                    f"{rel}:{node.lineno}")
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name)
                    and t.id == "AGGREGATE_FAMILIES"
                    for t in node.targets) and \
                    isinstance(node.value, ast.Dict):
                for k, v in zip(node.value.keys, node.value.values):
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str):
                        kind = v.value if isinstance(v, ast.Constant) \
                            else ""
                        add(k.value, kind, f"{rel}:{node.lineno}")
    return sites


_FAMILY_TOKEN_RE = None


def _family_tokens(text: str) -> List[str]:
    global _FAMILY_TOKEN_RE
    if _FAMILY_TOKEN_RE is None:
        import re
        _FAMILY_TOKEN_RE = re.compile(r"dl4j_tpu_\w*")
    return _FAMILY_TOKEN_RE.findall(text)


def _resolve_family(token: str, families: dict) -> bool:
    """A consumer token resolves when it is a declared family, a
    histogram sample (``_bucket``/``_sum``/``_count``), or a prefix
    filter matching at least one declared family."""
    if token in families:
        return True
    for suffix in ("_bucket", "_sum", "_count"):
        if token.endswith(suffix) and \
                families.get(token[:-len(suffix)]) == "histogram":
            return True
    return any(f.startswith(token) for f in families)


def _lint_metric_families(package_dir: Path,
                          tools_dir: Optional[Path],
                          docs_dir: Optional[Path]) -> List[str]:
    """Rule 6: emitted ⊆ declared ⊆ emitted (kinds matching), and
    every dl4j_tpu_* token tpu_watch/OPS.md consumes resolves."""
    families = _parse_families(package_dir / METRICS_PATH)
    if families is None:
        return []                   # no registry table (synthetic tree)
    problems: List[str] = []
    sites = _family_emit_sites(package_dir)
    for name in sorted(sites):
        for kind, where in sites[name]:
            if name not in families:
                problems.append(
                    f"{where}: metric family {name!r} is not declared "
                    f"in {METRICS_PATH} FAMILIES — stringly-typed "
                    "family drift (declare it there first)")
            elif kind and families[name] != kind:
                problems.append(
                    f"{where}: metric family {name!r} emitted as "
                    f"{kind} but declared {families[name]!r} in "
                    f"{METRICS_PATH} FAMILIES")
    for name in sorted(set(families) - set(sites)):
        problems.append(
            f"{METRICS_PATH}: FAMILIES entry {name!r} has no emit "
            "site anywhere in the package — a dead declaration "
            "advertising a metric that never exists")
    consumers = []
    if tools_dir is not None and (Path(tools_dir)
                                  / "tpu_watch.py").is_file():
        consumers.append(("tools/tpu_watch.py",
                          (Path(tools_dir) / "tpu_watch.py")
                          .read_text()))
    if docs_dir is not None and (Path(docs_dir) / "OPS.md").is_file():
        consumers.append(("docs/OPS.md",
                          (Path(docs_dir) / "OPS.md").read_text()))
    for label, text in consumers:
        for token in sorted(set(_family_tokens(text))):
            if token in FAMILY_TOKEN_ALLOWLIST:
                continue
            if not _resolve_family(token, families):
                problems.append(
                    f"{label}: references {token!r} which matches no "
                    f"family in {METRICS_PATH} FAMILIES — the "
                    "dashboard/runbook is watching a metric the code "
                    "does not emit")
    return problems


def _sentry_jit_calls(tree: ast.AST):
    for c in _calls(tree):
        ch = _attr_chain(c.func)
        if ch == "sentry.jit" or ch.endswith(".sentry.jit"):
            yield c


def _lint_serving_jits(package_dir: Path) -> List[str]:
    """Rule 7: in ``serving/``, (a) no raw ``jax.jit`` (the sentry
    must see every serving entry point), (b) every ``sentry.jit`` call
    lives inside a ``_build_*`` builder, (c) builders and the
    module-level ``WARMUP_FEEDS`` table match both ways, and (d) a
    ``warmup`` function reads the table."""
    serving = package_dir / SERVING_DIR
    if not serving.is_dir():
        return []
    problems: List[str] = []
    for path in sorted(serving.glob("*.py")):
        rel = f"{SERVING_DIR}/{path.name}"
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue                # rule-agnostic: lint_file reports it
        for c in _calls(tree):
            ch = _attr_chain(c.func)
            if ch == "jax.jit" or ch.endswith(".jax.jit"):
                problems.append(
                    f"{rel}:{c.lineno}: raw jax.jit in serving/ — "
                    "every serving entry point must go through "
                    "sentry.jit (retrace accounting + AOT warmup); a "
                    "bare jit here is invisible to the zero-retrace "
                    "fence")
        jit_calls = list(_sentry_jit_calls(tree))
        if not jit_calls:
            continue
        # innermost enclosing FunctionDef per sentry.jit call
        builders = set()
        covered = set()
        warmup_reads_table = False
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            inside = [c for c in jit_calls
                      if any(c is sub for sub in ast.walk(node))]
            if node.name == "warmup":
                warmup_reads_table = warmup_reads_table or any(
                    isinstance(n, ast.Name) and n.id == "WARMUP_FEEDS"
                    for n in ast.walk(node))
            if not inside:
                continue
            # walking outer defs first would mark calls covered by a
            # non-builder wrapper; only _build_* functions count
            if node.name.startswith("_build_"):
                builders.add(node.name)
                covered.update(id(c) for c in inside)
        for c in jit_calls:
            if id(c) not in covered:
                problems.append(
                    f"{rel}:{c.lineno}: sentry.jit outside a "
                    "_build_* builder — the WARMUP_FEEDS table can't "
                    "govern it, so warmup() can never AOT-compile "
                    "this entry point and the first live request "
                    "cold-traces")
        feeds = None
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "WARMUP_FEEDS"
                    for t in node.targets):
                if isinstance(node.value, ast.Dict):
                    feeds = {k.value for k in node.value.keys
                             if isinstance(k, ast.Constant)
                             and isinstance(k.value, str)}
        if not builders:
            continue
        if feeds is None:
            problems.append(
                f"{rel}: builds sentried serving entry points but has "
                "no WARMUP_FEEDS dict literal — nothing declares the "
                "warmup feeds and the first live request cold-traces")
            continue
        for b in sorted(builders - feeds):
            problems.append(
                f"{rel}: serving builder {b} has no WARMUP_FEEDS "
                "entry — its entry point cannot be AOT-warmed and the "
                "first live request stalls on a cold trace")
        for b in sorted(feeds - builders):
            problems.append(
                f"{rel}: WARMUP_FEEDS entry {b!r} names no _build_* "
                "builder — stale feed (renamed/removed entry point?)")
        if not warmup_reads_table:
            problems.append(
                f"{rel}: no warmup() reads WARMUP_FEEDS — the feed "
                "table is dead and serving entry points cold-trace")
    return problems


# rule 10: the spec-decode scheduler module and the metric-family
# prefixes its dashboard/runbook coverage is checked under
SCHEDULER_PATH = "serving/scheduler.py"
SPEC_FAMILY_PREFIXES = ("dl4j_tpu_serving_spec_",
                        "dl4j_tpu_serving_prefix_")


def _lint_spec_decode(package_dir: Path,
                      tools_dir: Optional[Path],
                      docs_dir: Optional[Path]) -> List[str]:
    """Rule 10: any ``_build_spec*`` builder in the serving scheduler
    implies a module-level ``SPEC_KS`` tuple literal (the admissible
    draft-width grid), a ``WARMUP_FEEDS`` entry for the builder, and a
    ``warmup()`` that references ``SPEC_KS`` — the warmed (k, bucket)
    signatures and the widths the constructor admits must come from
    the same table. Consumer side: spec/prefix family tokens in
    tpu_watch/OPS.md resolve, and each consumer watches at least one
    ``dl4j_tpu_serving_spec_*`` family."""
    sched = package_dir / SCHEDULER_PATH
    if not sched.is_file():
        return []
    try:
        tree = ast.parse(sched.read_text())
    except SyntaxError:
        return []                   # rule-agnostic: lint_file reports it
    problems: List[str] = []
    spec_builders = set()
    warmup_refs_grid = False
    feeds = None
    spec_ks: Optional[set] = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name.startswith("_build_spec"):
                spec_builders.add(node.name)
            elif node.name == "warmup":
                warmup_refs_grid = warmup_refs_grid or any(
                    isinstance(n, ast.Name) and n.id == "SPEC_KS"
                    for n in ast.walk(node))
        elif isinstance(node, ast.Assign):
            names = {t.id for t in node.targets
                     if isinstance(t, ast.Name)}
            if "SPEC_KS" in names and isinstance(
                    node.value, (ast.Tuple, ast.List)):
                spec_ks = {e.value for e in node.value.elts
                           if isinstance(e, ast.Constant)
                           and isinstance(e.value, int)}
            if "WARMUP_FEEDS" in names and isinstance(node.value,
                                                      ast.Dict):
                feeds = {k.value for k in node.value.keys
                         if isinstance(k, ast.Constant)
                         and isinstance(k.value, str)}
    if not spec_builders:
        return problems
    if not spec_ks:
        problems.append(
            f"{SCHEDULER_PATH}: has spec-decode builders "
            f"({', '.join(sorted(spec_builders))}) but no module-"
            "level SPEC_KS tuple literal — nothing pins admissible "
            "draft widths to the warmed k grid, so an arbitrary k "
            "cold-traces on its first live step")
    if feeds is not None:
        for b in sorted(spec_builders - feeds):
            problems.append(
                f"{SCHEDULER_PATH}: spec builder {b} has no "
                "WARMUP_FEEDS entry — its per-k executables are "
                "outside the warmup table and every configured k "
                "cold-traces mid-traffic")
    if spec_ks and not warmup_refs_grid:
        problems.append(
            f"{SCHEDULER_PATH}: warmup() never references SPEC_KS — "
            "the warmed spec signatures and the constructor's "
            "admissible k grid can silently drift apart")
    families = _parse_families(package_dir / METRICS_PATH)
    if families is None:
        return problems
    consumers = []
    if tools_dir is not None and (Path(tools_dir)
                                  / "tpu_watch.py").is_file():
        consumers.append(("tools/tpu_watch.py",
                          (Path(tools_dir) / "tpu_watch.py")
                          .read_text()))
    if docs_dir is not None and (Path(docs_dir) / "OPS.md").is_file():
        consumers.append(("docs/OPS.md",
                          (Path(docs_dir) / "OPS.md").read_text()))
    for label, text in consumers:
        tokens = sorted({t for t in _family_tokens(text)
                         if t.startswith(SPEC_FAMILY_PREFIXES)})
        for token in tokens:
            if not _resolve_family(token, families):
                problems.append(
                    f"{label}: references {token!r} which matches no "
                    f"family in {METRICS_PATH} FAMILIES — the "
                    "dashboard/runbook watches a spec-decode metric "
                    "the code does not emit")
        if not any(t.startswith("dl4j_tpu_serving_spec_")
                   for t in tokens):
            problems.append(
                f"{label}: no dl4j_tpu_serving_spec_* family "
                "referenced — the speculative-decode accept rate has "
                "no dashboard/runbook surface, so a draft-quality "
                "regression lands unwatched")
    return problems


_GAP_TOKEN_RE = None


def _parse_gap_keys(devtime_path: Path) -> Optional[set]:
    """``GAP_KEYS`` tuple literal from ``obs/devtime.py`` — AST only.
    None when the file/tuple is absent (synthetic trees)."""
    if not devtime_path.is_file():
        return None
    tree = ast.parse(devtime_path.read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "GAP_KEYS"
                for t in node.targets):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                return {e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)}
    return None


def _scope_call(chain: str) -> bool:
    parts = chain.split(".")
    return (parts[-1] == "scope" and "devtime" in parts) or \
        parts[-1] == "named_scope"


def _lint_devtime_scopes(package_dir: Path,
                         tools_dir: Optional[Path],
                         docs_dir: Optional[Path]) -> List[str]:
    """Rule 8: annotation points annotated, devtime family block
    present, and consumer ``gap.<key>`` tokens resolve against
    GAP_KEYS."""
    global _GAP_TOKEN_RE
    problems: List[str] = []
    for rel, fn_names in sorted(SCOPE_SITES.items()):
        path = package_dir / rel
        if not path.is_file():
            continue                # synthetic tree: nothing to hold
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue                # rule-agnostic: lint_file reports it
        for want in fn_names:
            found = annotated = False
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and \
                        node.name == want:
                    found = True
                    if any(_scope_call(_attr_chain(c.func))
                           for c in _calls(node)):
                        annotated = True
            if not found:
                problems.append(
                    f"{rel}: SCOPE_SITES names function {want!r} "
                    "which no longer exists — update the rule-8 "
                    "table to the renamed annotation point")
            elif not annotated:
                problems.append(
                    f"{rel}: {want}() carries no devtime.scope / "
                    "jax.named_scope — device-time attribution loses "
                    "this path's layers (every op lands in the "
                    "unattributed op:* bucket)")
    families = _parse_families(package_dir / METRICS_PATH)
    devtime_keys = _parse_gap_keys(package_dir / DEVTIME_PATH)
    if (package_dir / DEVTIME_PATH).is_file() and families is not None:
        if not any(f.startswith("dl4j_tpu_devtime_")
                   for f in families):
            problems.append(
                f"{METRICS_PATH}: no dl4j_tpu_devtime_* family in "
                "FAMILIES — the device-time observatory has no "
                "metric surface (the block was deleted?)")
    if devtime_keys is None:
        return problems
    if _GAP_TOKEN_RE is None:
        import re
        _GAP_TOKEN_RE = re.compile(r"\bgap\.([a-z_]+)")
    consumers = []
    if tools_dir is not None and (Path(tools_dir)
                                  / "tpu_watch.py").is_file():
        consumers.append(("tools/tpu_watch.py",
                          (Path(tools_dir) / "tpu_watch.py")
                          .read_text()))
    if docs_dir is not None and (Path(docs_dir) / "OPS.md").is_file():
        consumers.append(("docs/OPS.md",
                          (Path(docs_dir) / "OPS.md").read_text()))
    for label, text in consumers:
        for token in sorted(set(_GAP_TOKEN_RE.findall(text))):
            if token not in devtime_keys:
                problems.append(
                    f"{label}: references gap-report key "
                    f"'gap.{token}' which is not in {DEVTIME_PATH} "
                    "GAP_KEYS — the runbook/dashboard is reading a "
                    "column the gap report does not emit")
    return problems


def _parse_kernel_registry(path: Path) -> Optional[dict]:
    """``{kernel: {field: str | tuple}}`` from the KERNEL_REGISTRY
    dict literal — AST only. None when the file/table is absent
    (synthetic trees)."""
    if not path.is_file():
        return None
    tree = ast.parse(path.read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            named = any(isinstance(t, ast.Name)
                        and t.id == "KERNEL_REGISTRY"
                        for t in node.targets)
        elif isinstance(node, ast.AnnAssign):   # KERNEL_REGISTRY: ... =
            named = (isinstance(node.target, ast.Name)
                     and node.target.id == "KERNEL_REGISTRY"
                     and node.value is not None)
        else:
            continue
        if named:
            if not isinstance(node.value, ast.Dict):
                continue
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and isinstance(v, ast.Dict)):
                    continue
                entry = {}
                for fk, fv in zip(v.keys, v.values):
                    if not (isinstance(fk, ast.Constant)
                            and isinstance(fk.value, str)):
                        continue
                    if isinstance(fv, ast.Constant):
                        entry[fk.value] = fv.value
                    elif isinstance(fv, (ast.Tuple, ast.List)):
                        entry[fk.value] = tuple(
                            e.value for e in fv.elts
                            if isinstance(e, ast.Constant))
                out[k.value] = entry
            return out
    return None


def _is_pallas_call(chain: str) -> bool:
    return chain == "pallas_call" or chain.endswith(".pallas_call")


def _public_kernels(tree: ast.AST):
    """Public kernel surface of one ops module: non-underscore
    module-level functions that reach a ``pallas_call`` directly or
    through PRIVATE (underscore) module-level helpers — reachability
    stops at public functions, so a bench helper calling the public
    kernels is a consumer, not a kernel. Returns
    ``{fn_name: scope_literals_emitted_inside}``."""
    fns = {n.name: n for n in tree.body
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    direct = {}
    callees = {}
    for name, node in fns.items():
        chains = [_attr_chain(c.func) for c in _calls(node)]
        direct[name] = any(_is_pallas_call(ch) for ch in chains)
        # module-local calls appear as bare names
        callees[name] = {ch for ch in chains if ch in fns}

    def reaches(name, seen=()):
        if direct.get(name):
            return True
        if name in seen:
            return False
        for g in callees.get(name, ()):
            if g.startswith("_") and reaches(g, seen + (name,)):
                return True
        return False

    out = {}
    for name, node in fns.items():
        if name.startswith("_") or not reaches(name):
            continue
        scopes = set()
        for c in _calls(node):
            if _scope_call(_attr_chain(c.func)) and c.args and \
                    isinstance(c.args[0], ast.Constant) and \
                    isinstance(c.args[0].value, str):
                scopes.add(c.args[0].value)
        out[name] = scopes
    return out


def _lint_kernel_registry(package_dir: Path,
                          tests_dir: Optional[Path]) -> List[str]:
    """Rule 9 (see module doc): pallas containment + registry/kernel
    lockstep + fallback/parity/scope resolution."""
    problems: List[str] = []
    ops_dir = package_dir / OPS_DIR
    # (a) no pallas_call outside ops/
    for path in sorted(package_dir.rglob("*.py")):
        rel = path.relative_to(package_dir).as_posix()
        if rel.startswith(OPS_DIR + "/"):
            continue
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue                # rule-agnostic: lint_file reports it
        for c in _calls(tree):
            if _is_pallas_call(_attr_chain(c.func)):
                problems.append(
                    f"{rel}:{c.lineno}: raw pl.pallas_call outside "
                    f"{OPS_DIR}/ — kernels live in the ops library "
                    "behind the dispatch-gate/fallback/parity "
                    "contract (ARCHITECTURE §17); move it there and "
                    "register it in ops/kernel_registry.py")
    registry = _parse_kernel_registry(
        package_dir / KERNEL_REGISTRY_PATH)
    if not ops_dir.is_dir():
        return problems
    # public kernels per ops module
    module_kernels: dict = {}      # rel -> {fn: scopes}
    any_pallas = False
    for path in sorted(ops_dir.glob("*.py")):
        rel = f"{OPS_DIR}/{path.name}"
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue
        if any(_is_pallas_call(_attr_chain(c.func))
               for c in _calls(tree)):
            any_pallas = True
        module_kernels[rel] = _public_kernels(tree)
    if registry is None:
        if any_pallas:
            problems.append(
                f"{KERNEL_REGISTRY_PATH}: missing (or no "
                "KERNEL_REGISTRY dict literal) while ops/ contains "
                "Pallas kernels — the kernel library has no "
                "fallback/parity/scope contract")
        return problems
    declared_by_module: dict = {}
    for kname, entry in registry.items():
        declared_by_module.setdefault(entry.get("module", ""),
                                      {})[kname] = entry
    # a registry entry pointing at a module that doesn't exist would
    # otherwise skip every per-module check below — dead entries must
    # be flagged no matter how they died
    for mod in sorted(set(declared_by_module) - set(module_kernels)):
        for kname in sorted(declared_by_module[mod]):
            problems.append(
                f"{KERNEL_REGISTRY_PATH}: entry {kname!r} declares "
                f"module {mod!r} which is not an ops/ module — stale "
                "registry entry (moved/removed/typo'd module path?)")
    for rel, kernels in sorted(module_kernels.items()):
        declared = declared_by_module.get(rel, {})
        for fn in sorted(set(kernels) - set(declared)):
            problems.append(
                f"{rel}: public kernel {fn}() reaches pallas_call but "
                f"has no KERNEL_REGISTRY entry in "
                f"{KERNEL_REGISTRY_PATH} — undeclared kernels ship "
                "without a fallback/parity/scope contract")
        for kname in sorted(set(declared) - set(kernels)):
            problems.append(
                f"{KERNEL_REGISTRY_PATH}: entry {kname!r} names no "
                f"public kernel in {rel} — stale registry entry "
                "(renamed/removed kernel?)")
        # per-entry contract
        mod_tree = ast.parse((package_dir / rel).read_text())
        defs = {n.name for n in ast.walk(mod_tree)
                if isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef))}
        for kname in sorted(set(declared) & set(kernels)):
            entry = declared[kname]
            fb = entry.get("fallback")
            if not fb or fb not in defs:
                problems.append(
                    f"{KERNEL_REGISTRY_PATH}: kernel {kname!r} "
                    f"declares fallback {fb!r} which is not a "
                    f"function in {rel} — the gate-off path has no "
                    "value-identical XLA implementation")
            parity = entry.get("parity", "")
            if tests_dir is not None and Path(tests_dir).is_dir():
                ok = False
                if "::" in parity:
                    tfile, tname = parity.split("::", 1)
                    tpath = Path(tests_dir) / Path(tfile).name
                    ok = tpath.is_file() and \
                        f"def {tname}" in tpath.read_text()
                if not ok:
                    problems.append(
                        f"{KERNEL_REGISTRY_PATH}: kernel {kname!r} "
                        f"parity reference {parity!r} resolves to no "
                        "test — an unverified kernel's outputs drift "
                        "silently from its fallback")
            scope_lit = entry.get("scope")
            if not scope_lit or scope_lit not in kernels[kname]:
                problems.append(
                    f"{KERNEL_REGISTRY_PATH}: kernel {kname!r} "
                    f"declares scope {scope_lit!r} but {kname}() in "
                    f"{rel} never emits it via devtime.scope — its "
                    "device time lands unattributed")
            site_fns = SCOPE_SITES.get(rel, ())
            if kname not in site_fns:
                problems.append(
                    f"{KERNEL_REGISTRY_PATH}: kernel {kname!r} is not "
                    f"listed in SCOPE_SITES[{rel!r}] "
                    "(tools/lint_instrumentation.py) — rule 8 cannot "
                    "keep its devtime scope from disappearing")
    return problems


def _lint_comm_observatory(package_dir: Path,
                           tools_dir: Optional[Path],
                           docs_dir: Optional[Path]) -> List[str]:
    """Rule 11 (see module doc): collective emissions scoped, comm
    family block present, comm consumer tokens resolve, and tpu_watch
    actually watches the plane."""
    problems: List[str] = []
    for rel in COLLECTIVE_SCOPE_PATHS:
        path = package_dir / rel
        if not path.is_file():
            continue                # synthetic tree: nothing to hold
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue                # rule-agnostic: lint_file reports it
        # a collective call is covered when ANY enclosing function
        # (ast.walk of an outer def sees nested defs' calls too)
        # carries a devtime.scope / named_scope call
        covered = set()
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            coll = [c for c in _calls(node)
                    if _attr_chain(c.func).split(".")[-1]
                    in COLLECTIVE_EMITTERS]
            if coll and any(_scope_call(_attr_chain(c.func))
                            for c in _calls(node)):
                covered.update(id(c) for c in coll)
        for c in _calls(tree):
            ch = _attr_chain(c.func)
            if ch.split(".")[-1] not in COLLECTIVE_EMITTERS or \
                    id(c) in covered:
                continue
            problems.append(
                f"{rel}:{c.lineno}: collective emission ({ch}) outside "
                "any devtime.scope / jax.named_scope-carrying function "
                "— the communication observatory's wire ledger can "
                "only attribute these bytes to the anonymous op:* "
                "bucket; wrap the emitting phase in a devtime scope")
    families = _parse_families(package_dir / METRICS_PATH)
    if not (package_dir / COMMTIME_PATH).is_file() or families is None:
        return problems
    if not any(f.startswith(COMM_FAMILY_PREFIX) for f in families):
        problems.append(
            f"{METRICS_PATH}: no {COMM_FAMILY_PREFIX}* family in "
            "FAMILIES — the communication observatory has no metric "
            "surface (the block was deleted?)")
    consumers = []
    if tools_dir is not None and (Path(tools_dir)
                                  / "tpu_watch.py").is_file():
        consumers.append(("tools/tpu_watch.py",
                          (Path(tools_dir) / "tpu_watch.py")
                          .read_text()))
    if docs_dir is not None and (Path(docs_dir) / "OPS.md").is_file():
        consumers.append(("docs/OPS.md",
                          (Path(docs_dir) / "OPS.md").read_text()))
    for label, text in consumers:
        tokens = sorted({t for t in _family_tokens(text)
                         if t.startswith(COMM_FAMILY_PREFIX)})
        for token in tokens:
            if not _resolve_family(token, families):
                problems.append(
                    f"{label}: references {token!r} which matches no "
                    f"family in {METRICS_PATH} FAMILIES — the "
                    "dashboard/runbook watches a comm metric the code "
                    "does not emit")
        if label == "tools/tpu_watch.py" and not tokens:
            problems.append(
                f"{label}: no {COMM_FAMILY_PREFIX}* family referenced "
                "— the wire-byte/link-utilization plane has no "
                "dashboard surface, so a wire-bound regression lands "
                "unwatched")
    return problems


# rule 12: the elastic serving fleet module, the metric-family
# prefixes of its routing/supervision plane, and the call names that
# count as acquiring a membership lease
FLEET_PATH = "serving/fleet.py"
FLEET_FAMILY_PREFIXES = ("dl4j_tpu_router_", "dl4j_tpu_serving_fleet_")
LEASE_CALLS = frozenset({"renew", "start_auto_renew"})


def _lint_serving_fleet(package_dir: Path,
                        tools_dir: Optional[Path],
                        docs_dir: Optional[Path]) -> List[str]:
    """Rule 12 (see module doc): STARTUP_PREFETCH mirrors
    WARMUP_FEEDS, ServingReplica.start warms before it leases, the
    router/fleet metric surface exists with live emit sites, fleet
    consumer tokens resolve, and tpu_watch watches the router."""
    fleet = package_dir / FLEET_PATH
    if not fleet.is_file():
        return []
    try:
        tree = ast.parse(fleet.read_text())
    except SyntaxError:
        return []                   # rule-agnostic: lint_file reports it
    problems: List[str] = []

    # -- prefetch table mirrors the scheduler's warmup feeds ----------
    prefetch: Optional[set] = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "STARTUP_PREFETCH"
                for t in node.targets):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                prefetch = {e.value for e in node.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)}
    if prefetch is None:
        problems.append(
            f"{FLEET_PATH}: no module-level STARTUP_PREFETCH tuple "
            "literal — the replica spawn path has no declared AOT "
            "prefetch table, so a cold respawn's first request traces "
            "live")
    feeds: Optional[set] = None
    sched = package_dir / SCHEDULER_PATH
    if sched.is_file():
        try:
            stree = ast.parse(sched.read_text())
        except SyntaxError:
            stree = None            # rule-agnostic: lint_file reports it
        if stree is not None:
            for node in ast.walk(stree):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name)
                        and t.id == "WARMUP_FEEDS"
                        for t in node.targets) and \
                        isinstance(node.value, ast.Dict):
                    feeds = {k.value for k in node.value.keys
                             if isinstance(k, ast.Constant)
                             and isinstance(k.value, str)}
    if prefetch is not None and feeds is not None:
        for b in sorted(feeds - prefetch):
            problems.append(
                f"{FLEET_PATH}: scheduler builder {b} is missing from "
                "STARTUP_PREFETCH — a respawned replica passes the "
                "readiness gate with that entry point cold and its "
                "first live request stalls on a trace")
        for b in sorted(prefetch - feeds):
            problems.append(
                f"{FLEET_PATH}: STARTUP_PREFETCH entry {b!r} names no "
                f"WARMUP_FEEDS builder in {SCHEDULER_PATH} — stale "
                "prefetch entry (renamed/removed entry point?)")

    # -- warm-before-lease ordering inside ServingReplica.start -------
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef)
                and node.name == "ServingReplica"):
            continue
        for fn in node.body:
            if not (isinstance(fn, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))
                    and fn.name == "start"):
                continue
            warm = [c.lineno for c in _calls(fn)
                    if _attr_chain(c.func).split(".")[-1] == "warmup"]
            lease = [c.lineno for c in _calls(fn)
                     if _attr_chain(c.func).split(".")[-1]
                     in LEASE_CALLS]
            if not warm:
                problems.append(
                    f"{FLEET_PATH}: ServingReplica.start never calls "
                    "warmup() — replicas take leases cold and the "
                    "router routes live traffic onto untraced entry "
                    "points")
            elif lease and min(lease) < min(warm):
                problems.append(
                    f"{FLEET_PATH}:{min(lease)}: ServingReplica.start "
                    "acquires its membership lease before warmup() — "
                    "the router sees the replica as live while every "
                    "entry point is still cold; warm first, lease "
                    "last")

    # -- metric surface + consumer coverage ---------------------------
    families = _parse_families(package_dir / METRICS_PATH)
    if families is None:
        return problems
    emits = _family_emit_sites(package_dir)
    for prefix in FLEET_FAMILY_PREFIXES:
        if not any(f.startswith(prefix) for f in families):
            problems.append(
                f"{METRICS_PATH}: no {prefix}* family in FAMILIES — "
                "the serving-fleet plane has no metric surface (the "
                "block was deleted?)")
    for fam in sorted(f for f in families
                      if f.startswith(FLEET_FAMILY_PREFIXES)):
        if fam not in emits:
            problems.append(
                f"{METRICS_PATH}: fleet family {fam!r} is declared "
                "but never emitted — the router/supervisor path that "
                "fed it was deleted and the fleet dashboard reads a "
                "dead column")
    consumers = []
    if tools_dir is not None and (Path(tools_dir)
                                  / "tpu_watch.py").is_file():
        consumers.append(("tools/tpu_watch.py",
                          (Path(tools_dir) / "tpu_watch.py")
                          .read_text()))
    if docs_dir is not None and (Path(docs_dir) / "OPS.md").is_file():
        consumers.append(("docs/OPS.md",
                          (Path(docs_dir) / "OPS.md").read_text()))
    for label, text in consumers:
        tokens = sorted({t for t in _family_tokens(text)
                         if t.startswith(FLEET_FAMILY_PREFIXES)})
        for token in tokens:
            if not _resolve_family(token, families):
                problems.append(
                    f"{label}: references {token!r} which matches no "
                    f"family in {METRICS_PATH} FAMILIES — the "
                    "dashboard/runbook watches a fleet metric the "
                    "code does not emit")
        if label == "tools/tpu_watch.py" and not any(
                t.startswith("dl4j_tpu_router_") for t in tokens):
            problems.append(
                f"{label}: no dl4j_tpu_router_* family referenced — "
                "the routing plane has no dashboard surface, so "
                "structural sheds and re-route storms land unwatched")
    return problems


def run(package_dir: Path = PACKAGE,
        tests_dir: Optional[Path] = None,
        tools_dir: Optional[Path] = None,
        docs_dir: Optional[Path] = None) -> List[str]:
    problems: List[str] = []
    for path in sorted(package_dir.rglob("*.py")):
        rel = path.relative_to(package_dir).as_posix()
        problems.extend(lint_file(path, rel))
    if package_dir == PACKAGE:
        if tests_dir is None:
            tests_dir = REPO / "tests"
        if tools_dir is None:
            tools_dir = REPO / "tools"
        if docs_dir is None:
            docs_dir = REPO / "docs"
    problems.extend(_lint_fault_sites(package_dir, tests_dir))
    problems.extend(_lint_metric_families(package_dir, tools_dir,
                                          docs_dir))
    problems.extend(_lint_serving_jits(package_dir))
    problems.extend(_lint_spec_decode(package_dir, tools_dir,
                                      docs_dir))
    problems.extend(_lint_devtime_scopes(package_dir, tools_dir,
                                         docs_dir))
    problems.extend(_lint_kernel_registry(package_dir, tests_dir))
    problems.extend(_lint_comm_observatory(package_dir, tools_dir,
                                           docs_dir))
    problems.extend(_lint_serving_fleet(package_dir, tools_dir,
                                        docs_dir))
    return problems


def main() -> int:
    problems = run()
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} instrumentation lint violation(s)")
        return 1
    print("instrumentation lint: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
