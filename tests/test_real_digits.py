"""REAL handwritten-digit accuracy (BASELINE honesty item): the env
has no egress so MNIST cannot be fetched; the checked-in UCI digits
(real human handwriting) carry the real-data accuracy claim instead.
The synthetic-MNIST path must keep labeling itself synthetic."""
import numpy as np

from deeplearning4j_tpu.data import RealDigitsDataSetIterator
from deeplearning4j_tpu.data.digits import load_real_digits
from deeplearning4j_tpu.eval_ import Evaluation
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                          OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn import updaters as upd


def test_real_digits_are_real():
    x, y = load_real_digits(train=True)
    xt, yt = load_real_digits(train=False)
    # 1797 genuine samples, disjoint deterministic split
    assert len(x) + len(xt) == 1797
    assert x.shape[1:] == (8, 8, 1) and y.shape[1] == 10
    # real data: every class present in both splits
    assert set(y.argmax(1)) == set(range(10))
    assert set(yt.argmax(1)) == set(range(10))


def test_small_cnn_reaches_95pct_on_real_digits():
    """The reference's 'LeNet >= 99% on real MNIST' claim, scaled to
    the real data actually available offline."""
    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(upd.Adam(learning_rate=2e-3))
            .weight_init_fn("xavier").list()
            .layer(ConvolutionLayer(n_out=16, kernel_size=(3, 3),
                                    padding="SAME", activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=64, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 1)).build())
    net = MultiLayerNetwork(conf).init()
    it = RealDigitsDataSetIterator(batch_size=64, train=True)
    for _ in range(30):
        net.fit(it)
    xt, yt = load_real_digits(train=False)
    ev = Evaluation()
    ev.eval(yt, np.asarray(net.output(xt)))
    assert ev.accuracy() >= 0.95, ev.accuracy()


def test_synthetic_mnist_labels_itself():
    from deeplearning4j_tpu.data.mnist import MnistDataSetIterator
    it = MnistDataSetIterator(batch_size=32, train=True, n_examples=64)
    assert it.synthetic is True     # no real MNIST files in this env
