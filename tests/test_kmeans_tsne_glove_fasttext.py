"""KMeansClustering, BarnesHutTsne, Glove, FastText.

Reference analogs: KMeansTest (nearestneighbor-core), TsneTest
(deeplearning4j-tsne), GloveTest / FastTextTest (deeplearning4j-nlp).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (KMeansClustering,
                                           BarnesHutTsne)
from deeplearning4j_tpu.nlp import Glove, FastText


def _blobs(rng, n_per=30, centers=((0, 0), (6, 6), (0, 6))):
    pts, labs = [], []
    for i, c in enumerate(centers):
        pts.append(rng.randn(n_per, 2) * 0.4 + np.asarray(c))
        labs += [i] * n_per
    return np.concatenate(pts).astype(np.float32), np.asarray(labs)


class TestKMeans:
    def test_recovers_blobs(self):
        rng = np.random.RandomState(0)
        x, labs = _blobs(rng)
        km = KMeansClustering.setup(3, 50)
        cs = km.apply_to(x)
        # every true cluster maps to one dominant predicted cluster
        for i in range(3):
            assign = cs.assignments[labs == i]
            dominant = np.bincount(assign).max()
            assert dominant / len(assign) > 0.95
        assert cs.inertia() < 100.0
        assert len(cs.get_clusters()) == 3

    def test_predict_consistent(self):
        rng = np.random.RandomState(1)
        x, _ = _blobs(rng)
        km = KMeansClustering.setup(3, 30)
        cs = km.apply_to(x)
        again = km.predict(x)
        assert np.array_equal(cs.assignments, again)

    def test_cosine_distance_mode(self):
        rng = np.random.RandomState(2)
        x, _ = _blobs(rng)
        cs = KMeansClustering.setup(3, 20,
                                    distance="cosine").apply_to(x + 1.0)
        assert len(np.unique(cs.assignments)) >= 2


class TestTsne:
    def test_separates_blobs(self):
        rng = np.random.RandomState(0)
        # two well-separated 10-D clusters
        a = rng.randn(25, 10) * 0.3
        b = rng.randn(25, 10) * 0.3 + 5.0
        x = np.concatenate([a, b]).astype(np.float32)
        tsne = (BarnesHutTsne.builder().perplexity(10.0)
                .set_max_iter(250).number_of_dimensions(2).seed(0)
                .build())
        y = tsne.fit(x)
        assert y.shape == (50, 2)
        assert np.all(np.isfinite(y))
        # embedded cluster centers far apart vs intra-cluster spread
        ca, cb = y[:25].mean(0), y[25:].mean(0)
        spread = max(y[:25].std(), y[25:].std())
        assert np.linalg.norm(ca - cb) > 2 * spread
        assert tsne.get_data() is y


_CORPUS = ["the cat sat on the mat",
           "the dog sat on the log",
           "the cat chased the dog",
           "a dog and a cat played",
           "the mat was on the floor",
           "cats and dogs are pets"] * 6


class TestGlove:
    def test_trains_and_looks_up(self):
        g = (Glove.builder().layer_size(16).epochs(40)
             .min_word_frequency(1).learning_rate(0.05).seed(0).build())
        g.fit(_CORPUS)
        v = g.get_word_vector("cat")
        assert v is not None and v.shape == (16,)
        assert np.isfinite(g.similarity("cat", "dog"))
        nearest = g.words_nearest("cat", 3)
        assert len(nearest) == 3 and "cat" not in nearest

    def test_unknown_word(self):
        g = Glove(layer_size=8, epochs=2)
        g.fit(_CORPUS)
        assert g.get_word_vector("zebra") is None


class TestFastText:
    def test_supervised_classification(self):
        texts = (["good great excellent wonderful nice"] * 10
                 + ["bad terrible awful horrible poor"] * 10)
        labels = ["pos"] * 10 + ["neg"] * 10
        ft = (FastText.builder().supervised().dim(16).epochs(30)
              .learning_rate(0.5).seed(0).build())
        ft.fit(texts, labels)
        assert ft.predict("excellent wonderful") == "pos"
        assert ft.predict("terrible awful") == "neg"
        probs = ft.predict_probability("great nice")
        assert abs(sum(probs.values()) - 1.0) < 1e-5
        assert probs["pos"] > probs["neg"]

    def test_oov_word_vector(self):
        ft = FastText(supervised=True, dim=8, epochs=1)
        ft.fit(["hello world", "goodbye world"], ["a", "b"])
        v = ft.get_word_vector("helloo")     # OOV: subword composition
        assert v.shape == (8,)
        # shares subwords with an in-vocab word -> correlated vectors
        assert ft.similarity("hello", "helloo") > \
            ft.similarity("hello", "xyzzyq")

    def test_unsupervised_mode(self):
        ft = FastText(dim=12, epochs=2, min_count=1)
        ft.fit(_CORPUS)
        assert ft.get_word_vector("cat").shape == (12,)
        assert np.isfinite(ft.similarity("cat", "dog"))
