"""Test config: force CPU with 8 virtual devices BEFORE jax backends
initialize.

Mirrors the reference test strategy (SURVEY §4): same suite over every
backend — here the suite runs on CPU (x8 virtual devices for SPMD
tests); the driver separately compile-checks the TPU path.

NOTE on this environment: a sitecustomize hook registers the 'axon' TPU
plugin at interpreter startup and calls
``jax.config.update("jax_platforms", "axon,cpu")``, overriding any
JAX_PLATFORMS env var. Re-update the config here (backends are not yet
initialized when conftest loads) so tests never touch the TPU tunnel —
axon init is slow, serializes across processes, and would make every op
a remote dispatch.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(12345)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: round-end harness fences (subprocess bench/dossier "
        "runs, ~8 min); deselect with -m 'not slow' for quick loops")


# -- jax capability gates shared by the SPMD test files -----------------------
# This box's jaxlib predates jax.shard_map / jax.typeof / lax.pcast (the
# seed errored at collection on the files using them); on the TPU image's
# modern jax both markers are no-ops and the suites run in full.
requires_modern_jax = pytest.mark.skipif(
    not hasattr(jax, "typeof"),
    reason="ring/zigzag sequence-parallel needs jax.typeof/lax.pcast")
requires_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="SPMD suite skipped on pre-shard_map jax (tier-1 budget)")
