"""Word2Vec/ParagraphVectors, tokenizers, VPTree/KDTree/kNN, DeepWalk
(reference: Word2VecTests, VPTreeTest, DeepWalkGradientCheck)."""
import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (BruteForceNearestNeighbors,
                                           KDTree, VPTree)
from deeplearning4j_tpu.graphnn import DeepWalk, Graph
from deeplearning4j_tpu.nlp import (CommonPreprocessor,
                                    DefaultTokenizerFactory,
                                    ParagraphVectors, VocabCache,
                                    Word2Vec, WordVectorSerializer)


# --- tokenization / vocab ---------------------------------------------------

def test_tokenizer_preprocessor():
    tf = DefaultTokenizerFactory().set_token_pre_processor(
        CommonPreprocessor())
    toks = tf.create("Hello, World! 123 foo-bar").get_tokens()
    assert toks == ["hello", "world", "foo-bar"]


def test_vocab_build_and_noise():
    streams = [["a", "b", "a", "c"], ["a", "b", "rare"]]
    vc = VocabCache.build(streams, min_word_frequency=2)
    assert len(vc) == 2
    assert vc.index_of("a") == 0          # most frequent first
    assert "rare" not in vc
    noise = vc.noise_distribution()
    assert noise.shape == (2,)
    np.testing.assert_allclose(noise.sum(), 1.0)


# --- word2vec ---------------------------------------------------------------

def _toy_corpus():
    """Two topic clusters; co-occurring words should embed nearby."""
    rng = np.random.default_rng(0)
    animals = ["cat", "dog", "horse", "cow"]
    foods = ["apple", "bread", "cheese", "rice"]
    sents = []
    for _ in range(300):
        group = animals if rng.random() < 0.5 else foods
        sents.append(" ".join(rng.choice(group, size=6)))
    return sents


def test_word2vec_skipgram_learns_clusters():
    w2v = (Word2Vec.builder().layer_size(24).window_size(3)
           .min_word_frequency(1).negative_sample(4).epochs(3)
           .learning_rate(0.05).seed(1).batch_size(256).build())
    w2v.fit(_toy_corpus())
    assert w2v.has_word("cat") and w2v.has_word("apple")
    assert w2v.get_word_vector("cat").shape == (24,)
    same = w2v.similarity("cat", "dog")
    cross = w2v.similarity("cat", "apple")
    assert same > cross, (same, cross)
    nearest = w2v.words_nearest("cat", top_n=3)
    assert set(nearest) <= {"dog", "horse", "cow"}


def test_word2vec_cbow_runs():
    w2v = (Word2Vec.builder().layer_size(16).window_size(2)
           .min_word_frequency(1).negative_sample(3).epochs(2)
           .elements_learning_algorithm("CBOW").seed(2)
           .batch_size(128).build())
    w2v.fit(_toy_corpus()[:100])
    assert w2v.similarity("cat", "cat") == pytest.approx(1.0)
    assert np.isfinite(w2v.similarity("cat", "bread"))


def test_word2vec_serializer_roundtrip(tmp_path):
    w2v = (Word2Vec.builder().layer_size(12).min_word_frequency(1)
           .epochs(1).seed(3).build())
    w2v.fit(_toy_corpus()[:50])
    p = str(tmp_path / "w2v.zip")
    WordVectorSerializer.write_word2vec_model(w2v, p)
    back = WordVectorSerializer.read_word2vec_model(p)
    assert set(back.vocab.words()) == set(w2v.vocab.words())
    for w in ("cat", "apple"):
        if w2v.has_word(w):
            np.testing.assert_allclose(back.get_word_vector(w),
                                       w2v.get_word_vector(w),
                                       atol=1e-5)


def test_paragraph_vectors_dbow():
    docs = {
        "animals_1": "cat dog horse cow cat dog",
        "animals_2": "dog cow horse cat cow horse",
        "foods_1": "apple bread cheese rice apple bread",
        "foods_2": "bread rice apple cheese rice cheese",
    }
    pv = ParagraphVectors(layer_size=16, min_word_frequency=1,
                          negative=4, epochs=30, learning_rate=0.05,
                          seed=4, batch_size=64)
    pv.fit_documents(list(docs), list(docs.values()))
    va1 = pv.get_doc_vector("animals_1")
    assert va1.shape == (16,)

    def cos(a, b):
        return a @ b / (np.linalg.norm(a) * np.linalg.norm(b))

    va2 = pv.get_doc_vector("animals_2")
    vf1 = pv.get_doc_vector("foods_1")
    assert cos(va1, va2) > cos(va1, vf1)
    inferred = pv.infer_vector("cat horse dog")
    assert inferred.shape == (16,)
    assert np.isfinite(inferred).all()


# --- nearest neighbors ------------------------------------------------------

@pytest.fixture(scope="module")
def cloud():
    rng = np.random.default_rng(5)
    return rng.normal(size=(200, 8)).astype(np.float32)


def _exact_knn(points, q, k):
    d = np.linalg.norm(points - q, axis=1)
    idx = np.argsort(d)[:k]
    return list(idx), list(d[idx])


def test_vptree_matches_exact(cloud):
    tree = VPTree(cloud, "euclidean")
    rng = np.random.default_rng(6)
    for _ in range(5):
        q = rng.normal(size=8).astype(np.float32)
        got_idx, got_d = tree.search(q, 7)
        want_idx, want_d = _exact_knn(cloud, q, 7)
        np.testing.assert_allclose(sorted(got_d), sorted(want_d),
                                   rtol=1e-5)
        assert set(got_idx) == set(want_idx)


def test_vptree_cosine(cloud):
    tree = VPTree(cloud, "cosine")
    idx, d = tree.search(cloud[0], 1)
    assert idx[0] == 0 and d[0] < 1e-6


def test_kdtree_matches_exact(cloud):
    tree = KDTree(cloud)
    rng = np.random.default_rng(7)
    for _ in range(5):
        q = rng.normal(size=8).astype(np.float32)
        got_idx, got_d = tree.knn(q, 5)
        want_idx, want_d = _exact_knn(cloud, q, 5)
        np.testing.assert_allclose(got_d, want_d, rtol=1e-5)
        assert got_idx == want_idx
    nn_i, nn_d = tree.nn(cloud[3])
    assert nn_i == 3 and nn_d < 1e-6


def test_bruteforce_device_knn(cloud):
    knn = BruteForceNearestNeighbors(cloud, "euclidean")
    q = cloud[10] + 1e-4
    idx, d = knn.knn(q, 3)
    assert idx[0] == 10
    want_idx, want_d = _exact_knn(cloud, q, 3)
    assert set(idx) == set(want_idx)


# --- deepwalk ---------------------------------------------------------------

def test_deepwalk_two_cliques():
    """Vertices inside a clique should embed closer than across the
    single bridge edge."""
    g = Graph(10)
    for i in range(5):
        for j in range(i + 1, 5):
            g.add_edge(i, j)
            g.add_edge(i + 5, j + 5)
    g.add_edge(0, 5)      # bridge
    dw = DeepWalk(vector_size=16, window_size=3, walk_length=20,
                  walks_per_vertex=8, epochs=2, seed=8)
    dw.fit(g)
    assert dw.get_vertex_vector(1).shape == (16,)
    intra = dw.similarity(1, 2)
    inter = dw.similarity(1, 7)
    assert intra > inter, (intra, inter)
    assert set(dw.verts_nearest(2, 3)) <= {0, 1, 3, 4, 5}
