"""ZeRO-style sharded weight update (ISSUE 6, arxiv 2004.13336) on the
8-virtual-device CPU mesh: trajectory equivalence vs the replicated
SYNC path, the bitwise scatter/gather fence, the replica-lockstep
(param divergence == 0) fence, sharded optimizer-state footprint and
init-sharded guarantees, donation hygiene, warmup coverage, and the
sharded checkpoint round trip.

Equivalence note: the sharded update IS the replicated update in exact
arithmetic (scatter-sum ≡ all-reduce-sum elementwise; ``/n`` is an
exact power-of-two scale; the optimizer is elementwise on shards).
Bit-equality across the two *separately compiled* XLA programs is not
a property XLA grants — fusion/FMA choices differ per program and per
buffer shape, measured at ≤1 ulp/step on this backend — so the
trajectory test pins a tight float band while the in-program
scatter/gather-vs-pmean fence and the cross-replica param-divergence
fence assert the bit-level invariants that ARE guaranteed.
"""
import gc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn import MultiLayerNetwork, \
    NeuralNetConfiguration
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.parallel import (FlatShardLayout,
                                         ParallelWrapper,
                                         per_device_bytes)
from deeplearning4j_tpu.parallel._compat import (shard_map,
                                                 supports_psum_scatter)

pytestmark = [
    pytest.mark.skipif(len(jax.devices()) < 8,
                       reason="needs 8 virtual devices"),
    pytest.mark.skipif(not supports_psum_scatter(),
                       reason="this jax cannot express "
                              "psum_scatter/all_gather"),
]

N = 8


def _net(seed=42, gradient_normalization=None):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(upd.Adam(learning_rate=0.05)))
    if gradient_normalization:
        b = b.gradient_normalization(gradient_normalization)
    conf = (b.list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _toy_data(n=256, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    return DataSet(x, y)


def test_sharded_matches_replicated_trajectory():
    """≥10 steps of sharded-update training stay on the replicated
    SYNC trajectory leaf-for-leaf (identical in exact arithmetic;
    float-rounding band across the two XLA programs — see module
    doc), with bit-identical reported losses."""
    ds = _toy_data()
    net_a = _net()
    wa = ParallelWrapper.builder(net_a).workers(N).build()
    net_b = _net()
    wb = (ParallelWrapper.builder(net_b).workers(N)
          .sharded_update(True).build())
    wa.fit(ListDataSetIterator(ds, batch_size=64), epochs=3)   # 12 steps
    wb.fit(ListDataSetIterator(ds, batch_size=64), epochs=3)
    assert net_a.iteration == net_b.iteration == 12
    assert net_a.score_ == pytest.approx(net_b.score_, rel=1e-5,
                                         abs=1e-7)
    for lname in net_a.params:
        for k in net_a.params[lname]:
            np.testing.assert_allclose(
                np.asarray(net_a.params[lname][k]),
                np.asarray(net_b.params[lname][k]),
                rtol=1e-4, atol=1e-6, err_msg=f"{lname}/{k}")


def test_scatter_gather_grads_bitwise_equal_pmean():
    """In ONE program, the layout's reduce-scatter → mean → all-gather
    round trip is BITWISE the gradient ``pmean`` it replaces: scatter
    and all-reduce accumulate in the same order, and ``/n`` is an
    exact power-of-two scale."""
    from jax.sharding import PartitionSpec as P
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"data": N})
    params = {"l0": {"W": jax.random.normal(jax.random.PRNGKey(0),
                                            (5, 13)),
                     "b": jnp.zeros((13,))}}
    layout = FlatShardLayout(params, N)
    rng = np.random.default_rng(3)
    g_global = jax.tree.map(
        lambda p: jnp.asarray(rng.normal(
            size=(N,) + p.shape)).astype(p.dtype), params)

    def f(g):
        g = jax.tree.map(lambda a: a[0], g)     # this replica's grads
        pm = jax.tree.map(lambda a: jax.lax.pmean(a, "data"), g)
        rt = layout.gather(layout.scatter_mean(g, "data"), "data")
        return pm, rt

    pm, rt = jax.jit(shard_map(
        f, mesh=mesh, in_specs=(P("data"),), out_specs=(P(), P()),
        check_vma=False))(g_global)
    for a, b in zip(jax.tree.leaves(pm), jax.tree.leaves(rt)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_param_replica_divergence_exactly_zero():
    """The ZeRO lockstep fence: under sharded updates the diagnostic
    step's per-replica POST-GATHER param-norm spread is exactly 0.0
    (all replicas reassemble identical params), while the PR 4
    grad-norm replica divergence stays alive (> 0: replicas see
    different shards)."""
    ds = _toy_data(n=128)
    net = _net(seed=3)
    net.monitor_numerics(every=1)
    w = ParallelWrapper(net, workers=N, sharded_update=True)
    w.fit(ListDataSetIterator(ds, batch_size=64), epochs=2)
    num = net.last_numerics
    assert set(num["param_replica_divergence"]) == set(num["grad_norm"])
    assert all(v == 0.0
               for v in num["param_replica_divergence"].values())
    assert max(num["replica_divergence"].values()) > 0
    from deeplearning4j_tpu.obs import numerics as on
    snap = on.PARAM_REPLICA_DIVERGENCE.snapshot()
    assert snap and all(v == 0.0 for v in snap.values())


def test_opt_state_born_sharded_and_one_nth_footprint():
    """The optimizer state is initialized directly as 1/N shards
    (``P('data')`` moment leaves — never materialized replicated) and
    its per-device footprint is ~1/N of the replicated layout."""
    net = _net()
    w = ParallelWrapper(net, workers=N, sharded_update=True)
    w._prepare()
    from jax.sharding import PartitionSpec as P
    sharded_leaves = [
        l for l in jax.tree.leaves(w._dp_state) if l.ndim >= 1]
    assert sharded_leaves
    for leaf in sharded_leaves:
        assert leaf.sharding.spec == P("data"), leaf.sharding
        assert len(leaf.addressable_shards) == N
        assert leaf.addressable_shards[0].data.shape[0] \
            == leaf.shape[0] // N
    rep = per_device_bytes(net.opt_state)
    sh = per_device_bytes(w._dp_state, N)
    assert 0.08 < sh / rep < 0.2, (sh, rep)      # 1/8 + scalar counts
    # the footprint gauge reflects the active (sharded) layout
    from deeplearning4j_tpu.obs.metrics import OPT_STATE_BYTES
    snap = OPT_STATE_BYTES.snapshot()
    got = [v for k, v in snap.items() if "sharded" in k]
    assert got == [sh], snap


def test_sharded_update_rejects_cross_tree_grad_norm():
    """Per-layer / global-norm gradient clipping reduces across
    elements the shard doesn't hold — refused up front, not silently
    computed over 1/N slices."""
    net = _net(gradient_normalization="ClipL2PerParamType")
    w = ParallelWrapper(net, workers=N, sharded_update=True)
    with pytest.raises(ValueError, match="sharded_update"):
        w._prepare()
    with pytest.raises(ValueError, match="SYNC"):
        ParallelWrapper(_net(), workers=N,
                        mode=ParallelWrapper.AVERAGING,
                        sharded_update=True)


def test_warmup_covers_sharded_steps_and_feeds_table():
    """``warmup()`` AOT-compiles the sharded step AND its diagnostic
    sibling from batch-sharded abstract shapes: the first real fit
    batch dispatches to the warmed executables (aot_hits), tracing
    nothing new at dispatch time."""
    from deeplearning4j_tpu.perf import sentry
    from deeplearning4j_tpu.perf.warmup import WarmupSpec

    net = _net(seed=11)
    net.monitor_numerics(every=2)
    w = ParallelWrapper(net, workers=N, sharded_update=True)
    rep = w.warmup([WarmupSpec(features=(64, 4), labels=(64, 2))])
    assert rep["compiled"] == 2          # step + diag sibling
    w.fit(ListDataSetIterator(_toy_data(n=64), batch_size=64),
          epochs=2)
    st = sentry.stats()
    assert st["ParallelWrapper.sync_sharded_step"]["aot_hits"] >= 1
    assert st["ParallelWrapper.sync_sharded_diag_step"]["aot_hits"] >= 1
    # the feed table rule 4 enforces really does cover every builder
    from deeplearning4j_tpu.parallel import wrapper as wmod
    builders = {name for name in dir(ParallelWrapper)
                if name.startswith("_build_") and name.endswith("_step")}
    assert builders == set(wmod.WARMUP_FEEDS)


@pytest.mark.parametrize("mode", [ParallelWrapper.AVERAGING,
                                  ParallelWrapper.ASYNC])
def test_carried_state_donation_no_buffer_growth(mode):
    """Donation audit regression: every carried tree (params, opt
    state, layer state, accumulator state) is donated, so repeated
    steps reuse buffers instead of doubling live arrays."""
    net = _net(seed=9)
    w = ParallelWrapper(net, workers=N, mode=mode)
    w._prepare()
    x = jnp.asarray(_toy_data(n=64).features)
    y = jnp.asarray(_toy_data(n=64).labels)
    rng = jax.random.PRNGKey(0)

    def step(state):
        if mode == ParallelWrapper.ASYNC:
            p, o, a = state[:3]
            p, o, s, a, _ = w._step(p, o, state[3], a, x, y, rng)
            return (p, o, a, s)
        p, o = state[:2]
        p, o, s, _ = w._step(p, o, state[2], x, y, rng,
                             jnp.asarray(0, jnp.int32))
        return (p, o, s)

    state = w._dp_state + (net.state,)
    state = step(step(state))            # build + settle layouts
    gc.collect()
    n0 = len(jax.live_arrays())
    for _ in range(4):
        state = step(state)
    gc.collect()
    n1 = len(jax.live_arrays())
    assert n1 <= n0 + 2, (n0, n1)


def test_restore_nulled_dp_state_rebuilds_resume_exact(tmp_path):
    """``FaultTolerantTrainer._restore`` nulls ``_dp_state`` after
    restoring the net; the next ``fit`` must rebuild the shards FROM
    the restored ``net.opt_state`` (not re-init zeros) — a zip-saved
    mid-run checkpoint resumes onto the uninterrupted trajectory
    bit-exactly."""
    from deeplearning4j_tpu.serialization import ModelSerializer

    ds = _toy_data(n=64, seed=2)
    it = lambda: ListDataSetIterator(ds, batch_size=64)
    net_a = _net(seed=31)
    wa = ParallelWrapper(net_a, workers=N, sharded_update=True)
    wa.fit(it(), epochs=5)
    # zip export mid-run folds the LIVE shards (ModelSerializer
    # consults the ownership backref), not the stale init moments
    ModelSerializer.write_model(net_a, tmp_path / "mid.zip",
                                save_updater=True)
    wa.fit(it(), epochs=5)                       # uninterrupted ref
    net_b = ModelSerializer.restore_multi_layer_network(
        tmp_path / "mid.zip")
    assert any(np.any(np.asarray(l) != 0)
               for l in jax.tree.leaves(net_b.opt_state))
    wb = ParallelWrapper(net_b, workers=N, sharded_update=True)
    wb.fit(it(), epochs=5)                       # resumed 5 + 5
    for pa, pb in zip(jax.tree.leaves(net_a.params),
                      jax.tree.leaves(net_b.params)):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))
    # the _restore-style reset itself: next fit rebuilds, no crash
    wb._dp_state = None
    wb.fit(it(), epochs=1)
    assert np.isfinite(net_b.score_)


def test_sharded_checkpoint_roundtrip(tmp_path):
    """``save_wrapper``/``restore_wrapper``: the ZeRO optimizer shards
    checkpoint per device and restore onto the same topology (moment
    leaves come back ``P('data')``-sharded), and the resumed run
    continues the uninterrupted trajectory."""
    from jax.sharding import PartitionSpec as P
    from deeplearning4j_tpu.serialization import ShardedCheckpointer

    ds = _toy_data(n=128, seed=5)
    it = lambda: ListDataSetIterator(ds, batch_size=64)
    net_a = _net(seed=21)
    wa = ParallelWrapper(net_a, workers=N, sharded_update=True)
    wa.fit(it(), epochs=2)                       # 4 steps
    with ShardedCheckpointer(tmp_path / "ck", async_save=False) as ck:
        ck.save_wrapper(net_a.iteration, wa, wait=True)
        wa.fit(it(), epochs=2)                   # reference: 4 more
        net_b = _net(seed=99)                    # different init
        wb = ParallelWrapper(net_b, workers=N, sharded_update=True)
        ck.restore_wrapper(wb)
    assert net_b.iteration == 4
    for leaf in jax.tree.leaves(wb._dp_state):
        if leaf.ndim >= 1:
            assert leaf.sharding.spec == P("data"), leaf.sharding
    wb.fit(it(), epochs=2)
    for lname in net_a.params:
        for k in net_a.params[lname]:
            np.testing.assert_allclose(
                np.asarray(net_a.params[lname][k]),
                np.asarray(net_b.params[lname][k]),
                rtol=1e-6, atol=1e-7, err_msg=f"{lname}/{k}")
