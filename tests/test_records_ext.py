"""New record readers: SVMLight, Jackson JSON-lines, File,
TransformProcess wrapper, SequenceRecordReaderDataSetIterator.

Reference analogs: SVMLightRecordReaderTest, JacksonLineRecordReaderTest,
RecordReaderDataSetiteratorTest (sequence alignment cases).
"""
import numpy as np

from deeplearning4j_tpu.data import (
    CSVSequenceRecordReader, FileRecordReader, JacksonLineRecordReader,
    SVMLightRecordReader, TransformProcessRecordReader,
    SequenceRecordReaderDataSetIterator,
)
from deeplearning4j_tpu.data.transform import Schema, TransformProcess


def test_svmlight_reader():
    text = "1 1:0.5 3:2.0\n0 2:1.5  # comment\n"
    recs = list(SVMLightRecordReader(text, num_features=4))
    assert recs[0][:4] == [0.5, 0.0, 2.0, 0.0]
    assert recs[0][4] == 1
    assert recs[1][:4] == [0.0, 1.5, 0.0, 0.0]
    assert recs[1][4] == 0


def test_svmlight_zero_based():
    recs = list(SVMLightRecordReader("1 0:7.0", num_features=2,
                                     zero_based=True))
    assert recs[0][:2] == [7.0, 0.0]


def test_jackson_line_reader():
    text = '{"a": 1, "b": "x"}\n{"a": 2, "b": "y", "c": 9}\n'
    recs = list(JacksonLineRecordReader(text, fields=["b", "a"]))
    assert recs == [["x", 1], ["y", 2]]


def test_file_record_reader(tmp_path):
    p1 = tmp_path / "f1.txt"
    p1.write_text("hello")
    p2 = tmp_path / "f2.txt"
    p2.write_text("world")
    recs = list(FileRecordReader([p1, p2]))
    assert recs == [["hello"], ["world"]]


def test_transform_process_record_reader():
    from deeplearning4j_tpu.data.records import CollectionRecordReader
    schema = (Schema.builder()
              .add_column_double("x")
              .add_column_categorical("cat", ["a", "b"]).build())
    tp = (TransformProcess.builder(schema)
          .categorical_to_integer("cat").build())
    rr = TransformProcessRecordReader(
        CollectionRecordReader([[1.0, "a"], [2.0, "b"]]), tp)
    recs = list(rr)
    assert recs == [[1.0, 0], [2.0, 1]]


def _seq_sources():
    # two sequences of different lengths, label is last column
    s1 = "0.1,0.2,0\n0.3,0.4,1\n0.5,0.6,0\n"
    s2 = "0.7,0.8,1\n0.9,1.0,1\n"
    return [s1, s2]


def test_sequence_iterator_single_reader():
    reader = CSVSequenceRecordReader(_seq_sources())
    it = SequenceRecordReaderDataSetIterator(
        reader, batch_size=2, num_classes=2, label_index=-1)
    ds = next(iter(it))
    assert ds.features.shape == (2, 3, 2)          # padded to T=3
    assert ds.labels.shape == (2, 3, 2)
    assert np.allclose(ds.features_mask, [[1, 1, 1], [1, 1, 0]])
    assert np.allclose(ds.features[0, 1], [0.3, 0.4])
    assert np.allclose(ds.labels[0, 1], [0, 1])    # one-hot of 1
    # padding rows are zero
    assert float(ds.features[1, 2].sum()) == 0


def test_sequence_iterator_two_readers():
    feats = ["0.1,0.2\n0.3,0.4\n", "0.5,0.6\n"]
    labs = ["1\n0\n", "1\n"]
    it = SequenceRecordReaderDataSetIterator(
        CSVSequenceRecordReader(feats), batch_size=2, num_classes=2,
        labels_reader=CSVSequenceRecordReader(labs))
    ds = next(iter(it))
    assert ds.features.shape == (2, 2, 2)
    assert np.allclose(ds.labels[0, 0], [0, 1])
    assert np.allclose(ds.labels[0, 1], [1, 0])
    assert np.allclose(ds.features_mask, [[1, 1], [1, 0]])


def test_sequence_iterator_regression():
    srcs = ["1,2,0.5\n3,4,0.7\n"]
    it = SequenceRecordReaderDataSetIterator(
        CSVSequenceRecordReader(srcs), batch_size=1, regression=True,
        label_index=-1)
    ds = next(iter(it))
    assert ds.labels.shape == (1, 2, 1)
    assert np.allclose(ds.labels[0, :, 0], [0.5, 0.7])


def test_sequence_iterator_trains_rnn():
    """End-to-end: masked sequence batches train an RNN classifier."""
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn import updaters as upd

    rng = np.random.RandomState(0)
    sources = []
    for i in range(8):
        t = rng.randint(2, 5)
        rows = []
        for _ in range(t):
            lab = i % 2
            base = 1.0 if lab else -1.0
            rows.append(f"{base + rng.randn()*0.1:.3f},"
                        f"{base + rng.randn()*0.1:.3f},{lab}")
        sources.append("\n".join(rows) + "\n")
    it = SequenceRecordReaderDataSetIterator(
        CSVSequenceRecordReader(sources), batch_size=8, num_classes=2)
    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(upd.Adam(learning_rate=1e-2)).list()
            .layer(LSTM(n_out=8, activation="tanh"))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(2)).build())
    net = MultiLayerNetwork(conf).init()
    ds = next(iter(it))
    s0 = net.score(ds)
    net.fit(it, epochs=25)
    assert net.score(ds) < s0


def test_sequence_iterator_align_end():
    reader = CSVSequenceRecordReader(_seq_sources())
    it = SequenceRecordReaderDataSetIterator(
        reader, batch_size=2, num_classes=2, label_index=-1,
        alignment_mode="ALIGN_END")
    ds = next(iter(it))
    # shorter sequence (len 2, padded to 3) is right-aligned: last
    # timestep is real data, first is padding
    assert np.allclose(ds.features_mask, [[1, 1, 1], [0, 1, 1]])
    assert np.allclose(ds.features[1, 1], [0.7, 0.8])
    assert float(ds.features[1, 0].sum()) == 0
