"""TF GraphDef import conformance (reference: TFGraphTestAllSameDiff —
import a TF graph, execute, compare to TF-produced outputs)."""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.modelimport.tf_import import TFImporter  # noqa: E402


def _freeze(fn, *specs):
    """Concrete-trace fn, fold variables to constants, return
    (graph_def, input names, output names)."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)

    conc = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(conc)
    gd = frozen.graph.as_graph_def()
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    out_names = [t.name.split(":")[0] for t in frozen.outputs]
    return gd, in_names, out_names


def _check(fn, args, rtol=1e-4, atol=1e-5):
    specs = [tf.TensorSpec(a.shape, a.dtype) for a in args]
    gd, in_names, out_names = _freeze(fn, *specs)
    ref = fn(*[tf.constant(a) for a in args])
    if not isinstance(ref, (list, tuple)):
        ref = [ref]
    sd, vars_ = TFImporter.import_graph_def(gd, out_names)
    feed = {n: a for n, a in zip(in_names, args)}
    out_vars = [vars_[n] for n in out_names]
    res = sd.output(feed, out_vars)
    for o, r in zip(out_vars, ref):
        np.testing.assert_allclose(res[o.name], np.asarray(r),
                                   rtol=rtol, atol=atol)


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(3)


def test_mlp(rng):
    w1 = tf.Variable(rng.normal(size=(10, 16)).astype(np.float32) * 0.3)
    b1 = tf.Variable(np.zeros(16, np.float32))
    w2 = tf.Variable(rng.normal(size=(16, 4)).astype(np.float32) * 0.3)

    def fn(x):
        h = tf.nn.relu(tf.matmul(x, w1) + b1)
        return tf.nn.softmax(tf.matmul(h, w2))

    _check(fn, [rng.normal(size=(5, 10)).astype(np.float32)])


def test_elementwise_chain(rng):
    def fn(a, b):
        c = tf.exp(tf.minimum(a, 2.0)) / (tf.abs(b) + 1.0)
        d = tf.sqrt(tf.square(a) + 1e-3) - tf.tanh(b)
        return c * d + tf.math.erf(a) - tf.math.rsqrt(tf.abs(b) + 1.0)

    x = rng.normal(size=(3, 7)).astype(np.float32)
    y = rng.normal(size=(3, 7)).astype(np.float32)
    _check(fn, [x, y])


def test_reductions_and_shapes(rng):
    def fn(x):
        m = tf.reduce_mean(x, axis=[1, 2], keepdims=True)
        s = tf.reduce_sum(x - m, axis=-1)
        r = tf.reshape(s, [-1, 4])
        t = tf.transpose(r, [1, 0])
        return tf.concat([t, t * 2.0], axis=0)

    _check(fn, [rng.normal(size=(2, 4, 6)).astype(np.float32)])


def test_cnn(rng):
    k1 = tf.Variable(rng.normal(size=(3, 3, 2, 8)).astype(np.float32) * 0.2)
    gamma = tf.Variable(np.ones(8, np.float32))
    beta = tf.Variable(np.zeros(8, np.float32))
    mean = tf.Variable(rng.normal(size=8).astype(np.float32) * 0.1)
    var = tf.Variable(np.abs(rng.normal(size=8)).astype(np.float32) + 0.5)

    def fn(x):
        y = tf.nn.conv2d(x, k1, strides=[1, 1, 1, 1], padding="SAME")
        y, _, _ = tf.compat.v1.nn.fused_batch_norm(
            y, gamma, beta, mean, var, epsilon=1e-3, is_training=False)
        y = tf.nn.relu6(y)
        y = tf.nn.max_pool2d(y, 2, 2, padding="VALID")
        return tf.reduce_mean(y, axis=[1, 2])

    _check(fn, [rng.normal(size=(2, 8, 8, 2)).astype(np.float32)])


def test_depthwise_conv(rng):
    k = tf.Variable(rng.normal(size=(3, 3, 4, 2)).astype(np.float32) * 0.2)

    def fn(x):
        return tf.nn.depthwise_conv2d(x, k, strides=[1, 1, 1, 1],
                                      padding="VALID")

    _check(fn, [rng.normal(size=(2, 6, 6, 4)).astype(np.float32)])


def test_attention_like(rng):
    wq = tf.Variable(rng.normal(size=(8, 8)).astype(np.float32) * 0.3)
    wk = tf.Variable(rng.normal(size=(8, 8)).astype(np.float32) * 0.3)

    def fn(x):
        q = tf.matmul(x, wq)
        k = tf.matmul(x, wk)
        scores = tf.matmul(q, k, transpose_b=True) / 8.0 ** 0.5
        attn = tf.nn.softmax(scores)
        return tf.matmul(attn, x)

    _check(fn, [rng.normal(size=(4, 6, 8)).astype(np.float32)])


def test_slicing_padding(rng):
    def fn(x):
        a = x[:, 1:5:2, :]
        b = tf.pad(a, [[0, 0], [1, 1], [0, 0]])
        c = tf.stack([b, b * 2.0], axis=1)
        d = tf.squeeze(tf.expand_dims(c, -1), axis=-1)
        return tf.tile(d[:, 0], [1, 2, 1])

    _check(fn, [rng.normal(size=(2, 6, 3)).astype(np.float32)])


def test_gather_argmax_cast(rng):
    table = tf.Variable(rng.normal(size=(12, 5)).astype(np.float32))

    def fn(idx):
        e = tf.gather(table, idx, axis=0)
        am = tf.argmax(e, axis=-1)
        return tf.cast(am, tf.float32) + tf.reduce_max(e, axis=-1)

    _check(fn, [rng.integers(0, 12, size=(3, 4)).astype(np.int32)])


def test_finetune_trainable_consts(rng):
    """Frozen weights marked trainable become VARIABLEs and receive
    gradients (the BERT-fine-tune import pattern)."""
    w = tf.Variable(rng.normal(size=(6, 3)).astype(np.float32) * 0.4)

    def fn(x):
        return tf.reduce_sum(tf.nn.softmax(tf.matmul(x, w)) ** 2)

    x = rng.normal(size=(4, 6)).astype(np.float32)
    gd, in_names, out_names = _freeze(fn, tf.TensorSpec(x.shape, x.dtype))
    wname = next(n.name for n in gd.node if n.op == "Const"
                 and _np_shape(n) == (6, 3))
    sd, vars_ = TFImporter.import_graph_def(gd, trainable=[wname])
    assert vars_[wname].vtype == "VARIABLE"
    sd.set_loss_variables(vars_[out_names[0]])
    grads = sd.calculate_gradients({in_names[0]: x}, [wname])
    assert grads[wname].shape == (6, 3)
    assert np.abs(grads[wname]).sum() > 0


def _np_shape(node):
    from tensorflow.python.framework import tensor_util
    return tensor_util.MakeNdarray(node.attr["value"].tensor).shape


def test_prunes_unreachable_unsupported_branch(rng):
    """Side branches not feeding the requested outputs must not abort
    the import (reference ImportGraph prunes to outputs)."""
    from tensorflow.core.framework import graph_pb2

    def fn(x):
        return tf.nn.relu(x) + 1.0

    x = rng.normal(size=(3, 4)).astype(np.float32)
    gd, in_names, out_names = _freeze(fn, tf.TensorSpec(x.shape, x.dtype))
    # splice in an unreachable dynamic-shape side branch (the freezer
    # dead-code-eliminates one written in the fn itself)
    dead = gd.node.add()
    dead.name = "dead/TensorArray"
    dead.op = "TensorArrayV3"
    dead.input.append(in_names[0])
    assert any(n.op == "TensorArrayV3" for n in gd.node)
    with pytest.raises(ValueError, match="unsupported TF op"):
        TFImporter.import_graph_def(gd)            # unpruned: fails
    sd, vars_ = TFImporter.import_graph_def(gd, out_names)
    res = sd.output({in_names[0]: x}, [vars_[out_names[0]]])
    np.testing.assert_allclose(list(res.values())[0],
                               np.maximum(x, 0) + 1.0, rtol=1e-6)


def test_deep_chain_no_recursion_limit(rng):
    """Sequential chains far deeper than the Python recursion limit
    import fine (iterative toposort)."""
    def fn(x):
        y = x
        for _ in range(1500):
            y = y + 1.0
        return y

    x = rng.normal(size=(2, 3)).astype(np.float32)
    gd, in_names, out_names = _freeze(fn, tf.TensorSpec(x.shape, x.dtype))
    sd, vars_ = TFImporter.import_graph_def(gd, out_names)
    res = sd.output({in_names[0]: x}, [vars_[out_names[0]]])
    np.testing.assert_allclose(list(res.values())[0], x + 1500.0,
                               rtol=1e-4)


def test_gradients_through_imported_graph(rng):
    """Imported graphs are differentiable (the reference needed explicit
    doDiff per imported op; here jax.grad covers the whole trace)."""
    w = tf.Variable(rng.normal(size=(6, 3)).astype(np.float32) * 0.4)

    def fn(x):
        return tf.reduce_sum(tf.nn.softmax(tf.matmul(x, w)) ** 2)

    x = rng.normal(size=(4, 6)).astype(np.float32)
    specs = [tf.TensorSpec(x.shape, x.dtype)]
    gd, in_names, out_names = _freeze(fn, *specs)
    sd, vars_ = TFImporter.import_graph_def(gd)
    sd.set_loss_variables(vars_[out_names[0]])
    grads = sd.calculate_gradients({in_names[0]: x}, [in_names[0]])

    with tf.GradientTape() as tape:
        xt = tf.constant(x)
        tape.watch(xt)
        loss = fn(xt)
    ref = tape.gradient(loss, xt)
    np.testing.assert_allclose(grads[in_names[0]], np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_einsum_attention_block(rng):
    """Transformer-style einsum path (newer BERT exports)."""
    wq = tf.Variable(rng.normal(size=(8, 8)).astype(np.float32) * 0.3)

    def fn(q, k, v):
        qh = tf.einsum("btf,fh->bth", q, wq)
        scores = tf.einsum("bqh,bkh->bqk", qh, k) / 8.0 ** 0.5
        attn = tf.nn.softmax(scores, axis=-1)
        return tf.einsum("bqk,bkh->bqh", attn, v)

    _check(fn, [rng.normal(size=(2, 5, 8)).astype(np.float32)
                for _ in range(3)])


def test_comparison_select_onehot(rng):
    def fn(x, ids):
        mask = tf.cast(tf.greater(x, 0.0), tf.float32)
        sel = tf.where(tf.less(x, 1.0), x * 2.0, x)
        oh = tf.one_hot(ids, depth=5)
        return sel * mask + oh

    _check(fn, [rng.normal(size=(4, 5)).astype(np.float32),
                rng.integers(0, 5, (4,)).astype(np.int32)])


def test_split_concat_roundtrip(rng):
    def fn(x):
        a, b, c = tf.split(x, 3, axis=1)
        return tf.concat([c, a, b], axis=1) + x

    _check(fn, [rng.normal(size=(2, 9)).astype(np.float32)])


def test_unstack_stack(rng):
    def fn(x):
        rows = tf.unstack(x, axis=1)
        return tf.stack(rows[::-1], axis=1)

    _check(fn, [rng.normal(size=(2, 4, 3)).astype(np.float32)])


def test_slice_and_band_part(rng):
    def fn(x):
        s = tf.slice(x, [0, 1, 0], [-1, 3, -1])
        causal = tf.linalg.band_part(tf.ones((3, 3)), -1, 0)
        return tf.einsum("btf,ts->bsf", s, causal)

    _check(fn, [rng.normal(size=(2, 5, 4)).astype(np.float32)])


def test_cumsum_variants(rng):
    def fn(x):
        return (tf.cumsum(x, axis=1)
                + tf.cumsum(x, axis=1, exclusive=True)
                + tf.cumsum(x, axis=1, reverse=True))

    _check(fn, [rng.normal(size=(3, 6)).astype(np.float32)])


def test_topk_values(rng):
    def fn(x):
        vals, idx = tf.math.top_k(x, k=3)
        return vals + tf.cast(idx, tf.float32) * 0.001

    _check(fn, [rng.normal(size=(4, 10)).astype(np.float32)])


def test_shape_driven_reshape(rng):
    def fn(x):
        s = tf.shape(x)
        flat = tf.reshape(x, [s[0], -1])
        return tf.reduce_sum(flat, axis=1)

    # static input shape -> Shape folds to a const at import
    _check(fn, [rng.normal(size=(3, 4, 5)).astype(np.float32)])
