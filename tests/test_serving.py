"""Continuous-batching serving gateway (serving/ — ISSUE 13).

The three fences this file owns:

- **pager correctness**: paged decode (float AND int8 pages) is
  TOKEN-IDENTICAL to dense ``generate()`` for the same prompts/seed —
  continuous batching must never change what a request returns;
- **pager invariants**: no page owned by two live sequences, free-list
  conservation under admit/evict churn, trash page out of circulation;
- **serving semantics**: fixed-shape zero-retrace decode after
  warmup, admission control on free pages, queue-full/deadline
  shedding, graceful drain, tenant fairness, fault-shed without a
  wedged slot or leaked page, and the continuous-vs-request-at-a-time
  throughput acceptance.
"""
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.inference import (DeadlineExpiredError,
                                                   QueueFullError,
                                                   ServingShutdownError)
from deeplearning4j_tpu.serving import (DecodeScheduler, KVPager,
                                        PageTableError, SequenceAborted,
                                        ServingGateway)
from deeplearning4j_tpu.zoo import GPTNano
from deeplearning4j_tpu.zoo.gpt import CausalTransformerLM, prompt_bucket


def _tiny_model(**kw):
    """2-layer/32-hidden LM: fast compiles for the scheduling tests
    (the identity fences use GPTNano to cover GQA + 4 layers)."""
    kw.setdefault("vocab_size", 64)
    return CausalTransformerLM(hidden=32, n_layers=2, n_heads=2,
                               n_kv_heads=1,
                               max_len=kw.pop("max_len", 64),
                               seed=kw.pop("seed", 9), **kw)


@pytest.fixture(scope="module")
def tiny():
    model = _tiny_model()
    return model, model.init()


class _Req:
    """Minimal duck-typed request for driving DecodeScheduler
    directly (no gateway thread — deterministic churn tests)."""

    def __init__(self, prompt, max_new, temperature=None, eos_id=None):
        self.prompt = np.asarray(prompt, np.int32)
        self.max_new = max_new
        self.temperature = temperature
        self.eos_id = eos_id
        self.tokens = []
        self.done = False
        self.error = None

    def push(self, tok):
        self.tokens.append(int(tok))

    def finish(self):
        self.done = True

    def fail(self, e):
        self.error = e
        self.done = True


# =========================================================================
# pager-correctness fence: paged decode == dense generate(), token for
# token (float and int8 pages), across staggered admissions
# =========================================================================

# int8 rides the slow lane (~17s of fresh-GPTNano compiles vs tier-1's
# 870s wall-clock budget); tier-1 int8 paged identity stays fenced by
# test_int8_pages_roundtrip_token_for_token
@pytest.mark.parametrize("cache_quant", [
    None, pytest.param("int8", marks=pytest.mark.slow)])
def test_paged_decode_token_identical_to_dense(cache_quant):
    model = GPTNano(vocab_size=64, max_len=64, seed=7,
                    cache_quant=cache_quant)
    net = model.init()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 64, t).astype(np.int32)
               for t in (5, 17, 9, 30, 3, 22)]
    budgets = [10, 4, 16, 8, 12, 6]
    dense = [np.asarray(model.generate(net, p[None], n_new=n))[0]
             for p, n in zip(prompts, budgets)]
    # 3 slots for 6 requests: admissions stagger mid-decode, every
    # slot serves sequences at different positions/buckets — the
    # continuous batch must still reproduce every dense output exactly
    gw = ServingGateway(model, net, max_slots=3, block=8,
                        max_context=64)
    gw.warmup(prompt_lens=(3, 5, 9, 17, 22, 30))
    streams = [gw.submit(p, max_new=n)
               for p, n in zip(prompts, budgets)]
    for st, d in zip(streams, dense):
        got = st.result(timeout=120)
        np.testing.assert_array_equal(got, d)
    gw._sched.pager.check_invariants()
    assert gw._sched.pager.free_pages() == gw._sched.pager.n_pages - 1
    gw.shutdown()


# =========================================================================
# pager invariants
# =========================================================================

def test_pager_alloc_release_conservation():
    pager = KVPager(n_layers=2, n_kv_heads=1, head_dim=16, n_pages=9,
                    block=8, cache_quant=None)
    a, b = object(), object()
    pa = pager.alloc(3, a)
    pb = pager.alloc(4, b)
    assert len(pa) == 3 and len(pb) == 4
    assert 0 not in pa + pb                  # trash page reserved
    assert not set(pa) & set(pb)             # disjoint owners
    assert pager.free_pages() == 1
    assert pager.alloc(2, object()) is None  # exhausted -> refused
    assert pager.free_pages() == 1           # refusal takes nothing
    pager.check_invariants()
    assert pager.release(a) == 3
    assert pager.free_pages() == 4
    assert pager.release(b) == 4
    assert pager.free_pages() == 8           # full conservation
    pager.check_invariants()


def test_pager_detects_double_ownership():
    pager = KVPager(n_layers=1, n_kv_heads=1, head_dim=8, n_pages=5,
                    block=8, cache_quant=None)
    a, b = object(), object()
    pa = pager.alloc(2, a)
    pager.alloc(1, b)
    # corrupt the table the way a scheduler bug would
    pager._pages_of[id(b)].append(pa[0])
    with pytest.raises(PageTableError, match="two live sequences"):
        pager.check_invariants()


def test_pager_invariants_under_admit_evict_churn(tiny):
    """Seeded random admit/step/evict churn with the invariant check
    after EVERY transition: no shared pages, no leaks, full free-list
    conservation once drained."""
    model, net = tiny
    sched = DecodeScheduler(model, net, max_slots=3, block=8,
                            max_context=32, n_pages=10)
    sched.warmup(prompt_lens=range(1, 17))
    rng = np.random.default_rng(4)
    live = []
    for it in range(120):
        op = rng.integers(0, 3)
        if op == 0:
            r = _Req(rng.integers(0, 64, int(rng.integers(1, 17))),
                     int(rng.integers(1, 9)))
            if sched.can_admit(r.prompt.size, r.max_new):
                assert sched.admit(r)
                if not r.done:
                    live.append(r)
        elif op == 1:
            sched.step()
        elif live:
            sched.evict(live.pop(int(rng.integers(0, len(live)))))
        live = [r for r in live if not r.done]
        sched.pager.check_invariants()
    while any(s is not None for s in sched._slots):
        sched.step()
        sched.pager.check_invariants()
    assert sched.pager.free_pages() == sched.pager.n_pages - 1


def test_int8_pages_roundtrip_token_for_token(tiny):
    """Satellite: int8 page storage must reproduce the dense int8-KV
    decode path token-for-token on a fixed seed (the quantiser is
    shared — ``_quant_kv`` — so codes and scales are bit-equal)."""
    model = _tiny_model(cache_quant="int8", seed=11)
    net = model.init()
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, 64, 13).astype(np.int32)
    dense = np.asarray(model.generate(net, prompt[None], n_new=14))[0]
    sched = DecodeScheduler(model, net, max_slots=2, block=8,
                            max_context=64)
    sched.warmup(prompt_lens=(13,))
    r = _Req(prompt, 14)
    assert sched.admit(r)
    while not r.done:
        sched.step()
    np.testing.assert_array_equal(
        np.concatenate([prompt, np.asarray(r.tokens, np.int32)]),
        dense)


# =========================================================================
# fixed-shape contract: zero retraces after warmup
# =========================================================================

def test_zero_retraces_after_warmup(tiny):
    from deeplearning4j_tpu.perf import sentry
    model, net = tiny
    gw = ServingGateway(model, net, max_slots=3, block=8,
                        max_context=32, default_max_new=6)
    gw.warmup(prompt_lens=range(1, 25))
    before = sentry.total_traces()
    rng = np.random.default_rng(1)
    with sentry.strict():
        streams = [gw.submit(rng.integers(0, 64, int(t)), max_new=6)
                   for t in rng.integers(1, 25, 12)]
        for st in streams:
            st.result(timeout=120)
    assert sentry.total_traces() == before, \
        "continuous-batching traffic retraced after warmup"
    gw.shutdown()


def test_gateway_and_generate_share_bucket_table():
    """Satellite: the gateway's prefill buckets come from the same
    module-level helper generate()/warmup_decode use — drift here
    would be a guaranteed retrace on the first live request."""
    model = _tiny_model()
    assert model._bucket(5) == prompt_bucket(5) == 16
    assert prompt_bucket(17) == 32
    assert prompt_bucket(40, 48) == 48          # max_len clamp
    net = model.init()
    sched = DecodeScheduler(model, net, max_slots=2, block=16,
                            max_context=64)
    warm = sched.warmup(prompt_lens=range(1, 33))
    want = sorted({prompt_bucket(t, 64) for t in range(1, 33)})
    assert warm["buckets"] == want


# =========================================================================
# gateway serving semantics (shed / deadline / drain / fairness)
# =========================================================================

def test_queue_full_sheds_fast(tiny):
    from deeplearning4j_tpu.obs import metrics
    model, net = tiny
    # worker never started: the queue fills deterministically
    gw = ServingGateway(model, net, max_slots=2, block=8,
                        max_context=32, queue_limit=3,
                        default_max_new=4, start=False)
    p = np.zeros(4, np.int32)
    for _ in range(3):
        gw.submit(p)
    t0 = time.perf_counter()
    with pytest.raises(QueueFullError):
        gw.submit(p)
    assert time.perf_counter() - t0 < 0.5       # shed, not blocked
    shed = metrics.SERVING_SHED.labels(reason="queue_full")
    assert shed.get() >= 1


def test_deadline_sheds_unadmitted_requests(tiny):
    model, net = tiny
    gw = ServingGateway(model, net, max_slots=1, block=8,
                        max_context=64, default_max_new=4)
    gw.warmup(prompt_lens=(4,))
    blocker = gw.submit(np.zeros(4, np.int32), max_new=40)
    # explicit 0 deadline = already expired (the `is not None`
    # falsy-deadline contract): must shed, never serve
    doomed = gw.submit(np.zeros(4, np.int32), deadline_s=0.0)
    with pytest.raises(DeadlineExpiredError):
        doomed.result(timeout=30)
    assert blocker.result(timeout=120).shape == (44,)
    gw.shutdown()


def test_shutdown_drains_inflight_and_flushes_queue(tiny):
    model, net = tiny
    gw = ServingGateway(model, net, max_slots=1, block=8,
                        max_context=64, default_max_new=24)
    gw.warmup(prompt_lens=(4,))
    running = gw.submit(np.zeros(4, np.int32))
    # wait until it is admitted (first token streamed)
    for _ in range(500):
        if running.n_generated():
            break
        time.sleep(0.01)
    queued = [gw.submit(np.zeros(4, np.int32)) for _ in range(2)]
    dropped = gw.shutdown(drain=True)
    assert dropped == 2
    assert running.result(timeout=30).shape == (28,)  # drained to end
    for st in queued:
        with pytest.raises(ServingShutdownError):
            st.result(timeout=5)
    with pytest.raises(ServingShutdownError):
        gw.submit(np.zeros(4, np.int32))
    assert gw._sched.pager.free_pages() == gw._sched.pager.n_pages - 1


def test_tenant_round_robin_fairness(tiny):
    """One chatty tenant must not starve another: with one slot, a
    flood from tenant A and a late pair from tenant B interleave, so
    both B requests serve before A's tail."""
    model, net = tiny
    gw = ServingGateway(model, net, max_slots=1, block=8,
                        max_context=32, default_max_new=8,
                        queue_limit=32, start=False)
    a = [gw.submit(np.zeros(3, np.int32), tenant="A")
         for _ in range(6)]
    b = [gw.submit(np.zeros(3, np.int32), tenant="B")
         for _ in range(2)]
    gw.warmup(prompt_lens=(3,))
    gw._worker = threading.Thread(target=gw._loop, daemon=True)
    gw._worker.start()
    for st in a + b:
        st.result(timeout=120)
    # admission order == TTFT order with one slot
    t_first = {st: st.t_first for st in a + b}
    assert max(t_first[st] for st in b) < max(t_first[st] for st in a[3:])
    gw.shutdown()


def test_admission_control_on_free_pages(tiny):
    """Pool smaller than the offered load: admission defers until
    pages free up, every request still completes, nothing leaks."""
    model, net = tiny
    # 7 usable pages; each request needs ceil(max(16, 3+11)/8)=2 pages
    # -> at most 3 in flight despite 4 slots
    gw = ServingGateway(model, net, max_slots=4, block=8,
                        max_context=32, n_pages=8, default_max_new=12,
                        queue_limit=32)
    gw.warmup(prompt_lens=(3,))
    streams = [gw.submit(np.zeros(3, np.int32)) for _ in range(10)]
    for st in streams:
        assert st.result(timeout=120).shape == (15,)
    gw._sched.pager.check_invariants()
    assert gw._sched.pager.free_pages() == 7
    gw.shutdown()


def test_oversized_request_fails_loudly(tiny):
    model, net = tiny
    gw = ServingGateway(model, net, max_slots=2, block=8,
                        max_context=32, n_pages=3, start=False)
    with pytest.raises(ValueError, match="pages"):
        gw.submit(np.zeros(20, np.int32), max_new=12)
    with pytest.raises(ValueError, match="max_context"):
        gw.submit(np.zeros(30, np.int32), max_new=8)
    with pytest.raises(ValueError, match="empty"):
        gw.submit(np.zeros(0, np.int32))


def test_streaming_tokens_and_eos(tiny):
    model, net = tiny
    sched = DecodeScheduler(model, net, max_slots=2, block=8,
                            max_context=32)
    sched.warmup(prompt_lens=(5,))
    probe = _Req(np.arange(5), 6)
    sched.admit(probe)
    while not probe.done:
        sched.step()
    assert len(probe.tokens) == 6
    # eos: same prompt with eos_id = the 3rd token it will produce
    # stops there and frees the pages
    eos = probe.tokens[2]
    if eos not in probe.tokens[:2]:         # unambiguous cut point
        r = _Req(np.arange(5), 6, eos_id=eos)
        sched.admit(r)
        while not r.done:
            sched.step()
        assert r.tokens == probe.tokens[:3]
    sched.pager.check_invariants()
    assert sched.pager.free_pages() == sched.pager.n_pages - 1

    # gateway streaming surface: tokens() yields the same sequence
    # result() returns
    gw = ServingGateway(model, net, max_slots=2, block=8,
                        max_context=32, default_max_new=6)
    gw.warmup(prompt_lens=(5,))
    st = gw.submit(np.arange(5, dtype=np.int32))
    toks = list(st.tokens(timeout=60))
    np.testing.assert_array_equal(
        st.result(timeout=5), np.concatenate([np.arange(5), toks]))
    assert toks == probe.tokens
    gw.shutdown()


def test_cancel_queued_and_live_sequences(tiny):
    """The cancel path is a slot/page-freeing path like retire and
    shed: cancelling one QUEUED stream and one MID-GENERATION stream
    must finish both without error, release every page, and leave the
    remaining traffic serving."""
    model, net = tiny
    gw = ServingGateway(model, net, max_slots=1, block=8,
                        max_context=32, default_max_new=16)
    gw.warmup(prompt_lens=(4,))
    live = gw.submit(np.zeros(4, np.int32))
    for _ in range(500):                      # wait until admitted
        if live.n_generated():
            break
        time.sleep(0.005)
    queued = gw.submit(np.zeros(4, np.int32))
    survivor = gw.submit(np.zeros(4, np.int32), max_new=4)
    assert gw.cancel(queued)                  # unqueued immediately
    assert gw.cancel(live)                    # evicted by the worker
    assert queued.result(timeout=10).shape == (4,)   # no tokens, no error
    partial = live.result(timeout=30)
    assert live.error() is None and partial.shape[0] < 20
    assert survivor.result(timeout=60).shape == (8,)
    gw._sched.pager.check_invariants()
    assert gw._sched.pager.free_pages() == gw._sched.pager.n_pages - 1
    gw.shutdown()


def test_sampled_decoding_serves_without_retraces(tiny):
    from deeplearning4j_tpu.perf import sentry
    model, net = tiny
    gw = ServingGateway(model, net, max_slots=2, block=8,
                        max_context=32, default_max_new=6,
                        sample=True, top_k=8, top_p=0.9, seed=3)
    gw.warmup(prompt_lens=(4, 20))
    before = sentry.total_traces()
    outs = []
    for t in (4, 17):
        st = gw.submit(np.zeros(t, np.int32), temperature=0.8)
        outs.append(st.result(timeout=120))
    assert sentry.total_traces() == before
    for t, o in zip((4, 17), outs):
        gen = o[t:]
        assert gen.shape == (6,)
        assert ((gen >= 0) & (gen < model.vocab_size)).all()
    gw.shutdown()


# =========================================================================
# fault path: shed-not-wedge, no leaked pages (chaos.py drills the
# same site end-to-end)
# =========================================================================

def test_injected_fault_sheds_inflight_and_recovers(tiny):
    from deeplearning4j_tpu.obs import metrics
    from deeplearning4j_tpu.resilience import faults
    model, net = tiny
    gw = ServingGateway(model, net, max_slots=2, block=8,
                        max_context=64, default_max_new=30,
                        queue_limit=16)
    gw.warmup(prompt_lens=(4,))
    shed0 = metrics.SERVING_SHED.labels(reason="fault").get()
    with faults.active("serving:error=RuntimeError:nth=3:max=1"):
        # two different prompts -> different token streams: each
        # victim's structured error must carry ITS OWN tokens (a
        # shared exception instance leaked the first stream's tokens
        # into every other client's error)
        victims = [gw.submit(np.full(4, i, np.int32))
                   for i in range(2)]
        errors = 0
        for st in victims:
            try:
                st.result(timeout=60)
            except SequenceAborted as e:
                errors += 1
                assert e.tokens, "structured error carries the " \
                                 "tokens streamed before the fault"
                assert e.tokens == st._tokens, \
                    "cross-request token leakage in shed error"
        assert errors == 2
        assert victims[0]._tokens != victims[1]._tokens
        fired = sum(s["fires"] for s in faults.stats().values())
    assert fired == 1
    assert metrics.SERVING_SHED.labels(reason="fault").get() \
        == shed0 + 2
    # never a wedged slot or leaked page: pool is whole and the SAME
    # worker serves the next request
    gw._sched.pager.check_invariants()
    assert gw._sched.pager.free_pages() == gw._sched.pager.n_pages - 1
    post = gw.submit(np.zeros(4, np.int32), max_new=4)
    assert post.result(timeout=60).shape == (8,)
    gw.shutdown()


def test_starved_large_request_ages_into_admission(tiny):
    """Anti-starvation aging: a page-hungry request must not wait
    forever while smaller arrivals keep taking every freed page —
    past ``starvation_patience`` the oldest head blocks younger
    admissions until the pool accumulates its need."""
    model, net = tiny
    gw = ServingGateway(model, net, max_slots=2, block=8,
                        max_context=32, n_pages=5, queue_limit=32,
                        default_max_new=12, starvation_patience=0.2)
    gw.warmup(prompt_lens=(3, 4))
    small = lambda: gw.submit(np.zeros(3, np.int32), tenant="small",
                              max_new=12)          # 2 pages
    others = [small() for _ in range(2)]           # pool now full
    big = gw.submit(np.zeros(4, np.int32), tenant="big",
                    max_new=18)                    # needs 3 pages
    others += [small() for _ in range(8)]          # sustained smalls
    assert big.result(timeout=120).shape == (22,)
    for st in others:
        st.result(timeout=120)
    # aging moved it ahead of the small-request tail
    assert big.t_first < max(st.t_first for st in others[-4:])
    gw._sched.pager.check_invariants()
    gw.shutdown()


def test_admission_fault_sheds_request_not_worker(tiny):
    """A device error during PREFILL (not just the step) must shed
    that one request with a structured error, release its page
    reservation, and leave the worker serving — the admission path is
    outside the step's try block and killed the worker before."""
    model, net = tiny
    gw = ServingGateway(model, net, max_slots=2, block=8,
                        max_context=32, default_max_new=4)
    gw.warmup(prompt_lens=(4,))
    sched = gw._sched
    real_admit_fn = sched._admit_fn
    calls = [0]

    def poisoned(tb):
        calls[0] += 1
        if calls[0] == 1:
            raise RuntimeError("synthetic prefill device error")
        return real_admit_fn(tb)

    sched._admit_fn = poisoned
    victim = gw.submit(np.zeros(4, np.int32))
    with pytest.raises(SequenceAborted, match="admission fault"):
        victim.result(timeout=30)
    # reservation released, worker alive, next request serves
    ok = gw.submit(np.zeros(4, np.int32))
    assert ok.result(timeout=60).shape == (8,)
    sched.pager.check_invariants()
    assert sched.pager.free_pages() == sched.pager.n_pages - 1
    gw.shutdown()


def test_zero_temperature_rejected_loudly(tiny):
    """temperature=0.0 must raise, not silently sample at 1.0 (the
    falsy-zero bug class the deadline satellite fixed)."""
    model, net = tiny
    gw = ServingGateway(model, net, max_slots=2, block=8,
                        max_context=32, sample=True, top_k=4,
                        start=False)
    with pytest.raises(ValueError, match="temperature"):
        gw.submit(np.zeros(4, np.int32), temperature=0.0)


# =========================================================================
# acceptance: throughput vs request-at-a-time + SLO export
# =========================================================================

def test_continuous_batching_beats_request_at_a_time(tiny):
    """The ISSUE 13 acceptance row: under the synthetic multi-tenant
    closed-loop trace the gateway sustains >= 1.5x the sequential B=1
    generate() baseline with zero retraces after warmup. Runs via
    ``loadgen.subprocess_report`` — a one-device measurement (the
    bench/dossier environment), outside this suite's 8-virtual-device
    partitioning which throttles the device loop. The serving-family
    /metrics export is asserted in-process on a small trace."""
    from deeplearning4j_tpu.obs import metrics
    from deeplearning4j_tpu.serving import loadgen

    rep = loadgen.subprocess_report()
    if not rep.get("skipped") and (rep.get("speedup") or 0) < 1.5:
        # throughput measurements on a busy 1-core CI box jitter (the
        # bench protocol medians 3 estimates for the same reason):
        # one fresh-process retry before calling the regression real
        rep = {**loadgen.subprocess_report(),
               "first_attempt_speedup": rep.get("speedup")}
    assert not rep.get("skipped"), rep
    assert rep["retraces_after_warmup"] == 0
    assert rep["completed"] == rep["n_requests"] and rep["failed"] == 0
    assert rep["ttft_p99_ms"] is not None
    assert rep["speedup"] >= 1.5, rep

    # in-process: the SLO families flow through /metrics (the earlier
    # gateway tests produced traffic in this registry)
    model, net = tiny
    gw = ServingGateway(model, net, max_slots=2, block=8,
                        max_context=32, default_max_new=4)
    gw.warmup(prompt_lens=(4,))
    stats = loadgen.run_trace(
        gw, loadgen.gen_requests(n_requests=4, max_new=4,
                                 prompt_lens=(2, 8), vocab_size=64),
        mode="open", rate=200.0)
    gw.shutdown()
    assert stats["completed"] == 4
    fams = metrics.parse_exposition(metrics.exposition())
    names = {n for n, _ in fams}
    assert "dl4j_tpu_serving_ttft_seconds_count" in names
    assert "dl4j_tpu_serving_tokens_total" in names
    assert "dl4j_tpu_serving_kv_pages_free" in names
    assert "dl4j_tpu_serving_step_seconds_count" in names


# =========================================================================
# request-scoped serving traces (ISSUE 14 satellite): submit → admit →
# prefill → decode-steps → retire/abort as async tracks keyed by
# request id, zero events with tracing off
# =========================================================================

def test_request_traces_off_path_zero_events(tiny):
    from deeplearning4j_tpu import obs

    model, net = tiny
    gw = ServingGateway(model, net, max_slots=2, block=8,
                        max_context=32, default_max_new=4)
    gw.warmup(prompt_lens=(4,))
    e0 = obs.trace.events_recorded()
    gw.submit(np.arange(4, dtype=np.int32) % 64).result(timeout=60)
    gw.shutdown()
    assert obs.trace.events_recorded() == e0


def test_request_traces_nested_phases_with_ids(tiny, tmp_path):
    from deeplearning4j_tpu import obs

    model, net = tiny
    gw = ServingGateway(model, net, max_slots=2, block=8,
                        max_context=32, default_max_new=4)
    gw.warmup(prompt_lens=(4,))
    path = str(tmp_path / "serving_trace.jsonl")
    obs.trace.enable(path)
    try:
        streams = [gw.submit(np.arange(4, dtype=np.int32) % 64,
                             tenant=f"t{i % 2}") for i in range(3)]
        for s in streams:
            s.result(timeout=60)
    finally:
        obs.trace.disable()
    gw.shutdown()
    evs = obs.trace.read_trace(path)
    reqs = [e for e in evs
            if str(e.get("name", "")).startswith("serving.request")]
    # every phase present, as async b/e pairs sharing the request id
    by_phase = {}
    for e in reqs:
        by_phase.setdefault(e["name"], []).append(e)
    for phase in ("serving.request", "serving.request/queue_wait",
                  "serving.request/prefill",
                  "serving.request/decode_steps"):
        pair = by_phase[phase]
        assert {p["ph"] for p in pair} == {"b", "e"}
        assert len(pair) == 6       # 3 requests x (b, e)
    assert len(by_phase["serving.request/submit"]) == 3
    # ids: one async track per request, phases share their request's
    # id, and args carry rid + tenant + outcome
    ids = {e["id"] for e in reqs if e.get("ph") in ("b", "e")}
    assert len(ids) == 3
    lives = [e for e in by_phase["serving.request"]
             if e["ph"] == "b"]
    assert {e["args"]["tenant"] for e in lives} == {"t0", "t1"}
    assert all(e["args"]["outcome"] == "retired" for e in lives)
    assert all(e["args"]["tokens"] == 4 for e in lives)
    # nesting: each request's inner phases sit inside its life span
    for life in lives:
        rid = life["id"]
        end = next(e for e in by_phase["serving.request"]
                   if e["ph"] == "e" and e["id"] == rid)
        for phase in ("serving.request/queue_wait",
                      "serving.request/prefill",
                      "serving.request/decode_steps"):
            inner = [e for e in by_phase[phase] if e["id"] == rid]
            assert inner, (phase, rid)
            assert all(life["ts"] <= e["ts"] <= end["ts"] + 1e-3
                       for e in inner)


def test_aborted_request_trace_carries_outcome(tiny, tmp_path):
    from deeplearning4j_tpu import obs
    from deeplearning4j_tpu.resilience import faults

    model, net = tiny
    gw = ServingGateway(model, net, max_slots=2, block=8,
                        max_context=32, default_max_new=8)
    gw.warmup(prompt_lens=(4,))
    path = str(tmp_path / "abort_trace.jsonl")
    obs.trace.enable(path)
    try:
        with faults.active("serving:error=RuntimeError:nth=2:max=1"):
            st = gw.submit(np.arange(4, dtype=np.int32) % 64)
            with pytest.raises(SequenceAborted):
                st.result(timeout=60)
    finally:
        obs.trace.disable()
    gw.shutdown()
    evs = obs.trace.read_trace(path)
    lives = [e for e in evs if e.get("name") == "serving.request"
             and e.get("ph") == "b"]
    assert len(lives) == 1
    assert lives[0]["args"]["outcome"].startswith("aborted:")
    assert lives[0]["args"]["tokens"] >= 1   # salvaged tokens counted


# =========================================================================
# KV-pager occupancy observability (ISSUE 14 satellite)
# =========================================================================

def test_kv_occupancy_and_per_tenant_reserved_gauges(tiny):
    from deeplearning4j_tpu.obs import metrics

    model, net = tiny
    sched = DecodeScheduler(model, net, max_slots=2, block=8,
                            max_context=32)
    usable = sched.pager.n_pages - 1
    assert metrics.SERVING_KV_OCCUPANCY.snapshot()[""] == 0.0

    class _T(_Req):
        def __init__(self, prompt, max_new, tenant):
            super().__init__(prompt, max_new)
            self.tenant = tenant

    a = _T(np.arange(4) % 64, 8, "alice")
    b = _T(np.arange(4) % 64, 8, "bob")
    assert sched.admit(a) and sched.admit(b)
    occ = metrics.SERVING_KV_OCCUPANCY.snapshot()[""]
    used = usable - sched.pager.free_pages()
    assert occ == pytest.approx(used / usable)
    reserved = sched.pager.reserved_by_tenant()
    assert set(reserved) == {"alice", "bob"}
    assert reserved["alice"] == len(sched.pager.owned(a))
    fams = metrics.parse_exposition(metrics.exposition())
    assert fams[("dl4j_tpu_serving_kv_pages_reserved",
                 (("tenant", "alice"),))] == reserved["alice"]
    # release returns the gauges to empty
    sched.evict(a)
    sched.evict(b)
    assert metrics.SERVING_KV_OCCUPANCY.snapshot()[""] == 0.0
    assert sched.pager.reserved_by_tenant() == {}
    fams = metrics.parse_exposition(metrics.exposition())
    assert fams[("dl4j_tpu_serving_kv_pages_reserved",
                 (("tenant", "alice"),))] == 0.0


def test_pager_tenant_label_cardinality_capped():
    pager = KVPager(n_layers=1, n_kv_heads=1, head_dim=4,
                    n_pages=200, block=8, cache_quant=None)
    pager.max_tenant_labels = 3

    class _O:
        def __init__(self, tenant):
            self.tenant = tenant

    owners = [_O(f"tenant{i}") for i in range(6)]
    for o in owners:
        assert pager.alloc(1, o) is not None
    reserved = pager.reserved_by_tenant()
    assert set(reserved) == {"tenant0", "tenant1", "tenant2", "other"}
    assert reserved["other"] == 3
    for o in owners:
        pager.release(o)
    assert pager.reserved_by_tenant() == {}
    pager.check_invariants()


# =========================================================================
# ISSUE 16: speculative multi-token decode + copy-on-write prefix
# sharing — identity fences, refcount churn, zero-retrace grid
# =========================================================================

# the int8 halves of the two GPTNano fences below ride the slow lane:
# each costs ~15s of fresh-model compiles and tier-1 has an 870s
# wall-clock budget (the PR 10 flash-sweep precedent); the float
# halves stay tier-1 and the int8 shared-page roundtrip keeps a
# tier-1 fence via test_int8_pages_roundtrip_token_for_token
@pytest.mark.parametrize("cache_quant", [
    None, pytest.param("int8", marks=pytest.mark.slow)])
def test_spec_decode_token_identical_to_dense(cache_quant):
    """THE spec-decode fence: greedy speculative decode through the
    gateway (k=4, prompt-lookup drafts) emits exactly the dense
    ``generate()`` tokens — a wrong draft may only cost speed, never
    change an output."""
    model = GPTNano(vocab_size=64, max_len=64, seed=7,
                    cache_quant=cache_quant)
    net = model.init()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 64, t).astype(np.int32)
               for t in (5, 17, 9, 30, 3, 22)]
    budgets = [10, 4, 16, 8, 12, 6]
    dense = [np.asarray(model.generate(net, p[None], n_new=n))[0]
             for p, n in zip(prompts, budgets)]
    gw = ServingGateway(model, net, max_slots=3, block=8,
                        max_context=64, spec_k=4)
    # exactly the reachable buckets — warming 1/2 as well would buy
    # nothing but ~2 extra fresh-model compiles
    gw.warmup(prompt_lens=(3, 5, 9, 17, 22, 30))
    streams = [gw.submit(p, max_new=n)
               for p, n in zip(prompts, budgets)]
    for st, d in zip(streams, dense):
        np.testing.assert_array_equal(st.result(timeout=120), d)
    gw._sched.pager.check_invariants()
    assert gw._sched.pager.free_pages() == gw._sched.pager.n_pages - 1
    gw.shutdown()


@pytest.mark.parametrize("cache_quant", [
    None, pytest.param("int8", marks=pytest.mark.slow)])
def test_prefix_sharing_token_identical_to_dense(cache_quant):
    """Sharing fence (int8 case doubles as the shared-page roundtrip
    satellite): a whole-prompt sibling (tail CoW) and a
    novel-suffix sharer both ride the donor's pages yet reproduce
    dense ``generate()`` token-for-token, and every shared page
    returns to the free list afterwards."""
    from deeplearning4j_tpu.obs import metrics
    rng = np.random.default_rng(1)
    base = rng.integers(0, 64, 30).astype(np.int32)
    prompts = [base.copy(),          # donor
               base.copy(),          # tail share: whole prompt equal
               np.concatenate([base[:16], rng.integers(
                   0, 64, 8).astype(np.int32)])]   # full-page share
    budgets = [12, 12, 12]
    model = GPTNano(vocab_size=64, max_len=64, seed=7,
                    cache_quant=cache_quant)
    net = model.init()
    dense = [np.asarray(model.generate(net, p[None], n_new=n))[0]
             for p, n in zip(prompts, budgets)]
    gw = ServingGateway(model, net, max_slots=4, block=8,
                        max_context=64, prefix_sharing=True, spec_k=4)
    # one full-admit bucket reaches every prompt here (30/30/24 all
    # bucket to 32) and the suffix warmup closes downward on its own;
    # warming more admit buckets is pure compile time
    gw.warmup(prompt_lens=(30,))
    h0 = metrics.SERVING_PREFIX_HITS.snapshot()[""]
    s0 = metrics.SERVING_PREFIX_SAVED.snapshot()[""]
    streams = [gw.submit(p, max_new=n)
               for p, n in zip(prompts, budgets)]
    outs = [np.asarray(st.result(timeout=120)) for st in streams]
    for got, d in zip(outs, dense):
        np.testing.assert_array_equal(got, d)
    # both sharers hit the donor's chain and skipped prefix prefill
    assert metrics.SERVING_PREFIX_HITS.snapshot()[""] - h0 == 2
    assert metrics.SERVING_PREFIX_SAVED.snapshot()[""] - s0 >= 16 + 29
    gw._sched.pager.check_invariants()
    assert gw._sched.pager.free_pages() == gw._sched.pager.n_pages - 1
    gw.shutdown()


def test_spec_and_sharing_zero_retraces_after_warmup(tiny):
    """Any admission order over the warmed (k, bucket) grid — fresh
    prompts, exact repeats (tail CoW), shared prefixes with novel
    suffixes — stays retrace-free under the strict sentry."""
    from deeplearning4j_tpu.perf import sentry
    model, net = tiny
    gw = ServingGateway(model, net, max_slots=3, block=8,
                        max_context=32, default_max_new=6,
                        spec_k=2, prefix_sharing=True)
    gw.warmup(prompt_lens=range(1, 25))
    before = sentry.total_traces()
    rng = np.random.default_rng(1)
    base = rng.integers(0, 64, 24).astype(np.int32)
    with sentry.strict():
        streams = [gw.submit(rng.integers(0, 64, int(t)), max_new=6)
                   for t in rng.integers(1, 25, 6)]
        streams.append(gw.submit(base, max_new=6))
        streams.append(gw.submit(base, max_new=6))
        streams.append(gw.submit(
            np.concatenate([base[:16],
                            rng.integers(0, 64, 4).astype(np.int32)]),
            max_new=6))
        for st in streams:
            st.result(timeout=120)
    assert sentry.total_traces() == before, \
        "spec/sharing traffic retraced after warmup"
    gw._sched.pager.check_invariants()
    gw.shutdown()


def test_spec_accept_metrics_exported(tiny):
    from deeplearning4j_tpu.obs import metrics
    model, net = tiny
    gw = ServingGateway(model, net, max_slots=2, block=8,
                        max_context=32, spec_k=4)
    gw.warmup(prompt_lens=(4, 8))
    d0 = metrics.SERVING_SPEC_DRAFTED.snapshot()[""]
    a0 = metrics.SERVING_SPEC_ACCEPT.snapshot()[""]["count"]
    st = gw.submit(np.arange(6, dtype=np.int32) % 64, max_new=12)
    st.result(timeout=120)
    drafted = metrics.SERVING_SPEC_DRAFTED.snapshot()[""] - d0
    assert drafted > 0 and drafted % 3 == 0      # k-1 per spec step
    assert metrics.SERVING_SPEC_ACCEPT.snapshot()[""]["count"] > a0
    accepted = metrics.SERVING_SPEC_ACCEPTED.snapshot()[""]
    assert 0 <= accepted <= metrics.SERVING_SPEC_DRAFTED.snapshot()[""]
    gw.shutdown()


def test_pager_refcount_churn():
    """Seeded 120-op churn over alloc/adopt/cow/drop_ref/release with
    the invariant fence after EVERY transition: no page frees while a
    sibling still references it, refcounts conserve against the table,
    and the pool returns to full conservation at the end."""
    rng = np.random.default_rng(42)
    pager = KVPager(n_layers=1, n_kv_heads=1, head_dim=4, n_pages=33,
                    block=8, cache_quant=None)
    owners = {}          # name -> (owner object, exclusive pages)
    nxt = [0]

    def fresh():
        nxt[0] += 1
        return f"o{nxt[0]}"

    for _ in range(120):
        op = rng.choice(["alloc", "adopt", "cow", "drop", "release"])
        if op == "alloc":
            o = object()
            pages = pager.alloc(int(rng.integers(1, 4)), o)
            if pages is not None:
                owners[fresh()] = o
        elif op == "adopt" and owners:
            donor = owners[str(rng.choice(sorted(owners)))]
            pages = pager.owned(donor)
            if pages:
                share = pages[:int(rng.integers(1, len(pages) + 1))]
                taker = object()
                rc_before = {p: pager.refcount(p) for p in share}
                pager.adopt(share, taker)
                for p in share:
                    assert pager.refcount(p) == rc_before[p] + 1
                owners[fresh()] = taker
        elif op == "cow" and owners:
            o = owners[str(rng.choice(sorted(owners)))]
            shared = [p for p in pager.owned(o)
                      if pager.refcount(p) > 1]
            if shared and pager.free_pages():
                old = shared[0]
                rc = pager.refcount(old)
                new = pager.cow(o, old)
                assert new != old and pager.refcount(new) == 1
                # the original survived for its other holders
                assert pager.refcount(old) == rc - 1 >= 1
        elif op == "drop" and owners:
            o = owners[str(rng.choice(sorted(owners)))]
            pages = pager.owned(o)
            if pages:
                p = pages[int(rng.integers(len(pages)))]
                rc = pager.refcount(p)
                freed = pager.drop_ref(o, p)
                assert freed == (rc == 1)
        elif op == "release" and owners:
            name = str(rng.choice(sorted(owners)))
            pager.release(owners.pop(name))
        pager.check_invariants()
    for o in owners.values():
        pager.release(o)
    pager.check_invariants()
    assert pager.free_pages() == pager.n_pages - 1


def test_pager_chain_index_dies_with_pages():
    """A freed page invalidates every chain entry it belonged to —
    match_prefix can never hand out dead pages."""
    pager = KVPager(n_layers=1, n_kv_heads=1, head_dim=4, n_pages=9,
                    block=8, cache_quant=None)
    toks = np.arange(20, dtype=np.int32)
    a = object()
    pages = pager.alloc(3, a)
    pager.register_chain(toks, pages)
    m = pager.match_prefix(toks)
    assert m is not None and m[0] == 19 and m[2] is True
    assert pager.match_prefix(toks[:17])[0] == 16
    b = object()
    pager.adopt(pages[:2], b)       # sibling keeps first two alive
    pager.release(a)                # donor goes away; page 3 frees
    pager.check_invariants()
    # tail entry died with page 3 — the walk falls back to the
    # longest FULL-PAGE prefix the sibling's refs kept alive
    m = pager.match_prefix(toks)
    assert m is not None and m[0] == 16 and m[2] is False
    m = pager.match_prefix(toks[:17])
    assert m is not None and m[0] == 16              # prefix survives
    pager.release(b)
    pager.check_invariants()
    assert pager.match_prefix(toks[:17]) is None
    assert pager.free_pages() == pager.n_pages - 1


def test_cow_isolation_against_sibling():
    """CoW bookkeeping isolation: after a writer CoWs a shared page,
    the sibling still holds the original physical page (same id), so
    the writer's subsequent writes cannot touch the sibling's data."""
    pager = KVPager(n_layers=1, n_kv_heads=1, head_dim=4, n_pages=9,
                    block=8, cache_quant=None)
    a, b = object(), object()
    pa = pager.alloc(2, a)
    pager.adopt(pa, b)
    new = pager.cow(b, pa[1])
    assert new not in pa
    assert pager.owned(a) == pa                  # untouched
    assert set(pager.owned(b)) == {pa[0], new}
    assert pager.refcount(pa[1]) == 1            # back to exclusive
    pager.check_invariants()
    pager.release(a)
    pager.release(b)
    assert pager.free_pages() == pager.n_pages - 1
