"""Keras import conformance for the extended mapper set:
Conv2DTranspose, Conv3D, pooling/pad/crop/upsampling 1D/3D,
LocallyConnected, Masking/RepeatVector, noise layers, activations.

Reference analog: KerasModelEndToEndTest (import → forward → compare
to Keras-produced activations)."""
import numpy as np
import pytest

keras = pytest.importorskip("keras")

from deeplearning4j_tpu.modelimport import KerasModelImport  # noqa: E402


def _roundtrip(model, tmp_path, x, rtol=1e-4, atol=1e-5):
    path = str(tmp_path / "m.h5")
    model.save(path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    ref = np.asarray(model(x, training=False))
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, ref, rtol=rtol, atol=atol)
    return net


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(11)


def test_conv2d_transpose(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((6, 6, 3)),
        keras.layers.Conv2DTranspose(5, 2, strides=2, padding="same",
                                     activation="relu"),
        keras.layers.Flatten(),
        keras.layers.Dense(4),
    ])
    x = rng.normal(size=(2, 6, 6, 3)).astype(np.float32)
    _roundtrip(model, tmp_path, x)


def test_conv3d_and_pool3d(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((6, 6, 6, 2)),
        keras.layers.Conv3D(4, 2, activation="relu", padding="valid"),
        keras.layers.MaxPooling3D(2),
        keras.layers.Flatten(),
        keras.layers.Dense(3),
    ])
    x = rng.normal(size=(2, 6, 6, 6, 2)).astype(np.float32)
    _roundtrip(model, tmp_path, x)


def test_pad_crop_upsample_1d(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((10, 3)),
        keras.layers.ZeroPadding1D(2),
        keras.layers.Conv1D(4, 3, activation="relu"),
        keras.layers.Cropping1D((1, 2)),
        keras.layers.UpSampling1D(2),
        keras.layers.GlobalMaxPooling1D(),
        keras.layers.Dense(2),
    ])
    x = rng.normal(size=(2, 10, 3)).astype(np.float32)
    _roundtrip(model, tmp_path, x)


def test_pad_crop_3d(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((4, 4, 4, 2)),
        keras.layers.ZeroPadding3D(1),
        keras.layers.Cropping3D(((1, 0), (0, 1), (1, 1))),
        keras.layers.Flatten(),
        keras.layers.Dense(3),
    ])
    x = rng.normal(size=(2, 4, 4, 4, 2)).astype(np.float32)
    _roundtrip(model, tmp_path, x)


def test_locally_connected_mapper(rng):
    """Keras 3 removed LocallyConnected*; the mapper still imports
    Keras-2-era h5 configs — checked at mapper level against a manual
    per-position matmul."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.modelimport.keras_import import (
        _map_layer, _map_weights)
    layer, _ = _map_layer("LocallyConnected2D", {
        "name": "lc", "filters": 3, "kernel_size": [2, 2],
        "strides": [1, 1], "padding": "valid", "activation": "linear",
        "use_bias": True})
    oh = ow = 3   # 4x4 input, 2x2 valid kernel
    kW = rng.normal(size=(oh * ow, 2 * 2 * 2, 3)).astype(np.float32)
    kb = rng.normal(size=(oh, ow, 3)).astype(np.float32)
    params, state = _map_weights(layer, {}, [kW, kb])
    x = rng.normal(size=(1, 4, 4, 2)).astype(np.float32)
    layer.init(__import__("jax").random.PRNGKey(0), (4, 4, 2))
    y, _ = layer.apply({k: jnp.asarray(v) for k, v in params.items()},
                       state, jnp.asarray(x))
    # manual: position (i,j) uses its own kernel slice
    patches = np.stack([x[0, i:i + 2, j:j + 2, :].reshape(-1)
                        for i in range(3) for j in range(3)])
    ref = np.einsum("pk,pko->po", patches, kW) + kb.reshape(9, 3)
    np.testing.assert_allclose(np.asarray(y[0]).reshape(9, 3), ref,
                               rtol=1e-4, atol=1e-5)


def test_repeat_vector_and_masking(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((6,)),
        keras.layers.Dense(5, activation="relu"),
        keras.layers.RepeatVector(4),
        keras.layers.LSTM(7, return_sequences=False),
        keras.layers.Dense(2),
    ])
    x = rng.normal(size=(3, 6)).astype(np.float32)
    _roundtrip(model, tmp_path, x, rtol=1e-3, atol=1e-4)


def test_noise_layers_inference_identity(tmp_path, rng):
    # noise layers are train-only: at inference the import must match
    model = keras.Sequential([
        keras.layers.Input((8,)),
        keras.layers.GaussianNoise(0.5),
        keras.layers.Dense(6, activation="relu"),
        keras.layers.GaussianDropout(0.3),
        keras.layers.Dense(3),
    ])
    x = rng.normal(size=(4, 8)).astype(np.float32)
    _roundtrip(model, tmp_path, x)


def test_activation_layers(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((7,)),
        keras.layers.Dense(6),
        keras.layers.ELU(),
        keras.layers.Dense(4),
        keras.layers.Softmax(),
    ])
    x = rng.normal(size=(3, 7)).astype(np.float32)
    _roundtrip(model, tmp_path, x)


def test_thresholded_relu_mapper():
    """ThresholdedReLU was dropped in Keras 3; mapper-level check."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.modelimport.keras_import import _map_layer
    layer, _ = _map_layer("ThresholdedReLU", {"theta": 0.7})
    layer.init(__import__("jax").random.PRNGKey(0), (4,))
    y, _ = layer.apply({}, {}, jnp.asarray([[0.5, 0.8, -1.0, 2.0]]))
    np.testing.assert_allclose(np.asarray(y[0]), [0, 0.8, 0, 2.0])


def test_spatial_dropout_inference(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((8, 8, 3)),
        keras.layers.Conv2D(4, 3, activation="relu"),
        keras.layers.SpatialDropout2D(0.4),
        keras.layers.Flatten(),
        keras.layers.Dense(2),
    ])
    x = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
    _roundtrip(model, tmp_path, x)


# ---------------------------------------------------------------------------
# custom-layer SPI (reference KerasLayer.registerCustomLayer, VERDICT r3 #8)


def test_custom_layer_spi_end_to_end(tmp_path, rng):
    """A user-defined Keras layer imports through a registered handler
    mapping it onto SameDiffLayer, weights included — import → forward
    must equal the Keras model."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.modelimport import (register_keras_layer,
                                                unregister_keras_layer)
    from deeplearning4j_tpu.nn.layers import SameDiffLayer

    @keras.saving.register_keras_serializable("test_pkg")
    class ScaleShift(keras.layers.Layer):
        def build(self, input_shape):
            f = input_shape[-1]
            self.alpha = self.add_weight(shape=(f,), initializer="ones",
                                         name="alpha")
            self.beta = self.add_weight(shape=(f,), initializer="zeros",
                                        name="beta")

        def call(self, x):
            return x * self.alpha + self.beta

    model = keras.Sequential([
        keras.layers.Input((6,)),
        keras.layers.Dense(5, activation="tanh"),
        ScaleShift(),
        keras.layers.Dense(3, activation="softmax"),
    ])
    # give the custom weights non-trivial values
    ss = model.layers[1]
    ss.alpha.assign(rng.normal(size=(5,)).astype(np.float32))
    ss.beta.assign(rng.normal(size=(5,)).astype(np.float32))
    path = str(tmp_path / "custom.h5")
    model.save(path)

    # unknown layer without a handler: error names the hook
    with pytest.raises(ValueError, match="register_keras_layer"):
        KerasModelImport.import_keras_sequential_model_and_weights(path)

    register_keras_layer(
        "ScaleShift",
        lambda cfg: SameDiffLayer(
            name=cfg.get("name"),
            param_shapes={"alpha": (5,), "beta": (5,)},
            fn=lambda p, x: x * p["alpha"] + p["beta"],
            output_shape_fn=lambda s: s),
        lambda layer, cfg, w: ({"alpha": w[0], "beta": w[1]}, {}))
    try:
        x = rng.normal(size=(4, 6)).astype(np.float32)
        _roundtrip(model, tmp_path, x)
    finally:
        unregister_keras_layer("ScaleShift")


def test_custom_layer_spi_no_weights_fn(tmp_path, rng):
    """weights_fn omitted: a weightless custom layer falls through the
    built-in weight rules (empty list -> no params)."""
    from deeplearning4j_tpu.modelimport import (register_keras_layer,
                                                unregister_keras_layer)
    from deeplearning4j_tpu.nn.layers import ActivationLayer

    @keras.saving.register_keras_serializable("test_pkg")
    class DoubleIt(keras.layers.Layer):
        def call(self, x):
            return x * 2.0

    model = keras.Sequential([
        keras.layers.Input((4,)),
        DoubleIt(),
        keras.layers.Dense(2),
    ])
    path = str(tmp_path / "double.h5")
    model.save(path)
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.layers import SameDiffLayer
    register_keras_layer(
        "DoubleIt",
        lambda cfg: SameDiffLayer(name=cfg.get("name"),
                                  fn=lambda p, x: x * 2.0,
                                  output_shape_fn=lambda s: s))
    try:
        x = rng.normal(size=(3, 4)).astype(np.float32)
        _roundtrip(model, tmp_path, x)
    finally:
        unregister_keras_layer("DoubleIt")
