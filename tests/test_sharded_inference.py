"""pjit parameter-sharded serving (SURVEY §2.5 model-parallel
inference row): a model sharded over the mesh 'model' axis must hold
~1/N of its parameter bytes per device and produce outputs identical
to the unsharded network."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import requires_shard_map

from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.parallel import (ParallelInference, make_mesh,
                                         shard_model_params)



pytestmark = requires_shard_map

def _wide_net(hidden=512, n_in=64, classes=8):
    conf = (NeuralNetConfiguration.builder().seed(11)
            .updater(upd.Sgd(learning_rate=1e-2)).list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=classes, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _param_bytes(tree):
    return sum(l.size * l.dtype.itemsize
               for l in jax.tree_util.tree_leaves(tree))


def _local_bytes(tree):
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shard = leaf.addressable_shards[0]
        total += shard.data.size * shard.data.dtype.itemsize
    return total


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_sharded_params_bytes_and_outputs_match():
    net = _wide_net()
    x = np.random.default_rng(0).normal(size=(16, 64)).astype(np.float32)
    want = np.asarray(net.output(x))
    total = _param_bytes(net.params)

    mesh = make_mesh({"model": 8})
    shard_model_params(net, mesh, "model")

    # big weights sharded 8-ways: local bytes well under the total
    # (biases and the small head replicate)
    local = _local_bytes(net.params)
    assert local < total / 4, (local, total)
    # the dominant hidden x hidden weight must be exactly 1/8 local
    w2 = net.params["layer_1"]["W"]
    assert w2.addressable_shards[0].data.size * 8 == w2.size

    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_parallel_inference_sharded_serving():
    net = _wide_net()
    x = np.random.default_rng(1).normal(size=(4, 64)).astype(np.float32)
    want = np.asarray(net.output(x))
    mesh = make_mesh({"model": 8})
    pi = ParallelInference(net, mode=ParallelInference.BATCHED,
                           mesh=mesh, shard_params=True)
    try:
        got = pi.output(x)
    finally:
        pi.shutdown()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_shard_params_requires_mesh():
    net = _wide_net(hidden=32)
    with pytest.raises(ValueError, match="needs a mesh"):
        ParallelInference(net, shard_params=True)
