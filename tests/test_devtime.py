"""Device-time observatory (obs/devtime.py — ARCHITECTURE.md §16).

Fences: the xplane wire parser reads real captures, the HLO scope map
attributes forward AND backward ops to their layers, the roofline
math is exact, the gap report carries exactly GAP_KEYS ranked by
share, an instrumented smoke fit attributes EVERY layer type in the
net, and — the PR 2 contract — with ``DL4J_TPU_DEVTIME`` unset the
fit loops run zero profiler sessions and zero captures
(counter-asserted).
"""
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from deeplearning4j_tpu.nn import (MultiLayerNetwork,  # noqa: E402
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.config import InputType  # noqa: E402
from deeplearning4j_tpu.nn.layers import (ConvolutionLayer,  # noqa: E402
                                          DenseLayer, OutputLayer,
                                          SubsamplingLayer)
from deeplearning4j_tpu.nn import updaters as upd  # noqa: E402
from deeplearning4j_tpu.obs import devtime  # noqa: E402
from deeplearning4j_tpu.obs import metrics as obs_metrics  # noqa: E402
from deeplearning4j_tpu.perf import sentry  # noqa: E402
from deeplearning4j_tpu.perf.warmup import WarmupSpec  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_devtime():
    devtime.disable()
    devtime.reset_counters()
    yield
    devtime.disable()
    devtime.reset_counters()


def _probe_step():
    """Tiny scoped grad fn — the cheap capture donor."""
    def fwd(p, x):
        with devtime.scope("layer_0.DenseLayer"):
            h = jnp.tanh(x @ p["w0"])
        with devtime.scope("layer_1.OutputLayer"):
            o = h @ p["w1"]
        return jnp.sum(o ** 2)

    step = sentry.jit(jax.grad(fwd), name="devtime_probe")
    p = {"w0": jnp.ones((128, 128)), "w1": jnp.ones((128, 32))}
    x = jnp.ones((64, 128))
    step.warmup(p, x)
    return step, p, x


# -------------------------------------------------------------------------
# xplane wire parser
# -------------------------------------------------------------------------

def test_xplane_parser_reads_real_capture(tmp_path):
    step, p, x = _probe_step()
    with jax.profiler.trace(str(tmp_path)):
        for _ in range(2):
            jax.block_until_ready(step(p, x))
    paths = devtime.xplane_paths(str(tmp_path))
    assert paths and all(q.endswith(".xplane.pb") for q in paths)
    evs = []
    for q in paths:
        xs = devtime.read_xspace(q)
        assert xs["planes"], "no planes parsed"
        evs.extend(devtime.op_events(xs))
    assert evs, "no XLA-op execution events parsed"
    assert all(e["dur_ns"] > 0 for e in evs)
    # the executed module is identifiable (the scope-map join key)
    assert any("devtime_probe" in e["module"] or "jit_" in e["module"]
               for e in evs)


def test_xplane_paths_explicit_file_and_newest_session(tmp_path):
    step, p, x = _probe_step()
    import shutil
    import time as _time
    d1, d2 = tmp_path / "one", tmp_path / "two"
    with jax.profiler.trace(str(d1)):
        jax.block_until_ready(step(p, x))
    _time.sleep(0.05)
    with jax.profiler.trace(str(d2)):
        jax.block_until_ready(step(p, x))
    # merge target: every plane of the NEWEST session only
    newest = devtime.xplane_paths(str(tmp_path))
    assert all(str(d2) in q for q in newest)
    # a second host's plane in the same session dir is merged, not
    # dropped (the multi-host fix)
    session_dir = Path(newest[0]).parent
    shutil.copy(newest[0], session_dir / "host2.xplane.pb")
    merged = devtime.xplane_paths(str(tmp_path))
    assert len(merged) == len(newest) + 1
    # explicit file argument reads exactly that plane
    assert devtime.xplane_paths(newest[0]) == [newest[0]]


# -------------------------------------------------------------------------
# HLO scope map
# -------------------------------------------------------------------------

def test_hlo_scope_map_attributes_forward_and_backward():
    step, p, x = _probe_step()
    ex = devtime.sentry_executables(step)
    assert ex, "warmup must leave an AOT executable"
    sm = devtime.hlo_scope_map(ex[0].as_text())
    assert sm["module"]
    scopes = {i["scope"] for i in sm["ops"].values() if i["scope"]}
    assert {"layer_0.DenseLayer", "layer_1.OutputLayer"} <= scopes
    # backward ops (transpose(jvp(...))) attribute to their layer
    assert any(i["backward"] and i["scope"] == "layer_0.DenseLayer"
               for i in sm["ops"].values())
    # dot flops are the exact 2·M·N·K of at least the fwd matmuls:
    # 64x128 @ 128x128 and 64x128 @ 128x32
    dot_flops = sorted(i["flops"] for i in sm["ops"].values()
                      if i["kind"] == "dot")
    assert 2 * 64 * 128 * 128 in dot_flops
    assert 2 * 64 * 32 * 128 in dot_flops


def test_scope_trace_time_only():
    """The annotation must not change the computed values."""
    def plain(x):
        return jnp.tanh(x @ x).sum()

    def scoped(x):
        with devtime.scope("layer_9.Probe"):
            return jnp.tanh(x @ x).sum()

    x = jnp.linspace(-1, 1, 64 * 64).reshape(64, 64)
    a = jax.jit(plain)(x)
    b = jax.jit(scoped)(x)
    assert float(a) == float(b)


# -------------------------------------------------------------------------
# roofline math
# -------------------------------------------------------------------------

def test_roofline_math_units():
    # compute-bound: intensity 100 F/B vs ridge 10 F/B
    r = devtime.roofline(flops=1e12, bytes_=1e10, seconds=2.0,
                         peak_flops=1e12, peak_bytes_per_s=1e11)
    assert r["bound"] == "compute"
    assert r["achieved_tflops"] == pytest.approx(0.5)
    assert r["compute_utilization"] == pytest.approx(0.5)
    assert r["utilization"] == pytest.approx(0.5)
    # memory-bound: intensity 1 F/B under the same ridge
    r = devtime.roofline(flops=1e10, bytes_=1e10, seconds=0.05,
                         peak_flops=1e12, peak_bytes_per_s=1e11)
    assert r["bound"] == "memory"
    assert r["memory_utilization"] == pytest.approx(2.0)
    assert r["utilization"] == pytest.approx(2.0)
    # degenerate inputs never divide by zero
    r = devtime.roofline(1.0, 1.0, 0.0, 1e12, 1e11)
    assert r["bound"] == "unknown" and r["utilization"] == 0.0


def test_gap_report_schema_and_ranking():
    cap = {
        "scopes": {
            "layer_0.Dense": {
                "device_ms": 8.0, "share": 0.4, "ops": 10,
                "fusions": 2, "backward_ms": 4.0,
                "custom_call_ms": 0.0, "flops": 1e9, "bytes": 1e8,
                "kinds": {"dot": 4},
                "roofline": {"utilization": 0.1, "bound": "memory"}},
            "op:flash_kernel": {
                "device_ms": 6.0, "share": 0.3, "ops": 2,
                "fusions": 0, "backward_ms": 0.0,
                "custom_call_ms": 5.9, "flops": 1e9, "bytes": 1e8,
                "kinds": {"custom-call": 2},
                "roofline": {"utilization": 0.2, "bound": "compute"}},
            "layer_1.Output": {
                "device_ms": 4.0, "share": 0.2, "ops": 5,
                "fusions": 1, "backward_ms": 1.0,
                "custom_call_ms": 0.0, "flops": 1e9, "bytes": 1e8,
                "kinds": {"dot": 2},
                "roofline": {"utilization": 0.9, "bound": "compute"}},
            "op:noise": {
                "device_ms": 0.1, "share": 0.005, "ops": 1,
                "fusions": 0, "backward_ms": 0.0,
                "custom_call_ms": 0.0, "flops": 0.0, "bytes": 0.0,
                "kinds": {"copy": 1}},
        }}
    gaps = devtime.gap_report(cap, top=10)
    assert [tuple(g) for g in gaps] == [devtime.GAP_KEYS] * 4
    assert [g["share"] for g in gaps] == sorted(
        (g["share"] for g in gaps), reverse=True)
    by = {g["scope"]: g for g in gaps}
    # big share + low utilization -> candidate
    assert by["layer_0.Dense"]["pallas_candidate"] is True
    # already a custom call -> never re-flagged
    assert by["op:flash_kernel"]["pallas_candidate"] is False
    # near-roofline -> XLA already won, no candidate
    assert by["layer_1.Output"]["pallas_candidate"] is False
    # sub-threshold share -> no candidate (no cost info either)
    assert by["op:noise"]["pallas_candidate"] is False


# -------------------------------------------------------------------------
# capture pipeline + scope coverage (the acceptance fence)
# -------------------------------------------------------------------------

def _smoke_net():
    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(upd.Adam(learning_rate=1e-3)).list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 1)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 8, 8, 1)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    return net, x, y


def test_smoke_fit_attribution_covers_every_layer_type():
    net, x, y = _smoke_net()
    net.warmup([WarmupSpec(features=(8, 8, 8, 1), labels=(8, 3))])
    net.fit(x, y)                   # settle off the window
    # off-path fence FIRST: the fits above ran zero profiler sessions
    assert devtime.profiler_sessions() == 0
    assert devtime.captures() == 0
    rep = devtime.capture(
        lambda: [net.fit(x, y) for _ in range(2)],
        executables=devtime.sentry_executables(net._train_step_fn))
    cap = rep["capture"]
    scopes = cap["scopes"]
    # EVERY layer of the net appears in the attribution, named
    # layer_<i>.<RegisteredType>, with real device time
    for i, layer in enumerate(net.layers):
        key = f"layer_{i}.{type(layer).__name__}"
        assert key in scopes, (key, sorted(scopes))
        assert scopes[key]["device_ms"] > 0
    # the backward half attributes too (transpose(jvp(scope)) ops)
    assert sum(scopes[f"layer_{i}.{type(l).__name__}"]["backward_ms"]
               for i, l in enumerate(net.layers)) > 0
    # the optimizer phase is named, and attribution accounts for a
    # solid majority of measured device time
    assert "optimizer.update" in scopes
    assert cap["scope_coverage"] > 0.5
    # per-scope roofline rides along wherever cost info exists
    assert any("roofline" in e for e in scopes.values())
    assert devtime.captures() == 1 and devtime.profiler_sessions() == 1


def test_capture_publishes_devtime_gauges():
    step, p, x = _probe_step()
    devtime.capture(lambda: jax.block_until_ready(step(p, x)),
                    executables=devtime.sentry_executables(step))
    fams = obs_metrics.parse_exposition(obs_metrics.exposition())
    shares = {dict(labels).get("scope"): v for (n, labels), v
              in fams.items() if n == "dl4j_tpu_devtime_scope_share"}
    assert shares, "no scope-share gauges published"
    assert abs(sum(shares.values()) - 1.0) < 0.05
    assert fams.get(("dl4j_tpu_devtime_captures_total", ()), 0) >= 1
    # a second capture REPLACES the scope labelsets (no stale labels)
    devtime.capture(lambda: jax.block_until_ready(step(p, x)),
                    executables=devtime.sentry_executables(step))
    fams2 = obs_metrics.parse_exposition(obs_metrics.exposition())
    shares2 = {dict(labels).get("scope") for (n, labels), v
               in fams2.items()
               if n == "dl4j_tpu_devtime_scope_share"}
    assert shares2 <= set(shares) | shares2  # sanity: parse worked
    assert abs(sum(
        v for (n, _l), v in fams2.items()
        if n == "dl4j_tpu_devtime_scope_share") - 1.0) < 0.05


def test_cadence_monitor_and_off_path_fence():
    net, x, y = _smoke_net()
    net.fit(x, y)                   # compile outside any window
    s0 = devtime.profiler_sessions()
    assert s0 == 0                  # env unset: zero sessions so far
    devtime.configure(every=2, steps=2)
    for _ in range(4):
        net.fit(x, y)
    devtime.disable()
    assert devtime.captures() >= 1
    assert devtime.profiler_sessions() >= 1
    rep = devtime.last_report()
    assert rep is not None and rep["gaps"]
    # monitor off again: further fits never touch the profiler
    c0, s1 = devtime.captures(), devtime.profiler_sessions()
    for _ in range(2):
        net.fit(x, y)
    assert (devtime.captures(), devtime.profiler_sessions()) == (c0,
                                                                 s1)


def test_measure_capture_overhead_restores_state():
    c0, s0 = devtime.captures(), devtime.profiler_sessions()
    out = devtime.measure_capture_overhead(step_seconds=0.05,
                                           iters=2000)
    assert out["off_path_cost_us"] < 50.0
    assert out["monitor_enabled"] is False
    assert (devtime.captures(), devtime.profiler_sessions()) == (c0,
                                                                 s0)


# -------------------------------------------------------------------------
# xprof_summary integration (satellite: explicit file + merge)
# -------------------------------------------------------------------------

def test_xprof_summary_reads_capture_dir_and_file(tmp_path):
    import shutil

    import xprof_summary

    step, p, x = _probe_step()
    d = tmp_path / "cap"
    devtime.capture(lambda: jax.block_until_ready(step(p, x)),
                    executables=devtime.sentry_executables(step),
                    keep_dir=str(d))
    out = xprof_summary.summarize(str(d), top=5)
    assert "op class" in out and "%" in out
    planes = devtime.xplane_paths(str(d))
    # explicit file: exactly one plane read
    single = xprof_summary.summarize(planes[0], top=5)
    assert "planes: 1 file(s)" in single
    # a second host's plane doubles the merged totals, proving the
    # dir path merges instead of dropping hosts
    shutil.copy(planes[0],
                Path(planes[0]).parent / "hostB.xplane.pb")
    merged = xprof_summary.summarize(str(d), top=5)
    assert f"planes: {len(planes) + 1} file(s)" in merged
