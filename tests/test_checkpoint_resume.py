"""Crash → restore → resume continuity.

Reference analog (SURVEY §5 failure detection/recovery): the recovery
story is checkpoint-based — CheckpointListener + ModelSerializer
resume, "slice-level restart is the idiom". This test proves the
checkpoint round-trip is bit-continuable: a run interrupted mid-training
and resumed from the checkpoint produces the SAME params as the
uninterrupted run (updater state incl. Adam moments survives).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.serialization import ModelSerializer
from deeplearning4j_tpu.train import CheckpointListener


def _conf(seed=9):
    return (NeuralNetConfiguration.builder().seed(seed)
            .updater(upd.Adam(learning_rate=1e-2)).list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())


def _data():
    rng = np.random.RandomState(3)
    x = rng.randn(32, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]
    return DataSet(x, y)


def _params_close(a, b, tol=1e-6):
    import jax
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return all(np.allclose(np.asarray(x), np.asarray(y), atol=tol)
               for x, y in zip(la, lb))


def test_resume_equals_uninterrupted(tmp_path):
    ds = _data()

    # uninterrupted: 6 epochs straight
    ref = MultiLayerNetwork(_conf()).init()
    ref.fit(ListDataSetIterator([ds], batch_size=32), epochs=6)

    # interrupted: 3 epochs, checkpoint, "crash", restore, 3 more
    net = MultiLayerNetwork(_conf()).init()
    net.fit(ListDataSetIterator([ds], batch_size=32), epochs=3)
    path = tmp_path / "ckpt.zip"
    ModelSerializer.write_model(net, path, save_updater=True)
    del net                                        # the crash

    back = ModelSerializer.restore_multi_layer_network(str(path))
    back.fit(ListDataSetIterator([ds], batch_size=32), epochs=3)

    # Adam moments survived the round trip -> identical trajectory
    assert _params_close(ref.params, back.params)
    assert abs(ref.score(ds) - back.score(ds)) < 1e-6


def test_resume_without_updater_state_diverges(tmp_path):
    """Negative control: dropping the updater state changes the
    trajectory — proving the updaterState.bin analog is load-bearing."""
    ds = _data()
    ref = MultiLayerNetwork(_conf()).init()
    ref.fit(ListDataSetIterator([ds], batch_size=32), epochs=6)

    net = MultiLayerNetwork(_conf()).init()
    net.fit(ListDataSetIterator([ds], batch_size=32), epochs=3)
    path = tmp_path / "ckpt_noupd.zip"
    ModelSerializer.write_model(net, path, save_updater=False)
    back = ModelSerializer.restore_multi_layer_network(str(path))
    back.fit(ListDataSetIterator([ds], batch_size=32), epochs=3)
    assert not _params_close(ref.params, back.params)


def test_checkpoint_listener_keep_last(tmp_path):
    ds = _data()
    net = MultiLayerNetwork(_conf()).init()
    listener = CheckpointListener(tmp_path, save_every_n_epochs=1,
                                  keep_last=2)
    net.add_listeners(listener)
    net.fit(ListDataSetIterator([ds], batch_size=32), epochs=5)
    ckpts = sorted(tmp_path.glob("checkpoint_*.zip"))
    assert len(ckpts) == 2                      # keep-last-K enforced
    # latest checkpoint restores and continues
    back = ModelSerializer.restore_multi_layer_network(str(ckpts[-1]))
    s = back.score(ds)
    back.fit(ListDataSetIterator([ds], batch_size=32), epochs=1)
    assert back.score(ds) <= s + 1e-6
