"""Resilience subsystem (ARCHITECTURE.md §10): deterministic fault
injection, hardened checkpoint pipeline, retry/preemption policy,
serving load-shedding.

Reference analog (SURVEY §5): the reference's recovery story was
CheckpointListener + ModelSerializer resume + Spark task retry, tested
only by real outages. Here failure itself is a managed artifact: every
test drives a REAL code path (fit loop, checkpoint IO, serving queue)
through a seeded fault plan and asserts recovery — including the
acceptance fences: injected-fault matrix with obs counters, zero-
overhead off path, crash-consistency under kill -9, SIGTERM-during-fit
clean preemption.
"""
import json
import os
import signal
import subprocess
import sys
import textwrap
import time
import zipfile
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.obs import metrics
from deeplearning4j_tpu.resilience import checkpoint as rck
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.resilience.policy import (PreemptionHandler,
                                                  RetryPolicy, classify)
from deeplearning4j_tpu.serialization import ModelSerializer
from deeplearning4j_tpu.train.fault_tolerance import (
    FaultTolerantTrainer, newest_checkpoint, resume_or_init)

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.reset()
    yield
    faults.reset()


def _mlp(seed=11, n_in=8, n_out=3):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(upd.Adam(learning_rate=5e-3)).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=n_out, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _data(n=96, seed=5):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return DataSet(x, y)


def _iter(ds, bs=24):
    return ListDataSetIterator([b for b in ds.batch_by(bs)],
                               batch_size=bs)


def _params_equal(a, b, tol=1e-6):
    import jax
    return all(np.allclose(np.asarray(x), np.asarray(y), atol=tol)
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def _counter(metric, **labels):
    return metric.labels(**labels).get() if labels \
        else metric._children[()].get()


# =========================================================================
# fault plan parsing + off-path contract
# =========================================================================

def test_plan_parse_roundtrip():
    p = faults.FaultPlan.parse(
        "ckpt_*:error=OSError:p=0.5:seed=3:max=2;step:nth=6")
    assert len(p.rules) == 2
    assert p.rules[0].error == "OSError" and p.rules[0].max_fires == 2
    assert p.rules[1].site == "step" and p.rules[1].nth == 6
    assert p.rules[0].matches("ckpt_write")
    assert p.rules[0].matches("ckpt_commit")
    assert not p.rules[0].matches("step")


def test_named_plans_all_parse():
    for name in faults.NAMED_PLANS:
        assert faults.FaultPlan.parse(name).rules


@pytest.mark.parametrize("bad", ["", "step:frequency=2", "step:error=Nope",
                                 "step:p", "ckptwrite:error=OSError"])
def test_plan_parse_rejects(bad):
    with pytest.raises(ValueError):
        faults.FaultPlan.parse(bad)


def test_seeded_probability_is_deterministic():
    fire_pattern = []
    for _ in range(2):
        r = faults.FaultRule("s", p=0.5, seed=7, max_fires=1 << 30)
        fire_pattern.append([r.should_fire() for _ in range(32)])
    assert fire_pattern[0] == fire_pattern[1]
    assert any(fire_pattern[0]) and not all(fire_pattern[0])


def test_off_path_zero_evaluations():
    """Acceptance: with no plan active, training + checkpoint IO +
    serving pass every fault site and the evaluation counter never
    moves — the sites cost one branch, nothing else executes."""
    assert faults.plan() is None
    before = faults.evaluations()
    net = _mlp()
    ds = _data(48)
    net.fit(_iter(ds), epochs=1)                      # step + iterator
    ModelSerializer.write_model(net, "/tmp/_faults_off_probe.zip")
    os.unlink("/tmp/_faults_off_probe.zip")           # ckpt sites
    assert faults.evaluations() == before == 0
    assert faults.stats() == {}
    # flip the gate on: the SAME paths now evaluate sites (a valid
    # site whose nth is astronomically far away never fires)
    with faults.active("step:nth=1000000000"):
        net.fit(_iter(ds), epochs=1)
    assert faults.evaluations() > 0


# =========================================================================
# hardened checkpoint pipeline
# =========================================================================

def test_write_model_is_atomic_and_manifested(tmp_path):
    net = _mlp()
    p = tmp_path / "ckpt.zip"
    ModelSerializer.write_model(net, p)
    ok, why = rck.verify_checkpoint(p)
    assert ok, why
    m = json.loads(rck.manifest_path(p).read_text())
    assert m["crc32"] == rck.file_crc32(p)
    assert m["size"] == p.stat().st_size
    assert m["format_version"] == rck.FORMAT_VERSION
    assert not list(tmp_path.glob(".*tmp*"))          # no droppings


def test_commit_fault_preserves_previous_checkpoint(tmp_path):
    """A crash after the tmp zip is written but before os.replace: the
    previous checkpoint survives untouched, no tmp file remains, and
    the restart loop restores the OLD state."""
    net = _mlp()
    p = tmp_path / "checkpoint_iter_1.zip"
    ModelSerializer.write_model(net, p)
    old_bytes = p.read_bytes()
    net.fit(_iter(_data(48)), epochs=1)
    with faults.active("ckpt_commit:error=OSError:nth=1"):
        with pytest.raises(OSError):
            ModelSerializer.write_model(net, p)
    assert p.read_bytes() == old_bytes
    assert not list(tmp_path.glob(".*tmp*"))
    assert newest_checkpoint(tmp_path) == p


def test_truncated_newest_falls_back_and_quarantines(tmp_path):
    """Satellite acceptance: truncate the newest checkpoint mid-byte →
    restore falls back to the previous valid one and the corrupt file
    is quarantined (counter incremented)."""
    net = _mlp()
    it = _iter(_data(48))
    a = tmp_path / "checkpoint_iter_2.zip"
    b = tmp_path / "checkpoint_iter_4.zip"
    net.fit(it, epochs=1)
    ModelSerializer.write_model(net, a)
    import jax
    good_params = jax.tree.map(np.asarray, net.params)  # donation-safe
    net.fit(it, epochs=1)
    ModelSerializer.write_model(net, b)
    os.utime(b, (time.time() + 5, time.time() + 5))   # decisively newest
    # truncate mid-byte (and refresh the manifest-free scenario: drop
    # the sidecar so the zip-level sweep has to catch it)
    data = b.read_bytes()
    b.write_bytes(data[:len(data) // 2])
    rck.manifest_path(b).unlink()
    q0 = _counter(metrics.CKPT_QUARANTINED)
    newest = newest_checkpoint(tmp_path)
    assert newest == a
    assert _counter(metrics.CKPT_QUARANTINED) == q0 + 1
    assert not b.exists()
    assert (tmp_path / "corrupt" / b.name).exists()
    back = resume_or_init(lambda: _mlp(), tmp_path)
    assert _params_equal(back.params, good_params)


def test_manifest_crc_mismatch_detected(tmp_path):
    """Bit-rot INSIDE a structurally-valid zip member is caught by the
    whole-file CRC in the manifest (testzip alone can miss flips in
    the compressed stream that still inflate)."""
    net = _mlp()
    p = tmp_path / "checkpoint_iter_1.zip"
    ModelSerializer.write_model(net, p)
    data = bytearray(p.read_bytes())
    data[len(data) // 2] ^= 0xFF                      # single-byte rot
    p.write_bytes(bytes(data))
    ok, why = rck.verify_checkpoint(p)
    assert not ok
    assert "crc" in why.lower() or "zip" in why.lower()


def test_corrupt_manifest_falls_back_to_zip_checks(tmp_path):
    net = _mlp()
    p = tmp_path / "checkpoint_iter_1.zip"
    ModelSerializer.write_model(net, p)
    rck.manifest_path(p).write_text("{torn json")
    ok, why = rck.verify_checkpoint(p)
    assert ok, why                                    # zip itself is fine


def test_sharded_restore_latest_valid_quarantines(tmp_path):
    """Orbax path: an unrestorable step dir is quarantined and restore
    falls back to the newest step that restores."""
    from deeplearning4j_tpu.serialization import ShardedCheckpointer
    net = _mlp()
    ck = ShardedCheckpointer(tmp_path, keep_last=3, async_save=False)
    ck.save(1, net, wait=True)
    p1 = np.asarray(next(iter(
        __import__("jax").tree.leaves(net.params))))
    net.fit(_iter(_data(48)), epochs=1)
    ck.save(2, net, wait=True)
    # corrupt step 2: truncate one tensorstore data file
    files = [f for f in (tmp_path / "2").rglob("*") if f.is_file()]
    for f in files:
        f.write_bytes(f.read_bytes()[:3])
    fresh = _mlp()
    q0 = _counter(metrics.CKPT_QUARANTINED)
    ck.restore_latest_valid(fresh)
    assert np.allclose(
        np.asarray(next(iter(__import__("jax").tree.leaves(
            fresh.params)))), p1)
    assert _counter(metrics.CKPT_QUARANTINED) == q0 + 1
    assert (tmp_path / "corrupt" / "2").exists()
    assert ck.all_steps() == [1]
    ck.close()


# =========================================================================
# retry / classification policy
# =========================================================================

def test_classify_table():
    assert classify(OSError("disk flake")) == "transient"
    assert classify(ConnectionError("chip dropped")) == "transient"
    assert classify(TimeoutError("collective stall")) == "transient"
    assert classify(RuntimeError("XLA runtime hiccup")) == "transient"
    assert classify(RuntimeError("dot_general shape mismatch")) \
        == "deterministic"
    assert classify(ValueError("incompatible dtype")) == "deterministic"
    assert classify(FloatingPointError("x")) == "deterministic"
    assert classify(RuntimeError("loss is NaN")) == "deterministic"
    assert classify(faults.InjectedFault("boom")) == "transient"


def test_retry_policy_backoff_shape():
    p = RetryPolicy(base_delay_s=0.1, max_delay_s=1.0, jitter=0.0)
    assert [p.delay(i) for i in (1, 2, 3, 4, 5, 6)] == \
        [0.1, 0.2, 0.4, 0.8, 1.0, 1.0]                # clamped
    j = RetryPolicy(base_delay_s=0.1, jitter=0.5, seed=4)
    assert j.delay(2) == j.delay(2)                   # seeded
    assert 0.1 <= j.delay(2) <= 0.3                   # within jitter band


def test_retry_policy_call_semantics():
    calls = {"n": 0}
    slept = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("flake")
        return "ok"

    p = RetryPolicy(max_retries=5, base_delay_s=0.01, jitter=0.0)
    assert p.call(flaky, sleep=slept.append) == "ok"
    assert calls["n"] == 3 and len(slept) == 2

    def det():
        raise ValueError("shape mismatch forever")

    calls["n"] = 0
    with pytest.raises(ValueError):
        p.call(det, sleep=slept.append)


# =========================================================================
# injected-fault matrix (acceptance): recovery + obs counters per site
# =========================================================================

@pytest.fixture(scope="module")
def uninterrupted_run():
    """One shared fault-free 4-epoch reference trajectory (params
    snapshot + loss) for every matrix entry."""
    import jax
    ds = _data()
    base = _mlp()
    base.fit(_iter(ds), epochs=4)
    return (jax.tree.map(np.asarray, base.params),
            float(base.score(ds)), ds)


@pytest.mark.parametrize("site,spec", [
    ("step", "step:error=ConnectionError:nth=6:max=1"),
    ("iterator", "iterator:error=OSError:nth=9:max=1"),
    ("ckpt_write", "ckpt_write:error=OSError:nth=3:max=1"),
])
def test_fault_matrix_training_recovers(site, spec, tmp_path,
                                        uninterrupted_run):
    """For each training-side fault site, a seeded plan produces
    recovery: the chaotic run reaches the uninterrupted run's loss
    (bit-equal params for clean restores) and the injection counter
    incremented."""
    base_params, base_loss, ds = uninterrupted_run
    it = _iter(ds)

    net = _mlp()
    trainer = FaultTolerantTrainer(net, tmp_path,
                                   save_every_n_iterations=2,
                                   max_restarts=6)
    f0 = _counter(metrics.FAULTS_INJECTED, site=site)
    r0 = _counter(metrics.RESILIENCE_RESTARTS)
    with faults.active(spec):
        trainer.fit(it, epochs=4)
        fired = sum(s["fires"] for s in faults.stats().values())
    assert fired == 1
    assert _counter(metrics.FAULTS_INJECTED, site=site) == f0 + 1
    assert _counter(metrics.RESILIENCE_RESTARTS) == r0 + trainer.restarts
    assert trainer.restarts >= 1
    assert net.epoch == 4
    loss = float(net.score(ds))
    assert np.isfinite(loss)
    assert abs(loss - base_loss) <= 0.05
    if site in ("step", "iterator"):
        # fault hit after checkpoints existed → exact-resume trajectory
        assert _params_equal(base_params, net.params, tol=1e-5)


def test_fault_matrix_serving_sheds_not_blocks():
    """Serving-side acceptance: under an injected worker fault the
    queue sheds/errors rather than blocking, the counter increments,
    and the SAME worker thread keeps serving afterwards."""
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    net = _mlp()
    pi = ParallelInference(net, batch_limit=4, queue_limit=8,
                           buckets=(1, 2, 4))
    x = np.random.RandomState(0).randn(8, 8).astype(np.float32)
    f0 = _counter(metrics.FAULTS_INJECTED, site="serving")
    with faults.active("serving:error=RuntimeError:nth=1:max=1"):
        t0 = time.perf_counter()
        with pytest.raises(RuntimeError, match="injected fault"):
            pi.output(x[0], timeout=10.0)
        assert time.perf_counter() - t0 < 5.0         # fast error, no hang
    assert _counter(metrics.FAULTS_INJECTED, site="serving") == f0 + 1
    out = np.asarray(pi.output(x[1], timeout=10.0))   # worker survived
    assert out.shape[-1] == 3
    pi.shutdown()


def test_fault_matrix_worker_step_recovers(tmp_path):
    """ParallelWrapper fit loop site: FaultTolerantTrainer driving the
    wrapper (train_with=) restores and completes after an injected
    worker failure."""
    from deeplearning4j_tpu.parallel import ParallelWrapper
    ds = _data()
    it = _iter(ds)
    net = _mlp()
    pw = ParallelWrapper(net, mode=ParallelWrapper.SYNC,
                         prefetch_buffer=0)
    trainer = FaultTolerantTrainer(net, tmp_path,
                                   save_every_n_iterations=2,
                                   max_restarts=4, train_with=pw)
    f0 = _counter(metrics.FAULTS_INJECTED, site="worker_step")
    with faults.active("worker_step:error=ConnectionError:nth=6:max=1"):
        trainer.fit(it, epochs=3)
    assert _counter(metrics.FAULTS_INJECTED, site="worker_step") == f0 + 1
    assert trainer.restarts == 1
    assert net.epoch == 3
    assert np.isfinite(float(net.score(ds)))


# =========================================================================
# serving load-shedding + deadlines + graceful drain
# =========================================================================

def _blocked_pi(net, queue_limit=4):
    """ParallelInference whose worker is parked on an event — queue
    fills deterministically."""
    import threading
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    pi = ParallelInference(net, batch_limit=4, queue_limit=queue_limit,
                           buckets=(1, 2, 4))
    release = threading.Event()
    real = pi._infer

    def gated(batch):
        release.wait(20.0)
        return real(batch)

    pi._infer = gated
    return pi, release


def test_queue_full_sheds_fast():
    from deeplearning4j_tpu.parallel.inference import QueueFullError
    net = _mlp()
    pi, release = _blocked_pi(net, queue_limit=4)
    x = np.zeros(8, np.float32)
    obs_ = []
    s0 = _counter(metrics.REQS_SHED, reason="queue_full")
    # park the worker on the first request...
    obs_.append(pi.output_async(x))
    for _ in range(200):
        if pi._q.qsize() == 0:
            break
        time.sleep(0.005)
    assert pi._q.qsize() == 0         # worker holds it, queue is empty
    # ...then fill the queue exactly to its bound
    for _ in range(4):
        obs_.append(pi.output_async(x))
    t0 = time.perf_counter()
    with pytest.raises(QueueFullError):
        pi.output_async(x)
    assert time.perf_counter() - t0 < 0.5             # shed, not blocked
    assert _counter(metrics.REQS_SHED, reason="queue_full") == s0 + 1
    release.set()
    for ob in obs_:
        assert np.asarray(ob.get(10.0)).shape[-1] == 3
    pi.shutdown()


def test_deadline_expired_requests_skipped_not_computed():
    from deeplearning4j_tpu.parallel.inference import DeadlineExpiredError
    net = _mlp()
    pi, release = _blocked_pi(net, queue_limit=8)
    x = np.zeros(8, np.float32)
    s0 = _counter(metrics.REQS_SHED, reason="deadline")
    blocker = pi.output_async(x)                      # parks the worker
    time.sleep(0.05)
    doomed = pi.output_async(x, deadline_s=0.01)      # expires in queue
    alive = pi.output_async(x, deadline_s=30.0)
    time.sleep(0.1)                                   # let deadline pass
    release.set()
    with pytest.raises(DeadlineExpiredError):
        doomed.get(10.0)
    assert np.asarray(alive.get(10.0)).shape[-1] == 3
    assert np.asarray(blocker.get(10.0)).shape[-1] == 3
    assert _counter(metrics.REQS_SHED, reason="deadline") == s0 + 1
    pi.shutdown()


def test_zero_deadline_means_expired_not_disabled():
    """Falsy-deadline regression (ISSUE 13 satellite): an EXPLICIT
    deadline of 0/0.0 means "already expired" — the worker must shed
    it, never compute it. The old ``if deadline_s`` truthiness test
    silently read 0 as "no deadline"."""
    from deeplearning4j_tpu.parallel.inference import (
        DeadlineExpiredError)
    net = _mlp()
    pi, release = _blocked_pi(net, queue_limit=8)
    x = np.zeros(8, np.float32)
    s0 = _counter(metrics.REQS_SHED, reason="deadline")
    blocker = pi.output_async(x)                      # parks the worker
    time.sleep(0.05)
    doomed = pi.output_async(x, deadline_s=0.0)       # already expired
    release.set()
    with pytest.raises(DeadlineExpiredError):
        doomed.get(10.0)
    assert np.asarray(blocker.get(10.0)).shape[-1] == 3
    assert _counter(metrics.REQS_SHED, reason="deadline") == s0 + 1
    # output()'s timeout doubles as the deadline: timeout=0 must also
    # mean expired (sheds in the worker; the caller's get times out)
    pi2, release2 = _blocked_pi(net, queue_limit=8)
    b2 = pi2.output_async(x)
    time.sleep(0.05)
    with pytest.raises(TimeoutError):
        pi2.output(x, timeout=0)
    release2.set()
    assert np.asarray(b2.get(10.0)).shape[-1] == 3
    for _ in range(400):    # worker sheds it on its NEXT loop pass
        if _counter(metrics.REQS_SHED, reason="deadline") == s0 + 2:
            break
        time.sleep(0.005)
    assert _counter(metrics.REQS_SHED, reason="deadline") == s0 + 2
    pi.shutdown()
    pi2.shutdown()


def test_shutdown_flushes_queue_immediately():
    """Satellite acceptance: queued observables must not wait out their
    full timeout — shutdown errors them out immediately."""
    from deeplearning4j_tpu.parallel.inference import ServingShutdownError
    net = _mlp()
    pi, release = _blocked_pi(net, queue_limit=8)
    x = np.zeros(8, np.float32)
    s0 = _counter(metrics.REQS_SHED, reason="shutdown")
    blocker = pi.output_async(x)
    time.sleep(0.05)
    queued = [pi.output_async(x) for _ in range(4)]
    release.set()                                     # let blocker finish
    t0 = time.perf_counter()
    drained = pi.shutdown(timeout=10.0)
    flush_errors = 0
    for ob in queued:
        try:
            ob.get(timeout=0.5)
        except ServingShutdownError:
            flush_errors += 1
    assert time.perf_counter() - t0 < 5.0             # no 30 s stall
    assert flush_errors == drained > 0
    assert _counter(metrics.REQS_SHED, reason="shutdown") >= s0 + drained
    # post-shutdown submissions refuse immediately
    with pytest.raises(ServingShutdownError):
        pi.output_async(x)


# =========================================================================
# preemption (SIGTERM): in-process + subprocess clean-exit fence
# =========================================================================

def test_preemption_checkpoints_and_stops_cleanly(tmp_path):
    """Self-delivered SIGTERM mid-fit (the `preempt` named plan): the
    trainer checkpoints at the iteration boundary and returns instead
    of dying; resume_or_init continues from the preemption point."""
    ds = _data()
    net = _mlp()
    trainer = FaultTolerantTrainer(net, tmp_path,
                                   save_every_n_iterations=2)
    p0 = _counter(metrics.PREEMPTIONS)
    with faults.active("step:error=sigterm:nth=5:max=1"):
        trainer.fit(_iter(ds), epochs=5)
    assert trainer.preempted
    assert _counter(metrics.PREEMPTIONS) == p0 + 1
    assert net.epoch < 5                              # stopped early...
    ck = newest_checkpoint(tmp_path)
    assert ck is not None
    ok, why = rck.verify_checkpoint(ck)
    assert ok, why
    prog = json.loads((tmp_path / "progress.json").read_text())
    assert prog["iteration"] == net.iteration
    back = resume_or_init(lambda: _mlp(), tmp_path)   # ...and resumes
    assert back.iteration == net.iteration
    t2 = FaultTolerantTrainer(back, tmp_path, save_every_n_iterations=2)
    t2.fit(_iter(ds), epochs=5 - back.epoch)
    assert back.epoch == 5


_SIGTERM_CHILD = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.train.fault_tolerance import FaultTolerantTrainer

rng = np.random.RandomState(5)
x = rng.randn(96, 8).astype(np.float32)
y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 96)]
ds = DataSet(x, y)
it = ListDataSetIterator([b for b in ds.batch_by(24)], batch_size=24)
conf = (NeuralNetConfiguration.builder().seed(11)
        .updater(upd.Adam(learning_rate=5e-3)).list()
        .layer(DenseLayer(n_out=16, activation="tanh"))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(8)).build())
net = MultiLayerNetwork(conf).init()


class Beacon:
    def iteration_done(self, net, iteration, epoch):
        print(f"ITER {iteration}", flush=True)
    def on_epoch_start(self, net):
        pass
    def on_epoch_end(self, net):
        pass


net.listeners.append(Beacon())
trainer = FaultTolerantTrainer(net, %(ckdir)r, save_every_n_iterations=2)
trainer.fit(it, epochs=500)                 # SIGTERM ends this early
print(json.dumps({"preempted": trainer.preempted,
                  "iteration": net.iteration}), flush=True)
"""


def test_sigterm_during_fit_exits_zero_with_valid_checkpoint(tmp_path):
    """Satellite acceptance: SIGTERM-during-fit subprocess test — a
    valid final checkpoint and exit code 0."""
    child = subprocess.Popen(
        [sys.executable, "-c",
         _SIGTERM_CHILD % {"repo": str(REPO), "ckdir": str(tmp_path)}],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    # wait until training demonstrably runs, then preempt
    saw_iters = 0
    for line in child.stdout:
        if line.startswith("ITER"):
            saw_iters += 1
            if saw_iters == 6:
                child.send_signal(signal.SIGTERM)
                break
    out, _ = child.communicate(timeout=120)
    assert child.returncode == 0, out
    tail = [l for l in out.splitlines() if l.startswith("{")]
    assert tail, out
    final = json.loads(tail[-1])
    assert final["preempted"] is True
    assert final["iteration"] >= 6
    ck = newest_checkpoint(tmp_path)
    assert ck is not None
    ok, why = rck.verify_checkpoint(ck)
    assert ok, why
    back = resume_or_init(lambda: _mlp(), tmp_path)
    assert back.iteration == final["iteration"]


# =========================================================================
# crash consistency: kill -9 at arbitrary points during save
# =========================================================================

_KILL9_CHILD = r"""
import sys
import numpy as np
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.serialization import ModelSerializer

conf = (NeuralNetConfiguration.builder().seed(11)
        .updater(upd.Adam(learning_rate=5e-3)).list()
        .layer(DenseLayer(n_out=64, activation="tanh"))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(8)).build())
net = MultiLayerNetwork(conf).init()
print("READY", flush=True)
i = 0
while True:                       # save continuously until killed
    i += 1
    net.iteration = i
    ModelSerializer.write_model(
        net, %(ckdir)r + f"/checkpoint_iter_{i %% 4}.zip")
    print(f"SAVED {i}", flush=True)
"""


def test_kill9_during_save_leaves_restorable_newest(tmp_path):
    """Acceptance: kill -9 at ANY point during save leaves either the
    old or the new checkpoint fully restorable — several kill times
    sampled across the save cycle, every survivor directory must hold
    a valid newest checkpoint."""
    for delay in (0.02, 0.075):
        d = tmp_path / f"run_{int(delay * 1000)}"
        d.mkdir()
        child = subprocess.Popen(
            [sys.executable, "-c",
             _KILL9_CHILD % {"repo": str(REPO), "ckdir": str(d)}],
            stdout=subprocess.PIPE, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        saves = 0
        for line in child.stdout:
            if line.startswith("SAVED"):
                saves += 1
                if saves >= 2:
                    break
        time.sleep(delay)         # land the kill mid-save-cycle
        child.kill()              # SIGKILL: no cleanup code runs
        child.wait(timeout=60)
        child.stdout.close()
        ck = newest_checkpoint(d)
        assert ck is not None, f"no valid checkpoint after kill@{delay}"
        ok, why = rck.verify_checkpoint(ck)
        assert ok, f"kill@{delay}: {why}"
        back = ModelSerializer.restore_multi_layer_network(str(ck))
        assert back.iteration >= 1


# =========================================================================
# mid-epoch position + exact resume
# =========================================================================

def test_mid_epoch_restore_replays_exact_trajectory(tmp_path):
    """A fault mid-epoch-2 restores to the mid-epoch checkpoint, skips
    the already-trained batches (progress.json batch_in_epoch), and
    ends bit-identical to the uninterrupted run."""
    ds = _data()
    it = _iter(ds)                                    # 4 batches/epoch
    base = _mlp()
    base.fit(it, epochs=3)

    net = _mlp()
    trainer = FaultTolerantTrainer(net, tmp_path,
                                   save_every_n_iterations=2,
                                   max_restarts=3)
    # 7th step = batch 3 of epoch 2; newest ckpt iter 6 (batch 2),
    # restore must skip exactly 2 batches
    with faults.active("step:error=ConnectionError:nth=7:max=1"):
        trainer.fit(it, epochs=3)
    assert trainer.restarts == 1
    assert net.iteration == base.iteration == 12
    assert _params_equal(base.params, net.params, tol=1e-5)
