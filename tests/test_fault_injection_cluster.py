"""Cluster fault injection (VERDICT r2 #7; reference analog: Spark
task retry re-running failed partitions + MeshOrganizer node-failure
remap, SURVEY §5): SIGKILL one worker of a live 2-process
``jax.distributed`` cluster mid-fit, have the cluster manager (this
test harness) tear down the survivor, re-form the cluster, and
``resume_or_init`` from the last checkpoint — training must continue
to the same converged loss as an uninterrupted run.
"""
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

WORKER = textwrap.dedent("""
    import os, sys, warnings
    sys.path.insert(0, %(repo)r)
    warnings.filterwarnings("ignore")
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=os.environ["COORD"],
        num_processes=2, process_id=int(os.environ["PROC_ID"]))
    import numpy as np
    import jax.numpy as jnp

    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.parallel import (
        SharedTrainingMaster, ShardedDataSetIterator,
        SparkDl4jMultiLayer)
    from deeplearning4j_tpu.train.fault_tolerance import resume_or_init
    from deeplearning4j_tpu.train.listeners import CheckpointListener

    pid = jax.process_index()
    phase = os.environ["PHASE"]
    ckdir = os.environ["CKPT_DIR"]
    TOTAL_EPOCHS = 6

    def factory():
        conf = (NeuralNetConfiguration.builder().seed(42)
                .updater(upd.Adam(learning_rate=0.05)).list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)          # same data on every proc
    x = rng.standard_normal((384, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    data = [DataSet(x[i:i + 64], y[i:i + 64])
            for i in range(0, 384, 64)]

    net = factory() if phase != "resume" else \
        resume_or_init(factory, ckdir)
    if phase == "resume":
        assert net.iteration > 0, "resume_or_init found no checkpoint"
        print(f"proc {pid} resumed at epoch {net.epoch} "
              f"iter {net.iteration}", flush=True)

    if phase in ("inject", "resume") and pid == 0:
        # one writer: proc 0 checkpoints every other step (SYNC'd
        # params — the ENCODED master keeps net.params current)
        net.listeners.append(CheckpointListener(
            ckdir, save_every_n_iterations=2, keep_last=3))

    if phase == "inject" and pid == 1:
        class Killer:
            def iteration_done(self, net, iteration, epoch):
                if iteration >= 8:
                    print("proc 1 self-destructing", flush=True)
                    sys.stdout.flush()
                    os.kill(os.getpid(), 9)   # simulated chip loss
        net.listeners.append(Killer())

    master = SharedTrainingMaster.Builder(64).build()
    trainer = SparkDl4jMultiLayer(net, master)
    trainer.fit(ShardedDataSetIterator(data),
                epochs=TOTAL_EPOCHS - net.epoch)
    score = trainer.score()
    print(f"proc {pid} final epoch {net.epoch} score {score:.6f}",
          flush=True)
    print(f"proc {pid} DONE", flush=True)
""")


def _launch(repo, script, port, phase, ckdir):
    procs = []
    for pid in range(2):
        env = dict(os.environ,
                   COORD=f"127.0.0.1:{port}", PROC_ID=str(pid),
                   PHASE=phase, CKPT_DIR=str(ckdir),
                   XLA_FLAGS="--xla_force_host_platform_device_count=2",
                   JAX_PLATFORMS="cpu")
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    return procs


def _wait_all(procs, timeout=240):
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=timeout)
        outs.append(out)
    return outs


def _score(out):
    import re
    m = re.search(r"score (-?[\d.]+)", out)
    assert m, out[-2000:]
    return float(m.group(1))


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("DL4J_TPU_SKIP_MP") == "1",
                    reason="multi-process test disabled")
def test_kill_worker_resume_converges(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(WORKER % {"repo": repo})
    base_port = 29100 + (os.getpid() % 400)

    # 1. uninterrupted reference run
    ck_full = tmp_path / "ck_full"
    outs = _wait_all(_launch(repo, script, base_port, "full", ck_full))
    full_score = _score(outs[0])
    assert full_score < 0.35, outs[0][-2000:]

    # 2. interrupted run: proc 1 SIGKILLs itself mid-fit; the harness
    # (cluster manager) detects the dead node and tears down the peer
    ckdir = tmp_path / "ck"
    procs = _launch(repo, script, base_port + 1, "inject", ckdir)
    t0 = time.time()
    while procs[1].poll() is None and time.time() - t0 < 240:
        time.sleep(0.5)
    assert procs[1].poll() == -signal.SIGKILL, "worker 1 did not die"
    time.sleep(1.0)
    procs[0].kill()                    # failure-detector teardown
    procs[0].communicate(timeout=60)
    procs[1].communicate(timeout=60)
    ckpts = list(ckdir.glob("checkpoint_*.zip"))
    assert ckpts, "no checkpoint written before the failure"

    # 3. re-formed cluster resumes from the newest checkpoint
    outs = _wait_all(_launch(repo, script, base_port + 2, "resume",
                             ckdir))
    for pid, out in enumerate(outs):
        assert f"proc {pid} DONE" in out, out[-2000:]
    assert "resumed at epoch" in outs[0]
    resumed_score = _score(outs[0])

    # same converged loss as the uninterrupted run
    assert resumed_score < 0.35, resumed_score
    assert abs(resumed_score - full_score) < 0.1, (resumed_score,
                                                   full_score)
