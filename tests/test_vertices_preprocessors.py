"""Vertices (L2/LastTimeStep/DuplicateToTimeSeries/ReverseTimeSeries/
Preprocessor), the InputPreProcessor family, and ROCBinary.

Reference analogs: ComputationGraphTestRNN / TestGraphNodes,
preprocessor unit tests, ROCBinaryTest (SURVEY §4).
"""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.vertices import (
    L2Vertex, LastTimeStepVertex, DuplicateToTimeSeriesVertex,
    ReverseTimeSeriesVertex, PreprocessorVertex, vertex_from_dict,
)
from deeplearning4j_tpu.nn.preprocessors import (
    CnnToFeedForwardPreProcessor, FeedForwardToCnnPreProcessor,
    RnnToFeedForwardPreProcessor, FeedForwardToRnnPreProcessor,
    CnnToRnnPreProcessor, RnnToCnnPreProcessor,
    ComposableInputPreProcessor, preprocessor_from_dict,
)
from deeplearning4j_tpu.eval_ import ROCBinary


class TestVertices:
    def test_l2_vertex(self):
        a = jnp.asarray([[3.0, 0.0], [0.0, 0.0]])
        b = jnp.asarray([[0.0, 4.0], [0.0, 0.0]])
        d = L2Vertex().apply([a, b])
        assert np.isclose(float(d[0, 0]), 5.0)
        # coincident inputs: finite gradient (guarded sqrt)
        g = jax.grad(lambda x: jnp.sum(L2Vertex().apply([x, x])))(a)
        assert np.all(np.isfinite(np.asarray(g)))

    def test_last_time_step_vertex_masked(self):
        x = jnp.arange(24.0).reshape(2, 4, 3)
        mask = jnp.asarray([[1, 1, 0, 0], [1, 1, 1, 1]], jnp.float32)
        v = LastTimeStepVertex()
        out = v.apply([x], mask=mask)
        assert np.allclose(out[0], x[0, 1])     # len 2 -> step 1
        assert np.allclose(out[1], x[1, 3])
        assert np.allclose(v.apply([x]), x[:, -1])
        assert v.output_shape([(4, 3)]) == (3,)

    def test_duplicate_to_time_series(self):
        vec = jnp.asarray([[1.0, 2.0]])
        ts = jnp.zeros((1, 5, 7))
        out = DuplicateToTimeSeriesVertex().apply([vec, ts])
        assert out.shape == (1, 5, 2)
        assert np.allclose(out[0, 3], [1.0, 2.0])

    def test_reverse_time_series_masked(self):
        x = jnp.arange(8.0).reshape(1, 8, 1)
        mask = jnp.asarray([[1, 1, 1, 0, 0, 0, 0, 0]], jnp.float32)
        out = ReverseTimeSeriesVertex().apply([x], mask=mask)
        # valid prefix reversed, padding untouched
        assert np.allclose(out[0, :3, 0], [2, 1, 0])
        assert np.allclose(out[0, 3:, 0], [3, 4, 5, 6, 7])
        full = ReverseTimeSeriesVertex().apply([x])
        assert np.allclose(full[0, :, 0], np.arange(8.0)[::-1])

    def test_preprocessor_vertex_roundtrip(self):
        v = PreprocessorVertex(
            preprocessor=CnnToFeedForwardPreProcessor())
        x = jnp.ones((2, 3, 3, 2))
        assert v.apply([x]).shape == (2, 18)
        assert v.output_shape([(3, 3, 2)]) == (18,)
        back = vertex_from_dict(v.to_dict())
        assert isinstance(back.preprocessor, CnnToFeedForwardPreProcessor)


class TestPreprocessors:
    def test_cnn_ff_roundtrip(self):
        x = jnp.arange(36.0).reshape(1, 3, 3, 4)
        ff = CnnToFeedForwardPreProcessor().pre_process(x)
        assert ff.shape == (1, 36)
        back = FeedForwardToCnnPreProcessor(
            height=3, width=3, channels=4).pre_process(ff)
        assert np.allclose(back, x)

    def test_rnn_ff_roundtrip(self):
        x = jnp.arange(30.0).reshape(2, 5, 3)
        ff = RnnToFeedForwardPreProcessor().pre_process(x)
        assert ff.shape == (10, 3)
        back = FeedForwardToRnnPreProcessor(
            time_steps=5).pre_process(ff)
        assert np.allclose(back, x)

    def test_rnn_ff_mask(self):
        mask = jnp.ones((2, 5))
        m = RnnToFeedForwardPreProcessor().propagate_mask(mask)
        assert m.shape == (10,)
        m2 = FeedForwardToRnnPreProcessor(
            time_steps=5).propagate_mask(m)
        assert m2.shape == (2, 5)

    def test_cnn_rnn(self):
        x = jnp.ones((2, 4, 3, 5))
        seq = CnnToRnnPreProcessor().pre_process(x)
        assert seq.shape == (2, 4, 15)
        back = RnnToCnnPreProcessor(width=3, channels=5).pre_process(seq)
        assert back.shape == (2, 4, 3, 5)

    def test_output_shapes_match_pre_process(self):
        cases = [
            (CnnToFeedForwardPreProcessor(), (4, 4, 3)),
            (FeedForwardToCnnPreProcessor(height=2, width=2,
                                          channels=3), (12,)),
            (CnnToRnnPreProcessor(), (4, 4, 3)),
            (RnnToCnnPreProcessor(width=2, channels=2), (5, 4)),
        ]
        for proc, shape in cases:
            x = jnp.zeros((2,) + shape)
            got = proc.pre_process(x).shape[1:]
            assert tuple(got) == tuple(proc.output_shape(shape)), proc

    def test_composable_and_serialization(self):
        comp = ComposableInputPreProcessor(processors=[
            CnnToFeedForwardPreProcessor(),
            FeedForwardToCnnPreProcessor(height=2, width=2, channels=9)])
        x = jnp.ones((1, 6, 6, 1))
        assert comp.pre_process(x).shape == (1, 2, 2, 9)
        back = preprocessor_from_dict(comp.to_dict())
        assert isinstance(back, ComposableInputPreProcessor)
        # nested procs rehydrate as dicts -> rebuild
        assert len(back.processors) == 2

    def test_in_network_config(self):
        """cnn -> preprocessor -> dense end-to-end with JSON roundtrip."""
        from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.config import (InputType,
                                                  MultiLayerConfiguration)
        from deeplearning4j_tpu.nn.layers import (ConvolutionLayer,
                                                  OutputLayer)
        from deeplearning4j_tpu.nn import updaters as upd

        conf = (NeuralNetConfiguration.builder().seed(3)
                .updater(upd.Sgd(learning_rate=1e-2)).list()
                .layer(ConvolutionLayer(n_out=2, kernel_size=(3, 3),
                                        padding="VALID",
                                        activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .input_pre_processor(1, CnnToFeedForwardPreProcessor())
                .set_input_type(InputType.convolutional(5, 5, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(0).randn(4, 5, 5, 1).astype(np.float32)
        out = net.output(x)
        assert out.shape == (4, 2)
        # JSON round-trip preserves the preprocessor map
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        assert isinstance(conf2.input_preprocessors[1],
                          CnnToFeedForwardPreProcessor)
        net2 = MultiLayerNetwork(conf2).init()
        assert net2.output(x).shape == (4, 2)


class TestROCBinary:
    def test_perfect_and_random(self):
        roc = ROCBinary()
        labels = np.asarray([[1, 0], [1, 1], [0, 0], [0, 1]], np.float32)
        # output 0: perfectly separable; output 1: inverted
        # (col-1 labels are [0,1,0,1] -> scores 1-label)
        preds = np.asarray([[0.9, 0.9], [0.8, 0.1], [0.1, 0.8],
                            [0.2, 0.2]], np.float32)
        roc.eval(labels, preds)
        assert roc.num_labels() == 2
        assert roc.calculate_auc(0) == 1.0
        assert roc.calculate_auc(1) == 0.0
        assert np.isclose(roc.average_auc(), 0.5)
        assert "out 0" in roc.stats()

    def test_masked_columns(self):
        roc = ROCBinary()
        labels = np.asarray([[1], [0], [1], [0]], np.float32)
        preds = np.asarray([[0.9], [0.8], [0.2], [0.1]], np.float32)
        mask = np.asarray([[1], [0], [0], [1]], np.float32)
        roc.eval(labels, preds, mask=mask)
        assert roc.calculate_auc(0) == 1.0   # kept rows are separable

    def test_accumulates_batches(self):
        roc = ROCBinary()
        rng = np.random.RandomState(0)
        for _ in range(3):
            labels = (rng.rand(16, 3) > 0.5).astype(np.float32)
            roc.eval(labels, labels * 0.8 + 0.1)
        assert roc.num_labels() == 3
        assert roc.average_auc() == 1.0


def test_feed_forward_applies_preprocessors():
    """Regression: feed_forward/activate_selected_layers must honour
    conf.input_preprocessors like _forward does."""
    import numpy as np
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import (ConvolutionLayer,
                                              DenseLayer, OutputLayer)
    from deeplearning4j_tpu.nn import updaters as upd

    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(upd.Sgd(learning_rate=1e-2)).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(ConvolutionLayer(n_out=2, kernel_size=(2, 2),
                                    padding="VALID",
                                    activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .input_pre_processor(1, FeedForwardToCnnPreProcessor(
                height=4, width=4, channels=1))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.RandomState(0).randn(3, 8).astype(np.float32)
    acts = net.feed_forward(x)
    assert acts[-1].shape == (3, 2)
    mid = net.activate_selected_layers(0, 1, x)
    assert mid.ndim == 4                    # conv activation map


VERTEX_SPECS = {
    "MergeVertex": ({}, [(2, 3), (2, 3)]),
    "ElementWiseVertex": (dict(op="add"), [(4,), (4,)]),
    "SubsetVertex": (dict(from_=0, to=1), [(4,)]),
    "StackVertex": ({}, [(3,), (3,)]),
    "UnstackVertex": (dict(index=0, num=2), [(4,)]),
    "ScaleVertex": (dict(scale=2.0), [(4,)]),
    "ShiftVertex": (dict(shift=1.0), [(4,)]),
    "L2NormalizeVertex": ({}, [(4,)]),
    "ReshapeVertex": (dict(shape=(2, 2)), [(4,)]),
    "FlattenVertex": ({}, [(2, 2)]),
    "PoolHelperVertex": ({}, [(3, 3, 2)]),
    "AttentionVertex": (dict(n_heads=1), [(4, 6), (4, 6), (4, 6)]),
    "L2Vertex": ({}, [(4,), (4,)]),
    "LastTimeStepVertex": ({}, [(4, 3)]),
    "DuplicateToTimeSeriesVertex": ({}, [(3,), (5, 3)]),
    "ReverseTimeSeriesVertex": ({}, [(4, 3)]),
    "PreprocessorVertex": (dict(
        preprocessor=CnnToFeedForwardPreProcessor()), [(3, 3, 2)]),
}


def test_every_registered_vertex_has_spec():
    from deeplearning4j_tpu.nn.vertices import _VERTEX_REGISTRY
    missing = sorted(set(_VERTEX_REGISTRY) - set(VERTEX_SPECS))
    assert not missing, f"vertices without round-trip spec: {missing}"


def test_vertex_registry_roundtrip():
    from deeplearning4j_tpu.nn.vertices import (_VERTEX_REGISTRY,
                                                vertex_from_dict)
    for name, (kwargs, in_shapes) in sorted(VERTEX_SPECS.items()):
        v = _VERTEX_REGISTRY[name](**kwargs)
        back = vertex_from_dict(v.to_dict())
        assert type(back) is type(v), name
        xs = [jnp.asarray(np.random.RandomState(1)
                          .randn(2, *s).astype(np.float32))
              for s in in_shapes]
        if getattr(v, "needs_mask", False):
            y1, y2 = v.apply(xs, mask=None), back.apply(xs, mask=None)
        else:
            y1, y2 = v.apply(xs), back.apply(xs)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                                   rtol=1e-6, err_msg=name)
