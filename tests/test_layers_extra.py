"""Gradient + shape checks for the extra layer families
(locally-connected, capsnet primary/strength, OCNN, shape utilities).

Reference analog: GradientCheckTests / CNNGradientCheckTest coverage of
LocallyConnected*, CapsNet layers, OCNNOutputLayer (SURVEY §4).
"""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.layers import (
    LocallyConnected1DLayer, LocallyConnected2DLayer, PrimaryCapsules,
    CapsuleStrengthLayer, OCNNOutputLayer, FrozenLayerWithBackprop,
    MaskLayer, RepeatVector, Cropping1DLayer, Cropping3DLayer,
    ZeroPadding1DLayer, ZeroPadding3DLayer, Deconvolution3DLayer,
    DenseLayer, ConvolutionLayer, CapsuleLayer,
)
from deeplearning4j_tpu.nn.layers.base import layer_from_dict
from deeplearning4j_tpu.utils import check_gradients

KEY = jax.random.PRNGKey(0)


def _run(layer, input_shape, batch=2):
    params, state, out_shape = layer.init(KEY, input_shape)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch,) + input_shape)
    y, _ = layer.apply(params, state, x)
    assert y.shape == (batch,) + tuple(out_shape), (y.shape, out_shape)
    return params, state, x, y


def _gradcheck(layer, input_shape, batch=2, tol=1e-4):
    params, state, _ = layer.init(KEY, input_shape)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch,) + input_shape)

    def loss(p, xx):
        y, _ = layer.apply(p, state, xx)
        return jnp.sum(jnp.sin(y))

    if params:
        check_gradients(loss, params, x, max_rel_error=tol)
    check_gradients(lambda xx, p: loss(p, xx), x, params,
                    max_rel_error=tol)


def test_locally_connected_2d_gradcheck():
    _gradcheck(LocallyConnected2DLayer(n_out=2, kernel=(2, 2),
                                       activation="tanh"), (4, 4, 3))


def test_locally_connected_2d_differs_per_position():
    # with unshared weights, identical input patches map to DIFFERENT
    # outputs at different positions (the defining property vs conv)
    layer = LocallyConnected2DLayer(n_out=1, kernel=(1, 1))
    params, state, _ = layer.init(KEY, (3, 3, 1))
    x = jnp.ones((1, 3, 3, 1))
    y, _ = layer.apply(params, state, x)
    vals = np.asarray(y).ravel()
    assert len(np.unique(np.round(vals, 6))) > 1


def test_locally_connected_1d_gradcheck():
    _gradcheck(LocallyConnected1DLayer(n_out=3, kernel=2,
                                       activation="tanh"), (6, 2))


def test_capsnet_stack():
    # PrimaryCapsules -> CapsuleLayer -> CapsuleStrengthLayer end-to-end
    prim = PrimaryCapsules(capsule_dim=4, channels=2, kernel=(3, 3),
                           strides=(2, 2))
    p1, s1, shp1 = prim.init(KEY, (8, 8, 1))
    caps = CapsuleLayer(capsules=3, capsule_dim=6, routings=2)
    p2, s2, shp2 = caps.init(KEY, shp1)
    strength = CapsuleStrengthLayer()
    _, _, shp3 = strength.init(KEY, shp2)
    assert shp3 == (3,)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 8, 8, 1))
    h, _ = prim.apply(p1, s1, x)
    # squash keeps norms < 1
    norms = jnp.linalg.norm(h, axis=-1)
    assert float(jnp.max(norms)) < 1.0
    h, _ = caps.apply(p2, s2, h)
    probs, _ = strength.apply({}, {}, h)
    assert probs.shape == (2, 3)
    assert float(jnp.min(probs)) >= 0


def test_ocnn_output_layer():
    layer = OCNNOutputLayer(hidden_size=8, nu=0.1, activation="sigmoid")
    params, state, out_shape = layer.init(KEY, (5,))
    assert out_shape == (1,)
    x = jax.random.normal(jax.random.PRNGKey(3), (16, 5))
    scores, _ = layer.apply(params, state, x)
    loss_fn = layer.compute_loss_fn()
    loss = loss_fn(None, scores)
    assert np.isfinite(float(loss))
    # gradients flow to V and w through the hinge
    def f(p):
        s, _ = layer.apply(p, state, x)
        return loss_fn(None, s)
    g = jax.grad(f)(params)
    assert any(float(jnp.sum(jnp.abs(leaf))) > 0
               for leaf in jax.tree.leaves(g))
    # r update: nu-quantile of scores
    r2 = layer.updated_r(scores)
    frac_below = float(jnp.mean(scores <= r2))
    assert abs(frac_below - 0.1) < 0.2


def test_frozen_with_backprop_passes_input_grads():
    inner = DenseLayer(n_out=3, activation="tanh")
    layer = FrozenLayerWithBackprop(underlying=inner)
    params, state, _ = layer.init(KEY, (4,))
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 4))

    def loss_p(p):
        y, _ = layer.apply(p, state, x)
        return jnp.sum(y)

    def loss_x(xx):
        y, _ = layer.apply(params, state, xx)
        return jnp.sum(y)

    gp = jax.grad(loss_p)(params)
    assert all(float(jnp.sum(jnp.abs(leaf))) == 0
               for leaf in jax.tree.leaves(gp))      # params frozen
    gx = jax.grad(loss_x)(x)
    assert float(jnp.sum(jnp.abs(gx))) > 0           # input grads flow


def test_mask_layer():
    layer = MaskLayer()
    _, state, _ = layer.init(KEY, (4, 3))
    x = jnp.ones((2, 4, 3))
    mask = jnp.asarray([[1, 1, 0, 0], [1, 1, 1, 1]], jnp.float32)
    y, _ = layer.apply({}, state, x, mask=mask)
    assert float(jnp.sum(y[0, 2:])) == 0
    assert float(jnp.sum(y[1])) == 12


def test_repeat_vector():
    layer = RepeatVector(n=3)
    _run(layer, (5,))
    y, _ = layer.apply({}, {}, jnp.arange(4.0).reshape(1, 4))
    assert np.allclose(y[0, 0], y[0, 2])


def test_crop_pad_1d_3d():
    _run(Cropping1DLayer(cropping=(1, 2)), (8, 3))
    _run(ZeroPadding1DLayer(padding=(2, 1)), (8, 3))
    _run(Cropping3DLayer(cropping=(1, 1, 0, 1, 1, 0)), (4, 5, 6, 2))
    _run(ZeroPadding3DLayer(padding=(1, 0, 2, 0, 0, 1)), (3, 3, 3, 2))
    # pad then crop is identity
    pad = ZeroPadding1DLayer(padding=(2, 2))
    crop = Cropping1DLayer(cropping=(2, 2))
    x = jax.random.normal(KEY, (1, 4, 2))
    y, _ = pad.apply({}, {}, x)
    z, _ = crop.apply({}, {}, y)
    assert np.allclose(z, x)


def test_deconv3d_gradcheck():
    _gradcheck(Deconvolution3DLayer(n_out=2, kernel=(2, 2, 2),
                                    strides=(2, 2, 2),
                                    activation="tanh"), (2, 2, 2, 3),
               tol=5e-4)
    _, _, shp = Deconvolution3DLayer(
        n_out=2, strides=(2, 2, 2)).init(KEY, (2, 3, 4, 1))
    assert shp == (4, 6, 8, 2)


def test_extra_layers_serialization_roundtrip():
    for layer in [LocallyConnected2DLayer(n_out=2, kernel=(2, 2)),
                  PrimaryCapsules(capsule_dim=4, channels=2),
                  OCNNOutputLayer(hidden_size=8),
                  RepeatVector(n=3),
                  Cropping3DLayer(cropping=(1, 0, 1, 0, 1, 0)),
                  FrozenLayerWithBackprop(
                      underlying=DenseLayer(n_out=3))]:
        d = layer.to_dict()
        back = layer_from_dict(d)
        assert type(back) is type(layer)
        assert back.to_dict() == d


def test_locally_connected_in_network():
    """End-to-end: locally-connected feature extractor trains."""
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator

    rng = np.random.RandomState(0)
    x = rng.randn(32, 6, 6, 1).astype(np.float32)
    labels = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 32)]
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(upd.Adam(learning_rate=5e-3)).list()
            .layer(LocallyConnected2DLayer(n_out=2, kernel=(3, 3),
                                           activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.convolutional(6, 6, 1)).build())
    net = MultiLayerNetwork(conf).init()
    ds = DataSet(x, labels)
    s0 = net.score(ds)
    net.fit(ListDataSetIterator([ds], batch_size=32), epochs=20)
    assert net.score(ds) < s0


def test_samediff_layer_custom_forward():
    """Custom layer via param shapes + pure fn (reference SameDiffLayer):
    trains end-to-end with autodiff-provided backprop."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.layers import (SameDiffLayer,
                                              SameDiffOutputLayer)
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator

    custom = SameDiffLayer(
        param_shapes={"W": (6, 10), "b": (10,)},
        fn=lambda p, x: jnp.tanh(x @ p["W"] + p["b"]),
        output_shape_fn=lambda s: (10,))
    conf = (NeuralNetConfiguration.builder().seed(4)
            .updater(upd.Adam(learning_rate=1e-2)).list()
            .layer(custom)
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    assert net.params["layer_0"]["W"].shape == (6, 10)
    rng = np.random.RandomState(0)
    x = rng.randn(32, 6).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    ds = DataSet(x, y)
    s0 = net.score(ds)
    net.fit(ListDataSetIterator([ds], batch_size=32), epochs=20)
    assert net.score(ds) < s0

    # custom OUTPUT layer with custom loss
    out_layer = SameDiffOutputLayer(
        param_shapes={"W": (6, 1)},
        fn=lambda p, x: x @ p["W"],
        output_shape_fn=lambda s: (1,),
        loss_fn=lambda labels, out: jnp.mean((labels - out) ** 2))
    conf2 = (NeuralNetConfiguration.builder().seed(4)
             .updater(upd.Sgd(learning_rate=0.05)).list()
             .layer(out_layer)
             .set_input_type(InputType.feed_forward(6)).build())
    net2 = MultiLayerNetwork(conf2).init()
    yreg = (x @ rng.randn(6, 1)).astype(np.float32)
    ds2 = DataSet(x, yreg)
    s0 = net2.score(ds2)
    net2.fit(ListDataSetIterator([ds2], batch_size=32), epochs=30)
    assert net2.score(ds2) < s0 / 2


def test_samediff_layer_bias_heuristic_and_mask():
    """Regressions: rank-2 params named b* still get random init; the
    mask kwarg reaches mask-aware fns; mask-unaware losses get a
    masked fallback."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.layers import (SameDiffLayer,
                                              SameDiffOutputLayer)
    layer = SameDiffLayer(
        param_shapes={"blend": (4, 8), "bias": (8,)},
        fn=lambda p, x: x @ p["blend"] + p["bias"],
        output_shape_fn=lambda s: (8,))
    params, _, _ = layer.init(jax.random.PRNGKey(0), (4,))
    assert float(jnp.abs(params["blend"]).sum()) > 0    # NOT zero-init
    assert float(jnp.abs(params["bias"]).sum()) == 0

    seen = {}

    def mask_fn(p, x, mask=None):
        seen["mask"] = mask
        return x

    ml = SameDiffLayer(param_shapes={}, fn=mask_fn)
    ml.init(jax.random.PRNGKey(0), (4, 3))
    m = jnp.ones((2, 4))
    ml.apply({}, {}, jnp.ones((2, 4, 3)), mask=m)
    assert seen["mask"] is m

    # mask-unaware loss: padded steps do not change the loss
    out_layer = SameDiffOutputLayer(
        param_shapes={}, fn=lambda p, x: x,
        loss_fn=lambda labels, out: jnp.mean((labels - out) ** 2))
    lf = out_layer.compute_loss_fn()
    y = jnp.ones((2, 3, 1))
    out = jnp.zeros((2, 3, 1))
    mask = jnp.asarray([[1, 1, 0], [1, 0, 0]], jnp.float32)
    masked = float(lf(y, out, mask=mask))
    assert abs(masked - 1.0) < 1e-6       # mean over REAL steps only
