"""Registry-wide updater + schedule serialization round-trip
(reference: Jackson round-trip of IUpdater/ISchedule beans inside the
NeuralNetConfiguration JSON)."""
import numpy as np
import pytest

from deeplearning4j_tpu.nn import updaters as upd

UPDATER_SPECS = {
    "Sgd": dict(learning_rate=0.1),
    "Adam": dict(learning_rate=1e-3, beta1=0.85),
    "AdamW": dict(learning_rate=1e-3, weight_decay=0.02),
    "AdaMax": dict(learning_rate=1e-3),
    "Nadam": dict(learning_rate=1e-3),
    "AMSGrad": dict(learning_rate=1e-3),
    "Nesterovs": dict(learning_rate=0.1, momentum=0.95),
    "Momentum": dict(learning_rate=0.1),
    "RmsProp": dict(learning_rate=1e-3),
    "AdaGrad": dict(learning_rate=0.05),
    "AdaDelta": dict(),
    "NoOp": dict(),
}

SCHEDULE_SPECS = {
    "FixedSchedule": dict(value=0.1),
    "StepSchedule": dict(initial=0.1, decay_rate=0.5, step=10),
    "ExponentialSchedule": dict(initial=0.1, gamma=0.99),
    "InverseSchedule": dict(initial=0.1, gamma=0.01, power=1.0),
    "PolySchedule": dict(initial=0.1, power=2.0, max_iter=100),
    "SigmoidSchedule": dict(initial=0.1, gamma=0.1, step_center=50),
    "CosineSchedule": dict(initial=0.1, max_iter=100),
    "WarmupSchedule": dict(warmup_steps=10),
}


def _all_subclasses(cls):
    out = []
    for c in cls.__subclasses__():
        out.append(c)
        out.extend(_all_subclasses(c))
    return out


def test_every_updater_and_schedule_has_spec():
    missing_u = {c.__name__ for c in _all_subclasses(upd.Updater)} - \
        set(UPDATER_SPECS)
    assert not missing_u, f"updaters without round-trip spec: {missing_u}"
    missing_s = {c.__name__ for c in upd.Schedule.__subclasses__()} - \
        set(SCHEDULE_SPECS)
    assert not missing_s, f"schedules without spec: {missing_s}"


@pytest.mark.parametrize("name", sorted(UPDATER_SPECS))
def test_updater_roundtrip(name):
    u = getattr(upd, name)(**UPDATER_SPECS[name])
    d = u.to_dict()
    back = upd.updater_from_dict(d)
    assert type(back) is type(u)
    assert back.to_dict() == d
    # the optax transform from the rehydrated bean is numerically
    # identical: one update step on a fixed grad
    import jax.numpy as jnp
    import optax
    params = {"w": jnp.ones(4)}
    grads = {"w": jnp.full(4, 0.5)}
    for bean in (u, back):
        tx = bean.to_optax()
        st = tx.init(params)
        upds, _ = tx.update(grads, st, params)
        bean._probe = np.asarray(upds["w"])
    np.testing.assert_allclose(u._probe, back._probe, rtol=1e-7)


@pytest.mark.parametrize("name", sorted(SCHEDULE_SPECS))
def test_schedule_roundtrip(name):
    s = getattr(upd, name)(**SCHEDULE_SPECS[name])
    d = s.to_dict()
    back = upd.schedule_from_dict(d)
    assert type(back) is type(s)
    for step in (0, 7, 55, 99):
        np.testing.assert_allclose(float(s(step)), float(back(step)),
                                   rtol=1e-7, err_msg=f"{name}@{step}")


@pytest.mark.parametrize("name", sorted(UPDATER_SPECS))
def test_updater_with_schedule_roundtrip(name):
    if name == "NoOp":
        pytest.skip("NoOp has no learning rate")
    u = getattr(upd, name)(**UPDATER_SPECS[name])
    if not hasattr(u, "schedule"):
        pytest.skip(f"{name} has no schedule field")
    u.schedule = upd.StepSchedule(initial=0.1, decay_rate=0.5,
                                  step=5)
    back = upd.updater_from_dict(u.to_dict())
    assert isinstance(back.schedule, upd.StepSchedule)
    assert back.to_dict() == u.to_dict()


def test_warmup_schedule_nested_base_roundtrip():
    """Regression: warmup over a nested schedule serializes with @class
    and rehydrates; default base no longer crashes."""
    w = upd.WarmupSchedule(warmup_steps=4,
                           base=upd.CosineSchedule(initial=0.2,
                                                   max_iter=50))
    back = upd.schedule_from_dict(w.to_dict())
    assert isinstance(back.base, upd.CosineSchedule)
    for step in (0, 3, 10):
        np.testing.assert_allclose(float(w(step)), float(back(step)),
                                   rtol=1e-7)
    # default base is usable
    assert float(upd.WarmupSchedule(warmup_steps=2)(0)) > 0
