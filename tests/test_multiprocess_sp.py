"""TRUE multi-process sequence parallelism: two OS processes form a
jax.distributed cluster (2 procs × 2 CPU devices = one 4-device global
mesh) and run causal ring attention with the sequence axis sharded
ACROSS THE PROCESS BOUNDARY — the long-context path the single-process
virtual-mesh tests can't exercise. Result must match dense causal
attention computed locally from the same seed."""
import os
import textwrap

import pytest

from mp_harness import assert_all_done, run_two_process_workers

WORKER = textwrap.dedent("""
    import os, sys, warnings
    sys.path.insert(0, %(repo)r)
    warnings.filterwarnings("ignore")
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=os.environ["COORD"],
        num_processes=2, process_id=int(os.environ["PROC_ID"]))
    import numpy as np
    import jax.numpy as jnp
    from jax.experimental import multihost_utils as mhu
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.parallel import make_mesh, \\
        ring_self_attention
    from deeplearning4j_tpu.nn.layers.attention import \\
        scaled_dot_attention

    pid = jax.process_index()
    mesh = make_mesh({"seq": 4})          # spans both processes
    b, t, h, hkv, d = 1, 32, 4, 2, 8
    rng = np.random.default_rng(0)        # same data on every proc
    q = rng.standard_normal((b, t, h, d)).astype(np.float32)
    k = rng.standard_normal((b, t, hkv, d)).astype(np.float32)
    v = rng.standard_normal((b, t, hkv, d)).astype(np.float32)

    # each process feeds ITS slice of the global sequence (proc 0 owns
    # T[:16], proc 1 owns T[16:] — 2 devices each of the 4-way shard)
    sh = NamedSharding(mesh, P(None, "seq", None, None))
    lo, hi = pid * (t // 2), (pid + 1) * (t // 2)
    gq = jax.make_array_from_process_local_data(sh, q[:, lo:hi])
    gk = jax.make_array_from_process_local_data(sh, k[:, lo:hi])
    gv = jax.make_array_from_process_local_data(sh, v[:, lo:hi])

    # GQA causal ring across the process boundary (ICI+DCN analog)
    out = ring_self_attention(gq, gk, gv, mesh, causal=True)
    got = np.asarray(mhu.process_allgather(out, tiled=True))

    from deeplearning4j_tpu.nn.layers.attention import repeat_kv_heads
    want = np.asarray(scaled_dot_attention(
        jnp.asarray(q), repeat_kv_heads(jnp.asarray(k), h),
        repeat_kv_heads(jnp.asarray(v), h), causal=True))
    err = float(np.max(np.abs(got - want)))
    assert err < 2e-4, err
    print(f"proc {pid} ring-vs-dense err {err:.2e}", flush=True)

    # gradients flow through the cross-process ring (global arrays
    # must be ARGUMENTS, not closure captures, in multi-host jit)
    def loss(q, k, v):
        return jnp.sum(ring_self_attention(q, k, v, mesh,
                                           causal=True) ** 2)
    g = jax.jit(jax.grad(loss))(gq, gk, gv)
    gs = float(jnp.sum(jnp.abs(g)))       # collective-reduced scalar
    assert np.isfinite(gs)
    print(f"proc {pid} gradsum {gs:.6f}", flush=True)
    print(f"proc {pid} DONE", flush=True)
""")


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("DL4J_TPU_SKIP_MP") == "1",
                    reason="multi-process test disabled")
def test_two_process_ring_attention(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(WORKER % {"repo": repo})
    procs, outs = run_two_process_workers(
        script, port=29000 + (os.getpid() % 400))
    assert_all_done(procs, outs)
    # identical collective-reduced gradient checksum on both processes
    import re
    sums = [re.search(r"gradsum (-?[\d.]+)", o).group(1) for o in outs]
    assert sums[0] == sums[1], sums


COMPOSED_WORKER = textwrap.dedent("""
    import os, sys, warnings
    sys.path.insert(0, %(repo)r)
    warnings.filterwarnings("ignore")
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=os.environ["COORD"],
        num_processes=2, process_id=int(os.environ["PROC_ID"]))
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.parallel import (make_mesh,
        composed_context)
    from deeplearning4j_tpu.parallel.composed import lm_placement_specs
    from deeplearning4j_tpu.zoo import CausalTransformerLM

    pid = jax.process_index()
    assert len(jax.devices()) == 8, len(jax.devices())
    # data axis (major) spans the PROCESS boundary — DP gradient
    # reduction crosses DCN; seq/tensor stay intra-process (ICI analog)
    mesh = make_mesh({"data": 2, "seq": 2, "tensor": 2})
    VOCAB, T, B = 64, 32, 4

    def build():
        model = CausalTransformerLM(
            vocab_size=VOCAB, hidden=32, n_layers=2, n_heads=2,
            max_len=T, ffn_mult=2.0, tie_embeddings=True,
            sequence_parallel="ring", seed=5)
        return model, model.init(seq_len=T)

    rng = np.random.default_rng(0)            # same data on every proc
    x = rng.integers(0, VOCAB, (B, T)).astype(np.int32)
    y = rng.integers(0, VOCAB, (B, T)).astype(np.int32)

    # single-device reference, computed identically on each process
    _, ref = build()
    rstep = ref._make_train_step()
    rp, ro, rs = ref.params, ref.opt_state, ref.state
    ref_losses = []
    for _ in range(2):
        rp, ro, rs, rl = rstep(rp, ro, rs, jnp.asarray(x),
                               jnp.asarray(y), None, None,
                               jax.random.PRNGKey(0))
        ref_losses.append(float(rl))

    def gput(arr, spec):
        sh = NamedSharding(mesh, spec)
        host = np.asarray(arr)
        return jax.make_array_from_callback(
            host.shape, sh, lambda idx: host[idx])

    _, net = build()
    param_specs, opt_specs = lm_placement_specs(net.params,
                                                net.opt_state)
    net.params = jax.tree.map(gput, net.params, param_specs)
    net.opt_state = jax.tree.map(gput, net.opt_state, opt_specs)
    gx = gput(x, P("data", "seq"))
    gy = gput(y, P("data", "seq"))
    step = net._make_train_step()
    params, opt, state = net.params, net.opt_state, net.state
    losses = []
    with composed_context(mesh):
        for _ in range(2):
            params, opt, state, loss = step(params, opt, state, gx,
                                            gy, None, None,
                                            jax.random.PRNGKey(0))
            losses.append(float(loss))

    err = max(abs(a - b) for a, b in zip(losses, ref_losses))
    assert err < 2e-4 * max(ref_losses), (losses, ref_losses)
    print(f"proc {pid} composed losses {losses[0]:.6f},"
          f"{losses[1]:.6f}", flush=True)
    print(f"proc {pid} DONE", flush=True)
""")


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("DL4J_TPU_SKIP_MP") == "1",
                    reason="multi-process test disabled")
def test_two_process_composed_dp_sp_tp(tmp_path):
    """Composed DP×SP×TP across a REAL process boundary: 2 procs × 4
    devices form the {"data":2, "seq":2, "tensor":2} mesh with the DP
    axis spanning the processes (the DCN tier). Two causal-LM train
    steps must match the single-device reference on both processes
    (VERDICT r4 Missing #1, cross-process leg)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker_composed.py"
    script.write_text(COMPOSED_WORKER % {"repo": repo})
    procs, outs = run_two_process_workers(
        script, port=29400 + (os.getpid() % 400),
        extra_env={"XLA_FLAGS":
                   "--xla_force_host_platform_device_count=4"},
        timeout=900)
    assert_all_done(procs, outs)
    import re
    sums = [re.search(r"composed losses ([\d.,-]+)", o).group(1)
            for o in outs]
    assert sums[0] == sums[1], sums
