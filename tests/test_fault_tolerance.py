"""Fault-tolerant training: failure injection → checkpoint restore →
continuation (reference analog: Spark task retry + CheckpointListener
recovery; in-process fault-injection like the parameter-server tests
that kill in-JVM nodes, SURVEY §4/§5)."""
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.train.fault_tolerance import (
    FaultTolerantTrainer, newest_checkpoint, resume_or_init)


def _factory(seed=11):
    def make():
        conf = (NeuralNetConfiguration.builder().seed(seed)
                .updater(upd.Adam(learning_rate=5e-3)).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(5)).build())
        return MultiLayerNetwork(conf).init()
    return make


def _data():
    rng = np.random.RandomState(1)
    x = rng.randn(24, 5).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    return DataSet(x, y)


class _FailAt:
    """Listener that raises once at a given global iteration —
    in-process fault injection."""

    def __init__(self, at_iteration):
        self.at = at_iteration
        self.fired = False

    def iteration_done(self, net, iteration, epoch):
        if not self.fired and iteration >= self.at:
            self.fired = True
            raise ConnectionError("simulated chip failure")

    def on_epoch_start(self, net):
        pass

    def on_epoch_end(self, net):
        pass


def test_recovers_from_midtraining_failure(tmp_path):
    net = _factory()()
    ds = _data()
    it = ListDataSetIterator([ds] * 4, batch_size=24)  # 4 iters/epoch
    trainer = FaultTolerantTrainer(net, tmp_path,
                                   save_every_n_iterations=2)
    bomb = _FailAt(at_iteration=6)          # mid-epoch-2 failure
    net.listeners.append(bomb)
    trainer.fit(it, epochs=5)
    assert bomb.fired                       # the failure DID happen
    assert trainer.restarts == 1
    assert net.epoch == 5                   # training completed anyway
    assert np.isfinite(net.score(ds))
    assert newest_checkpoint(tmp_path) is not None


def test_gives_up_after_max_restarts(tmp_path):
    net = _factory()()
    it = ListDataSetIterator([_data()] * 2, batch_size=24)

    class AlwaysFail:
        def iteration_done(self, net, iteration, epoch):
            raise OSError("persistent failure")

        def on_epoch_start(self, net):
            pass

        def on_epoch_end(self, net):
            pass

    net.listeners.append(AlwaysFail())
    trainer = FaultTolerantTrainer(net, tmp_path, max_restarts=2,
                                   save_every_n_iterations=1)
    with pytest.raises(RuntimeError, match="failed 3 times"):
        trainer.fit(it, epochs=3)


def test_resume_or_init_restart_idempotent(tmp_path):
    """The slice-restart pattern: re-running the same script resumes."""
    factory = _factory()
    ds = _data()
    # "process 1": train and checkpoint
    net1 = resume_or_init(factory, tmp_path)
    assert net1.iteration == 0              # fresh start
    t1 = FaultTolerantTrainer(net1, tmp_path, save_every_n_iterations=1)
    t1.fit(ListDataSetIterator([ds] * 3, batch_size=24), epochs=2)
    iters_done = net1.iteration
    # "process 2" (after a simulated slice restart): resumes counters
    net2 = resume_or_init(factory, tmp_path)
    assert net2.iteration > 0
    assert net2.iteration <= iters_done
    t2 = FaultTolerantTrainer(net2, tmp_path, save_every_n_iterations=1)
    t2.fit(ListDataSetIterator([ds] * 3, batch_size=24), epochs=1)
    assert net2.epoch >= 3
