"""Native runtime tests: every C++ entry point vs the pure-Python
fallback (the same-suite-over-every-backend lesson, SURVEY §4)."""
import threading

import numpy as np
import pytest

from deeplearning4j_tpu import native


requires_native = pytest.mark.skipif(not native.available(),
                                     reason="native toolchain missing")


# --- CSV --------------------------------------------------------------------

def test_csv_parse_matches_python():
    text = b"1.5,2,3\n4,-5.25,6e2\n7,8,9\n"
    got = native.csv_parse_f32(text)
    np.testing.assert_allclose(
        got, [[1.5, 2, 3], [4, -5.25, 600], [7, 8, 9]])
    assert got.dtype == np.float32


def test_csv_parse_skip_rows_and_crlf():
    text = b"a,b,c\r\n1,2,3\r\n4,5,6\r\n"
    got = native.csv_parse_f32(text, skip_rows=1)
    np.testing.assert_allclose(got, [[1, 2, 3], [4, 5, 6]])


def test_csv_parse_rejects_non_numeric_and_ragged():
    assert native.csv_parse_f32(b"1,2\n3,x\n") is None
    assert native.csv_parse_f32(b"1,2\n3\n") is None


@requires_native
def test_csv_native_agrees_with_fallback():
    rng = np.random.default_rng(0)
    arr = rng.normal(size=(50, 7)).astype(np.float32)
    text = "\n".join(",".join(f"{v:.6g}" for v in row)
                     for row in arr).encode()
    nat = native.csv_parse_f32(text)
    py = native._csv_parse_py(text, ",", 0)
    np.testing.assert_allclose(nat, py, rtol=1e-6)
    np.testing.assert_allclose(nat, arr, rtol=1e-4)


def test_csv_record_reader_to_matrix(tmp_path):
    from deeplearning4j_tpu.data.records import CSVRecordReader
    p = tmp_path / "d.csv"
    p.write_text("h1,h2\n1,2\n3,4\n")
    m = CSVRecordReader(str(p), skip_lines=1).to_matrix()
    np.testing.assert_allclose(m, [[1, 2], [3, 4]])
    # non-numeric file → None (fallback signal), iterator still works
    assert CSVRecordReader(str(p)).to_matrix() is None
    rows = list(CSVRecordReader(str(p), skip_lines=1))
    assert rows == [[1, 2], [3, 4]]


# --- threshold codec --------------------------------------------------------

def test_threshold_codec_roundtrip():
    rng = np.random.default_rng(1)
    g = rng.normal(scale=0.01, size=1000).astype(np.float32)
    tau = 0.01
    sign, residual, nnz = native.encode_threshold(g, tau)
    assert sign.dtype == np.int8
    assert nnz == int(np.count_nonzero(sign))
    decoded = native.decode_threshold(sign, tau)
    np.testing.assert_allclose(decoded + residual, g, atol=1e-6)
    # residual of thresholded-away entries is the full gradient
    small = np.abs(g) <= tau
    np.testing.assert_allclose(residual[small], g[small])


def test_bitmap_roundtrip():
    rng = np.random.default_rng(2)
    sign = rng.choice([-1, 0, 1], size=123).astype(np.int8)
    pos, neg = native.bitmap_encode(sign)
    assert pos.size == (123 + 7) // 8
    out = native.bitmap_decode(pos, neg, 123, 0.5)
    np.testing.assert_allclose(out, 0.5 * sign.astype(np.float32))


@requires_native
def test_codec_native_agrees_with_fallback(monkeypatch):
    rng = np.random.default_rng(3)
    g = rng.normal(scale=0.02, size=513).astype(np.float32)
    tau = 0.015
    n_sign, n_res, n_nnz = native.encode_threshold(g, tau)
    n_pos, n_neg = native.bitmap_encode(n_sign)
    monkeypatch.setattr(native, "_load", lambda: None)
    p_sign, p_res, p_nnz = native.encode_threshold(g, tau)
    p_pos, p_neg = native.bitmap_encode(p_sign)
    np.testing.assert_array_equal(n_sign, p_sign)
    np.testing.assert_allclose(n_res, p_res, atol=1e-7)
    assert n_nnz == p_nnz
    np.testing.assert_array_equal(n_pos, p_pos)
    np.testing.assert_array_equal(n_neg, p_neg)


# --- workspace --------------------------------------------------------------

def test_workspace_alloc_reset_highwater():
    ws = native.Workspace(1 << 16)
    a = ws.alloc((16, 16), np.float32)
    a[:] = 3.0
    b = ws.alloc((8,), np.float64)
    b[:] = 2.0
    assert a.shape == (16, 16) and b.dtype == np.float64
    hw = ws.reset()
    assert hw >= 16 * 16 * 4 + 8 * 8
    # after reset the arena is reusable
    c = ws.alloc((4,), np.float32)
    c[:] = 1.0
    ws.close()


def test_workspace_spill_beyond_capacity():
    ws = native.Workspace(256)
    big = ws.alloc((1024,), np.float32)     # 4KB > 256B arena
    big[:] = 7.0
    assert float(big.sum()) == 7.0 * 1024
    hw = ws.reset()
    assert hw >= 4096
    ws.close()


# --- ring queue -------------------------------------------------------------

def test_ring_queue_fifo_and_close():
    q = native.RingQueue(capacity=4)
    for i in range(4):
        assert q.put(("item", i))
    assert q.qsize() == 4
    got = [q.get()[1] for _ in range(4)]
    assert got == [0, 1, 2, 3]
    q.close()
    with pytest.raises(StopIteration):
        q.get()


def test_ring_queue_producer_consumer_threads():
    q = native.RingQueue(capacity=8)
    N = 200
    out = []

    def producer():
        for i in range(N):
            q.put(i)
        q.close()

    def consumer():
        while True:
            try:
                out.append(q.get())
            except StopIteration:
                return

    t1 = threading.Thread(target=producer)
    t2 = threading.Thread(target=consumer)
    t2.start()
    t1.start()
    t1.join(timeout=30)
    t2.join(timeout=30)
    assert out == list(range(N))


def test_ring_queue_blocking_backpressure():
    q = native.RingQueue(capacity=2)
    q.put(1)
    q.put(2)
    done = threading.Event()

    def blocked_put():
        q.put(3)          # blocks until a slot frees
        done.set()

    t = threading.Thread(target=blocked_put)
    t.start()
    assert not done.wait(0.2), "put should block when full"
    assert q.get() == 1
    assert done.wait(5), "put should unblock after get"
    t.join()
    q.close()


def test_img_batch_normalize_native_matches_fallback():
    from deeplearning4j_tpu import native
    rng = np.random.default_rng(0)
    batch = rng.integers(0, 256, (4, 10, 12, 3), dtype=np.uint8)
    crops = np.stack([rng.integers(0, 3, 4), rng.integers(0, 5, 4)], 1)
    flips = rng.integers(0, 2, 4).astype(np.uint8)
    mean = np.array([0.485, 0.456, 0.406], np.float32)
    std = np.array([0.229, 0.224, 0.225], np.float32)
    kw = dict(out_hw=(8, 8), mean=mean, std=std,
              crop_offsets=crops, flips=flips)
    out = native.img_batch_normalize(batch, **kw)
    # force the numpy fallback and compare
    lib, native._lib = native._lib, None
    bf, native._build_failed = native._build_failed, True
    try:
        ref = native.img_batch_normalize(batch, **kw)
    finally:
        native._lib, native._build_failed = lib, bf
    assert out.shape == (4, 8, 8, 3)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_batch_image_etl():
    from deeplearning4j_tpu.data.image import BatchImageETL
    rng = np.random.default_rng(1)
    batch = rng.integers(0, 256, (3, 12, 12, 3), dtype=np.uint8)
    etl = BatchImageETL(out_hw=(8, 8), random_crop=True,
                        random_flip=True, seed=5)
    out = etl(batch, train=True)
    assert out.shape == (3, 8, 8, 3)
    assert out.dtype == np.float32
    assert 0.0 <= out.min() and out.max() <= 1.0
    # eval path: deterministic center crop
    e1, e2 = etl(batch, train=False), etl(batch, train=False)
    np.testing.assert_array_equal(e1, e2)


def test_chunk_message_roundtrip_and_reassembly():
    from deeplearning4j_tpu import native
    rng = np.random.default_rng(2)
    payload = rng.integers(0, 256, 200_000, dtype=np.uint8).tobytes()
    buf = native.chunk_message(7, payload, chunk_bytes=64 * 1024)
    frames = list(native.parse_frames(buf))
    assert len(frames) == 4 and all(f[0] == 7 for f in frames)
    assert b"".join(f[3] for f in frames) == payload
    # out-of-order, interleaved reassembly
    buf2 = native.chunk_message(8, b"x" * 100, chunk_bytes=40)
    f2 = list(native.parse_frames(buf2))
    r = native.MessageReassembler()
    order = [f2[2], frames[1], f2[0], frames[3], frames[0], f2[1],
             frames[2]]
    done = {}
    for mid, seq, tot, pl in order:
        import struct
        fb = struct.pack("<QIII", mid, seq, tot, len(pl)) + \
            struct.pack("<I", native.crc32(pl)) + pl
        for m, p in r.feed(fb):
            done[m] = p
    assert done == {7: payload, 8: b"x" * 100}
    assert r.pending() == 0
    # corruption detected
    bad = bytearray(buf)
    bad[-1] ^= 0xFF
    import pytest as _pytest
    with _pytest.raises(ValueError):
        list(native.parse_frames(bytes(bad)))
    # native crc equals zlib crc
    import zlib
    assert native.crc32(payload) == zlib.crc32(payload) & 0xFFFFFFFF


def test_reassembler_rejects_malformed_and_evicts():
    from deeplearning4j_tpu import native
    import struct

    def frame(mid, seq, tot, pl):
        return struct.pack("<QIII", mid, seq, tot, len(pl)) + \
            struct.pack("<I", native.crc32(pl)) + pl

    r = native.MessageReassembler(max_pending=2)
    # seq >= total: dropped, no crash
    assert r.feed(frame(1, 5, 2, b"x")) == []
    assert r.dropped_frames == 1
    # inconsistent total across frames of one message: dropped
    r.feed(frame(2, 0, 3, b"a"))
    r.feed(frame(2, 1, 4, b"b"))
    assert r.dropped_frames == 2
    # eviction: three incomplete messages, max_pending=2
    r2 = native.MessageReassembler(max_pending=2)
    for mid in (10, 11, 12):
        r2.feed(frame(mid, 0, 2, b"p"))
    assert r2.pending() == 2 and r2.evicted_messages == 1
    # the evicted message (oldest=10) can't complete; newest can
    assert r2.feed(frame(12, 1, 2, b"q")) == [(12, b"pq")]


def test_img_batch_normalize_negative_crops_clamped():
    from deeplearning4j_tpu import native
    batch = np.full((1, 6, 6, 1), 128, np.uint8)
    out = native.img_batch_normalize(
        batch, out_hw=(4, 4), crop_offsets=np.array([[-5, -3]]))
    np.testing.assert_allclose(out, 128 / 255.0, rtol=1e-6)


def test_chunk_message_rejects_bad_chunk_bytes():
    from deeplearning4j_tpu import native
    import pytest as _pytest
    for bad in (0, -1):
        with _pytest.raises(ValueError):
            native.chunk_message(1, b"abc", chunk_bytes=bad)


def test_native_cpp_suite_passes():
    """Build and run the native C++ unit tests (reference: libnd4j
    googletest suites / run_tests.sh)."""
    import subprocess
    from pathlib import Path
    native_dir = Path(__file__).parent.parent / "native"
    res = subprocess.run(["make", "test"], cwd=native_dir,
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "ALL PASSED" in res.stdout
