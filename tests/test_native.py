"""Native runtime tests: every C++ entry point vs the pure-Python
fallback (the same-suite-over-every-backend lesson, SURVEY §4)."""
import threading

import numpy as np
import pytest

from deeplearning4j_tpu import native


requires_native = pytest.mark.skipif(not native.available(),
                                     reason="native toolchain missing")


# --- CSV --------------------------------------------------------------------

def test_csv_parse_matches_python():
    text = b"1.5,2,3\n4,-5.25,6e2\n7,8,9\n"
    got = native.csv_parse_f32(text)
    np.testing.assert_allclose(
        got, [[1.5, 2, 3], [4, -5.25, 600], [7, 8, 9]])
    assert got.dtype == np.float32


def test_csv_parse_skip_rows_and_crlf():
    text = b"a,b,c\r\n1,2,3\r\n4,5,6\r\n"
    got = native.csv_parse_f32(text, skip_rows=1)
    np.testing.assert_allclose(got, [[1, 2, 3], [4, 5, 6]])


def test_csv_parse_rejects_non_numeric_and_ragged():
    assert native.csv_parse_f32(b"1,2\n3,x\n") is None
    assert native.csv_parse_f32(b"1,2\n3\n") is None


@requires_native
def test_csv_native_agrees_with_fallback():
    rng = np.random.default_rng(0)
    arr = rng.normal(size=(50, 7)).astype(np.float32)
    text = "\n".join(",".join(f"{v:.6g}" for v in row)
                     for row in arr).encode()
    nat = native.csv_parse_f32(text)
    py = native._csv_parse_py(text, ",", 0)
    np.testing.assert_allclose(nat, py, rtol=1e-6)
    np.testing.assert_allclose(nat, arr, rtol=1e-4)


def test_csv_record_reader_to_matrix(tmp_path):
    from deeplearning4j_tpu.data.records import CSVRecordReader
    p = tmp_path / "d.csv"
    p.write_text("h1,h2\n1,2\n3,4\n")
    m = CSVRecordReader(str(p), skip_lines=1).to_matrix()
    np.testing.assert_allclose(m, [[1, 2], [3, 4]])
    # non-numeric file → None (fallback signal), iterator still works
    assert CSVRecordReader(str(p)).to_matrix() is None
    rows = list(CSVRecordReader(str(p), skip_lines=1))
    assert rows == [[1, 2], [3, 4]]


# --- threshold codec --------------------------------------------------------

def test_threshold_codec_roundtrip():
    rng = np.random.default_rng(1)
    g = rng.normal(scale=0.01, size=1000).astype(np.float32)
    tau = 0.01
    sign, residual, nnz = native.encode_threshold(g, tau)
    assert sign.dtype == np.int8
    assert nnz == int(np.count_nonzero(sign))
    decoded = native.decode_threshold(sign, tau)
    np.testing.assert_allclose(decoded + residual, g, atol=1e-6)
    # residual of thresholded-away entries is the full gradient
    small = np.abs(g) <= tau
    np.testing.assert_allclose(residual[small], g[small])


def test_bitmap_roundtrip():
    rng = np.random.default_rng(2)
    sign = rng.choice([-1, 0, 1], size=123).astype(np.int8)
    pos, neg = native.bitmap_encode(sign)
    assert pos.size == (123 + 7) // 8
    out = native.bitmap_decode(pos, neg, 123, 0.5)
    np.testing.assert_allclose(out, 0.5 * sign.astype(np.float32))


@requires_native
def test_codec_native_agrees_with_fallback(monkeypatch):
    rng = np.random.default_rng(3)
    g = rng.normal(scale=0.02, size=513).astype(np.float32)
    tau = 0.015
    n_sign, n_res, n_nnz = native.encode_threshold(g, tau)
    n_pos, n_neg = native.bitmap_encode(n_sign)
    monkeypatch.setattr(native, "_load", lambda: None)
    p_sign, p_res, p_nnz = native.encode_threshold(g, tau)
    p_pos, p_neg = native.bitmap_encode(p_sign)
    np.testing.assert_array_equal(n_sign, p_sign)
    np.testing.assert_allclose(n_res, p_res, atol=1e-7)
    assert n_nnz == p_nnz
    np.testing.assert_array_equal(n_pos, p_pos)
    np.testing.assert_array_equal(n_neg, p_neg)


# --- workspace --------------------------------------------------------------

def test_workspace_alloc_reset_highwater():
    ws = native.Workspace(1 << 16)
    a = ws.alloc((16, 16), np.float32)
    a[:] = 3.0
    b = ws.alloc((8,), np.float64)
    b[:] = 2.0
    assert a.shape == (16, 16) and b.dtype == np.float64
    hw = ws.reset()
    assert hw >= 16 * 16 * 4 + 8 * 8
    # after reset the arena is reusable
    c = ws.alloc((4,), np.float32)
    c[:] = 1.0
    ws.close()


def test_workspace_spill_beyond_capacity():
    ws = native.Workspace(256)
    big = ws.alloc((1024,), np.float32)     # 4KB > 256B arena
    big[:] = 7.0
    assert float(big.sum()) == 7.0 * 1024
    hw = ws.reset()
    assert hw >= 4096
    ws.close()


# --- ring queue -------------------------------------------------------------

def test_ring_queue_fifo_and_close():
    q = native.RingQueue(capacity=4)
    for i in range(4):
        assert q.put(("item", i))
    assert q.qsize() == 4
    got = [q.get()[1] for _ in range(4)]
    assert got == [0, 1, 2, 3]
    q.close()
    with pytest.raises(StopIteration):
        q.get()


def test_ring_queue_producer_consumer_threads():
    q = native.RingQueue(capacity=8)
    N = 200
    out = []

    def producer():
        for i in range(N):
            q.put(i)
        q.close()

    def consumer():
        while True:
            try:
                out.append(q.get())
            except StopIteration:
                return

    t1 = threading.Thread(target=producer)
    t2 = threading.Thread(target=consumer)
    t2.start()
    t1.start()
    t1.join(timeout=30)
    t2.join(timeout=30)
    assert out == list(range(N))


def test_ring_queue_blocking_backpressure():
    q = native.RingQueue(capacity=2)
    q.put(1)
    q.put(2)
    done = threading.Event()

    def blocked_put():
        q.put(3)          # blocks until a slot frees
        done.set()

    t = threading.Thread(target=blocked_put)
    t.start()
    assert not done.wait(0.2), "put should block when full"
    assert q.get() == 1
    assert done.wait(5), "put should unblock after get"
    t.join()
    q.close()
