"""Fused-primitive kernel library fences (ISSUE 15, ARCHITECTURE §17):

- interpret-mode fwd AND bwd parity for every fused norm kernel vs its
  XLA fallback (tight f32 band; documented bf16 band),
- byte-identity of the gate-off programs (the dispatch must be a pure
  trace-time decision: gate off == the pre-kernel expression, bitwise,
  with no custom calls in the lowered program),
- ``DL4J_TPU_KERNEL_FORCE`` exercises every gated dispatch site both
  ways on CPU CI (the testability satellite: without the flag the
  dispatch decision itself only ever runs on a TPU),
- warmup/aot_hits + zero-new-traces for the gather-overlap step pair,
- the gather-overlap trajectory fence (bit-identical to the
  end-gather sharded trajectory on the same mesh),
- the fused-diag-tap regression fence: the fused single-pass stat taps
  must cost well under half the legacy two-pass program's extra
  byte traffic (deterministic — XLA's own cost model, no wall clocks),
- the gap report's ``closed_by`` loop closure.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops import fused_norms as fnorm
from deeplearning4j_tpu.ops import kernel_registry
from deeplearning4j_tpu.ops import pallas_kernels as pk


@pytest.fixture
def force_kernels(monkeypatch):
    monkeypatch.setenv("DL4J_TPU_KERNEL_FORCE", "1")


def _rand(rng, *shape, dtype=jnp.float32):
    return jnp.asarray(rng.standard_normal(shape), dtype)


# ---------------------------------------------------------------------------
# interpret-mode parity (fwd + bwd) — the contract rule 9 anchors on
# ---------------------------------------------------------------------------

def test_rms_norm_parity(force_kernels, rng):
    x = _rand(rng, 24, 96)
    g = _rand(rng, 96)
    co = _rand(rng, 24, 96)
    out = fnorm.rms_norm(x, g)
    ref = fnorm.rms_norm_reference(x, g)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-6

    def loss(fn):
        return lambda x, g: jnp.sum(fn(x, g) * co)

    gk = jax.grad(loss(fnorm.rms_norm), argnums=(0, 1))(x, g)
    gr = jax.grad(loss(fnorm.rms_norm_reference), argnums=(0, 1))(x, g)
    for a, b in zip(gk, gr):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-6


def test_rms_norm_parity_3d_rows(force_kernels, rng):
    """[B, T, F] inputs fold to rows and unfold back — the layer-stack
    calling convention."""
    x = _rand(rng, 3, 17, 130)     # ragged rows + >128 features
    g = _rand(rng, 130)
    out = fnorm.rms_norm(x, g)
    ref = fnorm.rms_norm_reference(x, g)
    assert out.shape == x.shape
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-6


def test_add_rms_norm_parity(force_kernels, rng):
    x = _rand(rng, 24, 96)
    d = _rand(rng, 24, 96)
    g = _rand(rng, 96)
    co = _rand(rng, 24, 96)
    y, s = fnorm.add_rms_norm(x, d, g)
    yr, sr = fnorm.add_rms_norm_reference(x, d, g)
    assert float(jnp.max(jnp.abs(y - yr))) < 2e-6
    assert float(jnp.max(jnp.abs(s - sr))) < 2e-6

    # both outputs carry cotangents (the residual stream continues)
    def loss(fn):
        def f(x, d, g):
            y, s = fn(x, d, g)
            return jnp.sum(y * co) + jnp.sum(s * s)
        return f

    gk = jax.grad(loss(fnorm.add_rms_norm), argnums=(0, 1, 2))(x, d, g)
    gr = jax.grad(loss(fnorm.add_rms_norm_reference),
                  argnums=(0, 1, 2))(x, d, g)
    for a, b in zip(gk, gr):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-5


def test_layer_norm_parity(force_kernels, rng):
    x = _rand(rng, 24, 96)
    g = _rand(rng, 96)
    b = _rand(rng, 96)
    co = _rand(rng, 24, 96)
    out = fnorm.layer_norm(x, g, b)
    ref = fnorm.layer_norm_reference(x, g, b)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-6

    def loss(fn):
        return lambda x, g, b: jnp.sum(fn(x, g, b) * co)

    gk = jax.grad(loss(fnorm.layer_norm), argnums=(0, 1, 2))(x, g, b)
    gr = jax.grad(loss(fnorm.layer_norm_reference),
                  argnums=(0, 1, 2))(x, g, b)
    for a, b_ in zip(gk, gr):
        assert float(jnp.max(jnp.abs(a - b_))) < 1e-5


def test_parity_bf16_band(force_kernels, rng):
    """bf16 storage: the kernel upcasts to f32 internally (one rounding
    at write-out) while the fallback's jnp ops round per-op — agreement
    is to a bf16 band (a couple of ulps at the sampled |x| range;
    measured max 0.031 = 1 ulp at |x|~4), not bitwise."""
    x = _rand(rng, 16, 128).astype(jnp.bfloat16)
    g = _rand(rng, 128).astype(jnp.bfloat16)
    out = fnorm.rms_norm(x, g).astype(jnp.float32)
    ref = fnorm.rms_norm_reference(x, g).astype(jnp.float32)
    assert float(jnp.max(jnp.abs(out - ref))) < 7e-2


def test_float64_always_falls_back(force_kernels, rng):
    """Semantic refusal: f64 (gradient checking) never dispatches."""
    x = jnp.asarray(rng.standard_normal((4, 32)), jnp.float64)
    g = jnp.asarray(rng.standard_normal(32), jnp.float64)
    out = fnorm.rms_norm(x, g)
    ref = fnorm.rms_norm_reference(x, g)
    assert np.array_equal(np.asarray(out), np.asarray(ref))


# ---------------------------------------------------------------------------
# gate-off byte-identity: the dispatch is trace-time only
# ---------------------------------------------------------------------------

def _op_kinds(fn, *args):
    from deeplearning4j_tpu.obs import devtime
    text = jax.jit(fn).lower(*args).compile().as_text()
    sm = devtime.hlo_scope_map(text)
    kinds = {}
    for info in sm["ops"].values():
        kinds[info["kind"]] = kinds.get(info["kind"], 0) + 1
    return kinds, text


def test_gate_off_programs_unchanged(rng, monkeypatch):
    """With the gate off (CPU, no force flag) every dispatch site runs
    the EXACT pre-kernel expression: bitwise-equal outputs, identical
    op-kind histograms, and no custom calls in the compiled program."""
    monkeypatch.delenv("DL4J_TPU_KERNEL_FORCE", raising=False)
    x = _rand(rng, 8, 64)
    d = _rand(rng, 8, 64)
    g = _rand(rng, 64)
    b = _rand(rng, 64)
    cases = [
        (lambda: (lambda q: fnorm.rms_norm(q, g)),
         lambda: (lambda q: fnorm.rms_norm_reference(q, g))),
        (lambda: (lambda q: fnorm.layer_norm(q, g, b)),
         lambda: (lambda q: fnorm.layer_norm_reference(q, g, b))),
        (lambda: (lambda q: fnorm.add_rms_norm(q, d, g)),
         lambda: (lambda q: fnorm.add_rms_norm_reference(q, d, g))),
    ]
    for mk_gated, mk_ref in cases:
        gated, ref = mk_gated(), mk_ref()
        out_g = jax.jit(gated)(x)
        out_r = jax.jit(ref)(x)
        for a, bb in zip(jax.tree_util.tree_leaves(out_g),
                         jax.tree_util.tree_leaves(out_r)):
            assert np.array_equal(np.asarray(a), np.asarray(bb))
        kinds_g, text_g = _op_kinds(gated, x)
        kinds_r, _ = _op_kinds(ref, x)
        assert kinds_g == kinds_r
        assert "custom-call" not in text_g


# ---------------------------------------------------------------------------
# DL4J_TPU_KERNEL_FORCE: every gated dispatch site, both ways
# ---------------------------------------------------------------------------

def test_force_flag_routes_norm_layer_sites(rng, monkeypatch):
    """Each norm dispatch site (RMSNorm layer, LayerNormalization
    layer, TransformerDecoderBlock residual epilogue, zoo.gpt._rms)
    takes the kernel path under the force flag and the fallback
    without it — counted at the pallas-call wrappers, with outputs
    agreeing across the two dispatches."""
    from deeplearning4j_tpu.nn.layers.core import (LayerNormalization,
                                                   RMSNorm)
    from deeplearning4j_tpu.zoo.gpt import _rms as gpt_rms

    calls = {"n": 0}
    orig_rms, orig_ln = fnorm._rms_fwd_call, fnorm._ln_fwd_call
    orig_add = fnorm._add_rms_fwd_call

    def wrap(fn):
        def inner(*a, **k):
            calls["n"] += 1
            return fn(*a, **k)
        return inner

    monkeypatch.setattr(fnorm, "_rms_fwd_call", wrap(orig_rms))
    monkeypatch.setattr(fnorm, "_ln_fwd_call", wrap(orig_ln))
    monkeypatch.setattr(fnorm, "_add_rms_fwd_call", wrap(orig_add))

    x = _rand(rng, 4, 48)
    rms = RMSNorm()
    p_rms, _, _ = rms.init(jax.random.PRNGKey(0), (48,))
    ln = LayerNormalization()
    p_ln, _, _ = ln.init(jax.random.PRNGKey(1), (48,))
    gam = _rand(rng, 48)
    delta = _rand(rng, 4, 48)

    def run_all():
        return (rms.apply(p_rms, {}, x)[0],
                ln.apply(p_ln, {}, x)[0],
                gpt_rms(x, gam),
                fnorm.add_rms_norm(x, delta, gam))

    monkeypatch.delenv("DL4J_TPU_KERNEL_FORCE", raising=False)
    off = run_all()
    assert calls["n"] == 0            # gate off: no kernel dispatch
    monkeypatch.setenv("DL4J_TPU_KERNEL_FORCE", "1")
    on = run_all()
    assert calls["n"] >= 4            # every site took the kernel path
    for a, b in zip(jax.tree_util.tree_leaves(off),
                    jax.tree_util.tree_leaves(on)):
        assert float(jnp.max(jnp.abs(jnp.asarray(a, jnp.float32)
                                     - jnp.asarray(b, jnp.float32)))) \
            < 1e-5


def test_force_flag_routes_flash_site(rng, monkeypatch):
    """``scaled_dot_attention``'s flash gate: forced, a shape far
    below DL4J_TPU_FLASH_MIN_T dispatches the interpret-mode kernel
    (counted); unforced on CPU it stays on the einsum. Semantic
    refusals hold under force."""
    from deeplearning4j_tpu.nn.layers import attention as att

    q = _rand(rng, 1, 64, 2, 16)
    k = _rand(rng, 1, 64, 2, 16)
    v = _rand(rng, 1, 64, 2, 16)
    calls = {"n": 0}
    orig = pk.flash_attention

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(pk, "flash_attention", counting)
    monkeypatch.delenv("DL4J_TPU_KERNEL_FORCE", raising=False)
    ref = att.scaled_dot_attention(q, k, v, causal=True)
    assert calls["n"] == 0
    monkeypatch.setenv("DL4J_TPU_KERNEL_FORCE", "1")
    out = att.scaled_dot_attention(q, k, v, causal=True)
    assert calls["n"] == 1
    assert float(jnp.max(jnp.abs(out - ref))) < 5e-5
    # semantic refusal survives the force: causal Tq > Tk stays einsum
    q_long = _rand(rng, 1, 96, 2, 16)
    att.scaled_dot_attention(q_long, k, v, causal=True)
    assert calls["n"] == 1


# ---------------------------------------------------------------------------
# gather-overlap: trajectory fence + warmup/zero-retrace fence
# ---------------------------------------------------------------------------

def _mlp_net(seed=7):
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(upd.Adam(learning_rate=1e-3)).list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(16)).build())
    return MultiLayerNetwork(conf).init()


def _toy_it(batch=64):
    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 16)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, batch)]
    return ListDataSetIterator(DataSet(x, y), batch_size=batch)


needs_mesh = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


@needs_mesh
def test_gather_overlap_trajectory_matches_sharded():
    """The overlap step is the sharded step with the gather moved
    across the step boundary — same math, so the trajectory is
    BIT-identical to end-gather sharded on the same mesh (unlike the
    vs-replicated comparison, the two programs share the scatter/
    update/gather building blocks)."""
    from deeplearning4j_tpu.parallel import ParallelWrapper
    from deeplearning4j_tpu.parallel._compat import \
        supports_psum_scatter
    if not supports_psum_scatter():
        pytest.skip("no lax.psum_scatter")

    def drive(**kw):
        net = _mlp_net()
        w = ParallelWrapper(net, workers=8, sharded_update=True, **kw)
        w.fit(_toy_it(), epochs=8)
        return net.params

    p_sh = drive()
    p_ov = drive(gather_overlap=True)
    for a, b in zip(jax.tree_util.tree_leaves(p_sh),
                    jax.tree_util.tree_leaves(p_ov)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@needs_mesh
def test_gather_overlap_respects_params_reassignment():
    """Assigning ``net.params`` between fits (loaded weights,
    transfer learning) must feed the NEXT overlap fit — the carried
    shards re-derive from the authoritative params at fit entry
    (review fix: they previously kept training the pre-assignment
    weights)."""
    from deeplearning4j_tpu.parallel import ParallelWrapper
    from deeplearning4j_tpu.parallel._compat import \
        supports_psum_scatter
    if not supports_psum_scatter():
        pytest.skip("no lax.psum_scatter")

    def drive(reassign):
        net = _mlp_net()
        w = ParallelWrapper(net, workers=8, sharded_update=True,
                            gather_overlap=True)
        w.fit(_toy_it(), epochs=2)
        if reassign is not None:
            net.params = jax.tree_util.tree_map(
                lambda l: jnp.zeros_like(l), net.params)
        w.fit(_toy_it(), epochs=1)
        return net.params

    p_cont = drive(None)
    p_zero = drive("zeros")
    # one step from zeros lands near zero (lr=1e-3); continuing the
    # old trajectory would keep O(initializer)-scale weights
    w_cont = np.abs(np.asarray(
        jax.tree_util.tree_leaves(p_cont)[0])).max()
    w_zero = np.abs(np.asarray(
        jax.tree_util.tree_leaves(p_zero)[0])).max()
    assert w_zero < 0.05 < w_cont, (w_zero, w_cont)


@needs_mesh
def test_gather_overlap_warmup_zero_retraces():
    """Warmup AOT-compiles the overlap step AND its diag sibling; the
    first real batches dispatch to the warmed executables (aot_hits)
    with zero new traces under the strict sentry."""
    from deeplearning4j_tpu.parallel import ParallelWrapper
    from deeplearning4j_tpu.parallel._compat import \
        supports_psum_scatter
    from deeplearning4j_tpu.perf import sentry
    from deeplearning4j_tpu.perf.warmup import WarmupSpec
    if not supports_psum_scatter():
        pytest.skip("no lax.psum_scatter")

    net = _mlp_net(seed=11)
    net.monitor_numerics(every=2)
    w = ParallelWrapper(net, workers=8, sharded_update=True,
                        gather_overlap=True)
    rep = w.warmup([WarmupSpec(features=(64, 16), labels=(64, 4))])
    assert rep["compiled"] == 2          # step + diag sibling
    before = sentry.total_traces()
    with sentry.strict(budget=0):
        w.fit(_toy_it(), epochs=2)
    assert sentry.total_traces() == before
    st = sentry.stats()
    assert st["ParallelWrapper.sync_sharded_overlap_step"][
        "aot_hits"] >= 1
    assert st["ParallelWrapper.sync_sharded_overlap_diag_step"][
        "aot_hits"] >= 1


# ---------------------------------------------------------------------------
# fused diag taps: deterministic cost fence (no wall clocks)
# ---------------------------------------------------------------------------

def test_fused_diag_taps_beat_twopass_baseline():
    """The fused-tap diagnostic program must move LESS THAN HALF the
    extra bytes the legacy two-pass program moved over the plain step
    (measured 6x less on the smoke LeNet — the ~17% → ≤8% diag-cost
    acceptance). XLA's own ``cost_analysis`` makes the fence
    deterministic: no wall clocks, no shared-CI-box noise."""
    from deeplearning4j_tpu.obs import numerics
    from deeplearning4j_tpu.zoo import LeNet

    b = 64
    key = jax.random.fold_in(jax.random.PRNGKey(0), 0)
    x = jax.ShapeDtypeStruct((b, 28, 28, 1), jnp.float32)
    y = jax.ShapeDtypeStruct((b, 10), jnp.float32)

    def program_bytes(step, net):
        step.warmup(net.params, net.opt_state, net.state, x, y,
                    None, None, key)
        ex = list(step._aot.values())[0]
        ca = ex.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        return float(ca.get("bytes accessed", 0.0))

    net = LeNet(num_classes=10, seed=0).init()
    net.monitor_numerics(every=1, raise_on_nonfinite=False)
    plain = program_bytes(net._make_train_step(), net)
    fused = program_bytes(net._make_diag_step(), net)
    orig = numerics.act_summary
    try:
        numerics.act_summary = numerics.act_summary_twopass
        legacy = program_bytes(net._make_diag_step(), net)
    finally:
        numerics.act_summary = orig
    assert fused > plain                  # the taps are real
    assert legacy > plain
    assert (fused - plain) < 0.5 * (legacy - plain), (
        f"fused diag taps move {fused - plain:.3e} extra bytes vs "
        f"legacy {legacy - plain:.3e} — the fused-tap win regressed")


def test_fused_moments_matches_masked_stats(rng):
    """fused_moments == the straightforward masked reductions,
    including non-finite entries."""
    from deeplearning4j_tpu.obs import numerics

    v = rng.standard_normal((100,)).astype(np.float32)
    v[7] = np.nan
    v[13] = np.inf
    x = jnp.asarray(v)
    s1, s2, mx, n_ok = jax.jit(numerics.fused_moments)(x)
    finite = np.isfinite(v)
    safe = np.where(finite, v, 0.0)
    assert float(s1) == pytest.approx(float(safe.sum()), rel=1e-6)
    assert float(s2) == pytest.approx(float((safe ** 2).sum()),
                                      rel=1e-6)
    assert float(mx) == pytest.approx(float(np.abs(safe).max()))
    assert int(n_ok) == int(finite.sum())


# ---------------------------------------------------------------------------
# gap-report loop closure
# ---------------------------------------------------------------------------

def test_gap_report_marks_closed_scopes(monkeypatch):
    """A norm scope that dispatches to a registered kernel (gate
    active) reports closed_by and stops being a candidate; with the
    gate off the gap stays open."""
    from deeplearning4j_tpu.obs import devtime

    cap = {"scopes": {
        "layer_3.RMSNorm": {
            "device_ms": 8.0, "share": 0.4, "ops": 10, "fusions": 2,
            "backward_ms": 4.0, "custom_call_ms": 0.0, "flops": 1e9,
            "bytes": 1e8, "kinds": {"multiply": 4},
            "roofline": {"utilization": 0.1, "bound": "memory"}},
        "layer_0.DenseLayer": {
            "device_ms": 6.0, "share": 0.3, "ops": 10, "fusions": 2,
            "backward_ms": 3.0, "custom_call_ms": 0.0, "flops": 1e9,
            "bytes": 1e8, "kinds": {"dot": 4},
            "roofline": {"utilization": 0.1, "bound": "memory"}},
    }}
    monkeypatch.delenv("DL4J_TPU_KERNEL_FORCE", raising=False)
    gaps = {g["scope"]: g for g in devtime.gap_report(cap)}
    # CPU, no force: the rms kernel's gate is off -> gap stays open
    assert gaps["layer_3.RMSNorm"]["closed_by"] is None
    assert gaps["layer_3.RMSNorm"]["pallas_candidate"] is True
    monkeypatch.setenv("DL4J_TPU_KERNEL_FORCE", "1")
    gaps = {g["scope"]: g for g in devtime.gap_report(cap)}
    assert gaps["layer_3.RMSNorm"]["closed_by"] == "rms_norm"
    assert gaps["layer_3.RMSNorm"]["pallas_candidate"] is False
    # no kernel covers a Dense matmul scope — still a candidate
    assert gaps["layer_0.DenseLayer"]["closed_by"] is None
    assert gaps["layer_0.DenseLayer"]["pallas_candidate"] is True


def test_registry_entries_resolve():
    """Every registry entry's fallback exists and is callable, and the
    closed gauge semantics follow gate_active."""
    from deeplearning4j_tpu.ops import fused_norms, pallas_kernels
    mods = {"ops/pallas_kernels.py": pallas_kernels,
            "ops/fused_norms.py": fused_norms}
    for name, entry in kernel_registry.KERNEL_REGISTRY.items():
        mod = mods[entry["module"]]
        assert callable(getattr(mod, entry["fallback"])), name
        assert entry["scope"].startswith("ops."), name
