"""Frozen-output integration tests.

Reference: ``dl4j-integration-tests`` IntegrationTestRunner — full
pipelines (train N iters, eval, serialize) compared against frozen
outputs checked into test resources, guarding regression across
releases. Goldens live in ``tests/resources/integration_goldens.json``
and are regenerated with ``python tests/test_integration_frozen.py``.

Runs on the CPU backend (conftest pins platform+seed), so values are
deterministic across rounds on the same jax version; comparisons use
loose-enough tolerances to survive fusion-order drift.
"""
import json
import os
from pathlib import Path

import numpy as np

GOLDEN_PATH = Path(__file__).parent / "resources" / \
    "integration_goldens.json"


def _mlp_pipeline():
    """Train a fixed-seed MLP 30 iters; return loss curve ends +
    output fingerprint."""
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator

    rng = np.random.RandomState(12345)
    x = rng.randn(64, 10).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 64)]
    conf = (NeuralNetConfiguration.builder().seed(12345)
            .updater(upd.Adam(learning_rate=5e-3)).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=4, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(10)).build())
    net = MultiLayerNetwork(conf).init()
    s0 = net.score(DataSet(x, y))
    net.fit(ListDataSetIterator([DataSet(x, y)], batch_size=64),
            epochs=30)
    out = np.asarray(net.output(x[:4]))
    return {
        "initial_score": float(s0),
        "final_score": float(net.score(DataSet(x, y))),
        "output_sample": [float(v) for v in out.ravel()],
        "param_l2": float(np.sqrt(sum(
            float((np.asarray(p) ** 2).sum())
            for p in __import__("jax").tree.leaves(net.params)))),
    }


def _cnn_pipeline():
    """Conv net forward fingerprint after a few fixed-seed steps."""
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import (ConvolutionLayer,
                                              SubsamplingLayer,
                                              OutputLayer)
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator

    rng = np.random.RandomState(777)
    x = rng.randn(16, 8, 8, 1).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
    conf = (NeuralNetConfiguration.builder().seed(777)
            .updater(upd.Sgd(learning_rate=1e-2)).list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                                    pooling_type="max"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 1)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(ListDataSetIterator([DataSet(x, y)], batch_size=16),
            epochs=10)
    out = np.asarray(net.output(x[:2]))
    return {"output_sample": [float(v) for v in out.ravel()],
            "final_score": float(net.score(DataSet(x, y)))}


def _serialization_pipeline():
    """Save→restore→identical outputs (the serialize leg of the
    reference integration tests)."""
    import tempfile
    from deeplearning4j_tpu.serialization import ModelSerializer
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd

    rng = np.random.RandomState(5)
    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(upd.Adam(learning_rate=1e-3)).list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    x = rng.randn(4, 6).astype(np.float32)
    before = np.asarray(net.output(x))
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.zip")
        ModelSerializer.write_model(net, path, save_updater=True)
        back = ModelSerializer.restore_multi_layer_network(path)
        after = np.asarray(back.output(x))
    return {"roundtrip_max_abs_diff": float(np.abs(before
                                                   - after).max())}


PIPELINES = {"mlp": _mlp_pipeline, "cnn": _cnn_pipeline,
             "serialization": _serialization_pipeline}


def _generate():
    goldens = {name: fn() for name, fn in PIPELINES.items()}
    GOLDEN_PATH.parent.mkdir(exist_ok=True)
    GOLDEN_PATH.write_text(json.dumps(goldens, indent=2))
    print(f"wrote {GOLDEN_PATH}")


def test_frozen_goldens():
    assert GOLDEN_PATH.exists(), \
        "regenerate goldens: python tests/test_integration_frozen.py"
    goldens = json.loads(GOLDEN_PATH.read_text())
    for name, fn in PIPELINES.items():
        got = fn()
        want = goldens[name]
        for key, val in want.items():
            if isinstance(val, list):
                np.testing.assert_allclose(
                    got[key], val, rtol=1e-3, atol=1e-5,
                    err_msg=f"{name}.{key}")
            else:
                assert abs(got[key] - val) <= max(1e-3,
                                                  1e-3 * abs(val)), \
                    f"{name}.{key}: {got[key]} != frozen {val}"


if __name__ == "__main__":
    import jax
    jax.config.update("jax_platforms", "cpu")
    _generate()


def test_dropped_tie_with_removed_source_restores_trained_value():
    """A tie whose SOURCE layer is removed by surgery while its dst
    layer is kept must materialize the TRAINED tied value into the
    kept layer — not silently re-randomize it (round-5 review: the
    fill must read the source net's FULL params, since the kept-layers
    dict no longer contains the removed source)."""
    import jax
    import numpy as np
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.nn.transferlearning import TransferLearning

    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(upd.Sgd(learning_rate=1e-2)).list()
            .layer(DenseLayer(n_out=4, activation="tanh"))
            .layer(DenseLayer(n_out=4, activation="tanh"))
            .layer(OutputLayer(n_out=4, activation="softmax",
                               loss="mcxent"))
            # downward tie: layer_0's W materializes FROM layer_1's W
            .tie_weights(0, "W", 1, "W")
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]
    for _ in range(3):
        net.fit(x, y)
    trained_src = np.asarray(net.params["layer_1"]["W"])

    # surgery removes layers 1..2 (the tie SOURCE goes away), puts a
    # fresh head on; layer_0 is kept untouched
    new = (TransferLearning.builder(net)
           .remove_layers_from_output(2)
           .add_layer(OutputLayer(n_out=4, activation="softmax",
                                  loss="mcxent"))
           .build())
    assert not getattr(new.conf, "tied_weights", [])
    got = np.asarray(new.params["layer_0"]["W"])
    np.testing.assert_array_equal(got, trained_src)
