"""Expert-parallel MoE and pipeline-parallel tests on the 8-device
virtual CPU mesh (SURVEY §4: multi-node-without-a-cluster testing).
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from conftest import requires_shard_map

from deeplearning4j_tpu.parallel import make_mesh
from deeplearning4j_tpu.parallel.moe import MixtureOfExperts, top_k_gating
from deeplearning4j_tpu.parallel.pipeline import (
    pipeline_apply, make_mlp_stage, pipeline_train_step)



pytestmark = requires_shard_map

class TestGating:
    def test_dispatch_combine_shapes_and_capacity(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (12, 4))
        w = jax.random.normal(jax.random.PRNGKey(1), (4, 4)) * 0.1
        disp, comb, aux = top_k_gating(x, w, top_k=2, capacity=3)
        assert disp.shape == (12, 4, 3)
        # no expert slot double-booked
        assert float(jnp.max(jnp.sum(disp, axis=0))) <= 1.0 + 1e-6
        # per-expert load ≤ capacity
        assert float(jnp.max(jnp.sum(disp, axis=(0, 2)))) <= 3 + 1e-6
        assert np.isfinite(float(aux))

    def test_combine_weights_sum_to_one_for_kept_tokens(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (8, 4))
        w = jax.random.normal(jax.random.PRNGKey(3), (4, 4)) * 0.1
        # generous capacity: nothing dropped
        disp, comb, _ = top_k_gating(x, w, top_k=2, capacity=16)
        sums = jnp.sum(comb, axis=(1, 2))
        assert np.allclose(sums, 1.0, atol=1e-5)


class TestMoE:
    def test_forward_and_grad_single_device(self):
        moe = MixtureOfExperts(d_model=8, d_hidden=16, num_experts=4,
                               top_k=2)
        params = moe.init()
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 6, 8))
        out, aux = moe.apply(params, x)
        assert out.shape == x.shape

        def loss(p):
            o, a = moe.apply(p, x)
            return jnp.sum(jnp.square(o)) + 0.01 * a
        g = jax.jit(jax.grad(loss))(params)
        for leaf in jax.tree.leaves(g):
            assert np.all(np.isfinite(np.asarray(leaf)))

    def test_expert_parallel_on_mesh(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        mesh = make_mesh({"expert": 8})
        moe = MixtureOfExperts(d_model=8, d_hidden=16, num_experts=8,
                               top_k=2)
        params = moe.shard(moe.init(), mesh, axis="expert")
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 8))

        @jax.jit
        def step(p, x):
            def loss(p):
                o, a = moe.apply(p, x)
                return jnp.mean(jnp.square(o)) + 0.01 * a
            return jax.value_and_grad(loss)(p)

        val, g = step(params, x)
        assert np.isfinite(float(val))
        # sharded leaves keep their expert-axis sharding
        assert g["w_in"].shape == (8, 8, 16)

    def test_ep_matches_single_device(self):
        """Same params, same input: EP-sharded == unsharded output."""
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        moe = MixtureOfExperts(d_model=4, d_hidden=8, num_experts=8,
                               top_k=2, seed=3)
        params = moe.init()
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 8, 4))
        ref, _ = moe.apply(params, x)
        mesh = make_mesh({"expert": 8})
        sharded = moe.shard(params, mesh, axis="expert")
        out, _ = jax.jit(moe.apply)(sharded, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)


class TestPipeline:
    def _stacked_params(self, S, d, seed=0):
        key = jax.random.PRNGKey(seed)
        k1, k2 = jax.random.split(key)
        return {"W": jax.random.normal(k1, (S, d, d)) * 0.1,
                "b": jax.random.normal(k2, (S, d)) * 0.01}

    def test_pipeline_matches_sequential(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        S, M, mb, d = 8, 4, 2, 6
        mesh = make_mesh({"stage": S})
        params = self._stacked_params(S, d)
        stage_fn = make_mlp_stage()
        x = jax.random.normal(jax.random.PRNGKey(1), (M, mb, d))
        out = pipeline_apply(stage_fn, params, x, mesh=mesh,
                             axis="stage")
        # sequential reference: stage 0..S-1 applied in order
        ref = x
        for s in range(S):
            p_s = jax.tree.map(lambda p: p[s], params)
            ref = jax.vmap(lambda xm: stage_fn(p_s, xm))(ref)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_pipeline_train_step_learns(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        S, M, mb, d = 8, 4, 2, 6
        mesh = make_mesh({"stage": S})
        params = self._stacked_params(S, d, seed=5)
        stage_fn = make_mlp_stage()
        x = jax.random.normal(jax.random.PRNGKey(2), (M, mb, d))
        y = jax.random.normal(jax.random.PRNGKey(3), (M, mb, d))

        def loss_fn(out, target):
            return jnp.mean(jnp.square(out - target))

        step, opt = pipeline_train_step(
            stage_fn, loss_fn, mesh=mesh, axis="stage",
            optimizer=optax.adam(1e-2))
        opt_state = opt.init(params)
        losses = []
        for _ in range(10):
            params, opt_state, loss = step(params, opt_state, x, y)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        assert all(np.isfinite(l) for l in losses)
