"""GraphRunner (TF in-process execution) + python4j executor tests.
Reference analogs: GraphRunnerTest (nd4j-tensorflow),
PythonExecutionerTest (python4j-core).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.python4j import PythonExecutioner, PythonJob


def _make_graphdef():
    tf = pytest.importorskip("tensorflow")

    @tf.function
    def f(a, b):
        return {"sum": a + b, "prod": tf.matmul(a, b)}

    conc = f.get_concrete_function(
        tf.TensorSpec([2, 2], tf.float32, name="a"),
        tf.TensorSpec([2, 2], tf.float32, name="b"))
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    frozen = convert_variables_to_constants_v2(conc)
    return frozen.graph.as_graph_def(), frozen


def test_graph_runner_matches_tf():
    gd, frozen = _make_graphdef()
    from deeplearning4j_tpu.modelimport.graph_runner import GraphRunner
    runner = GraphRunner(gd, input_names=["a", "b"],
                         output_names=["Identity", "Identity_1"])
    a = np.arange(4, dtype=np.float32).reshape(2, 2)
    b = np.ones((2, 2), np.float32)
    out = runner.run({"a": a, "b": b})
    # exact per-value comparison against the known math: outputs are
    # {a+b, a@b} in some Identity order
    want = {"sum": a + b, "prod": a @ b}
    got = list(out.values())
    m = [np.allclose(g, want["sum"]) or np.allclose(g, want["prod"])
         for g in got]
    assert all(m) and not np.allclose(got[0], got[1])
    # run_list order matches output_names and values match run()
    outs = runner.run_list([a, b])
    for name, v in zip(runner.output_names, outs):
        np.testing.assert_array_equal(v, out[name])
    # float64 numpy inputs are coerced to the placeholder dtype
    out64 = runner.run({"a": a.astype(np.float64),
                        "b": b.astype(np.float64)})
    for name in runner.output_names:
        np.testing.assert_allclose(out64[name], out[name], rtol=1e-6)


def test_graph_runner_skips_zero_output_terminals():
    tf = pytest.importorskip("tensorflow")
    gd, _ = _make_graphdef()
    noop = gd.node.add()
    noop.name = "init"
    noop.op = "NoOp"
    from deeplearning4j_tpu.modelimport.graph_runner import GraphRunner
    runner = GraphRunner(gd, input_names=["a", "b"])  # auto outputs
    assert "init" not in runner.output_names
    assert set(runner.output_names) == {"Identity", "Identity_1"}


def test_python_executioner():
    out = PythonExecutioner.exec(
        "c = a + b\nd = (a * b).sum()",
        inputs={"a": np.arange(3.0), "b": np.ones(3)},
        outputs=["c", "d"])
    np.testing.assert_allclose(out["c"], [1.0, 2.0, 3.0])
    assert out["d"] == 3.0
    with pytest.raises(KeyError):
        PythonExecutioner.exec("x = 1", outputs=["y"])


def test_python_job_setup_reuse():
    job = PythonJob("scale", "y = w * x", setup_code="w = 10")
    assert job.exec({"x": 3}, ["y"])["y"] == 30
    # fresh namespace per exec: leakage from previous run is not visible
    assert job.exec({"x": 4}, ["y"])["y"] == 40
    # in-place mutation of setup state doesn't leak across runs either
    job2 = PythonJob("acc", "w.append(x)\ny = list(w)", setup_code="w = []")
    assert job2.exec({"x": 1}, ["y"])["y"] == [1]
    assert job2.exec({"x": 2}, ["y"])["y"] == [2]
    # zero-copy: the SAME array object flows through
    a = np.zeros(4)
    out = PythonExecutioner.exec("b = a", inputs={"a": a}, outputs=["b"])
    assert out["b"] is a
