"""ONNX conformance sweep (reference: the onnx-import golden suite in
``nd4j-onnxruntime`` / samediff-import — many tiny graphs executed and
compared per-op).

Like the TF sweep, cases are *generated*: every mapped ONNX op is swept
across shapes/attrs, the graph bytes are produced by the in-package
``OnnxBuilder`` (the image has no ``onnx`` package), and goldens come
from torch (or exact numpy) running the same computation.  A coverage
test fails if a mapped op family is never swept.
"""
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from deeplearning4j_tpu.modelimport.onnx_import import (  # noqa: E402
    OnnxBuilder, _MAPPERS, import_onnx)

RNG = np.random.default_rng(77)
SWEPT = set()


def F32(*shape, lo=None, hi=None, scale=1.0):
    a = (RNG.normal(size=shape) * scale).astype(np.float32)
    if lo is not None:
        a = np.clip(a, lo, hi).astype(np.float32)
    return a


CASES = []


def ocase(cid, nodes, golden, inputs, rtol=1e-4, atol=1e-5, inits=()):
    """nodes: list of (op, in_names, out_names, attrs) building from
    graph inputs x0,x1,... to final output 'out'.  Input indices in
    ``inits`` become graph initializers (how real exporters carry
    shape/axes tensors) — still passed to the golden fn."""
    CASES.append(pytest.param(nodes, golden, inputs, rtol, atol,
                              frozenset(inits), id=cid))


def _run_case(nodes, golden, inputs, rtol, atol, inits=frozenset()):
    b = OnnxBuilder()
    feed = {}
    for i, a in enumerate(inputs):
        if i in inits:
            b.init(f"x{i}", a)
            continue
        b.input(f"x{i}", list(a.shape), a.dtype.type)
        feed[f"x{i}"] = a
    b.output("out")
    for op, ins, outs, attrs in nodes:
        b.node(op, ins, outs, **attrs)
        SWEPT.add(op)
    sd, vars_ = import_onnx(b.build())
    res = sd.output(feed, [vars_["out"]])
    got = res[vars_["out"].name]
    want = np.asarray(golden(*inputs))
    assert got.shape == want.shape, (got.shape, want.shape)
    if want.dtype.kind in "fc":
        np.testing.assert_allclose(got, want, rtol=rtol, atol=atol)
    else:
        np.testing.assert_array_equal(got, want)


def T(fn):
    """Torch golden from numpy inputs."""
    def g(*arrs):
        with torch.no_grad():
            return fn(*[torch.from_numpy(a) for a in arrs]).numpy()
    return g


# --- unary ------------------------------------------------------------------
_UNARY = {
    "Abs": (torch.abs, None, None), "Ceil": (torch.ceil, None, None),
    "Cos": (torch.cos, None, None), "Sin": (torch.sin, None, None),
    "Tan": (torch.tan, -1.2, 1.2), "Exp": (torch.exp, None, None),
    "Floor": (torch.floor, None, None),
    "Round": (torch.round, None, None),
    "Neg": (torch.neg, None, None), "Sign": (torch.sign, None, None),
    "Relu": (torch.relu, None, None),
    "Sigmoid": (torch.sigmoid, None, None),
    "Tanh": (torch.tanh, None, None),
    "Erf": (torch.erf, None, None),
    "Softplus": (F.softplus, None, None),
    "Elu": (F.elu, None, None),
    "LeakyRelu": (F.leaky_relu, None, None),
    "Gelu": (F.gelu, None, None),
    "Log": (torch.log, 0.1, 9.0), "Sqrt": (torch.sqrt, 0.0, 9.0),
    "Reciprocal": (torch.reciprocal, 0.3, 5.0),
}
for name, (tfn, lo, hi) in _UNARY.items():
    for sid, shp in [("r2", (3, 4)), ("r3", (2, 3, 5))]:
        ocase(f"unary-{name}-{sid}",
              [(name, ["x0"], ["out"], {})], T(tfn),
              [F32(*shp, lo=lo, hi=hi)])

# --- binary / variadic ------------------------------------------------------
_BINARY = {"Add": torch.add, "Sub": torch.sub, "Mul": torch.mul}
for name, tfn in _BINARY.items():
    ocase(f"binary-{name}", [(name, ["x0", "x1"], ["out"], {})],
          T(tfn), [F32(3, 4), F32(3, 4)])
    ocase(f"binary-{name}-bcast", [(name, ["x0", "x1"], ["out"], {})],
          T(tfn), [F32(2, 3, 4), F32(4)])
ocase("binary-Div", [("Div", ["x0", "x1"], ["out"], {})],
      T(torch.div), [F32(3, 4), F32(3, 4, lo=0.5, hi=4.0)])
ocase("binary-Pow", [("Pow", ["x0", "x1"], ["out"], {})],
      T(torch.pow), [F32(3, 4, lo=0.2, hi=3.0), F32(3, 4, lo=-2.0,
                                                    hi=2.0)])
ocase("variadic-Max", [("Max", ["x0", "x1", "x2"], ["out"], {})],
      lambda a, b, c: np.maximum(np.maximum(a, b), c),
      [F32(3, 4), F32(3, 4), F32(3, 4)])
ocase("variadic-Min", [("Min", ["x0", "x1", "x2"], ["out"], {})],
      lambda a, b, c: np.minimum(np.minimum(a, b), c),
      [F32(3, 4), F32(3, 4), F32(3, 4)])
ocase("variadic-Sum", [("Sum", ["x0", "x1", "x2"], ["out"], {})],
      lambda a, b, c: a + b + c, [F32(3, 4), F32(3, 4), F32(3, 4)])

# --- reductions -------------------------------------------------------------
_RED = {"ReduceSum": np.sum, "ReduceMean": np.mean,
        "ReduceMax": np.max, "ReduceMin": np.min}
for name, nfn in _RED.items():
    ocase(f"reduce-{name}-ax1keep",
          [(name, ["x0"], ["out"], {"axes": [1], "keepdims": 1})],
          lambda x, nfn=nfn: nfn(x, axis=1, keepdims=True),
          [F32(2, 3, 4)])
    ocase(f"reduce-{name}-ax02",
          [(name, ["x0"], ["out"], {"axes": [0, 2], "keepdims": 0})],
          lambda x, nfn=nfn: nfn(x, axis=(0, 2)), [F32(2, 3, 4)])
    ocase(f"reduce-{name}-all",
          [(name, ["x0"], ["out"], {"keepdims": 1})],
          lambda x, nfn=nfn: nfn(x, keepdims=True), [F32(3, 4)])

# --- matmul / gemm ----------------------------------------------------------
ocase("matmul-2d", [("MatMul", ["x0", "x1"], ["out"], {})],
      T(torch.matmul), [F32(3, 4), F32(4, 5)], rtol=1e-3)
ocase("matmul-batch", [("MatMul", ["x0", "x1"], ["out"], {})],
      T(torch.matmul), [F32(2, 3, 4), F32(2, 4, 5)], rtol=1e-3)
ocase("gemm-plain", [("Gemm", ["x0", "x1", "x2"], ["out"], {})],
      lambda a, b, c: a @ b + c, [F32(3, 4), F32(4, 5), F32(5)],
      rtol=1e-3)
ocase("gemm-transB",
      [("Gemm", ["x0", "x1", "x2"], ["out"], {"transB": 1})],
      lambda a, b, c: a @ b.T + c, [F32(3, 4), F32(5, 4), F32(5)],
      rtol=1e-3)
ocase("gemm-alphabeta",
      [("Gemm", ["x0", "x1", "x2"], ["out"],
        {"alpha": 0.5, "beta": 2.0, "transA": 1})],
      lambda a, b, c: 0.5 * (a.T @ b) + 2.0 * c,
      [F32(4, 3), F32(4, 5), F32(5)], rtol=1e-3)

# --- shape ops --------------------------------------------------------------
ocase("reshape-zeros-minus1", [("Reshape", ["x0", "x1"], ["out"], {})],
      lambda x, s: x.reshape(2, -1),
      [F32(2, 3, 4), np.asarray([0, -1], np.int64)], inits=(1,))
ocase("flatten-ax1", [("Flatten", ["x0"], ["out"], {"axis": 1})],
      lambda x: x.reshape(2, -1), [F32(2, 3, 4)])
ocase("flatten-ax2", [("Flatten", ["x0"], ["out"], {"axis": 2})],
      lambda x: x.reshape(6, 4), [F32(2, 3, 4)])
ocase("transpose-perm",
      [("Transpose", ["x0"], ["out"], {"perm": [0, 2, 1]})],
      lambda x: x.transpose(0, 2, 1), [F32(2, 3, 4)])
ocase("transpose-default", [("Transpose", ["x0"], ["out"], {})],
      lambda x: x.T, [F32(3, 5)])
ocase("squeeze-attr", [("Squeeze", ["x0"], ["out"], {"axes": [1]})],
      lambda x: x.squeeze(1), [F32(2, 1, 4)])
ocase("unsqueeze-attr",
      [("Unsqueeze", ["x0"], ["out"], {"axes": [0, 3]})],
      lambda x: x[None, ..., None], [F32(3, 4)])
ocase("concat-ax1", [("Concat", ["x0", "x1"], ["out"], {"axis": 1})],
      lambda a, b: np.concatenate([a, b], 1), [F32(2, 3), F32(2, 5)])
ocase("concat-neg", [("Concat", ["x0", "x1"], ["out"], {"axis": -1})],
      lambda a, b: np.concatenate([a, b], -1),
      [F32(2, 3, 2), F32(2, 3, 4)])
ocase("gather-ax0", [("Gather", ["x0", "x1"], ["out"], {})],
      lambda x, i: np.take(x, i, 0),
      [F32(5, 3), RNG.integers(0, 5, 4).astype(np.int64)])
ocase("gather-ax1", [("Gather", ["x0", "x1"], ["out"], {"axis": 1})],
      lambda x, i: np.take(x, i, 1),
      [F32(3, 6), RNG.integers(0, 6, 2).astype(np.int64)])
ocase("slice-steps",
      [("Slice", ["x0", "x1", "x2", "x3", "x4"], ["out"], {})],
      lambda x, s, e, a, st: x[1:5:2],
      [F32(6, 3), np.asarray([1], np.int64), np.asarray([5], np.int64),
       np.asarray([0], np.int64), np.asarray([2], np.int64)],
      inits=(1, 2, 3, 4))
ocase("pad-constant",
      [("Pad", ["x0"], ["out"], {"pads": [1, 0, 0, 2]})],
      lambda x: np.pad(x, [(1, 0), (0, 2)]), [F32(2, 3)])
ocase("pad-reflect",
      [("Pad", ["x0"], ["out"],
        {"pads": [0, 1, 0, 1], "mode": "reflect"})],
      lambda x: np.pad(x, [(0, 0), (1, 1)], mode="reflect"),
      [F32(2, 5)])
ocase("pad-edge",
      [("Pad", ["x0"], ["out"], {"pads": [1, 0, 1, 0], "mode": "edge"})],
      lambda x: np.pad(x, [(1, 1), (0, 0)], mode="edge"), [F32(3, 4)])
ocase("cast-roundtrip",
      [("Cast", ["x0"], ["i"], {"to": 6}),      # 6 = int32
       ("Cast", ["i"], ["out"], {"to": 1})],    # 1 = float32
      lambda x: x.astype(np.int32).astype(np.float32),
      [F32(3, 4, scale=3.0)])
ocase("identity-dropout",
      [("Dropout", ["x0"], ["out"], {})], lambda x: x, [F32(3, 4)])

# --- activations with attrs -------------------------------------------------
ocase("softmax-neg", [("Softmax", ["x0"], ["out"], {"axis": -1})],
      T(lambda x: torch.softmax(x, -1)), [F32(4, 6)])
ocase("softmax-ax1", [("Softmax", ["x0"], ["out"], {"axis": 1})],
      T(lambda x: torch.softmax(x, 1)), [F32(2, 3, 5)])
ocase("logsoftmax", [("LogSoftmax", ["x0"], ["out"], {"axis": -1})],
      T(lambda x: torch.log_softmax(x, -1)), [F32(4, 6)])
ocase("clip-attrs",
      [("Clip", ["x0"], ["out"], {"min": -0.5, "max": 0.5})],
      lambda x: np.clip(x, -0.5, 0.5), [F32(4, 6)])
ocase("prelu", [("PRelu", ["x0", "x1"], ["out"], {})],
      lambda x, s: np.where(x >= 0, x, s * x),
      [F32(3, 4), np.asarray([0.25], np.float32)])

# --- nn ---------------------------------------------------------------------
def _conv_case(cid, cin, cout, k, stride, pads, groups=1):
    x = F32(2, cin, 8, 8, scale=0.5)
    w = F32(cout, cin // groups, k, k, scale=0.3)
    bias = F32(cout, scale=0.1)
    ocase(cid,
          [("Conv", ["x0", "x1", "x2"], ["out"],
            {"kernel_shape": [k, k], "strides": [stride, stride],
             "pads": pads * 2, "group": groups})],
          T(lambda x, w, b: F.conv2d(
              x, w, b, stride=stride, padding=pads[0],
              groups=groups)),
          [x, w, bias], rtol=2e-3, atol=1e-4)


_conv_case("conv-3x3-same", 3, 4, 3, 1, [1, 1])
_conv_case("conv-3x3-valid", 3, 4, 3, 1, [0, 0])
_conv_case("conv-stride2", 2, 3, 3, 2, [1, 1])
_conv_case("conv-1x1", 4, 6, 1, 1, [0, 0])
_conv_case("conv-grouped", 4, 4, 3, 1, [1, 1], groups=2)

ocase("convtranspose",
      [("ConvTranspose", ["x0", "x1"], ["out"],
        {"kernel_shape": [2, 2], "strides": [2, 2]})],
      T(lambda x, w: F.conv_transpose2d(x, w, stride=2)),
      [F32(1, 3, 4, 4, scale=0.5), F32(3, 2, 2, 2, scale=0.3)],
      rtol=2e-3, atol=1e-4)
ocase("maxpool",
      [("MaxPool", ["x0"], ["out"],
        {"kernel_shape": [2, 2], "strides": [2, 2]})],
      T(lambda x: F.max_pool2d(x, 2)), [F32(2, 3, 8, 8)])
ocase("maxpool-pads",
      [("MaxPool", ["x0"], ["out"],
        {"kernel_shape": [3, 3], "strides": [2, 2],
         "pads": [1, 1, 1, 1]})],
      T(lambda x: F.max_pool2d(x, 3, 2, padding=1)),
      [F32(1, 2, 7, 7)])
ocase("avgpool",
      [("AveragePool", ["x0"], ["out"],
        {"kernel_shape": [2, 2], "strides": [2, 2]})],
      T(lambda x: F.avg_pool2d(x, 2)), [F32(2, 3, 8, 8)])
ocase("globalavgpool", [("GlobalAveragePool", ["x0"], ["out"], {})],
      lambda x: x.mean((2, 3), keepdims=True), [F32(2, 3, 5, 7)])
ocase("globalmaxpool", [("GlobalMaxPool", ["x0"], ["out"], {})],
      lambda x: x.max((2, 3), keepdims=True), [F32(2, 3, 5, 7)])
ocase("batchnorm-inference",
      [("BatchNormalization", ["x0", "x1", "x2", "x3", "x4"], ["out"],
        {"epsilon": 1e-5})],
      lambda x, s, b, m, v: s[None, :, None, None]
      * (x - m[None, :, None, None])
      / np.sqrt(v[None, :, None, None] + 1e-5)
      + b[None, :, None, None],
      [F32(2, 3, 4, 4), F32(3, lo=0.5, hi=1.5), F32(3),
       F32(3, scale=0.1), F32(3, lo=0.5, hi=1.5)], rtol=1e-3)
ocase("lrn",
      [("LRN", ["x0"], ["out"],
        {"alpha": 1e-3, "beta": 0.75, "bias": 1.0, "size": 3})],
      T(lambda x: F.local_response_norm(x, 3, alpha=1e-3, beta=0.75,
                                        k=1.0)),
      [F32(2, 6, 4, 4)], rtol=1e-3)

# --- composites -------------------------------------------------------------
ocase("composite-mlp",
      [("Gemm", ["x0", "x1", "x2"], ["h"], {"transB": 1}),
       ("Relu", ["h"], ["hr"], {}),
       ("Gemm", ["hr", "x3", "x4"], ["lg"], {"transB": 1}),
       ("Softmax", ["lg"], ["out"], {"axis": -1})],
      T(lambda x, w1, b1, w2, b2: torch.softmax(
          F.linear(torch.relu(F.linear(x, w1, b1)), w2, b2), -1)),
      [F32(4, 6), F32(8, 6, scale=0.3), F32(8), F32(3, 8, scale=0.3),
       F32(3)], rtol=1e-3)
ocase("composite-residual",
      [("MatMul", ["x0", "x1"], ["h"], {}),
       ("Relu", ["h"], ["hr"], {}),
       ("Add", ["x0", "hr"], ["out"], {})],
      lambda x, w: x + np.maximum(x @ w, 0),
      [F32(3, 6), F32(6, 6, scale=0.3)], rtol=1e-3)


# regression for the dormant ConvTranspose bug the sweep caught:
# asymmetric channel counts + nonzero ONNX pads
ocase("convtranspose-padded",
       [("ConvTranspose", ["x0", "x1", "x2"], ["out"],
         {"kernel_shape": [3, 3], "strides": [2, 2],
          "pads": [1, 1, 1, 1]})],
       T(lambda x, w, b: F.conv_transpose2d(x, w, b, stride=2,
                                            padding=1)),
       [F32(1, 4, 5, 5, scale=0.5), F32(4, 3, 3, 3, scale=0.3),
        F32(3, scale=0.1)], rtol=2e-3, atol=1e-4)


ocase("convtranspose-outputpadding",
      [("ConvTranspose", ["x0", "x1"], ["out"],
        {"kernel_shape": [3, 3], "strides": [2, 2],
         "pads": [1, 1, 1, 1], "output_padding": [1, 1]})],
      T(lambda x, w: F.conv_transpose2d(x, w, stride=2, padding=1,
                                        output_padding=1)),
      [F32(1, 3, 4, 4, scale=0.5), F32(3, 2, 3, 3, scale=0.3)],
      rtol=2e-3, atol=1e-4)
ocase("convtranspose-dilated",
      [("ConvTranspose", ["x0", "x1"], ["out"],
        {"kernel_shape": [3, 3], "strides": [1, 1],
         "dilations": [2, 2]})],
      T(lambda x, w: F.conv_transpose2d(x, w, dilation=2)),
      [F32(1, 2, 5, 5, scale=0.5), F32(2, 3, 3, 3, scale=0.3)],
      rtol=2e-3, atol=1e-4)
ocase("convtranspose-grouped",
      [("ConvTranspose", ["x0", "x1"], ["out"],
        {"kernel_shape": [3, 3], "strides": [2, 2], "group": 2})],
      T(lambda x, w: F.conv_transpose2d(x, w, stride=2, groups=2)),
      [F32(1, 4, 4, 4, scale=0.5), F32(4, 2, 3, 3, scale=0.3)],
      rtol=2e-3, atol=1e-4)
ocase("convtranspose-1d",
      [("ConvTranspose", ["x0", "x1"], ["out"],
        {"kernel_shape": [4], "strides": [2], "pads": [1, 1]})],
      T(lambda x, w: F.conv_transpose1d(x, w, stride=2, padding=1)),
      [F32(2, 3, 6, scale=0.5), F32(3, 2, 4, scale=0.3)],
      rtol=2e-3, atol=1e-4)


def test_convtranspose_autopad_rejected():
    b = OnnxBuilder()
    b.input("x", [1, 2, 4, 4]).output("out")
    b.init("w", F32(2, 2, 3, 3))
    b.node("ConvTranspose", ["x", "w"], ["out"],
           kernel_shape=[3, 3], auto_pad="SAME_UPPER")
    with pytest.raises(ValueError, match="auto_pad"):
        import_onnx(b.build())


@pytest.mark.parametrize("nodes,golden,inputs,rtol,atol,inits", CASES)
def test_onnx_conformance(nodes, golden, inputs, rtol, atol, inits):
    _run_case(nodes, golden, inputs, rtol, atol, inits)


def test_onnx_sweep_coverage():
    """Every mapped ONNX op must be exercised by the sweep (structural
    ops the builder emits implicitly are exempt)."""
    assert len(CASES) >= 100, len(CASES)
    if not SWEPT:
        pytest.skip("conformance cases did not run")
    exempt = {"Constant", "Identity"}
    unswept = sorted(set(_MAPPERS) - SWEPT - exempt)
    assert not unswept, f"mapped ONNX ops never swept: {unswept}"
