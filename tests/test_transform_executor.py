"""Distributed TransformProcess execution (reference
SparkTransformExecutor, datavec-spark): partition-parallel results must
be identical to the sequential LocalTransformExecutor path, including
closure-bearing transform steps."""
import numpy as np

from deeplearning4j_tpu.data import DistributedTransformExecutor
from deeplearning4j_tpu.data.transform import Schema, TransformProcess


def _tp_and_records(n=6000):
    rng = np.random.default_rng(0)
    schema = (Schema.Builder()
              .add_column_double("a")
              .add_column_double("b")
              .add_column_categorical("cls", ["cat", "dog", "owl"])
              .add_column_integer("drop_me")
              .build())
    tp = (TransformProcess.Builder(schema)
          .remove_columns("drop_me")
          .categorical_to_integer("cls")
          .transform_column("a", lambda v: v * 2.0 + 1.0)  # closure!
          .build())
    cats = ["cat", "dog", "owl"]
    records = [[float(i) * 0.5, float(rng.normal()),
                cats[i % 3], i] for i in range(n)]
    return tp, records


def test_distributed_matches_sequential():
    tp, records = _tp_and_records()
    want = tp.execute(records)
    got = DistributedTransformExecutor(num_workers=4).execute(
        tp, records)
    assert got == want                  # same rows, same order


def test_small_input_stays_sequential():
    tp, records = _tp_and_records(100)
    ex = DistributedTransformExecutor(num_workers=4,
                                      min_parallel_records=2048)
    assert ex.execute(tp, records) == tp.execute(records)


def test_single_worker_fallback():
    tp, records = _tp_and_records(3000)
    ex = DistributedTransformExecutor(num_workers=1)
    assert ex.execute(tp, records) == tp.execute(records)


def test_spawn_fallback_with_closure_transform():
    """A closure-bearing TransformProcess under spawn cannot pickle —
    the executor must detect that before paying for a pool and fall
    back to sequential, staying correct.  (The picklable-under-spawn
    happy path is not testable here: spawn children re-import the
    parent __main__, which deadlocks under pytest in this image.)"""
    tp2, records2 = _tp_and_records(3000)   # has a lambda step
    got2 = DistributedTransformExecutor(
        num_workers=2, start_method="spawn").execute(tp2, records2)
    assert got2 == tp2.execute(records2)    # fallback path
