"""TRUE multi-process distributed training test: two OS processes form
a jax.distributed cluster on localhost (2 procs x 2 CPU devices = one
4-device global mesh) and run SparkDl4jMultiLayer fit over it, each
process feeding its shard. Reference analog: GradientSharingTrainingTest
/ DelayedModelParameterServerTest simulate multi-node in ONE JVM
(SURVEY §4); this exercises the real process boundary instead.
"""
import os
import textwrap

import pytest

WORKER = textwrap.dedent("""
    import os, sys, warnings
    sys.path.insert(0, %(repo)r)
    warnings.filterwarnings("ignore")
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=os.environ["COORD"],
        num_processes=int(os.environ["NPROC"]),
        process_id=int(os.environ["PROC_ID"]))
    import numpy as np
    import jax.numpy as jnp

    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.parallel import (
        ParameterAveragingTrainingMaster, ShardedDataSetIterator,
        SparkDl4jMultiLayer, make_mesh)

    pid = jax.process_index()
    conf = (NeuralNetConfiguration.builder().seed(42)
            .updater(upd.Adam(learning_rate=0.05)).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)          # same data on every proc
    x = rng.standard_normal((448, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    # 7 batches -> UNEVEN round-robin shards (4 vs 3): processes must
    # agree on the per-epoch step count instead of desyncing/hanging
    data = [DataSet(x[i:i + 64], y[i:i + 64]) for i in range(0, 448, 64)]

    master = (ParameterAveragingTrainingMaster.Builder(64)
              .averaging_frequency(2).build())
    trainer = SparkDl4jMultiLayer(net, master)
    # each process trains on its round-robin shard of the batches
    trainer.fit(ShardedDataSetIterator(data), epochs=8)
    score = trainer.score()
    print(f"proc {pid} score {score:.4f}", flush=True)
    assert score < 0.4, score
    # replicated params must be identical across processes: compare a
    # checksum via a collective
    leaf = jax.tree.leaves(net.params)[0]
    s = float(jnp.sum(jnp.asarray(leaf)))
    print(f"proc {pid} checksum {s:.6f}", flush=True)

    # distributed evaluation: each process evaluates ONLY its shard,
    # merge_across_processes must reconstruct the full-data Evaluation
    # (reference SparkDl4jMultiLayer#doEvaluation reduce semantics)
    ev = trainer.evaluate(ShardedDataSetIterator(data))
    full = net.evaluate(ListDataSetIterator(data))   # all data, local
    assert ev.count == full.count, (ev.count, full.count)
    assert (ev.confusion == full.confusion).all()
    print(f"proc {pid} evalacc {ev.accuracy():.6f}", flush=True)
    print(f"proc {pid} DONE", flush=True)
""")


@pytest.mark.skipif(os.environ.get("DL4J_TPU_SKIP_MP") == "1",
                    reason="multi-process test disabled")
def test_two_process_distributed_training(tmp_path):
    from mp_harness import assert_all_done, run_two_process_workers
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(WORKER % {"repo": repo})
    procs, outs = run_two_process_workers(
        script, port=29500 + (os.getpid() % 500))
    assert_all_done(procs, outs)
    # identical replicated params on both processes
    import re
    sums = [re.search(r"checksum (-?[\d.]+)", o).group(1) for o in outs]
    assert sums[0] == sums[1], sums
    # merged evaluation identical on both processes
    accs = [re.search(r"evalacc (-?[\d.]+)", o).group(1) for o in outs]
    assert accs[0] == accs[1], accs
