"""TRUE multi-process distributed training test: two OS processes form
a jax.distributed cluster on localhost (2 procs x 2 CPU devices = one
4-device global mesh) and run SparkDl4jMultiLayer fit over it, each
process feeding its shard. Reference analog: GradientSharingTrainingTest
/ DelayedModelParameterServerTest simulate multi-node in ONE JVM
(SURVEY §4); this exercises the real process boundary instead.
"""
import os
import textwrap

import pytest

WORKER = textwrap.dedent("""
    import os, sys, warnings
    sys.path.insert(0, %(repo)r)
    warnings.filterwarnings("ignore")
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=os.environ["COORD"],
        num_processes=int(os.environ["NPROC"]),
        process_id=int(os.environ["PROC_ID"]))
    import numpy as np
    import jax.numpy as jnp

    from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import updaters as upd
    from deeplearning4j_tpu.parallel import (
        ParameterAveragingTrainingMaster, ShardedDataSetIterator,
        SparkDl4jMultiLayer, make_mesh)

    pid = jax.process_index()
    conf = (NeuralNetConfiguration.builder().seed(42)
            .updater(upd.Adam(learning_rate=0.05)).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)          # same data on every proc
    x = rng.standard_normal((448, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    # 7 batches -> UNEVEN round-robin shards (4 vs 3): processes must
    # agree on the per-epoch step count instead of desyncing/hanging
    data = [DataSet(x[i:i + 64], y[i:i + 64]) for i in range(0, 448, 64)]

    master = (ParameterAveragingTrainingMaster.Builder(64)
              .averaging_frequency(2).build())
    trainer = SparkDl4jMultiLayer(net, master)
    # each process trains on its round-robin shard of the batches
    trainer.fit(ShardedDataSetIterator(data), epochs=8)
    score = trainer.score()
    print(f"proc {pid} score {score:.4f}", flush=True)
    assert score < 0.4, score
    # replicated params must be identical across processes: compare a
    # checksum via a collective
    leaf = jax.tree.leaves(net.params)[0]
    s = float(jnp.sum(jnp.asarray(leaf)))
    print(f"proc {pid} checksum {s:.6f}", flush=True)

    # distributed evaluation: each process evaluates ONLY its shard,
    # merge_across_processes must reconstruct the full-data Evaluation
    # (reference SparkDl4jMultiLayer#doEvaluation reduce semantics)
    ev = trainer.evaluate(ShardedDataSetIterator(data))
    full = net.evaluate(ListDataSetIterator(data))   # all data, local
    assert ev.count == full.count, (ev.count, full.count)
    assert (ev.confusion == full.confusion).all()
    print(f"proc {pid} evalacc {ev.accuracy():.6f}", flush=True)
    print(f"proc {pid} DONE", flush=True)
""")


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("DL4J_TPU_SKIP_MP") == "1",
                    reason="multi-process test disabled")
def test_two_process_distributed_training(tmp_path):
    from mp_harness import assert_all_done, run_two_process_workers
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(WORKER % {"repo": repo})
    procs, outs = run_two_process_workers(
        script, port=29500 + (os.getpid() % 500))
    assert_all_done(procs, outs)
    # identical replicated params on both processes
    import re
    sums = [re.search(r"checksum (-?[\d.]+)", o).group(1) for o in outs]
    assert sums[0] == sums[1], sums
    # merged evaluation identical on both processes
    accs = [re.search(r"evalacc (-?[\d.]+)", o).group(1) for o in outs]
    assert accs[0] == accs[1], accs


ENCODED_DCN_WORKER = textwrap.dedent("""
    import os, sys, warnings
    sys.path.insert(0, %(repo)r)
    warnings.filterwarnings("ignore")
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=os.environ["COORD"],
        num_processes=2, process_id=int(os.environ["PROC_ID"]))
    import numpy as np
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.parallel import (
        EncodedGradientsAccumulator, make_mesh)

    pid = jax.process_index()
    assert len(jax.devices()) == 8
    # 'slice' (major) spans the process boundary — the DCN tier; only
    # 2-bit packed words cross it. 'data' is the intra-process ICI
    # tier with a dense f32 mean.
    mesh = make_mesh({"slice": 2, "data": 4})
    acc = EncodedGradientsAccumulator()
    rng = np.random.default_rng(0)            # same on both procs
    per_dev = rng.standard_normal((8, 64, 256)).astype(np.float32) * 0.01
    # per-SLICE state: leading slice axis, carried P("slice") between
    # steps (exchange_hierarchical docstring)
    state0 = acc.init_state({"w": jnp.zeros((64, 256), jnp.float32)})
    state_h = jax.tree.map(lambda x: np.stack([np.asarray(x)] * 2),
                           state0)

    sh = NamedSharding(mesh, P(("slice", "data")))
    gw = jax.make_array_from_callback(
        per_dev.shape, sh, lambda idx: per_dev[idx])
    sh_state = NamedSharding(mesh, P("slice"))
    state = jax.tree.map(
        lambda h: jax.make_array_from_callback(
            h.shape, sh_state, lambda idx, hh=h: hh[idx]), state_h)

    def f(g, st):
        g = jax.tree.map(lambda x: x[0], g)
        st = jax.tree.map(lambda x: x[0], st)
        out, st = acc.exchange_hierarchical(g, st, intra_axis="data",
                                            cross_axis="slice")
        expand = lambda x: jnp.asarray(x)[None]
        return jax.tree.map(expand, out), jax.tree.map(expand, st)

    out, new_state = jax.jit(shard_map(
        f, mesh=mesh, in_specs=(P(("slice", "data")), P("slice")),
        out_specs=(P(("slice", "data")), P("slice")),
        check_vma=False))({"w": gw}, state)
    from jax.experimental import multihost_utils as mhu
    got = np.asarray(mhu.process_allgather(out["w"], tiled=True))

    # expected: intra-slice dense mean (4 devices) -> threshold encode
    # per slice -> cross-slice decoded average; every device identical
    tau = float(np.asarray(state0["tau"]))
    slice_means = per_dev.reshape(2, 4, 64, 256).mean(1)
    enc = np.where(slice_means > tau, tau,
                   np.where(slice_means < -tau, -tau, 0.0))
    want = enc.mean(0)
    assert got.shape == (8, 64, 256)
    err = float(np.max(np.abs(got - want[None])))
    assert err < 1e-6, err
    assert float(np.max(np.abs(got - got[0:1]))) == 0.0
    print(f"proc {pid} encoded-DCN err {err:.2e}", flush=True)
    print(f"proc {pid} DONE", flush=True)
""")


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("DL4J_TPU_SKIP_MP") == "1",
                    reason="multi-process test disabled")
def test_two_process_hierarchical_encoded_dp(tmp_path):
    """The DCN story across a REAL process boundary (VERDICT r4 ask
    #6): dense intra-process mean + threshold-encoded cross-process
    exchange; result equals the numpy-expected two-tier reduction and
    is bit-identical on every device of both processes."""
    from mp_harness import assert_all_done, run_two_process_workers
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker_encdp.py"
    script.write_text(ENCODED_DCN_WORKER % {"repo": repo})
    procs, outs = run_two_process_workers(
        script, port=29800 + (os.getpid() % 150),
        extra_env={"XLA_FLAGS":
                   "--xla_force_host_platform_device_count=4"},
        timeout=600)
    assert_all_done(procs, outs)
