"""MultiLayerNetwork tests: config round-trip, fit convergence, masks,
tBPTT, checkpointing. Reference analogs: MultiLayerTest,
MultiLayerNetworkFitTests, TestRnnLayers (deeplearning4j-core).
"""
import tempfile
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.config import InputType, \
    MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers import (DenseLayer, LSTM, OutputLayer,
                                          RnnOutputLayer)
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.serialization import ModelSerializer


def _xor_net(updater=None):
    return (NeuralNetConfiguration.builder()
            .seed(42)
            .updater(updater or upd.Adam(learning_rate=0.05))
            .weight_init_fn("xavier")
            .list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(2))
            .build())


XOR_X = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
XOR_Y = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], np.float32)


def test_fit_learns_xor():
    net = MultiLayerNetwork(_xor_net()).init()
    first = None
    for _ in range(300):
        net.fit(XOR_X, XOR_Y)
        if first is None:
            first = net.score()
    assert net.score() < 0.05 < first
    preds = np.asarray(net.output(XOR_X))
    assert (preds.argmax(1) == XOR_Y.argmax(1)).all()
    np.testing.assert_allclose(preds.sum(1), 1.0, rtol=1e-5)


def test_config_json_roundtrip():
    conf = _xor_net()
    s = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(s)
    assert conf2.to_json() == s
    net = MultiLayerNetwork(conf2).init()
    assert net.num_params() == (2 * 8 + 8) + (8 * 2 + 2)


def test_summary_and_num_params():
    net = MultiLayerNetwork(_xor_net()).init()
    s = net.summary()
    assert "DenseLayer" in s and "Total params" in s


def test_checkpoint_roundtrip(tmp_path):
    net = MultiLayerNetwork(_xor_net()).init()
    for _ in range(20):
        net.fit(XOR_X, XOR_Y)
    path = tmp_path / "model.zip"
    ModelSerializer.write_model(net, path)
    net2 = ModelSerializer.restore_multi_layer_network(path)
    np.testing.assert_allclose(np.asarray(net.output(XOR_X)),
                               np.asarray(net2.output(XOR_X)), rtol=1e-6)
    assert net2.iteration == net.iteration
    # resume training exactly: updater state restored
    net.fit(XOR_X, XOR_Y)
    net2.fit(XOR_X, XOR_Y)
    np.testing.assert_allclose(np.asarray(net.output(XOR_X)),
                               np.asarray(net2.output(XOR_X)), rtol=1e-5)


def test_fit_iterator_and_evaluate():
    ds = DataSet(XOR_X.repeat(8, 0), XOR_Y.repeat(8, 0))
    it = ListDataSetIterator(ds, batch_size=8, shuffle=True)
    net = MultiLayerNetwork(_xor_net()).init()
    net.fit(it, epochs=60)
    e = net.evaluate(it)
    assert e.accuracy() == 1.0
    assert "Accuracy" in e.stats()


def test_rnn_fit_and_tbptt():
    t, f, k = 8, 3, 2
    conf = (NeuralNetConfiguration.builder()
            .seed(7)
            .updater(upd.Adam(learning_rate=0.02))
            .list()
            .layer(LSTM(n_out=6))
            .layer(RnnOutputLayer(n_out=k, activation="softmax",
                                  loss="mcxent"))
            .backprop_type("TruncatedBPTT")
            .tbptt_fwd_length(4)
            .set_input_type(InputType.recurrent(f))
            .build())
    net = MultiLayerNetwork(conf).init(input_shape=(t, f))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, t, f)).astype(np.float32)
    # task: label = sign of first feature at each step
    y = np.stack([(x[..., 0] > 0), (x[..., 0] <= 0)], -1).astype(
        np.float32)
    first = None
    for _ in range(60):
        net.fit(x, y)
        if first is None:
            first = net.score()
    assert net.score() < first * 0.5


def test_rnn_time_step_stateful():
    conf = (NeuralNetConfiguration.builder()
            .seed(3)
            .list()
            .layer(LSTM(n_out=4))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(3))
            .build())
    net = MultiLayerNetwork(conf).init(input_shape=(None, 3))
    x = np.random.default_rng(1).normal(size=(1, 6, 3)).astype(np.float32)
    full = np.asarray(net.output(x))
    net.rnn_clear_previous_state()
    step_outs = [np.asarray(net.rnn_time_step(x[:, i])) for i in range(6)]
    np.testing.assert_allclose(full[0, -1], step_outs[-1][0], rtol=1e-4,
                               atol=1e-5)


def test_per_layer_updater_and_frozen():
    from deeplearning4j_tpu.nn.layers.special import FrozenLayer
    frozen_dense = FrozenLayer(underlying=DenseLayer(n_out=8,
                                                     activation="tanh"))
    conf = (NeuralNetConfiguration.builder()
            .seed(42)
            .updater(upd.Adam(learning_rate=0.05))
            .list()
            .layer(frozen_dense)
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(2))
            .build())
    net = MultiLayerNetwork(conf).init()
    w_before = np.asarray(net.params["layer_0"]["W"]).copy()
    for _ in range(5):
        net.fit(XOR_X, XOR_Y)
    np.testing.assert_array_equal(
        w_before, np.asarray(net.params["layer_0"]["W"]))
    assert not np.allclose(0, np.asarray(net.params["layer_1"]["W"]))


def test_l2_regularization_affects_score():
    conf_plain = _xor_net()
    b = NeuralNetConfiguration.builder().seed(42) \
        .updater(upd.Adam(learning_rate=0.05)).l2_(0.1).list() \
        .layer(DenseLayer(n_out=8, activation="tanh")) \
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
    b.set_input_type(InputType.feed_forward(2))
    conf_l2 = b.build()
    n1 = MultiLayerNetwork(conf_plain).init()
    n2 = MultiLayerNetwork(conf_l2).init()
    n1.fit(XOR_X, XOR_Y)
    n2.fit(XOR_X, XOR_Y)
    assert n2.score() > n1.score()  # includes penalty


def test_gradient_normalization_modes():
    for mode in ("ClipL2PerLayer", "ClipElementWiseAbsoluteValue",
                 "ClipL2PerParamType"):
        conf = (NeuralNetConfiguration.builder()
                .seed(1)
                .updater(upd.Sgd(learning_rate=0.1))
                .gradient_normalization(mode, 0.5)
                .list()
                .layer(DenseLayer(n_out=4, activation="tanh"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(2))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(XOR_X, XOR_Y)
        assert np.isfinite(net.score())


def test_masked_sequence_fit():
    conf = (NeuralNetConfiguration.builder()
            .seed(5)
            .updater(upd.Adam(learning_rate=0.05))
            .list()
            .layer(LSTM(n_out=4))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(2))
            .build())
    net = MultiLayerNetwork(conf).init(input_shape=(5, 2))
    rng = np.random.default_rng(2)
    x = rng.normal(size=(8, 5, 2)).astype(np.float32)
    y = np.stack([(x[..., 0] > 0), (x[..., 0] <= 0)], -1).astype(
        np.float32)
    mask = (rng.uniform(size=(8, 5)) > 0.3).astype(np.float32)
    mask[:, 0] = 1
    net.fit(x, y, features_mask=mask, labels_mask=mask)
    assert np.isfinite(net.score())


# ---------------------------------------------------------------------------
# mixed precision (compute_dtype: bf16 fwd/bwd, fp32 master params)
# ---------------------------------------------------------------------------
def _mp_net(compute_dtype):
    b = (NeuralNetConfiguration.builder().seed(7)
         .updater(upd.Adam(learning_rate=1e-2))
         .l2_(1e-4))
    if compute_dtype:
        b = b.compute_data_type(compute_dtype)
    conf = (b.list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def test_mixed_precision_trains_close_to_fp32():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 8)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
    n32, nbf = _mp_net(None), _mp_net("bfloat16")
    for _ in range(25):
        n32.fit(x, y)
        nbf.fit(x, y)
    assert abs(n32.score() - nbf.score()) < 0.15
    # master params and grads stay fp32 — optimizer state too
    assert all(p.dtype == jnp.float32
               for p in jax.tree.leaves(nbf.params))
    # inference returns fp32 even though compute ran bf16
    assert np.asarray(nbf.output(x)).dtype == np.float32


def test_mixed_precision_json_roundtrip():
    net = _mp_net("bfloat16")
    conf2 = MultiLayerConfiguration.from_json(net.conf.to_json())
    assert conf2.compute_dtype == "bfloat16"


def test_mixed_precision_tbptt_rnn():
    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(upd.Adam(learning_rate=0.02))
            .compute_data_type("bfloat16")
            .list()
            .backprop_type("TruncatedBPTT")
            .tbptt_fwd_length(4).tbptt_back_length(4)
            .layer(LSTM(n_out=4))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(2))
            .build())
    net = MultiLayerNetwork(conf).init(input_shape=(8, 2))
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 8, 2)).astype(np.float32)
    y = np.stack([(x[..., 0] > 0), (x[..., 0] <= 0)], -1).astype(
        np.float32)
    net.fit(x, y)
    assert np.isfinite(net.score())
    assert all(p.dtype == jnp.float32
               for p in jax.tree.leaves(net.params))


def test_tbptt_scanned_matches_sequential():
    """The scanned-segment tBPTT fast path (no masks, t % k == 0) must
    train identically to the per-segment sequential path (forced here
    with an all-ones features mask, which is semantically a no-op)."""
    t, f = 8, 3

    def make():
        conf = (NeuralNetConfiguration.builder()
                .seed(11)
                .updater(upd.Sgd(learning_rate=0.05))
                .list()
                .layer(LSTM(n_out=5))
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .backprop_type("TruncatedBPTT")
                .tbptt_fwd_length(2)
                .set_input_type(InputType.recurrent(f))
                .build())
        return MultiLayerNetwork(conf).init(input_shape=(t, f))

    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, t, f)).astype(np.float32)
    y = np.stack([(x[..., 0] > 0), (x[..., 0] <= 0)], -1).astype(
        np.float32)
    ones = np.ones((8, t), np.float32)
    a, b = make(), make()
    for _ in range(5):
        a.fit(x, y)                       # scanned fast path
        b.fit(x, y, features_mask=ones)   # sequential path
    for la, lb in zip(jax.tree.leaves(a.params),
                      jax.tree.leaves(b.params)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=2e-5, atol=2e-6)
