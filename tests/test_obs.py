"""Telemetry spine (obs/): span tracing, metrics registry, worker
health — including the PR acceptance criteria: a 10-step fit under
tracing yields Chrome-trace JSONL whose spans cover >= 95% of wall
time with ETL/step/sync attribution; /metrics exposes step-latency
histograms plus sentry retrace counters in valid Prometheus text; and
tracing disabled records ZERO events on the step path with an
off-path cost far under 1% of a bench-class step.
"""
import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.data.iterators import AsyncDataSetIterator
from deeplearning4j_tpu.nn import MultiLayerNetwork, \
    NeuralNetConfiguration
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.obs import health, metrics, trace


def _net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(upd.Adam(learning_rate=0.01))
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _batches(n=10, b=8, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.standard_normal((b, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
        out.append(DataSet(x, y))
    return out


@pytest.fixture(autouse=True)
def _tracer_off_after():
    yield
    trace.reset()


# --- tracer -----------------------------------------------------------------

def test_span_nesting_roundtrips_through_jsonl(tmp_path):
    path = tmp_path / "t.jsonl"
    trace.enable(str(path))
    trace.set_thread_name("main-test")
    with obs.span("outer", {"k": 1}):
        with obs.span("inner"):
            pass
    t0 = obs.now()
    trace.add_span("explicit", t0, t0 + 0.5)    # explicit t0/t1 API
    trace.instant("marker")
    trace.disable()
    evs = trace.read_trace(str(path))
    by_name = {e["name"]: e for e in evs}
    # thread metadata carries the worker label
    assert by_name["thread_name"]["args"]["name"] == "main-test"
    outer, inner = by_name["outer"], by_name["inner"]
    assert outer["ph"] == inner["ph"] == "X"
    assert outer["tid"] == inner["tid"]
    # nesting: inner's interval contained in outer's (how Chrome/
    # Perfetto nest spans of one tid)
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1
    assert outer["args"] == {"k": 1}
    assert by_name["explicit"]["dur"] == pytest.approx(5e5, rel=1e-3)
    assert by_name["marker"]["ph"] == "i"
    # the file itself is Chrome "JSON array format": starts with [
    assert path.read_text().startswith("[\n")


def test_ring_buffer_bounds_memory(tmp_path):
    trace.enable(str(tmp_path / "r.jsonl"), ring=8)
    t0 = obs.now()
    for i in range(50):
        trace.add_span(f"s{i}", t0, t0 + 1e-6)
    assert len(trace.events()) <= 8
    assert trace.events_recorded() == 50
    # the FILE keeps everything the ring dropped
    trace.disable()
    assert sum(e.get("ph") == "X"
               for e in trace.read_trace(trace.trace_path())) == 50


def test_tracing_disabled_records_nothing_on_step_path():
    trace.reset()
    base = trace.events_recorded()
    net = _net()
    net.fit(ListDataSetIterator(_batches(3)))
    with obs.span("should-not-record"):
        pass
    t0 = obs.now()
    trace.add_span("also-not", t0, t0)
    # zero events allocated/recorded while disabled — the counter is
    # the zero-allocation guard the step path is held to
    assert trace.events_recorded() == base == 0
    assert trace.events() == []


def test_off_path_overhead_under_one_percent_of_bench_step():
    # bench.py computes this against the measured ResNet step; here the
    # same probe is held to <1% of a conservative 5 ms step (the real
    # bench step is far larger)
    # min of 3 probes: the measurement itself is µs-scale and a busy
    # box can inflate any single run
    rep = min((obs.overhead_report(step_seconds=0.005, iters=500)
               for _ in range(3)),
              key=lambda r: r["off_path_cost_us"])
    assert rep["tracing"] is False
    assert rep["off_path_cost_us"] < 50.0
    assert rep["overhead_pct_of_step"] < 1.0
    # the probe scrubs its synthetic samples from the live registry
    assert "obs_overhead_probe" not in metrics.step_summary()
    assert "obs_overhead_probe" not in str(
        metrics.STEPS.snapshot())


# --- the acceptance fit: 10 steps, traced -----------------------------------

def _coverage(spans):
    """Union coverage of [ts, ts+dur) over traced wall time."""
    spans = sorted(spans, key=lambda e: (e["ts"], -e["dur"]))
    wall = (max(e["ts"] + e["dur"] for e in spans)
            - min(e["ts"] for e in spans))
    covered = end = 0.0
    for e in spans:
        s, d = e["ts"], e["dur"]
        if s + d <= end:
            continue
        covered += (s + d) - max(s, end)
        end = s + d
    return covered / wall


def test_ten_step_fit_trace_covers_wall_time(tmp_path):
    path = tmp_path / "fit.jsonl"
    trace.enable(str(path))
    from deeplearning4j_tpu.train.listeners import ScoreIterationListener
    net = _net()
    net.set_listeners(ScoreIterationListener(5))
    net.fit(ListDataSetIterator(_batches(10)))
    trace.disable()
    evs = [e for e in trace.read_trace(str(path)) if e.get("ph") == "X"]
    names = {e["name"] for e in evs}
    # ETL / step / sync attribution present
    assert "MultiLayerNetwork.fit/etl" in names
    assert "MultiLayerNetwork.fit/step" in names
    assert "MultiLayerNetwork.fit/sync" in names
    assert "MultiLayerNetwork.fit/h2d" in names
    assert "MultiLayerNetwork.fit/dispatch" in names
    steps = [e for e in evs if e["name"] == "MultiLayerNetwork.fit/step"]
    assert len(steps) == 10
    # phases nest inside their step span
    syncs = sorted((e for e in evs
                    if e["name"] == "MultiLayerNetwork.fit/sync"),
                   key=lambda e: e["ts"])
    st = sorted(steps, key=lambda e: e["ts"])
    for s, sy in zip(st, syncs):
        assert s["ts"] <= sy["ts"] + 1e-3
        assert sy["ts"] + sy["dur"] <= s["ts"] + s["dur"] + 1e-3
    # >= 95% of traced wall time attributed (acceptance criterion)
    top = [e for e in evs if e["name"] in (
        "MultiLayerNetwork.fit/step", "MultiLayerNetwork.fit/etl",
        "MultiLayerNetwork.fit/listeners")]
    assert _coverage(top) >= 0.95


def test_env_gated_trace_end_to_end(tmp_path):
    """The acceptance path verbatim: a 10-step MultiLayerNetwork.fit
    in a fresh process with DL4J_TPU_TRACE set produces Chrome-trace
    JSONL covering >= 95% of wall time, and the same process's
    /metrics exposition carries the step histogram + retrace
    counters."""
    import os
    import subprocess
    import sys
    path = tmp_path / "env.jsonl"
    prog = """
import numpy as np
from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
conf = (NeuralNetConfiguration.builder().seed(7)
        .updater(upd.Adam(learning_rate=0.01)).list()
        .layer(DenseLayer(n_out=8, activation="relu"))
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(4)).build())
net = MultiLayerNetwork(conf).init()
rng = np.random.default_rng(0)
data = [DataSet(rng.standard_normal((8, 4)).astype(np.float32),
                np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)])
        for _ in range(10)]
net.fit(ListDataSetIterator(data))
from deeplearning4j_tpu.obs import metrics, trace
trace.flush()
print(metrics.REGISTRY.exposition())
"""
    env = dict(os.environ, DL4J_TPU_TRACE=str(path),
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", prog], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    # the child's /metrics content: step histogram + sentry counters
    fams = metrics.parse_exposition(
        "\n".join(ln for ln in r.stdout.splitlines()
                  if ln.startswith(("#", "dl4j_tpu_"))))
    entry = (("entry", "MultiLayerNetwork.fit"),)
    assert fams[("dl4j_tpu_step_latency_seconds_count", entry)] == 10
    assert fams[("dl4j_tpu_retrace_traces_total",
                 (("function", "MultiLayerNetwork.train_step"),))] >= 1
    # the trace file covers >= 95% of its wall time with attribution
    evs = [e for e in trace.read_trace(str(path))
           if e.get("ph") == "X"]
    top = [e for e in evs if e["name"] in (
        "MultiLayerNetwork.fit/step", "MultiLayerNetwork.fit/etl")]
    assert sum(e["name"].endswith("/step") for e in top) == 10
    assert {e["name"] for e in evs} >= {
        "MultiLayerNetwork.fit/etl", "MultiLayerNetwork.fit/step",
        "MultiLayerNetwork.fit/h2d", "MultiLayerNetwork.fit/dispatch",
        "MultiLayerNetwork.fit/sync"}
    assert _coverage(top) >= 0.95


def test_xprof_summary_reads_obs_trace(tmp_path):
    path = tmp_path / "fit.jsonl"
    trace.enable(str(path))
    net = _net()
    net.fit(ListDataSetIterator(_batches(4)))
    trace.disable()
    import sys
    sys.path.insert(0, "tools")
    import xprof_summary
    out = xprof_summary.summarize_obs(str(path))
    assert "MultiLayerNetwork.fit/step" in out
    assert "covered by spans" in out.splitlines()[1]


# --- metrics registry + exposition ------------------------------------------

def test_metrics_exposition_is_valid_prometheus_text():
    net = _net()
    net.fit(ListDataSetIterator(_batches(3)))
    text = metrics.REGISTRY.exposition()
    # parse_exposition raises on any malformed sample line
    fams = metrics.parse_exposition(text)
    # step-latency histogram for the fit entry point
    entry = (("entry", "MultiLayerNetwork.fit"),)
    inf_key = ("dl4j_tpu_step_latency_seconds_bucket",
               (("entry", "MultiLayerNetwork.fit"), ("le", "+Inf")))
    assert inf_key in fams
    count = fams[("dl4j_tpu_step_latency_seconds_count", entry)]
    assert fams[inf_key] == count >= 3
    assert fams[("dl4j_tpu_step_latency_seconds_sum", entry)] > 0
    # histogram buckets are cumulative (monotone nondecreasing in le)
    buckets = sorted(
        ((float("inf") if dict(k[1])["le"] == "+Inf"
          else float(dict(k[1])["le"])), v)
        for k, v in fams.items()
        if k[0] == "dl4j_tpu_step_latency_seconds_bucket"
        and dict(k[1]).get("entry") == "MultiLayerNetwork.fit")
    assert all(a[1] <= b[1] for a, b in zip(buckets, buckets[1:]))
    # sentry retrace + compile-cache families are first-class
    assert ("dl4j_tpu_retrace_traces_total",
            (("function", "MultiLayerNetwork.train_step"),)) in fams
    assert any(k[0] == "dl4j_tpu_compile_cache_requests_total"
               for k in fams)
    assert any(k[0] == "dl4j_tpu_compile_time_seconds_total"
               for k in fams)
    # TYPE lines present for the histogram family
    assert "# TYPE dl4j_tpu_step_latency_seconds histogram" in text


def test_metrics_server_and_healthz_endpoint():
    health.reset()
    srv = metrics.MetricsServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/metrics") as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            metrics.parse_exposition(r.read().decode())
        with urllib.request.urlopen(base + "/healthz") as r:
            h = json.loads(r.read().decode())
        assert h["status"] == "ok" and h["stale_workers"] == []
        # a deliberately-stalled worker flips /healthz to 503
        health.heartbeat("w-stalled", t=obs.now() - 1e4)
        health.heartbeat("w-live")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/healthz")
        assert ei.value.code == 503
        body = json.loads(ei.value.read().decode())
        assert body["stale_workers"] == ["w-stalled"]
    finally:
        srv.stop()
        health.reset()


def test_registry_reset_keeps_standing_family_handles():
    reg = metrics.MetricsRegistry()
    fam = reg.histogram("t_steps", "probe", ("entry",))
    fam.labels(entry="a").observe(0.1)
    reg.reset()
    assert "t_steps" in reg.exposition()     # family survives reset
    fam.labels(entry="a").observe(0.2)       # old handle still works
    assert '{entry="a"}' in str(reg.snapshot()["t_steps"]["values"])
    assert reg.snapshot()["t_steps"]["values"]['{entry="a"}'][
        "count"] == 1                        # pre-reset sample gone


def test_heartbeat_retire_clears_finished_worker():
    health.reset()
    health.heartbeat("done-worker", t=obs.now() - 1e4)
    assert health.stale_workers(stale_after=30) == ["done-worker"]
    health.retire("done-worker")             # normal loop completion
    assert health.check() == {}              # no permanent false alarm
    health.retire("never-registered")        # idempotent


def test_tpu_watch_captures_healthz_503_body(tmp_path, monkeypatch):
    import sys
    sys.path.insert(0, "tools")
    import tpu_watch
    monkeypatch.setattr(tpu_watch, "LOG", tmp_path / "log.jsonl")
    health.reset()
    health.heartbeat("w-stuck", t=obs.now() - 1e4)
    srv = metrics.MetricsServer(port=0).start()
    try:
        tpu_watch._scrape_telemetry(
            None, f"http://127.0.0.1:{srv.port}/healthz", None)
    finally:
        srv.stop()
        health.reset()
    recs = [json.loads(ln) for ln in
            (tmp_path / "log.jsonl").read_text().splitlines()]
    (rec,) = [r for r in recs if r["event"] == "healthz"]
    # the 503 body — naming the stale worker — must be captured, not
    # swallowed as an HTTPError
    assert rec["status"] == 503
    assert rec["body"]["stale_workers"] == ["w-stuck"]


def test_tpu_watch_trace_tail_is_incremental(tmp_path, monkeypatch):
    import sys
    sys.path.insert(0, "tools")
    import tpu_watch
    monkeypatch.setattr(tpu_watch, "LOG", tmp_path / "log.jsonl")
    tpu_watch._TRACE_POS.clear()
    tpu_watch._SPAN_TOTALS.clear()
    path = tmp_path / "t.jsonl"
    trace.enable(str(path))
    t0 = obs.now()
    trace.add_span("a", t0, t0 + 0.001)
    trace.flush()
    tpu_watch._scrape_telemetry(None, None, str(path))
    off1, _ = tpu_watch._TRACE_POS[str(path)]
    trace.add_span("a", t0, t0 + 0.002)
    trace.flush()
    tpu_watch._scrape_telemetry(None, None, str(path))
    off2, _ = tpu_watch._TRACE_POS[str(path)]
    trace.disable()
    assert off2 > off1 > 0                    # only the tail is re-read
    assert tpu_watch._SPAN_TOTALS["a"] == pytest.approx(3000, rel=0.01)
    recs = [json.loads(ln) for ln in
            (tmp_path / "log.jsonl").read_text().splitlines()]
    assert recs[-1]["top_spans_ms"]["a"] == pytest.approx(3.0,
                                                          rel=0.01)


def test_stale_worker_detector_explicit_clock():
    health.reset()
    now = obs.now()
    health.heartbeat("a", t=now - 5)
    health.heartbeat("b", t=now - 100)
    chk = health.check(stale_after=30, now=now)
    assert not chk["a"]["stale"] and chk["b"]["stale"]
    assert health.stale_workers(stale_after=30, now=now) == ["b"]
    assert chk["b"]["age_s"] == pytest.approx(100, abs=1)
    health.reset()


# --- instrumented subsystems ------------------------------------------------

def test_worker_step_recording_and_heartbeat(tmp_path):
    """record_worker_step (the ParallelWrapper.fit per-step call):
    latency histogram + collective-sync counter + heartbeat + spans."""
    health.reset()
    trace.enable(str(tmp_path / "w.jsonl"))
    before = metrics.WORKER_STEP.labels(worker="procX").count
    t0 = obs.now()
    obs.record_worker_step("procX", t0, t0 + 0.001, t0 + 0.002,
                           t0 + 0.010)
    trace.disable()
    assert metrics.WORKER_STEP.labels(worker="procX").count \
        == before + 1
    assert metrics.WORKER_SYNC.labels(worker="procX").value > 0
    assert not health.check(stale_after=30)["procX"]["stale"]
    names = {e["name"] for e in trace.events()}
    assert "ParallelWrapper.fit/step" in names
    assert "ParallelWrapper.fit/collective_sync" in names
    health.reset()


def test_parallel_wrapper_heartbeat_flags_stalled_worker():
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    try:
        from deeplearning4j_tpu.parallel import ParallelWrapper
    except ImportError:
        # this jaxlib lacks jax.shard_map: the parallel subsystem is
        # unimportable here (pre-existing, see tests/test_parallel.py)
        pytest.skip("parallel subsystem unimportable on this jax")
    health.reset()
    net = _net()
    w = ParallelWrapper.builder(net).workers(8).build()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    before = metrics.WORKER_STEP.labels(worker="proc0").count
    w.fit(ListDataSetIterator(DataSet(x, y), batch_size=16), epochs=1)
    # the fit loop heart-beat once per step and timed every worker step
    assert metrics.WORKER_STEP.labels(worker="proc0").count \
        - before >= 4
    # normal completion RETIRES the beat (PR 2 review fix: a finished
    # fit must not read as a permanently stale worker in a
    # train-then-serve process); only a crashed loop leaves one behind
    chk = health.check(stale_after=30)
    assert "proc0" not in chk
    # a worker that stops beating (stalled collective) gets flagged
    health.heartbeat("proc1", t=obs.now() - 1e3)
    assert health.stale_workers(stale_after=30) == ["proc1"]
    health.reset()


def test_async_iterator_feeds_etl_metrics():
    before = metrics.PREFETCH_WAIT._children[()].value
    it = AsyncDataSetIterator(ListDataSetIterator(_batches(5)),
                              queue_size=2)
    n = sum(1 for _ in it)
    assert n == 5
    assert it.etl_wait_seconds > 0
    assert metrics.PREFETCH_WAIT._children[()].value > before


def test_parallel_inference_queue_and_latency_metrics():
    try:
        from deeplearning4j_tpu.parallel.inference import \
            ParallelInference
    except ImportError:
        # parallel package __init__ needs jax.shard_map (pre-existing
        # import failure on this jaxlib, see tests/test_parallel.py)
        pytest.skip("parallel subsystem unimportable on this jax")
    net = _net()
    reqs0 = metrics.INFER_REQS._children[()].value
    lat0 = metrics.INFER_LATENCY._children[()].count
    pi = ParallelInference(net, batch_limit=8, buckets=(1, 2, 4, 8))
    try:
        out = pi.output(np.zeros((2, 4), np.float32))
        assert out.shape == (2, 2)
    finally:
        pi.shutdown()
    assert metrics.INFER_REQS._children[()].value == reqs0 + 1
    assert metrics.INFER_LATENCY._children[()].count == lat0 + 1
    assert metrics.INFER_BATCH._children[()].count >= 1


# --- merged report + consumers ----------------------------------------------

def test_report_merges_trace_metrics_health(tmp_path):
    trace.enable(str(tmp_path / "r.jsonl"))
    t0 = obs.now()
    trace.add_span("probe", t0, t0 + 0.001)
    rep = obs.report(spans=5)
    assert rep["trace"]["enabled"] is True
    assert rep["trace"]["events_recorded"] >= 1
    assert any(e.get("name") == "probe" for e in rep["spans"])
    assert "dl4j_tpu_step_latency_seconds" in rep["metrics"]
    assert isinstance(rep["health"], dict)
    json.dumps(rep)            # snapshot must be JSON-serializable


def test_crash_dump_carries_compile_and_obs_state():
    from deeplearning4j_tpu.utils import crashreport
    net = _net()
    report = crashreport.generate_memory_status_report(net)
    assert "compile subsystem (perf.compile_report)" in report
    assert "telemetry (obs.report" in report
    assert "compile_time_s" in report
    assert "dl4j_tpu_step_latency_seconds" in report


def test_stats_listener_records_obs_summary():
    from deeplearning4j_tpu.train.stats import (InMemoryStatsStorage,
                                                StatsListener)
    storage = InMemoryStatsStorage()
    net = _net()
    net.set_listeners(StatsListener(storage, frequency=1,
                                    session_id="obs_test"))
    net.fit(ListDataSetIterator(_batches(3)))
    recs = storage.get_records("obs_test")
    assert recs
    ob = recs[-1]["obs"]
    assert ob["tracing"] is False
    assert "MultiLayerNetwork.fit" in ob["step"]
    assert ob["step"]["MultiLayerNetwork.fit"]["count"] >= 3


def test_score_listener_logs_step_loss_not_extra_score():
    from deeplearning4j_tpu.train.listeners import (
        CollectScoresListener, ScoreIterationListener)

    class FakeNet:
        score_ = 0.125

        def score(self, dataset=None):
            raise AssertionError(
                "listener must not call net.score() per iteration "
                "(extra device sync)")

    net = FakeNet()
    ScoreIterationListener(1).iteration_done(net, 10, 0)
    c = CollectScoresListener()
    c.iteration_done(net, 1, 0)
    assert c.scores == [(1, 0.125)]
