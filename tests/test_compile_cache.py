"""Compile-subsystem tests (perf/: persistent XLA cache, AOT warmup,
retrace sentry).

Contracts under test: after ``warmup()`` the first real train step and
first serving request on every declared bucket execute with ZERO new
traces (the sentry's counter is the assertion anchor); the sentry
triggers at budget+1 distinct unplanned shapes (raises under strict,
warns otherwise); the persistent cache dir is populated by one process
and honored by a fresh one; and a tiny fit runs clean under
``sentry.strict()`` — the tier-1 fence that makes any future
retrace-storm regression fail loudly.
"""
import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.perf import (RetraceBudgetExceeded, WarmupSpec,
                                     compile_cache, sentry, warmup_plan)

REPO = Path(__file__).resolve().parents[1]

X4 = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
Y4 = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], np.float32)


def _mlp(n_in=2, n_out=2, seed=42):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(upd.Adam(learning_rate=0.05))
            .weight_init_fn("xavier").list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=n_out, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


# -- AOT warmup -------------------------------------------------------------

def test_warmup_then_fit_and_serve_zero_new_traces():
    net = _mlp()
    sentry.reset()
    report = net.warmup([WarmupSpec(features=(4, 2), labels=(4, 2))])
    assert report["compiled"] == 2          # train step + output fn
    assert report["seconds"] > 0
    before = sentry.total_traces()
    net.fit(X4, Y4)
    net.output(X4)
    assert sentry.total_traces() == before, \
        "fit/serve on a warmed bucket must not trace"
    # trace-free is necessary but not sufficient (jax's AOT path does
    # not feed jit's dispatch cache): the calls must have been SERVED
    # by the stored warmed executables, i.e. XLA compiled nothing
    snap = sentry.stats()
    assert snap["MultiLayerNetwork.train_step"]["aot_hits"] == 1
    assert snap["MultiLayerNetwork.output"]["aot_hits"] == 1
    assert snap["MultiLayerNetwork.train_step"]["compiles"] == 0


def test_warmup_idempotent_and_declares_planned():
    net = _mlp()
    # stats() merges by name across every net this pytest session made;
    # zero the ledger so the assertion sees only THIS net's warmup
    sentry.reset()
    spec = WarmupSpec(features=(4, 2), labels=(4, 2))
    net.warmup([spec])
    again = net.warmup([spec])
    assert again["compiled"] == 0           # already compiled
    snap = sentry.stats()["MultiLayerNetwork.train_step"]
    assert snap["planned_shapes"] >= 1
    assert snap["unplanned_shapes"] == 0


def test_warmup_every_declared_bucket_before_first_batch():
    """Multiple batch buckets warmed up front: a subsequent pass over
    EVERY bucket (the bucketed-iterator traffic pattern) is trace-free.
    """
    net = _mlp()
    specs = warmup_plan([2, 4, 8], feature_dims=(2,), label_dims=(2,))
    assert [s.features for s in specs] == [(2, 2), (4, 2), (8, 2)]
    net.warmup(specs)
    before = sentry.total_traces()
    for b in (2, 4, 8):
        net.fit(X4[:b] if b <= 4 else np.tile(X4, (2, 1)),
                Y4[:b] if b <= 4 else np.tile(Y4, (2, 1)))
        net.output(np.zeros((b, 2), np.float32))
    assert sentry.total_traces() == before


def test_graph_warmup_zero_new_traces():
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(upd.Sgd(learning_rate=0.1))
            .graph_builder().add_inputs("in")
            .add_layer("d", DenseLayer(n_out=6, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "d")
            .set_outputs("out")
            .set_input_types(**{"in": InputType.feed_forward(5)})
            .build())
    net = ComputationGraph(conf).init()
    net.warmup([WarmupSpec(features=(4, 5), labels=(4, 3))])
    before = sentry.total_traces()
    x = np.random.default_rng(0).random((4, 5), np.float32)
    y = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
    net.fit(x, y)
    net.output(x)
    assert sentry.total_traces() == before


def test_parallel_inference_warmup_covers_all_buckets():
    try:
        from deeplearning4j_tpu.parallel.inference import \
            ParallelInference
    except ImportError as e:                # old-jax container
        pytest.skip(f"parallel package unavailable: {e}")
    net = _mlp(n_in=3)
    pi = ParallelInference(net, buckets=(2, 4))
    try:
        report = pi.warmup(feature_shape=(3,))
        assert report["compiled"] == 2      # one forward per bucket
        before = sentry.total_traces()
        out = pi.output(np.ones((3, 3), np.float32))   # pads to 4
        assert np.asarray(out).shape == (3, 2)
        assert sentry.total_traces() == before, \
            "first serving request on a warmed bucket must not trace"
    finally:
        pi.shutdown()


def test_gpt_decode_warmup_zero_new_traces():
    from deeplearning4j_tpu.zoo import GPTNano
    model = GPTNano(vocab_size=64, max_len=64)
    net = model.init(seq_len=32)
    report = model.warmup_decode(net, n_new=4, batch_sizes=(2,),
                                 prompt_lens=(10,))
    assert report["compiled"] == 1          # one (batch, bucket) pair
    before = sentry.total_traces()
    out = model.generate(net, np.ones((2, 10), np.int32), n_new=4)
    assert out.shape == (2, 14)
    assert sentry.total_traces() == before
    decode = sentry.stats()["CausalTransformerLM.decode"]
    assert decode["aot_hits"] >= 1          # served by the warmed exe


def test_warmup_requires_initialized_network():
    from deeplearning4j_tpu.perf.warmup import warmup_network

    class Empty:
        params = None
    with pytest.raises(RuntimeError, match="init"):
        warmup_network(Empty(), [])


# -- retrace sentry ---------------------------------------------------------

def test_sentry_triggers_at_budget_plus_one():
    import jax.numpy as jnp
    fn = sentry.jit(lambda x: x + 1, name="_test_budget", budget=2)
    with sentry.strict():
        fn(jnp.zeros(1))
        fn(jnp.zeros(2))                    # 2 distinct: at budget, ok
        with pytest.raises(RetraceBudgetExceeded):
            fn(jnp.zeros(3))                # budget+1 → storm


def test_sentry_warns_without_strict(caplog):
    import jax.numpy as jnp
    fn = sentry.jit(lambda x: x * 2, name="_test_warn", budget=1)
    fn(jnp.zeros(1))
    with caplog.at_level("WARNING", logger="deeplearning4j_tpu.perf"):
        fn(jnp.zeros(2))
    assert any("retrace storm" in r.message for r in caplog.records)


def test_warmed_shapes_never_count_against_budget():
    import jax
    import jax.numpy as jnp
    fn = sentry.jit(lambda x: x - 1, name="_test_planned", budget=1)
    with sentry.strict():
        # 4 planned buckets on a budget of 1: warmup declares them,
        # so neither the warmup itself nor the live calls trip
        for n in (1, 2, 3, 4):
            fn.warmup(jax.ShapeDtypeStruct((n,), jnp.float32))
        for n in (1, 2, 3, 4):
            fn(jnp.zeros(n))


def test_registry_releases_dead_networks():
    """The sentry ledger must not leak: a collected network's
    FunctionStats leave the registry (weakrefs), so long-running
    processes that construct models repeatedly stay bounded."""
    import gc
    from deeplearning4j_tpu.perf.sentry import _LOCK, _live_stats

    def make():
        net = _mlp(seed=11)
        net.fit(X4, Y4)
        net.output(X4)

    gc.collect()                 # clear earlier tests' cyclic garbage
    with _LOCK:
        n0 = len(_live_stats())
    make()
    gc.collect()
    with _LOCK:
        n1 = len(_live_stats())
    assert n1 == n0, "dead network's sentry ledgers were not released"


def test_strict_budget_override():
    import jax.numpy as jnp
    fn = sentry.jit(lambda x: x, name="_test_override")   # global budget
    with sentry.strict(budget=1):
        fn(jnp.zeros(5))
        with pytest.raises(RetraceBudgetExceeded):
            fn(jnp.zeros(6))


def test_tiny_fit_under_strict_sentry():
    """CI fence (tier-1, not slow): a tiny uniform-shape fit + serve
    must run clean under ``sentry.strict()``. A future PR that lets an
    unbucketed shape slip into a hot path fails HERE, loudly, instead
    of degrading TPU throughput silently."""
    net = _mlp(seed=3)
    it = [(X4, Y4)] * 3
    with sentry.strict(budget=8):
        net.fit(iter(it))
        net.output(X4)


# -- persistent compile cache -----------------------------------------------

def test_cache_stats_shape():
    stats = compile_cache.cache_stats()
    assert {"dir", "enabled", "entries", "bytes", "compile_requests",
            "persistent_hits", "persistent_misses"} <= stats.keys()


def test_default_cache_gated_off_on_cpu(monkeypatch):
    """Without the explicit env var, a CPU-pinned process (this one —
    conftest forces JAX_PLATFORMS=cpu) must NOT get the default cache
    dir: jaxlib 0.4.x can segfault deserializing XLA:CPU executables."""
    monkeypatch.delenv("DL4J_TPU_COMPILE_CACHE", raising=False)
    assert compile_cache.configure() is None
    # explicit opt-in still wins on CPU
    monkeypatch.setenv("DL4J_TPU_COMPILE_CACHE", "off")
    assert compile_cache.configure() is None
    compile_cache.configure_from_env()


def test_configure_disable_values(tmp_path):
    for off in ("", "0", "off", "none"):
        assert compile_cache.configure(cache_dir=off) is None
    active = compile_cache.configure(cache_dir=str(tmp_path / "cc"))
    assert active == str(tmp_path / "cc") and os.path.isdir(active)
    # restore the ambient env-configured state for later tests
    compile_cache.configure_from_env()


_CACHE_CHILD = r"""
import json, sys
import numpy as np
from deeplearning4j_tpu.perf import compile_cache
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd

conf = (NeuralNetConfiguration.builder().seed(42)
        .updater(upd.Adam(learning_rate=0.05))
        .weight_init_fn("xavier").list()
        .layer(DenseLayer(n_out=8, activation="tanh"))
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(2)).build())
net = MultiLayerNetwork(conf).init()
x = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
y = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], np.float32)
net.fit(x, y)
print(json.dumps(compile_cache.cache_stats()))
"""


@pytest.mark.slow
def test_cache_populated_and_honored_across_processes(tmp_path):
    """Process 1 fills DL4J_TPU_COMPILE_CACHE; a FRESH process 2 running
    the identical workload compiles nothing XLA-side (every eligible
    compile request is a persistent hit)."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               DL4J_TPU_COMPILE_CACHE=str(tmp_path / "cache"))
    env.pop("XLA_FLAGS", None)

    def run():
        r = subprocess.run([sys.executable, "-c", _CACHE_CHILD],
                           cwd=REPO, env=env, timeout=420,
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr[-2000:]
        return json.loads(r.stdout.strip().splitlines()[-1])

    first = run()
    assert first["enabled"] and first["dir"] == str(tmp_path / "cache")
    assert first["entries"] > 0, first
    assert first["persistent_hits"] == 0
    second = run()
    assert second["persistent_hits"] > 0, second
    assert second["persistent_hits"] == second["compile_requests"], \
        second                               # every compile pre-paid
