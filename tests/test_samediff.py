"""Tests for the SameDiff-equivalent autodiff frontend.

Modeled on the reference test strategy (SURVEY.md §4): op forward
checks vs numpy, finite-difference gradient validation (reference
``OpValidation``/``GradCheckUtil``), end-to-end fit, save/load
round-trip (reference FlatBuffers serialization tests).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn import updaters as upd


def test_basic_arithmetic_eval():
    sd = SameDiff.create()
    a = sd.var("a", np.array([[1., 2.], [3., 4.]], np.float32))
    b = sd.constant("b", np.array([[10., 20.], [30., 40.]], np.float32))
    c = (a + b) * 2.0 - a / b
    out = c.eval()
    expect = (np.array([[1, 2], [3, 4.]]) + [[10, 20], [30, 40.]]) * 2 \
        - np.array([[1, 2], [3, 4.]]) / [[10, 20], [30, 40.]]
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_placeholder_and_matmul():
    sd = SameDiff.create()
    x = sd.placeholder("x", np.float32, -1, 3)
    w = sd.var("w", np.ones((3, 2), np.float32))
    y = x.mmul(w, name="y")
    xv = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = sd.output({"x": xv}, ["y"])["y"]
    np.testing.assert_allclose(out, xv @ np.ones((3, 2)), rtol=1e-6)


def test_namespaces_and_reductions():
    sd = SameDiff.create()
    x = sd.var("x", np.array([[1., -2.], [3., -4.]], np.float32))
    r = sd.nn.relu(x, name="r")
    s = sd.math.exp(x).sum(axis=1, name="s")
    outs = sd.output({}, ["r", "s"])
    np.testing.assert_allclose(outs["r"], np.maximum(
        [[1, -2], [3, -4.]], 0))
    np.testing.assert_allclose(
        outs["s"], np.exp([[1, -2], [3, -4.]]).sum(1), rtol=1e-5)


def test_gradients_match_finite_difference():
    sd = SameDiff.create()
    x = sd.var("x", np.array([0.5, -1.0, 2.0], np.float32))
    loss = sd.math.tanh(x).mul(x).sum(name="loss")
    sd.set_loss_variables("loss")
    g = sd.calculate_gradients({}, ["x"])["x"]

    xv = np.array([0.5, -1.0, 2.0], np.float64)
    eps = 1e-6

    def f(v):
        return float(np.sum(np.tanh(v) * v))
    fd = np.array([(f(xv + eps * np.eye(3)[i]) -
                    f(xv - eps * np.eye(3)[i])) / (2 * eps)
                   for i in range(3)])
    np.testing.assert_allclose(g, fd, rtol=1e-4, atol=1e-5)


def test_gradients_through_softmax_xent():
    sd = SameDiff.create()
    x = sd.placeholder("x", np.float32, -1, 4)
    lab = sd.placeholder("lab", np.float32, -1, 3)
    w = sd.var("w", 0.1 * np.ones((4, 3), np.float32))
    logits = x.mmul(w, name="logits")
    loss = sd.loss.softmax_cross_entropy(lab, logits, name="loss")
    sd.set_loss_variables("loss")
    rng = np.random.default_rng(0)
    feed = {"x": rng.normal(size=(5, 4)).astype(np.float32),
            "lab": np.eye(3, dtype=np.float32)[
                rng.integers(0, 3, 5)]}
    g = sd.calculate_gradients(feed, ["w"])["w"]
    assert g.shape == (4, 3)
    assert np.isfinite(g).all() and np.abs(g).max() > 0


def test_fit_linear_regression():
    rng = np.random.default_rng(42)
    X = rng.normal(size=(64, 3)).astype(np.float32)
    true_w = np.array([[1.5], [-2.0], [0.5]], np.float32)
    Y = X @ true_w

    sd = SameDiff.create()
    x = sd.placeholder("x", np.float32, -1, 3)
    y = sd.placeholder("y", np.float32, -1, 1)
    w = sd.var("w", np.zeros((3, 1), np.float32))
    pred = x.mmul(w, name="pred")
    sd.loss.mse(y, pred, name="loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(TrainingConfig(
        updater=upd.Adam(learning_rate=0.1),
        data_set_feature_mapping=["x"], data_set_label_mapping=["y"]))
    it = ListDataSetIterator(DataSet(X, Y), batch_size=64)
    losses = sd.fit(it, epochs=120)
    assert losses[-1] < 1e-2
    np.testing.assert_allclose(sd.get_variable("w").get_arr(),
                               true_w, atol=0.15)


def test_save_load_roundtrip(tmp_path):
    sd = SameDiff.create()
    x = sd.placeholder("x", np.float32, -1, 2)
    w = sd.var("w", np.array([[1., 2.], [3., 4.]], np.float32))
    out = sd.nn.softmax(x.mmul(w), name="out")
    xv = np.array([[1., 0.]], np.float32)
    before = sd.output({"x": xv}, ["out"])["out"]

    p = str(tmp_path / "model.sdz")
    sd.save(p)
    sd2 = SameDiff.load(p)
    after = sd2.output({"x": xv}, ["out"])["out"]
    np.testing.assert_allclose(before, after, rtol=1e-6)


def test_while_loop_control_flow():
    sd = SameDiff.create()
    i0 = sd.constant("i0", np.float32(0.0))
    acc0 = sd.constant("acc0", np.float32(1.0))
    i_out, acc_out = sd.while_loop(
        lambda i, acc: i < 5.0,
        lambda i, acc: (i + 1.0, acc * 2.0),
        [i0, acc0], name="loop")
    res = sd.output({}, [acc_out])[acc_out.name]
    assert float(res) == 32.0


def test_indexing_and_shape_ops():
    sd = SameDiff.create()
    x = sd.var("x", np.arange(12, dtype=np.float32).reshape(3, 4))
    row = x[1]
    col = x[:, 2:3]
    r = sd.output({}, [row, col])
    np.testing.assert_allclose(r[row.name], [4., 5., 6., 7.])
    np.testing.assert_allclose(r[col.name], [[2.], [6.], [10.]])


def test_eval_sugar_and_conv():
    sd = SameDiff.create()
    img = sd.placeholder("img", np.float32, -1, 8, 8, 1)
    k = sd.var("k", np.ones((3, 3, 1, 2), np.float32) / 9.0)
    y = sd.nn.conv2d(img, k, strides=(1, 1), padding="SAME", name="conv")
    p = sd.nn.max_pooling2d(y, kernel=(2, 2), strides=(2, 2), name="pool")
    out = sd.output({"img": np.ones((1, 8, 8, 1), np.float32)},
                    ["pool"])["pool"]
    assert out.shape == (1, 4, 4, 2)
    assert np.isfinite(out).all()


def test_default_loss_from_outputs():
    """Loss variables default to float terminal outputs (no explicit
    set_loss_variables), excluding int-derived terminals."""
    sd = SameDiff.create()
    x = sd.placeholder("x", jnp.float32, 2, 3)
    w = sd.var("w", np.ones((3, 2), np.float32))
    y = x.mmul(w, name="y")
    g = sd.calculate_gradients({"x": np.ones((2, 3), np.float32)}, ["w"])
    assert np.allclose(g["w"], 2.0)
    assert sd.outputs() == ["y"]


def test_default_loss_skips_int_chains():
    sd = SameDiff.create()
    a = sd.placeholder("a", jnp.float32, 4)
    b = sd.placeholder("b", jnp.float32, 4)
    w = sd.var("w", np.ones((4,), np.float32))
    # int-derived chain: sum(eq(...)) — must not be picked as a loss
    eq = sd._rec("eq", [a.mul(w), b])
    n_correct = eq.sum()
    import pytest
    with pytest.raises(ValueError, match="set_loss_variables"):
        sd.calculate_gradients(
            {"a": np.ones(4, np.float32), "b": np.ones(4, np.float32)},
            ["w"])


def test_namespace_views_cover_reference_families():
    """sd.cnn/sd.rnn/sd.image/sd.linalg/sd.bitwise (reference SDCNN,
    SDRNN, SDImage, SDLinalg, SDBitwise namespace classes)."""
    sd = SameDiff.create()
    x = sd.var("x", np.random.RandomState(0).randn(1, 8, 8, 2)
               .astype(np.float32))
    w = sd.var("w", np.random.RandomState(1).randn(3, 3, 2, 4)
               .astype(np.float32) * 0.1)
    y = sd.cnn.conv2d(x, w, padding="SAME", name="conv")
    p = sd.cnn.max_pooling2d(y, kernel=(2, 2), strides=(2, 2),
                             name="pool")
    out = sd.output({}, ["pool"])["pool"]
    assert out.shape == (1, 4, 4, 4)

    sd2 = SameDiff.create()
    m = sd2.var("m", np.random.RandomState(2).randn(3, 3)
                .astype(np.float32))
    sd2.linalg.matrix_inverse(m, name="inv")
    inv = sd2.output({}, ["inv"])["inv"]
    assert np.allclose(np.asarray(m.eval()) @ inv, np.eye(3),
                       atol=1e-4)

    sd3 = SameDiff.create()
    a = sd3.var("a", np.array([12, 10], np.int32))
    b = sd3.var("b", np.array([10, 3], np.int32))
    sd3.bitwise.bitwise_and(a, b, name="band")
    assert list(sd3.output({}, ["band"])["band"]) == [8, 2]
