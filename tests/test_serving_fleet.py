"""Elastic serving fleet (serving/fleet.py — ISSUE 18).

Unit-level fences under the chaos drill
(``tools/chaos.py --serving-fleet``, the slow-lane acceptance):

- **eligibility**: the router admits only to live (lease evidence)
  AND ready (warmup-complete) replicas — an expired lease or a
  published ``ready=False`` removes a replica within one aggregator
  read;
- **steering**: least-loaded placement uses published load PLUS the
  router's own in-flight accounting, so stale ties never pin the
  whole fleet onto the lexically first host;
- **loss discipline**: a transport failure re-routes; an impossible
  placement is a *structured* ``SequenceAborted`` bounded by the shed
  budget — never a hang, never a bare exception;
- **cold-start ordering**: ``ServingReplica.start`` warms every
  ``STARTUP_PREFETCH`` bucket (compile-store manifest consulted)
  BEFORE the first lease renewal, and ``/healthz`` answers 503 until
  the gateway is warm (the readiness gate satellite);
- **supervision**: the supervisor respawns to target without
  double-spawning a pending replica.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from deeplearning4j_tpu.obs import fleet as obs_fleet
from deeplearning4j_tpu.perf.compile_store import CompileStore
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.resilience.elastic import MembershipCoordinator
from deeplearning4j_tpu.serving import scheduler as serving_scheduler
from deeplearning4j_tpu.serving.fleet import (STARTUP_PREFETCH,
                                              FleetSupervisor,
                                              HttpTransport,
                                              ReplicaServer, RouterError,
                                              ServingReplica,
                                              ServingRouter)
from deeplearning4j_tpu.serving.gateway import (SequenceAborted,
                                                ServingGateway)
from deeplearning4j_tpu.zoo.gpt import CausalTransformerLM


# =========================================================================
# static contracts: fault sites, prefetch table
# =========================================================================

def test_fleet_fault_sites_registered():
    """The drill's kill switches exist: per-request "router" site,
    per-bring-up "replica_spawn" site, and the named "replica-crash"
    plan targeting the gateway's serving loop."""
    assert "router" in faults.KNOWN_SITES
    assert "replica_spawn" in faults.KNOWN_SITES
    assert faults.NAMED_PLANS["replica-crash"].startswith("serving:")
    assert "replica_spawn" in faults.NAMED_PLANS["spawn-crash"]


def test_startup_prefetch_mirrors_warmup_feeds():
    """Runtime half of lint rule 12: the fleet's prefetch table and
    the scheduler's WARMUP_FEEDS declare the same builder set."""
    assert sorted(STARTUP_PREFETCH) == \
        sorted(serving_scheduler.WARMUP_FEEDS)
    assert len(set(STARTUP_PREFETCH)) == len(STARTUP_PREFETCH)


# =========================================================================
# router: eligibility, steering, re-route, structured shed
# =========================================================================

class _ScriptedTransport:
    """Injectable wire: per-addr action (exception to raise, callable,
    or default success) + a call log."""

    def __init__(self, script=None):
        self.script = dict(script or {})
        self.calls = []

    def generate(self, addr, payload):
        self.calls.append(addr)
        action = self.script.get(addr)
        if isinstance(action, Exception):
            raise action
        if callable(action):
            return action(addr, payload)
        return {"tokens": [1, 2, 3], "rid": len(self.calls)}


class _Fleet:
    """A telemetry+lease plane under a tmp dir with a settable fake
    clock — publish replicas in any liveness/readiness state."""

    def __init__(self, root):
        self.root = root
        self.t = [1000.0]
        self.coords = {}

    def clock(self):
        return self.t[0]

    def publish(self, host, *, ready=True, lease=True,
                lease_secs=5.0, queue_depth=0, active=0):
        if lease and host not in self.coords:
            self.coords[host] = MembershipCoordinator(
                self.root, host, n_devices=1, lease_secs=lease_secs,
                clock=self.clock)
        if lease:
            self.coords[host].renew()
        tel = obs_fleet.FleetTelemetry(self.root, host, every_s=0.0,
                                       clock=self.clock)
        tel.update_serving(ready=ready, addr=f"127.0.0.1:{host}",
                           queue_depth=queue_depth, active=active)
        tel.publish(force=True)

    def router(self, transport, **kw):
        kw.setdefault("shed_budget", 8)
        kw.setdefault("retry_pause_s", 0.005)
        return ServingRouter(self.root, transport=transport,
                             clock=self.clock, **kw)


@pytest.fixture
def fleet(tmp_path):
    return _Fleet(tmp_path)


def test_router_admits_only_live_and_ready(fleet):
    fleet.publish("a", ready=True)
    fleet.publish("b", ready=False)           # warming: leased, not ready
    fleet.publish("c", ready=True, lease=False)   # no lease evidence
    tr = _ScriptedTransport()
    router = fleet.router(tr)
    assert sorted(router.replicas()) == ["a"]
    out = router.submit([1, 2], deadline_s=2.0)
    assert out["replica"] == "a"
    assert tr.calls == ["127.0.0.1:a"]


def test_router_drops_replica_whose_lease_expired(fleet):
    fleet.publish("a", ready=True, lease_secs=2.0)
    router = fleet.router(_ScriptedTransport())
    assert sorted(router.replicas()) == ["a"]
    fleet.t[0] += 3.5                          # lease window elapses
    assert router.replicas() == {}


def test_router_reroutes_on_transport_failure(fleet):
    fleet.publish("a", ready=True)
    fleet.publish("b", ready=True)
    tr = _ScriptedTransport(
        {"127.0.0.1:a": RouterError("replica a unreachable")})
    router = fleet.router(tr)
    out = router.submit([1], deadline_s=5.0)
    assert out["replica"] == "b"
    assert router.reroutes == 1 and router.sheds == 0
    assert tr.calls == ["127.0.0.1:a", "127.0.0.1:b"]


def test_router_inflight_accounting_breaks_stale_ties(fleet):
    """Published load refreshes once per replica tick; with two idle
    replicas every published tie would send ALL traffic to the
    lexically first host. The router's own in-flight count must steer
    the second concurrent request to the other replica."""
    fleet.publish("a", ready=True)
    fleet.publish("b", ready=True)
    placed = []
    router = None

    def outer(addr, payload):
        placed.append(addr)
        if len(placed) == 1:
            # while the first request is in flight on this host, a
            # second placement must pick the OTHER replica
            inner = router.submit([9], deadline_s=5.0)
            placed.append(("inner", inner["replica"]))
        return {"tokens": []}

    tr = _ScriptedTransport({"127.0.0.1:a": outer, "127.0.0.1:b": outer})
    router = fleet.router(tr)
    out = router.submit([1], deadline_s=5.0)
    assert out["replica"] == "a"               # published tie -> first
    assert placed[1] == "127.0.0.1:b"          # in-flight broke the tie
    assert placed[2] == ("inner", "b")
    # both slots drained afterwards
    assert router._inflight == {}


def test_router_sheds_structured_never_hangs(fleet):
    """No replica at all: submit returns within the deadline with a
    SequenceAborted (reason recorded), not a hang or a bare error."""
    router = fleet.router(_ScriptedTransport(), shed_budget=8)
    router.clock = time.time                   # real deadline math
    t0 = time.time()
    with pytest.raises(SequenceAborted) as e:
        router.submit([1], deadline_s=0.25)
    assert time.time() - t0 < 5.0
    assert "no live+ready replica" in str(e.value)
    assert router.sheds == 1


def test_router_shed_budget_marks_over_budget(fleet):
    router = fleet.router(_ScriptedTransport(), shed_budget=1)
    router.clock = time.time
    with pytest.raises(SequenceAborted):
        router.submit([1], deadline_s=0.05)
    with pytest.raises(SequenceAborted) as e:
        router.submit([1], deadline_s=0.05)
    assert "budget" in str(e.value)
    assert router.sheds == 2


def test_router_surfaces_replica_abort_without_retry(fleet):
    """A 409 from the replica is the structured-abort contract mid-
    stream — structural loss to surface, not a transport flake to
    retry (retrying would double-bill the shed budget's evidence)."""
    fleet.publish("a", ready=True)
    fleet.publish("b", ready=True)
    tr = _ScriptedTransport(
        {"127.0.0.1:a": SequenceAborted("replica died mid-decode",
                                        tokens=[4, 5])})
    router = fleet.router(tr)
    with pytest.raises(SequenceAborted) as e:
        router.submit([1], deadline_s=5.0)
    assert len(tr.calls) == 1                  # no blind retry
    assert router.sheds == 1 and router.reroutes == 0
    assert isinstance(e.value.cause, SequenceAborted)
    assert list(e.value.cause.tokens) == [4, 5]


def test_http_transport_maps_409_to_sequence_aborted():
    """The wire preserves the structured abort: tokens-so-far + cause
    cross the HTTP boundary intact; 5xx stays a re-routable
    RouterError."""
    import http.server

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            code = 409 if self.path == "/generate" else 500
            body = json.dumps({"error": "aborted", "message": "boom",
                               "tokens": [7, 8],
                               "cause": "Evicted"}).encode()
            self.send_response(code)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    addr = "127.0.0.1:%d" % httpd.server_address[1]
    tr = HttpTransport(timeout_s=5.0)
    try:
        with pytest.raises(SequenceAborted) as e:
            tr.generate(addr, {"prompt": [1]})
        assert list(e.value.tokens) == [7, 8]
        assert "boom" in str(e.value)
        with pytest.raises(RouterError):
            tr.generate("127.0.0.1:1", {"prompt": [1]})  # refused
    finally:
        httpd.shutdown()
        httpd.server_close()


# =========================================================================
# supervisor: respawn to target, pending is not double-spawned
# =========================================================================

class _FakeCoord:
    def __init__(self, live):
        self.live = list(live)
        self.expired = []

    def evict_expired(self, now=None):
        out, self.expired = self.expired, []
        return out

    def live_members(self, now=None):
        return sorted(self.live)


def test_supervisor_respawns_to_target_without_double_spawn():
    coord = _FakeCoord(["r0"])
    n = [0]

    def spawn():
        n[0] += 1
        return f"r{n[0]}"

    sup = FleetSupervisor(coord, spawn, target=3, clock=lambda: 0.0)
    out = sup.poll()
    assert out["spawned"] == ["r1", "r2"]
    # spawned-but-not-yet-leased replicas are pending, not respawned
    assert sup.poll()["spawned"] == []
    coord.live += ["r1", "r2"]                 # leases appear
    assert sup.poll() == {"evicted": [], "live": ["r0", "r1", "r2"],
                          "spawned": [], "pending": []}
    # an eviction re-opens exactly one slot
    coord.live.remove("r1")
    coord.expired = ["r1"]
    out = sup.poll()
    assert out["evicted"] == ["r1"] and out["spawned"] == ["r3"]


# =========================================================================
# replica lifecycle: readiness gate + warm-before-lease ordering
# =========================================================================

def _tiny_gateway():
    model = CausalTransformerLM(hidden=32, n_layers=2, n_heads=2,
                                n_kv_heads=1, max_len=64, seed=9,
                                vocab_size=64)
    return ServingGateway(model, model.init(), max_slots=2, block=8,
                          max_context=64)


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_healthz_gates_traffic_until_warm():
    """The readiness satellite: /healthz (and /generate) answer 503
    "warming" until warmup AOT-compiled every declared bucket — a
    cold replica never cold-traces on the request path."""
    gw = _tiny_gateway()
    srv = ReplicaServer(gw).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        code, body = _get(base + "/healthz")
        assert (code, body["status"]) == (503, "warming")
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps({"prompt": [1, 2, 3]}).encode(),
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 503
        gw.warmup(prompt_lens=(8,))
        code, body = _get(base + "/healthz")
        assert (code, body["status"]) == (200, "ok")
        stats = _get(base + "/stats")[1]
        assert stats["ready"] is True and stats["aot_hits"] >= 0
        assert stats["warm_buckets"]
        out = HttpTransport(timeout_s=30).generate(
            f"127.0.0.1:{srv.port}",
            {"prompt": [1, 2, 3], "max_new": 4})
        assert len(out["tokens"]) >= 4
    finally:
        srv.stop()
        gw.shutdown()


def test_replica_start_warms_before_lease(tmp_path):
    """Runtime half of lint rule 12's ordering clause: start() runs
    the startup prefetch and opens the HTTP front end BEFORE the first
    lease renewal, so a router can never see a lease on a cold
    replica. Also: the compile-store manifest misses cold and hits on
    the next same-fingerprint bring-up."""
    gw = _tiny_gateway()
    store = CompileStore(tmp_path / "store", jaxlib="t", topology="cpu")
    order = []
    coord = MembershipCoordinator(tmp_path / "fleet", "h0",
                                  n_devices=1, lease_secs=30.0)
    tel = obs_fleet.FleetTelemetry(tmp_path / "fleet", "h0",
                                   every_s=0.0)
    real_warm, real_renew = gw.warmup, coord.renew
    gw.warmup = lambda *a, **k: (order.append("warmup"),
                                 real_warm(*a, **k))[1]
    coord.renew = lambda: (order.append("renew"), real_renew())[1]
    rep = ServingReplica(gw, coord, tel, store=store)
    try:
        report = rep.start(prompt_lens=(8,))
        assert order == ["warmup", "renew"]
        assert report["manifest_hit"] is False
        assert gw.ready() and rep.server is not None
        # the published snapshot is immediately router-visible
        view = obs_fleet.aggregate(tmp_path / "fleet")
        row = view.serving_table()["h0"]
        assert row["ready"] and row["live"]
        assert row["addr"] == f"127.0.0.1:{rep.server.port}"
        tick = rep.tick()
        assert "h0" in tick["live"]
    finally:
        rep.stop()
    # same fingerprint, second bring-up: manifest hit (the fleet-store
    # half of zero-cold-start; the xla/ plane is proven in the drill)
    coord2 = MembershipCoordinator(tmp_path / "fleet", "h1",
                                   n_devices=1, lease_secs=30.0)
    tel2 = obs_fleet.FleetTelemetry(tmp_path / "fleet", "h1",
                                    every_s=0.0)
    gw2 = _tiny_gateway()
    rep2 = ServingReplica(gw2, coord2, tel2, store=store)
    try:
        assert rep2.start(prompt_lens=(8,))["manifest_hit"] is True
        assert store.counters()["hits"] >= 1
    finally:
        rep2.stop()
