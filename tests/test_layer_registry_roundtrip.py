"""Registry-wide layer serialization round-trip.

Reference analog: the Jackson JSON round-trip guarantee of every layer
config bean (MultiLayerConfiguration.toJson/fromJson is the model
format). Property checked for EVERY registered layer class: construct
→ to_dict → layer_from_dict → identical to_dict AND identical forward
outputs with the same init key. A layer missing from SPECS fails the
coverage gate, so new layers must register a case here.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.layers.base import (_LAYER_REGISTRY,
                                               layer_from_dict)
from deeplearning4j_tpu.nn import layers as L

KEY = jax.random.PRNGKey(3)

# class name -> (constructor kwargs, input_shape) | None = not directly
# round-trippable (callable fields documented to need re-attachment)
DENSE = dict(n_out=3)
SPECS = {
    "DenseLayer": (DENSE, (4,)),
    "OutputLayer": (dict(n_out=3, loss="mcxent"), (4,)),
    "LossLayer": (dict(loss="mse"), (4,)),
    "ActivationLayer": (dict(activation="tanh"), (4,)),
    "DropoutLayer": (dict(dropout=0.5), (4,)),
    "EmbeddingLayer": (dict(n_in=10, n_out=4), (1,)),
    "EmbeddingSequenceLayer": (dict(n_in=10, n_out=4), (5,)),
    "ElementWiseMultiplicationLayer": ({}, (4,)),
    "BatchNormalization": ({}, (6,)),
    "LayerNormalization": ({}, (6,)),
    "LocalResponseNormalization": ({}, (4, 4, 6)),
    "CnnLossLayer": (dict(loss="mse"), (4, 4, 2)),
    "Cnn3DLossLayer": (dict(loss="mse"), (2, 4, 4, 2)),
    "ConvolutionLayer": (dict(n_out=2, kernel_size=(2, 2)), (5, 5, 3)),
    "Convolution1DLayer": (dict(n_out=2, kernel_size=(2,)), (6, 3)),
    "Convolution3DLayer": (dict(n_out=2, kernel_size=(2, 2, 2)),
                           (4, 4, 4, 2)),
    "Deconvolution2DLayer": (dict(n_out=2, kernel_size=(2, 2),
                                  stride=(2, 2)), (4, 4, 3)),
    "Deconvolution3DLayer": (dict(n_out=2), (2, 2, 2, 3)),
    "DepthwiseConvolution2DLayer": (dict(kernel_size=(2, 2)), (4, 4, 3)),
    "SeparableConvolution2DLayer": (dict(n_out=4, kernel_size=(2, 2)),
                                    (4, 4, 3)),
    "SubsamplingLayer": (dict(kernel_size=(2, 2), stride=(2, 2)),
                         (4, 4, 2)),
    "Subsampling1DLayer": (dict(kernel_size=(2,), stride=(2,)), (6, 2)),
    "Subsampling3DLayer": (dict(kernel_size=(2, 2, 2),
                                stride=(2, 2, 2)), (4, 4, 4, 2)),
    "GlobalPoolingLayer": ({}, (4, 4, 2)),
    "Upsampling1DLayer": (dict(size=2), (4, 2)),
    "Upsampling2DLayer": (dict(size=(2, 2)), (3, 3, 2)),
    "Upsampling3DLayer": (dict(size=(2, 2, 2)), (2, 2, 2, 2)),
    "ZeroPaddingLayer": (dict(padding=(1, 1, 1, 1)), (3, 3, 2)),
    "ZeroPadding1DLayer": (dict(padding=(1, 1)), (4, 2)),
    "ZeroPadding3DLayer": ({}, (3, 3, 3, 2)),
    "CroppingLayer": (dict(cropping=(1, 1, 1, 1)), (5, 5, 2)),
    "Cropping1DLayer": (dict(cropping=(1, 1)), (6, 2)),
    "Cropping3DLayer": ({}, (4, 4, 4, 2)),
    "SpaceToDepthLayer": (dict(block_size=2), (4, 4, 2)),
    "DepthToSpaceLayer": (dict(block_size=2), (2, 2, 8)),
    "LSTM": (dict(n_out=4), (5, 3)),
    "ConvLSTM2D": (dict(n_out=3, kernel_size=(2, 2)), (4, 6, 6, 2)),
    "RMSNorm": ({}, (6,)),
    "TransformerDecoderBlock": (dict(n_heads=2, n_kv_heads=1), (5, 8)),
    "GravesLSTM": (dict(n_out=4), (5, 3)),
    "GravesBidirectionalLSTM": (dict(n_out=4), (5, 3)),
    "GRU": (dict(n_out=4), (5, 3)),
    "SimpleRnn": (dict(n_out=4), (5, 3)),
    "RnnOutputLayer": (dict(n_out=3, loss="mcxent"), (5, 4)),
    "RnnLossLayer": (dict(loss="mse"), (5, 4)),
    "SelfAttentionLayer": (dict(n_heads=2), (5, 4)),
    "LearnedSelfAttentionLayer": (dict(n_heads=2, n_queries=3), (5, 4)),
    "RecurrentAttentionLayer": (dict(n_out=4, n_heads=2), (5, 4)),
    "MultiHeadAttention": (dict(n_out=4, n_heads=2), (5, 4)),
    "TransformerEncoderBlock": (dict(n_heads=2, ffn_mult=2), (5, 4)),
    "PositionalEmbeddingLayer": ({}, (5, 4)),
    "ClsTokenPoolLayer": ({}, (5, 4)),
    "AutoEncoder": (dict(n_out=3), (6,)),
    "VariationalAutoencoder": (dict(n_out=3), (6,)),
    "CenterLossOutputLayer": (dict(n_out=3, loss="mcxent"), (4,)),
    "PReLULayer": ({}, (4,)),
    "CapsuleLayer": (dict(capsules=3, capsule_dim=4, routings=1),
                     (5, 6)),
    "PrimaryCapsules": (dict(capsule_dim=4, channels=2, kernel=(2, 2)),
                        (5, 5, 2)),
    "CapsuleStrengthLayer": ({}, (3, 4)),
    "OCNNOutputLayer": (dict(hidden_size=4), (5,)),
    "LocallyConnected1DLayer": (dict(n_out=2, kernel=2), (5, 2)),
    "LocallyConnected2DLayer": (dict(n_out=2, kernel=(2, 2)),
                                (4, 4, 2)),
    "MaskLayer": ({}, (4, 3)),
    "RepeatVector": (dict(n=3), (4,)),
    "GaussianNoiseLayer": (dict(stddev=0.1), (4,)),
    "GaussianDropoutLayer": (dict(rate=0.3), (4,)),
    "Yolo2OutputLayer": None,          # needs anchor boxes (ndarray field)
    "LambdaLayer": None,               # documented: fn re-attached
    "SameDiffLayer": None,             # documented: fn re-attached
    "SameDiffOutputLayer": None,
    "FrozenLayer": (dict(underlying=L.DenseLayer(**DENSE)), (4,)),
    "FrozenLayerWithBackprop": (dict(underlying=L.DenseLayer(**DENSE)),
                                (4,)),
    "Bidirectional": (dict(fwd=L.LSTM(n_out=3)), (5, 2)),
    "LastTimeStep": (dict(underlying=L.LSTM(n_out=3)), (5, 2)),
    "TimeDistributed": (dict(underlying=L.DenseLayer(**DENSE)), (5, 4)),
    "MaskZeroLayer": (dict(underlying=L.LSTM(n_out=3)), (5, 2)),
}


def test_every_registered_layer_has_spec():
    missing = sorted(set(_LAYER_REGISTRY) - set(SPECS))
    assert not missing, f"layers without round-trip spec: {missing}"


@pytest.mark.parametrize("name", sorted(n for n, s in SPECS.items()
                                        if s is not None))
def test_layer_roundtrip(name):
    kwargs, in_shape = SPECS[name]
    layer = _LAYER_REGISTRY[name](**kwargs)
    d = layer.to_dict()
    back = layer_from_dict(d)
    assert type(back) is type(layer)
    assert back.to_dict() == d, f"{name}: to_dict not a fixpoint"

    # identical forward with the same init key
    p1, s1, out1 = layer.init(KEY, in_shape)
    p2, s2, out2 = back.init(KEY, in_shape)
    assert tuple(out1) == tuple(out2)
    x = jax.random.normal(jax.random.PRNGKey(1), (2,) + in_shape)
    y1, _ = layer.apply(p1, s1, x)
    y2, _ = back.apply(p2, s2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-6, atol=1e-7,
                               err_msg=name)
