"""NDArray façade tests — reference analog: org.nd4j.linalg.Nd4jTestsC."""
import numpy as np
import pytest

from deeplearning4j_tpu import NDArray, Nd4j


def test_create_and_shape():
    a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
    assert a.shape == (2, 2)
    assert a.rank() == 2
    assert a.length() == 4
    assert a.is_matrix()


def test_zeros_ones_full():
    assert Nd4j.zeros((2, 3)).sum().item() == 0
    assert Nd4j.ones((2, 3)).sum().item() == 6
    assert Nd4j.full((2, 2), 7).mean().item() == 7


def test_arithmetic():
    a = Nd4j.create([1.0, 2.0, 3.0])
    b = Nd4j.create([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).numpy(), [5, 7, 9])
    np.testing.assert_allclose((a - b).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((a * b).numpy(), [4, 10, 18])
    np.testing.assert_allclose((b / a).numpy(), [4, 2.5, 2])
    np.testing.assert_allclose((a * 2 + 1).numpy(), [3, 5, 7])
    np.testing.assert_allclose((1 - a).numpy(), [0, -1, -2])


def test_inplace_spellings():
    a = Nd4j.create([1.0, 2.0])
    a.addi(1).muli(2)
    np.testing.assert_allclose(a.numpy(), [4, 6])


def test_mmul():
    a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
    b = Nd4j.eye(2)
    assert a.mmul(b).equals(a)
    c = a @ a
    np.testing.assert_allclose(c.numpy(), [[7, 10], [15, 22]])


def test_eq_elementwise_and_traced():
    import jax
    import jax.numpy as jnp
    a = Nd4j.create([1.0, 2.0, 3.0])
    b = Nd4j.create([1.0, 0.0, 3.0])
    np.testing.assert_array_equal((a == b).numpy(), [True, False, True])
    assert a.equals(a.dup()) and not a.equals(b)
    out = jax.jit(lambda x: Nd4j.where(x == x, x, x * 0))(a)
    np.testing.assert_allclose(out.numpy(), a.numpy())


def test_rand_advances_and_seeds():
    r1, r2 = Nd4j.rand((2, 2)), Nd4j.rand((2, 2))
    assert not r1.equals(r2)  # global stream advances
    s1, s2 = Nd4j.randn((2, 2), seed=7), Nd4j.randn((2, 2), seed=7)
    assert s1.equals(s2)
    Nd4j.set_random_seed(0)
    a = Nd4j.rand((2,))
    Nd4j.set_random_seed(0)
    assert a.equals(Nd4j.rand((2,)))


def test_put_with_ndarray_index():
    a = Nd4j.arange(5.0)
    idx = Nd4j.create([0, 2], dtype="int32")
    out = a.put(idx, 9.0)
    np.testing.assert_allclose(out.numpy(), [9, 1, 9, 3, 4])


def test_reductions():
    a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
    assert a.sum().item() == 10
    assert a.mean().item() == 2.5
    assert a.max().item() == 4
    assert a.min().item() == 1
    np.testing.assert_allclose(a.sum(axis=0).numpy(), [4, 6])
    np.testing.assert_allclose(a.argmax(axis=1).numpy(), [1, 1])
    assert a.norm1().item() == 10
    np.testing.assert_allclose(a.norm2().item(), np.sqrt(30), rtol=1e-6)


def test_std_matches_reference_ddof1():
    # nd4j std defaults to Bessel-corrected (population=false)
    a = Nd4j.create([1.0, 2.0, 3.0, 4.0])
    np.testing.assert_allclose(a.std().item(),
                               np.std([1, 2, 3, 4], ddof=1), rtol=1e-6)


def test_reshape_transpose_views():
    a = Nd4j.arange(6).reshape(2, 3)
    assert a.T.shape == (3, 2)
    assert a.ravel().shape == (6,)
    assert a.permute(1, 0).shape == (3, 2)
    assert a.expand_dims(0).shape == (1, 2, 3)


def test_indexing_and_put():
    a = Nd4j.arange(10.0)
    assert a[3].item() == 3
    b = a.put(0, 99.0)
    assert b[0].item() == 99 and a[0].item() == 0  # functional put


def test_dup_immutable():
    a = Nd4j.create([1.0])
    b = a.dup()
    b.addi(5)
    assert a[0].item() == 1


def test_concat_stack():
    a, b = Nd4j.ones((2, 2)), Nd4j.zeros((2, 2))
    assert Nd4j.concat(0, a, b).shape == (4, 2)
    assert Nd4j.stack(0, a, b).shape == (2, 2, 2)


def test_dtype_cast():
    a = Nd4j.create([1.5, 2.5])
    assert str(a.cast("int32").dtype) == "int32"
    assert str(a.cast("bfloat16").dtype) == "bfloat16"


def test_comparisons_and_where():
    a = Nd4j.create([1.0, 5.0, 3.0])
    m = a > 2
    np.testing.assert_array_equal(m.numpy(), [False, True, True])
    w = Nd4j.where(m, a, a * 0)
    np.testing.assert_allclose(w.numpy(), [0, 5, 3])


def test_elementwise_math():
    a = Nd4j.create([0.0, 1.0])
    np.testing.assert_allclose(a.exp().numpy(), np.exp([0, 1]), rtol=1e-6)
    np.testing.assert_allclose(a.tanh().numpy(), np.tanh([0, 1]), rtol=1e-5)
    np.testing.assert_allclose(a.sigmoid().numpy(),
                               1 / (1 + np.exp([0.0, -1.0])), rtol=1e-6)


def test_pytree_registration():
    import jax
    a = Nd4j.create([1.0, 2.0])
    out = jax.tree.map(lambda x: x, {"w": a})
    assert isinstance(out["w"], NDArray)


def test_row_column_vector_ops_and_access():
    from deeplearning4j_tpu.ndarray import Nd4j
    a = Nd4j.create(np.arange(12.0).reshape(3, 4))
    np.testing.assert_allclose(
        a.add_row_vector([1, 1, 1, 1]).numpy()[0], [1, 2, 3, 4])
    np.testing.assert_allclose(
        a.mul_column_vector([1, 2, 3]).numpy()[2], [24, 27, 30, 33])
    np.testing.assert_allclose(a.get_row(1).numpy(), [4, 5, 6, 7])
    np.testing.assert_allclose(a.get_column(2).numpy(), [2, 6, 10])
    np.testing.assert_allclose(a.get_rows(0, 2).numpy().shape, (2, 4))
    a.put_row(0, [9, 9, 9, 9]).put_scalar((1, 1), -1.0)
    assert a.get_double(0, 3) == 9 and a.get_int(1, 1) == -1
    assert a.sum_number() == 90.0  # 36 + (4-1+6+7) + 38
    b = Nd4j.create(np.full((3, 4), 5.0))
    assert a.gt(b).numpy().sum() == 10  # 9s row (4) + {6,7} + {8..11}


def test_distances_and_transforms():
    from deeplearning4j_tpu.ndarray import Nd4j, Transforms
    v1, v2 = Nd4j.create([1.0, 0.0]), Nd4j.create([0.0, 1.0])
    assert abs(v1.distance2(v2) - 2 ** 0.5) < 1e-6
    assert v1.distance1(v2) == 2.0
    assert abs(v1.cosine_sim(v2)) < 1e-6
    assert float(Transforms.sigmoid(Nd4j.scalar(0.0)).item()) == 0.5
    a = Nd4j.create(np.arange(6.0).reshape(2, 3))
    s = Transforms.all_cosine_similarities(a, a)
    np.testing.assert_allclose(np.diag(s.numpy()), 1.0, atol=1e-5)
    u = Transforms.unit_vec(Nd4j.create([3.0, 4.0]))
    np.testing.assert_allclose(u.numpy(), [0.6, 0.8], rtol=1e-6)
    n = Transforms.normalize_zero_mean_and_unit_variance(
        Nd4j.create(np.random.default_rng(0)
                    .standard_normal((50, 3)) * 7 + 3))
    assert abs(float(n.mean().item())) < 1e-5


def test_nd4j_factory_extras():
    from deeplearning4j_tpu.ndarray import Nd4j
    a = Nd4j.create(np.arange(6.0).reshape(2, 3))
    assert Nd4j.zeros_like(a).shape == (2, 3)
    assert Nd4j.ones_like(a).sum_number() == 6.0
    assert Nd4j.value_array_of((2, 2), 7.0).numpy().tolist() == \
        [[7, 7], [7, 7]]
    # std_number is Bessel-corrected like std()
    v = Nd4j.create([1.0, 2.0, 3.0, 4.0])
    assert abs(v.std_number() - float(v.std().item())) < 1e-6
    # zero-norm cosine guard: no NaN
    assert Nd4j.zeros((3,)).cosine_sim([1.0, 2.0, 3.0]) == 0.0
    assert Nd4j.pile(a, a, a).shape == (3, 2, 3)
    assert Nd4j.to_flattened(a, a).shape == (12,)
    assert Nd4j.diag(Nd4j.create([1.0, 2.0])).numpy()[1, 1] == 2.0
    assert Nd4j.rot90(a).shape == (3, 2)
    assert Nd4j.pad(a, ((1, 1), (0, 0))).shape == (4, 3)
    sh = Nd4j.shuffle(a, seed=0)
    assert sorted(sh.numpy()[:, 0].tolist()) == [0.0, 3.0]
    assert Nd4j.argsort(Nd4j.create([3.0, 1.0, 2.0])).numpy().tolist() \
        == [1, 2, 0]
    assert Nd4j.empty().length() == 0


class TestFacadeExtensions:
    """Nd4j.exec bridge + INDArray surface additions (replaceWhere,
    TAD API, host exports)."""

    def test_nd4j_exec_runs_registry_ops(self):
        out = Nd4j.exec("softmax", Nd4j.create([1.0, 2.0, 3.0]))
        assert abs(float(out.sum_number()) - 1.0) < 1e-5
        pooled, idx = Nd4j.exec(
            "max_pool_with_argmax", Nd4j.randn((1, 4, 4, 2)))
        assert pooled.shape == (1, 2, 2, 2)
        with pytest.raises(KeyError):
            Nd4j.exec("not_an_op", Nd4j.create([1.0]))

    def test_replace_where_and_cond(self):
        a = Nd4j.create([1.0, -2.0, 3.0, -4.0])
        out = a.replace_where(0.0, lambda x: x < 0)
        assert np.allclose(out.numpy(), [1, 0, 3, 0])
        m = a.cond(lambda x: x > 0)
        assert np.allclose(m.numpy(), [1, 0, 1, 0])
        got = a.get_where(None, lambda x: x < 0)
        assert np.allclose(got.numpy(), [-2, -4])

    def test_tad_api(self):
        a = Nd4j.create(np.arange(24.0).reshape(2, 3, 4))
        assert a.tensors_along_dimension(2) == 6
        t0 = a.tensor_along_dimension(0, 2)
        assert np.allclose(t0.numpy(), [0, 1, 2, 3])
        t1 = a.tensor_along_dimension(1, 2)
        assert np.allclose(t1.numpy(), [4, 5, 6, 7])
        v = a.vector_along_dimension(0, 2)
        assert v.length() == 4

    def test_predicates_and_exports(self):
        a = Nd4j.create([[1.0, 2.0, 3.0]])
        assert a.is_row_vector() and not a.is_column_vector()
        assert Nd4j.create([[1.0], [2.0]]).is_column_vector()
        assert Nd4j.eye(3).is_square()
        assert a.to_int_vector() == [1, 2, 3]
        assert a.rows() == 1 and a.columns() == 3
        m = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
        assert m.to_float_matrix() == [[1.0, 2.0], [3.0, 4.0]]

    def test_number_reductions(self):
        a = Nd4j.create([1.0, 2.0, 3.0, 4.0, 5.0])
        assert a.median_number() == 3.0
        assert abs(a.percentile_number(50) - 3.0) < 1e-6
        assert a.prod_number() == 120.0
        assert abs(a.var_number() - 2.0) < 1e-6


def test_ndarray_index_dsl():
    """Reference NDArrayIndex.interval/point/all over get/put."""
    from deeplearning4j_tpu.ndarray_index import NDArrayIndex as I
    a = Nd4j.create(np.arange(24.0).reshape(4, 6))
    sub = a.get(I.point(1), I.interval(2, 5))
    assert np.allclose(sub.numpy(), [8, 9, 10])
    inc = a.get(I.point(1), I.interval(2, 5, inclusive=True))
    assert np.allclose(inc.numpy(), [8, 9, 10, 11])
    col = a.get(I.all(), I.point(0))
    assert np.allclose(col.numpy(), [0, 6, 12, 18])
    strided = a.get(I.interval(0, 4, 2), I.all())
    assert strided.shape == (2, 6)
    up = a.put_indices((I.point(0), I.interval(0, 2)), Nd4j.create([9.0, 9.0]))
    assert np.allclose(up.numpy()[0, :3], [9, 9, 2])
    # original untouched (functional semantics)
    assert float(a.get_double(0, 0)) == 0.0
