"""Validation for the extended declarable-op surface (ops_registry_ext).

Model: the reference's OpValidation harness
(``org.nd4j.autodiff.validation.OpValidation`` — every declarable op's
forward checked against a trusted producer).  Here the trusted producers
are numpy / hand-computed closed forms; gradient coverage comes from the
existing gradcheck harness since every op is jax-differentiable.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.autodiff.ops_registry import OPS

rng = np.random.RandomState(7)


def A(*shape):
    return jnp.asarray(rng.randn(*shape).astype(np.float32))


class TestMathTransforms:
    def test_rint_trunc_mod(self):
        a = jnp.asarray([1.4, -1.6, 2.5])
        assert np.allclose(OPS["rint"](a), np.rint([1.4, -1.6, 2.5]))
        assert np.allclose(OPS["trunc"](a), [1.0, -1.0, 2.0])
        assert np.allclose(OPS["mod"](jnp.asarray([5.0, -5.0]),
                                      jnp.asarray([3.0, 3.0])),
                           [2.0, 1.0])

    def test_divide_no_nan(self):
        out = OPS["divide_no_nan"](jnp.asarray([1.0, 2.0]),
                                   jnp.asarray([0.0, 2.0]))
        assert np.allclose(out, [0.0, 1.0])

    def test_special_functions(self):
        import scipy.special as sp
        x = jnp.asarray([0.5, 1.5])
        assert np.allclose(OPS["igamma"](jnp.asarray(2.0), x),
                           sp.gammainc(2.0, np.asarray(x)), atol=1e-5)
        assert np.allclose(OPS["erfinv"](jnp.asarray(0.5)),
                           sp.erfinv(0.5), atol=1e-5)
        assert np.allclose(OPS["zeta"](jnp.asarray(2.0),
                                       jnp.asarray(1.0)),
                           np.pi ** 2 / 6, atol=1e-4)

    def test_merge_ops(self):
        a, b, c = A(3), A(3), A(3)
        assert np.allclose(OPS["mergeadd"](a, b, c), a + b + c)
        assert np.allclose(OPS["mergeavg"](a, b, c), (a + b + c) / 3)
        assert np.allclose(OPS["mergemax"](a, b, c),
                           np.maximum(np.maximum(a, b), c))
        assert np.allclose(OPS["mergemaxindex"](a, b, c),
                           np.argmax(np.stack([a, b, c]), 0))

    def test_clip_by_global_norm(self):
        a, b = jnp.ones(4) * 3, jnp.ones(4) * 4
        ca, cb = OPS["clip_by_global_norm"](a, b, clip_norm=1.0)
        g = np.sqrt(np.sum(np.square(ca)) + np.sum(np.square(cb)))
        assert np.isclose(g, 1.0, atol=1e-5)

    def test_clip_by_norm_zero_grad_finite(self):
        # sqrt'(0)=inf: all-zero tensor must not NaN-poison gradients
        for name in ("clip_by_norm", "clip_by_avg_norm"):
            g = jax.grad(lambda a: jnp.sum(OPS[name](
                a, clip_norm=1.0)))(jnp.zeros(3))
            assert np.all(np.isfinite(np.asarray(g))), name
        out = OPS["clip_by_norm"](jnp.ones(4) * 3.0, clip_norm=1.0)
        assert np.isclose(float(jnp.linalg.norm(out)), 1.0, atol=1e-5)
        small = jnp.asarray([0.1, 0.2])
        assert np.allclose(OPS["clip_by_norm"](small, clip_norm=1.0),
                           small)

    def test_standardize(self):
        x = A(4, 8)
        out = OPS["standardize"](x, axis=-1)
        assert np.allclose(np.mean(out, -1), 0, atol=1e-5)
        assert np.allclose(np.std(out, -1), 1, atol=1e-4)

    def test_check_numerics_eager_raises(self):
        with pytest.raises(FloatingPointError):
            OPS["check_numerics"](jnp.asarray([1.0, np.nan]))
        out = OPS["check_numerics"](jnp.asarray([1.0, 2.0]))
        assert np.allclose(out, [1.0, 2.0])


class TestBitwise:
    def test_basic(self):
        a = jnp.asarray([0b1100], jnp.int32)
        b = jnp.asarray([0b1010], jnp.int32)
        assert int(OPS["bitwise_and"](a, b)[0]) == 0b1000
        assert int(OPS["bitwise_or"](a, b)[0]) == 0b1110
        assert int(OPS["bitwise_xor"](a, b)[0]) == 0b0110
        assert int(OPS["shift_bits"](a, 1)[0]) == 0b11000
        assert int(OPS["rshift_bits"](a, 2)[0]) == 0b11

    def test_cyclic_shift(self):
        a = jnp.asarray([1], jnp.int32)
        out = OPS["cyclic_rshift_bits"](a, 1)
        assert int(out[0]) == -(1 << 31)  # wraps to sign bit

    def test_cyclic_shift_negative_and_zero(self):
        # rotl(-2, 1): 0xFFFFFFFE -> 0xFFFFFFFD == -3 (logical, not
        # sign-filling); n=0 must be the identity, not an UB 32-shift
        a = jnp.asarray([-2], jnp.int32)
        assert int(OPS["cyclic_shift_bits"](a, 1)[0]) == -3
        assert int(OPS["cyclic_shift_bits"](a, 0)[0]) == -2
        assert int(OPS["cyclic_rshift_bits"](a, 0)[0]) == -2

    def test_compare_and_bitpack(self):
        a = jnp.asarray([[1, -1, 1, -1, 1, 1, -1, -1]], jnp.float32)
        out = OPS["compare_and_bitpack"](a, threshold=0.0)
        assert int(out[0, 0]) == 0b10101100


class TestReductions:
    def test_all_any_count(self):
        a = jnp.asarray([[1, 0, 2], [0, 0, 0]], jnp.float32)
        assert np.array_equal(OPS["all"](a, axis=1), [False, False])
        assert np.array_equal(OPS["any"](a, axis=1), [True, False])
        assert np.array_equal(OPS["count_zero"](a, axis=1), [1, 3])

    def test_first_last_index(self):
        a = jnp.asarray([0.0, 0.5, 2.0, 0.1, 3.0])
        assert int(OPS["first_index"](a, condition="gt", value=1.0)) == 2
        assert int(OPS["last_index"](a, condition="gt", value=1.0)) == 4
        assert int(OPS["first_index"](a, condition="gt",
                                      value=99.0)) == -1

    def test_iamax(self):
        a = jnp.asarray([1.0, -5.0, 3.0])
        assert int(OPS["iamax"](a)) == 1
        assert int(OPS["iamin"](a)) == 0

    def test_percentile_median(self):
        a = A(100)
        assert np.isclose(OPS["median"](a), np.median(a), atol=1e-5)
        assert np.isclose(OPS["percentile"](a, q=75),
                          np.percentile(a, 75), atol=1e-4)

    def test_match_condition(self):
        a = jnp.asarray([1.0, -2.0, 3.0, -4.0])
        assert int(OPS["match_condition"](a, condition="lt",
                                          value=0.0)) == 2


class TestShapeOps:
    def test_basics(self):
        a = A(2, 3, 4)
        assert int(OPS["rank"](a)) == 3
        assert int(OPS["size"](a)) == 24
        assert int(OPS["size_at"](a, dim=1)) == 3
        assert OPS["flatten"](a).shape == (24,)
        assert OPS["broadcast_to"](jnp.ones(3), shape=(2, 3)).shape == (2, 3)

    def test_matrix_diag_roundtrip(self):
        d = A(4)
        m = OPS["matrix_diag"](d)
        assert np.allclose(OPS["matrix_diag_part"](m), d)
        m2 = OPS["matrix_set_diag"](jnp.zeros((4, 4)), d)
        assert np.allclose(m, m2)

    def test_matrix_band_part(self):
        a = jnp.ones((4, 4))
        out = OPS["matrix_band_part"](a, num_lower=0, num_upper=0)
        assert np.allclose(out, np.eye(4))

    def test_invert_permutation(self):
        p = jnp.asarray([2, 0, 1])
        assert np.array_equal(OPS["invert_permutation"](p), [1, 2, 0])

    def test_sequence_mask(self):
        out = OPS["sequence_mask"](jnp.asarray([1, 3]), maxlen=4)
        assert np.allclose(out, [[1, 0, 0, 0], [1, 1, 1, 0]])

    def test_confusion_matrix(self):
        cm = OPS["confusion_matrix"](jnp.asarray([0, 1, 1]),
                                     jnp.asarray([0, 1, 0]),
                                     num_classes=2)
        assert np.array_equal(cm, [[1, 0], [1, 1]])

    def test_unique(self):
        vals, counts = OPS["unique_with_counts"](
            jnp.asarray([3, 1, 3, 2, 1, 3]), size=3)
        assert np.array_equal(vals, [1, 2, 3])
        assert np.array_equal(counts, [2, 1, 3])

    def test_dynamic_partition_stitch(self):
        a = jnp.asarray([10.0, 20.0, 30.0, 40.0])
        parts = OPS["dynamic_partition"](a, jnp.asarray([0, 1, 0, 1]),
                                         num_partitions=2)
        assert np.allclose(parts[0], [10, 30])
        out = OPS["dynamic_stitch"](jnp.asarray([0, 2]),
                                    jnp.asarray([1, 3]),
                                    parts[0], parts[1])
        assert np.allclose(out, a)

    def test_scatter_nd(self):
        idx = jnp.asarray([[0], [2]])
        out = OPS["scatter_nd"](idx, jnp.asarray([1.0, 2.0]), shape=(4,))
        assert np.allclose(out, [1, 0, 2, 0])

    def test_unsorted_segments(self):
        a = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        ids = jnp.asarray([0, 1, 0, 1])
        assert np.allclose(OPS["unsorted_segment_sum"](
            a, ids, num_segments=2), [4, 6])
        assert np.allclose(OPS["unsorted_segment_mean"](
            a, ids, num_segments=2), [2, 3])
        assert np.allclose(OPS["unsorted_segment_prod"](
            a, ids, num_segments=2), [3, 8])

    def test_space_batch_roundtrip(self):
        x = A(1, 4, 4, 1)
        sb = OPS["space_to_batch"](x, block_size=2,
                                   paddings=[[0, 0], [0, 0]])
        assert sb.shape == (4, 2, 2, 1)
        bs = OPS["batch_to_space"](sb, block_size=2,
                                   crops=[[0, 0], [0, 0]])
        assert np.allclose(bs, x, atol=1e-6)

    def test_reverse_sequence(self):
        a = jnp.arange(12.0).reshape(2, 6)
        out = OPS["reverse_sequence"](a, jnp.asarray([3, 5]))
        assert np.allclose(out[0], [2, 1, 0, 3, 4, 5])
        assert np.allclose(out[1], [10, 9, 8, 7, 6, 11])

    def test_nth_element(self):
        a = jnp.asarray([5.0, 1.0, 3.0])
        assert float(OPS["nth_element"](a, n=1)) == 3.0
        assert float(OPS["nth_element"](a, n=0, reverse=True)) == 5.0


class TestConvPool:
    def test_conv1d_matches_manual(self):
        x = A(2, 8, 3)
        w = A(3, 3, 5)
        out = OPS["conv1d"](x, w, padding="VALID")
        ref = jax.lax.conv_general_dilated(
            x, w, (1,), "VALID", dimension_numbers=("NWC", "WIO", "NWC"))
        assert np.allclose(out, ref, atol=1e-5)

    def test_conv3d_shape(self):
        out = OPS["conv3d"](A(1, 4, 4, 4, 2), A(2, 2, 2, 2, 6),
                            padding="VALID")
        assert out.shape == (1, 3, 3, 3, 6)

    def test_deconv2d_shape(self):
        out = OPS["deconv2d"](A(1, 4, 4, 3), A(2, 2, 3, 8),
                              strides=(2, 2), padding="SAME")
        assert out.shape == (1, 8, 8, 8)

    def test_sconv2d_equals_composition(self):
        x = A(1, 6, 6, 3)
        wd = A(3, 3, 3, 2)       # depthwise (H,W,C,M)
        wp = A(1, 1, 6, 4)       # pointwise
        out = OPS["sconv2d"](x, wd, wp, padding="VALID")
        assert out.shape == (1, 4, 4, 4)

    def test_pool3d(self):
        x = A(1, 4, 4, 4, 2)
        assert OPS["max_pooling3d"](x).shape == (1, 2, 2, 2, 2)
        avg = OPS["avg_pooling3d"](x)
        assert np.isclose(float(avg[0, 0, 0, 0, 0]),
                          float(np.mean(np.asarray(
                              x[0, :2, :2, :2, 0]))), atol=1e-5)

    def test_pnorm_pool(self):
        x = jnp.abs(A(1, 4, 4, 1))
        out = OPS["pnormpool2d"](x, pnorm=2)
        man = np.sqrt(np.sum(np.square(np.asarray(x[0, :2, :2, 0]))))
        assert np.isclose(float(out[0, 0, 0, 0]), man, atol=1e-4)

    def test_max_pool_with_argmax_decodes(self):
        x = A(2, 6, 6, 3)
        p, idx = OPS["max_pool_with_argmax"](x, kernel=(2, 2),
                                             strides=(2, 2))
        flat = np.asarray(x).reshape(2, -1)
        dec = np.take_along_axis(flat, np.asarray(idx).reshape(2, -1), 1)
        assert np.allclose(dec.reshape(p.shape), p)

    def test_im2col_col2im_adjoint(self):
        x = A(1, 5, 5, 2)
        cols = OPS["im2col"](x, kernel=(3, 3))
        assert cols.shape == (1, 3, 3, 18)
        back = OPS["col2im"](jnp.ones_like(cols), input_shape=x.shape,
                             kernel=(3, 3))
        # center pixel is covered by all 9 windows
        assert float(back[0, 2, 2, 0]) == 9.0

    def test_lrn_identity_for_zero_alpha(self):
        x = A(1, 4, 4, 8)
        out = OPS["lrn"](x, alpha=0.0, beta=0.75, bias=1.0)
        assert np.allclose(out, x, atol=1e-6)

    def test_lrn_even_depth_and_value(self):
        x = A(1, 2, 2, 8)
        out = OPS["lrn"](x, depth=4)            # even depth: valid shape
        assert out.shape == x.shape
        # closed-form check at channel 0, depth=5: window = channels 0..2
        out5 = OPS["lrn"](x, depth=5, bias=2.0, alpha=1e-2, beta=0.5)
        xs = np.asarray(x)[0, 0, 0]
        ref = xs[0] / np.sqrt(2.0 + 1e-2 * np.sum(xs[:3] ** 2))
        assert np.isclose(float(out5[0, 0, 0, 0]), ref, atol=1e-5)

    def test_upsampling(self):
        x = A(1, 2, 2, 1)
        up = OPS["upsampling2d"](x, factor=2)
        assert up.shape == (1, 4, 4, 1)
        assert np.allclose(up[0, :2, :2, 0], x[0, 0, 0, 0])


class TestRecurrent:
    def test_lstm_cell_manual(self):
        B, I, H = 2, 3, 4
        x, h, c = A(B, I), A(B, H), A(B, H)
        wx, wh, b = A(I, 4 * H), A(H, 4 * H), A(4 * H)
        hn, cn = OPS["lstm_cell"](x, h, c, wx, wh, b)
        z = np.asarray(x) @ np.asarray(wx) + np.asarray(h) @ np.asarray(
            wh) + np.asarray(b)
        i_, f_, g_, o_ = np.split(z, 4, -1)
        sig = lambda v: 1 / (1 + np.exp(-v))
        c_ref = sig(f_) * np.asarray(c) + sig(i_) * np.tanh(g_)
        h_ref = sig(o_) * np.tanh(c_ref)
        assert np.allclose(hn, h_ref, atol=1e-5)
        assert np.allclose(cn, c_ref, atol=1e-5)

    def test_lstm_layer_scan_matches_loop(self):
        T, B, I, H = 5, 2, 3, 4
        x = A(T, B, I)
        h = jnp.zeros((B, H))
        c = jnp.zeros((B, H))
        wx, wh, b = A(I, 4 * H), A(H, 4 * H), A(4 * H)
        hs, hT, cT = OPS["lstm_layer"](x, h, c, wx, wh, b)
        hh, cc = h, c
        for t in range(T):
            hh, cc = OPS["lstm_cell"](x[t], hh, cc, wx, wh, b)
            assert np.allclose(hs[t], hh, atol=1e-5)
        assert np.allclose(hT, hh, atol=1e-5)

    def test_gru_shapes(self):
        T, B, I, H = 4, 2, 3, 5
        hs, hT = OPS["gru"](A(T, B, I), jnp.zeros((B, H)),
                            A(I, 3 * H), A(H, 3 * H), A(3 * H))
        assert hs.shape == (T, B, H) and hT.shape == (B, H)

    def test_sru_shapes(self):
        T, B, H = 4, 2, 5
        hs, cT = OPS["sru"](A(T, B, H), jnp.zeros((B, H)),
                            A(H, 3 * H), A(2 * H))
        assert hs.shape == (T, B, H)


class TestUpdaters:
    def test_adam_first_step(self):
        g = jnp.ones(3)
        u, m, v = OPS["adam_updater"](g, jnp.zeros(3), jnp.zeros(3),
                                      lr=0.1)
        # bias-corrected first step ≈ lr * sign(g)
        assert np.allclose(u, 0.1, atol=1e-3)

    def test_sgd(self):
        assert np.allclose(OPS["sgd_updater"](jnp.ones(2), lr=0.5), 0.5)

    def test_nesterovs_matches_reference_formula(self):
        g, v = jnp.ones(2), jnp.zeros(2)
        upd, v2 = OPS["nesterovs_updater"](g, v, lr=0.1, momentum=0.9)
        assert np.allclose(v2, -0.1)
        assert np.allclose(upd, -(0.9 * v2 - 0.1 * g))

    def test_all_updaters_preserve_shape(self):
        g = A(4)
        z = jnp.zeros(4)
        for name, args, kw in [
                ("ada_max_updater", (g, z, z), dict(lr=0.1)),
                ("nadam_updater", (g, z, z), dict(lr=0.1)),
                ("ams_grad_updater", (g, z, z, z), dict(lr=0.1)),
                ("ada_delta_updater", (g, z, z), {}),
                ("ada_grad_updater", (g, z), dict(lr=0.1)),
                ("rms_prop_updater", (g, z), dict(lr=0.1)),
                ("ada_belief_updater", (g, z, z), dict(lr=0.1))]:
            out = OPS[name](*args, **kw)
            assert out[0].shape == g.shape, name


class TestLosses:
    def test_l2_loss(self):
        a = A(5)
        assert np.isclose(OPS["l2_loss"](a),
                          np.sum(np.square(a)) / 2, atol=1e-5)

    def test_hinge(self):
        labels = jnp.asarray([1.0, 0.0])
        logits = jnp.asarray([0.5, -2.0])
        ref = np.mean([max(0, 1 - 0.5), max(0, 1 - 2.0)])
        assert np.isclose(OPS["hinge_loss"](labels, logits), ref)

    def test_weighted_xent_matches_plain_when_w1(self):
        labels = jnp.asarray([1.0, 0.0, 1.0])
        logits = A(3)
        w = OPS["weighted_cross_entropy_with_logits"](labels, logits,
                                                      pos_weight=1.0)
        p = OPS["loss_sigmoid_cross_entropy"](labels, logits)
        assert np.isclose(w, p, atol=1e-5)

    def test_log_poisson(self):
        labels = jnp.asarray([2.0])
        logp = jnp.asarray([0.5])
        ref = np.exp(0.5) - 2.0 * 0.5
        assert np.isclose(OPS["log_poisson_loss"](labels, logp), ref,
                          atol=1e-5)

    def test_moments_pipeline(self):
        a = A(3, 4)
        cnt, s, ss = OPS["sufficient_statistics"](a, axis=[0])
        mean, var = OPS["normalize_moments"](cnt, s, ss)
        assert np.allclose(mean, np.mean(a, 0), atol=1e-5)
        assert np.allclose(var, np.var(a, 0), atol=1e-4)

    def test_weighted_moments_uniform(self):
        a = A(3, 4)
        mean, var = OPS["weighted_moments"](a, jnp.ones_like(a),
                                            axis=(0,))
        assert np.allclose(mean, np.mean(a, 0), atol=1e-5)


class TestImageOps:
    def test_hsv_roundtrip(self):
        rgb = jnp.asarray(rng.rand(6, 6, 3).astype(np.float32))
        back = OPS["hsv_to_rgb"](OPS["rgb_to_hsv"](rgb))
        assert np.allclose(back, rgb, atol=1e-4)

    def test_yuv_roundtrip(self):
        rgb = jnp.asarray(rng.rand(6, 6, 3).astype(np.float32))
        assert np.allclose(OPS["yuv_to_rgb"](OPS["rgb_to_yuv"](rgb)),
                           rgb, atol=1e-4)
        assert np.allclose(OPS["yiq_to_rgb"](OPS["rgb_to_yiq"](rgb)),
                           rgb, atol=1e-4)

    def test_grayscale(self):
        rgb = jnp.ones((2, 2, 3))
        assert np.allclose(OPS["rgb_to_grs"](rgb), 0.9999, atol=1e-3)

    def test_adjust_contrast_mean_preserved(self):
        img = jnp.asarray(rng.rand(1, 8, 8, 3).astype(np.float32))
        out = OPS["adjust_contrast"](img, factor=2.0)
        assert np.allclose(np.mean(out, (1, 2)), np.mean(img, (1, 2)),
                           atol=1e-5)

    def test_nms_suppresses_overlap(self):
        boxes = jnp.asarray([[0, 0, 1, 1], [0, 0, 0.95, 0.95],
                             [0.5, 0.5, 1.5, 1.5]], jnp.float32)
        scores = jnp.asarray([0.9, 0.8, 0.7], jnp.float32)
        keep = OPS["non_max_suppression"](boxes, scores,
                                          max_output_size=3,
                                          iou_threshold=0.5)
        assert list(np.asarray(keep)) == [0, 2, -1]

    def test_crop_and_resize_identity(self):
        img = jnp.asarray(rng.rand(1, 5, 5, 1).astype(np.float32))
        out = OPS["crop_and_resize"](img,
                                     jnp.asarray([[0.0, 0.0, 1.0, 1.0]]),
                                     jnp.asarray([0]), crop_size=(5, 5))
        assert np.allclose(out[0], img[0], atol=1e-5)

    def test_resize_bicubic_shape(self):
        out = OPS["resize_bicubic"](A(1, 4, 4, 3), size=(8, 8))
        assert out.shape == (1, 8, 8, 3)


class TestRandomOps:
    def test_shapes_and_determinism(self):
        a = OPS["random_exponential"](shape=(100,), seed=1)
        b = OPS["random_exponential"](shape=(100,), seed=1)
        assert np.allclose(a, b)
        assert float(jnp.min(a)) >= 0

    def test_truncated_normal_bounds(self):
        a = OPS["truncated_normal"](shape=(1000,), seed=0)
        assert float(jnp.max(jnp.abs(a))) <= 2.0 + 1e-5

    def test_multinomial(self):
        logits = jnp.asarray([[0.0, 100.0]])
        s = OPS["random_multinomial"](logits, num_samples=10, seed=0)
        assert np.all(np.asarray(s) == 1)

    def test_multinomial_batched(self):
        logits = jnp.asarray([[100.0, 0.0, 0.0], [0.0, 0.0, 100.0]])
        s = OPS["random_multinomial"](logits, num_samples=5, seed=0)
        assert s.shape == (2, 5)
        assert np.all(np.asarray(s[0]) == 0)
        assert np.all(np.asarray(s[1]) == 2)

    def test_random_crop(self):
        out = OPS["random_crop"](A(8, 8, 3), size=(4, 4, 3), seed=3)
        assert out.shape == (4, 4, 3)

    def test_alpha_dropout_identity_when_deterministic(self):
        x = A(10)
        assert np.allclose(OPS["alpha_dropout"](x, rate=0.5, seed=0), x)


class TestLinalgExtra:
    def test_lu_reconstruct(self):
        a = A(4, 4) + 4 * jnp.eye(4)
        p, l, u = OPS["lu"](a)
        assert np.allclose(p @ l @ u, a, atol=1e-4)

    def test_gemm(self):
        a, b, c = A(3, 4), A(4, 5), A(3, 5)
        out = OPS["gemm"](a, b, c, alpha=2.0, beta=0.5)
        assert np.allclose(out, 2 * np.asarray(a) @ np.asarray(b)
                           + 0.5 * np.asarray(c), atol=1e-4)

    def test_self_adjoint_eig(self):
        a = A(4, 4)
        sym = (a + a.T) / 2
        w, v = OPS["self_adjoint_eig"](sym)
        assert np.allclose(v @ jnp.diag(w) @ v.T, sym, atol=1e-4)

    def test_matrix_power(self):
        a = A(3, 3)
        assert np.allclose(OPS["matrix_power"](a, n=3),
                           np.asarray(a) @ np.asarray(a) @ np.asarray(a),
                           atol=1e-4)


class TestJittability:
    """Core new ops must trace into XLA (static shapes) — the TPU path."""

    def test_jit_composite(self):
        @jax.jit
        def f(x, w):
            y = OPS["conv1d"](x, w, padding="SAME")
            y = OPS["lrn"](y, depth=3)
            y = OPS["standardize"](y, axis=-1)
            return OPS["l2_loss"](y)
        out = f(A(2, 8, 3), A(3, 3, 4))
        assert np.isfinite(float(out))

    def test_jit_histogram(self):
        h = jax.jit(lambda a: OPS["histogram"](a, nbins=4))(
            jnp.arange(8.0))
        assert int(jnp.sum(h)) == 8

    def test_jit_nms(self):
        f = jax.jit(lambda b, s: OPS["non_max_suppression"](
            b, s, max_output_size=4))
        keep = f(jnp.asarray([[0, 0, 1, 1.0]] * 6),
                 jnp.arange(6, dtype=jnp.float32))
        assert int(keep[0]) == 5

    def test_jit_lstm_layer_grad(self):
        T, B, I, H = 3, 2, 3, 4

        def loss(wx):
            hs, _, _ = OPS["lstm_layer"](
                jnp.ones((T, B, I)), jnp.zeros((B, H)),
                jnp.zeros((B, H)), wx, jnp.ones((H, 4 * H)) * 0.1,
                jnp.zeros(4 * H))
            return jnp.sum(hs)
        g = jax.jit(jax.grad(loss))(jnp.ones((I, 4 * H)) * 0.1)
        assert np.all(np.isfinite(np.asarray(g)))


class TestOpsBatch2:
    def test_split_v(self):
        parts = OPS["split_v"](jnp.arange(10.0), sizes=[3, 3, 4])
        assert [p.shape[0] for p in parts] == [3, 3, 4]
        assert np.allclose(parts[2], [6, 7, 8, 9])

    def test_cumsum_exclusive(self):
        a = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        assert np.allclose(OPS["cumsum_exclusive"](a), [0, 1, 3, 6])
        assert np.allclose(OPS["cumsum_exclusive"](a, reverse=True),
                           [9, 7, 4, 0])

    def test_ctc_greedy_decoder(self):
        # frames argmax: [1, 1, blank, 0... wait] use explicit logits
        logits = jnp.asarray([[[0.0, 2.0], [0.0, 1.0], [3.0, 0.0],
                               [3.0, 0.0], [0.0, 4.0]]])
        ids, lens = OPS["ctc_greedy_decoder"](logits, jnp.asarray([5]))
        # path 1,1,0,0,1 -> merge repeats & strip blank(0) -> [1, 1]
        assert int(lens[0]) == 2
        assert list(np.asarray(ids[0][:2])) == [1, 1]

    def test_ctc_greedy_respects_seq_length(self):
        logits = jnp.asarray([[[0.0, 2.0], [0.0, 2.0], [0.0, 2.0]]])
        ids, lens = OPS["ctc_greedy_decoder"](logits, jnp.asarray([1]))
        assert int(lens[0]) == 1

    def test_boolean_mask_and_select(self):
        a = jnp.asarray([1.0, 2.0, 3.0])
        out = OPS["boolean_mask"](a, jnp.asarray([True, False, True]))
        assert np.allclose(out, [1, 3])
        sel = OPS["select"](jnp.asarray([True, False]),
                            jnp.asarray([1.0, 1.0]),
                            jnp.asarray([9.0, 9.0]))
        assert np.allclose(sel, [1, 9])

    def test_rot90_flips(self):
        img = jnp.arange(4.0).reshape(1, 2, 2, 1)
        r = OPS["rot90"](img)
        assert r.shape == (1, 2, 2, 1)
        lr = OPS["flip_left_right"](img)
        assert float(lr[0, 0, 0, 0]) == 1.0
        ud = OPS["flip_up_down"](img)
        assert float(ud[0, 0, 0, 0]) == 2.0

    def test_dilation2d_identity_kernel(self):
        x = jnp.asarray(rng.rand(1, 4, 4, 1).astype(np.float32))
        out = OPS["dilation2d"](x, jnp.zeros((1, 1, 1)),
                                padding="VALID")
        assert np.allclose(out, x)

    def test_bidirectional_rnn_shapes(self):
        T, B, I, H = 3, 2, 2, 4
        z = jnp.zeros((B, H))
        out, hf, hb = OPS["static_bidirectional_rnn"](
            jnp.ones((T, B, I)), z, z, z, z,
            A(I, 4 * H), A(H, 4 * H), A(4 * H),
            A(I, 4 * H), A(H, 4 * H), A(4 * H))
        assert out.shape == (T, B, 2 * H)

    def test_norm_orders(self):
        a = jnp.asarray([3.0, -4.0])
        assert np.isclose(OPS["norm"](a, ord=1), 7.0)
        assert np.isclose(OPS["norm"](a, ord=2), 5.0)
        assert np.isclose(OPS["norm"](a, ord="inf"), 4.0)

    def test_dtype_casts_and_creation(self):
        assert OPS["to_int32"](jnp.asarray([1.7])).dtype == jnp.int32
        assert OPS["to_bfloat16"](jnp.ones(2)).dtype == jnp.bfloat16
        assert OPS["ones"](shape=(2, 3)).shape == (2, 3)
        assert OPS["tri"](n=3)[0, 1] == 0.0

    def test_segment_prod_scatter_div(self):
        a = jnp.asarray([2.0, 3.0, 4.0, 5.0])
        ids = jnp.asarray([0, 0, 1, 1])
        assert np.allclose(OPS["segment_prod"](a, ids, num_segments=2),
                           [6, 20])
        out = OPS["scatter_div"](jnp.asarray([8.0, 9.0]),
                                 jnp.asarray([0]), jnp.asarray([2.0]))
        assert np.allclose(out, [4, 9])
