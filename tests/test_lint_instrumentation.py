"""Tier-1 fence: every ``sentry.jit`` hot path emits obs telemetry and
nothing outside ``obs/`` step-times with ``time.time()`` — run as part
of the suite so a future PR that adds an uninstrumented jitted path
(or reintroduces a second wall clock) fails CI loudly."""
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import lint_instrumentation  # noqa: E402


def test_package_passes_instrumentation_lint():
    problems = lint_instrumentation.run()
    assert not problems, "\n".join(problems)


def test_lint_catches_uninstrumented_hot_path(tmp_path):
    (tmp_path / "hot.py").write_text(
        "from deeplearning4j_tpu.perf import sentry\n"
        "step = sentry.jit(lambda x: x)\n")
    (tmp_path / "clock.py").write_text(
        "import time\nstart = time.time()\n")
    (tmp_path / "fine.py").write_text(
        "from deeplearning4j_tpu.perf import sentry\n"
        "from deeplearning4j_tpu import obs\n"
        "step = sentry.jit(lambda x: x)\n"
        "obs.record_step('e', 0.0, 0.0, 0.0, 0.0)\n")
    problems = lint_instrumentation.run(tmp_path)
    assert len(problems) == 2
    assert any("hot.py" in p and "sentry.jit" in p for p in problems)
    assert any("clock.py" in p and "time.time()" in p
               for p in problems)
