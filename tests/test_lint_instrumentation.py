"""Tier-1 fence: every ``sentry.jit`` hot path emits obs telemetry and
nothing outside ``obs/`` step-times with ``time.time()`` — run as part
of the suite so a future PR that adds an uninstrumented jitted path
(or reintroduces a second wall clock) fails CI loudly."""
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import lint_instrumentation  # noqa: E402


def test_package_passes_instrumentation_lint():
    problems = lint_instrumentation.run()
    assert not problems, "\n".join(problems)


def test_lint_catches_uninstrumented_hot_path(tmp_path):
    (tmp_path / "hot.py").write_text(
        "from deeplearning4j_tpu.perf import sentry\n"
        "step = sentry.jit(lambda x: x)\n")
    (tmp_path / "clock.py").write_text(
        "import time\nstart = time.time()\n")
    (tmp_path / "fine.py").write_text(
        "from deeplearning4j_tpu.perf import sentry\n"
        "from deeplearning4j_tpu import obs\n"
        "step = sentry.jit(lambda x: x)\n"
        "obs.record_step('e', 0.0, 0.0, 0.0, 0.0)\n")
    problems = lint_instrumentation.run(tmp_path)
    assert len(problems) == 2
    assert any("hot.py" in p and "sentry.jit" in p for p in problems)
    assert any("clock.py" in p and "time.time()" in p
               for p in problems)


def test_lint_catches_step_variant_without_warmup_feed(tmp_path):
    """Rule 4: a ParallelWrapper step builder missing from
    WARMUP_FEEDS (or a stale feed, or a warmup() that ignores the
    table) fails the lint — new step signatures can't silently
    cold-trace their first real batch."""
    pdir = tmp_path / "parallel"
    pdir.mkdir()
    (pdir / "wrapper.py").write_text(
        "class ParallelWrapper:\n"
        "    def _build_sync_step(self):\n"
        "        pass\n"
        "    def _build_fancy_new_step(self):\n"
        "        pass\n"
        "    def warmup(self, specs):\n"
        "        return WARMUP_FEEDS\n"
        "WARMUP_FEEDS = {\n"
        "    '_build_sync_step': None,\n"
        "    '_build_removed_step': None,\n"
        "}\n")
    problems = lint_instrumentation.run(tmp_path)
    assert any("_build_fancy_new_step" in p and "WARMUP_FEEDS" in p
               for p in problems)
    assert any("_build_removed_step" in p and "stale" in p
               for p in problems)
    # dead table: warmup() that never reads WARMUP_FEEDS
    (pdir / "wrapper.py").write_text(
        "class ParallelWrapper:\n"
        "    def _build_sync_step(self):\n"
        "        pass\n"
        "    def warmup(self, specs):\n"
        "        return None\n"
        "WARMUP_FEEDS = {'_build_sync_step': None}\n")
    problems = lint_instrumentation.run(tmp_path)
    assert any("never reads WARMUP_FEEDS" in p for p in problems)


def _fault_tree(tmp_path, known_sites, plans, inject_calls,
                test_text=""):
    """Synthesize a package tree for rule 5: a resilience/faults.py
    declaring ``known_sites``/``plans``, a module making the given
    inject calls, and an optional tests dir."""
    rdir = tmp_path / "pkg" / "resilience"
    rdir.mkdir(parents=True)
    sites = ", ".join(repr(s) for s in known_sites)
    plan_lines = ", ".join(f"{k!r}: {v!r}" for k, v in plans.items())
    (rdir / "faults.py").write_text(
        f"KNOWN_SITES = frozenset({{{sites}}})\n"
        f"NAMED_PLANS = {{{plan_lines}}}\n"
        "def inject(site):\n    pass\n")
    body = "from pkg.resilience import faults\n" + "".join(
        f"faults.inject({s!r})\n" for s in inject_calls)
    (tmp_path / "pkg" / "consumer.py").write_text(body)
    tdir = tmp_path / "tests"
    tdir.mkdir(exist_ok=True)
    (tdir / "test_x.py").write_text(test_text)
    return tmp_path / "pkg", tdir


def test_lint_rule5_dead_and_undeclared_and_unplanned_sites(tmp_path):
    """Rule 5: a KNOWN_SITES entry with no call site is dead; an
    inject() of an undeclared site is untargetable; a declared+called
    site with neither a named plan nor a test reference is
    undrillable."""
    pkg, tdir = _fault_tree(
        tmp_path,
        known_sites=["step", "ghost", "orphan"],
        plans={"p1": "step:error=OSError:nth=1"},
        inject_calls=["step", "rogue", "orphan"])
    problems = lint_instrumentation.run(pkg, tdir)
    assert any("ghost" in p and "dead site" in p for p in problems)
    assert any("rogue" in p and "KNOWN_SITES" in p for p in problems)
    assert any("orphan" in p and "no NAMED_PLANS rule" in p
               for p in problems)
    # 'step' is planned: not flagged
    assert not any("'step'" in p for p in problems)


def test_lint_rule5_test_reference_and_glob_plan_cover(tmp_path):
    """A quoted site string in tests/ counts as coverage, and a glob
    plan rule (ckpt_*) covers every site it matches."""
    pkg, tdir = _fault_tree(
        tmp_path,
        known_sites=["ckpt_write", "ckpt_commit", "serving"],
        plans={"io": "ckpt_*:error=OSError:p=0.5"},
        inject_calls=["ckpt_write", "ckpt_commit", "serving"],
        test_text='PLAN = "serving:error=RuntimeError:nth=2"\n'
                  'SITE = "serving"\n')
    problems = lint_instrumentation.run(pkg, tdir)
    assert problems == []


def test_lint_rule5_real_package_sites_all_live_and_drillable():
    """The live package: every KNOWN_SITES entry (including the
    elastic layer's host_death/coordinator) is threaded and covered —
    asserted through the full run() already, but pin the vocabulary
    parse here so a refactor that moves the tables fails loudly."""
    declared, plan_pats = lint_instrumentation._parse_fault_vocabulary(
        lint_instrumentation.PACKAGE / "resilience" / "faults.py")
    assert {"host_death", "coordinator", "step",
            "worker_step"} <= declared
    injected = lint_instrumentation._inject_sites(
        lint_instrumentation.PACKAGE)
    assert declared == set(injected)


def _metrics_tree(tmp_path, families, body="", watch=None, ops=None):
    """Synthesize a package tree for rule 6: an obs/metrics.py with a
    FAMILIES dict + registrations, an optional extra module, and
    optional tools/tpu_watch.py + docs/OPS.md consumers."""
    obs_dir = tmp_path / "pkg" / "obs"
    obs_dir.mkdir(parents=True)
    fams = ", ".join(f"{k!r}: {v!r}" for k, v in families.items())
    (obs_dir / "metrics.py").write_text(
        f"FAMILIES = {{{fams}}}\n"
        "class MetricsRegistry:\n    pass\n"
        "REGISTRY = MetricsRegistry()\n" + body)
    tools_dir = docs_dir = None
    if watch is not None:
        tools_dir = tmp_path / "tools"
        tools_dir.mkdir()
        (tools_dir / "tpu_watch.py").write_text(watch)
    if ops is not None:
        docs_dir = tmp_path / "docs"
        docs_dir.mkdir()
        (docs_dir / "OPS.md").write_text(ops)
    return tmp_path / "pkg", tools_dir, docs_dir


def test_lint_rule6_undeclared_dead_and_kind_mismatch(tmp_path):
    """Rule 6: a registration of an undeclared family is drift; a
    FAMILIES entry with no emit site is dead; a kind mismatch between
    declaration and emit site is flagged."""
    pkg, _t, _d = _metrics_tree(
        tmp_path,
        families={"dl4j_tpu_a_total": "counter",
                  "dl4j_tpu_ghost_total": "counter",
                  "dl4j_tpu_b_depth": "gauge"},
        body='A = REGISTRY.counter("dl4j_tpu_a_total", "doc")\n'
             'B = REGISTRY.counter("dl4j_tpu_b_depth", "doc")\n'
             'R = REGISTRY.gauge("dl4j_tpu_rogue", "doc")\n')
    problems = lint_instrumentation.run(pkg, tmp_path / "tests")
    assert any("dl4j_tpu_rogue" in p and "not declared" in p
               for p in problems)
    assert any("dl4j_tpu_ghost_total" in p and "no emit site" in p
               for p in problems)
    assert any("dl4j_tpu_b_depth" in p and "counter" in p
               for p in problems)
    assert not any("'dl4j_tpu_a_total'" in p for p in problems)


def test_lint_rule6_collector_tuples_and_aggregate_tables_count(
        tmp_path):
    """Pull-time collector tuples and AGGREGATE_FAMILIES dict entries
    are emit sites — they keep their declarations alive."""
    pkg, _t, _d = _metrics_tree(
        tmp_path,
        families={"dl4j_tpu_col_total": "counter",
                  "dl4j_tpu_agg_skew": "gauge"},
        body='def _collector():\n'
             '    yield ("dl4j_tpu_col_total", "counter", "d", [])\n'
             'AGGREGATE_FAMILIES = {"dl4j_tpu_agg_skew": "gauge"}\n')
    problems = lint_instrumentation.run(pkg, tmp_path / "tests")
    assert problems == []


def test_lint_rule6_consumer_tokens_must_resolve(tmp_path):
    """Every dl4j_tpu_* token in tpu_watch/OPS.md must name a declared
    family — exactly, via a histogram sample suffix, or as a prefix
    filter; an unresolvable token is a dashboard watching nothing."""
    pkg, tools_dir, docs_dir = _metrics_tree(
        tmp_path,
        families={"dl4j_tpu_lat_seconds": "histogram",
                  "dl4j_tpu_numerics_x": "gauge"},
        body='H = REGISTRY.histogram("dl4j_tpu_lat_seconds", "d")\n'
             'G = REGISTRY.gauge("dl4j_tpu_numerics_x", "d")\n',
        watch='KEYS = ("dl4j_tpu_lat_seconds_count",\n'
              '        "dl4j_tpu_numerics_")\n'
              'BAD = "dl4j_tpu_never_emitted_total"\n',
        ops="Watch `dl4j_tpu_lat_seconds` and the\n"
            "`dl4j_tpu_retired_family` counter.\n")
    problems = lint_instrumentation.run(pkg, tmp_path / "tests",
                                        tools_dir, docs_dir)
    assert any("tpu_watch" in p and "dl4j_tpu_never_emitted_total" in p
               for p in problems)
    assert any("OPS.md" in p and "dl4j_tpu_retired_family" in p
               for p in problems)
    # suffix + prefix + exact tokens all resolved
    assert not any("dl4j_tpu_lat_seconds" in p and "matches no" in p
                   for p in problems)
    assert not any("dl4j_tpu_numerics_" in p for p in problems)


def test_lint_rule6_real_package_families_all_declared():
    """The live package: the FAMILIES table parses and covers the
    standing families (pin the vocabulary so a refactor that moves
    the table fails loudly)."""
    fams = lint_instrumentation._parse_families(
        lint_instrumentation.PACKAGE / "obs" / "metrics.py")
    assert fams and fams["dl4j_tpu_step_latency_seconds"] == \
        "histogram"
    assert {"dl4j_tpu_collective_skew_seconds",
            "dl4j_tpu_fleet_snapshots_published_total",
            "dl4j_tpu_flight_recorder_dumps_total",
            "dl4j_tpu_mesh_epoch"} <= set(fams)
    sites = lint_instrumentation._family_emit_sites(
        lint_instrumentation.PACKAGE)
    assert set(sites) == set(fams)


def test_lint_catches_listener_side_device_reductions(tmp_path):
    """Rule 3: jnp / jax.tree.map reductions in listener/stats paths
    (the old StatsListener._prev_params pattern) are flagged; the
    numpy-over-leaves host histogram opt-in stays legal."""
    stats_dir = tmp_path / "train"
    stats_dir.mkdir()
    (stats_dir / "stats.py").write_text(
        "import jax\nimport jax.numpy as jnp\n"
        "def norms(params, prev):\n"
        "    upd = jax.tree.map(lambda a, b: a - b, params, prev)\n"
        "    return jnp.sqrt(sum(jnp.sum(jnp.square(l))\n"
        "                        for l in jax.tree.leaves(upd)))\n")
    (stats_dir / "listeners.py").write_text(
        "import jax\nimport numpy as np\n"
        "def hist(sub):\n"
        "    return np.concatenate([np.asarray(l).ravel()\n"
        "                           for l in jax.tree.leaves(sub)])\n")
    problems = lint_instrumentation.run(tmp_path)
    assert any("train/stats.py" in p and "jax.tree.map" in p
               for p in problems)
    assert any("train/stats.py" in p and "jnp." in p for p in problems)
    assert not any("train/listeners.py" in p for p in problems)


def test_lint_rule7_serving_jits_sentried_and_fed(tmp_path):
    """Rule 7: a raw jax.jit in serving/, a sentry.jit outside a
    _build_* builder, a builder without a WARMUP_FEEDS entry, a stale
    feed, and a warmup() that ignores the table are all flagged."""
    sdir = tmp_path / "serving"
    sdir.mkdir()
    (sdir / "bad.py").write_text(
        "import jax\n"
        "from deeplearning4j_tpu.perf import sentry\n"
        "from deeplearning4j_tpu import obs\n"
        "raw = jax.jit(lambda x: x)\n"
        "stray = sentry.jit(lambda x: x)\n"
        "obs.record_step('e', 0.0, 0.0, 0.0, 0.0)\n"
        "class S:\n"
        "    def _build_step_fn(self):\n"
        "        return sentry.jit(lambda x: x)\n"
        "    def _build_orphan_fn(self):\n"
        "        return sentry.jit(lambda x: x)\n"
        "    def warmup(self):\n"
        "        return None\n"
        "WARMUP_FEEDS = {'_build_step_fn': 'feed',\n"
        "                '_build_removed_fn': 'stale'}\n")
    problems = lint_instrumentation.run(tmp_path)
    assert any("bad.py:4" in p and "raw jax.jit" in p
               for p in problems)
    assert any("bad.py:5" in p and "outside a _build_" in p
               for p in problems)
    assert any("_build_orphan_fn" in p and "WARMUP_FEEDS" in p
               for p in problems)
    assert any("_build_removed_fn" in p and "stale" in p
               for p in problems)
    assert any("no warmup() reads WARMUP_FEEDS" in p
               for p in problems)


def test_lint_rule7_clean_serving_module_passes(tmp_path):
    sdir = tmp_path / "serving"
    sdir.mkdir()
    (sdir / "good.py").write_text(
        "from deeplearning4j_tpu.perf import sentry\n"
        "from deeplearning4j_tpu import obs\n"
        "WARMUP_FEEDS = {'_build_step_fn': 'feed'}\n"
        "class S:\n"
        "    def _build_step_fn(self):\n"
        "        def step(x):\n"
        "            return x\n"
        "        return sentry.jit(step)\n"
        "    def warmup(self):\n"
        "        assert WARMUP_FEEDS\n"
        "        obs.record_step('e', 0.0, 0.0, 0.0, 0.0)\n"
        "        return 0\n")
    assert not lint_instrumentation.run(tmp_path)


def test_lint_rule7_missing_feed_table(tmp_path):
    sdir = tmp_path / "serving"
    sdir.mkdir()
    (sdir / "nofeeds.py").write_text(
        "from deeplearning4j_tpu.perf import sentry\n"
        "from deeplearning4j_tpu import obs\n"
        "obs.record_step('e', 0.0, 0.0, 0.0, 0.0)\n"
        "class S:\n"
        "    def _build_step_fn(self):\n"
        "        return sentry.jit(lambda x: x)\n")
    problems = lint_instrumentation.run(tmp_path)
    assert any("no WARMUP_FEEDS dict literal" in p for p in problems)


def _spec_scheduler(tmp_path, text):
    sdir = tmp_path / "pkg" / "serving"
    sdir.mkdir(parents=True, exist_ok=True)
    (sdir / "scheduler.py").write_text(text)
    return tmp_path / "pkg"


def test_lint_rule10_spec_builder_needs_grid_and_feed(tmp_path):
    """Rule 10: a _build_spec* builder without a module-level SPEC_KS
    tuple literal (nothing pins admissible draft widths to the warmed
    k grid) and without a WARMUP_FEEDS entry is flagged on both
    counts."""
    pkg = _spec_scheduler(
        tmp_path,
        "WARMUP_FEEDS = {'_build_step_fn': 'feed'}\n"
        "class S:\n"
        "    def _build_step_fn(self):\n"
        "        return None\n"
        "    def _build_spec_step_fn(self):\n"
        "        return None\n"
        "    def warmup(self):\n"
        "        return WARMUP_FEEDS\n")
    problems = lint_instrumentation.run(pkg, tmp_path / "tests")
    assert any("no module-level SPEC_KS tuple literal" in p
               for p in problems)
    assert any("_build_spec_step_fn" in p
               and "outside the warmup table" in p for p in problems)


def test_lint_rule10_warmup_must_walk_spec_grid(tmp_path):
    """Rule 10: SPEC_KS exists and the builder is fed, but warmup()
    never references the grid — the warmed spec signatures and the
    admissible widths can silently drift apart."""
    pkg = _spec_scheduler(
        tmp_path,
        "SPEC_KS = (2, 4)\n"
        "WARMUP_FEEDS = {'_build_spec_step_fn': 'feed'}\n"
        "class S:\n"
        "    def _build_spec_step_fn(self):\n"
        "        return None\n"
        "    def warmup(self):\n"
        "        return WARMUP_FEEDS\n")
    problems = lint_instrumentation.run(pkg, tmp_path / "tests")
    assert any("warmup() never references SPEC_KS" in p
               for p in problems)


# the real scheduler's rule-8 SCOPE_SITES entries apply to any tree
# carrying serving/scheduler.py, so the clean synthetic module must
# define all three annotation points with devtime scopes
_CLEAN_SPEC_SCHEDULER = (
    "SPEC_KS = (2, 4, 8)\n"
    "WARMUP_FEEDS = {'_build_spec_step_fn': 'feed'}\n"
    "class S:\n"
    "    def _build_step_fn(self):\n"
    "        return devtime.scope('serve.decode')\n"
    "    def _build_spec_step_fn(self):\n"
    "        return devtime.scope('serve.spec')\n"
    "    def _build_suffix_admit_fn(self):\n"
    "        return devtime.scope('serve.admit')\n"
    "    def warmup(self):\n"
    "        for k in SPEC_KS:\n"
    "            pass\n"
    "        return WARMUP_FEEDS\n")


def test_lint_rule10_clean_scheduler_passes(tmp_path):
    pkg = _spec_scheduler(tmp_path, _CLEAN_SPEC_SCHEDULER)
    assert not lint_instrumentation.run(pkg, tmp_path / "tests")


def test_lint_rule10_consumer_spec_tokens(tmp_path):
    """Rule 10 consumer side: a spec/prefix family token in
    tpu_watch/OPS.md that matches no FAMILIES entry is flagged with
    the spec-decode message, and a consumer that watches prefix
    families but no dl4j_tpu_serving_spec_* family leaves the accept
    rate without a dashboard/runbook surface."""
    pkg, tools_dir, docs_dir = _metrics_tree(
        tmp_path,
        families={"dl4j_tpu_serving_spec_accept_rate": "histogram",
                  "dl4j_tpu_serving_prefix_hits_total": "counter"},
        body='H = REGISTRY.histogram('
             '"dl4j_tpu_serving_spec_accept_rate", "d")\n'
             'C = REGISTRY.counter('
             '"dl4j_tpu_serving_prefix_hits_total", "d")\n',
        watch='KEYS = ("dl4j_tpu_serving_spec_accept_rate",\n'
              '        "dl4j_tpu_serving_spec_ghost_total")\n',
        ops="Watch `dl4j_tpu_serving_prefix_hits_total` only.\n")
    _spec_scheduler(tmp_path, _CLEAN_SPEC_SCHEDULER)
    problems = lint_instrumentation.run(pkg, tmp_path / "tests",
                                        tools_dir, docs_dir)
    assert any("tpu_watch" in p
               and "dl4j_tpu_serving_spec_ghost_total" in p
               and "spec-decode metric" in p for p in problems)
    assert any("OPS.md" in p
               and "no dl4j_tpu_serving_spec_* family" in p
               for p in problems)
    assert not any("tpu_watch" in p
                   and "no dl4j_tpu_serving_spec_* family" in p
                   for p in problems)


def test_lint_rule8_missing_scope_annotation(tmp_path):
    """Rule 8: a SCOPE_SITES function stripped of its devtime.scope /
    named_scope call fails the lint — attribution would silently lose
    that path's layers into the op:* bucket."""
    nn_dir = tmp_path / "nn"
    nn_dir.mkdir()
    (nn_dir / "multilayer.py").write_text(
        "class MultiLayerNetwork:\n"
        "    def _forward(self, params, x):\n"
        "        return x\n")
    problems = lint_instrumentation.run(tmp_path)
    assert any("multilayer.py" in p and "_forward" in p
               and "devtime.scope" in p for p in problems), problems
    # annotated variant passes (either spelling)
    (nn_dir / "multilayer.py").write_text(
        "from deeplearning4j_tpu import obs\n"
        "class MultiLayerNetwork:\n"
        "    def _forward(self, params, x):\n"
        "        with obs.devtime.scope('layer_0.Dense'):\n"
        "            return x\n")
    assert not lint_instrumentation.run(tmp_path)
    (nn_dir / "multilayer.py").write_text(
        "import jax\n"
        "class MultiLayerNetwork:\n"
        "    def _forward(self, params, x):\n"
        "        with jax.named_scope('dl4j.layer_0.Dense'):\n"
        "            return x\n")
    assert not lint_instrumentation.run(tmp_path)


def test_lint_rule8_renamed_annotation_point(tmp_path):
    """A SCOPE_SITES entry whose function vanished is reported — the
    table must follow refactors, not rot."""
    zoo_dir = tmp_path / "zoo"
    zoo_dir.mkdir()
    (zoo_dir / "gpt.py").write_text(
        "class CausalTransformerLM:\n"
        "    def _renamed_decode(self):\n"
        "        pass\n")
    problems = lint_instrumentation.run(tmp_path)
    assert any("gpt.py" in p and "_token_logits" in p
               and "no longer exists" in p for p in problems)


def test_lint_rule8_gap_keys_must_resolve(tmp_path):
    """Every gap.<key> token OPS.md / tpu_watch references must be a
    GAP_KEYS member; devtime families must exist in FAMILIES."""
    pkg, tools_dir, docs_dir = _metrics_tree(
        tmp_path,
        {"dl4j_tpu_devtime_scope_share": "gauge"},
        body="REGISTRY.gauge('dl4j_tpu_devtime_scope_share', 'd',"
             " ('scope',))\n",
        ops="rank by gap.share, filter gap.pallas_candidate, and "
            "never gap.bogus_column\n")
    obs_dir = pkg / "obs"
    (obs_dir / "devtime.py").write_text(
        "GAP_KEYS = ('scope', 'share', 'pallas_candidate')\n")
    problems = lint_instrumentation.run(pkg, tools_dir=tools_dir,
                                        docs_dir=docs_dir)
    assert any("gap.bogus_column" in p and "GAP_KEYS" in p
               for p in problems), problems
    assert not any("gap.share" in p for p in problems)
    # deleting the devtime family block is caught
    (obs_dir / "metrics.py").write_text(
        "FAMILIES = {'dl4j_tpu_steps_total': 'counter'}\n"
        "class MetricsRegistry:\n    pass\n"
        "REGISTRY = MetricsRegistry()\n"
        "REGISTRY.counter('dl4j_tpu_steps_total', 'd')\n")
    problems = lint_instrumentation.run(pkg, tools_dir=tools_dir,
                                        docs_dir=docs_dir)
    assert any("no dl4j_tpu_devtime_* family" in p for p in problems)


def test_lint_rule8_real_package_annotation_points_hold():
    """The live package: every SCOPE_SITES function exists and is
    annotated, and the real OPS.md/tpu_watch gap keys resolve."""
    problems = [p for p in lint_instrumentation.run()
                if "devtime" in p or "gap." in p
                or "named_scope" in p]
    assert not problems, "\n".join(problems)


# -------------------------------------------------------------------------
# rule 9: Pallas kernels registered, contained, and contracted
# -------------------------------------------------------------------------

_CLEAN_KERNEL_MODULE = (
    "from jax.experimental import pallas as pl\n"
    "from deeplearning4j_tpu.obs import devtime\n"
    "def _rms_fwd_call(x):\n"
    "    return pl.pallas_call(None)(x)\n"
    "def rms_norm_reference(x, g):\n"
    "    return x\n"
    "def rms_norm(x, g):\n"
    "    with devtime.scope('ops.rms_norm'):\n"
    "        return _rms_fwd_call(x)\n")


def _kernel_registry_text(parity="tests/test_k.py::test_rms",
                          fallback="rms_norm_reference",
                          scope="ops.rms_norm",
                          name="rms_norm"):
    return (
        "KERNEL_REGISTRY = {\n"
        f"    '{name}': {{\n"
        "        'module': 'ops/fused_norms.py',\n"
        f"        'fallback': '{fallback}',\n"
        f"        'parity': '{parity}',\n"
        f"        'scope': '{scope}',\n"
        "        'closes': ('*.RMSNorm',),\n"
        "        'gate': 'fused_norm',\n"
        "    },\n"
        "}\n")


def _mk_kernel_tree(tmp_path, module=_CLEAN_KERNEL_MODULE,
                    registry=None, with_test=True):
    ops = tmp_path / "ops"
    ops.mkdir()
    # named fused_norms.py so the synthetic kernel resolves against
    # the real SCOPE_SITES table
    (ops / "fused_norms.py").write_text(module)
    (ops / "kernel_registry.py").write_text(
        registry if registry is not None else _kernel_registry_text())
    tests = tmp_path / "tests"
    tests.mkdir()
    if with_test:
        (tests / "test_k.py").write_text("def test_rms():\n    pass\n")
    return tests


def test_lint_rule9_clean_kernel_module_passes(tmp_path):
    tests = _mk_kernel_tree(tmp_path)
    problems = [p for p in lint_instrumentation.run(
        tmp_path, tests_dir=tests) if "kernel" in p.lower()
        or "pallas" in p.lower()]
    assert not problems, "\n".join(problems)


def test_lint_rule9_pallas_call_outside_ops(tmp_path):
    _mk_kernel_tree(tmp_path)
    (tmp_path / "rogue.py").write_text(
        "from jax.experimental import pallas as pl\n"
        "out = pl.pallas_call(None)(1)\n")
    problems = lint_instrumentation.run(tmp_path,
                                        tests_dir=tmp_path / "tests")
    assert any("rogue.py" in p and "pallas_call" in p
               for p in problems)


def test_lint_rule9_unregistered_public_kernel(tmp_path):
    tests = _mk_kernel_tree(
        tmp_path,
        module=_CLEAN_KERNEL_MODULE + (
            "def layer_norm(x, g):\n"
            "    with devtime.scope('ops.layer_norm'):\n"
            "        return _rms_fwd_call(x)\n"))
    problems = lint_instrumentation.run(tmp_path, tests_dir=tests)
    assert any("layer_norm" in p and "no KERNEL_REGISTRY entry" in p
               for p in problems)


def test_lint_rule9_stale_registry_entry(tmp_path):
    stale = (
        "    'gone_kernel': {\n"
        "        'module': 'ops/fused_norms.py',\n"
        "        'fallback': 'rms_norm_reference',\n"
        "        'parity': 'tests/test_k.py::test_rms',\n"
        "        'scope': 'ops.gone',\n"
        "        'closes': (),\n"
        "        'gate': 'always',\n"
        "    },\n}\n")
    base = _kernel_registry_text()
    assert base.endswith("}\n")
    tests = _mk_kernel_tree(tmp_path, registry=base[:-2] + stale)
    problems = lint_instrumentation.run(tmp_path, tests_dir=tests)
    assert any("gone_kernel" in p and "stale" in p for p in problems)


def test_lint_rule9_missing_fallback_parity_and_scope(tmp_path):
    tests = _mk_kernel_tree(
        tmp_path,
        registry=_kernel_registry_text(
            fallback="no_such_fn",
            parity="tests/test_k.py::test_missing",
            scope="ops.wrong_scope"))
    problems = lint_instrumentation.run(tmp_path, tests_dir=tests)
    assert any("no_such_fn" in p for p in problems)
    assert any("test_missing" in p and "parity" in p for p in problems)
    assert any("ops.wrong_scope" in p and "devtime.scope" in p
               for p in problems)


def test_lint_rule9_missing_registry_table(tmp_path):
    ops = tmp_path / "ops"
    ops.mkdir()
    (ops / "fused_norms.py").write_text(_CLEAN_KERNEL_MODULE)
    problems = lint_instrumentation.run(tmp_path)
    assert any("KERNEL_REGISTRY" in p and "missing" in p
               for p in problems)


# -------------------------------------------------------------------------
# rule 11: communication observatory — scoped collectives + comm plane
# -------------------------------------------------------------------------

def test_lint_rule11_unscoped_collective_emission(tmp_path):
    """Rule 11: a collective primitive called outside any scope-
    carrying function in a COLLECTIVE_SCOPE_PATHS module is flagged —
    its wire bytes could only land in the anonymous op:* bucket."""
    pdir = tmp_path / "parallel"
    pdir.mkdir()
    (pdir / "zero.py").write_text(
        "import jax\n"
        "from deeplearning4j_tpu.obs import devtime\n"
        "def scatter_mean(grads, axis_name):\n"
        "    with devtime.scope('zero.reduce_scatter'):\n"
        "        return jax.lax.psum_scatter(grads, axis_name)\n"
        "def gather(shards, axis_name):\n"
        "    return jax.lax.all_gather(shards, axis_name)\n")
    problems = lint_instrumentation.run(tmp_path)
    assert any("zero.py:7" in p and "all_gather" in p
               and "op:*" in p for p in problems), problems
    # the scoped site is NOT flagged
    assert not any("zero.py:5" in p for p in problems)
    # annotating the bare site clears the rule; a collective inside a
    # nested helper of a scoped function is covered too
    (pdir / "zero.py").write_text(
        "import jax\n"
        "from deeplearning4j_tpu.obs import devtime\n"
        "def scatter_mean(grads, axis_name):\n"
        "    with devtime.scope('zero.reduce_scatter'):\n"
        "        return jax.lax.psum_scatter(grads, axis_name)\n"
        "def gather(shards, axis_name):\n"
        "    def _pull(s):\n"
        "        return jax.lax.all_gather(s, axis_name)\n"
        "    with devtime.scope('zero.all_gather'):\n"
        "        return _pull(shards)\n")
    assert not lint_instrumentation.run(tmp_path)


def test_lint_rule11_module_level_collective_flagged(tmp_path):
    """A module-level (function-less) collective emission can never be
    covered by a scope — always flagged."""
    pdir = tmp_path / "parallel"
    pdir.mkdir()
    (pdir / "compression.py").write_text(
        "import jax\n"
        "TOTAL = jax.lax.psum(1, 'data')\n")
    problems = lint_instrumentation.run(tmp_path)
    assert any("compression.py:2" in p and "psum" in p
               for p in problems), problems


def test_lint_rule11_comm_family_block_and_consumer_tokens(tmp_path):
    """While obs/commtime.py exists: the dl4j_tpu_comm_* block must
    exist in FAMILIES, comm tokens in tpu_watch/OPS.md must resolve,
    and tpu_watch must watch at least one comm family."""
    pkg, tools_dir, docs_dir = _metrics_tree(
        tmp_path,
        families={"dl4j_tpu_comm_scope_wire_bytes": "gauge"},
        body='G = REGISTRY.gauge('
             '"dl4j_tpu_comm_scope_wire_bytes", "d")\n',
        watch='KEYS = ("dl4j_tpu_comm_scope_wire_bytes",\n'
              '        "dl4j_tpu_comm_ghost_total")\n',
        ops="Watch `dl4j_tpu_comm_retired_gauge` here.\n")
    (pkg / "obs" / "commtime.py").write_text("WIRE = 1\n")
    problems = lint_instrumentation.run(pkg, tmp_path / "tests",
                                        tools_dir, docs_dir)
    assert any("tpu_watch" in p and "dl4j_tpu_comm_ghost_total" in p
               and "comm metric" in p for p in problems), problems
    assert any("OPS.md" in p and "dl4j_tpu_comm_retired_gauge" in p
               for p in problems)
    assert not any("dl4j_tpu_comm_scope_wire_bytes" in p
                   for p in problems)
    # no comm family block at all while commtime.py exists → flagged,
    # and a tpu_watch with no comm token leaves the plane unwatched
    pkg2 = tmp_path / "p2"
    p2, tools2, docs2 = _metrics_tree(
        pkg2, families={"dl4j_tpu_steps_total": "counter"},
        body='C = REGISTRY.counter("dl4j_tpu_steps_total", "d")\n',
        watch='KEYS = ("dl4j_tpu_steps_total",)\n')
    (p2 / "obs" / "commtime.py").write_text("WIRE = 1\n")
    problems = lint_instrumentation.run(p2, pkg2 / "tests",
                                        tools2, docs2)
    assert any("no dl4j_tpu_comm_* family in" in p
               for p in problems), problems
    assert any("tpu_watch" in p
               and "no dl4j_tpu_comm_* family referenced" in p
               for p in problems)


def test_lint_rule11_gated_off_without_commtime(tmp_path):
    """A tree without obs/commtime.py gets no comm-plane demands (the
    collective-scope check still applies to existing modules)."""
    pkg, tools_dir, docs_dir = _metrics_tree(
        tmp_path, families={"dl4j_tpu_steps_total": "counter"},
        body='C = REGISTRY.counter("dl4j_tpu_steps_total", "d")\n',
        watch='KEYS = ("dl4j_tpu_steps_total",)\n')
    assert not lint_instrumentation.run(pkg, tmp_path / "tests",
                                        tools_dir, docs_dir)


def test_lint_rule11_real_package_collectives_scoped():
    """The live package: every explicit collective emission in the
    scanned parallel/ modules is scope-covered and the comm plane has
    its dashboard surface."""
    problems = [p for p in lint_instrumentation.run()
                if "comm" in p or "collective emission" in p]
    assert not problems, "\n".join(problems)


# rule 12: the elastic serving fleet — prefetch table lockstep,
# warm-before-lease ordering, and the router/fleet metric surface

# the synthetic scheduler must satisfy rules 7/8/10 on its own (rule 8
# SCOPE_SITES applies to any tree carrying serving/scheduler.py)
_FLEET_SCHED = (
    "SPEC_KS = (2,)\n"
    "WARMUP_FEEDS = {'_build_step_fn': 'f',\n"
    "                '_build_spec_step_fn': 'f',\n"
    "                '_build_suffix_admit_fn': 'f'}\n"
    "class S:\n"
    "    def _build_step_fn(self):\n"
    "        return devtime.scope('serve.decode')\n"
    "    def _build_spec_step_fn(self):\n"
    "        return devtime.scope('serve.spec')\n"
    "    def _build_suffix_admit_fn(self):\n"
    "        return devtime.scope('serve.admit')\n"
    "    def warmup(self):\n"
    "        for k in SPEC_KS:\n"
    "            pass\n"
    "        return WARMUP_FEEDS\n")

_CLEAN_FLEET = (
    "STARTUP_PREFETCH = ('_build_step_fn', '_build_spec_step_fn',\n"
    "                    '_build_suffix_admit_fn')\n"
    "class ServingReplica:\n"
    "    def start(self):\n"
    "        self.gateway.warmup()\n"
    "        self.coord.renew()\n"
    "        self.coord.start_auto_renew()\n")


def _fleet_tree(tmp_path, fleet_text, sched_text=_FLEET_SCHED):
    sdir = tmp_path / "pkg" / "serving"
    sdir.mkdir(parents=True, exist_ok=True)
    (sdir / "fleet.py").write_text(fleet_text)
    if sched_text is not None:
        (sdir / "scheduler.py").write_text(sched_text)
    return tmp_path / "pkg"


def test_lint_rule12_clean_fleet_passes(tmp_path):
    pkg = _fleet_tree(tmp_path, _CLEAN_FLEET)
    assert not lint_instrumentation.run(pkg, tmp_path / "tests")


def test_lint_rule12_prefetch_mirrors_warmup_feeds(tmp_path):
    """Rule 12: a scheduler builder missing from STARTUP_PREFETCH
    cold-traces on the respawned replica's first request; a prefetch
    entry naming no builder is stale — both directions flagged."""
    pkg = _fleet_tree(
        tmp_path,
        "STARTUP_PREFETCH = ('_build_step_fn',\n"
        "                    '_build_spec_step_fn',\n"
        "                    '_build_ghost_fn')\n"
        "class ServingReplica:\n"
        "    def start(self):\n"
        "        self.gateway.warmup()\n"
        "        self.coord.renew()\n")
    problems = lint_instrumentation.run(pkg, tmp_path / "tests")
    assert any("_build_suffix_admit_fn" in p
               and "missing from STARTUP_PREFETCH" in p
               for p in problems)
    assert any("'_build_ghost_fn'" in p and "stale" in p
               for p in problems)


def test_lint_rule12_missing_prefetch_table(tmp_path):
    pkg = _fleet_tree(
        tmp_path,
        "class ServingReplica:\n"
        "    def start(self):\n"
        "        self.gateway.warmup()\n"
        "        self.coord.renew()\n")
    problems = lint_instrumentation.run(pkg, tmp_path / "tests")
    assert any("no module-level STARTUP_PREFETCH" in p
               for p in problems)


def test_lint_rule12_lease_before_warm_flagged(tmp_path):
    """Rule 12 ordering: a ServingReplica.start that acquires its
    membership lease before warmup() advertises a cold replica to the
    router; a start that never warms is flagged too."""
    pkg = _fleet_tree(
        tmp_path,
        "STARTUP_PREFETCH = ('_build_step_fn',\n"
        "                    '_build_spec_step_fn',\n"
        "                    '_build_suffix_admit_fn')\n"
        "class ServingReplica:\n"
        "    def start(self):\n"
        "        self.coord.renew()\n"
        "        self.gateway.warmup()\n")
    problems = lint_instrumentation.run(pkg, tmp_path / "tests")
    assert any("lease before warmup()" in p for p in problems)
    pkg = _fleet_tree(
        tmp_path,
        "STARTUP_PREFETCH = ('_build_step_fn',\n"
        "                    '_build_spec_step_fn',\n"
        "                    '_build_suffix_admit_fn')\n"
        "class ServingReplica:\n"
        "    def start(self):\n"
        "        self.coord.start_auto_renew()\n")
    problems = lint_instrumentation.run(pkg, tmp_path / "tests")
    assert any("never calls warmup()" in p for p in problems)


def test_lint_rule12_fleet_metric_surface(tmp_path):
    """Rule 12 metric side: a declared-but-unemitted fleet family, a
    consumer token matching no family, a tpu_watch with no router
    family, and a FAMILIES table with no serving-fleet prefix at all
    are each flagged with fleet-specific messages."""
    pkg, tools_dir, docs_dir = _metrics_tree(
        tmp_path,
        families={"dl4j_tpu_router_requests_total": "counter",
                  "dl4j_tpu_router_sheds_total": "counter"},
        body='C = REGISTRY.counter('
             '"dl4j_tpu_router_requests_total", "d")\n',
        watch='KEYS = ("dl4j_tpu_router_requests_total",)\n',
        ops="Watch `dl4j_tpu_router_ghost_total` here.\n")
    _fleet_tree(tmp_path, _CLEAN_FLEET)
    problems = lint_instrumentation.run(pkg, tmp_path / "tests",
                                        tools_dir, docs_dir)
    assert any("dl4j_tpu_router_sheds_total" in p
               and "never emitted" in p for p in problems)
    assert any("OPS.md" in p and "dl4j_tpu_router_ghost_total" in p
               and "fleet metric" in p for p in problems)
    assert any("no dl4j_tpu_serving_fleet_* family" in p
               for p in problems)
    # the watch references a router family: not flagged for that
    assert not any("tpu_watch" in p
                   and "no dl4j_tpu_router_* family" in p
                   for p in problems)


def test_lint_rule12_watch_must_reference_router(tmp_path):
    pkg, tools_dir, docs_dir = _metrics_tree(
        tmp_path,
        families={"dl4j_tpu_router_requests_total": "counter",
                  "dl4j_tpu_serving_fleet_spawns_total": "counter"},
        body='C = REGISTRY.counter('
             '"dl4j_tpu_router_requests_total", "d")\n'
             'S = REGISTRY.counter('
             '"dl4j_tpu_serving_fleet_spawns_total", "d")\n',
        watch='KEYS = ("dl4j_tpu_serving_fleet_spawns_total",)\n')
    _fleet_tree(tmp_path, _CLEAN_FLEET)
    problems = lint_instrumentation.run(pkg, tmp_path / "tests",
                                        tools_dir, docs_dir)
    assert any("tpu_watch" in p
               and "no dl4j_tpu_router_* family" in p
               for p in problems)


def test_lint_rule12_gated_off_without_fleet_module(tmp_path):
    """A tree without serving/fleet.py gets no fleet-plane demands."""
    pkg, tools_dir, docs_dir = _metrics_tree(
        tmp_path, families={"dl4j_tpu_steps_total": "counter"},
        body='C = REGISTRY.counter("dl4j_tpu_steps_total", "d")\n',
        watch='KEYS = ("dl4j_tpu_steps_total",)\n')
    assert not lint_instrumentation.run(pkg, tmp_path / "tests",
                                        tools_dir, docs_dir)


def test_lint_rule12_real_package_fleet_contract():
    """The live package: the prefetch table mirrors the warmup feeds,
    start() warms before it leases, and the router/fleet families all
    have emit sites + dashboard coverage."""
    problems = [p for p in lint_instrumentation.run()
                if "fleet" in p or "STARTUP_PREFETCH" in p
                or "router" in p]
    assert not problems, "\n".join(problems)


def test_lint_rule9_real_package_kernels_registered():
    """The live package: every public kernel in ops/ is registered
    with a resolvable fallback/parity/scope, and no pallas_call lives
    outside ops/."""
    problems = [p for p in lint_instrumentation.run()
                if "pallas" in p.lower() or "KERNEL_REGISTRY" in p]
    assert not problems, "\n".join(problems)
