"""Examples smoke tests — every example runs end-to-end in FAST mode
(reference analog: dl4j-examples compiled+run in CI)."""
import os
import runpy
from pathlib import Path

import jax
import pytest

from conftest import requires_modern_jax as ring

EXAMPLES = Path(__file__).parent.parent / "examples"



@pytest.mark.parametrize("name", [
    "lenet_mnist", "char_rnn_textgen", "bert_finetune",
    "distributed_data_parallel", "samediff_autodiff",
    pytest.param("parallelism_modes", marks=ring),
    "hyperparameter_search", "transfer_learning",
    "model_serving", "pretrained_zoo",
    pytest.param("long_context_attention", marks=ring),
    "sharded_serving",
    pytest.param("causal_lm", marks=ring),
    "bert_pretrain_mlm",
])
def test_example_runs(name, monkeypatch, capsys):
    monkeypatch.setenv("DL4J_TPU_EXAMPLE_FAST", "1")
    runpy.run_path(str(EXAMPLES / f"{name}.py"), run_name="__main__")
    out = capsys.readouterr().out
    assert out.strip(), f"{name} produced no output"
