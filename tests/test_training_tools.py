"""Transfer learning, early stopping, stats/UI, profiler, crash report
(reference: TransferLearningTest, EarlyStoppingTest, StatsListener/UI,
OpProfiler, CrashReportingUtil — SURVEY §2.3/§5)."""
import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn import (FineTuneConfiguration,
                                   MultiLayerNetwork,
                                   NeuralNetConfiguration,
                                   TransferLearning,
                                   TransferLearningHelper)
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import (DenseLayer, FrozenLayer,
                                          OutputLayer)
from deeplearning4j_tpu.nn import updaters as upd


def _mk_net(n_in=8, hidden=16, classes=3, seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(upd.Adam(learning_rate=1e-2)).list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=classes, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _data(rng, n=64, n_in=8, classes=3):
    x = rng.normal(size=(n, n_in)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, n)]
    return DataSet(x, y)


# --- transfer learning ------------------------------------------------------

def test_transfer_freeze_and_replace_head(rng):
    net = _mk_net()
    ds = _data(rng)
    net.fit(ListDataSetIterator(ds, batch_size=32), epochs=3)
    w0 = np.asarray(net.params["layer_0"]["W"]).copy()

    new = (TransferLearning.builder(net)
           .fine_tune_configuration(
               FineTuneConfiguration(updater=upd.Sgd(learning_rate=1e-2)))
           .set_feature_extractor(1)            # freeze layers 0..1
           .remove_output_layer()
           .add_layer(OutputLayer(n_out=5, activation="softmax",
                                  loss="mcxent"))
           .build())
    assert isinstance(new.layers[0], FrozenLayer)
    assert isinstance(new.layers[1], FrozenLayer)
    assert new.layers[2].n_out == 5
    # frozen weights carried over exactly
    np.testing.assert_array_equal(
        np.asarray(new.params["layer_0"]["W"]), w0)

    y5 = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 64)]
    ds5 = DataSet(np.asarray(ds.features), y5)
    new.fit(ListDataSetIterator(ds5, batch_size=32), epochs=3)
    # frozen layer untouched by training, head moved
    np.testing.assert_array_equal(
        np.asarray(new.params["layer_0"]["W"]), w0)
    assert new.output(np.asarray(ds.features)).shape == (64, 5)


def test_transfer_nout_replace(rng):
    net = _mk_net()
    new = (TransferLearning.builder(net)
           .n_out_replace(1, 24, weight_init="xavier")
           .build())
    assert new.layers[1].n_out == 24
    assert np.asarray(new.params["layer_1"]["W"]).shape == (16, 24)
    assert np.asarray(new.params["layer_2"]["W"]).shape == (24, 3)
    out = new.output(rng.normal(size=(4, 8)).astype(np.float32))
    assert out.shape == (4, 3)


def test_transfer_helper_featurize(rng):
    net = _mk_net()
    ds = _data(rng)
    helper = TransferLearningHelper(net, frozen_until=1)
    feats = helper.featurize(ds)
    assert np.asarray(feats.features).shape == (64, 16)
    before = np.asarray(helper.net.params["layer_2"]["W"]).copy()
    helper.fit_featurized(ListDataSetIterator(feats, batch_size=32),
                          epochs=2)
    after = np.asarray(helper.net.params["layer_2"]["W"])
    assert np.abs(after - before).max() > 0
    # original (pre-freeze) net is untouched and still usable
    assert np.asarray(net.output(np.asarray(ds.features))).shape == (64, 3)
    # frozen part unchanged; full-net output consistent with tail
    tail_out = helper.unfrozen_mln().output(np.asarray(feats.features))
    full_out = helper.output(np.asarray(ds.features))
    np.testing.assert_allclose(np.asarray(tail_out),
                               np.asarray(full_out), rtol=1e-5)


# --- early stopping ---------------------------------------------------------

def test_early_stopping_max_epochs(rng):
    from deeplearning4j_tpu.train import (DataSetLossCalculator,
                                          EarlyStoppingConfiguration,
                                          EarlyStoppingTrainer,
                                          MaxEpochsTerminationCondition)

    net = _mk_net()
    train = ListDataSetIterator(_data(rng), batch_size=32)
    val = ListDataSetIterator(_data(rng, n=32), batch_size=32)
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(val),
        epoch_terminations=[MaxEpochsTerminationCondition(4)])
    result = EarlyStoppingTrainer(cfg, net, train).fit()
    assert result.total_epochs == 4
    assert result.best_model is not None
    assert result.best_model_epoch >= 0
    assert np.isfinite(result.best_model_score)
    assert len(result.score_vs_epoch) == 4


def test_early_stopping_patience(rng):
    from deeplearning4j_tpu.train import (
        DataSetLossCalculator, EarlyStoppingConfiguration,
        EarlyStoppingTrainer, MaxEpochsTerminationCondition,
        ScoreImprovementEpochTerminationCondition)

    net = _mk_net()
    # random labels ≠ learnable → score plateaus fast on tiny LR
    net.conf.updater = upd.Sgd(learning_rate=1e-8)
    net._build_optimizer()
    train = ListDataSetIterator(_data(rng), batch_size=64)
    val = ListDataSetIterator(_data(rng, n=32), batch_size=32)
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(val),
        epoch_terminations=[
            ScoreImprovementEpochTerminationCondition(
                patience=2, min_improvement=1e-4),
            MaxEpochsTerminationCondition(50)])
    result = EarlyStoppingTrainer(cfg, net, train).fit()
    assert result.total_epochs < 50
    assert "ScoreImprovement" in result.termination_details


def test_early_stopping_file_saver(tmp_path, rng):
    from deeplearning4j_tpu.train import (EarlyStoppingConfiguration,
                                          EarlyStoppingTrainer,
                                          LocalFileModelSaver,
                                          MaxEpochsTerminationCondition)

    net = _mk_net()
    train = ListDataSetIterator(_data(rng), batch_size=32)
    saver = LocalFileModelSaver(str(tmp_path))
    cfg = EarlyStoppingConfiguration(
        model_saver=saver,
        epoch_terminations=[MaxEpochsTerminationCondition(2)])
    result = EarlyStoppingTrainer(cfg, net, train).fit()
    best = saver.get_best_model()
    assert best is not None
    x = rng.normal(size=(4, 8)).astype(np.float32)
    assert np.asarray(best.output(x)).shape == (4, 3)


def test_early_stopping_requires_termination_condition(rng):
    from deeplearning4j_tpu.train import (EarlyStoppingConfiguration,
                                          EarlyStoppingTrainer)

    net = _mk_net()
    train = ListDataSetIterator(_data(rng), batch_size=32)
    with pytest.raises(ValueError, match="no termination"):
        EarlyStoppingTrainer(EarlyStoppingConfiguration(), net,
                             train).fit()


def test_early_stopping_throttled_eval_respects_max_epochs(rng):
    from deeplearning4j_tpu.train import (DataSetLossCalculator,
                                          EarlyStoppingConfiguration,
                                          EarlyStoppingTrainer,
                                          MaxEpochsTerminationCondition)

    net = _mk_net()
    train = ListDataSetIterator(_data(rng), batch_size=64)
    val = ListDataSetIterator(_data(rng, n=32), batch_size=32)
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(val),
        evaluate_every_n_epochs=3,
        epoch_terminations=[MaxEpochsTerminationCondition(4)])
    result = EarlyStoppingTrainer(cfg, net, train).fit()
    assert result.total_epochs == 4          # no overshoot to 6


# --- stats / UI -------------------------------------------------------------

def test_stats_listener_and_storage(rng):
    from deeplearning4j_tpu.train import InMemoryStatsStorage, StatsListener

    storage = InMemoryStatsStorage()
    net = _mk_net()
    net.set_listeners(StatsListener(storage, frequency=1,
                                    session_id="t1",
                                    collect_histograms=True))
    net.fit(ListDataSetIterator(_data(rng), batch_size=32), epochs=2)
    recs = storage.get_records("t1")
    assert len(recs) == 4           # 2 batches × 2 epochs
    assert all("score" in r and "param_norms" in r for r in recs)
    assert "update_ratios" in recs[-1]
    assert recs[-1]["histograms"]["layer_0"]["counts"]


def test_stats_listener_throttled_frequency_keeps_ratios(rng):
    from deeplearning4j_tpu.train import InMemoryStatsStorage, StatsListener

    storage = InMemoryStatsStorage()
    net = _mk_net()
    net.set_listeners(StatsListener(storage, frequency=2, session_id="f2"))
    net.fit(ListDataSetIterator(_data(rng), batch_size=16), epochs=2)
    recs = storage.get_records("f2")
    assert len(recs) >= 2
    assert any("update_ratios" in r for r in recs[1:])


def test_file_stats_storage_roundtrip(tmp_path, rng):
    from deeplearning4j_tpu.train import FileStatsStorage, StatsListener

    storage = FileStatsStorage(str(tmp_path))
    net = _mk_net()
    net.set_listeners(StatsListener(storage, session_id="s1"))
    net.fit(ListDataSetIterator(_data(rng), batch_size=64), epochs=1)
    again = FileStatsStorage(str(tmp_path))
    assert again.list_session_ids() == ["s1"]
    assert again.get_records("s1")


def test_ui_server(rng):
    from deeplearning4j_tpu.train import (InMemoryStatsStorage,
                                          StatsListener, UIServer)

    storage = InMemoryStatsStorage()
    net = _mk_net()
    net.set_listeners(StatsListener(storage, session_id="ui1"))
    net.fit(ListDataSetIterator(_data(rng), batch_size=64), epochs=1)
    ui = UIServer(port=0).attach(storage).start()
    try:
        html = urllib.request.urlopen(
            f"http://127.0.0.1:{ui.port}/?session=ui1",
            timeout=5).read().decode()
        assert "Training dashboard" in html and "svg" in html
        # live dashboard: polling script + chart containers present
        assert "setInterval(tick, 2000)" in html
        for cid in ("score", "ratios", "steptime", "phist", "uhist",
                    "ahist", "sys"):
            assert f'id="{cid}"' in html, cid
        data = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{ui.port}/json?session=ui1",
            timeout=5).read())
        assert data and data[0]["iteration"] >= 1
        sessions = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{ui.port}/sessions", timeout=5).read())
        assert "ui1" in sessions
    finally:
        ui.stop()


def test_stats_listener_full_collection(rng):
    """Histogram/activation/system-metric collection (reference
    StatsListener parity: per-layer param/update/activation histograms
    + memory/step-time/ETL system metrics)."""
    from deeplearning4j_tpu.data.iterators import AsyncDataSetIterator
    from deeplearning4j_tpu.train import (InMemoryStatsStorage,
                                          StatsListener)

    storage = InMemoryStatsStorage()
    net = _mk_net()
    ds = _data(rng)
    base = ListDataSetIterator(ds, batch_size=64)
    it = AsyncDataSetIterator(base, 2)
    net.set_listeners(StatsListener(
        storage, session_id="full1", collect_histograms=True,
        activation_sample=ds.features[:8], iterator=it))
    net.fit(it, epochs=2)
    recs = storage.get_records("full1")
    assert len(recs) >= 2
    last = recs[-1]
    # system metrics
    assert last["sys"]["mem_rss_mb"] > 0
    assert last["sys"]["step_time_ms"] > 0
    assert "etl_wait_ms" in last["sys"]
    # param + update histograms per layer
    for key in ("histograms", "update_histograms"):
        assert set(last[key]) == set(net.params), key
        h = next(iter(last[key].values()))
        assert sum(h["counts"]) > 0 and h["min"] < h["max"]
    # activation histograms: input + every layer
    ah = last["activation_histograms"]
    assert "input" in ah and len(ah) == len(net.layers) + 1
    # ratios present from the second record on
    assert all(v >= 0 for v in last["update_ratios"].values())


# --- profiler / crash report -----------------------------------------------

def test_op_profiler(rng):
    from deeplearning4j_tpu.utils import OpProfiler

    prof = OpProfiler.get_instance()
    prof.reset()
    prof.enabled = True
    net = _mk_net()
    ds = _data(rng)
    with prof.section("fit", sync=None):
        net.fit(ListDataSetIterator(ds, batch_size=64), epochs=1)
    prof.enabled = False
    stats = prof.stats()
    assert stats["fit"]["count"] == 1
    assert stats["fit"]["total_ms"] > 0
    report = prof.print_dashboard()
    assert "fit" in report


def test_performance_tracker():
    from deeplearning4j_tpu.utils import PerformanceTracker

    bw = PerformanceTracker.measure_bandwidth(1 << 20)
    assert bw["h2d_gbps"] > 0 and bw["d2h_gbps"] > 0


def test_crash_report_contents(tmp_path, rng):
    from deeplearning4j_tpu.utils import crashreport

    net = _mk_net()
    report = crashreport.generate_memory_status_report(net)
    assert "device memory" in report
    assert "DenseLayer" in report or "network" in report
    crashreport.crash_dump_output_directory(str(tmp_path))
    path = crashreport.write_memory_crash_dump(
        net, RuntimeError("RESOURCE_EXHAUSTED: fake"))
    assert path is not None
    assert "RESOURCE_EXHAUSTED" in open(path).read()
    assert crashreport.is_oom(RuntimeError("RESOURCE_EXHAUSTED: x"))
    assert not crashreport.is_oom(RuntimeError("bad shapes"))


def test_verbose_op_execution_mode(capsys):
    """Reference enableVerboseMode: every op execution printed; opcount
    stats when profiling is enabled (SURVEY §5 tracing)."""
    import numpy as np
    from deeplearning4j_tpu.ndarray import Nd4j
    from deeplearning4j_tpu.utils.profiler import OpProfiler
    from deeplearning4j_tpu.autodiff import SameDiff

    prof = OpProfiler.get_instance()
    prof.reset()
    prof.enable_verbose_mode(True)
    prof.enabled = True
    try:
        Nd4j.exec("softmax", Nd4j.create([1.0, 2.0]))
        sd = SameDiff.create()
        x = sd.var("x", np.ones((2, 2), np.float32))
        sd.math.exp(x, name="e")
        sd.output({}, ["e"])
        out = capsys.readouterr().out
        assert "[op] softmax" in out
        assert "[op] exp" in out
        assert prof.stats().get("op:softmax", {}).get("count", 0) >= 1
        # samediff fires at trace time -> op_trace: bucket
        assert prof.stats().get("op_trace:exp", {}).get("count", 0) >= 1
    finally:
        prof.enable_verbose_mode(False)
        prof.enabled = False
        prof.reset()


def test_environment_flag_registry(monkeypatch):
    """Tier-2 runtime flags (reference ND4JEnvironmentVars analog)."""
    from deeplearning4j_tpu import environment as env
    assert env.get_flag("DL4J_TPU_UI_PORT") == 9000
    monkeypatch.setenv("DL4J_TPU_UI_PORT", "8123")
    assert env.get_flag("DL4J_TPU_UI_PORT") == 8123
    monkeypatch.setenv("DL4J_TPU_VERBOSE_OPS", "true")
    assert env.get_flag("DL4J_TPU_VERBOSE_OPS") is True
    desc = env.describe()
    assert "DL4J_TPU_DEFAULT_DTYPE" in desc and "8123" in desc
    # apply_startup_flags applies verbose to the profiler singleton
    from deeplearning4j_tpu.utils.profiler import OpProfiler
    prof = OpProfiler.get_instance()
    was = prof.verbose
    try:
        env.apply_startup_flags()
        assert prof.verbose is True
    finally:
        prof.verbose = was
