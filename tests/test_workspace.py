"""Memory workspace tests (allocation tracking + leak debug mode).

Reference analog: workspace tests under nd4j-backends
(``org.nd4j.linalg.workspace.*`` — scoped enter/leave, leak DebugMode,
AllocationsTracker counters).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.ndarray import Nd4j
from deeplearning4j_tpu.utils import (
    MemoryWorkspace, WorkspaceConfiguration, AllocationsTracker,
    get_workspace_manager, scope_out_of_workspaces)


def test_tracks_allocations_inside_scope():
    ws = MemoryWorkspace("WS_T1")
    with ws:
        a = Nd4j.zeros((4, 4))
        b = a.add(1.0)
    assert ws.total_allocations >= 2
    assert ws.total_bytes >= 2 * 4 * 4 * 4
    # outside the scope: no tracking
    n0 = ws.total_allocations
    _ = Nd4j.zeros((8,))
    assert ws.total_allocations == n0


def test_leak_detection():
    ws = MemoryWorkspace("WS_LEAK")
    kept = {}
    with ws:
        kept["x"] = Nd4j.ones((3, 3))      # escapes the scope
        _tmp = Nd4j.ones((2,))             # dies with the scope
    del _tmp
    leaks = ws.leaked_arrays()
    assert any(shape == (3, 3) for _, shape in leaks)
    with pytest.raises(RuntimeError, match="outlive"):
        ws.assert_no_leaks()
    # detach() is the sanctioned way out: the copy is NOT tracked,
    # so keeping it past the scope is clean
    ws2 = MemoryWorkspace("WS_LEAK2")
    with ws2:
        y = MemoryWorkspace.detach(Nd4j.ones((3, 3)))
    ws2.assert_no_leaks()          # y escapes legally
    assert float(y.sum_number()) == 9.0


def test_no_leaks_passes_when_clean():
    ws = MemoryWorkspace("WS_CLEAN")
    with ws:
        s = float(Nd4j.ones((4,)).sum_number())
    ws.assert_no_leaks()
    assert s == 4.0


def test_cyclic_generations():
    ws = MemoryWorkspace("WS_CYCLE")
    for _ in range(3):
        with ws:
            Nd4j.zeros((2,))
    assert ws.generation == 3
    assert not ws.is_scope_active()


def test_manager_and_tracker():
    mgr = get_workspace_manager()
    ws = mgr.get_workspace_for_current_thread(
        "WS_MGR", WorkspaceConfiguration(initial_size=1 << 20))
    assert mgr.get_workspace_for_current_thread("WS_MGR") is ws
    with mgr.get_and_activate_workspace("WS_MGR"):
        Nd4j.ones((16,))
    assert not ws.is_scope_active()   # with-exit closed the scope
    rep = AllocationsTracker.instance().report()
    assert "WS_MGR" in rep
    mgr.destroy_workspace("WS_MGR")
    assert mgr.get_workspace_for_current_thread("WS_MGR") is not ws


def test_scope_out_of_workspaces():
    ws = MemoryWorkspace("WS_OUT")
    with ws:
        n0 = ws.total_allocations
        with scope_out_of_workspaces():
            Nd4j.zeros((64,))              # not tracked
        assert ws.total_allocations == n0
        Nd4j.zeros((2,))                   # tracked again
        assert ws.total_allocations == n0 + 1


def test_nested_workspaces_track_innermost():
    outer = MemoryWorkspace("WS_OUTER")
    inner = MemoryWorkspace("WS_INNER")
    with outer:
        with inner:
            Nd4j.zeros((4,))
        assert inner.total_allocations == 1
        # current policy: innermost scope owns the allocation
        assert outer.total_allocations == 0


def test_get_and_activate_enters_scope():
    """Regression: get_and_activate must actually activate (reference
    getAndActivateWorkspace), and notify_scope_left closes it."""
    mgr = get_workspace_manager()
    ws = mgr.get_and_activate_workspace("WS_ACT")
    try:
        assert ws.is_scope_active()
        Nd4j.ones((4,))
        assert ws.total_allocations == 1
    finally:
        ws.notify_scope_left()
    assert not ws.is_scope_active()
    with pytest.raises(RuntimeError, match="not active"):
        ws.notify_scope_left()        # double close: clear error
    mgr.destroy_workspace("WS_ACT")


def test_scope_out_does_not_disturb_other_threads():
    """Regression: scope_out_of_workspaces on one thread must not
    disable tracking on another thread's active workspace."""
    import threading
    ws = MemoryWorkspace("WS_THREAD")
    inside = threading.Event()
    release = threading.Event()

    def other():
        with scope_out_of_workspaces():
            inside.set()
            release.wait(timeout=10)

    t = threading.Thread(target=other)
    with ws:
        t.start()
        assert inside.wait(timeout=10)
        Nd4j.ones((2,))               # tracked despite thread B's scope-out
        release.set()
        t.join()
    assert ws.total_allocations == 1


def test_nested_reentry_of_same_workspace():
    """Regression (ADVICE r1): a nested `with ws:` on an already-active
    workspace must not pop the scope at the inner block's exit — the
    outer block keeps tracking, and the outer exit closes cleanly."""
    ws = MemoryWorkspace("WS_REENTER")
    with ws:
        with ws:                      # idempotent re-entry
            Nd4j.zeros((4,))
        assert ws.is_scope_active()   # outer scope still active
        Nd4j.zeros((4,))              # still tracked, no RuntimeError
        assert ws.total_allocations == 2
    assert not ws.is_scope_active()
    assert ws.generation == 1         # one real enter/leave cycle


def test_nested_get_and_activate_pairs():
    """Regression (r2 review): two stacked get_and_activate/
    notify_scope_left pairs must nest — the inner close may not pop the
    outer activation's scope."""
    mgr = get_workspace_manager()
    outer = mgr.get_and_activate_workspace("WS_NEST2")
    inner = mgr.get_and_activate_workspace("WS_NEST2")
    assert inner is outer
    inner.notify_scope_left()
    assert outer.is_scope_active()
    outer.notify_scope_left()
    assert not outer.is_scope_active()
    mgr.destroy_workspace("WS_NEST2")
