"""End-to-end slice (SURVEY §7 step 3 / BASELINE config #1):
MNIST reader → LeNet config → fit → Evaluation → checkpoint/resume.
Reference analog: dl4j-examples LeNetMnistExample + IntegrationTestsDL4J.
"""
import numpy as np

from deeplearning4j_tpu.data.mnist import MnistDataSetIterator
from deeplearning4j_tpu.serialization import ModelSerializer
from deeplearning4j_tpu.zoo import LeNet


def test_lenet_mnist_end_to_end(tmp_path):
    train_it = MnistDataSetIterator(batch_size=64, train=True,
                                    n_examples=2048)
    test_it = MnistDataSetIterator(batch_size=256, train=False,
                                   n_examples=512)
    net = LeNet(num_classes=10, seed=123).init()
    assert net.num_params() > 100_000

    net.fit(train_it, epochs=2)
    e = net.evaluate(test_it)
    # synthetic digits are separable; LeNet should nail them quickly
    assert e.accuracy() > 0.97, e.stats()

    path = tmp_path / "lenet.zip"
    ModelSerializer.write_model(net, path)
    net2 = ModelSerializer.restore_multi_layer_network(path)
    x = next(iter(test_it)).features[:8]
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(net2.output(x)), rtol=1e-5)
