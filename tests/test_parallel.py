"""Distributed tests on the virtual 8-device CPU mesh.

Reference analogs: ParallelWrapperTest (workers on CPU backend),
DelayedModelParameterServerTest-style in-process multi-node simulation
(SURVEY §4 "multi-node without a cluster").
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import (requires_modern_jax,
                      requires_shard_map)

from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn import MultiLayerNetwork, \
    NeuralNetConfiguration
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.parallel import (
    AdaptiveThresholdAlgorithm, EncodedGradientsAccumulator,
    ParallelInference, ParallelWrapper, decode_bitmap, decode_threshold,
    encode_bitmap, encode_threshold, make_mesh,
)
from deeplearning4j_tpu.parallel.ring_attention import (
    ring_self_attention, ulysses_attention)

pytestmark = [
    pytest.mark.skipif(len(jax.devices()) < 8,
                       reason="needs 8 virtual devices"),
    requires_shard_map,
]


def _net(seed=42):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(upd.Adam(learning_rate=0.05))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _toy_data(n=256, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y_idx = (x.sum(1) > 0).astype(int)
    y = np.eye(2, dtype=np.float32)[y_idx]
    return DataSet(x, y)

def test_make_mesh_shapes():
    m = make_mesh({"data": 4, "model": 2})
    assert m.devices.shape == (4, 2)
    m2 = make_mesh({"data": -1})
    assert m2.devices.size == len(jax.devices())
    with pytest.raises(ValueError):
        make_mesh({"data": 16})


def test_parallel_wrapper_sync_learns():
    net = _net()
    w = (ParallelWrapper.builder(net).workers(8).build())
    it = ListDataSetIterator(_toy_data(), batch_size=64)
    w.fit(it, epochs=10)
    assert net.score() < 0.3
    ds = _toy_data(64, seed=3)
    preds = np.asarray(net.output(ds.features)).argmax(1)
    assert (preds == ds.labels.argmax(1)).mean() > 0.9


def test_sync_matches_single_device_step():
    """DP over 8 devices must equal single-device full-batch training
    (same global batch, sync allreduce semantics)."""
    ds = _toy_data(64)
    net_a = _net()
    net_a.fit(ds.features, ds.labels)
    net_b = _net()
    w = ParallelWrapper.builder(net_b).workers(8).build()
    it = ListDataSetIterator(ds, batch_size=64)
    w.fit(it, epochs=1)
    for ka in net_a.params:
        for kk in net_a.params[ka]:
            np.testing.assert_allclose(
                np.asarray(net_a.params[ka][kk]),
                np.asarray(net_b.params[ka][kk]), rtol=2e-3, atol=2e-5)


def test_parallel_wrapper_averaging():
    net = _net()
    w = (ParallelWrapper.builder(net).workers(8)
         .training_mode(ParallelWrapper.AVERAGING)
         .averaging_frequency(2).build())
    it = ListDataSetIterator(_toy_data(), batch_size=64)
    w.fit(it, epochs=6)
    ds = _toy_data(64, seed=3)
    preds = np.asarray(net.output(ds.features)).argmax(1)
    assert (preds == ds.labels.argmax(1)).mean() > 0.85


def test_averaging_mode_averages_updater_state():
    """averageUpdaters=true (reference Builder default): at each
    averaging round the optimizer MOMENTS are pmean'd with the params,
    and _sync_back folds the replica mean — not replica 0's moments
    (VERDICT r3 #9)."""
    net = _net()
    w = (ParallelWrapper.builder(net).workers(8)
         .training_mode(ParallelWrapper.AVERAGING)
         .averaging_frequency(1).build())
    assert w.average_updaters        # reference default
    it = ListDataSetIterator(_toy_data(), batch_size=64)
    w.fit(it, epochs=1)
    # frequency=1: every step averaged → replicas agree on moments
    p_stack, o_stack = w._dp_state
    for leaf in jax.tree.leaves(o_stack):
        a = np.asarray(leaf)
        if np.issubdtype(a.dtype, np.floating):
            np.testing.assert_allclose(a, np.broadcast_to(a[:1], a.shape),
                                       rtol=1e-6, atol=1e-7)
    # and the net got the replica mean
    for got, stack in zip(jax.tree.leaves(net.opt_state),
                          jax.tree.leaves(o_stack)):
        a = np.asarray(stack)
        want = a.mean(0) if np.issubdtype(a.dtype, np.floating) else a[0]
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-6, atol=1e-7)


def test_averaging_mode_updaters_opt_out():
    """average_updaters=False (reference averageUpdaters(false)):
    moments stay replica-local and _sync_back keeps replica 0's."""
    net = _net()
    w = (ParallelWrapper.builder(net).workers(8)
         .training_mode(ParallelWrapper.AVERAGING)
         .averaging_frequency(2).average_updaters(False).build())
    it = ListDataSetIterator(_toy_data(), batch_size=64)
    w.fit(it, epochs=2)
    p_stack, o_stack = w._dp_state
    # shards differ → at least one float moment leaf diverges
    diverged = any(
        np.issubdtype(np.asarray(l).dtype, np.floating)
        and not np.allclose(np.asarray(l),
                            np.broadcast_to(np.asarray(l)[:1],
                                            np.asarray(l).shape))
        for l in jax.tree.leaves(o_stack))
    assert diverged
    for got, stack in zip(jax.tree.leaves(net.opt_state),
                          jax.tree.leaves(o_stack)):
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(stack)[0])


def test_parallel_wrapper_encoded():
    net = _net()
    acc = EncodedGradientsAccumulator(
        AdaptiveThresholdAlgorithm(initial_threshold=1e-4))
    w = (ParallelWrapper.builder(net).workers(8)
         .gradients_accumulator(acc).build())
    it = ListDataSetIterator(_toy_data(), batch_size=64)
    w.fit(it, epochs=10)
    ds = _toy_data(64, seed=3)
    preds = np.asarray(net.output(ds.features)).argmax(1)
    assert (preds == ds.labels.argmax(1)).mean() > 0.85


def test_parallel_wrapper_async_converges_vs_sync():
    """ASYNC mode (reference SharedTrainingMaster async exchange,
    staleness-1 peer updates + local residuals) must converge to the
    same quality as SYNC on the toy task."""
    net_async = _net()
    acc = EncodedGradientsAccumulator(
        AdaptiveThresholdAlgorithm(initial_threshold=1e-4))
    w = (ParallelWrapper.builder(net_async).workers(8)
         .training_mode(ParallelWrapper.ASYNC)
         .gradients_accumulator(acc).build())
    it = ListDataSetIterator(_toy_data(), batch_size=64)
    w.fit(it, epochs=10)

    net_sync = _net()
    ws = ParallelWrapper.builder(net_sync).workers(8).build()
    ws.fit(ListDataSetIterator(_toy_data(), batch_size=64), epochs=10)

    ds = _toy_data(64, seed=3)
    acc_async = (np.asarray(net_async.output(ds.features)).argmax(1)
                 == ds.labels.argmax(1)).mean()
    acc_sync = (np.asarray(net_sync.output(ds.features)).argmax(1)
                == ds.labels.argmax(1)).mean()
    assert acc_async > 0.85, acc_async
    assert acc_async >= acc_sync - 0.1, (acc_async, acc_sync)


def test_async_exchange_staleness_semantics():
    """Step 1 must deliver ONLY the replica's own update (peers'
    in-flight queues are empty); step 2 must deliver step-1 peer
    messages — the one-step staleness contract."""
    from jax.sharding import Mesh
    from deeplearning4j_tpu.parallel._compat import shard_map
    from jax.sharding import PartitionSpec as P

    acc = EncodedGradientsAccumulator(
        AdaptiveThresholdAlgorithm(initial_threshold=0.5))
    devs = np.array(jax.devices()[:2])
    mesh = Mesh(devs, ("data",))
    g = jnp.stack([jnp.full((4,), 1.0), jnp.full((4,), -1.0)])  # per-dev

    def two_steps(g):
        g = g[0]
        st = acc.init_async_state(g)
        out1, st = acc.exchange_async(g, st, "data")
        out2, st = acc.exchange_async(jnp.zeros_like(g), st, "data")
        return out1[None], out2[None]

    o1, o2 = shard_map(
        two_steps, mesh=mesh, in_specs=(P("data"),),
        out_specs=(P("data"), P("data")), check_vma=False)(g)
    tau = 0.5
    # step 1: own update only, averaged over 2 devices: ±tau/2
    np.testing.assert_allclose(np.asarray(o1[0]), tau / 2, atol=1e-6)
    np.testing.assert_allclose(np.asarray(o1[1]), -tau / 2, atol=1e-6)
    # step 2: peer's step-1 message arrives (grad now zero, residual
    # 1-tau stays below the adapted threshold)
    np.testing.assert_allclose(np.asarray(o2[0]),
                               np.asarray(-o2[1]), atol=1e-6)
    assert abs(float(o2[0][0])) > 0  # something did arrive late


def test_threshold_encode_decode_roundtrip():
    g = jnp.asarray(np.random.default_rng(0).normal(size=(64,)) * 0.01)
    tau = 0.005
    sign, residual = encode_threshold(g, tau)
    decoded = decode_threshold(sign, tau)
    np.testing.assert_allclose(np.asarray(decoded + residual),
                               np.asarray(g), rtol=1e-6)
    # sparsity: only |g|>tau encoded
    assert (np.asarray(sign) != 0).sum() == (np.abs(np.asarray(g)) >
                                             tau).sum()


def test_bitmap_pack_roundtrip():
    rng = np.random.default_rng(1)
    sign = jnp.asarray(rng.choice([-1, 0, 1], size=(37,)), jnp.int8)
    pos, neg = encode_bitmap(sign)
    # 16x compression: 2 bitmaps of ceil(37/8)=5 bytes vs 148 bytes f32
    assert pos.size == 5 and neg.size == 5
    out = decode_bitmap(pos, neg, 37)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(sign))


@requires_modern_jax
def test_ring_attention_matches_full():
    mesh = make_mesh({"seq": 8})
    b, t, h, d = 2, 32, 4, 8
    rng = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (b, t, h, d))
    k = jax.random.normal(kk, (b, t, h, d))
    v = jax.random.normal(kv, (b, t, h, d))
    from deeplearning4j_tpu.nn.layers.attention import \
        scaled_dot_attention
    full = scaled_dot_attention(q, k, v)
    ring = ring_self_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(full), np.asarray(ring),
                               rtol=2e-4, atol=2e-5)


@requires_modern_jax
def test_ring_attention_masked():
    mesh = make_mesh({"seq": 8})
    b, t, h, d = 1, 16, 2, 4
    key = jax.random.PRNGKey(1)
    q = jax.random.normal(key, (b, t, h, d))
    mask = (jnp.arange(t)[None, :] < 10).astype(jnp.float32)
    from deeplearning4j_tpu.nn.layers.attention import \
        scaled_dot_attention
    full = scaled_dot_attention(q, q, q, mask=mask)
    ring = ring_self_attention(q, q, q, mesh, mask=mask)
    np.testing.assert_allclose(np.asarray(full), np.asarray(ring),
                               rtol=2e-4, atol=2e-5)


def _multi_io_graph(seed=1):
    from deeplearning4j_tpu.nn import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.vertices import ElementWiseVertex
    from deeplearning4j_tpu.nn import updaters as upd
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(upd.Adam(learning_rate=0.05))
            .graph_builder()
            .add_inputs("a", "b")
            .add_layer("da", DenseLayer(n_out=8, activation="tanh"), "a")
            .add_layer("db", DenseLayer(n_out=8, activation="tanh"), "b")
            .add_vertex("sum", ElementWiseVertex(op="add"), "da", "db")
            .add_layer("out1", OutputLayer(n_out=2, activation="softmax",
                                           loss="mcxent"), "sum")
            .add_layer("out2", OutputLayer(n_out=1,
                                           activation="identity",
                                           loss="mse"), "sum")
            .set_outputs("out1", "out2")
            .set_input_types(a=InputType.feed_forward(3),
                             b=InputType.feed_forward(3))
            .build())
    return ComputationGraph(conf).init()


def _multi_io_data(n=256, batch=32):
    from deeplearning4j_tpu.data.dataset import MultiDataSet
    rng = np.random.default_rng(0)
    xa = rng.normal(size=(n, 3)).astype(np.float32)
    xb = rng.normal(size=(n, 3)).astype(np.float32)
    y1 = np.eye(2, dtype=np.float32)[((xa + xb).sum(1) > 0).astype(int)]
    y2 = (xa - xb).sum(1, keepdims=True).astype(np.float32)
    return [MultiDataSet([xa[i:i + batch], xb[i:i + batch]],
                         [y1[i:i + batch], y2[i:i + batch]])
            for i in range(0, n, batch)]


@pytest.mark.parametrize("mode", [ParallelWrapper.SYNC,
                                  ParallelWrapper.ENCODED,
                                  ParallelWrapper.AVERAGING,
                                  ParallelWrapper.ASYNC])
def test_parallel_wrapper_multi_io_graph(mode):
    """DP over a 2-input/2-output ComputationGraph in all four modes
    (VERDICT r2 #5 — the reference ParallelWrapper handles arbitrary
    ComputationGraphs): every feature/label leaf shards over the data
    axis."""
    net = _multi_io_graph()
    data = _multi_io_data()
    wrapper = ParallelWrapper(net, mode=mode, averaging_frequency=2,
                              prefetch_buffer=0)
    wrapper.fit(data, epochs=4)
    assert np.isfinite(net.score_)
    assert net.score_ < 1.0, net.score_
    # trained params still produce well-formed multi-output inference
    o1, o2 = net.output(data[0].features[0], data[0].features[1])
    assert o1.shape == (32, 2) and o2.shape == (32, 1)


def test_training_masters_multi_io_graph():
    """Both TrainingMaster strategies drive a multi-io graph (single
    process; the cross-process path shares the same wrapper step)."""
    from deeplearning4j_tpu.parallel import (
        ParameterAveragingTrainingMaster, SharedTrainingMaster)
    from deeplearning4j_tpu.parallel.master import SparkComputationGraph
    for master in (ParameterAveragingTrainingMaster.Builder(32)
                   .averaging_frequency(2).build(),
                   SharedTrainingMaster.Builder(32).build()):
        net = _multi_io_graph()
        trainer = SparkComputationGraph(net, master)
        trainer.fit(_multi_io_data(), epochs=3)
        assert np.isfinite(net.score_) and net.score_ < 1.2


def test_do_evaluation_multi_io_graph():
    """doEvaluation over a 2-input/2-output graph: list features feed
    output(*x), evaluation runs on the first output/label pair."""
    from deeplearning4j_tpu.parallel import \
        ParameterAveragingTrainingMaster
    from deeplearning4j_tpu.parallel.master import SparkComputationGraph
    from deeplearning4j_tpu.eval_.evaluation import Evaluation
    net = _multi_io_graph()
    data = _multi_io_data(n=64, batch=32)
    trainer = SparkComputationGraph(
        net, ParameterAveragingTrainingMaster.Builder(32).build())
    ev, = trainer.do_evaluation(data, Evaluation())
    assert ev.count == 64
    assert 0.0 <= ev.accuracy() <= 1.0


@requires_modern_jax
def test_ring_attention_causal_matches_full():
    """Causal ring attention (VERDICT r2 #2): per-ring-step block
    offsets must land the causal diagonal exactly — the long-context
    causal-LM training path."""
    mesh = make_mesh({"seq": 8})
    b, t, h, d = 2, 32, 4, 8
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(kq, (b, t, h, d))
    k = jax.random.normal(kk, (b, t, h, d))
    v = jax.random.normal(kv, (b, t, h, d))
    from deeplearning4j_tpu.nn.layers.attention import \
        scaled_dot_attention
    full = scaled_dot_attention(q, k, v, causal=True)
    ring = ring_self_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(full), np.asarray(ring),
                               rtol=2e-4, atol=1e-5)


@requires_modern_jax
def test_ring_attention_causal_gradients_match():
    """Backward ring (dk/dv accumulators traveling with their kv block)
    must match autodiff through dense causal attention."""
    mesh = make_mesh({"seq": 8})
    b, t, h, d = 1, 32, 2, 8
    kq, kk, kv, kc = jax.random.split(jax.random.PRNGKey(4), 4)
    q = jax.random.normal(kq, (b, t, h, d))
    k = jax.random.normal(kk, (b, t, h, d))
    v = jax.random.normal(kv, (b, t, h, d))
    co = jax.random.normal(kc, (b, t, h, d))
    from deeplearning4j_tpu.nn.layers.attention import \
        scaled_dot_attention

    g_ring = jax.grad(
        lambda q, k, v: jnp.sum(
            ring_self_attention(q, k, v, mesh, causal=True) * co),
        argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(
        lambda q, k, v: jnp.sum(
            scaled_dot_attention(q, k, v, causal=True) * co),
        argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-5)


@requires_modern_jax
def test_ring_attention_masked_gradients_match():
    mesh = make_mesh({"seq": 8})
    b, t, h, d = 1, 16, 2, 4
    key = jax.random.PRNGKey(5)
    q = jax.random.normal(key, (b, t, h, d))
    co = jax.random.normal(jax.random.PRNGKey(6), (b, t, h, d))
    mask = (jnp.arange(t)[None, :] < 11).astype(jnp.float32)
    from deeplearning4j_tpu.nn.layers.attention import \
        scaled_dot_attention

    g_ring = jax.grad(lambda x: jnp.sum(
        ring_self_attention(x, x, x, mesh, mask=mask) * co))(q)
    g_full = jax.grad(lambda x: jnp.sum(
        scaled_dot_attention(x, x, x, mask=mask) * co))(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               rtol=2e-4, atol=2e-5)


@requires_modern_jax
def test_ring_attention_causal_masked():
    """Causal + key-mask together (padded causal LM batch)."""
    mesh = make_mesh({"seq": 8})
    b, t, h, d = 2, 24, 2, 4
    q = jax.random.normal(jax.random.PRNGKey(7), (b, t, h, d))
    mask = (jnp.arange(t)[None, :]
            < jnp.asarray([[24], [17]])).astype(jnp.float32)
    from deeplearning4j_tpu.nn.layers.attention import \
        scaled_dot_attention
    full = scaled_dot_attention(q, q, q, mask=mask, causal=True)
    ring = ring_self_attention(q, q, q, mesh, mask=mask, causal=True)
    valid = np.asarray(mask)[:, :, None, None]
    np.testing.assert_allclose(np.asarray(full) * valid,
                               np.asarray(ring) * valid,
                               rtol=2e-4, atol=2e-5)


@requires_modern_jax
def test_zigzag_ring_matches_dense_causal():
    """Load-balanced zigzag layout: permute → distributed causal
    attention → unpermute must equal dense causal attention in the
    original order (fwd)."""
    from deeplearning4j_tpu.parallel import (
        zigzag_permute, zigzag_ring_self_attention, zigzag_unpermute)
    mesh = make_mesh({"seq": 8})
    n, (b, t, h, d) = 8, (2, 64, 2, 8)
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(9), 3)
    q = jax.random.normal(kq, (b, t, h, d))
    k = jax.random.normal(kk, (b, t, h, d))
    v = jax.random.normal(kv, (b, t, h, d))
    from deeplearning4j_tpu.nn.layers.attention import \
        scaled_dot_attention
    want = scaled_dot_attention(q, k, v, causal=True)
    zz = zigzag_ring_self_attention(
        zigzag_permute(q, n), zigzag_permute(k, n),
        zigzag_permute(v, n), mesh)
    got = zigzag_unpermute(zz, n)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=2e-4, atol=1e-5)


@requires_modern_jax
def test_zigzag_ring_gradients_match():
    from deeplearning4j_tpu.parallel import (
        zigzag_permute, zigzag_ring_self_attention, zigzag_unpermute)
    mesh = make_mesh({"seq": 8})
    n, (b, t, h, d) = 8, (1, 32, 2, 8)
    q = jax.random.normal(jax.random.PRNGKey(10), (b, t, h, d))
    co = jax.random.normal(jax.random.PRNGKey(11), (b, t, h, d))
    from deeplearning4j_tpu.nn.layers.attention import \
        scaled_dot_attention

    def loss_zz(x):
        xz = zigzag_permute(x, n)
        o = zigzag_ring_self_attention(xz, xz, xz, mesh)
        return jnp.sum(zigzag_unpermute(o, n) * co)

    def loss_dense(x):
        return jnp.sum(scaled_dot_attention(x, x, x, causal=True) * co)

    g_zz = jax.grad(loss_zz)(q)
    g_d = jax.grad(loss_dense)(q)
    np.testing.assert_allclose(np.asarray(g_zz), np.asarray(g_d),
                               rtol=2e-4, atol=2e-5)


@requires_modern_jax
def test_zigzag_ring_masked_matches_dense():
    """Key-masked zigzag (padded / packed-document causal batch) must
    equal dense causal+mask — the balanced schedule is not given up
    when the batch carries padding (VERDICT r3 #5)."""
    from deeplearning4j_tpu.parallel import (
        zigzag_permute, zigzag_ring_self_attention, zigzag_unpermute)
    mesh = make_mesh({"seq": 8})
    n, (b, t, h, d) = 8, (2, 64, 2, 8)
    q = jax.random.normal(jax.random.PRNGKey(12), (b, t, h, d))
    mask = (jnp.arange(t)[None, :]
            < jnp.asarray([[64], [41]])).astype(jnp.float32)
    from deeplearning4j_tpu.nn.layers.attention import \
        scaled_dot_attention
    want = scaled_dot_attention(q, q, q, mask=mask, causal=True)
    zz = zigzag_ring_self_attention(
        zigzag_permute(q, n), zigzag_permute(q, n),
        zigzag_permute(q, n), mesh,
        mask=zigzag_permute(mask, n, axis=1))
    got = zigzag_unpermute(zz, n)
    valid = np.asarray(mask)[:, :, None, None]
    np.testing.assert_allclose(np.asarray(want) * valid,
                               np.asarray(got) * valid,
                               rtol=2e-4, atol=1e-5)


@requires_modern_jax
def test_zigzag_ring_masked_gradients_match():
    from deeplearning4j_tpu.parallel import (
        zigzag_permute, zigzag_ring_self_attention, zigzag_unpermute)
    mesh = make_mesh({"seq": 8})
    n, (b, t, h, d) = 8, (1, 32, 2, 8)
    q = jax.random.normal(jax.random.PRNGKey(13), (b, t, h, d))
    co = jax.random.normal(jax.random.PRNGKey(14), (b, t, h, d))
    mask = (jnp.arange(t)[None, :] < 23).astype(jnp.float32)
    from deeplearning4j_tpu.nn.layers.attention import \
        scaled_dot_attention
    valid = mask[:, :, None, None]

    def loss_zz(x):
        xz = zigzag_permute(x, n)
        o = zigzag_ring_self_attention(
            xz, xz, xz, mesh, mask=zigzag_permute(mask, n, axis=1))
        return jnp.sum(zigzag_unpermute(o, n) * co * valid)

    def loss_dense(x):
        return jnp.sum(
            scaled_dot_attention(x, x, x, mask=mask, causal=True)
            * co * valid)

    g_zz = jax.grad(loss_zz)(q)
    g_d = jax.grad(loss_dense)(q)
    np.testing.assert_allclose(np.asarray(g_zz), np.asarray(g_d),
                               rtol=2e-4, atol=2e-5)


def test_zigzag_permute_roundtrip():
    from deeplearning4j_tpu.parallel import (zigzag_permute,
                                             zigzag_unpermute)
    x = jnp.arange(2 * 48.0).reshape(2, 48)
    rt = zigzag_unpermute(zigzag_permute(x, 8, axis=1), 8, axis=1)
    np.testing.assert_array_equal(np.asarray(rt), np.asarray(x))


@pytest.mark.parametrize("mode", ["ring", "ulysses", "zigzag_ring"])
@requires_modern_jax
def test_sequence_parallel_layer_api(mode):
    """MultiHeadAttention(sequence_parallel=...) under an ambient
    distributed_context must equal the same layer outside the context
    (the high-level long-context path; users never touch shard_map)."""
    from deeplearning4j_tpu.parallel import (distributed_context,
                                             make_mesh)
    from deeplearning4j_tpu.nn.layers import MultiHeadAttention
    mesh = make_mesh({"seq": 8})
    t = 32
    layer = MultiHeadAttention(n_in=16, n_out=16, n_heads=8,
                               causal=True, sequence_parallel=mode)
    params, _, _ = layer.init(jax.random.PRNGKey(0), (t, 16))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, t, 16))
    local, _ = layer.apply(params, {}, x)          # no ambient context
    with distributed_context(mesh):
        dist, _ = layer.apply(params, {}, x)
    np.testing.assert_allclose(np.asarray(local), np.asarray(dist),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("mode", ["ring", "zigzag_ring"])
@requires_modern_jax
def test_sequence_parallel_layer_api_masked(mode):
    """Padded batches through the layer API: the key mask reaches the
    distributed attention (zigzag included — VERDICT r3 #5) and the
    result matches local masked attention on valid positions."""
    from deeplearning4j_tpu.parallel import (distributed_context,
                                             make_mesh)
    from deeplearning4j_tpu.nn.layers import MultiHeadAttention
    mesh = make_mesh({"seq": 8})
    t = 32
    layer = MultiHeadAttention(n_in=16, n_out=16, n_heads=8,
                               causal=True, sequence_parallel=mode)
    params, _, _ = layer.init(jax.random.PRNGKey(0), (t, 16))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, t, 16))
    mask = (jnp.arange(t)[None, :]
            < jnp.asarray([[t], [21]])).astype(jnp.float32)
    local, _ = layer.apply(params, {}, x, mask=mask)
    with distributed_context(mesh):
        dist, _ = layer.apply(params, {}, x, mask=mask)
    valid = np.asarray(mask)[:, :, None]
    np.testing.assert_allclose(np.asarray(local) * valid,
                               np.asarray(dist) * valid,
                               rtol=2e-4, atol=2e-5)


@requires_modern_jax
def test_sequence_parallel_context_invalidates_traces():
    """A net fit OUTSIDE the context first must re-trace when entering
    it (and vice versa) — the ambient decision is never baked into a
    stale jit cache. Also: a typo'd mode raises even single-chip."""
    from deeplearning4j_tpu.parallel import (distributed_context,
                                             make_mesh)
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import (GlobalPoolingLayer,
                                              MultiHeadAttention,
                                              OutputLayer,
                                              TransformerEncoderBlock)
    from deeplearning4j_tpu.nn import updaters as upd
    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(upd.Adam(learning_rate=0.01)).list()
            .layer(TransformerEncoderBlock(n_heads=8, causal=True,
                                           sequence_parallel="ring"))
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType("rnn", (16, 16))).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 16, 16)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
    net.fit(x, y)                      # traces LOCAL attention
    local_fn = net._train_step_fn
    with distributed_context(make_mesh({"seq": 8})):
        net.fit(x, y)                  # must re-trace distributed
        assert net._train_step_fn is not local_fn
        dist_fn = net._train_step_fn
    net.fit(x, y)                      # back outside: re-trace again
    assert net._train_step_fn is not dist_fn
    assert np.isfinite(net.score())

    bad = MultiHeadAttention(n_in=16, n_out=16, n_heads=2,
                             sequence_parallel="ulyses")
    params, _, _ = bad.init(jax.random.PRNGKey(0), (8, 16))
    with pytest.raises(ValueError, match="sequence_parallel"):
        bad.apply(params, {}, jnp.zeros((1, 8, 16)))


@requires_modern_jax
def test_sequence_parallel_transformer_trains():
    """A full MultiLayerNetwork with a sequence-parallel transformer
    block trains under the ambient context (grads flow through the
    ring inside the jitted train step)."""
    from deeplearning4j_tpu.parallel import (distributed_context,
                                             make_mesh)
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.config import InputType
    from deeplearning4j_tpu.nn.layers import (GlobalPoolingLayer,
                                              OutputLayer,
                                              TransformerEncoderBlock)
    from deeplearning4j_tpu.nn import updaters as upd
    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(upd.Adam(learning_rate=0.01)).list()
            .layer(TransformerEncoderBlock(n_heads=8, causal=True,
                                           sequence_parallel="ring"))
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType("rnn", (16, 16))).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 16, 16)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
    with distributed_context(make_mesh({"seq": 8})):
        for _ in range(3):
            net.fit(x, y)
    assert np.isfinite(net.score())


def test_ulysses_attention_legacy_alias():
    """The original ring_attention.ulysses_attention import location
    must keep working (now delegating to parallel/ulysses.py)."""
    from deeplearning4j_tpu.parallel import ulysses_self_attention
    assert ulysses_attention is ulysses_self_attention
    mesh = make_mesh({"seq": 8})
    key = jax.random.PRNGKey(2)
    q = jax.random.normal(key, (2, 32, 8, 4))
    from deeplearning4j_tpu.nn.layers.attention import \
        scaled_dot_attention
    np.testing.assert_allclose(
        np.asarray(scaled_dot_attention(q, q, q)),
        np.asarray(ulysses_attention(q, q, q, mesh)),
        rtol=2e-4, atol=2e-5)


def test_parallel_inference_batched():
    net = _net()
    pi = ParallelInference(net, mode=ParallelInference.BATCHED,
                           batch_limit=16)
    try:
        xs = [np.random.default_rng(i).normal(size=(4,)).astype(
            np.float32) for i in range(10)]
        obs = [pi.output_async(x) for x in xs]
        outs = [o.get(timeout=30) for o in obs]
        direct = np.asarray(net.output(np.stack(xs)))
        np.testing.assert_allclose(np.stack(outs), direct, rtol=1e-4,
                                   atol=1e-5)
    finally:
        pi.shutdown()


def test_parallel_inference_error_propagates():
    net = _net()
    pi = ParallelInference(net, mode=ParallelInference.BATCHED)
    try:
        with pytest.raises(Exception):
            pi.output(np.ones((3,), np.float32))  # wrong feature size
    finally:
        pi.shutdown()


def test_tensor_parallel_matmul_sharding():
    """TP capability (new vs reference, SURVEY §2.5): shard a weight's
    output dim over 'model'; XLA partitions the matmul."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh({"data": 2, "model": 4})
    w = jax.device_put(jnp.ones((16, 32)),
                       NamedSharding(mesh, P(None, "model")))
    x = jax.device_put(jnp.ones((8, 16)),
                       NamedSharding(mesh, P("data", None)))
    y = jax.jit(lambda a, b: a @ b)(x, w)
    assert y.shape == (8, 32)
    np.testing.assert_allclose(np.asarray(y), 16.0)


def test_ulysses_attention_matches_full():
    """All-to-all sequence parallelism (second long-context strategy):
    identical outputs to single-device attention, with and without
    mask/causal."""
    from deeplearning4j_tpu.parallel import ulysses_self_attention
    from deeplearning4j_tpu.nn.layers.attention import \
        scaled_dot_attention

    mesh = make_mesh({"seq": 8})
    b, t, h, d = 2, 32, 8, 4
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(kq, (b, t, h, d))
    k = jax.random.normal(kk, (b, t, h, d))
    v = jax.random.normal(kv, (b, t, h, d))
    full = scaled_dot_attention(q, k, v)
    uly = ulysses_self_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(full), np.asarray(uly),
                               rtol=2e-4, atol=2e-5)
    # causal
    fullc = scaled_dot_attention(q, k, v, causal=True)
    ulyc = ulysses_self_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(fullc), np.asarray(ulyc),
                               rtol=2e-4, atol=2e-5)
    # key mask
    mask = (np.arange(t)[None, :] < np.array([[20], [28]])).astype(
        np.float32) * np.ones((b, 1), np.float32)
    mask = jnp.asarray(mask)
    fullm = scaled_dot_attention(q, k, v, mask=mask)
    ulym = ulysses_self_attention(q, k, v, mesh, mask=mask)
    np.testing.assert_allclose(np.asarray(fullm), np.asarray(ulym),
                               rtol=2e-4, atol=2e-5)
    # gradient flows through the all-to-alls
    g = jax.grad(lambda q: jnp.sum(
        ulysses_self_attention(q, k, v, mesh) ** 2))(q)
    assert g.shape == q.shape and bool(jnp.all(jnp.isfinite(g)))


def test_ulysses_rejects_indivisible_heads():
    from deeplearning4j_tpu.parallel import ulysses_self_attention
    mesh = make_mesh({"seq": 8})
    x = jnp.zeros((1, 16, 4, 8))    # 4 heads < 8 devices
    with pytest.raises(ValueError, match="divisible"):
        ulysses_self_attention(x, x, x, mesh)
