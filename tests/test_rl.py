"""RL package tests (reference: rl4j QLearningDiscrete/A3C tests —
rl4j uses toy deterministic MDPs the same way)."""
import numpy as np
import pytest

from deeplearning4j_tpu.rl import (A3CConfiguration, A3CDiscrete,
                                   BoltzmannQ, CartPole, EpsGreedy,
                                   ExpReplay, GridWorld, Greedy,
                                   QLearningConfiguration,
                                   QLearningDiscrete, VectorizedMDP)
from deeplearning4j_tpu.rl.network import DQNFactoryStdDense


# --- envs -------------------------------------------------------------------

def test_cartpole_dynamics():
    env = CartPole(seed=3)
    obs = env.reset()
    assert obs.shape == (4,)
    total = 0
    while not env.is_done():
        obs, r, done, _ = env.step(1)
        total += r
    assert 1 <= total < 200     # constant-push falls over quickly


def test_gridworld_shortest_path_reward():
    env = GridWorld(n=3)
    env.reset()
    # optimal: 2 downs + 2 rights = 4 steps, reward -3 + 10
    rs = [env.step(a)[1] for a in [1, 1, 3, 3]]
    assert rs == [-1, -1, -1, 10]
    assert env.is_done()


def test_vectorized_mdp_autoreset():
    v = VectorizedMDP(GridWorld(n=3, max_steps=5), n=4)
    obs = v.reset()
    assert obs.shape == (4, 9)
    for _ in range(6):
        obs, r, d = v.step(np.zeros(4, np.int32))
    assert obs.shape == (4, 9)    # envs auto-reset after max_steps


# --- replay -----------------------------------------------------------------

def test_exp_replay_ring_and_sampling():
    rep = ExpReplay(max_size=8, obs_shape=(2,), batch_size=4, seed=0)
    for i in range(12):           # wraps around
        rep.store(np.full(2, i), i % 3, float(i), np.full(2, i + 1),
                  i % 2)
    assert len(rep) == 8
    obs, a, r, nxt, d = rep.get_batch()
    assert obs.shape == (4, 2) and a.shape == (4,)
    assert r.min() >= 4.0         # oldest 4 were overwritten


# --- policies ---------------------------------------------------------------

def test_policies():
    rng = np.random.default_rng(0)
    q = np.array([0.1, 5.0, -1.0])
    assert Greedy().next_action(q, 0, rng) == 1
    eps = EpsGreedy(min_epsilon=0.1, anneal_steps=100)
    assert eps.epsilon(0) == 1.0
    assert eps.epsilon(100) == pytest.approx(0.1)
    acts = {BoltzmannQ(0.1).next_action(q, 0, rng) for _ in range(20)}
    assert 1 in acts              # low temperature ≈ greedy


# --- DQN --------------------------------------------------------------------

def test_dqn_learns_gridworld():
    """DQN should find the shortest path on a 3x3 grid (optimal
    return = -3 + 10 = 7)."""
    conf = QLearningConfiguration(
        seed=7, max_step=3000, max_epoch_step=30, batch_size=64,
        exp_rep_max_size=3000, target_dqn_update_freq=100,
        update_start=100, min_epsilon=0.05, epsilon_nb_step=1500,
        gamma=0.95, learning_rate=2e-3, double_dqn=True)
    ql = QLearningDiscrete(GridWorld(n=3, max_steps=30), conf,
                           DQNFactoryStdDense(hidden=(32,)))
    res = ql.train()
    assert res.total_steps >= conf.max_step
    assert ql.play() == 7.0, "greedy policy should be optimal"


def test_dqn_dueling_and_save_load(tmp_path):
    conf = QLearningConfiguration(seed=1, max_step=300, max_epoch_step=20,
                                  update_start=50)
    ql = QLearningDiscrete(GridWorld(n=3), conf,
                           DQNFactoryStdDense(hidden=(16,),
                                              dueling=True))
    ql.train()
    obs = GridWorld(n=3).reset()
    q_before = ql.q_values(obs)
    p = str(tmp_path / "dqn")
    ql.save(p)
    ql2 = QLearningDiscrete(GridWorld(n=3), conf,
                            DQNFactoryStdDense(hidden=(16,),
                                               dueling=True))
    ql2.load(p)
    np.testing.assert_allclose(ql2.q_values(obs), q_before, rtol=1e-6)


# --- A2C/A3C ----------------------------------------------------------------

def test_dqn_load_rebuilds_from_checkpoint_conf(tmp_path):
    """load() must train with the checkpoint's hyperparameters, not
    the constructor's."""
    conf = QLearningConfiguration(seed=1, max_step=200, gamma=0.5,
                                  learning_rate=5e-4, batch_size=16,
                                  update_start=50)
    ql = QLearningDiscrete(GridWorld(n=3), conf,
                           DQNFactoryStdDense(hidden=(8,)))
    ql.train()
    p = str(tmp_path / "q")
    ql.save(p)
    other = QLearningDiscrete(GridWorld(n=3),
                              QLearningConfiguration(seed=9),
                              DQNFactoryStdDense(hidden=(8,)))
    other.load(p)
    assert other.conf.gamma == 0.5
    assert other.replay.batch_size == 16
    obs = GridWorld(n=3).reset()
    np.testing.assert_allclose(other.q_values(obs), ql.q_values(obs),
                               rtol=1e-6)


def test_async_nstep_q_learns_gridworld():
    from deeplearning4j_tpu.rl import AsyncNStepQLearningDiscrete
    conf = A3CConfiguration(seed=11, max_step=12000, n_envs=8,
                            n_step=8, gamma=0.9, learning_rate=2e-3)
    nq = AsyncNStepQLearningDiscrete(GridWorld(n=3, max_steps=20), conf)
    nq.train()
    assert nq.play(GridWorld(n=3, max_steps=20)) > 0


def test_a3c_improves_on_gridworld():
    conf = A3CConfiguration(seed=5, max_step=12000, n_envs=8, n_step=8,
                            gamma=0.95, learning_rate=3e-3,
                            entropy_coef=0.01)
    a3c = A3CDiscrete(GridWorld(n=3, max_steps=20), conf)
    a3c.train()
    # greedy policy reaches goal (optimal 7; allow any positive path)
    score = a3c.play(GridWorld(n=3, max_steps=20))
    assert score > 0, score
    assert a3c.mean_returns[-1] > a3c.mean_returns[0]


def test_gym_adapter_gymnasium_cartpole():
    """Env-adapter SPI (reference rl4j-gym GymEnv): wrap a real
    gymnasium env, check spaces/reset/step/new_instance, and run a
    short DQN training through it."""
    gymnasium = pytest.importorskip("gymnasium")
    from deeplearning4j_tpu.rl import (GymEnvAdapter,
                                       QLearningConfiguration,
                                       QLearningDiscreteDense)

    mdp = GymEnvAdapter(lambda: gymnasium.make("CartPole-v1"), seed=0)
    assert mdp.action_space.size == 2
    assert mdp.observation_space.shape == (4,)
    obs = mdp.reset()
    assert obs.shape == (4,) and mdp.is_done() is False
    obs2, r, done, info = mdp.step(1)
    assert obs2.shape == (4,) and r == 1.0 and isinstance(info, dict)
    clone = mdp.new_instance()
    assert clone is not mdp and clone.action_space.size == 2

    cfg = QLearningConfiguration(max_step=300, batch_size=32,
                                 target_dqn_update_freq=100,
                                 epsilon_nb_step=200)
    learner = QLearningDiscreteDense(mdp, cfg)
    res = learner.train()
    assert res.total_steps >= 300
    assert np.isfinite(res.episode_rewards[-1])
    mdp.close()


def test_gym_adapter_classic_4tuple_api():
    """Duck-typed adapter: classic gym 4-tuple step + bare-obs reset."""
    from deeplearning4j_tpu.rl import GymEnvAdapter

    class OldEnv:
        class action_space:
            n = 3
        class observation_space:
            shape = (2,)
            low = np.array([-1, -1.0])
            high = np.array([1, 1.0])

        def reset(self):
            self.t = 0
            return np.zeros(2)

        def step(self, a):
            self.t += 1
            return np.ones(2) * self.t, 0.5, self.t >= 2, {"k": 1}

    mdp = GymEnvAdapter(OldEnv())
    assert mdp.action_space.size == 3
    assert mdp.reset().shape == (2,)
    _, r, done, info = mdp.step(0)
    assert r == 0.5 and not done and info == {"k": 1}
    _, _, done, _ = mdp.step(0)
    assert done and mdp.is_done()
    with pytest.raises(ValueError, match="new_instance"):
        mdp.new_instance()
    # an env CLASS counts as a factory (review r2): instance built,
    # new_instance supported, classic-API seed does not crash reset
    mdp2 = GymEnvAdapter(OldEnv, seed=3)
    assert mdp2.reset().shape == (2,)
    assert mdp2.new_instance().action_space.size == 3
