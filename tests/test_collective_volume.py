"""Collective-volume CI gates (VERDICT r3 #7): the BASELINE.md wire
table is enforced, not just documented. Each gate compiles a
representative distributed step on the virtual 8-device mesh, parses
the optimized HLO with ``tools.collective_volume``, and asserts the
collective kinds + byte volumes against the ring-algorithm formulas —
a sharding regression (lost allreduce, extra all-gather, mask tensor
rejoining the ring) fails the suite instead of silently drifting a doc.

Reference analog: there is none — the reference never gates wire
volume; this enforces BASELINE #5's "linear to 32 chips" derisking.
"""
import importlib.util
import pathlib
import sys

import jax
import numpy as np
import pytest

from conftest import requires_modern_jax

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")

_TOOLS = pathlib.Path(__file__).resolve().parent.parent / "tools"


def _load_cv():
    spec = importlib.util.spec_from_file_location(
        "collective_volume", _TOOLS / "collective_volume.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("collective_volume", mod)
    spec.loader.exec_module(mod)
    return mod

@pytest.fixture(scope="module")
def cv():
    return _load_cv()


def _volumes(cv, jitted, args, kind):
    colls = cv.collectives_of(jitted.lower(*args).compile())
    return [w for k, _, w in colls if k == kind], colls


def test_dp_resnet_allreduce_matches_ring_formula(cv):
    """DP ResNet-50: ONE gradient-sync all-reduce family whose total
    wire volume equals 2·P·(n−1)/n — the minimal ring volume for the
    fp32 gradient bytes P (BASELINE #5 reading)."""
    jitted, args = cv.dp_resnet()
    ar, colls = _volumes(cv, jitted, args, "all-reduce")
    assert ar, "gradient all-reduce disappeared from the DP step"
    params = args[0]
    p_bytes = sum(np.prod(p.shape) * p.dtype.itemsize
                  for p in jax.tree.leaves(params))
    want = 2 * p_bytes * 7 / 8
    got = sum(ar)
    # small non-gradient allreduces (loss mean, BN stats) ride along;
    # the gradient sync must dominate and not exceed the formula by
    # more than a few percent
    assert want * 0.98 < got < want * 1.05, (got, want)
    # and nothing else moves: a DP step has no business all-gathering
    other = [k for k, _, _ in colls
             if k in ("all-gather", "reduce-scatter", "all-to-all")]
    assert not other, other


def test_dp_broken_sharding_is_caught(cv):
    """Canary: the same step with the batch REPLICATED (a classic
    sharding regression — every device computes the full batch) emits
    no gradient all-reduce, so the formula gate above would fail.
    Proves the gate detects the regression class it exists for."""
    broken, args = cv.dp_resnet(sharded=False)
    ar, _ = _volumes(cv, broken, args, "all-reduce")
    assert sum(ar) < 1e6   # ~0: the gradient sync is gone


def test_zero_dp_reduce_scatter_allgather_and_footprint(cv):
    """ZeRO-DP sharded weight update (ISSUE 6): the compiled sharded
    SYNC step moves gradients by reduce-scatter and params by
    all-gather — at the per-shard/full-tensor byte volumes the flat
    layout implies — and the resident optimizer state drops to ~1/N
    of the replicated footprint per device."""
    from deeplearning4j_tpu.parallel._compat import supports_psum_scatter
    if not supports_psum_scatter():
        pytest.skip("this jax cannot express psum_scatter")
    jitted, args, acct = cv.dp_sharded_wrapper()
    colls = cv.collectives_of(jitted.lower(*args).compile())
    rs = [(nb, w) for k, nb, w in colls if k == "reduce-scatter"]
    ag = [(nb, w) for k, nb, w in colls if k == "all-gather"]
    assert rs, "sharded step lost its gradient reduce-scatter"
    assert ag, "sharded step lost its param all-gather"
    n = 8
    # reduce-scatter results are the per-device grad shards: total
    # ≈ grad_bytes/n (pad slack allowed); all-gather results are the
    # full flat params: total ≈ param_bytes (plus the small loss mean)
    got_rs = sum(nb for nb, _ in rs)
    assert acct["grad_bytes"] / n * 0.95 < got_rs \
        < acct["grad_bytes"] / n * 1.2, (got_rs, acct)
    got_ag = sum(nb for nb, _ in ag)
    assert acct["param_bytes"] * 0.95 < got_ag \
        < acct["param_bytes"] * 1.2, (got_ag, acct)
    # optimizer-state residency: ~1/N of replicated (adam: 2 moment
    # trees + scalar counts)
    ratio = acct["opt_bytes_per_device"] \
        / acct["opt_bytes_replicated_per_device"]
    assert 1 / n * 0.8 < ratio < 1 / n * 1.6, acct
    # and no dense gradient allreduce remains (scatter replaced it)
    ar = [nb for k, nb, _ in colls if k == "all-reduce"]
    assert sum(ar) < acct["grad_bytes"] * 0.05, ar


def test_zero_dp_replicated_baseline_has_no_scatter(cv):
    """Canary for the gate above: the SAME wrapper step with
    ``sharded_update=False`` emits NO reduce-scatter/all-gather — the
    gradient sync is one fused all-reduce and the optimizer state
    stays replicated (ratio 1)."""
    jitted, args, acct = cv.dp_sharded_wrapper(sharded_update=False)
    colls = cv.collectives_of(jitted.lower(*args).compile())
    other = [k for k, _, _ in colls
             if k in ("reduce-scatter", "all-gather")]
    assert not other, other
    ar = [nb for k, nb, _ in colls if k == "all-reduce"]
    assert sum(ar) > acct["grad_bytes"] * 0.95
    assert acct["opt_bytes_per_device"] \
        == acct["opt_bytes_replicated_per_device"]


def test_tp_mlp_activation_allreduce_only(cv):
    """TP col→row MLP: activations (not params) allreduce — volume is
    activation-sized (≪ param bytes), and no collective-permute."""
    jitted, args = cv.tp_mlp()
    ar, colls = _volumes(cv, jitted, args, "all-reduce")
    assert ar
    params, x = args
    p_bytes = sum(np.prod(p.shape) * p.dtype.itemsize
                  for p in jax.tree.leaves(params))
    act_bytes = np.prod(x.shape) * x.dtype.itemsize
    got = sum(ar)
    # well under even 10% of a param sync; within 8x of one activation
    # allreduce (fwd+bwd, dtype promotion allowed)
    assert got < 0.1 * p_bytes
    assert got <= 8 * 2 * act_bytes * 7 / 8, (got, act_bytes)
    assert not [k for k, _, _ in colls if k == "collective-permute"]


@requires_modern_jax
def test_sp_ring_volume_and_no_mask_tensor(cv):
    """SP causal ring fwd+bwd at T=8k: KV blocks + gradient
    accumulators ride collective-permute for n trips; with no key mask
    given, NO mask tensor rotates (round 4's km=None threading) — the
    volume stays within the k/v/dk/dv formula."""
    jitted, args = cv.sp_ring()
    cp, colls = _volumes(cv, jitted, args, "collective-permute")
    assert cp, "ring lost its collective-permutes"
    (q,) = args
    b, t, h, d = q.shape
    n = 8
    shard_bf16 = b * (t // n) * h * d * 2
    shard_f32 = 2 * shard_bf16
    # fwd: k+v; bwd: k+v + dk+dv accumulators (f32); each rotates once
    # per ring trip × n trips. XLA:CPU promotes bf16 buffers to f32,
    # so the band spans bf16-preserved (TPU) .. all-f32 (CPU).
    want_lo = n * (4 * shard_bf16 + 2 * shard_f32)
    want_hi = n * 6 * shard_f32
    got = sum(cp)
    assert want_lo * 0.85 < got < want_hi * 1.1, \
        (got, want_lo, want_hi)


@requires_modern_jax
def test_sp_ring_masked_adds_only_mask_bytes(cv):
    """With a key mask the ring carries ONE extra small tensor: volume
    grows by ≈ n·(mask shard bytes)·trips and nothing else."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.ring_attention import \
        ring_self_attention
    mesh = make_mesh({"seq": 8})
    b, t, h, d = 1, 8192, 8, 128
    q = jnp.zeros((b, t, h, d), jnp.bfloat16)
    mask = jnp.ones((b, t), jnp.float32)

    def loss(q):
        return jnp.sum(ring_self_attention(
            q, q, q, mesh, mask=mask, causal=True)
            .astype(jnp.float32) ** 2)

    jitted = jax.jit(jax.value_and_grad(loss))
    cp_m, _ = _volumes(cv, jitted, (q,), "collective-permute")
    jit_u, args_u = cv.sp_ring()
    cp_u, _ = _volumes(cv, jit_u, args_u, "collective-permute")
    n = 8
    # folded mask is [B·H, T/n] f32 replicated per kv head row
    mask_bytes = h * (t // n) * 4
    extra = sum(cp_m) - sum(cp_u)
    # fwd + bwd each rotate the mask once per trip
    want_extra = 2 * n * mask_bytes
    assert 0 < extra <= want_extra * 1.3, (extra, want_extra)


@requires_modern_jax
def test_composed_dp_sp_tp_per_axis_gates(cv):
    """Composed DP×SP×TP step (VERDICT r4 Missing #1): every
    collective rides its OWN mesh axis — ppermutes only on 'seq'
    (inside the ring loop), gradient all-reduces only on 'data'/'seq'
    at gradient-byte volume, 'tensor' all-reduces only at activation
    scale (TP matmul partials), and no collective spans an unexpected
    axis combination."""
    step, args, ctx, axes = cv.composed_lm()
    with ctx:
        compiled = step.lower(*args).compile()
    colls = cv.collectives_with_axes(compiled, axes)
    assert colls, "composed step emitted no collectives"

    # 1. every collective's groups align to a mesh-axis subset
    unattributed = [(k, nb) for k, nb, ax, _ in colls if ax is None]
    assert not unattributed, unattributed

    # 2. ppermute: 'seq' only, inside the ring's while loop
    perms = [(ax, w) for k, nb, ax, w in colls
             if k == "collective-permute"]
    assert perms, "ring lost its collective-permutes"
    assert all(ax == ("seq",) and inwhile for ax, inwhile in perms), \
        perms

    # 3. gradient sync: hierarchical all-reduce over ('data',) and
    # ('seq',), each moving the per-device gradient bytes (TP-sharded
    # leaves count at 1/tensor_size)
    params = args[0]
    import numpy as np
    tp = axes["tensor"]
    grad_bytes = 0
    for leaf in jax.tree.leaves(params):
        nb = int(np.prod(leaf.shape)) * 4        # grads are f32
        sharded = any(ax == "tensor"
                      for ax in (leaf.sharding.spec or ()))
        grad_bytes += nb // tp if sharded else nb
    # band: the gate must catch the regression class (a lost gradient
    # sync drops the WHOLE volume; runaway gathering adds multiples),
    # not pin XLA's grouping choices — small tensors (loss mean, the
    # tied-embedding grad contribution) drift between allreduce groups
    # across compiles, so allow ±25% around the gradient bytes
    for axis in (("data",), ("seq",)):
        got = sum(nb for k, nb, ax, _ in colls
                  if k == "all-reduce" and ax == axis)
        assert grad_bytes * 0.75 < got < grad_bytes * 1.25, \
            (axis, got, grad_bytes)

    # 4. 'tensor' all-reduces are activation partials: each op at most
    # activation-cube bytes, never gradient-accumulated volume
    x = args[3]
    b, t = x.shape
    act_cap = b * t * 64 * 4          # [B, T, hidden*2] f32 headroom
    tensor_ars = [nb for k, nb, ax, _ in colls
                  if k == "all-reduce" and ax == ("tensor",)]
    assert tensor_ars, "TP lost its activation psums"
    assert max(tensor_ars) <= act_cap, (max(tensor_ars), act_cap)

    # 5. nothing reduces over an axis combo that would mean the
    # shardings collapsed (e.g. a single flat group of all 8)
    bad = [(k, ax) for k, nb, ax, _ in colls
           if k == "all-reduce" and ax is not None and len(ax) > 1]
    assert not bad, bad


@requires_modern_jax
def test_composed_without_tp_sharding_loses_tensor_psums(cv):
    """Canary: the same composed step with params fully REPLICATED
    (the lost-TP regression) emits no 'tensor'-axis activation
    all-reduce — proving gate #4 detects what it exists for."""
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deeplearning4j_tpu.parallel import (
        composed_context, composed_data_sharding, make_mesh)
    from deeplearning4j_tpu.zoo import CausalTransformerLM

    model = CausalTransformerLM(
        vocab_size=64, hidden=32, n_layers=2, n_heads=2, max_len=32,
        ffn_mult=2.0, tie_embeddings=True, sequence_parallel="ring",
        seed=7)
    net = model.init(seq_len=32)
    mesh = make_mesh({"data": 2, "seq": 2, "tensor": 2})
    repl = NamedSharding(mesh, P())
    net.params = jax.tree.map(
        lambda x: jax.device_put(x, repl), net.params)
    net.opt_state = jax.tree.map(
        lambda x: jax.device_put(x, repl), net.opt_state)
    ds = composed_data_sharding(mesh)
    rng = np.random.default_rng(0)
    x = jax.device_put(
        jnp.asarray(rng.integers(0, 64, (4, 32)), jnp.int32), ds)
    y = jax.device_put(
        jnp.asarray(rng.integers(0, 64, (4, 32)), jnp.int32), ds)
    step = net._make_train_step()
    with composed_context(mesh):
        compiled = step.lower(
            net.params, net.opt_state, net.state, x, y, None, None,
            jax.random.PRNGKey(0)).compile()
    colls = cv.collectives_with_axes(
        compiled, dict(data=2, seq=2, tensor=2))
    tensor_ars = [nb for k, nb, ax, _ in colls
                  if k == "all-reduce" and ax == ("tensor",)]
    assert not tensor_ars, tensor_ars


@requires_modern_jax
def test_hierarchical_encoded_dp_dcn_volume(cv):
    """Two-tier DP (VERDICT r4 ask #6): dense f32 all-reduce stays on
    the intra-slice 'data' axis; only 2-bit-packed int32 words cross
    the 'slice' (DCN) axis — gathered bytes ≈ grad_bytes/16 per peer.
    The encoded path must never move dense f32 across 'slice'."""
    import jax.numpy as jnp
    import numpy as np
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from deeplearning4j_tpu.parallel import EncodedGradientsAccumulator
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"slice": 2, "data": 4})
    acc = EncodedGradientsAccumulator()
    g_shape = (64, 2048)                      # 512 KB f32 per device
    grads = {"w": jnp.ones((8,) + g_shape, jnp.float32) * 0.01}
    # state is PER-SLICE (leading slice axis, carried P("slice") —
    # see exchange_hierarchical's docstring)
    state = jax.tree.map(
        lambda x: jnp.stack([x, x]),
        acc.init_state({"w": grads["w"][0]}))

    def f(g, st):
        g = jax.tree.map(lambda x: x[0], g)   # per-device block
        st = jax.tree.map(lambda x: x[0], st)  # this slice's state
        out, st = acc.exchange_hierarchical(g, st, intra_axis="data",
                                            cross_axis="slice")
        expand = lambda x: jnp.asarray(x)[None]
        return (jax.tree.map(expand, out), jax.tree.map(expand, st))

    jitted = jax.jit(shard_map(
        f, mesh=mesh, in_specs=(P(("slice", "data")), P("slice")),
        out_specs=(P(("slice", "data")), P("slice")),
        check_vma=False))
    compiled = jitted.lower(grads, state).compile()
    colls = cv.collectives_with_axes(compiled,
                                     dict(slice=2, data=4))
    grad_bytes = int(np.prod(g_shape)) * 4

    # dense f32 reduction: 'data' only, grad-sized
    dense = [nb for k, nb, ax, _ in colls
             if k == "all-reduce" and ax == ("data",)]
    assert dense and grad_bytes * 0.95 < max(dense), (dense,
                                                      grad_bytes)
    # nothing grad-sized and dense crosses 'slice' (or spans both)
    for k, nb, ax, _ in colls:
        if ax is not None and "slice" in ax:
            assert nb <= grad_bytes / 8, (k, nb, ax)
    # the packed cross-slice gather exists and is ~1/16 wire: the
    # gathered result is [2, C] int32 where C = elements/16
    packed = [nb for k, nb, ax, _ in colls
              if k == "all-gather" and ax == ("slice",)]
    assert packed, "packed cross-slice exchange disappeared"
    want = 2 * grad_bytes / 16                # both slices' words
    assert want * 0.9 < max(packed) < want * 1.3, (packed, want)
