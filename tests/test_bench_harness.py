"""The driver's round-end artifacts must not rot: bench.py's headline
JSON line and the perf-dossier smoke path are executed as real
subprocesses (the round-4 device-loop signature change broke bench.py
while the whole suite stayed green — this is the regression fence).
"""
import json
import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


def _run(args, timeout=600):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    return subprocess.run(
        [sys.executable, *args], cwd=REPO, env=env, timeout=timeout,
        capture_output=True, text=True)


@pytest.mark.slow
def test_bench_prints_one_json_line():
    r = _run(["bench.py"])
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout[-2000:]
    payload = json.loads(lines[0])
    assert payload["metric"] == "resnet50_train_images_per_sec_per_chip"
    # CPU run must still produce a NUMBER (the skip path is for an
    # unreachable TPU backend, not for running on CPU)
    assert payload.get("value") and payload["value"] > 0, payload


@pytest.mark.slow
def test_perf_dossier_smoke_all_configs():
    r = _run(["tools/perf_dossier.py", "--smoke"])
    assert r.returncode == 0, (r.stdout[-1500:], r.stderr[-1500:])
    assert "SMOKE RUN" in r.stdout
    for cfg in ("ResNet-50", "BERT-base", "charRNN", "flash-attn",
                "causal-LM"):
        assert cfg in r.stdout, (cfg, r.stdout[-2000:])
    assert "FAILED" not in r.stdout, r.stdout[-2000:]
