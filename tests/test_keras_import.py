"""Keras import conformance (reference: KerasModelEndToEndTest —
import → forward → compare to Keras-produced activations)."""
import numpy as np
import pytest

keras = pytest.importorskip("keras")

from deeplearning4j_tpu.modelimport import KerasModelImport  # noqa: E402


def _save(model, tmp_path, name="m.h5"):
    p = str(tmp_path / name)
    model.save(p)
    return p


def _keras_out(model, x):
    return np.asarray(model(x, training=False))


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(7)


def test_sequential_mlp(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((12,)),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(8, activation="tanh"),
        keras.layers.Dense(3, activation="softmax"),
    ])
    path = _save(model, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(4, 12)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               _keras_out(model, x), rtol=1e-4, atol=1e-5)


def test_sequential_cnn(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((16, 16, 3)),
        keras.layers.Conv2D(8, 3, activation="relu", padding="same"),
        keras.layers.BatchNormalization(),
        keras.layers.MaxPooling2D(2),
        keras.layers.Conv2D(12, 3, activation="relu", padding="valid"),
        keras.layers.GlobalAveragePooling2D(),
        keras.layers.Dropout(0.25),
        keras.layers.Dense(5, activation="softmax"),
    ])
    path = _save(model, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(2, 16, 16, 3)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               _keras_out(model, x), rtol=1e-4, atol=1e-5)


def test_sequential_depthwise_separable(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((10, 10, 4)),
        keras.layers.DepthwiseConv2D(3, depth_multiplier=2,
                                     activation="relu"),
        keras.layers.SeparableConv2D(6, 3, activation="relu"),
        keras.layers.Flatten(),
        keras.layers.Dense(4),
    ])
    path = _save(model, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(2, 10, 10, 4)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               _keras_out(model, x), rtol=1e-4, atol=1e-5)


def test_sequential_lstm(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((7, 5)),
        keras.layers.LSTM(9, return_sequences=True),
        keras.layers.LSTM(6),        # return last step
        keras.layers.Dense(3, activation="softmax"),
    ])
    path = _save(model, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(3, 7, 5)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               _keras_out(model, x), rtol=1e-4, atol=1e-5)


def test_sequential_gru_simplernn(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((6, 4)),
        keras.layers.GRU(8, return_sequences=True, reset_after=False),
        keras.layers.GRU(7, return_sequences=True, reset_after=True),
        keras.layers.SimpleRNN(5),
        keras.layers.Dense(2),
    ])
    path = _save(model, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(2, 6, 4)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               _keras_out(model, x), rtol=1e-4, atol=1e-5)


def test_sequential_bidirectional(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((5, 3)),
        keras.layers.Bidirectional(keras.layers.LSTM(4,
                                                     return_sequences=True)),
        keras.layers.Bidirectional(keras.layers.LSTM(3)),
        keras.layers.Dense(2),
    ])
    path = _save(model, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(2, 5, 3)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               _keras_out(model, x), rtol=1e-4, atol=1e-5)


def test_sequential_embedding(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((6,)),
        keras.layers.Embedding(20, 8),
        keras.layers.LSTM(5),
        keras.layers.Dense(2, activation="softmax"),
    ])
    path = _save(model, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.integers(0, 20, size=(3, 6)).astype(np.int32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               _keras_out(model, x), rtol=1e-4, atol=1e-5)


def test_functional_graph(tmp_path, rng):
    inp = keras.layers.Input((10,), name="in0")
    a = keras.layers.Dense(8, activation="relu", name="branch_a")(inp)
    b = keras.layers.Dense(8, activation="tanh", name="branch_b")(inp)
    added = keras.layers.Add(name="add")([a, b])
    cat = keras.layers.Concatenate(name="cat")([added, a])
    out = keras.layers.Dense(4, activation="softmax", name="head")(cat)
    model = keras.Model(inp, out)
    path = _save(model, tmp_path)
    graph = KerasModelImport.import_keras_model_and_weights(path)
    x = rng.normal(size=(5, 10)).astype(np.float32)
    ours = np.asarray(graph.output_single(x))
    np.testing.assert_allclose(ours, _keras_out(model, x),
                               rtol=1e-4, atol=1e-5)


def test_keras_v3_archive(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((9,)),
        keras.layers.Dense(6, activation="relu"),
        keras.layers.Dense(2),
    ])
    p = str(tmp_path / "m.keras")
    model.save(p)
    net = KerasModelImport.import_model(p)
    x = rng.normal(size=(4, 9)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               _keras_out(model, x), rtol=1e-4, atol=1e-5)


def test_keras_v3_archive_cnn(tmp_path, rng):
    """v3 weight-group keys are snake-cased class names (conv2d,
    max_pooling2d) — regression for the name-matching path."""
    model = keras.Sequential([
        keras.layers.Input((12, 12, 2)),
        keras.layers.Conv2D(4, 3, activation="relu"),
        keras.layers.MaxPooling2D(2),
        keras.layers.LayerNormalization(),
        keras.layers.Flatten(),
        keras.layers.Dense(3),
    ])
    p = str(tmp_path / "m.keras")
    model.save(p)
    net = KerasModelImport.import_model(p)
    x = rng.normal(size=(2, 12, 12, 2)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               _keras_out(model, x), rtol=1e-4, atol=1e-5)


def test_keras_v3_bidirectional(tmp_path, rng):
    """v3 weights nest under forward_layer/backward_layer subgroups —
    forward must come first despite alphabetical h5 iteration."""
    model = keras.Sequential([
        keras.layers.Input((5, 3)),
        keras.layers.Bidirectional(keras.layers.LSTM(4,
                                                     return_sequences=True)),
        keras.layers.Dense(2),
    ])
    p = str(tmp_path / "m.keras")
    model.save(p)
    net = KerasModelImport.import_model(p)
    x = rng.normal(size=(2, 5, 3)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               _keras_out(model, x), rtol=1e-4, atol=1e-5)


def test_activation_layers_and_loss(tmp_path, rng):
    model = keras.Sequential([
        keras.layers.Input((6,)),
        keras.layers.Dense(8),
        keras.layers.LeakyReLU(negative_slope=0.3),
        keras.layers.Dense(4),
        keras.layers.ReLU(max_value=6.0),
        keras.layers.Dense(3, activation="softmax"),
    ])
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    path = _save(model, tmp_path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(4, 6)).astype(np.float32) * 3
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               _keras_out(model, x), rtol=1e-4, atol=1e-5)
    from deeplearning4j_tpu.nn.layers import OutputLayer
    assert isinstance(net.conf.layers[-1], OutputLayer)
    assert net.conf.layers[-1].loss == "sparse_mcxent"


def test_functional_flatten_concat(tmp_path, rng):
    """Flatten feeding a Concatenate must flatten for real (not pass
    through) or element order diverges from Keras."""
    inp = keras.layers.Input((6, 6, 2), name="img")
    a = keras.layers.Conv2D(3, 3, activation="relu", name="ca")(inp)
    fa = keras.layers.Flatten(name="fa")(a)
    b = keras.layers.Conv2D(2, 3, activation="tanh", name="cb")(inp)
    fb = keras.layers.Flatten(name="fb")(b)
    cat = keras.layers.Concatenate(name="cat")([fa, fb])
    out = keras.layers.Dense(4, name="head")(cat)
    model = keras.Model(inp, out)
    path = _save(model, tmp_path)
    graph = KerasModelImport.import_keras_model_and_weights(path)
    x = rng.normal(size=(3, 6, 6, 2)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(graph.output_single(x)),
                               _keras_out(model, x), rtol=1e-4, atol=1e-5)
