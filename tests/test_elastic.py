"""Elastic multi-host training (ARCHITECTURE.md §13,
resilience/elastic.py): membership coordinator with generation-
numbered mesh epochs, bounded-timeout collectives, exec-based mesh
re-formation, and resharded restore — plus the PR 5 × PR 3 interplay
(SIGTERM under a ZeRO sharded wrapper publishes a SHARDED checkpoint)
and the multi-host chaos drill on tests/mp_harness.py.
"""
import os
import sys
import time
from pathlib import Path

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.obs import metrics
from deeplearning4j_tpu.parallel._compat import supports_psum_scatter
from deeplearning4j_tpu.resilience import elastic, faults

REPO = Path(__file__).resolve().parents[1]

needs_scatter = pytest.mark.skipif(
    not supports_psum_scatter(),
    reason="jax runtime has no psum_scatter/all_gather")


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.reset()
    yield
    faults.reset()


def _mlp(seed=11, n_in=8, n_out=3, hidden=16):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(upd.Adam(learning_rate=5e-3)).list()
            .layer(DenseLayer(n_out=hidden, activation="tanh"))
            .layer(OutputLayer(n_out=n_out, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _iter(n=48, batch=8, seed=5, n_in=8, n_out=3):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, n_in).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.randint(0, n_out, n)]
    return ListDataSetIterator(DataSet(x, y), batch_size=batch)


# =========================================================================
# bounded-timeout collectives
# =========================================================================

def test_bounded_sync_value_error_and_timeout():
    assert elastic.bounded_sync(lambda: 41 + 1, 5.0) == 42
    with pytest.raises(ValueError, match="boom"):
        elastic.bounded_sync(
            lambda: (_ for _ in ()).throw(ValueError("boom")), 5.0)
    t0 = time.perf_counter()
    with pytest.raises(elastic.CollectiveTimeoutError,
                       match="re-form"):
        elastic.bounded_sync(lambda: time.sleep(30), 0.2,
                             what="unit probe")
    assert time.perf_counter() - t0 < 5.0   # raised, did not wait out
    # timeout 0/None = straight call (no watchdog thread)
    assert elastic.bounded_sync(lambda: "x", 0) == "x"


# =========================================================================
# membership coordinator: leases, eviction, agreement, epochs
# =========================================================================

def _clockpair(start=1000.0):
    t = [start]
    return t, (lambda: t[0])


def test_two_hosts_agree_then_evict_missed_lease(tmp_path):
    """Formation at epoch 1, then host b misses its lease: a alone
    commits epoch 2 without b, the eviction is counted, and b's stale
    context is rejected by the epoch stamp."""
    import threading
    t, clock = _clockpair()
    a = elastic.MembershipCoordinator(tmp_path, "a", lease_secs=5.0,
                                      clock=clock, port_base=31000)
    b = elastic.MembershipCoordinator(tmp_path, "b", lease_secs=5.0,
                                      clock=clock, port_base=31000)
    a.renew()
    b.renew()
    assert a.live_members() == ["a", "b"]
    recs = {}
    th = threading.Thread(
        target=lambda: recs.__setitem__("a", a.agree_membership(10.0)))
    th.start()
    recs["b"] = b.agree_membership(10.0)
    th.join(timeout=30)
    assert recs["a"]["epoch"] == recs["b"]["epoch"] == 1
    assert sorted(recs["a"]["members"]) == ["a", "b"]
    assert recs["a"]["coordinator"] == "a"      # deterministic leader
    assert a.rank_of(recs["a"]) == 0 and b.rank_of(recs["b"]) == 1
    ctx_b = elastic.ElasticContext(b, recs["b"])

    # b goes silent; its lease expires after the window
    e0 = metrics.HOSTS_EVICTED._children[()].get()
    t[0] += 6.0
    a.renew()
    rec2 = a.agree_membership(10.0)
    assert rec2["epoch"] == 2 and rec2["members"] == ["a"]
    assert metrics.HOSTS_EVICTED._children[()].get() == e0 + 1
    assert (tmp_path / "members" / "evicted").is_dir()
    # epoch-salted port moved with the generation
    assert rec2["port"] != recs["a"]["port"]

    # the straggler's next step is rejected, not silently absorbed
    with pytest.raises(elastic.StaleMeshEpoch, match="epoch 2"):
        ctx_b.pre_step(0)


def test_agreement_with_dotted_host_ids(tmp_path):
    """Host ids are arbitrary strings — hostnames with dots must ack
    cleanly (the ack files are parsed by prefix, not Path.suffix)."""
    import threading
    t, clock = _clockpair()
    a = elastic.MembershipCoordinator(tmp_path, "node.a.example",
                                      lease_secs=5.0, clock=clock)
    b = elastic.MembershipCoordinator(tmp_path, "node.b.example",
                                      lease_secs=5.0, clock=clock)
    a.renew()
    b.renew()
    recs = {}
    th = threading.Thread(
        target=lambda: recs.__setitem__("a", a.agree_membership(10.0)))
    th.start()
    recs["b"] = b.agree_membership(10.0)
    th.join(timeout=30)
    assert sorted(recs["a"]["members"]) == ["node.a.example",
                                           "node.b.example"]
    assert recs["a"]["epoch"] == 1


def test_agreement_supersedes_proposal_naming_dead_member(tmp_path):
    """A proposal whose member died before acking must be SUPERSEDED,
    not waited on forever: the leader re-proposes the current live
    set at the same generation and stale-set acks don't count."""
    import threading
    t, clock = _clockpair()
    mk = lambda h: elastic.MembershipCoordinator(
        tmp_path, h, lease_secs=5.0, clock=clock, port_base=31000)
    a, b, c = mk("a"), mk("b"), mk("c")
    for co in (a, b, c):
        co.renew()
    # a stale pre-crash proposal names all three; c dies before acking
    elastic._write_json(tmp_path / "proposals" / "1.json",
                        {"epoch": 1, "members": ["a", "b", "c"],
                         "coordinator": "a", "addr": "127.0.0.1",
                         "port": 31001})
    t[0] += 6.0                     # c's lease expires
    recs = {}
    th = threading.Thread(
        target=lambda: recs.__setitem__("a", a.agree_membership(15.0)))
    th.start()
    recs["b"] = b.agree_membership(15.0)
    th.join(timeout=40)
    assert recs["a"]["epoch"] == 1
    assert sorted(recs["a"]["members"]) == ["a", "b"]
    assert recs["a"] == recs["b"]


def test_graceful_leave_evicts_without_lease_wait(tmp_path):
    t, clock = _clockpair()
    a = elastic.MembershipCoordinator(tmp_path, "a", lease_secs=50.0,
                                      clock=clock)
    b = elastic.MembershipCoordinator(tmp_path, "b", lease_secs=50.0,
                                      clock=clock)
    a.renew()
    b.renew()
    b.leave()                       # SIGTERM path: no lease to wait out
    assert a.live_members() == ["a"]
    rec = a.agree_membership(10.0)
    assert rec["members"] == ["a"] and rec["epoch"] == 1


def test_join_settles_and_commits(tmp_path):
    """join(expected=N) forms as soon as all leases exist; the epoch
    gauge reflects the committed generation."""
    import threading
    t, clock = _clockpair()
    a = elastic.MembershipCoordinator(tmp_path, "a", lease_secs=5.0,
                                      clock=clock)
    b = elastic.MembershipCoordinator(tmp_path, "b", lease_secs=5.0,
                                      clock=clock)
    out = {}
    th = threading.Thread(
        target=lambda: out.__setitem__("a", a.join(expected=2,
                                                   timeout_s=20)))
    th.start()
    out["b"] = b.join(expected=2, timeout_s=20)
    th.join(timeout=30)
    assert out["a"]["epoch"] == out["b"]["epoch"] == 1
    assert metrics.MESH_EPOCH._children[()].get() == 1.0


def test_lease_ages_surface_on_healthz(tmp_path):
    """The coordinator mirrors peer lease ages into obs/health.py —
    a dead peer is named by the PR 2 scrape surface."""
    from deeplearning4j_tpu.obs import health
    health.reset()
    t, clock = _clockpair()
    a = elastic.MembershipCoordinator(tmp_path, "a", lease_secs=5.0,
                                      clock=clock)
    b = elastic.MembershipCoordinator(tmp_path, "b", lease_secs=5.0,
                                      clock=clock)
    b.renew()
    t[0] += 40.0                    # b silent for 40s
    a.renew()
    chk = health.check(stale_after=30.0)
    assert not chk["host:a"]["stale"]
    assert chk["host:b"]["stale"]
    assert chk["host:b"]["age_s"] >= 39.0
    health.reset()


def test_fault_sites_host_death_and_coordinator(tmp_path):
    """The elastic layer's injection sites fire like every other
    failure mode, and the named host-preempt plan parses."""
    assert faults.FaultPlan.parse("host-preempt")
    t, clock = _clockpair()
    co = elastic.MembershipCoordinator(tmp_path, "a", lease_secs=5.0,
                                       clock=clock)
    co.renew()
    rec_stub = {"epoch": 0, "members": ["a"], "port": 1}
    # commit epoch 0 == coordinator's view (no epoch.json -> 0)
    ctx = elastic.ElasticContext(co, rec_stub)
    with faults.active("host_death:error=InjectedFault:nth=1"):
        with pytest.raises(faults.InjectedFault):
            ctx.pre_step(0)
    with faults.active("coordinator:error=OSError:nth=1"):
        with pytest.raises(OSError):
            co.renew()


def test_elastic_env_is_epoch_salted():
    rec = {"epoch": 3, "members": ["h0", "h1"], "addr": "127.0.0.1",
           "port": 31303}
    env = elastic.elastic_env(rec)
    assert env["DL4J_TPU_COORD"] == "127.0.0.1:31303"
    assert env["DL4J_TPU_NPROC"] == "2"


def test_coordinator_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("DL4J_TPU_ELASTIC_DIR", str(tmp_path / "el"))
    monkeypatch.setenv("DL4J_TPU_HOST_ID", "envhost")
    monkeypatch.setenv("DL4J_TPU_HOST_LEASE_SECS", "7.5")
    co = elastic.MembershipCoordinator.from_env()
    assert co.host == "envhost" and co.lease_secs == 7.5
    co.renew()
    assert co.live_members() == ["envhost"]
    monkeypatch.delenv("DL4J_TPU_ELASTIC_DIR")
    with pytest.raises(ValueError, match="DL4J_TPU_ELASTIC_DIR"):
        elastic.MembershipCoordinator.from_env()


# =========================================================================
# reshard repad: bit-identity both directions
# =========================================================================

def test_repad_flat_leaves_bit_identity_8_to_4_to_8():
    from deeplearning4j_tpu.parallel.zero import repad_flat_leaves
    rng = np.random.RandomState(0)
    sizes = [10, 64, 7, 1]
    pad = lambda s, n: ((s + n - 1) // n) * n
    src8 = []
    for s in sizes:
        v = np.zeros(pad(s, 8), np.float32)
        v[:s] = rng.randn(s)
        src8.append(v)
    ref4 = [np.zeros(pad(s, 4), np.float32) for s in sizes]
    ref8 = [np.zeros(pad(s, 8), np.float32) for s in sizes]
    at4 = repad_flat_leaves(src8, ref4)
    back8 = repad_flat_leaves(at4, ref8)
    for a, b in zip(src8, back8):
        assert a.shape == b.shape
        assert np.array_equal(a, b)          # bit-identical round trip
    # scalars pass through untouched
    assert repad_flat_leaves([np.float32(3.0)],
                             [np.zeros((), np.float32)])[0] == 3.0
    # a non-zero tail is a layout mismatch, not data to drop silently
    bad = np.ones(16, np.float32)
    with pytest.raises(ValueError, match="non-zero"):
        repad_flat_leaves([bad], [np.zeros(12, np.float32)])


# =========================================================================
# harness: N workers + deterministic kill_after
# =========================================================================

def test_mp_harness_kill_after(tmp_path):
    """The generalized harness SIGKILLs the requested worker on
    schedule and still reaps everyone (no jax involved — this is the
    scaffolding other drills stand on)."""
    from mp_harness import run_workers
    script = tmp_path / "w.py"
    script.write_text(
        "import os, time\n"
        "if os.environ['PROC_ID'] == '2':\n"
        "    time.sleep(60)\n"
        "print('proc %s DONE' % os.environ['PROC_ID'], flush=True)\n")
    t0 = time.perf_counter()
    procs, outs = run_workers(script, port=29999, n=3,
                              kill_after={2: 1.0}, timeout=30)
    assert time.perf_counter() - t0 < 30
    assert procs[0].returncode == 0 and "proc 0 DONE" in outs[0]
    assert procs[1].returncode == 0 and "proc 1 DONE" in outs[1]
    assert procs[2].returncode == -9


# =========================================================================
# PR 5 x PR 3 interplay: SIGTERM under a ZeRO wrapper -> SHARDED publish
# =========================================================================

@needs_scatter
def test_preempt_sharded_wrapper_publishes_sharded_and_resumes_bitexact(
        tmp_path):
    """SIGTERM mid-fit with sharded_update=True publishes through
    ShardedCheckpointer.save_wrapper (1/N shards, world manifest) —
    NOT the replicated zip path — and a fresh process resuming from it
    replays the uninterrupted trajectory bit-exactly."""
    from deeplearning4j_tpu.serialization import ShardedCheckpointer
    from deeplearning4j_tpu.train.fault_tolerance import (
        FaultTolerantTrainer)

    def drive(trainer_dir, plan, epochs, net):
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        w = ParallelWrapper(net, workers=2, sharded_update=True,
                            prefetch_buffer=0)
        tr = FaultTolerantTrainer(net, trainer_dir,
                                  save_every_n_iterations=3,
                                  train_with=w)
        if plan:
            with faults.active(plan):
                tr.fit(_iter(), epochs=epochs)
        else:
            tr.fit(_iter(), epochs=epochs)
        return tr, w

    d = tmp_path / "ck"
    net = _mlp()
    # 6 batches/epoch; SIGTERM at the 5th worker step -> mid-epoch 0
    tr, w = drive(d, "worker_step:error=sigterm:nth=5:max=1", 3, net)
    assert tr.preempted
    stop_iter = net.iteration
    assert stop_iter == 5
    sh = ShardedCheckpointer(d / "sharded", async_save=False)
    assert sh.all_steps() and max(sh.all_steps()) == stop_iter
    wm = sh.world_manifest(stop_iter)
    assert wm["n_shards"] == 2 and wm["layout"] == "zero-flat"
    # the preemption did NOT go through the replicated zip path: the
    # newest zip is an older periodic save from the listener
    from deeplearning4j_tpu.train.fault_tolerance import (
        newest_checkpoint)
    zips = newest_checkpoint(d)
    assert zips is None or \
        FaultTolerantTrainer._zip_iteration(zips) < stop_iter
    sh.close()

    # fresh process image: new net + wrapper + trainer resume from the
    # SHARDED chain (it is newer than any zip) and finish the budget
    net2 = _mlp()
    tr2, w2 = drive(d, None, 3, net2)   # target = restored epoch + 3
    # wait: restored epoch is 0 (preempt mid-epoch 0) -> 3 epochs total
    assert net2.epoch == 3

    # uninterrupted baseline: same seed, same wrapper shape, no faults
    net3 = _mlp()
    _, w3 = drive(tmp_path / "base", None, 3, net3)
    assert net3.epoch == 3 and net3.iteration == net2.iteration
    for a, b in zip(jax.tree_util.tree_leaves(net2.params),
                    jax.tree_util.tree_leaves(net3.params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# =========================================================================
# the multi-host chaos drill (mp_harness; slow — the acceptance fence)
# =========================================================================

@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("DL4J_TPU_SKIP_MP") == "1",
                    reason="multi-process test disabled")
@needs_scatter
def test_elastic_drill_sigkill_reform_reshard_baseline():
    """ISSUE 7 acceptance: SIGKILL one of three hosts mid-epoch →
    survivors raise out of the dead collective within the lease
    window, re-form the mesh at world size 2 (mesh epoch 2),
    reshard-restore the newest valid checkpoint (6 shards → 4), and
    the post-recovery trajectory is bit-identical to the same-scale
    uninterrupted baseline; mesh-epoch/eviction/restart metrics are
    exported. ISSUE 12 rides the same drill: a flight-recorder bundle
    must exist whose skew series names the killed host as the
    final-step straggler, the leader's eviction bundle must carry the
    corpse's final telemetry, and the surviving epoch's fleet
    exposition must carry mesh_epoch=2 labels."""
    sys.path.insert(0, str(REPO / "tools"))
    import chaos
    res = chaos._elastic_scenario(hosts=3, kill_host=2,
                                  port=29300 + (os.getpid() % 300))
    assert res["ok"], res
    assert res["victim_rc"] == -9
    assert res["survivor_world"] == 2 and res["mesh_epoch"] == 2
    assert res["resumed_step"] and res["resumed_step"] > 0
    assert res["detect_s"] <= 4 * res["lease_s"]
    assert res["trajectory_match"] is True
    assert res["hosts_evicted"] >= 1 and res["restarts"] >= 1
    # fleet observability plane (obs/fleet.py, ISSUE 12)
    assert res["flight_bundles"] >= 2          # survivor dump + evict
    assert res["straggler_final"] == "h2"      # the corpse, named
    assert res["evict_bundle_named_dead"] is True
    assert res["dead_last_step"] and res["dead_last_step"] > 0
    assert res["fleet_epoch2"] is True


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("DL4J_TPU_SKIP_MP") == "1",
                    reason="multi-process test disabled")
@needs_scatter
def test_elastic_host_preempt_named_plan_drill():
    """DL4J_TPU_FAULT_PLAN=host-preempt on one host of a live fleet:
    the victim gets SIGTERM at its nth elastic step, leaves
    gracefully (lease dropped), and the survivors re-form and finish."""
    sys.path.insert(0, str(REPO / "tools"))
    import chaos
    res = chaos._elastic_preempt_scenario(
        hosts=2, port=29650 + (os.getpid() % 200))
    assert res["ok"], res
    assert res["victim_preempted"] is True
    assert res["survivors_done"] == 1
