"""Zoo-wide config JSON round-trip: every zoo model's configuration
serializes and rehydrates to an identical, runnable network
(reference: Jackson round-trip of every zoo model's
MultiLayerConfiguration/ComputationGraphConfiguration — the arch half
of the model format)."""
import json

import numpy as np
import pytest

from deeplearning4j_tpu import zoo

# model name -> (factory kwargs shrunk for test speed, input shape)
SPECS = {
    "LeNet": (dict(num_classes=5), (28, 28, 1)),
    "SimpleCNN": (dict(num_classes=4, input_shape=(16, 16, 3)),
                  (16, 16, 3)),
    "AlexNet": (dict(num_classes=6, input_shape=(64, 64, 3)),
                (64, 64, 3)),
    "Darknet19": (dict(num_classes=5, input_shape=(32, 32, 3)),
                  (32, 32, 3)),
    "SqueezeNet": (dict(num_classes=5, input_shape=(48, 48, 3)),
                   (48, 48, 3)),
    "VGG16": (dict(num_classes=4, input_shape=(32, 32, 3)),
              (32, 32, 3)),
}


@pytest.mark.parametrize("name", sorted(SPECS))
def test_zoo_conf_roundtrip(name):
    kwargs, in_shape = SPECS[name]
    model = getattr(zoo, name)(**kwargs)
    conf = model.conf()
    is_graph = hasattr(conf, "inputs")
    if is_graph:
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration)
        conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
        assert json.loads(conf2.to_json()) == json.loads(conf.to_json())
        net = ComputationGraph(conf2).init()
        x = np.zeros((1,) + in_shape, np.float32)
        out = net.output(x)
        out = out[0] if isinstance(out, (list, tuple)) else out
    else:
        from deeplearning4j_tpu.nn import MultiLayerNetwork
        from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        assert json.loads(conf2.to_json()) == json.loads(conf.to_json())
        net = MultiLayerNetwork(conf2).init()
        out = net.output(np.zeros((1,) + in_shape, np.float32))
    n_cls = kwargs.get("num_classes")
    assert np.asarray(out).shape[-1] == n_cls
    assert np.all(np.isfinite(np.asarray(out)))
