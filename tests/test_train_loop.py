"""Scanned device-loop training (steps_per_loop): numerically identical
to sequential per-batch fit, for both MultiLayerNetwork and
ComputationGraph. (TPU-native capability — amortises per-dispatch
latency; no reference analog, the reference pays a JNI crossing per op.)
"""
import numpy as np

from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
import jax


def _batches(n=6, b=32, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.standard_normal((b, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
        out.append(DataSet(x, y))
    return out


def _mln():
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(upd.Adam(learning_rate=0.01))
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def test_mln_steps_per_loop_matches_sequential():
    data = _batches()
    a, b = _mln(), _mln()
    a.fit(ListDataSetIterator(data))
    b.fit(ListDataSetIterator(data), steps_per_loop=4)  # groups 4 + 2
    assert a.iteration == b.iteration == len(data)
    for la, lb in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=2e-5, atol=2e-6)
    assert abs(a.score() - b.score()) < 1e-5


def test_graph_steps_per_loop_matches_sequential():
    data = _batches()

    def make():
        conf = (NeuralNetConfiguration.builder().seed(11)
                .updater(upd.Sgd(learning_rate=0.05))
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_out=8, activation="tanh"),
                           "in")
                .add_layer("out", OutputLayer(n_out=2,
                                              activation="softmax",
                                              loss="mcxent"), "d")
                .set_outputs("out")
                .set_input_types(**{"in": InputType.feed_forward(4)})
                .build())
        return ComputationGraph(conf).init()

    a, b = make(), make()
    a.fit(ListDataSetIterator(data))
    b.fit(ListDataSetIterator(data), steps_per_loop=3)
    for la, lb in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=2e-5, atol=2e-6)


def test_steps_per_loop_shape_change_flushes():
    rng = np.random.default_rng(1)
    data = _batches(4, b=32) + _batches(3, b=16, seed=2)
    net = _mln()
    net.fit(ListDataSetIterator(data), steps_per_loop=4)
    assert net.iteration == len(data)
    assert np.isfinite(net.score())


def _masked_rnn_graph():
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(upd.Adam(learning_rate=0.02))
            .graph_builder()
            .add_inputs("in")
            .add_layer("rnn", LSTM(n_out=8), "in")
            .add_layer("out", RnnOutputLayer(n_out=2,
                                             activation="softmax",
                                             loss="mcxent"), "rnn")
            .set_outputs("out")
            .set_input_types(**{"in": InputType.recurrent(4, 6)})
            .build())
    return ComputationGraph(conf).init()


def _masked_mds_batches(n=6, b=8, t=6, seed=3):
    from deeplearning4j_tpu.data import MultiDataSet
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.standard_normal((b, t, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[
            rng.integers(0, 2, (b, t))]
        m = (np.arange(t)[None, :]
             < rng.integers(3, t + 1, (b, 1))).astype(np.float32)
        out.append(MultiDataSet([x], [y], features_masks=[m],
                                labels_masks=[m]))
    return out


class _ListIt:
    def __init__(self, items):
        self.items = items

    def reset(self):
        pass

    def __iter__(self):
        return iter(self.items)


def test_graph_steps_per_loop_groups_masked_batches():
    """Masked batches must keep the scanned device loop (a BERT
    fine-tune with pad masks ran per-batch before round 4) — grouped
    fit equals sequential fit, and no per-batch dispatch happens for
    full groups."""
    data = _masked_mds_batches()
    a, b = _masked_rnn_graph(), _masked_rnn_graph()
    a.fit(_ListIt(data))
    per_batch_calls = []
    orig = b._fit_batch
    b._fit_batch = lambda *args, **kw: (per_batch_calls.append(1),
                                        orig(*args, **kw))[1]
    b.fit(_ListIt(data), steps_per_loop=3)   # 6 batches = 2 groups
    assert not per_batch_calls, "masked batches fell out of the loop"
    assert a.iteration == b.iteration == len(data)
    for la, lb in zip(jax.tree.leaves(a.params),
                      jax.tree.leaves(b.params)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=2e-5, atol=2e-6)
