"""Multi-node TrainingMaster tests on the virtual 8-device CPU mesh.

Reference analogs: TestSparkMultiLayerParameterAveraging and
GradientSharingTrainingTest run Spark ``local[*]`` — multi-node
simulated in one JVM (SURVEY §4). Here the 8 virtual devices play the
workers and the masters drive the same ParallelWrapper modes a real
multi-host mesh would.
"""
import jax
import numpy as np
import pytest

from conftest import requires_shard_map

from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.parallel import (
    ParameterAveragingTrainingMaster, SharedTrainingMaster,
    ShardedDataSetIterator, SparkDl4jMultiLayer,
)

pytestmark = [
    pytest.mark.skipif(len(jax.devices()) < 8,
                       reason="needs 8 virtual devices"),
    requires_shard_map,
]


def _net(seed=42):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(upd.Adam(learning_rate=0.05))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=512, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 4)).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int64)
    yh = np.eye(2, dtype=np.float32)[y]
    return [DataSet(x[i:i + 64], yh[i:i + 64]) for i in range(0, n, 64)]


def test_parameter_averaging_master_learns():
    master = (ParameterAveragingTrainingMaster.Builder(64)
              .averaging_frequency(2)
              .collect_training_stats()
              .build())
    trainer = SparkDl4jMultiLayer(_net(), master)
    net = trainer.fit(ListDataSetIterator(_data()), epochs=6)
    assert trainer.score() < 0.35
    assert trainer.stats, "collect_training_stats recorded nothing"
    x = np.asarray(_data(64)[0].features)
    out = np.asarray(net.output(x))
    assert out.shape == (64, 2)


def test_shared_training_master_learns():
    master = (SharedTrainingMaster.Builder(64)
              .threshold(1e-3)
              .build())
    trainer = SparkDl4jMultiLayer(_net(), master)
    trainer.fit(ListDataSetIterator(_data()), epochs=6)
    assert trainer.score() < 0.35


def test_spark_computation_graph():
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.parallel import SparkComputationGraph
    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(upd.Adam(learning_rate=0.05))
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_out=16, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"), "d")
            .set_outputs("out")
            .set_input_types(**{"in": InputType.feed_forward(4)})
            .build())
    g = ComputationGraph(conf).init()
    master = ParameterAveragingTrainingMaster.Builder(64).build()
    trainer = SparkComputationGraph(g, master)
    trainer.fit(ListDataSetIterator(_data()), epochs=6)
    assert trainer.score() < 0.35


def test_masters_config_roundtrip():
    m = (SharedTrainingMaster.Builder(32)
         .threshold(5e-4).residual_post_processor_clip(3.0).build())
    d = m.to_json()
    assert d["@class"] == "SharedTrainingMaster"
    assert d["threshold"] == 5e-4 and d["residual_clip"] == 3.0
    m2 = (ParameterAveragingTrainingMaster.Builder(32)
          .averaging_frequency(7).build())
    assert m2.to_json()["averaging_frequency"] == 7


def test_sharded_iterator_partitions():
    data = _data(256)
    shards = [list(ShardedDataSetIterator(data, i, 4)) for i in range(4)]
    # every batch lands in exactly one shard
    assert sum(len(s) for s in shards) == len(data)
    seen = {id(ds) for s in shards for ds in s}
    assert len(seen) == len(data)
    # reset() propagates to resettable bases
    it = ShardedDataSetIterator(ListDataSetIterator(data), 0, 2)
    n1 = len(list(it))
    it.reset()
    assert len(list(it)) == n1
