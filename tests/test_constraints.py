"""Parameter constraints + weight noise (reference LayerConstraint /
conf.weightnoise). Reference analog: TestConstraints,
TestWeightNoise (deeplearning4j-core).
"""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.constraints import (DropConnect,
                                               MaxNormConstraint,
                                               MinMaxNormConstraint,
                                               NonNegativeConstraint,
                                               UnitNormConstraint,
                                               WeightNoise)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import updaters as upd


def _col_norms(w):
    return np.sqrt((np.asarray(w) ** 2).sum(0))


def test_constraint_math():
    w = jnp.asarray(np.random.default_rng(0)
                    .standard_normal((6, 4)).astype(np.float32)) * 3.0
    out = MaxNormConstraint(max_norm=1.0).constrain(w)
    assert (_col_norms(out) <= 1.0 + 1e-5).all()
    out = UnitNormConstraint().constrain(w)
    np.testing.assert_allclose(_col_norms(out), 1.0, rtol=1e-5)
    out = MinMaxNormConstraint(min_norm=2.0, max_norm=4.0).constrain(w)
    n = _col_norms(out)
    assert (n >= 2.0 - 1e-4).all() and (n <= 4.0 + 1e-4).all()
    out = NonNegativeConstraint().constrain(-w)
    assert (np.asarray(out) >= 0).all()
    # bias untouched by default in the tree-level apply
    params = {"W": w, "b": -jnp.ones((4,))}
    ap = MaxNormConstraint(max_norm=0.1).apply(params)
    np.testing.assert_array_equal(np.asarray(ap["b"]),
                                  np.asarray(params["b"]))
    assert (_col_norms(ap["W"]) <= 0.1 + 1e-5).all()


def _net(layer_kw=None, out_kw=None):
    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(upd.Sgd(learning_rate=0.5)).list()
            .layer(DenseLayer(n_out=8, activation="tanh",
                              **(layer_kw or {})))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent", **(out_kw or {})))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def test_constraints_enforced_during_training():
    net = _net(layer_kw={"constraints": [MaxNormConstraint(
        max_norm=0.7)]})
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    for _ in range(10):           # big LR would push norms way past 0.7
        net.fit(x, y)
    w = net.params["layer_0"]["W"]
    assert (_col_norms(w) <= 0.7 + 1e-4).all()
    # the unconstrained layer's bias moved freely (nothing clipped it
    # to the constrained layer's budget) — constraints are per-layer
    assert "layer_1" in net.params
    n1 = _col_norms(net.params["layer_1"]["W"])
    w_init = _net().params["layer_1"]["W"]
    assert not np.allclose(n1, _col_norms(w_init))


def test_weight_noise_train_only():
    net = _net(layer_kw={"weight_noise": WeightNoise(stddev=0.5)})
    rng = np.random.default_rng(1)
    x = rng.standard_normal((32, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    w_before = np.asarray(net.params["layer_0"]["W"]).copy()
    net.fit(x, y)
    # training ran with noise but the MASTER params moved only by the
    # gradient step (no noise baked in): finite and changed
    w_after = np.asarray(net.params["layer_0"]["W"])
    assert np.isfinite(w_after).all() and not np.allclose(w_before,
                                                          w_after)
    # inference is noise-free and deterministic
    o1, o2 = np.asarray(net.output(x)), np.asarray(net.output(x))
    np.testing.assert_array_equal(o1, o2)


def test_dropconnect_learns():
    net = _net(layer_kw={"weight_noise": DropConnect(
        weight_retain_prob=0.8)})
    rng = np.random.default_rng(2)
    x = rng.standard_normal((128, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    first = None
    for _ in range(40):
        net.fit(x, y)
        if first is None:
            first = net.score()
    assert net.score() < first * 0.7


def test_config_roundtrip_with_constraints_and_noise():
    net = _net(layer_kw={"constraints": [UnitNormConstraint()],
                         "weight_noise": DropConnect(
                             weight_retain_prob=0.9)})
    js = net.conf.to_json()
    from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
    conf2 = MultiLayerConfiguration.from_json(js)
    l0 = conf2.layers[0]
    assert isinstance(l0.constraints[0], UnitNormConstraint)
    assert isinstance(l0.weight_noise, DropConnect)
    assert l0.weight_noise.weight_retain_prob == 0.9


def test_constraints_and_noise_in_tbptt_path():
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    conf = (NeuralNetConfiguration.builder().seed(4)
            .updater(upd.Sgd(learning_rate=0.5)).list()
            .layer(LSTM(n_out=6, constraints=[MaxNormConstraint(
                max_norm=0.5)],
                weight_noise=WeightNoise(stddev=0.1)))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .backprop_type("TruncatedBPTT").tbptt_fwd_length(2)
            .set_input_type(InputType.recurrent(3)).build())
    net = MultiLayerNetwork(conf).init(input_shape=(8, 3))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 8, 3)).astype(np.float32)
    y = np.stack([(x[..., 0] > 0), (x[..., 0] <= 0)], -1).astype(
        np.float32)
    for _ in range(6):
        net.fit(x, y)
    for key in ("W", "U"):
        n = _col_norms(net.params["layer_0"][key])
        assert (n <= 0.5 + 1e-4).all(), (key, n.max())
