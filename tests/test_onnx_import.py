"""ONNX import conformance tests.

Reference strategy (SURVEY §4 golden tests): import a graph produced by
a trusted source and compare outputs. The image has no ``onnx`` package
(so torch cannot export), so fixtures are built with the in-package
OnnxBuilder (public onnx.proto3 field numbers) and goldens come from
torch modules carrying IDENTICAL weights — this validates both the wire
codec (decode of spec-conformant bytes) and op semantics vs torch.
"""
import numpy as np
import pytest
import torch
import torch.nn as nn

from deeplearning4j_tpu.modelimport.onnx_import import (OnnxBuilder,
                                                        OnnxModel,
                                                        import_onnx,
                                                        import_onnx_model)


def _run(model_bytes, feed, outputs):
    sd, vars_ = import_onnx(model_bytes)
    res = sd.output(feed, [vars_[o] for o in outputs])
    return [res[vars_[o].name] for o in outputs]


# --- wire codec -------------------------------------------------------------

def test_wire_roundtrip_tensor_and_attrs():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(3, 4)).astype(np.float32)
    b = OnnxBuilder("g")
    b.input("x", [2, 3]).output("y")
    b.init("w", w)
    b.node("MatMul", ["x", "w"], ["y"])
    m = OnnxModel(b.build())
    assert m.producer == "deeplearning4j_tpu"
    assert m.opset == 13
    assert m.graph.name == "g"
    np.testing.assert_array_equal(m.graph.initializers["w"], w)
    n = m.graph.nodes[0]
    assert n.op_type == "MatMul"
    assert n.inputs == ["x", "w"] and n.outputs == ["y"]
    assert m.graph.inputs[0] == ("x", [2, 3], np.float32)


def test_wire_attr_kinds():
    b = OnnxBuilder()
    b.input("x", [1]).output("y")
    b.node("Weird", ["x"], ["y"], alpha=0.5, axis=-1, mode="edge",
           pads=[1, 2, 3, 4], t=np.ones((2, 2), np.float32))
    n = OnnxModel(b.build()).graph.nodes[0]
    assert n.attr_f("alpha") == pytest.approx(0.5)
    assert n.attr_i("axis") == -1
    assert n.attr_s("mode") == "edge"
    assert n.attr_ints("pads") == [1, 2, 3, 4]
    np.testing.assert_array_equal(n.attrs["t"].t, np.ones((2, 2)))


# --- op conformance vs torch ------------------------------------------------

def test_mlp_gemm_matches_torch():
    torch.manual_seed(0)
    lin1 = nn.Linear(6, 8)
    lin2 = nn.Linear(8, 3)
    x = np.random.default_rng(1).normal(size=(4, 6)).astype(np.float32)
    with torch.no_grad():
        expected = torch.softmax(
            lin2(torch.relu(lin1(torch.from_numpy(x)))), -1).numpy()

    b = OnnxBuilder()
    b.input("x", [4, 6]).output("probs")
    b.init("w1", lin1.weight.detach().numpy())     # [out, in]
    b.init("b1", lin1.bias.detach().numpy())
    b.init("w2", lin2.weight.detach().numpy())
    b.init("b2", lin2.bias.detach().numpy())
    b.node("Gemm", ["x", "w1", "b1"], ["h"], transB=1)
    b.node("Relu", ["h"], ["hr"])
    b.node("Gemm", ["hr", "w2", "b2"], ["logits"], transB=1)
    b.node("Softmax", ["logits"], ["probs"], axis=-1)

    (got,) = _run(b.build(), {"x": x}, ["probs"])
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_convnet_matches_torch():
    torch.manual_seed(1)
    conv = nn.Conv2d(2, 5, 3, stride=1, padding=1)
    bn = nn.BatchNorm2d(5).eval()
    bn.running_mean.data = torch.randn(5) * 0.1
    bn.running_var.data = torch.rand(5) + 0.5
    x = np.random.default_rng(2).normal(
        size=(2, 2, 8, 8)).astype(np.float32)
    with torch.no_grad():
        t = torch.max_pool2d(
            torch.relu(bn(conv(torch.from_numpy(x)))), 2)
        expected = torch.flatten(t, 1).numpy()

    b = OnnxBuilder()
    b.input("x", [2, 2, 8, 8]).output("flat")
    b.init("w", conv.weight.detach().numpy())
    b.init("cb", conv.bias.detach().numpy())
    b.init("scale", bn.weight.detach().numpy())
    b.init("bb", bn.bias.detach().numpy())
    b.init("mean", bn.running_mean.numpy())
    b.init("var", bn.running_var.numpy())
    b.node("Conv", ["x", "w", "cb"], ["c"], kernel_shape=[3, 3],
           pads=[1, 1, 1, 1], strides=[1, 1])
    b.node("BatchNormalization",
           ["c", "scale", "bb", "mean", "var"], ["bn"],
           epsilon=float(bn.eps))
    b.node("Relu", ["bn"], ["r"])
    b.node("MaxPool", ["r"], ["p"], kernel_shape=[2, 2],
           strides=[2, 2])
    b.node("Flatten", ["p"], ["flat"], axis=1)

    (got,) = _run(b.build(), {"x": x}, ["flat"])
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_grouped_conv_and_global_pool_match_torch():
    torch.manual_seed(3)
    conv = nn.Conv2d(4, 8, 3, groups=2, padding=1)
    x = np.random.default_rng(4).normal(
        size=(1, 4, 6, 6)).astype(np.float32)
    with torch.no_grad():
        expected = torch.nn.functional.adaptive_avg_pool2d(
            conv(torch.from_numpy(x)), 1).numpy()

    b = OnnxBuilder()
    b.input("x", [1, 4, 6, 6]).output("y")
    b.init("w", conv.weight.detach().numpy())
    b.init("cb", conv.bias.detach().numpy())
    b.node("Conv", ["x", "w", "cb"], ["c"], kernel_shape=[3, 3],
           pads=[1, 1, 1, 1], group=2)
    b.node("GlobalAveragePool", ["c"], ["y"])
    (got,) = _run(b.build(), {"x": x}, ["y"])
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_avgpool_elementwise_reduce_match_torch():
    x = np.random.default_rng(5).normal(
        size=(2, 3, 4, 4)).astype(np.float32)
    with torch.no_grad():
        t = torch.from_numpy(x)
        ap = torch.nn.functional.avg_pool2d(t, 2)
        expected = (ap.mean(dim=(2, 3)) * 2.0 + 1.0).numpy()

    b = OnnxBuilder()
    b.input("x", [2, 3, 4, 4]).output("y")
    b.init("two", np.float32(2.0))
    b.init("one", np.float32(1.0))
    b.node("AveragePool", ["x"], ["p"], kernel_shape=[2, 2],
           strides=[2, 2])
    b.node("ReduceMean", ["p"], ["m"], axes=[2, 3], keepdims=0)
    b.node("Mul", ["m", "two"], ["s"])
    b.node("Add", ["s", "one"], ["y"])
    (got,) = _run(b.build(), {"x": x}, ["y"])
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_shape_ops_and_concat():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    b = OnnxBuilder()
    b.input("x", [2, 3, 4]).output("y")
    b.init("newshape", np.asarray([2, 12], np.int64))
    b.node("Reshape", ["x", "newshape"], ["r"])
    b.node("Transpose", ["r"], ["t"], perm=[1, 0])
    b.node("Concat", ["t", "t"], ["y"], axis=1)
    (got,) = _run(b.build(), {"x": x}, ["y"])
    expected = np.concatenate([x.reshape(2, 12).T] * 2, axis=1)
    np.testing.assert_allclose(got, expected)


def test_one_shot_convenience_and_unknown_op():
    b = OnnxBuilder()
    b.input("x", [2, 2]).output("y")
    b.node("Relu", ["x"], ["y"])
    x = np.array([[-1.0, 2.0], [3.0, -4.0]], np.float32)
    out = import_onnx_model(b.build(), {"x": x})
    np.testing.assert_allclose(out["y"], np.maximum(x, 0))

    bad = OnnxBuilder()
    bad.input("x", [1]).output("y")
    bad.node("NoSuchOp", ["x"], ["y"])
    with pytest.raises(NotImplementedError, match="NoSuchOp"):
        import_onnx(bad.build())


def test_trainable_import_fine_tune():
    """Initializers marked trainable become VARIABLEs with gradients
    (the fine-tune path, mirroring TF import)."""
    b = OnnxBuilder()
    b.input("x", [2, 3]).output("y")
    b.init("w", np.ones((3, 2), np.float32))
    b.node("MatMul", ["x", "w"], ["y"])
    sd, vars_ = import_onnx(b.build(), trainable=["w"])
    assert "w" in [v.name for v in sd.variables()]
    grads = sd.calculate_gradients(
        {"x": np.ones((2, 3), np.float32)}, ["w"]) \
        if hasattr(sd, "calculate_gradients") else None
    if grads is not None:
        assert grads["w"].shape == (3, 2)
