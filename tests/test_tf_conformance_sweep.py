"""TF GraphDef conformance sweep (reference: TFGraphTestAllSameDiff —
thousands of tiny frozen TF graphs executed and compared per-op).

Instead of checked-in graph assets (the reference ships ~2k frozen
protobufs), cases are *generated*: every mapped TF op is swept across
parameterized shapes/dtypes/attrs, the golden outputs are minted
in-process by running the same function under TF eager, and the
imported SameDiff graph must match within per-op tolerance.  A final
coverage test reports mapped-vs-swept ops and fails if a mapped op
family is missing from the sweep.
"""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.modelimport.tf_import import (  # noqa: E402
    TFImporter, _MAPPERS)


RNG = np.random.default_rng(2026)

#: TF op types observed across all swept graphs (filled as cases run)
SWEPT_OPS = set()

#: per-TF-op tolerance overrides (float32 default 1e-4/1e-5)
OP_TOL = {
    "Conv2D": (2e-3, 1e-4),
    "DepthwiseConv2dNative": (2e-3, 1e-4),
    "MatMul": (1e-3, 1e-5),
    "BatchMatMulV2": (1e-3, 1e-5),
    "Einsum": (1e-3, 1e-5),
    "Erfc": (1e-4, 1e-6),
    "Log": (1e-3, 1e-5),
    "Pow": (1e-3, 1e-5),
    "Rsqrt": (1e-3, 1e-5),
    "FusedBatchNormV3": (1e-3, 1e-4),
    "Softmax": (1e-4, 1e-6),
}


def _freeze(fn, *specs):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)

    conc = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(conc)
    gd = frozen.graph.as_graph_def()
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    out_names = [t.name.split(":")[0] for t in frozen.outputs]
    return gd, in_names, out_names


def _run_case(fn, args, rtol=1e-4, atol=1e-5):
    specs = [tf.TensorSpec(a.shape, a.dtype) for a in args]
    gd, in_names, out_names = _freeze(fn, *specs)
    ops_here = {n.op for n in gd.node}
    SWEPT_OPS.update(ops_here)
    for op_ in ops_here:            # widest tolerance of any op present
        r, a_ = OP_TOL.get(op_, (0, 0))
        rtol, atol = max(rtol, r), max(atol, a_)
    ref = fn(*[tf.constant(a) for a in args])
    if not isinstance(ref, (list, tuple)):
        ref = [ref]
    sd, vars_ = TFImporter.import_graph_def(gd, out_names)
    feed = {n: a for n, a in zip(in_names, args)}
    out_vars = [vars_[n] for n in out_names]
    res = sd.output(feed, out_vars)
    assert len(out_vars) == len(ref)
    for o, r in zip(out_vars, ref):
        got, want = res[o.name], np.asarray(r)
        assert got.shape == want.shape, (got.shape, want.shape)
        if want.dtype.kind in "fc":
            np.testing.assert_allclose(got, want, rtol=rtol, atol=atol)
        else:
            np.testing.assert_array_equal(got, want)


def F(*shape, lo=None, hi=None, scale=1.0):
    a = (RNG.normal(size=shape) * scale).astype(np.float32)
    if lo is not None:
        a = np.clip(a, lo, hi).astype(np.float32)
    return a


def I(*shape, hi=4):
    return RNG.integers(0, hi, size=shape).astype(np.int32)


# ---------------------------------------------------------------------------
# case generation: (id, fn, args) triples

CASES = []


def case(cid, fn, *args):
    CASES.append(pytest.param(fn, list(args), id=cid))


# --- unary elementwise: every mapped op × 4 ranks --------------------------
# op -> (tf fn, clip-lo, clip-hi); None = unrestricted domain
_UNARY_TF = {
    "neg": (tf.negative, None, None), "abs": (tf.abs, None, None),
    "exp": (tf.exp, None, None), "square": (tf.square, None, None),
    "sign": (tf.sign, None, None), "floor": (tf.floor, None, None),
    "ceil": (tf.math.ceil, None, None), "round": (tf.round, None, None),
    "sin": (tf.sin, None, None), "cos": (tf.cos, None, None),
    "tan": (tf.tan, None, None), "atan": (tf.atan, None, None),
    "sinh": (tf.sinh, None, None), "cosh": (tf.cosh, None, None),
    "tanh": (tf.tanh, None, None), "erf": (tf.math.erf, None, None),
    "erfc": (tf.math.erfc, None, None),
    "sigmoid": (tf.sigmoid, None, None), "relu": (tf.nn.relu, None, None),
    "relu6": (tf.nn.relu6, None, None), "elu": (tf.nn.elu, None, None),
    "selu": (tf.nn.selu, None, None),
    "softplus": (tf.nn.softplus, None, None),
    "softsign": (tf.nn.softsign, None, None),
    "log": (tf.math.log, 0.1, 9.0), "log1p": (tf.math.log1p, -0.5, 9.0),
    "sqrt": (tf.sqrt, 0.0, 9.0), "rsqrt": (tf.math.rsqrt, 0.2, 9.0),
    "asin": (tf.asin, -0.9, 0.9), "acos": (tf.acos, -0.9, 0.9),
    "reciprocal": (tf.math.reciprocal, 0.3, 5.0),
}
_UNARY_SHAPES = [("r1", (7,)), ("r2", (3, 4)), ("r3", (2, 3, 5)),
                 ("r4", (2, 2, 3, 2))]
for name, (op_, lo, hi) in _UNARY_TF.items():
    for sid, shp in _UNARY_SHAPES:
        case(f"unary-{name}-{sid}",
             (lambda op_: lambda x: op_(x))(op_),
             F(*shp, lo=lo, hi=hi, scale=0.8))
case("unary-leakyrelu", lambda x: tf.nn.leaky_relu(x, alpha=0.3),
     F(3, 4))
case("unary-leakyrelu-default", lambda x: tf.nn.leaky_relu(x), F(4,))

# --- binary elementwise: each op × same-shape + broadcast ------------------
_BINARY_TF = {
    "add": tf.add, "sub": tf.subtract, "mul": tf.multiply,
    "realdiv": tf.divide, "maximum": tf.maximum, "minimum": tf.minimum,
    "squared_difference": tf.math.squared_difference,
}
for name, op_ in _BINARY_TF.items():
    case(f"binary-{name}", (lambda op_: lambda a, b: op_(a, b))(op_),
         F(3, 4), F(3, 4))
    case(f"binary-{name}-bcast",
         (lambda op_: lambda a, b: op_(a, b))(op_), F(2, 3, 4), F(4))
case("binary-pow", lambda a, b: tf.pow(a, b),
     F(3, 4, lo=0.2, hi=3.0), F(3, 4, lo=-2.0, hi=2.0))
case("binary-floormod", lambda a, b: tf.math.floormod(a, b),
     F(3, 4), F(3, 4, lo=0.5, hi=3.0))
case("binary-addn", lambda a, b, c: tf.add_n([a, b, c]),
     F(3, 4), F(3, 4), F(3, 4))
# scalar-const broadcast flavor (frozen graphs are full of these)
for name, op_ in _BINARY_TF.items():
    case(f"binary-{name}-scalar",
         (lambda op_: lambda a: op_(a, tf.constant(1.5)))(op_), F(3, 4))
# int32 arithmetic keeps exact semantics
for name, op_ in [("add", tf.add), ("sub", tf.subtract),
                  ("mul", tf.multiply), ("maximum", tf.maximum),
                  ("minimum", tf.minimum)]:
    case(f"binary-{name}-int",
         (lambda op_: lambda a, b: op_(a, b))(op_),
         I(3, 4, hi=9), I(3, 4, hi=9))

# --- comparisons / logical -------------------------------------------------
_CMP_TF = {"less": tf.less, "less_equal": tf.less_equal,
           "greater": tf.greater, "greater_equal": tf.greater_equal,
           "equal": tf.equal, "not_equal": tf.not_equal}
for name, op_ in _CMP_TF.items():
    case(f"cmp-{name}",
         (lambda op_: lambda a, b: tf.cast(op_(a, b), tf.float32))(op_),
         F(3, 4), F(3, 4))
    case(f"cmp-{name}-bcast",
         (lambda op_: lambda a, b: tf.cast(op_(a, b), tf.float32))(op_),
         F(2, 3, 4), F(4))
case("cmp-logical", lambda a, b: tf.cast(
    tf.logical_and(a > 0, tf.logical_or(b > 0, tf.logical_not(a < b))),
    tf.float32), F(3, 4), F(3, 4))
case("cmp-select", lambda c, a, b: tf.where(c > 0, a, b),
     F(3, 4), F(3, 4), F(3, 4))
case("cmp-select-scalar", lambda c, a: tf.where(c > 0, a, 0.0),
     F(2, 5), F(2, 5))

# --- reductions: op × axis × keepdims --------------------------------------
_RED_TF = {"sum": tf.reduce_sum, "mean": tf.reduce_mean,
           "max": tf.reduce_max, "min": tf.reduce_min,
           "prod": tf.reduce_prod}
for name, op_ in _RED_TF.items():
    for ax, kd in [(0, False), (1, True), ((0, 2), False),
                   ((-1,), True)]:
        axid = str(ax).replace(" ", "")
        case(f"reduce-{name}-ax{axid}-kd{kd}",
             (lambda op_, ax, kd: lambda x: op_(
                 x, axis=ax, keepdims=kd))(op_, ax, kd),
             F(2, 3, 4, scale=0.5))

# --- matmul family ---------------------------------------------------------
case("matmul-plain", lambda a, b: tf.matmul(a, b), F(3, 4), F(4, 5))
case("matmul-ta", lambda a, b: tf.matmul(a, b, transpose_a=True),
     F(4, 3), F(4, 5))
case("matmul-tb", lambda a, b: tf.matmul(a, b, transpose_b=True),
     F(3, 4), F(5, 4))
case("matmul-tatb", lambda a, b: tf.matmul(
    a, b, transpose_a=True, transpose_b=True), F(4, 3), F(5, 4))
case("matmul-batch", lambda a, b: tf.matmul(a, b),
     F(2, 3, 4), F(2, 4, 5))
case("matmul-batch-tb", lambda a, b: tf.matmul(a, b, transpose_b=True),
     F(2, 3, 4), F(2, 5, 4))
for eq in ["ij,jk->ik", "bij,bjk->bik", "bth,hd->btd",
           "bhtd,bhsd->bhts", "ij->ji"]:
    eqid = eq.replace(",", "_").replace("->", "-")
    if eq == "ij->ji":
        case(f"einsum-{eqid}",
             (lambda eq: lambda a: tf.einsum(eq, a))(eq), F(3, 4))
    elif eq == "bhtd,bhsd->bhts":
        case(f"einsum-{eqid}",
             (lambda eq: lambda a, b: tf.einsum(eq, a, b))(eq),
             F(2, 2, 3, 4), F(2, 2, 5, 4))
    else:
        shapes = {"ij,jk->ik": [(3, 4), (4, 5)],
                  "bij,bjk->bik": [(2, 3, 4), (2, 4, 5)],
                  "bth,hd->btd": [(2, 3, 4), (4, 5)]}[eq]
        case(f"einsum-{eqid}",
             (lambda eq: lambda a, b: tf.einsum(eq, a, b))(eq),
             *[F(*s) for s in shapes])

# --- shape manipulation ----------------------------------------------------
case("reshape-const", lambda x: tf.reshape(x, [4, 6]), F(2, 3, 4))
case("reshape-minus1", lambda x: tf.reshape(x, [2, -1]), F(2, 3, 4))
case("reshape-shapedriven", lambda x: tf.reshape(
    x, [tf.shape(x)[0], -1]), F(3, 4, 5))
case("transpose-r2", lambda x: tf.transpose(x), F(3, 4))
case("transpose-perm", lambda x: tf.transpose(x, [0, 2, 1]), F(2, 3, 4))
case("transpose-perm2", lambda x: tf.transpose(x, [2, 0, 1]), F(2, 3, 4))
case("expanddims-0", lambda x: tf.expand_dims(x, 0), F(3, 4))
case("expanddims-neg", lambda x: tf.expand_dims(x, -1), F(3, 4))
case("squeeze-all", lambda x: tf.squeeze(x), F(1, 3, 1, 4))
case("squeeze-ax", lambda x: tf.squeeze(x, axis=2), F(2, 3, 1, 4))
case("concat-ax0", lambda a, b: tf.concat([a, b], 0), F(2, 4), F(3, 4))
case("concat-ax1", lambda a, b: tf.concat([a, b], 1), F(3, 2), F(3, 5))
case("concat-neg", lambda a, b: tf.concat([a, b], -1),
     F(2, 3, 2), F(2, 3, 4))
case("pack-ax0", lambda a, b: tf.stack([a, b]), F(3, 4), F(3, 4))
case("pack-ax1", lambda a, b: tf.stack([a, b], axis=1), F(3, 4), F(3, 4))
case("unpack", lambda x: tf.add_n(tf.unstack(x, axis=1)), F(3, 4, 2))
case("tile", lambda x: tf.tile(x, [2, 3]), F(2, 3))
case("tile-r3", lambda x: tf.tile(x, [1, 2, 2]), F(2, 2, 3))
case("gather-ax0", lambda x, i: tf.gather(x, i), F(5, 3), I(4, hi=5))
case("gather-ax1", lambda x, i: tf.gather(x, i, axis=1),
     F(3, 6), I(2, hi=6))
case("pad-zero", lambda x: tf.pad(x, [[1, 0], [0, 2]]), F(2, 3))
case("pad-value", lambda x: tf.pad(
    x, [[1, 1], [2, 0]], constant_values=3.5), F(2, 3))
case("slice-basic", lambda x: tf.slice(x, [1, 0], [2, 3]), F(4, 5))
case("slice-neg1", lambda x: tf.slice(x, [0, 2], [-1, -1]), F(3, 6))
case("stridedslice-basic", lambda x: x[1:3, ::2], F(4, 6))
case("stridedslice-shrink", lambda x: x[:, 1], F(4, 6))
case("stridedslice-negstep", lambda x: x[::-1], F(5, 3))
case("stridedslice-open", lambda x: x[1:], F(5, 3))
case("split-even", lambda x: tf.add_n(tf.split(x, 3, axis=1)), F(2, 9))
case("splitv", lambda x: tf.concat(
    tf.split(x, [2, 4], axis=1)[::-1], 1), F(3, 6))
case("shape-of", lambda x: tf.cast(tf.shape(x), tf.float32), F(3, 5))
case("size-rank", lambda x: tf.cast(
    tf.size(x) + tf.rank(x), tf.float32), F(2, 3))
case("fill-shapechain", lambda x: x + tf.fill([3, 4], 2.0), F(3, 4))
case("range-chain", lambda x: x * tf.range(4.0), F(3, 4))
case("cast-int", lambda x: tf.cast(tf.cast(x, tf.int32), tf.float32),
     F(3, 4, scale=3.0))
case("onehot", lambda i: tf.one_hot(i, 5), I(6, hi=5))
case("argmax-ax1", lambda x: tf.cast(tf.argmax(x, 1), tf.float32),
     F(4, 6))
case("matrixbandpart", lambda x: tf.linalg.band_part(x, 1, 2), F(5, 5))
case("cumsum-plain", lambda x: tf.cumsum(x, axis=1), F(3, 6))
case("cumsum-excl", lambda x: tf.cumsum(x, axis=0, exclusive=True),
     F(4, 3))
case("cumsum-rev", lambda x: tf.cumsum(x, axis=1, reverse=True),
     F(3, 6))
case("cumsum-exclrev", lambda x: tf.cumsum(
    x, axis=1, exclusive=True, reverse=True), F(3, 6))
case("topk", lambda x: tf.math.top_k(x, k=2)[0], F(4, 7))
case("topk-k1", lambda x: tf.math.top_k(x, k=1)[0], F(3, 5))
case("topk-indices", lambda x: tf.cast(
    tf.math.top_k(x, k=3)[1], tf.float32), F(2, 9))
case("argmax-ax0", lambda x: tf.cast(tf.argmax(x, 0), tf.float32),
     F(4, 6))
case("argmax-r3", lambda x: tf.cast(tf.argmax(x, 2), tf.float32),
     F(2, 3, 5))
case("onehot-r2", lambda i: tf.one_hot(i, 3), I(2, 4, hi=3))
case("cast-bool-roundtrip", lambda x: tf.cast(
    tf.cast(x, tf.bool), tf.float32), F(3, 4))
case("stridedslice-step3", lambda x: x[::3], F(9, 2))
case("stridedslice-negbegin", lambda x: x[-2:], F(5, 3))
case("stridedslice-mixed", lambda x: x[1:-1, 2], F(4, 6))
case("stridedslice-r3", lambda x: x[:, 1:3, ::2], F(2, 4, 6))
case("gather-r3", lambda x, i: tf.gather(x, i, axis=2),
     F(2, 3, 6), I(4, hi=6))
case("pad-r3", lambda x: tf.pad(x, [[0, 0], [1, 1], [2, 2]]),
     F(2, 3, 4))
case("transpose-r4", lambda x: tf.transpose(x, [0, 3, 1, 2]),
     F(2, 3, 4, 2))
case("tile-r1", lambda x: tf.tile(x, [4]), F(3))
case("concat-three", lambda a, b, c: tf.concat([a, b, c], 1),
     F(2, 1), F(2, 2), F(2, 3))
case("pack-neg", lambda a, b: tf.stack([a, b], axis=-1),
     F(3, 4), F(3, 4))
case("range-int", lambda x: x + tf.cast(
    tf.range(2, 10, 2), tf.float32), F(3, 4))

# --- nn ops ----------------------------------------------------------------
case("biasadd", lambda x, b: tf.nn.bias_add(x, b), F(4, 6), F(6))
case("softmax", lambda x: tf.nn.softmax(x), F(4, 6))
case("softmax-r3", lambda x: tf.nn.softmax(x), F(2, 3, 5))
case("logsoftmax", lambda x: tf.nn.log_softmax(x), F(4, 6))
for strides, pad in [(1, "SAME"), (1, "VALID"), (2, "SAME"),
                     (2, "VALID")]:
    case(f"conv2d-s{strides}-{pad}",
         (lambda s, p: lambda x, w: tf.nn.conv2d(
             x, w, strides=[1, s, s, 1], padding=p))(strides, pad),
         F(2, 8, 8, 3, scale=0.5), F(3, 3, 3, 4, scale=0.3))
case("conv2d-dilated", lambda x, w: tf.nn.conv2d(
    x, w, strides=[1, 1, 1, 1], padding="SAME", dilations=2),
    F(1, 10, 10, 2, scale=0.5), F(3, 3, 2, 3, scale=0.3))
case("depthwise", lambda x, w: tf.nn.depthwise_conv2d(
    x, w, strides=[1, 1, 1, 1], padding="SAME"),
    F(2, 8, 8, 3, scale=0.5), F(3, 3, 3, 2, scale=0.3))
for pool, pad in [("max", "SAME"), ("max", "VALID"), ("avg", "SAME"),
                  ("avg", "VALID")]:
    fn_ = tf.nn.max_pool2d if pool == "max" else tf.nn.avg_pool2d
    case(f"pool-{pool}-{pad}",
         (lambda fn_, p: lambda x: fn_(x, 2, 2, p))(fn_, pad),
         F(2, 8, 8, 3))
case("fusedbn-inference", lambda x: tf.compat.v1.nn.fused_batch_norm(
    x, scale=np.ones(3, np.float32) * 1.5,
    offset=np.ones(3, np.float32) * 0.2,
    mean=np.zeros(3, np.float32), variance=np.ones(3, np.float32),
    is_training=False)[0], F(2, 4, 4, 3))
case("conv2d-1x1", lambda x, w: tf.nn.conv2d(
    x, w, strides=[1, 1, 1, 1], padding="VALID"),
    F(2, 5, 5, 4, scale=0.5), F(1, 1, 4, 6, scale=0.3))
case("conv2d-5x5", lambda x, w: tf.nn.conv2d(
    x, w, strides=[1, 1, 1, 1], padding="SAME"),
    F(1, 9, 9, 2, scale=0.5), F(5, 5, 2, 3, scale=0.2))
case("conv2d-rect-stride", lambda x, w: tf.nn.conv2d(
    x, w, strides=[1, 2, 1, 1], padding="SAME"),
    F(1, 8, 8, 2, scale=0.5), F(3, 3, 2, 3, scale=0.3))
case("pool-max-k3", lambda x: tf.nn.max_pool2d(x, 3, 1, "VALID"),
     F(2, 7, 7, 2))
case("pool-avg-k3s1", lambda x: tf.nn.avg_pool2d(x, 3, 1, "SAME"),
     F(2, 7, 7, 2))
case("biasadd-nhwc", lambda x, b: tf.nn.bias_add(x, b),
     F(2, 4, 4, 3), F(3))
case("softmax-ax-neg", lambda x: tf.nn.softmax(x, axis=1), F(3, 4, 5))
case("logsoftmax-r3", lambda x: tf.nn.log_softmax(x), F(2, 3, 5))

# --- int dtype paths -------------------------------------------------------
case("int-arith", lambda a, b: tf.cast(a + b * 2, tf.float32),
     I(3, 4), I(3, 4))
case("int-reduce", lambda a: tf.cast(tf.reduce_sum(a, 1), tf.float32),
     I(3, 4, hi=9))
case("int-gather-concat", lambda x, i: tf.concat(
    [tf.gather(x, i), x[:2]], 0), F(5, 3), I(3, hi=5))

# --- composite graphs (multi-op, shape-arithmetic heavy) -------------------
case("composite-mlp", lambda x, w1, w2: tf.nn.softmax(
    tf.matmul(tf.nn.relu(tf.matmul(x, w1)), w2)),
    F(4, 8), F(8, 16, scale=0.3), F(16, 3, scale=0.3))
case("composite-norm", lambda x: (x - tf.reduce_mean(x, -1, True))
     / tf.sqrt(tf.math.reduce_variance(x, -1, True) + 1e-5)
     if hasattr(tf.math, "reduce_variance_unused") else
     (x - tf.reduce_mean(x, -1, True)) * tf.math.rsqrt(
         tf.reduce_mean(tf.square(x - tf.reduce_mean(x, -1, True)),
                        -1, True) + 1e-5), F(3, 8))
case("composite-attention", lambda q, k, v: tf.matmul(tf.nn.softmax(
    tf.matmul(q, k, transpose_b=True)
    / tf.sqrt(tf.cast(tf.shape(q)[-1], tf.float32))), v),
    F(2, 5, 4), F(2, 5, 4), F(2, 5, 4))
case("composite-flatten-dense", lambda x, w: tf.matmul(
    tf.reshape(x, [tf.shape(x)[0], -1]), w),
    F(3, 4, 5), F(20, 6, scale=0.3))
case("composite-mean-sub", lambda x: x - tf.reduce_mean(x, 0),
     F(6, 4))
case("composite-cumsum-mask", lambda x: x * tf.cast(
    tf.cumsum(tf.ones_like(x), 1) <= 3.0, tf.float32), F(2, 6))
case("composite-gelu", lambda x: 0.5 * x * (1.0 + tf.math.erf(
    x / tf.sqrt(2.0))), F(4, 6))
case("composite-residual", lambda x, w: x + tf.matmul(
    tf.nn.relu(tf.matmul(x, w)), tf.transpose(w)),
    F(3, 6), F(6, 6, scale=0.3))
case("composite-minmax-norm", lambda x: (x - tf.reduce_min(x, 0)) / (
    tf.reduce_max(x, 0) - tf.reduce_min(x, 0) + 1e-6), F(5, 3))
case("composite-swish", lambda x: x * tf.sigmoid(x), F(4, 6))
case("composite-clip", lambda x: tf.minimum(tf.maximum(x, -1.0), 1.0),
     F(4, 6, scale=2.0))
case("composite-conv-bn-relu", lambda x, w: tf.nn.relu(
    tf.compat.v1.nn.fused_batch_norm(
        tf.nn.conv2d(x, w, strides=[1, 1, 1, 1], padding="SAME"),
        scale=np.ones(4, np.float32), offset=np.zeros(4, np.float32),
        mean=np.zeros(4, np.float32),
        variance=np.ones(4, np.float32), is_training=False)[0]),
    F(1, 6, 6, 2, scale=0.5), F(3, 3, 2, 4, scale=0.3))
case("composite-pool-flatten", lambda x, w: tf.matmul(tf.reshape(
    tf.nn.max_pool2d(x, 2, 2, "VALID"), [tf.shape(x)[0], -1]), w),
    F(2, 4, 4, 3), F(12, 5, scale=0.3))
case("composite-masked-mean", lambda x, m: tf.reduce_sum(x * m, 1)
     / (tf.reduce_sum(m, 1) + 1e-6), F(3, 6), (RNG.random((3, 6)) > 0.4)
     .astype(np.float32))
case("composite-embedding-lookup", lambda e, i: tf.reduce_mean(
    tf.gather(e, i), axis=1), F(10, 4, scale=0.5), I(3, 5, hi=10))


@pytest.mark.parametrize("fn,args", CASES)
def test_conformance(fn, args):
    _run_case(fn, args)


# --- TF1-era raw graphs ----------------------------------------------------
# TF2 tracing emits AddV2/SelectV2 and constant-folds Shape/Rank of
# static-shape inputs, so the legacy ops only appear in v1 GraphDefs.
# Build those directly with raw_ops and mint goldens via GraphRunner
# (the same path the reference's TF runner plays for golden minting).

RAW_CASES = []


def raw_case(cid, builder, args):
    RAW_CASES.append(pytest.param(builder, args, id=cid))


raw_case("raw-add-select-rank-shape", lambda x, y: (
    tf.raw_ops.Select(
        condition=tf.raw_ops.Greater(x=x, y=y),
        x=tf.raw_ops.Add(x=x, y=y),
        # v1 Select does not broadcast: expand the scalar to x's shape.
        # Shape(x)[0] also exercises import-time StridedSlice folding
        # over a genuine (un-folded-by-TF) Shape node.
        y=x * 0.0 + tf.cast(tf.raw_ops.Rank(input=x)
                            + tf.raw_ops.Shape(input=x)[0],
                            tf.float32))),
    [F(3, 4), F(3, 4)])
raw_case("raw-div-inv", lambda x, y: tf.raw_ops.Div(
    x=tf.raw_ops.Inv(x=x), y=y),
    [F(3, 4, lo=0.5, hi=4.0), F(3, 4, lo=0.5, hi=4.0)])
raw_case("raw-gather-pad", lambda x, i: tf.raw_ops.Pad(
    input=tf.raw_ops.Gather(params=x, indices=i),
    paddings=tf.constant([[0, 1], [1, 0]])), [F(5, 3), I(4, hi=5)])


@pytest.mark.parametrize("builder,args", RAW_CASES)
def test_conformance_raw_v1(builder, args):
    from deeplearning4j_tpu.modelimport.graph_runner import GraphRunner

    g = tf.compat.v1.Graph()
    with g.as_default():
        phs = [tf.compat.v1.placeholder(
            tf.as_dtype(a.dtype), a.shape, name=f"in{k}")
            for k, a in enumerate(args)]
        out = builder(*phs)
        out = tf.identity(out, name="out")
    gd = g.as_graph_def()
    SWEPT_OPS.update(n.op for n in gd.node)
    in_names = [f"in{k}" for k in range(len(args))]
    runner = GraphRunner(gd, input_names=in_names, output_names=["out"])
    golden = runner.run({n: a for n, a in zip(in_names, args)})["out"]
    sd, vars_ = TFImporter.import_graph_def(gd, ["out"])
    out_var = vars_["out"]           # Identity aliases its producer,
    res = sd.output({n: a for n, a in zip(in_names, args)},
                    [out_var])       # so key results by .name
    np.testing.assert_allclose(res[out_var.name], golden,
                               rtol=1e-4, atol=1e-5)


def test_sweep_size_and_coverage_report():
    """The sweep must stay ≥300 cases and cover every mapped op family.

    Structural/source ops that freezing itself emits (Const,
    Placeholder, Identity...) are exempt; everything else in _MAPPERS
    must appear in at least one swept graph.
    """
    assert len(CASES) >= 300, f"sweep shrank to {len(CASES)} cases"
    if not SWEPT_OPS:
        pytest.skip("conformance cases did not run in this session")
    exempt = {
        "Const", "Placeholder", "PlaceholderWithDefault", "Identity",
        "StopGradient", "PreventGradient", "Snapshot", "CheckNumerics",
        # aliases TF2 tracing never emits (exercised via raw v1 cases
        # where constructible, kept for TF1 graphs otherwise)
        "BatchMatMul", "FusedBatchNorm", "FusedBatchNormV2",
    }
    mapped = set(_MAPPERS) - exempt
    unswept = sorted(mapped - SWEPT_OPS)
    assert not unswept, (
        f"mapped TF ops never exercised by the sweep: {unswept}")


def test_dynamic_batch_shape_driven_reshape():
    """Frozen graphs traced with a None batch dim keep real Shape nodes
    (TF cannot fold them); the importer must resolve the
    Shape→StridedSlice→Pack→Reshape chain symbolically at trace time."""
    x = F(4, 5, 6)

    def fn(t):
        s = tf.shape(t)
        flat = tf.reshape(t, [s[0], -1])
        return tf.nn.softmax(flat)

    gd, in_names, out_names = _freeze(
        fn, tf.TensorSpec((None, 5, 6), tf.float32))
    assert "Shape" in {n.op for n in gd.node}   # really dynamic
    SWEPT_OPS.update(n.op for n in gd.node)
    golden = fn(tf.constant(x)).numpy()
    sd, vars_ = TFImporter.import_graph_def(gd, out_names)
    out = vars_[out_names[0]]
    res = sd.output({in_names[0]: x}, [out])
    np.testing.assert_allclose(res[out.name], golden,
                               rtol=1e-4, atol=1e-5)


def test_dynamic_batch_concat_shape_target():
    """Shape-vector built by ConcatV2([batch_slice, const_tail])."""
    x = F(3, 4, 5)

    def fn(t):
        tail = tf.constant([20], tf.int32)
        tgt = tf.concat([tf.shape(t)[:1], tail], 0)
        return tf.reshape(t, tgt) * 2.0

    gd, in_names, out_names = _freeze(
        fn, tf.TensorSpec((None, 4, 5), tf.float32))
    SWEPT_OPS.update(n.op for n in gd.node)
    golden = fn(tf.constant(x)).numpy()
    sd, vars_ = TFImporter.import_graph_def(gd, out_names)
    out = vars_[out_names[0]]
    res = sd.output({in_names[0]: x}, [out])
    np.testing.assert_allclose(res[out.name], golden,
                               rtol=1e-5, atol=1e-6)


def test_dynamic_batch_import_serializes():
    """reshape_sym keeps dynamic-batch imports JSON-serializable (no
    python closures in the graph): save → load → same outputs."""
    import os
    import tempfile

    from deeplearning4j_tpu.autodiff.samediff import SameDiff

    x = F(4, 5, 6)

    def fn(t):
        s = tf.shape(t)
        return tf.nn.softmax(tf.reshape(t, [s[0], -1]))

    gd, in_names, out_names = _freeze(
        fn, tf.TensorSpec((None, 5, 6), tf.float32))
    golden = fn(tf.constant(x)).numpy()
    sd, vars_ = TFImporter.import_graph_def(gd, out_names)
    out = vars_[out_names[0]]
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "g.zip")
        sd.save(p)
        sd2 = SameDiff.load(p)
        res = sd2.output({in_names[0]: x}, [sd2.get_variable(out.name)])
    np.testing.assert_allclose(res[out.name], golden,
                               rtol=1e-4, atol=1e-5)
