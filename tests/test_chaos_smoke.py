"""tools/chaos.py tier-1 smoke: the chaos harness itself must stay
runnable — one training plan and one serving plan end-to-end in
subprocesses, asserting convergence-to-baseline under injected faults
(ISSUE 3 satellite; the full plan sweep is a shell away:
``python tools/chaos.py --plan <each>``)."""
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
CHAOS = str(REPO / "tools" / "chaos.py")


def _run(*args):
    r = subprocess.run(
        [sys.executable, CHAOS, *args], cwd=REPO, text=True,
        capture_output=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stdout + r.stderr
    payload = r.stdout[r.stdout.index("{"):]
    return json.loads(payload)


def test_chaos_list_names_every_plan():
    r = subprocess.run([sys.executable, CHAOS, "--list"], cwd=REPO,
                       text=True, capture_output=True, timeout=120,
                       env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0
    from deeplearning4j_tpu.resilience.faults import NAMED_PLANS
    for name in NAMED_PLANS:
        assert name in r.stdout


def test_chaos_training_plan_converges_to_baseline():
    out = _run("--plan", "worker-crash", "--epochs", "3")
    assert out["ok"] is True
    res = out["results"][0]
    assert res["faults_fired"] >= 1
    assert res["restarts"] >= 1
    # clean restore path: the recovered trajectory is bit-identical
    assert res["exact_resume"] is True


def test_chaos_serving_plan_sheds_and_survives():
    out = _run("--plan", "serving-crash")
    assert out["ok"] is True
    res = out["results"][0]
    assert res["faults_fired"] >= 1
    assert res["shed_at_enqueue"] > 0
    assert res["errored_by_fault"] > 0
    assert res["completed"] > 0
    assert res["worker_survived"] is True
    # the same plan drills the continuous-batching gateway: in-flight
    # sequences shed with a structured error (tokens-so-far attached),
    # the paged pool comes back whole, and the same worker serves a
    # post-fault wave — never a wedged slot or leaked page
    gw = out["results"][1]
    assert gw["mode"] == "serving-gateway"
    assert gw["faults_fired"] >= 1
    assert gw["aborted"] > 0 and gw["tokens_salvaged"] > 0
    assert gw["completed"] + gw["aborted"] == gw["requests"]
    assert gw["post_fault_completed"] == 3
    assert gw["pages_conserved"] is True
    # and once more with CoW prefix sharing + speculative decode live:
    # a mid-trace fault under refcounted shared pages must shed only
    # the aborted sequences' refs (shared pages survive their
    # siblings, the pool is conserved both after the shed and after a
    # post-fault shared wave whose outputs are dense-identical)
    cow = out["results"][2]
    assert cow["mode"] == "serving-gateway-cow"
    assert cow["faults_fired"] >= 1
    assert cow["aborted"] > 0
    assert cow["completed"] + cow["aborted"] == cow["requests"]
    assert cow["prefix_hits"] >= 5 and cow["cow_copies"] >= 3
    assert cow["post_fault_dense_identical"] == 3
    assert cow["pages_conserved"] is True


# the ISSUE 18 acceptance drill: 3 leased replicas, a multi-tenant
# trace, one replica killed mid-trace (plan "replica-crash"), and the
# router + supervisor + compile-store triad must hold every contract at
# once — detection within one lease window, zero hung clients, losses
# within the shed budget (all structured aborts), a respawned replica
# whose warm path rides the compile store (aot/persistent-hit
# evidence, cold TTFT ≤ 1.2× warm), and a post-drill epoch flip with
# the new replica live+ready. ~20 s of wall plus warmups: slow lane.
@pytest.mark.slow
def test_chaos_serving_fleet_drill():
    r = subprocess.run(
        [sys.executable, CHAOS, "--serving-fleet"], cwd=REPO,
        text=True, capture_output=True, timeout=580,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-4000:]
    out = json.loads(r.stdout[r.stdout.index("{"):])
    assert out["ok"] is True, out
    res = out["results"][0]
    assert res["mode"] == "serving-fleet"
    assert res["victim_rc"] == 17                  # fault-plan exit
    assert res["detect_s"] <= res["lease_s"] + 1.0
    assert res["hung"] == 0
    assert res["completed"] + res["aborted"] == res["requests"]
    assert res["aborted"] <= res["shed_budget"]
    assert res["router_sheds"] <= res["shed_budget"]
    assert res["router_reroutes"] >= 1
    assert res["new_manifest_hit"] is True
    assert res["new_persistent_hits"] > 0 and res["new_aot_hits"] > 0
    assert res["cold_ttft_p50_s"] <= \
        1.2 * res["warm_ttft_p50_s"] + 0.01
    assert res["epoch_after"] > res["epoch_before"]
    assert res["new_replica_ready"] and res["new_replica_live"]
    assert res["clean_exit"] is True
