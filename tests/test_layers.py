"""Layer-level tests w/ finite-difference gradient checks.

Reference analog: org.deeplearning4j.gradientcheck.GradientCheckTests,
CNNGradientCheckTest, LSTMGradientCheckTests (SURVEY §4).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.layers import (
    ActivationLayer, BatchNormalization, Bidirectional, ConvolutionLayer,
    Convolution1DLayer, DenseLayer, DepthwiseConvolution2DLayer,
    DropoutLayer, EmbeddingLayer, GlobalPoolingLayer, GravesLSTM, GRU,
    LastTimeStep, LayerNormalization, LSTM, MultiHeadAttention,
    PReLULayer, SelfAttentionLayer, SeparableConvolution2DLayer,
    SimpleRnn, SubsamplingLayer, TransformerEncoderBlock,
    LocalResponseNormalization, Upsampling2DLayer, SpaceToDepthLayer,
    DepthToSpaceLayer,
)
from deeplearning4j_tpu.utils import check_gradients

KEY = jax.random.PRNGKey(0)


def _gradcheck_layer(layer, input_shape, batch=2, train=False, mask=None,
                     tol=1e-4):
    params, state, out_shape = layer.init(KEY, input_shape)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch,) + input_shape)

    def loss(p, xx):
        y, _ = layer.apply(p, state, xx, train=train, mask=mask)
        return jnp.sum(jnp.sin(y))  # nonlinear reduction exercises grads

    if params:
        check_gradients(loss, params, x, max_rel_error=tol)
    # also check input gradients
    check_gradients(lambda xx, p: loss(p, xx), x, params,
                    max_rel_error=tol)
    return out_shape


def test_dense_gradcheck():
    out = _gradcheck_layer(DenseLayer(n_out=3, activation="tanh"), (4,))
    assert out == (3,)


def test_dense_layernorm_gradcheck():
    _gradcheck_layer(DenseLayer(n_out=3, activation="sigmoid",
                                has_layer_norm=True), (4,))


def test_conv2d_gradcheck():
    out = _gradcheck_layer(
        ConvolutionLayer(n_out=2, kernel_size=(2, 2), padding="VALID",
                         activation="tanh"), (4, 4, 2))
    assert out == (3, 3, 2)


def test_conv2d_same_shape():
    layer = ConvolutionLayer(n_out=3, kernel_size=(3, 3), padding="SAME",
                             stride=(2, 2))
    _, _, out = layer.init(KEY, (8, 8, 1))
    assert out == (4, 4, 3)


def test_conv1d_gradcheck():
    _gradcheck_layer(Convolution1DLayer(n_out=2, kernel_size=(2,),
                                        activation="tanh"), (5, 3))


def test_depthwise_separable():
    _gradcheck_layer(DepthwiseConvolution2DLayer(
        kernel_size=(2, 2), depth_multiplier=2), (3, 3, 2))
    _gradcheck_layer(SeparableConvolution2DLayer(
        n_out=3, kernel_size=(2, 2)), (3, 3, 2))


def test_pooling_types():
    for pt in ("max", "avg", "pnorm", "sum"):
        layer = SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                                 pooling_type=pt)
        _, _, out = layer.init(KEY, (4, 4, 3))
        assert out == (2, 2, 3)
        x = jax.random.normal(KEY, (2, 4, 4, 3))
        y, _ = layer.apply({}, {}, x)
        assert y.shape == (2, 2, 2, 3)


def test_avg_pool_matches_numpy():
    layer = SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                             pooling_type="avg")
    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    y, _ = layer.apply({}, {}, x)
    expect = np.asarray(x).reshape(2, 2, 2, 2, 1).mean(axis=(1, 3))
    np.testing.assert_allclose(np.asarray(y)[0, ..., 0],
                               expect[..., 0].reshape(2, 2), rtol=1e-6)


def test_batchnorm_train_and_infer():
    layer = BatchNormalization()
    params, state, _ = layer.init(KEY, (3,))
    x = jax.random.normal(KEY, (16, 3)) * 5 + 2
    y, new_state = layer.apply(params, state, x, train=True)
    np.testing.assert_allclose(np.mean(np.asarray(y), axis=0), 0,
                               atol=1e-5)
    np.testing.assert_allclose(np.std(np.asarray(y), axis=0), 1,
                               atol=1e-2)
    assert not np.allclose(np.asarray(new_state["mean"]), 0)
    # inference path uses running stats (different result)
    y2, s2 = layer.apply(params, new_state, x, train=False)
    assert s2 is new_state


def test_batchnorm_gradcheck():
    _gradcheck_layer(BatchNormalization(), (3,), batch=4, train=True,
                     tol=5e-4)


def test_layernorm_lrn():
    _gradcheck_layer(LayerNormalization(), (5,), tol=5e-4)
    layer = LocalResponseNormalization()
    x = jax.random.normal(KEY, (2, 3, 3, 8))
    y, _ = layer.apply({}, {}, x)
    assert y.shape == x.shape


def test_lstm_gradcheck():
    _gradcheck_layer(LSTM(n_out=3), (4, 2), tol=5e-4)


def test_graves_lstm_peephole_gradcheck():
    _gradcheck_layer(GravesLSTM(n_out=2), (3, 2), tol=5e-4)


def test_gru_simplernn():
    _gradcheck_layer(GRU(n_out=3), (3, 2), tol=5e-4)
    _gradcheck_layer(SimpleRnn(n_out=3), (3, 2), tol=5e-4)


def test_lstm_masking_holds_state():
    layer = LSTM(n_out=4)
    params, state, _ = layer.init(KEY, (5, 3))
    x = jax.random.normal(KEY, (2, 5, 3))
    mask = jnp.array([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], jnp.float32)
    y, s = layer.apply(params, state, x, mask=mask)
    # masked outputs zero
    np.testing.assert_allclose(np.asarray(y[0, 3:]), 0, atol=1e-7)
    # state for example 0 equals state after step 2 (held)
    y2, s2 = layer.apply(params, state, x[:, :3], mask=mask[:, :3])
    np.testing.assert_allclose(np.asarray(s["h"][0]),
                               np.asarray(s2["h"][0]), rtol=1e-5)


def test_lstm_stored_state_continuation():
    layer = LSTM(n_out=3)
    params, state, _ = layer.init(KEY, (6, 2))
    x = jax.random.normal(KEY, (1, 6, 2))
    y_full, _ = layer.apply(params, state, x)
    y1, s1 = layer.apply(params, state, x[:, :3])
    y2, _ = layer.apply(params, state, x[:, 3:], initial_state=s1)
    np.testing.assert_allclose(np.asarray(y_full[:, 3:]),
                               np.asarray(y2), rtol=1e-5, atol=1e-6)


def test_bidirectional_modes():
    for mode in ("concat", "add", "mul", "average"):
        layer = Bidirectional(fwd=LSTM(n_out=3), mode=mode)
        params, state, out = layer.init(KEY, (4, 2))
        x = jax.random.normal(KEY, (2, 4, 2))
        y, _ = layer.apply(params, state, x)
        want = 6 if mode == "concat" else 3
        assert y.shape == (2, 4, want)
        assert out[-1] == want


def test_last_time_step_masked():
    layer = LastTimeStep(underlying=LSTM(n_out=3))
    params, state, out = layer.init(KEY, (5, 2))
    assert out == (3,)
    x = jax.random.normal(KEY, (2, 5, 2))
    mask = jnp.array([[1, 1, 0, 0, 0], [1, 1, 1, 1, 1]], jnp.float32)
    y, _ = layer.apply(params, state, x, mask=mask)
    # example 0's output equals running only 2 steps
    yfull, s2 = layer.apply(params, state, x[:, :2], mask=mask[:, :2])
    np.testing.assert_allclose(np.asarray(y[0]), np.asarray(yfull[0]),
                               rtol=1e-5)


def test_embedding():
    layer = EmbeddingLayer(n_in=10, n_out=4)
    params, state, _ = layer.init(KEY, (1,))
    idx = jnp.array([1, 5, 9])
    y, _ = layer.apply(params, state, idx)
    assert y.shape == (3, 4)
    np.testing.assert_allclose(np.asarray(y[0]),
                               np.asarray(params["W"][1]))


def test_attention_layers():
    _gradcheck_layer(MultiHeadAttention(n_out=4, n_heads=2), (3, 4),
                     tol=5e-4)
    layer = SelfAttentionLayer(n_out=4, n_heads=2)
    params, state, out = layer.init(KEY, (5, 4))
    assert out == (5, 4)
    x = jax.random.normal(KEY, (2, 5, 4))
    mask = jnp.array([[1, 1, 1, 0, 0], [1] * 5], jnp.float32)
    y, _ = layer.apply(params, state, x, mask=mask)
    np.testing.assert_allclose(np.asarray(y[0, 3:]), 0, atol=1e-6)


def test_attention_mask_invariance():
    """Masked-out keys must not affect unmasked outputs."""
    layer = MultiHeadAttention(n_out=4, n_heads=2, project_out=False)
    params, state, _ = layer.init(KEY, (5, 4))
    x = jax.random.normal(KEY, (1, 5, 4))
    mask = jnp.array([[1, 1, 1, 0, 0]], jnp.float32)
    y1, _ = layer.apply(params, state, x, mask=mask)
    x2 = x.at[:, 3:].set(99.0)  # garbage in masked positions
    y2, _ = layer.apply(params, state, x2, mask=mask)
    np.testing.assert_allclose(np.asarray(y1[:, :3]),
                               np.asarray(y2[:, :3]), rtol=1e-4)


def test_transformer_block():
    layer = TransformerEncoderBlock(n_heads=2)
    params, state, out = layer.init(KEY, (4, 8))
    x = jax.random.normal(KEY, (2, 4, 8))
    y, _ = layer.apply(params, state, x)
    assert y.shape == (2, 4, 8)


def test_global_pooling_masked():
    layer = GlobalPoolingLayer(pooling_type="avg")
    x = jnp.stack([jnp.ones((4, 3)), 2 * jnp.ones((4, 3))])
    mask = jnp.array([[1, 1, 0, 0], [1, 1, 1, 1]], jnp.float32)
    y, _ = layer.apply({}, {}, x, mask=mask)
    np.testing.assert_allclose(np.asarray(y), [[1] * 3, [2] * 3])


def test_spatial_utils():
    x = jax.random.normal(KEY, (1, 4, 4, 4))
    y, _ = SpaceToDepthLayer(block_size=2).apply({}, {}, x)
    assert y.shape == (1, 2, 2, 16)
    z, _ = DepthToSpaceLayer(block_size=2).apply({}, {}, y)
    np.testing.assert_allclose(np.asarray(z), np.asarray(x), rtol=1e-6)
    u, _ = Upsampling2DLayer(size=(2, 2)).apply({}, {}, x)
    assert u.shape == (1, 8, 8, 4)


def test_dropout_train_vs_infer():
    layer = DropoutLayer(dropout=0.5)
    x = jnp.ones((4, 100))
    y_inf, _ = layer.apply({}, {}, x, train=False)
    np.testing.assert_allclose(np.asarray(y_inf), 1.0)
    y_tr, _ = layer.apply({}, {}, x, train=True,
                          rng=jax.random.PRNGKey(3))
    arr = np.asarray(y_tr)
    assert ((arr == 0) | (arr == 2)).all()
    assert 0.3 < (arr == 0).mean() < 0.7
    # inverted dropout preserves expectation roughly
    assert 0.8 < arr.mean() < 1.2


def test_prelu():
    _gradcheck_layer(PReLULayer(), (4,))


def test_recurrent_attention_gradcheck():
    from deeplearning4j_tpu.nn.layers import RecurrentAttentionLayer
    _gradcheck_layer(RecurrentAttentionLayer(n_out=4, n_heads=2),
                     (3, 2), tol=5e-4)


def test_graves_bidirectional_lstm():
    from deeplearning4j_tpu.nn.layers import GravesBidirectionalLSTM
    layer = GravesBidirectionalLSTM(n_out=3)
    params, state, out = layer.init(KEY, (5, 2))
    x = jax.random.normal(KEY, (2, 5, 2))
    y, _ = layer.apply(params, state, x)
    assert y.shape == (2, 5, 3)          # reference semantics: summed
    # weight_init/dropout forwarded to the wrapped GravesLSTM
    l2 = GravesBidirectionalLSTM(n_out=3, weight_init="uniform",
                                 dropout=0.1)
    assert l2.fwd.weight_init == "uniform" and l2.fwd.dropout == 0.1


def test_upsampling_1d_3d_and_cnn_loss():
    from deeplearning4j_tpu.nn.layers import (Cnn3DLossLayer,
                                              CnnLossLayer,
                                              Upsampling1DLayer,
                                              Upsampling3DLayer)
    x1 = jax.random.normal(KEY, (2, 4, 3))
    up1 = Upsampling1DLayer(size=3)
    y1, _ = up1.apply({}, {}, x1)
    assert y1.shape == (2, 12, 3)
    m = jnp.asarray(np.array([[1, 1, 0, 0], [1, 0, 0, 0]], np.float32))
    assert up1.propagate_mask(m, (4, 3)).shape == (2, 12)
    np.testing.assert_array_equal(np.asarray(y1[:, 0]),
                                  np.asarray(y1[:, 2]))
    x3 = jax.random.normal(KEY, (1, 2, 3, 4, 2))
    y3, _ = Upsampling3DLayer(size=(2, 2, 2)).apply({}, {}, x3)
    assert y3.shape == (1, 4, 6, 8, 2)
    xl = jax.random.normal(KEY, (2, 3, 3, 4))
    yl, _ = CnnLossLayer(loss="mse", activation="sigmoid").apply({}, {}, xl)
    assert yl.shape == xl.shape and float(yl.min()) >= 0.0
    y3l, _ = Cnn3DLossLayer().apply({}, {}, x3)
    assert y3l.shape == x3.shape


def test_recurrent_attention_mask_holds_state():
    from deeplearning4j_tpu.nn.layers import RecurrentAttentionLayer
    layer = RecurrentAttentionLayer(n_out=4, n_heads=2)
    params, state, _ = layer.init(KEY, (5, 3))
    x = jax.random.normal(KEY, (2, 5, 3))
    m = jnp.asarray(np.array([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]],
                             np.float32))
    y, _ = layer.apply(params, state, x, mask=m)
    # masked positions emit zeros
    np.testing.assert_array_equal(np.asarray(y[0, 3:]), 0.0)
    # valid prefix must not depend on what lies beyond the mask
    x2 = x.at[0, 3:].set(123.0)
    y2, _ = layer.apply(params, state, x2, mask=m)
    np.testing.assert_allclose(np.asarray(y[0, :3]),
                               np.asarray(y2[0, :3]), rtol=1e-5)
