"""ComputationGraph tests. Reference analogs: ComputationGraphTestRNN,
TestComputationGraphNetwork (deeplearning4j-core).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.nn import NeuralNetConfiguration
from deeplearning4j_tpu.nn.config import InputType
from deeplearning4j_tpu.nn.graph import (ComputationGraph,
                                         ComputationGraphConfiguration)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.vertices import (ElementWiseVertex,
                                            L2NormalizeVertex,
                                            MergeVertex, ScaleVertex,
                                            StackVertex, SubsetVertex,
                                            UnstackVertex)
from deeplearning4j_tpu.nn import updaters as upd
from deeplearning4j_tpu.serialization import ModelSerializer

XOR_X = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
XOR_Y = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], np.float32)


def _two_branch_graph():
    return (NeuralNetConfiguration.builder()
            .seed(42)
            .updater(upd.Adam(learning_rate=0.05))
            .graph_builder()
            .add_inputs("in")
            .add_layer("d1", DenseLayer(n_out=8, activation="tanh"), "in")
            .add_layer("d2", DenseLayer(n_out=8, activation="relu"), "in")
            .add_vertex("merge", MergeVertex(), "d1", "d2")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"), "merge")
            .set_outputs("out")
            .set_input_types(**{"in": InputType.feed_forward(2)})
            .build())


def test_graph_fit_learns_xor():
    g = ComputationGraph(_two_branch_graph()).init()
    for _ in range(300):
        g.fit(XOR_X, XOR_Y)
    preds = np.asarray(g.output(XOR_X)[0])
    assert (preds.argmax(1) == XOR_Y.argmax(1)).all()
    assert g.score() < 0.05


def test_graph_json_roundtrip():
    conf = _two_branch_graph()
    s = conf.to_json()
    conf2 = ComputationGraphConfiguration.from_json(s)
    assert conf2.to_json() == s
    g = ComputationGraph(conf2).init()
    assert g.num_params() > 0


def test_graph_checkpoint_roundtrip(tmp_path):
    g = ComputationGraph(_two_branch_graph()).init()
    for _ in range(10):
        g.fit(XOR_X, XOR_Y)
    p = tmp_path / "graph.zip"
    ModelSerializer.write_model(g, p)
    g2 = ModelSerializer.restore_computation_graph(p)
    np.testing.assert_allclose(np.asarray(g.output(XOR_X)[0]),
                               np.asarray(g2.output(XOR_X)[0]),
                               rtol=1e-6)


def test_multi_input_multi_output():
    conf = (NeuralNetConfiguration.builder()
            .seed(1)
            .updater(upd.Adam(learning_rate=0.03))
            .graph_builder()
            .add_inputs("a", "b")
            .add_layer("da", DenseLayer(n_out=4, activation="tanh"), "a")
            .add_layer("db", DenseLayer(n_out=4, activation="tanh"), "b")
            .add_vertex("sum", ElementWiseVertex(op="add"), "da", "db")
            .add_layer("out1", OutputLayer(n_out=2, activation="softmax",
                                           loss="mcxent"), "sum")
            .add_layer("out2", OutputLayer(n_out=1, activation="identity",
                                           loss="mse"), "sum")
            .set_outputs("out1", "out2")
            .set_input_types(a=InputType.feed_forward(3),
                             b=InputType.feed_forward(3))
            .build())
    g = ComputationGraph(conf).init()
    rng = np.random.default_rng(0)
    xa = rng.normal(size=(16, 3)).astype(np.float32)
    xb = rng.normal(size=(16, 3)).astype(np.float32)
    y1 = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
    y2 = rng.normal(size=(16, 1)).astype(np.float32)
    g.fit([xa, xb], [y1, y2])
    assert np.isfinite(g.score())
    o1, o2 = g.output(xa, xb)
    assert o1.shape == (16, 2) and o2.shape == (16, 1)


def test_vertices_math():
    import jax.numpy as jnp
    a = jnp.ones((2, 4))
    b = 2 * jnp.ones((2, 4))
    assert MergeVertex().apply([a, b]).shape == (2, 8)
    np.testing.assert_allclose(
        np.asarray(ElementWiseVertex(op="max").apply([a, b])), 2.0)
    np.testing.assert_allclose(
        np.asarray(ElementWiseVertex(op="average").apply([a, b])), 1.5)
    s = SubsetVertex(from_=1, to=2).apply([jnp.arange(8.0).reshape(2, 4)])
    np.testing.assert_allclose(np.asarray(s), [[1, 2], [5, 6]])
    st = StackVertex().apply([a, b])
    assert st.shape == (4, 4)
    un = UnstackVertex(index=1, num=2).apply([st])
    np.testing.assert_allclose(np.asarray(un), 2.0)
    n = L2NormalizeVertex().apply([a])
    np.testing.assert_allclose(np.linalg.norm(np.asarray(n), axis=-1),
                               1.0, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ScaleVertex(scale=3.0)
                                          .apply([a])), 3.0)


def test_graph_cycle_detection():
    from deeplearning4j_tpu.nn.graph import _Node, _toposort
    nodes = [_Node("x", "vertex", ScaleVertex(), ["y"]),
             _Node("y", "vertex", ScaleVertex(), ["x"])]
    with pytest.raises(ValueError):
        _toposort(nodes, ["in"])


def test_resnet50_builds_and_runs_tiny():
    from deeplearning4j_tpu.zoo.resnet import ResNet50
    # tiny input for CI speed; full 224 shape exercised in bench
    model = ResNet50(num_classes=10, input_shape=(32, 32, 3))
    g = model.init()
    assert g.num_params() > 20_000_000  # ~23.5M backbone+head
    x = np.random.default_rng(0).normal(size=(2, 32, 32, 3)).astype(
        np.float32)
    out = g.output(x)[0]
    assert out.shape == (2, 10)
    np.testing.assert_allclose(np.asarray(out).sum(1), 1.0, rtol=1e-4)
    y = np.eye(10, dtype=np.float32)[[0, 1]]
    g.fit(x, y)
    assert np.isfinite(g.score())


def test_graph_checkpoint_without_input_types(tmp_path):
    """Graphs initialized via explicit input_shapes must restore."""
    conf = (NeuralNetConfiguration.builder().seed(9)
            .updater(upd.Adam(learning_rate=0.05))
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_out=4, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"), "d")
            .set_outputs("out").build())
    g = ComputationGraph(conf).init(input_shapes={"in": (2,)})
    g.fit(XOR_X, XOR_Y)
    p = tmp_path / "g.zip"
    ModelSerializer.write_model(g, p)
    g2 = ModelSerializer.restore_computation_graph(p)
    np.testing.assert_allclose(np.asarray(g.output(XOR_X)[0]),
                               np.asarray(g2.output(XOR_X)[0]),
                               rtol=1e-6)


def test_merge_vertex_output_shape_nondefault_axis():
    """Regression (ADVICE r1): output_shape must honour the configured
    axis (batchless convention), not hard-code the last dim."""
    import jax.numpy as jnp
    # batched rank-3 arrays: merging on axis=1 (time), batchless idx 0
    a = jnp.ones((2, 3, 4))
    b = jnp.ones((2, 5, 4))
    v = MergeVertex(axis=1)
    assert v.apply([a, b]).shape == (2, 8, 4)
    assert v.output_shape([(3, 4), (5, 4)]) == (8, 4)
    # negative axis indexes the same dim in batched and batchless forms
    v2 = MergeVertex(axis=-2)
    assert v2.apply([a, b]).shape == (2, 8, 4)
    assert v2.output_shape([(3, 4), (5, 4)]) == (8, 4)
    # default (-1) unchanged
    assert MergeVertex().output_shape([(3, 4), (3, 6)]) == (3, 10)
    import pytest
    with pytest.raises(ValueError, match="batch axis"):
        MergeVertex(axis=0).output_shape([(3, 4), (5, 4)])


def test_make_train_loop_direct_signature():
    """bench.py drives ComputationGraph._make_train_loop DIRECTLY with
    stacked batches — the signature is a public-ish contract (round-4
    regression: adding mask stacks broke bench.py's call arity)."""
    import jax
    import jax.numpy as jnp
    conf = (NeuralNetConfiguration.builder().seed(1).graph_builder()
            .add_inputs("input")
            .add_layer("d", DenseLayer(n_out=8, activation="tanh"),
                       "input")
            .add_layer("out", OutputLayer(n_out=2,
                                          activation="softmax",
                                          loss="mcxent"), "d")
            .set_outputs("out")
            .set_input_types(input=InputType.feed_forward(4)).build())
    net = ComputationGraph(conf).init()
    loop = net._make_train_loop()
    k = 3
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((k, 16, 4)), jnp.float32)
    y = jnp.asarray(np.eye(2, dtype=np.float32)[
        rng.integers(0, 2, (k, 16))])
    rngs = jnp.stack([jax.random.fold_in(jax.random.PRNGKey(0), i)
                      for i in range(k)])
    # the bench.py calling convention: empty mask stacks
    p, o, s, losses = loop(net.params, net.opt_state, net.state,
                           {"input": x}, [y], {}, {}, rngs)
    assert losses.shape == (k,)
    assert np.isfinite(np.asarray(losses)).all()
