"""BertIterator / wordpiece / LM-packing pipelines (reference:
``org.deeplearning4j.iterator.BertIterator`` TestBertIterator — MLM
masking semantics, fixed-length shapes, classification task — and the
char-RNN CharacterIterator analog for causal-LM packing)."""
import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (BertIterator,
                                    BertWordPieceTokenizer,
                                    LMSequenceIterator)
from deeplearning4j_tpu.nlp.bert_iterator import (CLS, MASK, PAD, SEP,
                                                  SPECIALS, UNK)

CORPUS = ["the quick brown fox jumps over the lazy dog",
          "pack my box with five dozen liquor jugs",
          "how vexingly quick daft zebras jump",
          "the five boxing wizards jump quickly"] * 4


@pytest.fixture(scope="module")
def tok():
    return BertWordPieceTokenizer(
        BertWordPieceTokenizer.build_vocab(CORPUS))


def test_wordpiece_whole_words(tok):
    assert tok.tokenize("the quick fox") == ["the", "quick", "fox"]


def test_wordpiece_decomposes_unseen_words(tok):
    # "quickest" is not a whole word in the vocab but decomposes into
    # the known word piece + character continuations
    pieces = tok.tokenize("quickest")
    assert pieces[0] == "quick"
    assert all(p.startswith("##") for p in pieces[1:])
    assert "".join(p.lstrip("#") for p in pieces) == "quickest"


def test_wordpiece_unknown_char_is_unk():
    vocab = BertWordPieceTokenizer.build_vocab(["abc"])
    t = BertWordPieceTokenizer(vocab)
    assert t.tokenize("abc xyz") == ["abc", UNK]


def test_vocab_file_roundtrip(tok, tmp_path):
    """vocab.txt save/load (reference BertWordPieceTokenizer(vocabFile))
    — and the masking invariant holds for a vocab whose special ids
    are NOT the first rows (real-BERT layout)."""
    p = tmp_path / "vocab.txt"
    tok.save_vocab(p)
    tok2 = BertWordPieceTokenizer.from_vocab_file(p)
    assert tok2.vocab == tok.vocab
    assert tok2.tokenize("the quick fox") == tok.tokenize("the quick fox")
    # scrambled layout: specials at high ids (like Google's vocab.txt
    # where [CLS]=101 etc.)
    pieces = sorted(tok.vocab, key=tok.vocab.get)
    scrambled = [w for w in pieces if w not in SPECIALS] + \
        [w for w in pieces if w in SPECIALS]
    (tmp_path / "v2.txt").write_text("\n".join(scrambled) + "\n")
    tok3 = BertWordPieceTokenizer.from_vocab_file(tmp_path / "v2.txt")
    it = BertIterator(tok3, CORPUS, batch_size=4, seq_len=16, seed=21)
    v = tok3.vocab
    for mds in it:
        ids = mds.features[0]
        lmask = mds.labels_masks[0]
        special = np.isin(ids, [v[s] for s in SPECIALS])
        assert not (special & (lmask > 0) & (ids != v[MASK])).any()


def test_mask_lm_batch_shapes_and_semantics(tok):
    it = BertIterator(tok, CORPUS, batch_size=4, seq_len=16,
                      task="mask_lm", seed=1)
    mds = next(iter(it))
    ids, segs = mds.features
    (y,), (lmask,) = mds.labels, mds.labels_masks
    assert ids.shape == (4, 16) and segs.shape == (4, 16)
    # input (key) masks: [PAD] positions excluded for BOTH graph inputs
    # (ADVICE r3 — upstream BertIterator supplies an input mask)
    fm_tok, fm_seg = mds.features_masks
    v_pad = tok.vocab["[PAD]"]
    assert (fm_tok == (ids != v_pad)).all()
    assert (fm_seg == fm_tok).all()
    assert y.shape == (4, 16, len(tok.vocab))
    v = tok.vocab
    # every row starts with [CLS], has a [SEP], pads with [PAD]
    assert (ids[:, 0] == v[CLS]).all()
    assert all((row == v[SEP]).any() for row in ids)
    # at least one scored position per example; specials never scored
    assert (lmask.sum(axis=1) >= 1).all()
    special = np.isin(ids, [v[s] for s in SPECIALS])
    # corrupted specials: [MASK] appears only at scored positions
    assert not (special & (lmask > 0) & (ids != v[MASK])).any()
    # labels at scored positions are the ORIGINAL ids (one-hot argmax
    # differs from the corrupted input wherever [MASK] was placed)
    orig = np.argmax(y, axis=-1)
    masked_here = (ids == v[MASK]) & (lmask > 0)
    assert (orig[masked_here] != v[MASK]).all()


def test_mask_lm_corruption_statistics(tok):
    """Across a large sample: ~15% of maskable positions selected; of
    the selected, ~80% become [MASK] (10% random / 10% kept)."""
    it = BertIterator(tok, CORPUS * 40, batch_size=16, seq_len=16,
                      task="mask_lm", seed=2)
    sel_frac, mask_frac, n = [], [], 0
    v = tok.vocab
    for mds in it:
        ids = mds.features[0]
        lmask = mds.labels_masks[0]
        maskable = ~np.isin(ids, [v[s] for s in (PAD, CLS, SEP)])
        # positions [MASK]ed or otherwise selected
        sel_frac.append(lmask.sum() / maskable.sum())
        mask_frac.append(((ids == v[MASK]) & (lmask > 0)).sum()
                         / max(lmask.sum(), 1))
        n += 1
        if n >= 8:
            break
    assert 0.10 < np.mean(sel_frac) < 0.22, np.mean(sel_frac)
    assert 0.65 < np.mean(mask_frac) < 0.92, np.mean(mask_frac)


@pytest.mark.parametrize("seed", range(20, 30))
def test_mask_lm_random_replacement_never_special(tok, seed):
    """The 10% random replacements must never be a special token
    (regression: full-vocab draw could plant [PAD]/[CLS] mid-sentence
    at scored positions — observed at seed 21 with a full-range
    draw)."""
    it = BertIterator(tok, CORPUS, batch_size=4, seq_len=16,
                      task="mask_lm", seed=seed)
    v = tok.vocab
    for mds in it:
        ids = mds.features[0]
        lmask = mds.labels_masks[0]
        special = np.isin(ids, [v[s] for s in SPECIALS])
        assert not (special & (lmask > 0) & (ids != v[MASK])).any()


def test_trailing_partial_batch_not_dropped(tok):
    it = BertIterator(tok, CORPUS[:6], batch_size=4, seq_len=16,
                      seed=0)
    sizes = [m.features[0].shape[0] for m in it]
    assert sizes == [4, 2]          # nothing silently dropped
    # fewer sentences than batch_size still yields one (short) batch
    it2 = BertIterator(tok, CORPUS[:3], batch_size=8, seq_len=16)
    assert [m.features[0].shape[0] for m in it2] == [3]


def test_reset_changes_masking(tok):
    it = BertIterator(tok, CORPUS, batch_size=4, seq_len=16, seed=3)
    a = next(iter(it)).features[0].copy()
    it.reset()
    b = next(iter(it)).features[0]
    assert (a != b).any()          # fresh corruption per epoch


def test_seq_classification_emits_pad_mask(tok):
    data = [(t, i % 2) for i, t in enumerate(CORPUS)]
    it = BertIterator(tok, data, batch_size=4, seq_len=16,
                      task="seq_classification", num_classes=2, seed=2)
    mds = next(iter(it))
    ids = mds.features[0]
    fm_tok, fm_seg = mds.features_masks
    assert (fm_tok == (ids != tok.vocab["[PAD]"])).all()
    assert (fm_seg == fm_tok).all()


def test_seq_classification_batches(tok):
    data = [(s, i % 2) for i, s in enumerate(CORPUS)]
    it = BertIterator(tok, data, batch_size=4, seq_len=16,
                      task="seq_classification", num_classes=2)
    mds = next(iter(it))
    assert mds.features[0].shape == (4, 16)
    assert mds.labels[0].shape == (4, 2)
    assert (mds.labels[0].sum(axis=1) == 1).all()


def test_bert_mlm_end_to_end_trains(tok):
    """BertTiny MLM fine-tune through BertIterator: loss decreases
    (the reference's TestBertIterator + BERT pretraining path)."""
    from deeplearning4j_tpu.zoo import BertTiny
    from deeplearning4j_tpu.nn import updaters as upd
    net = BertTiny(vocab_size=len(tok.vocab), max_len=32,
                   updater=upd.Adam(learning_rate=1e-3),
                   seed=7).init_mlm(seq_len=16)
    it = BertIterator(tok, CORPUS, batch_size=4, seq_len=16, seed=4)
    s0 = None
    for _ in range(4):
        net.fit(it)
        s0 = s0 if s0 is not None else net.score()
    assert np.isfinite(net.score())
    assert net.score() < s0, (s0, net.score())


def test_lm_sequence_iterator_packs_and_trains(tok):
    it = LMSequenceIterator.from_texts(CORPUS, tok, batch_size=4,
                                       seq_len=12)
    ds = next(iter(it))
    x, y = ds.features, ds.labels
    assert x.shape == (4, 12) and y.shape == (4, 12)
    np.testing.assert_array_equal(x[0, 1:], y[0, :-1])   # shifted by 1
    # stream continuity: row 1 starts at the token row 0's target ends
    assert x[1, 0] == y[0, -1]
    from deeplearning4j_tpu.zoo import CausalTransformerLM
    model = CausalTransformerLM(vocab_size=len(tok.vocab), hidden=64,
                                n_layers=2, n_heads=4, max_len=32,
                                seed=9)
    net = model.init(seq_len=12)
    s0 = None
    for _ in range(4):
        for ds in it:
            net.fit(ds.features, ds.labels)
            s0 = s0 if s0 is not None else net.score()
    assert net.score() < s0, (s0, net.score())


def test_lm_iterator_rejects_short_corpus(tok):
    with pytest.raises(ValueError, match="shorter"):
        LMSequenceIterator([1, 2, 3], batch_size=2, seq_len=8)


def test_lm_iterator_trailing_windows_not_dropped():
    """50 tokens @ T=12 pack into 4 windows; batch_size=8 must yield
    one SHORT batch of 4 rows, not silently nothing."""
    it = LMSequenceIterator(list(range(50)), batch_size=8, seq_len=12)
    batches = list(it)
    assert len(batches) == 1 and len(it) == 1
    assert batches[0].features.shape == (4, 12)
    # and a 10-window corpus with batch_size=4 yields 4+4+2
    it2 = LMSequenceIterator(list(range(121)), batch_size=4,
                             seq_len=12)
    assert [b.features.shape[0] for b in it2] == [4, 4, 2]


def test_encode_fixed_truncation_keeps_sep(tok):
    """Over-long sentences keep the trailing [SEP]; PAIR truncation
    pops from the longer sentence so BOTH segments (and both [SEP]s)
    survive (reference truncateSeqPair semantics)."""
    it = BertIterator(tok, CORPUS, batch_size=2, seq_len=8)
    long_text = " ".join(CORPUS)
    ids, segs, n = it._encode_fixed(long_text)
    v = tok.vocab
    assert n == 8 and ids[-1] == v[SEP] and ids[0] == v[CLS]
    assert ids.count(v[SEP]) == 1 and set(segs) == {0}
    # pair: a huge text_a must NOT evict text_b — segment 1 survives
    ids2, segs2, n2 = it._encode_fixed(long_text, "lazy dog")
    assert n2 == 8 and ids2[-1] == v[SEP]
    assert ids2.count(v[SEP]) == 2
    assert 1 in segs2                    # second segment present
    seps = [i for i, t in enumerate(ids2) if t == v[SEP]]
    assert segs2[seps[0]] == 0 and segs2[seps[1]] == 1
